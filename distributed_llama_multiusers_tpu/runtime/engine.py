"""Inference engine: compiled decode/prefill steps over a lane-based KV cache.

This is the TPU-native replacement for the reference executor + forward loop
(src/nn/nn-executor.cpp:134-187, src/app.cpp:179-231): instead of a
spin-barrier thread pool stepping a flat op list and shipping control packets
to workers, there are two compiled XLA programs —

- ``decode``: one token for every lane at its own position (the whole
  continuous batch advances in a single device step), and
- ``prefill``: a bucketed prompt chunk for ONE lane (dynamic-sliced out of
  the lane axis so other lanes' caches are untouched) — full prompt
  processing, fixing reference defect (a).

Shapes are bucketed (prompt chunks padded up to fixed sizes) so XLA compiles
a handful of programs once, replacing the reference's dynamic ``batchSize``
argument (nn-executor.cpp:171). All per-lane state (positions, sampling,
stream decode) lives with the scheduler; the engine is stateless apart from
the device-resident cache it threads through.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from ..analysis import jitcheck
from ..grammar.slab import (
    DEFAULT_SLAB_EDGES,
    DEFAULT_SLAB_STATES,
    GrammarSlab,
)
from ..lockcheck import make_lock
from ..models.config import LlamaConfig
from ..models.llama import (
    KVCache,
    LlamaParams,
    PagedKVCache,
    init_kv_cache,
    init_paged_kv_cache,
    llama_forward,
)
from ..telemetry.logs import log_event
from ..utils import faults
from .kvpool import DEFAULT_MAX_PARKED, DEFAULT_PAGE_SIZE, KVPagePool
from .spec import SPEC_DRAFT

DEFAULT_PREFILL_BUCKETS = (16, 64, 256, 1024)

# host-swap transfer batch: pages moved per device dispatch by the
# gather/scatter swap programs (fixed operand shape = ONE compile each;
# short batches pad by repeating the first page — duplicate scatter
# indices carrying identical values are deterministic, and the pool
# axis has no sentinel page to park padding on)
_SWAP_BATCH = 8

# THE top-p default for every sampling surface (engine wrappers, scheduler
# batch vectors, control-plane packet normalization, Request): one constant,
# so a future default change cannot desync the compiled-step operands from
# the scheduler's per-lane vectors (they must be byte-identical for stream
# identity across the sync/multi/pipelined paths)
DEFAULT_TOPP = 0.9

# bounded in-flight ring for the async decode pipeline (--pipeline-depth):
# at most this many dispatched-but-unconsumed steps. 2 = classic one-step
# lag (consume step k while step k+1 runs); 0/1 disables pipelining.
DEFAULT_PIPELINE_DEPTH = 2


@dataclass
class EngineStats:
    """Per-call timing + transfer counters — the analogue of the reference's
    per-step-type totalTime[] and socket byte counters (SURVEY.md §5.1,
    src/dllama.cpp:54-64, src/nn/nn-network.cpp:493-508)."""

    prefill_s: float = 0.0
    decode_s: float = 0.0
    prefill_tokens: int = 0
    decode_steps: int = 0
    host_bytes_in: int = 0  # device->host logits/token traffic
    spec_steps: int = 0  # speculative verify steps (one per batched call)
    # maintained by the consuming loops (scheduler / SpecStream), since the
    # engine cannot know how many verified tokens the caller commits.
    # DRAFTED lanes only (draft_len > 0), consumed tokens only — so
    # emitted/lane_steps reads as acceptance in [1, K+1]:
    spec_emitted: int = 0  # tokens consumed from spec steps, drafted lanes
    spec_lane_steps: int = 0  # (drafted lane, spec-step) pairs
    prefix_hits: int = 0  # admissions that reused another lane's KV prefix
    prefix_tokens_saved: int = 0  # prompt tokens NOT re-prefilled
    multi_dispatches: int = 0  # decode_multi calls (each = h decode steps,
    # ONE host round-trip — the serving loop's per-token dispatch amortizer)
    # zero-flush serving (decode_spec_pipelined / decode_spec_prefill_fused):
    spec_pipelined_steps: int = 0  # spec verify steps dispatched INSIDE the
    # pipelined ring (each also counts in spec_steps/pipeline_dispatches)
    spec_accept_hist: dict = field(default_factory=dict)  # device accept
    # count -> occurrences, DRAFTED lanes only (0 = no draft survived the
    # carry-alignment gate, K = full acceptance); written by the consuming
    # scheduler, which is the only layer that knows which lanes drafted
    host_exact_lanes: int = 0  # lanes routed through the host Sampler
    # (host_sampling=True escape hatch only — the on-device sampler is
    # full-vocab exact, so this reads 0 in default serving)
    # async decode pipeline (decode_pipelined / pipeline_consume):
    overlap_s: float = 0.0  # host-side time between a step's dispatch and
    # the start of its (lagged) readback — work the device execution hid,
    # which the synchronous path would have serialized
    pipeline_dispatches: int = 0  # pipelined steps dispatched
    pipeline_flushes: int = 0  # chains aborted before their lanes finished
    # (speculation/host-exact/stop flush); a natural end-of-chain drain
    # does not count, and with fused prefill an admission does not either,
    # so steady-state decode — churn included — reads 0
    pipeline_depth_hist: dict = field(default_factory=dict)  # ring depth
    # right after each dispatch -> count (how deep the overlap actually ran)
    # stall-free admissions (decode_prefill_fused):
    fused_steps: int = 0  # fused prefill+decode dispatches (each advances
    # every generating lane one token AND consumes one prompt chunk)
    admission_stall_s: float = 0.0  # host time generating lanes spent
    # stalled behind admission work (sync prefill chunks, or in-chain lane
    # claims taken while the ring was empty); ~0 when fused dispatches
    # carry the admission under a full ring
    fused_bucket_hist: dict = field(default_factory=dict)  # prefill bucket
    # -> fused dispatches that carried a chunk of that bucket
    # estimated per-step collective payload (bytes/chip), from the compiled
    # decode program's post-SPMD HLO — the Sent/Recv kB analogue on a mesh
    sync_bytes_per_decode: int = 0
    sync_collectives_per_decode: int = 0
    # cumulative estimated collective payload (bytes/chip) dispatched with
    # decode-FAMILY steps (sync/multi/spec/pipelined/fused), i.e.
    # sync_bytes_per_decode accrued per chained step — feeds /stats and the
    # dllama_sync_bytes_total counter on /metrics. Prefill-only dispatches
    # are not counted (their program's traffic differs from the decode
    # estimate); 0 off-mesh or before collective_stats() runs.
    sync_bytes_total: int = 0
    # failure containment (multihost.worker_serve): supervised-restart and
    # classified replay-protocol-error counts on THIS process, so pod
    # worker health is a stats read, not a stderr grep
    worker_restarts: int = 0
    worker_replay_errors: int = 0
    # grammar-constrained decoding (grammar/): admissions that attached a
    # compiled automaton, and dispatches that carried at least one
    # constrained lane (every step family threads the mask; these count
    # the ones where it actually bit)
    grammar_lanes: int = 0
    grammar_masked_steps: int = 0
    # compile stability (analysis/jitcheck.py, ISSUE 15): XLA backend
    # compiles observed AFTER warmup_engine armed the recompile witness —
    # the machine-checked form of "one compiled program per (family,
    # bucket), compiled only at warmup". Must read 0 in steady serving;
    # any bump means an unwarmed family or an aval-changing operand
    # rebuild stalled every lane mid-service. NOT cleared by reset():
    # like sync_bytes_per_decode it describes the process since warmup,
    # not a stats window — a window reset must not hide a recompile.
    jit_compiles_after_warmup: int = 0
    # writers (engine hot paths, scheduler counters) hold this around their
    # multi-field bumps; snapshot()/reset() hold it while copying, so a
    # /stats read sees one consistent point in time instead of field-by-field
    # values racing the batching thread
    lock: threading.Lock = field(
        # built via make_lock so the runtime lock-order witness
        # (DLLAMA_LOCKCHECK=1) can wrap it; literal cross-checked by dlint
        default_factory=lambda: make_lock("EngineStats.lock"),
        repr=False, compare=False,
    )

    # dlint guarded-by declaration (analysis/lock_check.py): every counter
    # above may only be read or written inside `with <stats>.lock:` (or in
    # __init__ / *_locked methods). Machine-checked by `make lint` — a new
    # unlocked bump anywhere in the package fails tier-1. Not annotated,
    # so the dataclass does not treat it as a field.
    _dlint_guarded_by = {
        ("lock",): (
            "prefill_s", "decode_s", "prefill_tokens", "decode_steps",
            "host_bytes_in", "spec_steps", "spec_emitted", "spec_lane_steps",
            "prefix_hits", "prefix_tokens_saved", "multi_dispatches",
            "spec_pipelined_steps", "spec_accept_hist", "host_exact_lanes",
            "overlap_s", "pipeline_dispatches", "pipeline_flushes",
            "pipeline_depth_hist",
            "fused_steps", "admission_stall_s", "fused_bucket_hist",
            "sync_bytes_per_decode", "sync_collectives_per_decode",
            "sync_bytes_total", "worker_restarts", "worker_replay_errors",
            "grammar_lanes", "grammar_masked_steps",
            "jit_compiles_after_warmup",
        ),
    }

    def _counters(self) -> dict:
        # dict-valued counters (the depth histogram) are copied, not
        # aliased: a snapshot must not mutate under its reader's feet
        return {
            k: (dict(v) if isinstance(v, dict) else v)
            for k, v in self.__dict__.items()
            if k != "lock"
        }

    def snapshot(self) -> dict:
        """Consistent point-in-time copy of every counter (one lock hold)."""
        with self.lock:
            return self._counters()

    def reset(self) -> "EngineStats":
        with self.lock:
            snap = EngineStats(**self._counters())
            self.prefill_s = self.decode_s = self.overlap_s = 0.0
            self.prefill_tokens = self.decode_steps = self.host_bytes_in = 0
            self.spec_steps = self.spec_emitted = self.spec_lane_steps = 0
            self.prefix_hits = self.prefix_tokens_saved = 0
            self.multi_dispatches = 0
            self.spec_pipelined_steps = self.host_exact_lanes = 0
            self.spec_accept_hist = {}
            self.pipeline_dispatches = self.pipeline_flushes = 0
            self.pipeline_depth_hist = {}
            self.fused_steps = 0
            self.admission_stall_s = 0.0
            self.fused_bucket_hist = {}
            self.sync_bytes_total = 0
            self.worker_restarts = self.worker_replay_errors = 0
            self.grammar_lanes = self.grammar_masked_steps = 0
            # per-decode sync_* stay: they describe the compiled program,
            # not a window; jit_compiles_after_warmup stays: it describes
            # compile stability since warmup, and a window reset hiding a
            # mid-serving recompile would defeat the witness
        return snap

    def preserved(self):
        """Context manager: restore all counters on exit — for probes and
        warmup, whose fake engine calls must not pollute serving totals."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            snap = self.snapshot()
            try:
                yield self
            finally:
                with self.lock:
                    self.__dict__.update(snap)

        return cm()


class InferenceEngine:
    # dlint resource-lifecycle declaration (analysis/resourcemodel.py):
    # the engine's paged façade mirrors the pool's lane-page ownership —
    # ``paged_admit`` acquires (pool admit + device table write as one
    # unit), ``paged_finish``/``paged_reset`` give it back. Same kind as
    # the pool's own vocabulary so wrappers of either balance.
    _dlint_acquires = {"kv-page": ("paged_admit",)}
    _dlint_releases = {"kv-page": ("paged_finish", "paged_reset")}

    # dlint device-affinity declaration: these methods touch pytrees the
    # compiled step families DONATE (engine.cache, the paged table, the
    # grammar slab). Off the batching loop they race the live chain —
    # the step that is about to consume the buffer they mutate (the race
    # PR 16 caught live). Legal callers: the loop-thread closure
    # (_dlint_loop_roots on the scheduler) or a closure handed to
    # scheduler.run_device_op(). Checked by dlint device-affinity.
    _dlint_device_affine = (
        "apply_paged_admit", "copy_lane", "paged_unmap_all",
        "export_kv_page", "import_kv_page",
        "swap_out_pages", "swap_in_pages",
    )

    def __init__(
        self,
        config: LlamaConfig,
        params: LlamaParams,
        n_lanes: int = 8,
        prefill_buckets: tuple[int, ...] = DEFAULT_PREFILL_BUCKETS,
        cache_dtype=None,
        emulate_q80_activations: bool = False,
        mesh=None,
        replicate_outputs: bool = False,
        q80_sync: bool = False,
        pipeline_depth: int | None = None,
        paged_kv: bool = False,
        kv_page_size: int = DEFAULT_PAGE_SIZE,
        kv_pool_pages: int | None = None,
        kv_max_parked: int = DEFAULT_MAX_PARKED,
        kv_host_bytes: int = 0,
        grammar_slab_states: int | None = None,
        grammar_slab_edges: int | None = None,
    ):
        """``paged_kv=True`` stores KV as a pooled set of fixed-size pages
        behind a per-lane page table (runtime/kvpool.py) instead of
        contiguous per-lane planes: prefix sharing becomes a refcount
        bump on the shared pages (zero HBM copies; ``copy_lane`` is the
        contiguous path's primitive and is refused here), divergence is
        served by a single-page copy-on-write, and finished sessions
        park their sharable pages so resident sessions exceed lanes.
        Token streams are byte-identical to the contiguous layout
        (pinned). ``kv_page_size`` is the page granularity in tokens
        (power of two; shrunk to fit short seq_len configs);
        ``kv_pool_pages`` sizes the pool (default: the contiguous
        layout's exact footprint, n_lanes x blocks-per-full-lane);
        ``kv_max_parked`` bounds parked sessions (LRU-evicted under pool
        pressure); ``kv_host_bytes`` budgets the host-RAM swap tier
        between "parked" and "dropped" (0 disables it, restoring
        drop-to-rebuild bit-for-bit — see ``kvpool.HostTier``)."""
        self.config = config
        self.params = params
        self.n_lanes = n_lanes
        self.mesh = mesh
        self.prefill_buckets = tuple(
            b for b in sorted(prefill_buckets) if b <= config.seq_len
        ) or (min(16, config.seq_len),)
        if cache_dtype is None:
            # bf16 KV on TPU (half the HBM of f32; the reference shards its
            # f32 KV only because RPi has no bf16 — src/nn/nn-core.cpp:198-205);
            # f32 on CPU where the parity oracle runs
            cache_dtype = (
                jnp.bfloat16 if jax.devices()[0].platform == "tpu" else jnp.float32
            )
        self.cache_dtype = cache_dtype
        if paged_kv:
            if mesh is not None and (
                dict(mesh.shape).get("dp", 1) > 1
                or dict(mesh.shape).get("sp", 1) > 1
            ):
                # the pool is ONE global resource every lane maps into
                # (parallel/sharding.paged_cache_shardings): under dp it
                # would replicate — sized to the contiguous layout's
                # WHOLE footprint, a dp-fold HBM regression — and sp has
                # no per-lane S axis to shard. Serving pod meshes are
                # pure-TP; refuse the silent misconfiguration.
                raise ValueError(
                    "paged_kv requires a pure-TP mesh (dp=1, sp=1): the "
                    "page pool replicates over dp and cannot shard over "
                    "sp — use --paged-kv off on dp/sp meshes"
                )
            # paged pool: page granularity shrinks to fit short contexts
            # (tiny test configs) but stays a power of two; the default
            # pool size is the contiguous layout's exact HBM footprint —
            # oversubscription comes from sessions reserving only what
            # they can use (prompt + max_tokens), not a bigger pool
            # one construction recipe (validation, power-of-two shrink,
            # contiguous-footprint default), shared with the mock so the
            # scheduler-level tests exercise the identical pool geometry
            self.kvpool = KVPagePool.for_seq_len(
                config.seq_len, n_lanes, page_size=kv_page_size,
                pool_pages=kv_pool_pages, max_parked=kv_max_parked,
                host_bytes=kv_host_bytes,
            )
            # swap-tier traffic counters: single-writer (every swap op
            # runs on the scheduler loop thread / device-op funnel),
            # read lock-free by pool_stats() from HTTP threads
            self.swap_ins = 0
            self.swap_outs = 0
            self.swap_in_bytes = 0
            self.swap_out_bytes = 0
            self.swap_in_ms = 0.0
            bs = self.kvpool.page_size
            n_pages = self.kvpool.n_pages
            # dlint: ok[host-sync] host int lists -> the numpy table mirror; no device value involved
            self._host_tables = np.asarray(
                [self.kvpool.table_row([])] * n_lanes, np.int32
            )
            init_fn = partial(
                init_paged_kv_cache, config, n_lanes, n_pages, bs,
                n_blocks=self.kvpool.blocks_per_lane, dtype=cache_dtype,
            )
            if mesh is not None:
                from ..parallel.sharding import paged_cache_shardings

                shardings = paged_cache_shardings(mesh)
                self.cache = jax.jit(
                    init_fn, out_shardings=shardings
                )()
                # every table replacement must carry this sharding (see
                # _replace_leaf, THE sanctioned constructor): a bare
                # jnp.asarray leaf would change the compiled programs'
                # input aval (recompile per admission on a single-host
                # mesh; incompatible-devices failure on a multi-process
                # pod) — machine-checked by dlint's jit-stability
                self._table_sharding = shardings.table
            else:
                self.cache = init_fn()
                self._table_sharding = None
        elif mesh is not None:
            self.kvpool = None
            # materialize the cache already placed (lanes over dp, sequence
            # over sp, kv heads over tp — parallel/sharding.cache_shardings);
            # round 2 left serving caches unplaced, so GSPMD chose for us
            from ..parallel.sharding import cache_shardings

            self.cache = jax.jit(
                partial(init_kv_cache, config, n_lanes, dtype=cache_dtype),
                out_shardings=cache_shardings(mesh),
            )()
        else:
            self.kvpool = None
            self.cache = init_kv_cache(config, n_lanes, dtype=cache_dtype)
        self.stats = EngineStats()
        # async decode pipeline: bounded ring of dispatched-but-unconsumed
        # steps plus the on-device token carry feeding the next dispatch
        self.pipeline_depth = (
            DEFAULT_PIPELINE_DEPTH if pipeline_depth is None
            else max(0, pipeline_depth)
        )
        # ring entries: (kind, packed device array, t_dispatched) with kind
        # "tok" ([2, n(+1)] greedy/sampled rows) or "spec" ([n(+1), K+2]
        # emitted tokens + per-lane emit count)
        self._pl_inflight: deque = deque()
        self._pl_carry = None  # [n] device int32: next feed per lane
        # [n] device int32: each lane's next WRITE position — part of the
        # carry since spec verify steps advance lanes by a per-lane accept
        # count the host only learns one step later (pos+1 generalizes to
        # pos+accepted+1). Dispatch positions with value -1 select this
        # carried position; >= 0 overrides from host metadata (parked /
        # admitting / freshly reseeded lanes).
        self._pl_carry_pos = None
        # [n] device int32: each lane's grammar-automaton state (absolute
        # slab id; 0 = FREE/unconstrained), advanced ON DEVICE by every
        # chosen token exactly like the position carry — same -1/override
        # dispatch semantics, so constrained lanes ride the zero-flush
        # chain without any host round-trip
        self._pl_carry_g = None
        # grammar slab (grammar/slab.py): fixed-capacity mask + transition
        # tables, state 0 = FREE (all-ones mask) so unconstrained lanes run
        # the identical compiled math. Device copies upload lazily on slab
        # version bumps (admissions of new schemas) — shapes never change,
        # so grammar churn can never trigger an XLA recompile.
        self.grammar_slab = GrammarSlab(
            config.vocab_size,
            n_states=grammar_slab_states or DEFAULT_SLAB_STATES,
            n_edges=grammar_slab_edges or DEFAULT_SLAB_EDGES,
        )
        self._g_dev = None
        self._g_version = -1
        self._g_vocab = None  # token piece table (grammar_init)
        self._g_vocab_key = None
        self._g_eos: tuple = ()
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            # the slab tables are small and read by every chip: fully
            # replicated, like the token carries
            self._g_sharding = NamedSharding(mesh, PartitionSpec())
        else:
            self._g_sharding = None

        cfg = config
        q80 = emulate_q80_activations
        # Q80-compressed wo/w2 sync (the reference's default transport);
        # meaningful on DCN-spanning meshes where payload bytes matter
        q80s = q80_sync

        sp_mesh = mesh

        if replicate_outputs and mesh is not None:
            # multi-host: logits/greedy must come back fully replicated, or
            # no process can fetch them (a cross-host-sharded jax.Array is
            # not locally convertible; the reference instead gathers logits
            # to its root over TCP, SYNC_NODE_SLICES_EXCEPT_ROOT)
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(mesh, PartitionSpec())
            replicate = lambda x: jax.lax.with_sharding_constraint(x, rep)
        else:
            replicate = lambda x: x

        if mesh is not None:
            # mesh-native token plumbing: the on-device carry feeding the
            # next pipelined dispatch and the packed [2, n(+1)] token
            # readbacks are EXPLICITLY replicated — a few bytes per step —
            # so GSPMD can never choose a sharded layout that would splice
            # a cross-device gather between chained dispatches (the pod
            # serving path's first-dispatch stall). Logits keep the
            # replicate_outputs policy above (replicating [n, vocab] f32 is
            # an all-gather worth paying only when a host must read it).
            from jax.sharding import NamedSharding, PartitionSpec

            _tok_rep = NamedSharding(mesh, PartitionSpec())
            rep_tokens = lambda x: jax.lax.with_sharding_constraint(x, _tok_rep)
        else:
            rep_tokens = lambda x: x

        # grammar-constrained decoding (grammar/): per-state packed legal-
        # token masks + compact transitions, gathered INSIDE the compiled
        # step. ``gtab`` = (masks [S, ceil(V/32)] u32, edge_keys [E] i32
        # sorted as state*V+token, edge_next [E] i32, default_next [S]
        # i32) rides every family as an operand (device-resident, updated
        # only on schema admission); ``gs``/``g`` are per-lane automaton
        # states — 0 is the FREE state (all-ones mask, self-loop), so
        # unconstrained lanes run the identical math and their streams
        # stay byte-identical by construction.
        _g_tok_ids = jnp.arange(cfg.vocab_size, dtype=jnp.uint32)

        def _g_bits(gtab, g):
            row = gtab[0][g]  # [ceil(V/32)] uint32
            return (
                (row[_g_tok_ids >> 5] >> (_g_tok_ids & jnp.uint32(31)))
                & jnp.uint32(1)
            ).astype(jnp.bool_)

        def _g_mask_row(gtab, g, row):
            # -inf outside the state's legal set: the masked row feeds the
            # SAME argmax + full-vocab sort/cumsum/categorical as before
            return jnp.where(_g_bits(gtab, g), row, -jnp.inf)

        _g_mask_rows = jax.vmap(_g_mask_row, in_axes=(None, 0, 0))

        def _g_next1(gtab, g, tok):
            # compact transition: sorted sparse exceptions, else the
            # state's majority target. Illegal tokens (never chosen — the
            # mask excluded them) land on the bounded default.
            keys, nxt, dflt = gtab[1], gtab[2], gtab[3]
            key = g * cfg.vocab_size + tok
            j = jnp.clip(jnp.searchsorted(keys, key), 0, keys.shape[0] - 1)
            return jnp.where(keys[j] == key, nxt[j], dflt[g]).astype(
                jnp.int32
            )

        _g_next = jax.vmap(_g_next1, in_axes=(None, 0, 0))
        self._g_next_host = _g_next1  # pod-free debug/testing surface

        def _g_walk_greedy(gtab, gs, logits, full):
            """Per-position masked greedy + grammar state walk over a
            spec verify window: g_t applies to ``logits[:, t]`` and
            advances by the FED token ``full[:, t+1]`` (teacher-forced;
            along the accepted prefix fed == emitted so the walk is
            exact, past the first mismatch the states are junk nothing
            consumes). ONE implementation shared by the sync and
            in-chain verify cores, so the acceptance rule cannot drift
            between them. Returns (masked greedy [n, K], states [n, K])."""
            rows = jnp.moveaxis(logits, 1, 0)  # [K, n, V]
            fed_next = jnp.concatenate(
                [full[:, 1:], jnp.zeros_like(full[:, :1])], axis=1
            ).T  # [K, n]; last row junk (no t+1)

            def _walk(g, xs):
                row_t, fed_t = xs
                mg = jnp.argmax(
                    _g_mask_rows(gtab, g, row_t), axis=-1
                ).astype(jnp.int32)
                return _g_next(gtab, g, fed_t), (mg, g)

            _, (mgreedy, gstates) = jax.lax.scan(
                _walk, gs, (rows, fed_next)
            )
            return mgreedy.T, gstates.T

        # EXACT on-device top-p: the nucleus is computed over the FULL
        # vocab (top_k with k == vocab_size is a total descending sort), so
        # no truncation class exists and wide-nucleus / high-temperature
        # requests sample on device like everyone else — the host Sampler
        # survives only as the host_sampling=True escape hatch. (PR 9's
        # dead `device_topk` knob is gone: a knob that selects no program
        # is exactly what the warmup-coverage lint would mis-model.)
        nucleus_k = cfg.vocab_size

        def _sample_lane(row, temp, topp, seed, pos, greedy):
            """Exact nucleus sample for one lane, on device: full-vocab
            sort → cumulative sum → nucleus mask → categorical draw.

            Reproduces the reference Sampler's sort→cumsum→cutoff shape
            (src/tokenizer.cpp:416-457) over the WHOLE vocab, so the kept
            set equals the host Sampler's exact nucleus for any (temp,
            topp); only the RNG differs (fold_in(seed, pos) + categorical
            here vs xorshift64* there — pinned by
            tests/test_sampler_parity.py). Deterministic per (seed,
            position): seeded runs reproduce."""
            vals, idx = jax.lax.top_k(row, nucleus_k)
            t = jnp.maximum(temp, 1e-6)
            p = jax.nn.softmax(vals.astype(jnp.float32) / t)
            csum = jnp.cumsum(p)
            topp_eff = jnp.where((topp <= 0.0) | (topp >= 1.0), 1.0, topp)
            # keep every token up to and including the one crossing topp
            keep = (csum - p) < topp_eff
            p = jnp.where(keep, p, 0.0)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
            choice = jax.random.categorical(key, jnp.log(p))
            return jnp.where(temp == 0.0, greedy, idx[choice].astype(jnp.int32))

        self._sample_lanes = jax.vmap(_sample_lane)
        self._sample_one = jax.jit(
            lambda row, temp, topp, seed, pos: _sample_lane(
                row, temp, topp, seed, pos, jnp.argmax(row).astype(jnp.int32)
            )
        )

        def _sample_lanes_or_greedy(step, temps, topps, seeds, positions,
                                    greedy):
            # the full-vocab sort is only worth paying when some lane
            # actually samples: an XLA Conditional (ONE branch executes at
            # runtime, unlike a select) skips the whole sampler for
            # all-greedy batches — the common serving case — with a single
            # compiled program, so no program-selection flag has to ride
            # the pod control packets
            return jax.lax.cond(
                jnp.any(temps > 0.0),
                lambda: self._sample_lanes(
                    step, temps, topps, seeds, positions, greedy
                ),
                lambda: greedy,
            )

        def _decode_core(params, cache, tokens, positions, temps, topps,
                         seeds, gtab, gs):
            # tokens/positions: [n_lanes] -> [n_lanes, 1]
            logits, cache = llama_forward(
                cfg, params, tokens[:, None], positions[:, None], cache,
                emulate_q80_activations=q80, mesh=sp_mesh, q80_sync=q80s,
            )
            step = logits[:, 0, :]
            # grammar mask BEFORE both the argmax and the exact top-p sort:
            # constrained lanes' greedy continuation IS the masked argmax.
            # FREE lanes (gs == 0) see an all-ones mask — identity.
            mstep = _g_mask_rows(gtab, gs, step)
            greedy = jnp.argmax(mstep, axis=-1).astype(jnp.int32)
            # sampling fused into the compiled step: a sampled lane costs a
            # 4-byte token transfer, not a [vocab] f32 row (VERDICT Weak #3)
            sampled = _sample_lanes_or_greedy(
                mstep, temps, topps, seeds, positions, greedy
            )
            # the automaton advances on the CHOSEN token, on device — the
            # grammar twin of the position carry
            chosen = jnp.where(temps == 0.0, greedy, sampled)
            new_g = _g_next(gtab, gs, chosen)
            return step, greedy, sampled, new_g, cache

        @partial(jax.jit, donate_argnums=(1,))
        def _decode(params, cache, tokens, positions, temps, topps, seeds,
                    gtab, gs):
            step, greedy, sampled, _, cache = _decode_core(
                params, cache, tokens, positions, temps, topps, seeds,
                gtab, gs,
            )
            # greedy+sampled stacked into ONE [2, n] array: a decode step
            # costs a single device->host round trip, not two (the transfer
            # is latency-bound — 8 bytes/lane payload)
            return (
                replicate(step),
                rep_tokens(jnp.stack([greedy, sampled])),
                cache,
            )

        @partial(jax.jit, donate_argnums=(1,))
        def _decode_nologits(params, cache, tokens, positions, temps, topps,
                             seeds, gtab, gs):
            # the common all-device-sampling step: no [n, vocab] output kept
            # alive (the row is still computed for argmax, but never
            # materialized as a program output, so it pins no HBM and — in
            # the pipelined path — can never force a sync)
            _, greedy, sampled, _, cache = _decode_core(
                params, cache, tokens, positions, temps, topps, seeds,
                gtab, gs,
            )
            return rep_tokens(jnp.stack([greedy, sampled])), cache

        def _eff_positions(carry_pos, pos_host):
            # the carried-position select: host positions >= 0 override
            # (parked / admitting / reseeded lanes), -1 reads the device
            # carry — the only layer that knows a lane's position once a
            # spec verify step with a per-lane accept count is in flight
            return jnp.where(pos_host < 0, carry_pos, pos_host)

        # the grammar-state select is the identical rule (-1 = carry)
        _eff_g = _eff_positions

        @partial(jax.jit, donate_argnums=(1,))
        def _decode_pl(params, cache, tokens, carry_pos, positions, temps,
                       topps, seeds, gtab, carry_g, gs_host):
            # pipelined step: the per-lane feed rule (greedy lanes continue
            # with argmax, device-sampled lanes with the fused sample — the
            # same select the decode_multi scan body applies) runs ON DEVICE
            # and comes back as the carry for the NEXT dispatch, so step k+1
            # needs no host readback of step k at all. Positions ride the
            # carry too (clamped at seq_len, where the KV scatter drops
            # writes — the same park rule the host applies); the grammar
            # state rides it identically.
            pos = _eff_positions(carry_pos, positions)
            gs = _eff_g(carry_g, gs_host)
            _, greedy, sampled, new_g, cache = _decode_core(
                params, cache, tokens, pos, temps, topps, seeds, gtab, gs
            )
            nxt = jnp.where(temps == 0.0, greedy, sampled)
            new_pos = jnp.minimum(pos + 1, cfg.seq_len)
            return (
                rep_tokens(nxt),
                rep_tokens(new_pos),
                rep_tokens(new_g),
                rep_tokens(jnp.stack([greedy, sampled])),
                cache,
            )

        def _spec_verify_core(params, cache, feed, pos, drafts, draft_len,
                              temps, topps, seeds, gtab, gs):
            """Speculative verify INSIDE the pipelined step family: up to
            SPEC_DRAFT host-shipped draft tokens are verified against the
            device's own carry in one forward, per-lane accepted counts
            advance the position carry (pos + accepted + 1), and the next
            feed token is the model's continuation after the accepted
            prefix — exactly ``_decode_spec``'s math with one extra gate:

            drafts[:, 0] is the HOST'S CANDIDATE FOR THE CARRY TOKEN
            ITSELF. The host probes its n-gram index one step behind the
            device (its history ends at the token fed into the in-flight
            step), so it ships K+1 candidates starting at the token it
            cannot see; the device admits the remaining K only when
            candidate 0 equals the actual carry (on a reseed the host
            knows the feed exactly and ships it as candidate 0, so the
            gate passes trivially). A mismatch costs nothing but the
            acceptance — verification is against the model's own argmax,
            so emitted tokens are ALWAYS the plain greedy stream.

            Junk-KV safety is ``_decode_spec``'s contract verbatim, with
            the draft clamp moved ON DEVICE (the host's stale position
            could under-clamp): eff_len <= seq_len - pos - 1, and writes
            at >= seq_len drop in the cache scatter.

            Grammar: the automaton state WALKS the verify window — the
            state for window position t is ``gs`` advanced by the fed
            tokens ``full[1..t]``, so each position's greedy is the
            MASKED argmax under its own state (a constrained lane's
            "model's own greedy path" is the masked one; FREE lanes see
            identity masks). Along the accepted prefix the fed tokens
            equal the masked greedy, so the walk is exact; past the first
            mismatch the states are junk that nothing consumes. The new
            carry is the state after the accepted prefix plus the model's
            own continuation token."""
            hit0 = (drafts[:, 0] == feed) & (draft_len > 0)
            eff_len = jnp.where(hit0, draft_len - 1, 0)
            eff_len = jnp.clip(
                eff_len, 0, jnp.maximum(cfg.seq_len - pos - 1, 0)
            )
            full = jnp.concatenate([feed[:, None], drafts[:, 1:]], axis=1)
            k_spec = full.shape[1]  # SPEC_DRAFT + 1
            pos2d = pos[:, None] + jnp.arange(k_spec, dtype=jnp.int32)
            logits, cache = llama_forward(
                cfg, params, full, pos2d, cache,
                emulate_q80_activations=q80, mesh=sp_mesh, q80_sync=q80s,
            )

            # per-position masked greedy + state walk (K is tiny: a short
            # scan, not a flush-worthy cost) — the shared verify-window
            # rule, so sync and in-chain acceptance cannot drift
            greedy, gstates = _g_walk_greedy(gtab, gs, logits, full)

            match = (full[:, 1:] == greedy[:, :-1]).astype(jnp.int32)
            lead = jnp.cumprod(match, axis=1)
            in_draft = (
                jnp.arange(k_spec - 1, dtype=jnp.int32)[None, :]
                < eff_len[:, None]
            )
            accepted = jnp.sum(lead * in_draft, axis=1).astype(jnp.int32)
            n_emit = accepted + 1
            sampled0 = _sample_lanes_or_greedy(
                _g_mask_rows(gtab, gs, logits[:, 0, :]),
                temps, topps, seeds, pos, greedy[:, 0],
            )
            emitted = greedy.at[:, 0].set(
                jnp.where(temps > 0.0, sampled0, greedy[:, 0])
            )
            nxt = jnp.take_along_axis(
                emitted, (n_emit - 1)[:, None], axis=1
            )[:, 0]
            # grammar carry: state after full[0..accepted] (the walk's
            # entry at index `accepted`), advanced by the continuation
            g_a = jnp.take_along_axis(
                gstates, accepted[:, None], axis=1
            )[:, 0]
            new_g = _g_next(gtab, g_a, nxt)
            new_pos = jnp.minimum(pos + n_emit, cfg.seq_len)
            # ONE [n, K+2] lagged transfer: emitted tokens + emit count
            packed = jnp.concatenate([emitted, n_emit[:, None]], axis=1)
            return nxt, new_pos, new_g, packed, cache

        @partial(jax.jit, donate_argnums=(1,))
        def _decode_spec_pl(params, cache, tokens, carry_pos, positions,
                            drafts, draft_len, temps, topps, seeds, gtab,
                            carry_g, gs_host):
            pos = _eff_positions(carry_pos, positions)
            gs = _eff_g(carry_g, gs_host)
            nxt, new_pos, new_g, packed, cache = _spec_verify_core(
                params, cache, tokens, pos, drafts, draft_len, temps,
                topps, seeds, gtab, gs,
            )
            return (
                rep_tokens(nxt),
                rep_tokens(new_pos),
                rep_tokens(new_g),
                rep_tokens(packed),
                cache,
            )

        @partial(jax.jit, donate_argnums=(1,))
        def _decode_spec_prefill(params, cache, tokens, carry_pos,
                                 positions, drafts, draft_len, temps, topps,
                                 seeds, p_lane, p_tokens, p_start, p_n,
                                 p_temp, p_topp, p_seed, gtab, carry_g,
                                 gs_host, p_g):
            """Fused admission + speculative verify: ONE dispatch that
            consumes one bounded prompt chunk for lane ``p_lane`` AND
            verifies every generating lane's drafts — the composition the
            zero-flush chain needs when a request is admitting while
            greedy lanes draft. The prefill half is ``_prefill_half``
            verbatim (the ``decode_prefill_fused`` contract); the verify
            half is ``_spec_verify_core``; the packed readback appends the
            chunk's boundary greedy/sampled pair as one extra ROW
            ([n+1, K+2] — spec packs are row-per-lane, unlike the
            [2, n+1] column pack of the plain fused step)."""
            _, p_greedy, p_sampled, cache = _prefill_half(
                params, cache, p_lane, p_tokens, p_start, p_n,
                p_temp, p_topp, p_seed, gtab, p_g,
            )
            pos = _eff_positions(carry_pos, positions)
            gs = _eff_g(carry_g, gs_host)
            nxt, new_pos, new_g, packed, cache = _spec_verify_core(
                params, cache, tokens, pos, drafts, draft_len, temps,
                topps, seeds, gtab, gs,
            )
            p_first = jnp.where(p_temp == 0.0, p_greedy, p_sampled)
            nxt = nxt.at[p_lane].set(p_first)
            new_pos = new_pos.at[p_lane].set(p_start + p_n)
            # the admitting lane's grammar carry: its automaton start
            # state advanced by the boundary token (junk mid-prompt, the
            # final chunk's dispatch overwrites it — the token-carry rule)
            new_g = new_g.at[p_lane].set(_g_next1(gtab, p_g, p_first))
            brow = jnp.zeros((1, packed.shape[1]), jnp.int32)
            brow = brow.at[0, 0].set(p_greedy).at[0, 1].set(p_sampled)
            packed = jnp.concatenate([packed, brow], axis=0)
            return (
                rep_tokens(nxt),
                rep_tokens(new_pos),
                rep_tokens(new_g),
                rep_tokens(packed),
                cache,
            )

        @partial(jax.jit, donate_argnums=(1,))
        def _decode_spec(params, cache, tokens, drafts, draft_len, positions,
                         temps, topps, seeds, gtab, gs):
            """Speculative decode: verify K = 1 + n_draft tokens per lane in
            ONE forward (prompt-lookup speculation — decode is weight-read-
            bound, so a K-token step costs the same HBM traffic as a 1-token
            step and emits up to K tokens on greedy lanes when drafts hit).

            tokens [n]: each lane's real next token. drafts [n, K-1]: draft
            continuations (garbage beyond draft_len). draft_len [n]: 0 for
            sampled/undrafted lanes. Emits greedy[t] for the longest prefix
            where draft[t+1] == greedy[t], plus the model's own continuation
            — exactly the tokens plain greedy decode would produce, in the
            same order (standard speculative-verification identity).

            Cache safety: all K positions get KV writes; slots past the
            accepted prefix stay uncommitted (per-lane pos only advances by
            what the scheduler consumes) and are rewritten before any query
            can read them — the same invariant chunked prefill relies on.
            Writes at positions >= seq_len are dropped by the cache scatter
            (mode="drop"), so lanes near the end of their sequence are safe
            as long as the caller clamps that lane's draft_len to
            seq_len - pos - 1 (emitted token t reads logits at pos + t,
            which needs in-bounds KV through pos + t)."""
            full = jnp.concatenate([tokens[:, None], drafts], axis=1)  # [n, K]
            k_spec = full.shape[1]
            pos2d = positions[:, None] + jnp.arange(k_spec, dtype=jnp.int32)
            logits, cache = llama_forward(
                cfg, params, full, pos2d, cache,
                emulate_q80_activations=q80, mesh=sp_mesh, q80_sync=q80s,
            )
            # per-position masked greedy via the SHARED grammar state
            # walk (the _spec_verify_core rule; identity for FREE lanes)
            greedy, _ = _g_walk_greedy(gtab, gs, logits, full)
            match = (full[:, 1:] == greedy[:, :-1]).astype(jnp.int32)
            lead = jnp.cumprod(match, axis=1)  # leading-match indicator
            in_draft = (
                jnp.arange(k_spec - 1, dtype=jnp.int32)[None, :]
                < draft_len[:, None]
            )
            accepted = jnp.sum(lead * in_draft, axis=1).astype(jnp.int32)
            n_emit = accepted + 1  # [n]
            # lane 0-position sample for temp>0 lanes (their draft_len is 0)
            sampled0 = _sample_lanes_or_greedy(
                _g_mask_rows(gtab, gs, logits[:, 0, :]),
                temps, topps, seeds, positions, greedy[:, 0],
            )
            emitted = greedy.at[:, 0].set(
                jnp.where(temps > 0.0, sampled0, greedy[:, 0])
            )
            # ONE [n, K+1] transfer: emitted tokens + emit count
            packed_out = jnp.concatenate([emitted, n_emit[:, None]], axis=1)
            return replicate(logits[:, 0, :]), rep_tokens(packed_out), cache

        self._decode_spec_fn = _decode_spec

        def _prefill_half(params, cache, lane, tokens, start_pos, n_tokens,
                          temp, topp, seed, gtab, p_g):
            """The prompt-chunk math shared by ``_prefill`` and the fused
            ``_decode_prefill``: lane slice, forward, KV splice, boundary
            argmax + fused sample. ONE implementation, so the fused
            admission path's byte-identical-to-prefill_chunk contract
            holds structurally, not by parallel maintenance.

            tokens: [bucket] int32, first n_tokens real; lane, start_pos,
            n_tokens traced scalars (one compile per bucket size only).
            Padded tail tokens write at positions >= start_pos + n_tokens,
            which later real writes overwrite before they become readable
            (mask s <= pos), so no masking is needed. First-token sampling
            is compiled into the step: multi-host pods replay the
            identical program (a root-only jit over the global-mesh logits
            would not be dispatchable)."""
            bucket = tokens.shape[0]
            positions = start_pos + jnp.arange(bucket, dtype=jnp.int32)
            if isinstance(cache, PagedKVCache):
                # paged layout: there is no per-lane plane to slice — the
                # POOL rides whole and the lane's one-ROW page table scopes
                # every write and read to that lane's pages (writes beyond
                # its mapped blocks hit sentinel entries and drop)
                row = jax.lax.dynamic_slice_in_dim(cache.table, lane, 1, axis=0)
                logits, lane_cache = llama_forward(
                    cfg,
                    params,
                    tokens[None, :],
                    positions[None, :],
                    PagedKVCache(k=cache.k, v=cache.v, table=row),
                    emulate_q80_activations=q80,
                    mesh=sp_mesh,
                    q80_sync=q80s,
                )
                out_cache = PagedKVCache(
                    k=lane_cache.k, v=lane_cache.v, table=cache.table
                )
            else:
                # slice this lane's cache to batch-of-1
                k_lane = jax.lax.dynamic_slice_in_dim(cache.k, lane, 1, axis=1)
                v_lane = jax.lax.dynamic_slice_in_dim(cache.v, lane, 1, axis=1)
                logits, lane_cache = llama_forward(
                    cfg,
                    params,
                    tokens[None, :],
                    positions[None, :],
                    KVCache(k=k_lane, v=v_lane),
                    emulate_q80_activations=q80,
                    mesh=sp_mesh,
                    q80_sync=q80s,
                )
                k = jax.lax.dynamic_update_slice_in_dim(cache.k, lane_cache.k, lane, axis=1)
                v = jax.lax.dynamic_update_slice_in_dim(cache.v, lane_cache.v, lane, axis=1)
                out_cache = KVCache(k=k, v=v)
            last = jax.lax.dynamic_index_in_dim(logits[0], n_tokens - 1, axis=0, keepdims=False)
            # grammar: the boundary token — the request's FIRST generated
            # token when this is the final chunk — samples under the
            # automaton's start-state mask (p_g; 0 = FREE = identity)
            mlast = _g_mask_row(gtab, p_g, last)
            greedy = jnp.argmax(mlast).astype(jnp.int32)
            # same runtime gate as the decode families: a greedy admission
            # (temp 0) skips the full-vocab sampler sort entirely
            sampled = jax.lax.cond(
                temp > 0.0,
                lambda: _sample_lane(
                    mlast, temp, topp, seed, start_pos + n_tokens - 1, greedy
                ),
                lambda: greedy,
            )
            return last, greedy, sampled, out_cache

        @partial(jax.jit, donate_argnums=(1,))
        def _prefill(params, cache, lane, tokens, start_pos, n_tokens,
                     temp, topp, seed, gtab, p_g):
            last, greedy, sampled, cache = _prefill_half(
                params, cache, lane, tokens, start_pos, n_tokens,
                temp, topp, seed, gtab, p_g,
            )
            return (
                replicate(last),
                rep_tokens(jnp.stack([greedy, sampled])),
                cache,
            )

        @partial(jax.jit, donate_argnums=(1,))
        def _decode_prefill(params, cache, feed, carry_pos, positions,
                            temps, topps, seeds, p_lane, p_tokens, p_start,
                            p_n, p_temp, p_topp, p_seed, gtab, carry_g,
                            gs_host, p_g):
            """Fused prefill+decode: ONE device dispatch that consumes one
            bucketed prompt chunk for lane ``p_lane`` AND advances every
            generating lane one pipelined decode step — the stall-free
            admission unit. Compiles once per prefill bucket (p_tokens
            shape), like ``_prefill``.

            The prefill half IS ``_prefill``'s math — the shared
            ``_prefill_half`` closure (lane slice, padded-tail
            overwrite-before-readable, boundary-token sampling fused in);
            the decode half is byte-identical math to
            ``_decode_pl`` (same feed rule, same fold_in(seed, pos) draws)
            — lanes are a batch axis, so the admitting lane's fresh KV is
            invisible to the generating lanes' attention and their token
            streams equal the unfused path's exactly. The admitting lane
            rides the decode batch too, parked at position seq_len (its
            junk write drops, its junk sample is overwritten below).

            Carry: the admitting lane's slot holds the chunk's boundary
            token (greedy at temp 0, fused-sampled otherwise — exactly the
            first generated token when this is the FINAL chunk), so the
            next dispatch can feed a freshly admitted lane without any
            host round-trip; mid-prompt that slot is junk the same way an
            idle lane's is. Output is ONE [2, n+1] pack: decode greedy/
            sampled rows plus the prefill boundary pair in the extra
            column."""
            _, p_greedy, p_sampled, cache = _prefill_half(
                params, cache, p_lane, p_tokens, p_start, p_n,
                p_temp, p_topp, p_seed, gtab, p_g,
            )
            pos = _eff_positions(carry_pos, positions)
            gs = _eff_g(carry_g, gs_host)
            _, greedy, sampled, new_g, cache = _decode_core(
                params, cache, feed, pos, temps, topps, seeds, gtab, gs
            )
            nxt = jnp.where(temps == 0.0, greedy, sampled)
            # host-exact admissions never take the fused path, so the
            # boundary feed rule is the plain temp-0-greedy-else-sampled
            # select the sync _prefill_step applies
            p_first = jnp.where(p_temp == 0.0, p_greedy, p_sampled)
            nxt = nxt.at[p_lane].set(p_first)
            # the joined lane's NEXT write position is the chunk boundary:
            # carried on device so the lane can ride spec steps immediately
            new_pos = jnp.minimum(pos + 1, cfg.seq_len)
            new_pos = new_pos.at[p_lane].set(p_start + p_n)
            # its grammar carry joins the same way: start state advanced
            # by the boundary token (junk mid-prompt; final chunk wins)
            new_g = new_g.at[p_lane].set(_g_next1(gtab, p_g, p_first))
            packed = jnp.concatenate(
                [
                    jnp.stack([greedy, sampled]),
                    jnp.stack([p_greedy, p_sampled])[:, None],
                ],
                axis=1,
            )
            return (
                rep_tokens(nxt),
                rep_tokens(new_pos),
                rep_tokens(new_g),
                rep_tokens(packed),
                cache,
            )

        @partial(jax.jit, donate_argnums=(0,))
        def _copy_lane(cache, src, dst):
            # whole-lane KV copy (prefix caching): static shapes mean ONE
            # compile for any prefix length; slots past the shared prefix
            # are garbage for dst, but dst's prefill rewrites them before
            # any query can read them (the chunked-prefill invariant). The
            # copy is an HBM-to-HBM move (~cache-lane bytes), orders of
            # magnitude cheaper than re-prefilling the prefix.
            k_src = jax.lax.dynamic_index_in_dim(cache.k, src, axis=1, keepdims=False)
            v_src = jax.lax.dynamic_index_in_dim(cache.v, src, axis=1, keepdims=False)
            return KVCache(
                k=cache.k.at[:, dst].set(k_src),
                v=cache.v.at[:, dst].set(v_src),
            )

        @partial(jax.jit, donate_argnums=(0,))
        def _copy_page(cache, src, dst):
            # single-page HBM copy — the paged path's copy-on-write unit
            # (page_size tokens x all layers, vs _copy_lane's whole-lane
            # move): traced scalars mean ONE compile for any (src, dst)
            # pair. Slots past the divergence point carry the source's
            # stale content, which the tail prefill rewrites before any
            # query can read them (the chunked-prefill invariant).
            k_src = jax.lax.dynamic_index_in_dim(cache.k, src, axis=1, keepdims=False)
            v_src = jax.lax.dynamic_index_in_dim(cache.v, src, axis=1, keepdims=False)
            return PagedKVCache(
                k=cache.k.at[:, dst].set(k_src),
                v=cache.v.at[:, dst].set(v_src),
                table=cache.table,
            )

        self._copy_page_fn = _copy_page

        @partial(jax.jit, donate_argnums=(0,))
        def _write_page(cache, page, k_page, v_page):
            # whole-page K/V write — the disagg IMPORT unit (a page
            # arriving from a peer replica lands here). Traced page
            # scalar + fixed host-array operand avals: ONE compile for
            # any destination page, warmed at warmup like the COW copy.
            return PagedKVCache(
                k=cache.k.at[:, page].set(k_page),
                v=cache.v.at[:, page].set(v_page),
                table=cache.table,
            )

        self._write_page_fn = _write_page

        @jax.jit
        def _gather_pages(cache, idx):
            # batched page READ for host swap-out: NOT donated — the
            # cache stays the live serving pytree, and dispatch order
            # (this read before any later-dispatched donated write)
            # guarantees the gathered bytes are the pre-eviction content
            # even when the pages are already re-popped for the same
            # admission. Fixed [_SWAP_BATCH] idx operand: ONE compile.
            return cache.k[:, idx], cache.v[:, idx]

        self._gather_pages_fn = _gather_pages

        @partial(jax.jit, donate_argnums=(0,))
        def _scatter_pages(cache, idx, k_pages, v_pages):
            # batched page WRITE for host swap-in — the donated cache
            # pytree orders it before any later-dispatched tail
            # prefill/decode, exactly like a COW copy, and the fixed
            # [_SWAP_BATCH] operand shapes mean ONE compile for any
            # destination set (padding repeats a real page with its own
            # content — an idempotent duplicate write)
            return PagedKVCache(
                k=cache.k.at[:, idx].set(k_pages),
                v=cache.v.at[:, idx].set(v_pages),
                table=cache.table,
            )

        self._scatter_pages_fn = _scatter_pages

        def _make_decode_multi(h):
            @partial(jax.jit, donate_argnums=(1,))
            def _decode_multi(params, cache, tokens, positions, temps, topps,
                              seeds, gtab, gs):
                """h chained decode steps in ONE device program (lax.scan):
                greedy lanes feed argmax forward, device-sampled lanes feed
                their fused sample (same fold_in(seed, pos) stream as h
                single steps — the token sequences are identical). One
                [h, n] transfer replaces h round trips; through a
                high-latency device link (the serving loop's regime) the
                per-token dispatch overhead drops by h. Host-side EOS/stop
                handling is retroactive: steps past a lane's stop write
                junk KV that the overwrite-before-readable invariant
                (chunked prefill, spec verify) already covers. The grammar
                state threads the scan carry like the position does."""
                def body(carry, _):
                    tok, pos, g, cache = carry
                    logits, cache = llama_forward(
                        cfg, params, tok[:, None], pos[:, None], cache,
                        emulate_q80_activations=q80, mesh=sp_mesh,
                        q80_sync=q80s,
                    )
                    step = logits[:, 0, :]
                    mstep = _g_mask_rows(gtab, g, step)
                    greedy = jnp.argmax(mstep, axis=-1).astype(jnp.int32)
                    sampled = _sample_lanes_or_greedy(
                        mstep, temps, topps, seeds, pos, greedy
                    )
                    nxt = jnp.where(temps == 0.0, greedy, sampled)
                    return (nxt, pos + 1, _g_next(gtab, g, nxt), cache), nxt

                (_, _, _, cache), chosen = jax.lax.scan(
                    body, (tokens, positions, gs, cache), None, length=h
                )
                return rep_tokens(chosen), cache  # chosen [h, n]

            return _decode_multi

        self._make_decode_multi = _make_decode_multi
        self._decode_multi_fns: dict[int, object] = {}

        self._copy_lane_fn = _copy_lane
        self._decode_prefill_fn = _decode_prefill
        self._decode_fn = _decode
        self._decode_nologits_fn = _decode_nologits
        self._decode_pl_fn = _decode_pl
        self._decode_spec_pl_fn = _decode_spec_pl
        self._decode_spec_prefill_fn = _decode_spec_prefill
        self._prefill_fn = _prefill
        # AOT-compiled decode executable (set by collective_stats, which
        # must lower+compile to read the post-SPMD HLO): reused for dispatch
        # so --benchmark mesh runs don't compile the decode step twice
        self._decode_exec = None

    # -- grammar-constrained decoding (grammar/) ----------------------------

    # the scheduler gates response_format requests on this; pod roots
    # broadcast attach/detach as OP_GRAMMAR packets (RootControlEngine)
    @property
    def supports_grammar(self) -> bool:
        return self._g_vocab is not None

    def grammar_init(self, token_table, eos_ids) -> None:
        """Register the tokenizer's piece table (raw bytes per token id,
        None for special tokens) + EOS ids — what the automaton compiler
        walks. Model vocab padding beyond the tokenizer table compiles as
        illegal-everywhere. Without this call, ``response_format``
        requests are refused (the --grammar off escape hatch)."""
        from ..grammar.automaton import vocab_fingerprint

        table = list(token_table)[: self.config.vocab_size]
        table += [None] * (self.config.vocab_size - len(table))
        self._g_vocab = table
        self._g_vocab_key = vocab_fingerprint(table)
        self._g_eos = tuple(int(e) for e in eos_ids)  # dlint: ok[host-sync] eos_ids are host ints from the tokenizer, never device values

    def grammar_attach(self, rf: dict):
        """Compile ``response_format`` (cached per (vocab, schema)) and
        install it into the slab; returns the :class:`~..grammar.slab.
        SlabHandle` whose ``start_state`` the lane's grammar carry seeds
        from. Raises the ValueError family (GrammarError) on a bad
        schema — request-scoped, a 400 — and
        :class:`~..grammar.slab.GrammarSlabFull` when live schemas
        exhaust the slab (load: the scheduler sheds it retryably)."""
        if self._g_vocab is None:
            raise ValueError(
                "structured output is disabled on this engine "
                "(--grammar off, or no tokenizer vocab registered)"
            )
        from ..grammar.automaton import compile_automaton

        auto = compile_automaton(
            rf, self._g_vocab, self._g_eos, vocab_key=self._g_vocab_key
        )
        handle = self.grammar_slab.attach(auto)
        with self.stats.lock:
            self.stats.grammar_lanes += 1
        return handle

    def grammar_detach(self, key: str) -> None:
        """Release one attach reference (the tables park for the next
        same-schema admission; evicted only under slab pressure)."""
        self.grammar_slab.detach(key)

    def grammar_stats(self) -> dict:
        """Slab pressure snapshot for /stats; {} when grammar is off."""
        return (
            self.grammar_slab.stats() if self._g_vocab is not None else {}
        )

    def _gtab(self):
        """The slab's device copies, re-uploaded only when the slab
        version moved (a new schema installed / an entry evicted) —
        shapes are capacity-fixed and the leaves go through
        ``_replace_leaf``, so this is never a recompile."""
        if self._g_version != self.grammar_slab.version:
            masks, ek, en, dflt = self.grammar_slab.arrays()
            self._g_dev = tuple(
                self._replace_leaf(a, self._g_sharding)
                for a in (masks, ek, en, dflt)
            )
            self._g_version = self.grammar_slab.version
        return self._g_dev

    def _g_vec(self, g_states, reseed: bool) -> np.ndarray:
        """Default grammar-state vector: all-FREE on a reseed (there is
        no carry), all-carry (-1) on a chained dispatch — so engines
        serving no constrained lane behave exactly as before."""
        if g_states is not None:
            return g_states
        if reseed:
            return np.zeros(self.n_lanes, np.int32)
        return np.full(self.n_lanes, -1, np.int32)

    # -- public API ---------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    def max_chunk(self) -> int:
        return self.prefill_buckets[-1]

    def prefill_chunk(
        self,
        lane: int,
        chunk: list[int],
        start_pos: int,
        temp: float = 0.0,
        topp: float = DEFAULT_TOPP,
        seed: int = 0,
        g_state: int = 0,
    ):
        """One bucketed prompt chunk for one lane — the unit the scheduler
        interleaves between decode steps so active lanes never stall more
        than one bucket (VERDICT Weak #2). Returns (last_logits [vocab]
        device array, greedy_token int, sampled_token int — equals greedy
        at temp 0)."""
        if len(chunk) > self.max_chunk():
            raise ValueError(f"chunk of {len(chunk)} exceeds bucket {self.max_chunk()}")
        if start_pos + len(chunk) > self.config.seq_len:
            raise ValueError(
                f"chunk of {len(chunk)} tokens at pos {start_pos} exceeds "
                f"seq_len {self.config.seq_len}"
            )
        faults.fire("engine.dispatch")  # chaos harness; no-op unarmed
        t0 = time.perf_counter()
        bucket = self.bucket_for(len(chunk))
        padded = np.zeros(bucket, np.int32)
        padded[: len(chunk)] = chunk
        last, toks, self.cache = self._prefill_fn(
            self.params,
            self.cache,
            jnp.int32(lane),
            jnp.asarray(padded),
            jnp.int32(start_pos),
            jnp.int32(len(chunk)),
            jnp.float32(temp),
            jnp.float32(topp),
            jnp.uint32(seed & 0xFFFFFFFF),
            self._gtab(),
            jnp.int32(g_state),
        )
        # dlint: ok[host-sync] the one [2] int32 readback per prefill chunk (greedy+sampled), counted below
        toks_np = np.asarray(toks)
        greedy = int(toks_np[0])
        sampled = int(toks_np[1])
        with self.stats.lock:
            self.stats.host_bytes_in += toks_np.nbytes
            self.stats.prefill_s += time.perf_counter() - t0
            self.stats.prefill_tokens += len(chunk)
        return last, greedy, sampled

    def prefill(
        self,
        lane: int,
        tokens: list[int],
        start_pos: int = 0,
        temp: float = 0.0,
        topp: float = DEFAULT_TOPP,
        seed: int = 0,
        g_state: int = 0,
    ):
        """Process a full prompt on one lane in bucketed chunks. Returns
        (last_logits np[vocab], greedy_token int, total_positions)."""
        if not tokens:
            raise ValueError("prefill needs at least one token (empty prompt)")
        pos = start_pos
        remaining = list(tokens)
        last = greedy = None
        while remaining:
            chunk = remaining[: self.max_chunk()]
            remaining = remaining[len(chunk) :]
            last, greedy, self.last_sampled = self.prefill_chunk(
                lane, chunk, pos, temp=temp, topp=topp, seed=seed,
                g_state=g_state,
            )
            pos += len(chunk)
        return last, greedy, pos

    def decode(
        self,
        tokens: np.ndarray,
        positions: np.ndarray,
        temps: np.ndarray | None = None,
        topps: np.ndarray | None = None,
        seeds: np.ndarray | None = None,
        want_logits: bool = True,
        g_states: np.ndarray | None = None,
    ):
        """One decode step for all lanes. tokens/positions: int32 [n_lanes]
        (idle lanes: any in-range position; their writes are never readable).
        temps/topps/seeds (optional, [n_lanes]) drive on-device sampling.
        Returns (logits device-array [n_lanes, vocab], greedy np[n_lanes],
        sampled np[n_lanes] — equals greedy where temps == 0).

        ``want_logits=False`` (the common all-device-sampling step — no
        host-exact lane will read them) returns None logits and runs the
        no-logits-output program: the [n_lanes, vocab] f32 row is never
        materialized, so it pins no HBM between steps."""
        n = self.n_lanes
        if temps is None:
            temps = np.zeros(n, np.float32)
        if topps is None:
            topps = np.full(n, DEFAULT_TOPP, np.float32)
        if seeds is None:
            seeds = np.zeros(n, np.uint32)
        faults.fire("engine.dispatch")  # chaos harness; no-op unarmed
        t0 = time.perf_counter()
        if g_states is None:
            g_states = np.zeros(n, np.int32)
        operands = (
            self.params,
            self.cache,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(temps, jnp.float32),
            jnp.asarray(topps, jnp.float32),
            jnp.asarray(seeds, jnp.uint32),
            self._gtab(),
            jnp.asarray(g_states, jnp.int32),
        )
        if want_logits:
            fn = self._decode_exec if self._decode_exec is not None else self._decode_fn
            logits, toks, self.cache = fn(*operands)
        else:
            logits = None
            toks, self.cache = self._decode_nologits_fn(*operands)
        # dlint: ok[host-sync] the ONE [2, n] int32 readback per decode step (greedy+sampled rows), counted below
        toks_np = np.asarray(toks)
        greedy_np, sampled_np = toks_np[0], toks_np[1]
        with self.stats.lock:
            self.stats.host_bytes_in += toks_np.nbytes
            self.stats.decode_s += time.perf_counter() - t0
            self.stats.decode_steps += 1
            self.stats.sync_bytes_total += self.stats.sync_bytes_per_decode
        return logits, greedy_np, sampled_np

    # pod roots broadcast multi-step decodes as OP_DECODE_MULTI packets
    supports_multi_step = True

    def decode_multi(
        self,
        tokens: np.ndarray,
        positions: np.ndarray,
        temps: np.ndarray | None = None,
        topps: np.ndarray | None = None,
        seeds: np.ndarray | None = None,
        h: int = 8,
        g_states: np.ndarray | None = None,
    ) -> np.ndarray:
        """``h`` chained decode steps for all lanes in one device dispatch.

        Feed rule per lane and step: greedy lanes (temp 0) continue with
        argmax, device-sampled lanes with the fused sampler — byte-identical
        to ``h`` successive ``decode`` calls (same fold_in(seed, pos) draw
        per position). Host-exact-sampling lanes are NOT supported (they
        need full logits on host every step); callers gate on that.

        Returns ``chosen`` np[h, n]: the token each lane would feed at step
        j+1. The caller consumes its current next_token plus chosen[:h-1]
        and adopts chosen[h-1] as the new next_token, discarding everything
        after a lane's stop condition — junk KV from discarded steps is
        rewritten before any query can read it (the chunked-prefill
        invariant; see _decode_multi)."""
        n = self.n_lanes
        if temps is None:
            temps = np.zeros(n, np.float32)
        if topps is None:
            topps = np.full(n, DEFAULT_TOPP, np.float32)
        if seeds is None:
            seeds = np.zeros(n, np.uint32)
        if g_states is None:
            g_states = np.zeros(n, np.int32)
        fn = self._decode_multi_fns.get(h)
        if fn is None:
            fn = self._decode_multi_fns[h] = self._make_decode_multi(h)
        faults.fire("engine.dispatch")  # chaos harness; no-op unarmed
        t0 = time.perf_counter()
        chosen, self.cache = fn(
            self.params,
            self.cache,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(temps, jnp.float32),
            jnp.asarray(topps, jnp.float32),
            jnp.asarray(seeds, jnp.uint32),
            self._gtab(),
            jnp.asarray(g_states, jnp.int32),
        )
        # dlint: ok[host-sync] the ONE [h, n] int32 readback per multi-step dispatch, counted below
        chosen_np = np.asarray(chosen)
        with self.stats.lock:
            self.stats.host_bytes_in += chosen_np.nbytes
            self.stats.decode_s += time.perf_counter() - t0
            self.stats.decode_steps += h
            self.stats.multi_dispatches += 1
            self.stats.sync_bytes_total += h * self.stats.sync_bytes_per_decode
        return chosen_np

    # pod roots broadcast pipelined dispatches as OP_DECODE_PIPELINED packets
    supports_pipelined = True

    def pipeline_inflight(self) -> int:
        """Dispatched-but-unconsumed pipelined steps (ring occupancy)."""
        return len(self._pl_inflight)

    @property
    def pipeline_active(self) -> bool:
        """True while a pipelined chain holds state (in-flight steps or a
        device token carry) — direct decode/spec callers must flush first."""
        return len(self._pl_inflight) > 0 or self._pl_carry is not None

    def decode_pipelined(
        self,
        positions: np.ndarray,
        temps: np.ndarray | None = None,
        topps: np.ndarray | None = None,
        seeds: np.ndarray | None = None,
        tokens: np.ndarray | None = None,
        g_states: np.ndarray | None = None,
    ) -> None:
        """Dispatch ONE pipelined decode step and return without reading
        anything back (JAX async dispatch queues the program immediately).

        ``tokens=None`` feeds the ON-DEVICE carry — the previous step's
        per-lane where(temp==0, greedy, sampled) select, which is exactly
        the token the synchronous loop would have fed after its readback —
        so chained dispatches never round-trip tokens through the host.
        Passing a host ``tokens`` array (re)seeds the chain (the first step
        after a flush). Temps/topps/seeds are host metadata riding each
        dispatch without any sync; a position of ``-1`` selects the
        DEVICE-CARRIED position for that lane (required once a spec verify
        step — whose per-lane accept count the host learns one step late —
        is anywhere in the chain), while ``>= 0`` overrides from host
        metadata (parked/admitting lanes at seq_len, real positions on a
        reseed — a reseed must not pass -1 anywhere, there is no carry).

        The ring is bounded at ``pipeline_depth``: callers must
        ``pipeline_consume()`` the oldest step before dispatching past it.
        Junk steps dispatched after a lane's (not-yet-discovered) stop are
        safe: their KV writes land above the lane's committed tokens and are
        rewritten before any query reads them — the same discard rule
        chunked prefill and ``decode_multi`` overshoot rely on."""
        n = self.n_lanes
        if temps is None:
            temps = np.zeros(n, np.float32)
        if topps is None:
            topps = np.full(n, DEFAULT_TOPP, np.float32)
        if seeds is None:
            seeds = np.zeros(n, np.uint32)
        self.check_pipelined_dispatch(tokens is not None, positions,
                                      g_states)
        faults.fire("engine.dispatch")  # chaos harness; no-op unarmed
        g_states = self._g_vec(g_states, tokens is not None)
        feed, carry_pos, carry_g = self._pl_feed(tokens, positions)
        nxt, new_pos, new_g, packed, self.cache = self._decode_pl_fn(
            self.params,
            self.cache,
            feed,
            carry_pos,
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(temps, jnp.float32),
            jnp.asarray(topps, jnp.float32),
            jnp.asarray(seeds, jnp.uint32),
            self._gtab(),
            carry_g,
            jnp.asarray(g_states, jnp.int32),
        )
        self._pl_carry = nxt
        self._pl_carry_pos = new_pos
        self._pl_carry_g = new_g
        self._pl_inflight.append(("tok", packed, time.perf_counter()))
        with self.stats.lock:
            self.stats.pipeline_dispatches += 1
            self.stats.sync_bytes_total += self.stats.sync_bytes_per_decode
            d = len(self._pl_inflight)
            self.stats.pipeline_depth_hist[d] = (
                self.stats.pipeline_depth_hist.get(d, 0) + 1
            )

    # pod roots broadcast fused admission steps as OP_DECODE_PREFILL_FUSED
    supports_fused_prefill = True

    def _pl_feed(self, tokens, positions):
        """Resolve the (feed tokens, carried positions, carried grammar
        states) operand triple for a pipelined-family dispatch: the
        device carries when chained (``tokens is None``), host arrays on
        a reseed — where the carried operands are zeros placeholders the
        ``-1`` selects never read, because a reseed must pass real
        positions (and grammar states) everywhere."""
        if tokens is None:
            return self._pl_carry, self._pl_carry_pos, self._pl_carry_g
        z = jnp.zeros(self.n_lanes, jnp.int32)
        return jnp.asarray(tokens, jnp.int32), z, z

    def check_pipelined_dispatch(self, reseed: bool,
                                 positions=None,
                                 g_states=None) -> None:
        """Raise every host-side error a pipelined dispatch would, WITHOUT
        dispatching: pod roots call this before broadcasting the control
        packet so a bad call dies on the root with ZERO packets out — a
        packet whose root-side compute never happens leaves worker rings
        and carries desynced and deadlocks the next collective. The
        reseed-position rule is part of this set for the same reason: a
        ``-1`` carried-position sentinel on a reseed (there is no carry to
        read) must die BEFORE any packet, not in every process's
        ``_pl_feed`` mid-replay."""
        if reseed and positions is not None and int(np.min(positions)) < 0:
            raise ValueError(
                "reseed dispatch with a -1 position: the carried-position "
                "select has no carry to read on a reseed — pass real "
                "positions for every lane"
            )
        if reseed and g_states is not None and int(np.min(g_states)) < 0:
            raise ValueError(
                "reseed dispatch with a -1 grammar state: the carried-"
                "state select has no carry to read on a reseed — pass "
                "real states (0 = unconstrained) for every lane"
            )
        if len(self._pl_inflight) >= max(1, self.pipeline_depth):
            raise RuntimeError(
                f"pipeline ring full (depth {self.pipeline_depth}): consume "
                "the oldest in-flight step before dispatching another"
            )
        if not reseed and self._pl_carry is None:
            raise RuntimeError(
                "no device token carry: seed the chain with tokens= "
                "(first dispatch after construction or a flush)"
            )

    def check_fused_dispatch(self, chunk, p_start: int, reseed: bool,
                             positions=None, g_states=None) -> None:
        """``check_pipelined_dispatch`` plus the prompt-chunk bounds the
        fused prefill half enforces — the full pre-broadcast validation
        set for OP_DECODE_PREFILL_FUSED."""
        if not chunk:
            raise ValueError("fused prefill needs a non-empty prompt chunk")
        if len(chunk) > self.max_chunk():
            raise ValueError(
                f"chunk of {len(chunk)} exceeds bucket {self.max_chunk()}"
            )
        if p_start + len(chunk) > self.config.seq_len:
            raise ValueError(
                f"chunk of {len(chunk)} tokens at pos {p_start} exceeds "
                f"seq_len {self.config.seq_len}"
            )
        self.check_pipelined_dispatch(reseed, positions, g_states)

    def decode_prefill_fused(
        self,
        positions: np.ndarray,
        temps: np.ndarray | None = None,
        topps: np.ndarray | None = None,
        seeds: np.ndarray | None = None,
        p_lane: int = 0,
        chunk: list[int] | None = None,
        p_start: int = 0,
        p_temp: float = 0.0,
        p_topp: float = DEFAULT_TOPP,
        p_seed: int = 0,
        tokens: np.ndarray | None = None,
        g_states: np.ndarray | None = None,
        p_g: int = 0,
    ) -> None:
        """Dispatch ONE fused prefill+decode step into the pipelined ring:
        every generating lane advances one token (the ``decode_pipelined``
        feed rule, carry and all) AND lane ``p_lane`` consumes one bounded
        prompt chunk — the same dispatch, the same compiled program (one
        per prefill bucket). Admissions therefore ride the live chain
        instead of flushing it: the chain's dispatch cadence is untouched
        and ``pipeline_flushes`` stays 0 under steady churn.

        The admitting lane's decode-batch position must park at seq_len
        (callers pass it that way; its junk decode write drops under the
        mode="drop" scatter — the chunk's own KV writes are the real
        ones). The carry slot for ``p_lane`` comes back as the chunk's
        boundary token, so when this is the prompt's final chunk the NEXT
        dispatch can feed the freshly admitted lane straight from device.
        Consume via ``pipeline_consume`` like any other step; the packed
        readback is [2, n+1], the extra column being the boundary
        greedy/sampled pair.

        Junk-KV safety is the ``prefill_chunk`` contract verbatim: padded
        tail writes and any in-flight decode overshoot land in slots that
        are rewritten before any query can read them."""
        n = self.n_lanes
        if temps is None:
            temps = np.zeros(n, np.float32)
        if topps is None:
            topps = np.full(n, DEFAULT_TOPP, np.float32)
        if seeds is None:
            seeds = np.zeros(n, np.uint32)
        self.check_fused_dispatch(chunk, p_start, tokens is not None,
                                  positions, g_states)
        faults.fire("engine.dispatch")  # chaos harness; no-op unarmed
        g_states = self._g_vec(g_states, tokens is not None)
        feed, carry_pos, carry_g = self._pl_feed(tokens, positions)
        bucket = self.bucket_for(len(chunk))
        padded = np.zeros(bucket, np.int32)
        padded[: len(chunk)] = chunk
        nxt, new_pos, new_g, packed, self.cache = self._decode_prefill_fn(
            self.params,
            self.cache,
            feed,
            carry_pos,
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(temps, jnp.float32),
            jnp.asarray(topps, jnp.float32),
            jnp.asarray(seeds, jnp.uint32),
            jnp.int32(p_lane),
            jnp.asarray(padded),
            jnp.int32(p_start),
            jnp.int32(len(chunk)),
            jnp.float32(p_temp),
            jnp.float32(p_topp),
            jnp.uint32(p_seed & 0xFFFFFFFF),
            self._gtab(),
            carry_g,
            jnp.asarray(g_states, jnp.int32),
            jnp.int32(p_g),
        )
        self._pl_carry = nxt
        self._pl_carry_pos = new_pos
        self._pl_carry_g = new_g
        self._pl_inflight.append(("tok", packed, time.perf_counter()))
        with self.stats.lock:
            self.stats.pipeline_dispatches += 1
            self.stats.fused_steps += 1
            self.stats.sync_bytes_total += self.stats.sync_bytes_per_decode
            self.stats.prefill_tokens += len(chunk)
            self.stats.fused_bucket_hist[bucket] = (
                self.stats.fused_bucket_hist.get(bucket, 0) + 1
            )
            d = len(self._pl_inflight)
            self.stats.pipeline_depth_hist[d] = (
                self.stats.pipeline_depth_hist.get(d, 0) + 1
            )

    def pipeline_consume(self):
        """Blocking readback of the OLDEST in-flight pipelined step — the
        lagged half of the pipeline: while this step's tokens cross to the
        host, the younger dispatches keep the device busy.

        Plain/fused steps return (greedy np[n|n+1], sampled np[n|n+1]) —
        the [2, n] token rows, plus the chunk's boundary pair in the extra
        column for a fused step; the token a lane fed into the NEXT
        in-flight step is greedy[i] for temp-0 lanes and sampled[i]
        otherwise (the on-device feed rule). SPEC verify steps
        (``decode_spec_pipelined`` family) return
        (emitted np[n(+1), K+1], n_emit np[n(+1)]) — ``decode_spec``'s
        readback shape, with the boundary pair riding ``emitted[-1, :2]``
        when the step also carried a chunk. Callers know which kind they
        dispatched (the scheduler's meta deque records it)."""
        if not self._pl_inflight:
            raise RuntimeError("pipeline ring empty: nothing to consume")
        faults.fire("engine.consume")  # chaos harness; no-op unarmed
        kind, packed, dispatched_at = self._pl_inflight.popleft()
        t0 = time.perf_counter()
        # dlint: ok[host-sync] the lagged ONE packed int32 readback per pipelined step, counted below
        toks_np = np.asarray(packed)
        t1 = time.perf_counter()
        with self.stats.lock:
            self.stats.host_bytes_in += toks_np.nbytes
            self.stats.decode_s += t1 - t0
            self.stats.decode_steps += 1
            # host time between this step's dispatch and the start of its
            # readback: work the device execution hid (the synchronous path
            # serializes exactly this span)
            self.stats.overlap_s += max(0.0, t0 - dispatched_at)
        if kind == "spec":
            return toks_np[:, :-1], toks_np[:, -1]
        return toks_np[0], toks_np[1]

    def pipeline_flush(self, count: bool = True) -> int:
        """Drain every in-flight step (DISCARDING the tokens) and drop the
        device carry; the next dispatch must reseed with host tokens.
        Returns how many steps were discarded. The scheduler drains valid
        chains through ``pipeline_consume`` and only calls this for the
        carry reset, so a non-zero return here means an abort (counted in
        ``stats.pipeline_flushes``). ``count=False`` drains without the
        abort accounting — pod workers' rings lag the root by design, so
        their drain at a clean chain end is expected, not an abort."""
        n = len(self._pl_inflight)
        while self._pl_inflight:
            self.pipeline_consume()
        self._pl_carry = None
        self._pl_carry_pos = None
        self._pl_carry_g = None
        if n and count:
            with self.stats.lock:
                self.stats.pipeline_flushes += 1
        return n

    def pipeline_abort(self) -> int:
        """Containment primitive (the supervised scheduler loop's engine-
        failure path): drop every in-flight step WITHOUT reading anything
        back, and drop the carry. ``pipeline_flush`` drains through
        ``pipeline_consume`` — but after an engine-scoped failure each
        readback of a poisoned step would re-raise the same error, so
        containment must be able to abandon the ring host-side. The
        device buffers are released with the dropped references; the next
        chain reseeds from host tokens like any post-flush dispatch, and
        the affected lanes' KV is treated as garbage (the scheduler
        discards their resident-KV maps). Counts as a pipeline flush —
        an aborted chain is the definition of one."""
        n = len(self._pl_inflight)
        self._pl_inflight.clear()
        self._pl_carry = None
        self._pl_carry_pos = None
        self._pl_carry_g = None
        if n:
            with self.stats.lock:
                self.stats.pipeline_flushes += 1
        return n

    # drafts per speculative step (K = SPEC_DRAFT + 1 verified tokens)
    SPEC_DRAFT = SPEC_DRAFT
    # pod roots forward this via RootControlEngine.__getattr__ and broadcast
    # verify steps as OP_DECODE_SPEC control packets
    supports_speculative = True

    def decode_spec(
        self,
        tokens: np.ndarray,
        drafts: np.ndarray,
        draft_len: np.ndarray,
        positions: np.ndarray,
        temps: np.ndarray | None = None,
        topps: np.ndarray | None = None,
        seeds: np.ndarray | None = None,
        g_states: np.ndarray | None = None,
    ):
        """One speculative decode step for all lanes: verifies each lane's
        next token plus up to SPEC_DRAFT drafted continuations in a single
        forward. tokens/positions/draft_len: [n_lanes]; drafts:
        [n_lanes, SPEC_DRAFT]. Greedy lanes emit their plain-decode token
        stream exactly (speculative-verification identity); temp>0 lanes
        must pass draft_len 0 and emit one fused-sampled token.

        Caller contract (per lane): draft_len[i] <= seq_len - positions[i]
        - 1, so every emitted token's logits row has in-bounds KV behind it;
        overshooting draft-slot KV writes are dropped by the cache scatter.
        Returns (step_logits [n, vocab] device array, emitted np[n, K],
        n_emit np[n])."""
        n = self.n_lanes
        if temps is None:
            temps = np.zeros(n, np.float32)
        if topps is None:
            topps = np.full(n, DEFAULT_TOPP, np.float32)
        if seeds is None:
            seeds = np.zeros(n, np.uint32)
        if g_states is None:
            g_states = np.zeros(n, np.int32)
        faults.fire("engine.dispatch")  # chaos harness; no-op unarmed
        t0 = time.perf_counter()
        logits, packed_out, self.cache = self._decode_spec_fn(
            self.params,
            self.cache,
            jnp.asarray(tokens, jnp.int32),
            jnp.asarray(drafts, jnp.int32),
            jnp.asarray(draft_len, jnp.int32),
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(temps, jnp.float32),
            jnp.asarray(topps, jnp.float32),
            jnp.asarray(seeds, jnp.uint32),
            self._gtab(),
            jnp.asarray(g_states, jnp.int32),
        )
        # dlint: ok[host-sync] the ONE [n, K+1] int32 readback per speculative verify step, counted below
        out_np = np.asarray(packed_out)
        emitted, n_emit = out_np[:, :-1], out_np[:, -1]
        with self.stats.lock:
            self.stats.host_bytes_in += out_np.nbytes
            self.stats.decode_s += time.perf_counter() - t0
            self.stats.decode_steps += 1
            self.stats.spec_steps += 1
            self.stats.sync_bytes_total += self.stats.sync_bytes_per_decode
        return logits, emitted, n_emit

    # pod roots broadcast in-chain spec verify steps as
    # OP_DECODE_SPEC_PIPELINED / OP_DECODE_SPEC_PREFILL_FUSED packets
    supports_spec_pipelined = True

    def check_spec_drafts(self, drafts) -> None:
        """THE draft-shape contract, in one place: every spec-pipelined
        entry point (engine dispatch, fused variant, and the pod root's
        pre-broadcast validation) calls this, so a future layout change
        cannot silently diverge one copy from the others."""
        shape = getattr(drafts, "shape", None)
        want = (self.n_lanes, self.SPEC_DRAFT + 1)
        if shape != want:
            raise ValueError(
                f"spec drafts shape {shape} != {want} (SPEC_DRAFT + 1 "
                "columns: candidate 0 is the host's guess at the carry "
                "token itself)"
            )

    def check_spec_pipelined_dispatch(self, drafts, reseed: bool,
                                      positions=None,
                                      g_states=None) -> None:
        """``check_pipelined_dispatch`` plus the draft-shape contract —
        the full pre-broadcast validation set for OP_DECODE_SPEC_PIPELINED
        (a packet whose root-side compute raises desyncs the pod)."""
        self.check_spec_drafts(drafts)
        self.check_pipelined_dispatch(reseed, positions, g_states)

    def decode_spec_pipelined(
        self,
        positions: np.ndarray,
        drafts: np.ndarray,
        draft_len: np.ndarray,
        temps: np.ndarray | None = None,
        topps: np.ndarray | None = None,
        seeds: np.ndarray | None = None,
        tokens: np.ndarray | None = None,
        g_states: np.ndarray | None = None,
    ) -> None:
        """Dispatch ONE speculative verify step INTO the pipelined ring —
        the zero-flush composition of ``decode_spec`` and
        ``decode_pipelined``: up to SPEC_DRAFT host-shipped drafts are
        verified against the device's own token carry inside the async
        chain, the per-lane accepted counts advance the POSITION carry
        (``pos + accepted + 1``), and the lagged readback packs
        ``[n, K+1]`` emitted tokens + counts exactly like ``decode_spec``.
        The chain never aborts for a draft hit.

        ``drafts`` is ``[n, SPEC_DRAFT + 1]``: column 0 is the host's
        candidate for the carry token itself (the host's n-gram index is
        one step behind the device — the same lag the consume half already
        models), verified on device before the remaining K count; on a
        reseed the host knows the feed and ships it as candidate 0.
        ``draft_len`` counts the real candidates INCLUDING column 0, so a
        lane needs ``draft_len >= 2`` to possibly accept anything.
        Position semantics are ``decode_pipelined``'s (-1 = device carry).
        Consume via ``pipeline_consume``; junk steps racing a stop follow
        the same discard rule as every pipelined step."""
        n = self.n_lanes
        if temps is None:
            temps = np.zeros(n, np.float32)
        if topps is None:
            topps = np.full(n, DEFAULT_TOPP, np.float32)
        if seeds is None:
            seeds = np.zeros(n, np.uint32)
        # drafts arrive as a host ndarray from the scheduler's n-gram probe
        # (or the worker's packet slot view); shape-checked, never synced
        self.check_spec_pipelined_dispatch(drafts, tokens is not None,
                                           positions, g_states)
        faults.fire("engine.dispatch")  # chaos harness; no-op unarmed
        g_states = self._g_vec(g_states, tokens is not None)
        feed, carry_pos, carry_g = self._pl_feed(tokens, positions)
        nxt, new_pos, new_g, packed, self.cache = self._decode_spec_pl_fn(
            self.params,
            self.cache,
            feed,
            carry_pos,
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(drafts, jnp.int32),
            jnp.asarray(draft_len, jnp.int32),
            jnp.asarray(temps, jnp.float32),
            jnp.asarray(topps, jnp.float32),
            jnp.asarray(seeds, jnp.uint32),
            self._gtab(),
            carry_g,
            jnp.asarray(g_states, jnp.int32),
        )
        self._pl_carry = nxt
        self._pl_carry_pos = new_pos
        self._pl_carry_g = new_g
        self._pl_inflight.append(("spec", packed, time.perf_counter()))
        with self.stats.lock:
            self.stats.pipeline_dispatches += 1
            self.stats.spec_steps += 1
            self.stats.spec_pipelined_steps += 1
            self.stats.sync_bytes_total += self.stats.sync_bytes_per_decode
            d = len(self._pl_inflight)
            self.stats.pipeline_depth_hist[d] = (
                self.stats.pipeline_depth_hist.get(d, 0) + 1
            )

    def decode_spec_prefill_fused(
        self,
        positions: np.ndarray,
        drafts: np.ndarray,
        draft_len: np.ndarray,
        temps: np.ndarray | None = None,
        topps: np.ndarray | None = None,
        seeds: np.ndarray | None = None,
        p_lane: int = 0,
        chunk: list[int] | None = None,
        p_start: int = 0,
        p_temp: float = 0.0,
        p_topp: float = DEFAULT_TOPP,
        p_seed: int = 0,
        tokens: np.ndarray | None = None,
        g_states: np.ndarray | None = None,
        p_g: int = 0,
    ) -> None:
        """``decode_spec_pipelined`` that ALSO consumes one bounded prompt
        chunk for lane ``p_lane`` — the full zero-flush composition: an
        admitting chunk and a spec verify step share one dispatch, so
        speculation, fused admission, and pipelining multiply instead of
        trading off. Contracts are the union of ``decode_prefill_fused``
        (chunk bounds, boundary-token carry, junk-KV safety) and
        ``decode_spec_pipelined`` (draft alignment, position carry); the
        packed readback is ``[n+1, K+2]`` with the boundary greedy/sampled
        pair in ``emitted[-1, :2]``."""
        n = self.n_lanes
        if temps is None:
            temps = np.zeros(n, np.float32)
        if topps is None:
            topps = np.full(n, DEFAULT_TOPP, np.float32)
        if seeds is None:
            seeds = np.zeros(n, np.uint32)
        # host ndarray from the probe/packet — shape-checked, never synced
        self.check_spec_drafts(drafts)
        self.check_fused_dispatch(chunk, p_start, tokens is not None,
                                  positions, g_states)
        faults.fire("engine.dispatch")  # chaos harness; no-op unarmed
        g_states = self._g_vec(g_states, tokens is not None)
        feed, carry_pos, carry_g = self._pl_feed(tokens, positions)
        bucket = self.bucket_for(len(chunk))
        padded = np.zeros(bucket, np.int32)
        padded[: len(chunk)] = chunk
        nxt, new_pos, new_g, packed, self.cache = (
            self._decode_spec_prefill_fn(
                self.params,
                self.cache,
                feed,
                carry_pos,
                jnp.asarray(positions, jnp.int32),
                jnp.asarray(drafts, jnp.int32),
                jnp.asarray(draft_len, jnp.int32),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray(topps, jnp.float32),
                jnp.asarray(seeds, jnp.uint32),
                jnp.int32(p_lane),
                jnp.asarray(padded),
                jnp.int32(p_start),
                jnp.int32(len(chunk)),
                jnp.float32(p_temp),
                jnp.float32(p_topp),
                jnp.uint32(p_seed & 0xFFFFFFFF),
                self._gtab(),
                carry_g,
                jnp.asarray(g_states, jnp.int32),
                jnp.int32(p_g),
            )
        )
        self._pl_carry = nxt
        self._pl_carry_pos = new_pos
        self._pl_carry_g = new_g
        self._pl_inflight.append(("spec", packed, time.perf_counter()))
        with self.stats.lock:
            self.stats.pipeline_dispatches += 1
            self.stats.fused_steps += 1
            self.stats.spec_steps += 1
            self.stats.spec_pipelined_steps += 1
            self.stats.sync_bytes_total += self.stats.sync_bytes_per_decode
            self.stats.prefill_tokens += len(chunk)
            self.stats.fused_bucket_hist[bucket] = (
                self.stats.fused_bucket_hist.get(bucket, 0) + 1
            )
            d = len(self._pl_inflight)
            self.stats.pipeline_depth_hist[d] = (
                self.stats.pipeline_depth_hist.get(d, 0) + 1
            )

    def sample_token(
        self, logits_row, temp: float, topp: float, seed: int, pos: int
    ) -> int:
        """On-device sample from a single [vocab] logits row (the prefill
        boundary token), same kernel as the fused decode sampler."""
        tok = self._sample_one(
            jnp.asarray(logits_row),
            jnp.float32(temp),
            jnp.float32(topp),
            jnp.uint32(seed & 0xFFFFFFFF),
            jnp.int32(pos),
        )
        with self.stats.lock:
            self.stats.host_bytes_in += 4
        return int(tok)  # dlint: ok[host-sync] intentional 4-byte token transfer, counted above

    def collective_stats(self, refresh: bool = False) -> dict:
        """Estimated per-decode-step collective traffic from the compiled
        program's post-SPMD HLO — the analogue of the reference's per-socket
        byte counters (src/nn/nn-network.cpp:493-508). Returns {} off-mesh."""
        if self.mesh is None:
            return {}
        if getattr(self, "_coll_stats", None) is not None and not refresh:
            return self._coll_stats
        from ..parallel.comm_stats import collective_stats_of_compiled

        n = self.n_lanes
        z = np.zeros(n, np.int32)
        zf = np.zeros(n, np.float32)
        compiled = self._decode_fn.lower(
            self.params,
            self.cache,
            jnp.asarray(z),
            jnp.asarray(z),
            jnp.asarray(zf),
            jnp.asarray(zf),
            jnp.asarray(z.astype(np.uint32)),
            self._gtab(),
            jnp.asarray(z),
        ).compile()
        stats = collective_stats_of_compiled(compiled)
        # stamp the dequant path the compiled step bakes in (static
        # argname): per-step traffic numbers are only comparable across
        # runs when the kernel mode they were measured under is recorded
        from ..ops.dequant_select import dequant_stats

        stats.update(dequant_stats())
        # keep the executable for dispatch: decode shapes never change, so
        # this one AOT compile replaces the jit path's own compile
        self._decode_exec = compiled
        with self.stats.lock:
            self.stats.sync_bytes_per_decode = stats.get("total_bytes", 0)
            self.stats.sync_collectives_per_decode = stats.get("n_collectives", 0)
        self._coll_stats = stats
        return stats

    def measured_sync_stats(self, steps: int = 4) -> dict:
        """MEASURED per-decode-step time split from a profiler trace
        (parallel/comm_stats.measured_step_breakdown): device busy ms and
        collective (sync) ms per step — the measured analogue of the
        reference's per-token Sync readout (src/dllama.cpp:54-64), vs the
        static byte estimate of ``collective_stats``.

        Benchmark probe: it runs the decode step with zero tokens at
        position 0 on every lane, which REWRITES cache slot 0 — call it
        before serving or after generation, not mid-request."""
        from ..parallel.comm_stats import measured_step_breakdown

        z = np.zeros(self.n_lanes, np.int32)
        zf = np.zeros(self.n_lanes, np.float32)
        zu = np.zeros(self.n_lanes, np.uint32)

        def step():
            # decode returns host numpy for greedy, so it has already blocked
            self.decode(z, z, zf, zf, zu)

        with self.stats.preserved():
            return measured_step_breakdown(step, steps=steps)

    def lane_logits(self, logits, lane: int) -> np.ndarray:
        """Transfer one lane's logits to host (counted, for sampling)."""
        faults.fire("engine.transfer")  # chaos harness; no-op unarmed
        # dlint: ok[host-sync] sanctioned [vocab] f32 transfer API: the choke point that counts the bytes
        out = np.asarray(logits[lane])
        with self.stats.lock:
            self.stats.host_bytes_in += out.nbytes
        return out

    def all_logits(self, logits) -> np.ndarray:
        """Single batched device->host transfer of all lanes' logits."""
        faults.fire("engine.transfer")  # chaos harness; no-op unarmed
        # dlint: ok[host-sync] sanctioned batched [n, vocab] f32 transfer API: the choke point that counts the bytes
        out = np.asarray(logits)
        with self.stats.lock:
            self.stats.host_bytes_in += out.nbytes
        return out

    def copy_lane(self, src: int, dst: int,
                  prefix_len: int | None = None) -> None:
        """Copy lane ``src``'s whole KV cache into lane ``dst`` (prefix
        caching on the CONTIGUOUS layout: a new request sharing a prompt
        prefix with tokens already resident in ``src`` skips prefilling
        that prefix — the scheduler tracks which tokens each lane's cache
        holds and calls this before prefilling only the tail). No
        reference analogue: its lanes share one cache (defect (c)), so
        prefix reuse is impossible there.

        ``prefix_len`` (when the caller knows it) lets a zero-length
        share short-circuit like ``src == dst`` does: both used to
        rebuild the whole cache pytree for a copy that moves nothing.
        Paged engines refuse outright — sharing there is a refcount bump
        on the SAME physical pages (``paged_admit``), and a whole-lane
        HBM copy is exactly the cost the paged layout exists to avoid."""
        if self.kvpool is not None:
            raise RuntimeError(
                "copy_lane is the contiguous layout's primitive; a paged "
                "engine shares prefix pages by refcount via paged_admit"
            )
        if src == dst or prefix_len == 0:
            return  # nothing would move: skip the whole-cache rebuild
        self.cache = self._copy_lane_fn(
            self.cache, jnp.int32(src), jnp.int32(dst)
        )

    # -- paged KV pool (runtime/kvpool.py): the host/device seam ------------

    def _paged_table_row(self, blocks) -> np.ndarray:
        """A lane's page-table row (the pool's shared encoding recipe,
        ``kvpool.table_row``) as the int32 device-leaf dtype."""
        # dlint: ok[host-sync] host int list -> numpy row; no device value involved
        return np.asarray(self.kvpool.table_row(list(blocks)), np.int32)

    def _replace_leaf(self, host_array, sharding):
        """THE sanctioned device-leaf constructor — the ``engine.py``
        aval-stability rule promoted from a comment into code (PR 11's
        review found the failure by hand; dlint's ``jit-stability``
        check now whitelists exactly this function). Every device-pytree
        leaf rebuilt between dispatches (the page-table row, the grammar
        slab tables) MUST come through here:

        - off-mesh (``sharding is None``): a plain ``jnp.asarray`` of
          the host mirror — same shape/dtype, so the leaf's aval is
          unchanged by construction;
        - on a mesh: ``make_array_from_callback`` with the NamedSharding
          captured at init, built from each process's (identical) host
          mirror — the ONLY form that both preserves the compiled
          programs' input aval (a bare ``jnp.asarray`` would drop the
          sharding and force a recompile per replacement on a
          single-host mesh) and works on multi-process pods where the
          mesh is not fully addressable."""
        if sharding is None:
            return jnp.asarray(host_array)
        return jax.make_array_from_callback(
            host_array.shape, sharding, lambda idx: host_array[idx]
        )

    def _table_leaf(self):
        """The host table mirror as the cache pytree's table leaf, via
        the sanctioned sharding-preserving constructor."""
        return self._replace_leaf(self._host_tables, self._table_sharding)

    def apply_paged_admit(self, lane: int, row, copies) -> None:
        """Device half of a paged admission (or release): apply the COW
        page ``copies`` then ship lane ``lane``'s new table ``row`` — both
        thread the donated cache pytree, so they are ordered BEFORE any
        later-dispatched tail prefill/decode by construction. Split from
        ``paged_admit`` so pod workers can replay it from OP_KV_TABLE
        packets while the pool bookkeeping stays root-only."""
        for src, dst in copies:
            self.cache = self._copy_page_fn(
                self.cache, jnp.int32(src), jnp.int32(dst)
            )
        self._host_tables[lane] = row
        # a table update between dispatches is just a new pytree leaf
        # (host->device, a few KB of int32 — never a device sync)
        self.cache = self.cache._replace(table=self._table_leaf())

    def paged_admit(self, lane: int, tokens, reserve_tokens: int,
                    min_share_tokens: int = 1) -> int:
        """Reserve lane ``lane``'s pages for a request (prompt ``tokens``,
        whole potential range ``reserve_tokens``) and apply the device
        half. Returns ``start`` — prompt tokens already resident via the
        prefix tree (refcount bumps on SHARED pages, zero HBM copies,
        plus at most one single-page COW at the divergent block); the
        caller prefills only ``tokens[start:]``. Raises
        :class:`~.kvpool.PoolExhausted` when the pool cannot serve the
        reservation even after evicting parked sessions.

        Tiered residency ordering: (1) the pool admission may evict
        parked pages and stage them for swap-out; (2) those stage
        entries DRAIN (device gather -> host tier) before anything
        writes — the gather dispatches first, so it reads pre-eviction
        bytes even when an evicted page was immediately re-popped as
        this admission's fresh page; (3) host-tier hits scatter back in
        (``swapins``); (4) COW copies + the table row apply. All four
        thread the donated cache pytree, so the tail prefill can never
        observe a half-applied admission."""
        start, blocks, copies, swapins = self.kvpool.admit(
            lane, tokens, reserve_tokens, min_share_tokens
        )
        self.drain_kv_swapouts()
        if swapins:
            self.swap_in_pages([p for p, _ in swapins],
                               [b for _, b in swapins])
        self.apply_paged_admit(lane, self._paged_table_row(blocks), copies)
        return start

    def paged_commit(self, lane: int, tokens) -> None:
        """Register lane ``lane``'s committed history into the prefix tree
        (host bookkeeping only — the KV bytes are already on device)."""
        self.kvpool.commit(lane, tokens)

    def paged_finish(self, lane: int, park: bool = True) -> None:
        """Release lane ``lane``'s pages at request end. ``park=True``
        keeps its tree-registered blocks resident (refcounted, LRU-
        bounded) so follow-ups and same-prompt admissions share copy-free;
        ``park=False`` frees everything (failure path). The lane's table
        row resets to all-unmapped — skipped entirely when the lane never
        mapped anything (the exhaustion-shed reject path), so overload
        rejects stay host-only cheap. Parking may overflow the LRU bound
        and stage swap-outs — drained here, before the unmap's table
        write could be followed by page-reusing dispatches."""
        held = self.kvpool.finish(lane, park=park)
        self.drain_kv_swapouts()
        if held:
            self.apply_paged_admit(lane, self._paged_table_row([]), [])

    def paged_unmap_all(self) -> None:
        """Device half of the paged reset: every lane's table row goes
        all-unmapped. Split from :meth:`paged_reset` so pod workers can
        replay it from an OP_KV_TABLE reset packet (lane == -1) while the
        pool bookkeeping stays root-only."""
        self._host_tables[:] = self.kvpool.table_row([])
        self.cache = self.cache._replace(table=self._table_leaf())

    def paged_reset(self) -> None:
        """Containment: after an engine-scoped failure the device pool
        contents are not trusted — drop every mapping, parked session and
        tree node, and unmap every lane's table row."""
        self.kvpool.reset()
        self.paged_unmap_all()

    def pool_stats(self) -> dict:
        """Page-pool pressure snapshot for /stats (bridged to /metrics);
        ``{}`` on contiguous engines. Merges the engine's swap-traffic
        counters next to the pool's host-tier gauges so the whole tier
        story reads off one surface."""
        if self.kvpool is None:
            return {}
        out = self.kvpool.stats()
        out.update({
            "swap_ins": self.swap_ins,
            "swap_outs": self.swap_outs,
            "swap_in_bytes": self.swap_in_bytes,
            "swap_out_bytes": self.swap_out_bytes,
            "swap_in_ms": round(self.swap_in_ms, 3),
        })
        return out

    def _page_leaf_geometry(self) -> tuple[tuple, "np.dtype"]:
        """One page's K (or V) leaf shape/dtype: ``[L, page_size,
        n_kv_heads, head_size]`` sliced out of the pool axis."""
        k = self.cache.k
        return (k.shape[0],) + tuple(k.shape[2:]), np.dtype(k.dtype)

    def export_kv_page(self, page: int) -> bytes:
        """Serialize physical page ``page``'s K/V bytes (K then V, raw
        row-major) for cross-replica transfer (disagg/kvtransfer.py).
        A host sync by design — the disagg hand-off IS a host round
        trip, and it only runs on committed (immutable) pages, so the
        bytes are stable while the source lane keeps decoding."""
        if self.kvpool is None:
            raise RuntimeError("export_kv_page needs a paged engine")
        # dlint: ok[host-sync] sanctioned disagg export choke point: one committed page's K/V leaves the device here
        k = np.asarray(self.cache.k[:, page])
        # dlint: ok[host-sync] second half of the same sanctioned page export
        v = np.asarray(self.cache.v[:, page])
        return k.tobytes() + v.tobytes()

    def import_kv_page(self, page: int, payload: bytes) -> None:
        """Write a transferred page's K/V bytes into physical page
        ``page`` (the inverse of :meth:`export_kv_page`), through the
        warmed single-page write program — the donated cache pytree
        orders it before any later-dispatched prefill/decode, exactly
        like a COW copy. Raises ``ValueError`` on a size mismatch
        (geometry-skewed peer) before touching the device."""
        if self.kvpool is None:
            raise RuntimeError("import_kv_page needs a paged engine")
        shape, dtype = self._page_leaf_geometry()
        half = int(np.prod(shape)) * dtype.itemsize
        if len(payload) != 2 * half:
            raise ValueError(
                f"kv page payload is {len(payload)} bytes, expected "
                f"{2 * half} for page geometry {tuple(shape)} {dtype}"
            )
        k_page = np.frombuffer(payload[:half], dtype=dtype).reshape(shape)
        v_page = np.frombuffer(payload[half:], dtype=dtype).reshape(shape)
        self.cache = self._write_page_fn(
            self.cache, jnp.int32(page), k_page, v_page
        )

    # -- tiered KV residency: the device halves of the host swap tier -------

    def swap_out_pages(self, pages) -> list:
        """Batched device->host read of physical pages' K/V bytes for the
        swap tier — ``export_kv_page``'s encoding (K then V, raw
        row-major per page) at ``_SWAP_BATCH`` pages per dispatch, so
        swapping a whole evicted chain costs ceil(n/_SWAP_BATCH) device
        programs instead of n. A host sync by design, like the disagg
        export: the pages just LEFT the pool (or are committed and
        immutable), so the bytes are stable."""
        if self.kvpool is None:
            raise RuntimeError("swap_out_pages needs a paged engine")
        out: list = []
        for off in range(0, len(pages), _SWAP_BATCH):
            chunk = [int(p) for p in pages[off: off + _SWAP_BATCH]]  # dlint: ok[host-sync] page ids are host ints from the pool, never device values
            n = len(chunk)
            # dlint: ok[host-sync] host int list -> fixed-shape index operand; no device value involved
            idx = np.asarray(
                (chunk + [chunk[0]] * _SWAP_BATCH)[:_SWAP_BATCH], np.int32
            )
            k_g, v_g = self._gather_pages_fn(self.cache, idx)
            # dlint: ok[host-sync] sanctioned swap-out choke point: evicted committed pages' K/V leave the device here
            k_h = np.asarray(k_g)
            # dlint: ok[host-sync] second half of the same sanctioned swap-out gather
            v_h = np.asarray(v_g)
            for i in range(n):
                out.append(k_h[:, i].tobytes() + v_h[:, i].tobytes())
        return out

    def swap_in_pages(self, pages, payloads) -> None:
        """Batched host->device write reactivating swapped pages (the
        inverse of :meth:`swap_out_pages`): every payload is
        size-validated against the page-leaf geometry BEFORE anything
        dispatches (a geometry-skewed payload must not half-apply), then
        the chunked scatter threads the donated cache pytree — ordered
        before any later-dispatched tail prefill, exactly like a COW
        copy. Raises ``ValueError`` on a size or count mismatch."""
        if self.kvpool is None:
            raise RuntimeError("swap_in_pages needs a paged engine")
        if len(pages) != len(payloads):
            raise ValueError(
                f"swap_in_pages: {len(pages)} pages vs "
                f"{len(payloads)} payloads"
            )
        if not pages:
            return
        shape, dtype = self._page_leaf_geometry()
        half = int(np.prod(shape)) * dtype.itemsize
        for i, payload in enumerate(payloads):
            if len(payload) != 2 * half:
                raise ValueError(
                    f"swap_in_pages: payload {i} is {len(payload)} bytes, "
                    f"expected {2 * half} for page geometry "
                    f"{tuple(shape)} {dtype}"
                )
        t0 = time.perf_counter()
        for off in range(0, len(pages), _SWAP_BATCH):
            chunk_p = [int(p) for p in pages[off: off + _SWAP_BATCH]]  # dlint: ok[host-sync] page ids are host ints from the pool, never device values
            chunk_b = list(payloads[off: off + _SWAP_BATCH])
            while len(chunk_p) < _SWAP_BATCH:  # idempotent duplicate pad
                chunk_p.append(chunk_p[0])
                chunk_b.append(chunk_b[0])
            idx = np.asarray(chunk_p, np.int32)  # dlint: ok[host-sync] host int list -> index operand; no device value involved
            k_stack = np.stack(
                [np.frombuffer(b[:half], dtype=dtype).reshape(shape)
                 for b in chunk_b], axis=1,
            )
            v_stack = np.stack(
                [np.frombuffer(b[half:], dtype=dtype).reshape(shape)
                 for b in chunk_b], axis=1,
            )
            self.cache = self._scatter_pages_fn(
                self.cache, idx, k_stack, v_stack
            )
        self.swap_ins += len(pages)
        self.swap_in_bytes += sum(len(b) for b in payloads)
        self.swap_in_ms += (time.perf_counter() - t0) * 1000.0

    def drain_kv_swapouts(self) -> int:
        """Move the pool's staged swap-outs into the host tier: take the
        pending ``(node_key, block, page)`` triples, read the pages in
        batched device gathers, and store each payload under its chain
        key. Runs inside every paged mutation point (admit/finish/
        swap_out_parked) BEFORE any device write that could reuse the
        freed pages. Best-effort cache with strict accounting: a failed
        device read discards the batch (the tier just misses — the
        sessions rebuild from the journal as before) and re-raises for
        engine-scoped containment; an over-budget ``put`` simply drops.
        Returns how many pages the tier actually stored."""
        if self.kvpool is None:
            return 0
        tier = self.kvpool.host_tier
        if not tier.enabled:
            return 0
        pending = self.kvpool.take_pending_swapouts()
        if not pending:
            return 0
        try:
            payloads = self.swap_out_pages([p for _, _, p in pending])
        except BaseException:
            for node_key, _blk, _page in pending:
                tier.discard(node_key)
            raise
        stored = 0
        for (node_key, blk, _page), payload in zip(pending, payloads):
            if tier.put(node_key, blk, payload):
                stored += 1
        self.swap_outs += len(pending)
        self.swap_out_bytes += sum(len(b) for b in payloads)
        return stored

    def swap_out_parked(self) -> int:
        """Evict every parked session straight into the host tier (the
        bench/test lever for the middle residency tier; pressure
        eviction takes the same path organically). Returns how many
        sessions were evicted."""
        if self.kvpool is None:
            return 0
        n = self.kvpool.swap_out_parked()
        self.drain_kv_swapouts()
        return n

    def reset_swap_stats(self) -> None:
        """Zero the swap-traffic counters (warmup drops its own warm
        dispatch from them, like reset_worker_stats for pod counters —
        a METHOD so pod proxies reach the owning engine's attributes)."""
        self.swap_ins = 0
        self.swap_outs = 0
        self.swap_in_bytes = 0
        self.swap_out_bytes = 0
        self.swap_in_ms = 0.0

    def reset_lane(self, lane: int) -> None:
        """Nothing to clear on device: a fresh request's prefill rewrites the
        lane's cache from position 0, and reads are masked to s <= pos."""


def warmup_engine(
    engine, spec: bool = True, multi_step: int = 0, pipeline: bool = True
) -> None:
    """Compile every serving program up front (each prefill bucket, decode
    with AND without the logits output, the speculative verify step, every
    multi-step horizon bucket the scheduler can pick, the pipelined step,
    the fused prefill+decode step per bucket, and — paged engines — the
    single-page COW copy) so the first real request doesn't pay XLA
    compiles mid-service — the analogue of the reference finishing its
    executor build before accepting connections (src/app.cpp:233-312).

    Deliberately a FREE function driving the PUBLIC engine API: on a
    multi-host pod root the proxy's decode/prefill_chunk broadcast control
    packets so workers replay the same compiles; an InferenceEngine method
    reached through the proxy's __getattr__ would bypass the broadcast and
    deadlock the mesh. The junk KV writes land in uncommitted slots
    (admission rewrites from position 0) and the stats counters are
    restored afterwards."""
    n = engine.n_lanes
    z = np.zeros(n, np.int32)
    # resolve + pin the dequant selection BEFORE anything compiles: the
    # mode is a static argname of the Q40 matmul jit, so under
    # DLLAMA_DEQUANT=auto the per-site table answers are baked into the
    # programs warmed below, and a post-warmup table change would retrace
    # every family mid-serving — freeze_for_serving makes that a loud
    # error instead (ops/dequant_select.py)
    from ..ops.dequant_select import dequant_stats, freeze_for_serving

    freeze_for_serving()
    # warmup's own compiles are the sanctioned ones: pause the recompile
    # witness for the duration (tests warm several engines per process —
    # one engine's warmup must not fire another's armed witness); arming
    # for THIS engine happens at the end, once every program is compiled
    with jitcheck.warming(), engine.stats.preserved():
        for bucket in engine.prefill_buckets:
            engine.prefill_chunk(0, [0] * bucket, 0)
        engine.decode(z, z)
        # the serving loop's common step materializes no logits — a
        # distinct program that would otherwise compile mid-request
        engine.decode(z, z, want_logits=False)
        if spec and getattr(engine, "supports_speculative", False):
            engine.decode_spec(
                z, np.zeros((n, engine.SPEC_DRAFT), np.int32), z, z
            )
        if multi_step > 1 and getattr(engine, "supports_multi_step", False):
            from .spec import pow2_floor

            # the WHOLE horizon set the scheduler dispatches: every
            # power-of-two bucket down to 2 (batch endgames shrink the
            # horizon, and a lazily compiled h charges first-request
            # latency mid-service)
            h = pow2_floor(multi_step)
            while h > 1:
                engine.decode_multi(z, z, h=h)
                h //= 2
        if (
            pipeline
            and getattr(engine, "supports_pipelined", False)
            and getattr(engine, "pipeline_depth", 0) > 1
        ):
            # each pipelined family is warmed TWICE: the reseed form
            # (host-array feed/positions) and the CHAINED form
            # (positions -1 = read the device carry). On a mesh these
            # are DIFFERENT compiled programs — the chained dispatch's
            # feed/carry operands arrive with the replicated
            # NamedSharding the previous step produced, not host
            # arrays — so warming only the reseed left the first live
            # chained step of every pod serving loop paying an XLA
            # compile mid-service (found by the DLLAMA_JITCHECK witness
            # on the virtual pod; single-chip engines hit one program
            # for both forms). The ring is depth >= 2 here, so the
            # chained dispatch fits before the flush.
            neg = np.full(n, -1, np.int32)
            engine.decode_pipelined(z, tokens=z)
            engine.decode_pipelined(neg)
            engine.pipeline_flush()
            spec_pl = bool(
                spec and getattr(engine, "supports_spec_pipelined", False)
            )
            if spec_pl:
                # the in-chain spec verify step: the first draft hit in a
                # live chain must not eat an XLA compile — reseed AND
                # chained forms, like the plain pipelined step
                k1 = engine.SPEC_DRAFT + 1
                engine.decode_spec_pipelined(
                    z, np.zeros((n, k1), np.int32), z, tokens=z
                )
                engine.decode_spec_pipelined(
                    neg, np.zeros((n, k1), np.int32), z
                )
                engine.pipeline_flush()
            if getattr(engine, "supports_fused_prefill", False):
                # the fused prefill+decode family compiles per bucket —
                # without this, the FIRST admission into a live chain
                # pays a fresh XLA compile exactly when lanes are hot.
                # Admissions ride the LIVE chain by design, so the
                # chained form is the one serving actually dispatches —
                # warm it behind each bucket's reseed form.
                park = np.full(n, engine.config.seq_len, np.int32)
                for bucket in engine.prefill_buckets:
                    engine.decode_prefill_fused(
                        park, p_lane=0, chunk=[0] * bucket, tokens=z,
                    )
                    engine.decode_prefill_fused(
                        neg, p_lane=0, chunk=[0] * bucket,
                    )
                    engine.pipeline_flush()
                    if spec_pl:
                        # admitting chunk + spec verify sharing a dispatch
                        # compiles per bucket too — both forms again
                        engine.decode_spec_prefill_fused(
                            park, np.zeros((n, k1), np.int32), z,
                            p_lane=0, chunk=[0] * bucket, tokens=z,
                        )
                        engine.decode_spec_prefill_fused(
                            neg, np.zeros((n, k1), np.int32), z,
                            p_lane=0, chunk=[0] * bucket,
                        )
                        engine.pipeline_flush()
        pool = getattr(engine, "kvpool", None)
        apply_paged = getattr(engine, "apply_paged_admit", None)
        if pool is not None and apply_paged is not None:
            # the single-page COW program: the first divergent-block
            # admission must not eat an XLA compile mid-service. Page 0
            # onto itself copies zeros over zeros through the real
            # program, and the all-sentinel row leaves lane 0's table in
            # its initial unmapped state (pod roots broadcast via the
            # RootControlEngine override so workers compile too)
            apply_paged(
                0,
                np.full(pool.blocks_per_lane, pool.n_pages, np.int32),
                [(0, 0)],
            )
            exp = getattr(engine, "export_kv_page", None)
            imp = getattr(engine, "import_kv_page", None)
            if callable(exp) and callable(imp):
                # the disagg page-write program: the first adopted page
                # must not eat an XLA compile mid-service. Page 0's own
                # zeros ride back over themselves through the real
                # program (pod roots broadcast via the RootControlEngine
                # override so workers compile too).
                imp(0, exp(0))
            swap_out = getattr(engine, "swap_out_pages", None)
            swap_in = getattr(engine, "swap_in_pages", None)
            if callable(swap_out) and callable(swap_in):
                # the batched swap gather/scatter programs (host tier):
                # the first pressure eviction / host-tier reactivation
                # must not eat an XLA compile mid-service. Page 0's own
                # zeros ride out and back through the real programs —
                # batch padding makes this the same compiled shape as
                # any real batch (pod roots broadcast the swap-in via
                # the RootControlEngine override so workers compile too).
                swap_in([0], swap_out([0]))
                reset_swap = getattr(engine, "reset_swap_stats", None)
                if callable(reset_swap):
                    reset_swap()
        if pool is None and n > 1:
            # the contiguous prefix-reuse primitive (found by dlint's
            # warmup-coverage at adoption): the first shared-prefix
            # admission used to pay the whole-lane-copy compile
            # mid-serving. Traced src/dst scalars: ONE program for any
            # pair; lane 1's junk is rewritten by its next admission.
            engine.copy_lane(0, 1)
        # the host-exact escape hatch's standalone sampler (same
        # adoption finding): one [vocab] program, pennies to warm
        engine.sample_token(
            np.zeros(engine.config.vocab_size, np.float32),
            0.7, 0.9, 1, 0,
        )
    # pod roots: drop the replayed warmup traffic from worker counters too
    reset_workers = getattr(engine, "reset_worker_stats", None)
    if reset_workers is not None:
        reset_workers()
    # one structured line deployments verify engine config from logs alone
    # (telemetry/logs.py; the scheduler-side twin is scheduler_start)
    mesh = getattr(engine, "mesh", None)
    # mesh engines: AOT-compile the decode step NOW (outside preserved(), so
    # the sync_bytes_per_decode estimate survives into serving) — the first
    # pod dispatch must not pay the compile, and /stats should report the
    # per-step collective payload from the start
    if mesh is not None:
        coll = getattr(engine, "collective_stats", None)
        if callable(coll):
            with jitcheck.warming():
                try:
                    coll()
                except Exception:  # the probe is evidence, never a startup blocker
                    pass
    # from here on a new XLA backend compile is a broken invariant: every
    # one bumps stats.jit_compiles_after_warmup (surfaced on /stats,
    # bridged to /metrics, banked by the bench phases), and under
    # DLLAMA_JITCHECK=1 raises RecompileAfterWarmup at the guilty
    # dispatch — the runtime twin of the warmup-coverage/jit-stability
    # static checks (analysis/jitcheck.py, docs/LINT.md)
    jitcheck.arm(engine.stats)
    pipelined = bool(
        pipeline
        and getattr(engine, "supports_pipelined", False)
        and getattr(engine, "pipeline_depth", 0) > 1
    )
    from ..ops.ring_collective import ring_sync_enabled

    log_event(
        "warmup_engine",
        n_lanes=n,
        buckets_warmed=list(engine.prefill_buckets),
        mesh_shape=dict(mesh.shape) if mesh is not None else None,
        ring_sync=bool(mesh is not None and ring_sync_enabled()),
        pipeline_depth=getattr(engine, "pipeline_depth", 0),
        pipelined=pipelined,
        # fused admissions need the live pipeline (and were only warmed
        # under it) — same gate the scheduler's _fused_ok applies, so
        # this line and scheduler_start cannot contradict each other
        fused_prefill=bool(
            pipelined and getattr(engine, "supports_fused_prefill", False)
        ),
        multi_step=multi_step,
        speculative=bool(
            spec and getattr(engine, "supports_speculative", False)
        ),
        # drafts verified INSIDE the pipelined chain (zero-flush serving)
        spec_pipelined=bool(
            pipelined
            and spec
            and getattr(engine, "supports_spec_pipelined", False)
        ),
        # the recompile witness is armed (counting) from here on; strict
        # means DLLAMA_JITCHECK=1 will raise on any post-warmup compile
        jitcheck_strict=jitcheck.enabled(),
        seq_len=engine.config.seq_len,
        # the dequant path every warmed program baked in: the configured
        # knob plus (under auto) the per-site table resolutions recorded
        # while the families above traced
        **dequant_stats(),
    )
