#!/usr/bin/env python
"""Convert an original Meta Llama-3 `tokenizer.model` (tiktoken base64 ranks)
to the `.t` format.

Usage: python convert-tokenizer-llama3.py <tokenizerModelPath> [name]

Reimplementation of the reference (converter/convert-tokenizer-llama3.py):
256 reserved special tokens appended after the base vocab, llama3 chat
template embedded, <|begin_of_text|> as bos, <|eot_id|>/<|end_of_text|> as eos.
"""

from __future__ import annotations

import base64
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_llama_multiusers_tpu.formats.tokenizer_file import TokenizerData, write_tokenizer_file

LLAMA3_CHAT_TEMPLATE = (
    "{% set loop_messages = messages %}{% for message in loop_messages %}"
    "{% set content = '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n\n'"
    "+ message['content'] | trim + '<|eot_id|>' %}"
    "{% if loop.index0 == 0 %}{% set content = bos_token + content %}{% endif %}"
    "{{ content }}{% endfor %}"
    "{% if add_generation_prompt %}{{ '<|start_header_id|>assistant<|end_header_id|>\n\n' }}{% endif %}"
)

SPECIAL_TOKENS = [
    "<|begin_of_text|>",
    "<|end_of_text|>",
    "<|reserved_special_token_0|>",
    "<|reserved_special_token_1|>",
    "<|finetune_right_pad_id|>",
    "<|step_id|>",
    "<|start_header_id|>",
    "<|end_header_id|>",
    "<|eom_id|>",
    "<|eot_id|>",
    "<|python_tag|>",
] + [f"<|reserved_special_token_{i}|>" for i in range(2, 247)]


def convert(model_path: str, out_path: str) -> None:
    vocab: list[bytes] = []
    scores: list[float] = []
    with open(model_path, "rb") as f:
        for rank, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            token_b64, _rank = line.split()
            vocab.append(base64.b64decode(token_b64))
            # descending scores preserve tiktoken merge priority under the
            # runtime's best-score merge loop
            scores.append(float(-rank))
    n_base = len(vocab)
    bos_id = n_base
    eos_ids = []
    for i, name in enumerate(SPECIAL_TOKENS):
        vocab.append(name.encode("utf-8"))
        scores.append(0.0)
        if name in ("<|end_of_text|>", "<|eot_id|>"):
            eos_ids.append(n_base + i)

    data = TokenizerData(
        vocab=vocab,
        scores=scores,
        bos_id=bos_id,
        eos_token_ids=eos_ids,
        chat_template=LLAMA3_CHAT_TEMPLATE,
    )
    with open(out_path, "wb") as f:
        write_tokenizer_file(f, data)
    print(f"✅ {out_path}: vocab {len(vocab)}, bos {bos_id}, eos {eos_ids}")


def main() -> None:
    if len(sys.argv) < 2:
        print("Usage: python convert-tokenizer-llama3.py <tokenizerModelPath> [name]")
        raise SystemExit(1)
    name = sys.argv[2] if len(sys.argv) > 2 else "llama3"
    convert(sys.argv[1], f"dllama_tokenizer_{name}.t")


if __name__ == "__main__":
    main()
