"""MoE (mixture-of-experts) model family + expert parallelism.

The reference carries n_experts/n_active_experts in its header and its
converter emits expert tensors, but the runtime only executes dense Llama
(src/llm.hpp:16-17, src/llm.cpp:21-24) — and the converter drops the router
tensor entirely, so no reference MoE file was ever runnable. This framework
implements the capability (Mixtral semantics): .m format carries a
block_moe_gate router per layer, the forward routes top-k with softmax over
selected logits, and experts shard over the ep mesh axis.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
from distributed_llama_multiusers_tpu.formats.synthetic import (
    tiny_header,
    write_synthetic_model,
)
from distributed_llama_multiusers_tpu.models import (
    init_kv_cache,
    llama_forward,
    llama_forward_train,
    params_from_random,
)
from distributed_llama_multiusers_tpu.models.config import LlamaConfig
from distributed_llama_multiusers_tpu.models.loader import (
    load_params_from_m,
    load_params_from_m_quantized,
    quantize_params,
)
from distributed_llama_multiusers_tpu.models.oracle import OracleLlama, oracle_weights_from_m
from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh, validate_mesh_for_config
from distributed_llama_multiusers_tpu.parallel.sharding import shard_params
from distributed_llama_multiusers_tpu.quants.packed import PackedQ40


@pytest.fixture(scope="module")
def moe_model(tmp_path_factory):
    d = tmp_path_factory.mktemp("moe")
    header = tiny_header(n_experts=4, n_active_experts=2)
    path = str(d / "moe.m")
    write_synthetic_model(path, header, seed=3)
    return path, header


def test_moe_header_roundtrip(moe_model):
    path, header = moe_model
    h = load_model_header(path)
    assert h.n_experts == 4 and h.n_active_experts == 2
    assert h.file_size == header.file_size or h.file_size > 0


def test_moe_forward_matches_oracle(moe_model):
    """Greedy decode parity: XLA MoE forward vs the numpy oracle."""
    path, header = moe_model
    h = load_model_header(path)
    config, params = load_params_from_m(path, h, dtype=jnp.float32)
    assert params.layers.w1.shape == (2, 4, 64, 128)
    assert params.layers.moe_gate.shape == (2, 64, 4)

    oracle = OracleLlama(config, oracle_weights_from_m(path, h), emulate_q80=False)
    prompt = [5, 9, 21]
    want = oracle.generate_greedy(prompt, n_steps=8)

    cache = init_kv_cache(config, 1)
    pos = 0
    logits = None
    for tok in prompt:
        logits, cache = llama_forward(
            config, params,
            jnp.asarray([[tok]], jnp.int32), jnp.asarray([[pos]], jnp.int32), cache,
        )
        pos += 1
    got = []
    cur = int(jnp.argmax(logits[0, 0]))
    for _ in range(8):
        got.append(cur)
        logits, cache = llama_forward(
            config, params,
            jnp.asarray([[cur]], jnp.int32), jnp.asarray([[pos]], jnp.int32), cache,
        )
        pos += 1
        cur = int(jnp.argmax(logits[0, 0]))
    assert got == want, (got, want)


def test_moe_quantized_load_matches_dense_load(moe_model):
    """PackedQ40 expert stacks (per-expert dequant loop) == dense-dequant load."""
    path, _ = moe_model
    h = load_model_header(path)
    config, dense_params = load_params_from_m(path, h, dtype=jnp.float32)
    _, qparams = load_params_from_m_quantized(path, h, dtype=jnp.float32)
    assert isinstance(qparams.layers.w1, PackedQ40)
    assert qparams.layers.w1.packed.shape == (2, 4, 32, 128)

    tokens = jnp.asarray([[7, 3]], jnp.int32)
    positions = jnp.asarray([[0, 1]], jnp.int32)
    ref, _ = llama_forward(config, dense_params, tokens, positions, init_kv_cache(config, 1))
    got, _ = llama_forward(config, qparams, tokens, positions, init_kv_cache(config, 1))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_moe_ep_sharded_forward_parity(moe_model):
    """Experts sharded over ep (+tp/sp): logits identical to single-device."""
    path, _ = moe_model
    h = load_model_header(path)
    config, params = load_params_from_m(path, h, dtype=jnp.float32)
    plan = MeshPlan(dp=1, tp=2, sp=2, ep=2)
    validate_mesh_for_config(config, plan)
    mesh = make_mesh(plan)

    tokens = jnp.asarray([[5, 9, 21, 3]], jnp.int32)
    positions = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    ref, _ = llama_forward(config, params, tokens, positions, init_kv_cache(config, 1))
    sp_params = shard_params(params, mesh)
    assert sp_params.layers.w1.sharding.spec == jax.sharding.PartitionSpec("pp", "ep", None, "tp")
    got, _ = llama_forward(config, sp_params, tokens, positions, init_kv_cache(config, 1))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4, rtol=2e-4)


def test_moe_train_forward_and_grad():
    """Training twin: MoE forward differentiates (router included) on an
    ep+sp mesh — the dryrun_multichip path."""
    config = LlamaConfig(
        dim=64, hidden_dim=128, n_layers=2, n_heads=8, n_kv_heads=4,
        vocab_size=96, seq_len=32, n_experts=4, n_active_experts=2,
    )
    mesh = make_mesh(MeshPlan(dp=1, tp=2, sp=2, ep=2))
    params = shard_params(params_from_random(config, seed=2, dtype=jnp.float32), mesh)
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, 96, (2, 16)), jnp.int32)

    def loss(p):
        logits = llama_forward_train(config, p, tokens, mesh=mesh)
        return jnp.mean(jax.nn.logsumexp(logits, axis=-1))

    val, grads = jax.jit(jax.value_and_grad(loss))(params)
    assert np.isfinite(float(val))
    gnorm = float(jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32)**2) for g in jax.tree.leaves(grads))))
    assert np.isfinite(gnorm) and gnorm > 0


def test_moe_random_quantize_roundtrip():
    """params_from_random + quantize_params handle the expert axis."""
    config = LlamaConfig(
        dim=64, hidden_dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
        vocab_size=96, seq_len=16, n_experts=2, n_active_experts=1,
    )
    params = params_from_random(config, seed=1, dtype=jnp.float32, to_device=False)
    q = quantize_params(params, to_device=False)
    assert q.layers.w1.packed.shape == (2, 2, 32, 128)
    tokens = jnp.asarray([[1, 2, 3]], jnp.int32)
    positions = jnp.asarray([[0, 1, 2]], jnp.int32)
    ref, _ = llama_forward(config, jax.tree.map(jnp.asarray, params), tokens, positions, init_kv_cache(config, 1))
    got, _ = llama_forward(config, jax.tree.map(jnp.asarray, q), tokens, positions, init_kv_cache(config, 1))
    # Q40 noise only
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=0.5)


def test_moe_ep2_packed_stays_dequant_in_matmul(moe_model, monkeypatch):
    """ep>1 + PackedQ40 + live kernel must keep experts quantized in HBM
    (shard_map expert-parallel path) — never unpack_q40 to dense planes
    (round-3 Weak #4). Parity vs the dense-weight single-device forward."""
    import distributed_llama_multiusers_tpu.quants.packed as packed_mod
    from distributed_llama_multiusers_tpu.ops import linear

    path, _ = moe_model
    h = load_model_header(path)
    config, dense_params = load_params_from_m(path, h, dtype=jnp.float32)
    _, qparams = load_params_from_m_quantized(path, h, dtype=jnp.float32)
    mesh = make_mesh(MeshPlan(tp=2, ep=2))
    q_sh = shard_params(qparams, mesh)

    tokens = jnp.asarray([[5, 9, 21, 3]], jnp.int32)
    positions = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    ref, _ = llama_forward(config, dense_params, tokens, positions, init_kv_cache(config, 1))

    def boom(*a, **k):
        raise AssertionError("unpack_q40 called: expert weights dequantized to HBM on the ep path")

    monkeypatch.setattr(packed_mod, "unpack_q40", boom)
    linear.set_pallas_interpret(True)
    try:
        got, _ = llama_forward(
            config, q_sh, tokens, positions, init_kv_cache(config, 1), mesh=mesh
        )
    finally:
        linear.set_pallas_interpret(False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_moe_sparse_dispatch_rows_scale_with_k():
    """The sparse dispatch feeds the expert matmuls exactly B*T*k rows —
    per-token FFN work scales with k (n_active), not E (round-3 Weak #4):
    the jaxpr's three grouped matmuls (ragged_dot: gate/up/down) each take
    an lhs of N*k rows whatever E is."""
    from distributed_llama_multiusers_tpu.models.llama import LlamaLayerParams, _moe_ffn
    from distributed_llama_multiusers_tpu.ops.activations import silu

    E, d, h, N = 8, 64, 128, 256
    rng = np.random.default_rng(0)
    lp = LlamaLayerParams(
        wq=None, wk=None, wv=None, wo=None,
        w1=jnp.asarray(rng.standard_normal((E, d, h), dtype=np.float32)),
        w2=jnp.asarray(rng.standard_normal((E, h, d), dtype=np.float32)),
        w3=jnp.asarray(rng.standard_normal((E, d, h), dtype=np.float32)),
        rms_att=None, rms_ffn=None,
        moe_gate=jnp.asarray(rng.standard_normal((d, E), dtype=np.float32)),
    )
    y = jnp.asarray(rng.standard_normal((1, N, d), dtype=np.float32))

    def ragged_lhs_rows(k):
        jaxpr = jax.make_jaxpr(lambda y: _moe_ffn(y, y, lp, silu, k, lambda v: v))(y)
        rows = []

        def walk(jx):
            for eqn in jx.eqns:
                if eqn.primitive.name.startswith("ragged_dot"):
                    rows.append(eqn.invars[0].aval.shape[0])
                for v in eqn.params.values():
                    if hasattr(v, "jaxpr"):
                        walk(v.jaxpr)

        walk(jaxpr.jaxpr)
        return rows

    assert ragged_lhs_rows(1) == [N, N, N]
    assert ragged_lhs_rows(2) == [2 * N, 2 * N, 2 * N]


def test_moe_sparse_matches_dense_dispatch():
    """The grouped sparse dispatch is numerically the same mixture as the
    dense all-experts einsum (selection via zero routing weights)."""
    from distributed_llama_multiusers_tpu.models.llama import (
        LlamaLayerParams,
        _moe_ffn,
        _moe_router_weights,
    )
    from distributed_llama_multiusers_tpu.ops.activations import silu

    E, d, h, N, k = 4, 64, 128, 33, 2
    rng = np.random.default_rng(1)
    w1 = jnp.asarray(rng.standard_normal((E, d, h), dtype=np.float32) * 0.1)
    w2 = jnp.asarray(rng.standard_normal((E, h, d), dtype=np.float32) * 0.1)
    w3 = jnp.asarray(rng.standard_normal((E, d, h), dtype=np.float32) * 0.1)
    gate = jnp.asarray(rng.standard_normal((d, E), dtype=np.float32))
    lp = LlamaLayerParams(
        wq=None, wk=None, wv=None, wo=None, w1=w1, w2=w2, w3=w3,
        rms_att=None, rms_ffn=None, moe_gate=gate,
    )
    y = jnp.asarray(rng.standard_normal((2, N, d), dtype=np.float32))

    sparse = _moe_ffn(y, y, lp, silu, k, lambda v: v)

    rw = _moe_router_weights(y, gate, k)
    g = silu(jnp.einsum("btd,edh->bteh", y, w1))
    u = jnp.einsum("btd,edh->bteh", y, w3)
    dd = jnp.einsum("bteh,ehd->bted", g * u, w2)
    dense = jnp.einsum("bted,bte->btd", dd, rw)

    np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense), atol=1e-4, rtol=1e-4)


def test_moe_serving_with_speculation(moe_model, tmp_path):
    """MoE models through the full serving path: InferenceEngine + scheduler
    with speculation enabled (the verify step runs T=K+1 forwards through
    the sparse dispatch). The greedy stream must match the plain-decode
    stream — the speculative-verification identity must hold for MoE too."""
    from distributed_llama_multiusers_tpu.formats.synthetic import (
        write_synthetic_tokenizer,
    )
    from distributed_llama_multiusers_tpu.runtime import (
        ContinuousBatchingScheduler,
        InferenceEngine,
        Request,
    )
    from distributed_llama_multiusers_tpu.tokenizer import Tokenizer

    path, header = moe_model
    tok_path = str(tmp_path / "moe.t")
    write_synthetic_tokenizer(tok_path, vocab_size=header.vocab_size)
    tok = Tokenizer(tok_path)
    _, params = load_params_from_m(path, load_model_header(path), dtype=jnp.float32)
    config = LlamaConfig.from_header(load_model_header(path))

    def run(speculative):
        engine = InferenceEngine(config, params, n_lanes=2, prefill_buckets=(8,))
        sched = ContinuousBatchingScheduler(
            engine, tok, speculative=speculative
        )
        r = Request(prompt="ab ab ab ab ab", max_tokens=10, temperature=0.0)
        sched.start()
        try:
            sched.submit(r)
            r.future.result(timeout=300)
        finally:
            sched.stop()
        assert r.error is None, r.error
        return list(r.generated_tokens)

    assert run(True) == run(False)


def test_moe_packed_single_shard_prefill_flops_scale_with_k(monkeypatch):
    """Round-4 weak #3: single-shard PackedQ40 experts took a Python loop
    over all E experts (FLOPs ∝ E) for EVERY step shape. Now only
    decode-shaped steps (token count below MOE_PACKED_SPARSE_MIN_TOKENS,
    where the loop is bytes-optimal) keep the dequant-in-matmul loop;
    prefill/training-shaped steps dequantize each expert once and take the
    grouped ragged_dot dispatch — per-token expert compute ∝ k. Both paths
    must agree numerically."""
    from distributed_llama_multiusers_tpu.models import llama as llama_mod
    from distributed_llama_multiusers_tpu.ops import linear

    config = LlamaConfig(
        dim=64, hidden_dim=128, n_layers=1, n_heads=4, n_kv_heads=2,
        vocab_size=96, seq_len=64, n_experts=4, n_active_experts=2,
    )
    params = params_from_random(config, seed=2, dtype=jnp.float32, to_device=False)
    q = jax.tree.map(jnp.asarray, quantize_params(params, to_device=False))

    def ragged_dots(t_len, b=1):
        tokens = jnp.zeros((b, t_len), jnp.int32)
        positions = jnp.broadcast_to(
            jnp.arange(t_len, dtype=jnp.int32)[None, :], (b, t_len)
        )
        jaxpr = jax.make_jaxpr(
            lambda p, c: llama_forward(config, p, tokens, positions, c)
        )(q, init_kv_cache(config, b))
        hits = []

        def walk(jx):
            for eqn in jx.eqns:
                if eqn.primitive.name.startswith("ragged_dot"):
                    hits.append(eqn.invars[0].aval.shape[0])
                for v in eqn.params.values():
                    if hasattr(v, "jaxpr"):
                        walk(v.jaxpr)

        walk(jaxpr.jaxpr)
        return hits

    linear.set_pallas_interpret(True)
    try:
        t_big = llama_mod.MOE_PACKED_SPARSE_MIN_TOKENS
        assert ragged_dots(1) == []  # decode-shaped: per-expert loop
        # speculative verify (T=K=4) at 8 lanes is decode-shaped too: the
        # gate reads T, not B*T, so a full spec batch stays on the
        # bandwidth-bound packed loop (code-review finding, round 5)
        assert ragged_dots(4, b=8) == []
        # prefill-shaped: 3 grouped matmuls of T*k rows per layer
        assert ragged_dots(t_big) == [t_big * 2] * 3

        # numeric parity: grouped dispatch vs the per-expert loop on the
        # same packed weights and tokens
        tokens = jnp.asarray([[5, 9, 21, 3] * (t_big // 4)], jnp.int32)
        positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
        sparse, _ = llama_forward(
            config, q, tokens, positions, init_kv_cache(config, 1)
        )
        monkeypatch.setattr(
            llama_mod, "MOE_PACKED_SPARSE_MIN_TOKENS", 10**9
        )
        loop, _ = llama_forward(
            config, q, tokens, positions, init_kv_cache(config, 1)
        )
    finally:
        linear.set_pallas_interpret(False)
    np.testing.assert_allclose(
        np.asarray(sparse), np.asarray(loop), atol=1e-4, rtol=1e-4
    )
