"""Selection-table semantics for ``DLLAMA_DEQUANT=auto``
(ops/dequant_select): load validation fails loudly, most-specific-match
precedence, the decode/prefill boundary rides the blockdot cap, measured
winners round-trip through record_win, and the table freezes at warmup.

Pure-host module under test: these tests run without touching a device.
"""

from __future__ import annotations

import json

import pytest

from distributed_llama_multiusers_tpu.ops import dequant_select as ds
from distributed_llama_multiusers_tpu.ops import pallas_q40 as pq
from distributed_llama_multiusers_tpu.ops.pallas_q40 import (
    BLOCKDOT_MAX_M,
    DEQUANT_MODES,
    SELECTABLE_MODES,
)


@pytest.fixture(autouse=True)
def fresh_state():
    ds._reset_for_tests()
    yield
    ds._reset_for_tests()


def _write_table(tmp_path, rules, **top):
    p = tmp_path / "table.json"
    p.write_text(json.dumps({"version": 1, "rules": rules, **top}))
    return str(p)


# -- table load + validation --------------------------------------------------


def test_shipped_table_loads_and_covers_both_classes():
    t = ds.DequantTable()  # the checked-in ops/dequant_table.json
    assert t.resolve(4096, 14336, "decode") == "i8blockdot"
    assert t.resolve(4096, 14336, "prefill") == "bf16chain"
    assert t.provenance["rows"] >= 2
    assert t.provenance["version"] is not None


def test_unknown_mode_in_table_fails_loudly(tmp_path):
    path = _write_table(tmp_path, [
        {"d_in": "*", "d_out": "*", "m_class": "*", "mode": "turbo9"},
    ])
    with pytest.raises(ValueError, match="turbo9"):
        ds.DequantTable(path)


def test_unknown_m_class_in_table_fails_loudly(tmp_path):
    path = _write_table(tmp_path, [
        {"d_in": "*", "d_out": "*", "m_class": "midfill", "mode": "v4"},
    ])
    with pytest.raises(ValueError, match="m_class"):
        ds.DequantTable(path)


# -- resolution ---------------------------------------------------------------


def test_most_specific_rule_wins(tmp_path):
    path = _write_table(tmp_path, [
        {"d_in": "*", "d_out": "*", "m_class": "decode", "mode": "i8blockdot"},
        {"d_in": 512, "d_out": "*", "m_class": "decode", "mode": "blockdot"},
        {"d_in": 512, "d_out": 1024, "m_class": "decode", "mode": "u8chain"},
    ])
    t = ds.DequantTable(path)
    assert t.resolve(128, 256, "decode") == "i8blockdot"
    assert t.resolve(512, 256, "decode") == "blockdot"
    assert t.resolve(512, 1024, "decode") == "u8chain"


def test_no_matching_rule_falls_back(tmp_path):
    path = _write_table(tmp_path, [
        {"d_in": "*", "d_out": "*", "m_class": "decode", "mode": "i8blockdot"},
    ])
    t = ds.DequantTable(path)
    assert t.resolve(128, 256, "prefill") == ds.FALLBACK_MODE


def test_m_class_boundary_is_the_blockdot_cap():
    assert ds.m_class_of(1) == "decode"
    assert ds.m_class_of(BLOCKDOT_MAX_M) == "decode"
    assert ds.m_class_of(BLOCKDOT_MAX_M + 1) == "prefill"


def test_resolve_mode_records_sites(tmp_path, monkeypatch):
    path = _write_table(tmp_path, [
        {"d_in": "*", "d_out": "*", "m_class": "decode", "mode": "blockdot"},
    ])
    monkeypatch.setenv(ds._TABLE_ENV, path)
    assert ds.resolve_mode(512, 1024, 4) == "blockdot"
    assert ds.resolved_sites() == {"512x1024/decode": "blockdot"}


# -- record_win round-trip ----------------------------------------------------


def test_record_win_round_trip_and_upsert(tmp_path, monkeypatch):
    path = str(tmp_path / "fresh.json")
    monkeypatch.setenv(ds._TABLE_ENV, path)
    ds.record_win(512, 1024, "decode", "blockdot", source="unit")
    t = ds.reload_table()
    assert t.resolve(512, 1024, "decode") == "blockdot"
    rows = t.provenance["rows"]
    # same key upserts in place — no duplicate rows accumulate
    ds.record_win(512, 1024, "decode", "u8chain", source="unit2")
    t = ds.reload_table()
    assert t.resolve(512, 1024, "decode") == "u8chain"
    assert t.provenance["rows"] == rows
    with open(path) as f:
        data = json.load(f)
    assert data["rules"][0]["source"] == "unit2"
    assert data["updated"]


def test_record_win_validates_mode_and_class(tmp_path, monkeypatch):
    monkeypatch.setenv(ds._TABLE_ENV, str(tmp_path / "t.json"))
    with pytest.raises(ValueError, match="unknown dequant mode"):
        ds.record_win("*", "*", "decode", "turbo9", source="unit")
    with pytest.raises(ValueError, match="unknown m_class"):
        ds.record_win("*", "*", "midfill", "v4", source="unit")


# -- freeze semantics ---------------------------------------------------------


def test_freeze_blocks_reload_and_reports_provenance(tmp_path, monkeypatch):
    path = _write_table(tmp_path, [
        {"d_in": "*", "d_out": "*", "m_class": "*", "mode": "i8blockdot"},
    ])
    monkeypatch.setenv(ds._TABLE_ENV, path)
    pq.set_dequant_mode("auto")
    try:
        prov = ds.freeze_for_serving()
        assert prov is not None and prov["rows"] == 1
        with pytest.raises(RuntimeError, match="frozen"):
            ds.reload_table()
        # record_win still writes the FILE — the live resolution is pinned,
        # the next serving start picks the row up
        ds.record_win(64, 128, "decode", "v4", source="unit")
    finally:
        pq.set_dequant_mode(None)


def test_freeze_under_fixed_mode_skips_table_load(tmp_path, monkeypatch):
    # a fixed mode never consults the table: freeze must not even load it
    # (a corrupt table file cannot take down a non-auto serving start)
    bad = tmp_path / "corrupt.json"
    bad.write_text("{not json")
    monkeypatch.setenv(ds._TABLE_ENV, str(bad))
    pq.set_dequant_mode("i8blockdot")
    try:
        assert ds.freeze_for_serving() is None
    finally:
        pq.set_dequant_mode(None)


# -- stats + bench stamps -----------------------------------------------------


def test_dequant_stats_and_bench_stamp_keys(tmp_path, monkeypatch):
    path = _write_table(tmp_path, [
        {"d_in": "*", "d_out": "*", "m_class": "*", "mode": "bf16chain"},
    ], updated="2026-08-07")
    monkeypatch.setenv(ds._TABLE_ENV, path)
    pq.set_dequant_mode("auto")
    try:
        ds.resolve_mode(256, 512, 8)
        stats = ds.dequant_stats()
        assert stats["dequant_mode"] == "auto"
        assert stats["dequant_sites"] == {"256x512/decode": "bf16chain"}
        assert stats["dequant_table"]["rows"] == 1
        stamp = ds.bench_stamp("primary")
        assert stamp["primary_dequant_mode"] == "auto"
        assert stamp["primary_dequant_sites"] == stats["dequant_sites"]
        assert "1 rows" in stamp["primary_dequant_table"]
        assert "2026-08-07" in stamp["primary_dequant_table"]
    finally:
        pq.set_dequant_mode(None)


def test_bench_stamp_minimal_under_fixed_mode():
    stamp = ds.bench_stamp("serving")
    assert stamp["serving_dequant_mode"] == pq.DEQUANT_MODE
    assert "serving_dequant_sites" not in stamp
    assert "serving_dequant_table" not in stamp


# -- CLI pairing --------------------------------------------------------------


def test_args_dequant_choices_match_selectable_modes():
    """app/args.py stays jax-free, so its --dequant choices list is a
    hand-copied mirror of SELECTABLE_MODES — this pins the pairing."""
    from distributed_llama_multiusers_tpu.app.args import build_parser

    parser = build_parser("test")
    action = next(a for a in parser._actions if a.dest == "dequant")
    assert set(action.choices) == set(SELECTABLE_MODES)
    assert action.default is None  # None -> leave the env/default alone


def test_selectable_is_modes_plus_auto():
    assert SELECTABLE_MODES == DEQUANT_MODES + ("auto",)
