"""Pure-functional Llama forward pass, designed for XLA/TPU.

This is the TPU-native re-design of the reference's graph builder
(src/llm.cpp:126-438). The reference emits a per-node static op list with
explicit sync points; here the entire decode step is ONE traced function —
layers run under ``lax.scan`` (compile-time O(1) in depth), tensor-parallel
slicing is expressed as sharding annotations (see ``parallel/sharding.py``)
and XLA inserts the collectives that the reference implements as
SYNC_NODE_SLICES quantized all-gathers over TCP (src/nn/nn-network.cpp:537-569).

Layer math (reference data flow, SURVEY.md §3.4):
    x += attn(rms_norm(x)) ; x += ffn(rms_norm(x))
with GQA attention over a pre-allocated per-lane KV cache, interleaved RoPE,
and SiLU/GELU gated FFN. All reductions and attention math run in float32;
matmuls run in the params' dtype (bf16 on TPU) with f32 accumulation.

Optional ``emulate_q80_activations`` reproduces the reference's lossy
activation quantization (cast to Q80 before each quantized matmul and at the
TP sync boundary, src/llm.cpp:232-239,308-314) for numerical parity testing.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..formats.model_file import HiddenAct
from ..ops.activations import gelu, silu
from ..ops.linear import matmul, shared_q80_acts
from ..ops.norm import rms_norm
from ..ops.rope import apply_rope
from ..jax_compat import shard_map
from .config import LlamaConfig


class LlamaLayerParams(NamedTuple):
    """Per-layer weights, stacked along a leading [n_layers] axis.

    Matmul weights are stored [d_in, d_out] so that y = x @ W (the .m file
    stores the transpose, [d_out, d_in]; the loader transposes once). Each
    matmul field holds either a dense array or a ``PackedQ40`` (weights kept
    quantized in HBM, dequantized inside the matmul — ops/linear.py).

    MoE models (config.n_experts > 0): w1/w2/w3 gain a leading expert axis
    ([L, E, d_in, d_out]) and ``moe_gate`` holds the router ([L, dim, E]);
    dense models carry moe_gate=None.
    """

    wq: jnp.ndarray  # [L, dim, dim]
    wk: jnp.ndarray  # [L, dim, kv_dim]
    wv: jnp.ndarray  # [L, dim, kv_dim]
    wo: jnp.ndarray  # [L, dim, dim]
    w1: jnp.ndarray  # [L, dim, hidden]   gate     (MoE: [L, E, dim, hidden])
    w2: jnp.ndarray  # [L, hidden, dim]   down     (MoE: [L, E, hidden, dim])
    w3: jnp.ndarray  # [L, dim, hidden]   up       (MoE: [L, E, dim, hidden])
    rms_att: jnp.ndarray  # [L, dim]
    rms_ffn: jnp.ndarray  # [L, dim]
    moe_gate: jnp.ndarray | None = None  # [L, dim, n_experts] router, f32
    # Qwen2-family q/k/v projection biases (config.qkv_bias); None for the
    # Llama/Mistral/Mixtral families. Added to the matmul outputs BEFORE
    # RoPE, matching the HF formulation.
    bq: jnp.ndarray | None = None  # [L, dim]
    bk: jnp.ndarray | None = None  # [L, kv_dim]
    bv: jnp.ndarray | None = None  # [L, kv_dim]


class LlamaParams(NamedTuple):
    embedding: jnp.ndarray  # [vocab, dim]
    layers: LlamaLayerParams
    rms_final: jnp.ndarray  # [dim]
    wcls: jnp.ndarray  # [dim, vocab]
    rope_cos: jnp.ndarray  # [seq_len, head_size//2] f32
    rope_sin: jnp.ndarray  # [seq_len, head_size//2] f32


class KVCache(NamedTuple):
    k: jnp.ndarray  # [L, B, S, n_kv_heads, head_size]
    v: jnp.ndarray  # [L, B, S, n_kv_heads, head_size]


class PagedKVCache(NamedTuple):
    """Paged KV layout (the vLLM-style indirection): one device-resident
    pool of fixed-size pages shared by every lane, plus a per-lane page
    table mapping logical block ``b`` of lane ``i`` to physical page
    ``table[i, b]``. A page holds ``page_size`` tokens' K/V for EVERY
    layer at the same physical index, so one table drives all layers.

    ``table`` entries equal to ``n_pages`` mean "unmapped": writes
    through them are dropped by the ``mode="drop"`` scatter and reads
    land past the attention mask. The table rides the cache pytree, so
    every compiled step family threads the indirection automatically —
    no signature changes, and a table update between dispatches is just
    a new pytree leaf (the pool arrays are donated through as always)."""

    k: jnp.ndarray  # [L, n_pages, page_size, n_kv_heads, head_size]
    v: jnp.ndarray  # [L, n_pages, page_size, n_kv_heads, head_size]
    table: jnp.ndarray  # [B, blocks_per_lane] int32 physical page ids


def init_kv_cache(config: LlamaConfig, n_lanes: int, dtype=jnp.float32) -> KVCache:
    shape = (config.n_layers, n_lanes, config.seq_len, config.n_kv_heads, config.head_size)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def init_paged_kv_cache(
    config: LlamaConfig,
    n_lanes: int,
    n_pages: int,
    page_size: int,
    n_blocks: int | None = None,
    dtype=jnp.float32,
) -> PagedKVCache:
    """Zero-filled page pool + all-unmapped tables (every entry is the
    ``n_pages`` sentinel; admission maps real pages per lane).
    ``n_blocks`` is the table width — pass the pool's authoritative
    ``blocks_per_lane`` so the device leaf and the host mirror cannot
    drift; the ceil-div fallback serves direct/test construction."""
    blocks = n_blocks if n_blocks is not None else -(-config.seq_len // page_size)
    shape = (
        config.n_layers, n_pages, page_size,
        config.n_kv_heads, config.head_size,
    )
    return PagedKVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        table=jnp.full((n_lanes, blocks), n_pages, jnp.int32),
    )


def _to_cache_dtype(x: jnp.ndarray, dtype) -> jnp.ndarray:
    """Cast fresh K/V rows to the cache storage dtype. float8_e4m3 (the
    quarter-footprint serving option, --kv-dtype f8) has no inf: saturate
    at its finite max so a rare activation outlier degrades to clipping
    instead of NaN-poisoning the lane's cache."""
    if dtype == jnp.float8_e4m3fn:
        lim = float(jnp.finfo(dtype).max)
        x = jnp.clip(x, -lim, lim)
    return x.astype(dtype)


def _maybe_bias(y: jnp.ndarray, b: jnp.ndarray | None) -> jnp.ndarray:
    """Add a per-layer projection bias when present (Qwen2-family q/k/v,
    config.qkv_bias); identity for the bias-free families."""
    if b is None:
        return y
    return y + b.astype(y.dtype)


def _qdq_q80(x: jnp.ndarray) -> jnp.ndarray:
    """Quantize-dequantize through Q80 blocks — emulates the reference's
    F32->Q80 casts (src/nn/nn-quants.cpp:154-172) via the shared JAX codec."""
    from ..quants.jax_codec import qdq_q80

    return qdq_q80(x, mode="runtime")


def _use_sp(mesh, b: int, t: int | None = None) -> bool:
    """Whether attention should take the sequence-parallel shard_map path:
    needs an sp>1 mesh and whole shards — lanes tiling dp (single-lane
    prefill with dp>1 stays on GSPMD) and, when queries are sequence-sharded
    (t given, ring attention), t tiling sp."""
    if mesh is None or mesh.shape.get("sp", 1) <= 1:
        return False
    if b % mesh.shape.get("dp", 1) != 0:
        return False
    return t is None or t % mesh.shape["sp"] == 0


def _moe_topk(y: jnp.ndarray, moe_gate: jnp.ndarray, n_active: int):
    """Router top-k: returns (weights [B,T,k] f32 — softmax renormalized over
    the selected k, Mixtral semantics — and expert ids [B,T,k] int32). The
    router reads the unquantized normed activations. The reference carries
    n_experts in its header but never executes MoE — SURVEY.md §2.4."""
    logits = jnp.einsum(
        "btd,de->bte", y.astype(jnp.float32), moe_gate.astype(jnp.float32)
    )
    vals, idx = jax.lax.top_k(logits, n_active)
    return jax.nn.softmax(vals, axis=-1), idx


def _moe_router_weights(y: jnp.ndarray, moe_gate: jnp.ndarray, n_active: int) -> jnp.ndarray:
    """Dense routing weights [B, T, E]: top-k weights scattered over the
    expert axis, zero for unselected experts."""
    w, idx = _moe_topk(y, moe_gate, n_active)
    onehot = jax.nn.one_hot(idx, moe_gate.shape[-1], dtype=w.dtype)  # [B,T,k,E]
    return jnp.einsum("btk,btke->bte", w, onehot)


def _moe_ffn_sparse(yq, topw, topi, w1, w2, w3, act_fn, maybe_qdq):
    """Exact sparse top-k dispatch via grouped matmuls: the B*T*k
    (token, expert) assignments are sorted by expert and each expert
    multiplies only its own contiguous row group (``lax.ragged_dot`` — the
    MXU-native MoE primitive; static shapes, no capacity, no token drops).
    Per-token FFN FLOPs scale with k = n_active, not E, unlike a dense
    dispatch that runs every expert on every token."""
    b, t, d = yq.shape
    e, k = w1.shape[0], topi.shape[-1]
    n = b * t
    x_flat = yq.reshape(n, d)
    expert_flat = topi.reshape(n * k)
    token_flat = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)
    weight_flat = topw.reshape(n * k)
    order = jnp.argsort(expert_flat)  # stable: ties keep token order
    tok_sorted = token_flat[order]
    xs = x_flat[tok_sorted]  # [n*k, d]
    group_sizes = jnp.zeros((e,), jnp.int32).at[expert_flat].add(1)
    g = act_fn(jax.lax.ragged_dot(xs, w1, group_sizes))
    u = jax.lax.ragged_dot(xs, w3, group_sizes)
    ds = jax.lax.ragged_dot(maybe_qdq(g * u), w2, group_sizes)  # [n*k, d]
    contrib = ds * weight_flat[order][:, None].astype(ds.dtype)
    out = jnp.zeros((n, d), ds.dtype).at[tok_sorted].add(contrib)
    return out.reshape(b, t, d)


def _moe_ffn_ep_packed(yq, rw, w1, w2, w3, act_fn, maybe_qdq, mesh):
    """Expert-parallel MoE over PackedQ40 stacks WITHOUT dequantizing to
    HBM: shard_map pins each device's resident experts (ep axis) and tp
    slice, runs the dequant-in-matmul kernel per local expert, and psums the
    routed partial sums over (ep, tp) — the EP-native layout where weights
    never move, only the (small) activations are replicated."""
    from jax.sharding import PartitionSpec as P

    from ..ops.linear import q40_matmul_local
    from ..quants.packed import PackedQ40

    e = w1.packed.shape[0]
    ep = mesh.shape.get("ep", 1)
    e_local = e // ep

    def body(yq, rw, p1, s1, p2, s2, p3, s3):
        ep_idx = jax.lax.axis_index("ep")
        out = None
        for el in range(e_local):
            g = act_fn(q40_matmul_local(yq, PackedQ40(p1[el], s1[el])))
            u = q40_matmul_local(yq, PackedQ40(p3[el], s3[el]))
            d = q40_matmul_local(maybe_qdq(g * u), PackedQ40(p2[el], s2[el]))
            w_e = jax.lax.dynamic_slice_in_dim(rw, ep_idx * e_local + el, 1, axis=-1)
            term = d * w_e.astype(d.dtype)
            out = term if out is None else out + term
        return jax.lax.psum(out, ("ep", "tp"))

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(), P(),
            P("ep", None, "tp"), P("ep", None, "tp"),  # w1 planes [E, din/2|32, h]
            P("ep", "tp", None), P("ep", "tp", None),  # w2 planes [E, h/2|32, d]
            P("ep", None, "tp"), P("ep", None, "tp"),  # w3 planes
        ),
        out_specs=P(),
        check_vma=False,
    )(yq, rw, w1.packed, w1.scales, w2.packed, w2.scales, w3.packed, w3.scales)


# sequence-length threshold at which the single-shard PackedQ40 path stops
# looping over every expert (dequant-in-matmul, bytes-optimal) and instead
# dequantizes each expert ONCE and takes the grouped ragged_dot dispatch
# (FLOPs ∝ k). Shapes are static under jit, so this is a compile-time
# branch. Gated on T (per-lane step length), NOT B*T: decode (T=1) and
# speculative verify (T=K=4) are weight-bandwidth-bound at ANY lane count —
# every resident expert's bytes are the cost either way, so dequantizing to
# a dense temp would only add traffic — while prefill/training sequences
# (T >= this) are compute-bound, where paying ~4.5x the expert bytes once
# buys an E/k FLOPs cut.
MOE_PACKED_SPARSE_MIN_TOKENS = 32


def _moe_ffn(y, yq, lp, act_fn, n_active: int, maybe_qdq, ep_sharded: bool = False,
             mesh=None):
    """Gated-FFN mixture. Dispatch:

    - dense expert weights, single shard: exact sparse grouped dispatch
      (``_moe_ffn_sparse``) — FLOPs proportional to k, not E.
    - PackedQ40 + Pallas, single shard, decode-shaped (T below
      MOE_PACKED_SPARSE_MIN_TOKENS — plain decode and speculative verify):
      static per-expert dequant-in-matmul loop (weight-bandwidth-bound:
      every resident expert's bytes are the cost, and they are read exactly
      once, straight from the packed planes).
    - PackedQ40, single shard, prefill/training-shaped
      (T >= MOE_PACKED_SPARSE_MIN_TOKENS): dequantize each expert once and
      run the same grouped ragged_dot dispatch as dense — FLOPs ∝ k, not E
      (round-4 weak #3: the loop paid E/k× the FLOPs on prefill).
    - PackedQ40 + Pallas, ep-sharded mesh: shard_map expert-parallel path
      (``_moe_ffn_ep_packed``) — weights stay quantized and resident.
    - otherwise (dense weights on an ep mesh, or no Pallas): dense-dispatch
      einsums whose expert axis GSPMD partitions over ep; selection happens
      through the zero routing weights."""
    from ..ops.linear import pallas_kernel_active
    from ..quants.packed import PackedQ40, unpack_q40

    w1, w2, w3 = lp.w1, lp.w2, lp.w3
    if isinstance(w1, PackedQ40):
        # the ep shard_map path needs the mesh handle (pipeline stages run
        # under vmap, where shard_map does not nest) and whole-block tp
        # shards: hidden % (32*tp) covers the w2 plane sharding AND the
        # per-shard Q80 qdq blocks; otherwise fall through to unpack+einsum
        def _ep_path_ok():
            if mesh is None:
                return False
            tp = mesh.shape.get("tp", 1)
            hidden = w1.packed.shape[-1]
            return tp == 1 or hidden % (32 * tp) == 0

        keep_packed = ep_sharded or yq.shape[1] < MOE_PACKED_SPARSE_MIN_TOKENS
        if pallas_kernel_active() and keep_packed and (
            not ep_sharded or _ep_path_ok()
        ):
            rw = _moe_router_weights(y, lp.moe_gate, n_active)
            if ep_sharded:
                return _moe_ffn_ep_packed(
                    yq, rw, w1, w2, w3, act_fn, maybe_qdq, mesh
                )
            out = None
            for e in range(w1.packed.shape[0]):
                g = act_fn(matmul(yq, PackedQ40(w1.packed[e], w1.scales[e])))
                u = matmul(yq, PackedQ40(w3.packed[e], w3.scales[e]))
                d = matmul(maybe_qdq(g * u), PackedQ40(w2.packed[e], w2.scales[e]))
                term = d * rw[..., e : e + 1].astype(d.dtype)
                out = term if out is None else out + term
            return out
        w1 = unpack_q40(w1, yq.dtype)
        w2 = unpack_q40(w2, yq.dtype)
        w3 = unpack_q40(w3, yq.dtype)
    if not ep_sharded:
        topw, topi = _moe_topk(y, lp.moe_gate, n_active)
        return _moe_ffn_sparse(yq, topw, topi, w1, w2, w3, act_fn, maybe_qdq)
    rw = _moe_router_weights(y, lp.moe_gate, n_active)
    g = act_fn(jnp.einsum("btd,edh->bteh", yq, w1))
    u = jnp.einsum("btd,edh->bteh", yq, w3)
    d = jnp.einsum("bteh,ehd->bted", maybe_qdq(g * u), w2)
    return jnp.einsum("bted,bte->btd", d, rw.astype(d.dtype))


def _dense_attention(qf, kf, vf, mask, scale):
    """Single-device GQA attention with materialized scores (reference
    multiheadAtt_F32, src/nn/nn-cpu-ops.cpp:749-784). qf: [B,T,K,G,H] f32;
    kf/vf: [B,S,K,H] f32; mask: [B,T,S]."""
    scores = jnp.einsum("btkgh,bskh->btkgs", qf * scale, kf)
    scores = jnp.where(mask[:, :, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("btkgs,bskh->btkgh", probs, vf)


def llama_forward(
    config: LlamaConfig,
    params: LlamaParams,
    tokens: jnp.ndarray,  # [B, T] int32
    positions: jnp.ndarray,  # [B, T] int32 (per-lane positions; fixes reference defect (b))
    cache: KVCache,
    emulate_q80_activations: bool = False,
    mesh=None,
    q80_sync: bool = False,
) -> tuple[jnp.ndarray, KVCache]:
    """Returns (logits [B, T, vocab] float32, updated cache).

    Works for prefill (T > 1) and decode (T = 1) alike; the KV cache is
    per-lane (fixes reference defect (c) where all lanes shared one cache).

    ``cache`` may be a :class:`PagedKVCache` (paged attention): K/V are
    gathered per lane through the page table into the same ``[B, S, ...]``
    view the contiguous path reads — identical values in identical order,
    so the attention math (and the token streams) are byte-identical to
    the contiguous layout — and the KV append scatters through the table
    to ``(page, slot)``. The choice is a pytree-structure property, fixed
    at trace time: one compiled program per layout, no runtime flag.

    With ``mesh`` (axes dp/tp/sp) and sp > 1, attention runs sequence-
    parallel over the S-sharded cache via flash-stats psum
    (parallel/ring_attention.sp_attention) instead of relying on GSPMD to
    partition the dense-scores einsum.

    ``q80_sync`` (with a tp>1 mesh): the wo/w2 row-parallel outputs cross
    the mesh as Q80 (int8 + f16 block scales) instead of f32 — the
    reference's default transport (--buffer-float-type q80, ZQ pipe
    src/llm.cpp:150) realized as psum_scatter + quantized all_gather
    (parallel/collectives.q80_sync_matmul).
    """
    b, t = tokens.shape
    h_cfg = config
    n_heads, n_kv, hd = h_cfg.n_heads, h_cfg.n_kv_heads, h_cfg.head_size
    eps = h_cfg.norm_epsilon
    act_fn = silu if h_cfg.hidden_act == HiddenAct.SILU else gelu

    maybe_qdq = _qdq_q80 if emulate_q80_activations else (lambda y: y)
    paged = isinstance(cache, PagedKVCache)
    # sp (sequence-parallel) attention shards the contiguous S axis; the
    # paged pool has no per-lane S axis to shard, so paged caches take
    # the dense path (GSPMD still partitions the einsums) — pod serving
    # meshes are pure-TP, where the pool shards over kv heads instead
    use_sp = _use_sp(mesh, b) and not paged
    use_q80_sync = False
    if q80_sync and mesh is not None:
        from ..parallel.collectives import q80_sync_engages, q80_sync_matmul

        # shared predicate with the runtime_setup startup log
        use_q80_sync = q80_sync_engages(h_cfg, dict(mesh.shape))
    use_ring_sync = False
    if mesh is not None:
        from ..ops.ring_collective import (
            ring_sync_engages,
            ring_sync_matmul,
            ring_sync_supported,
        )

        # ring-overlapped TP sync (default on, DLLAMA_RING_SYNC=off escape
        # hatch): pure-TP meshes route the wo/w2 row-parallel matmuls
        # through the chunked ring instead of GSPMD's post-matmul
        # all-reduce; with q80_sync the gather half ships the Q80 wire
        use_ring_sync = ring_sync_engages(h_cfg, dict(mesh.shape))

    def synced_matmul(y, w):
        """A row-parallel (col-sliced) wo/w2 matmul plus its TP sync:
        ring-overlapped (optionally Q80-wire), Q80 psum_scatter+gather, or
        the plain GSPMD matmul whose all-reduce XLA inserts."""
        if use_ring_sync:
            d_out = w.d_out if hasattr(w, "d_out") else w.shape[-1]
            if ring_sync_supported(d_out, mesh.shape["tp"], use_q80_sync):
                out = ring_sync_matmul(y, w, mesh, q80_wire=use_q80_sync)
                # the Q80 wire quantizes ON the wire (the q80 branch's
                # contract); the f32 wire keeps the output-side cast
                return out if use_q80_sync else maybe_qdq(out)
        if use_q80_sync:
            return q80_sync_matmul(y, w, mesh)
        return maybe_qdq(matmul(y, w))

    # Shared Q80 activation operands (ops/pallas_q40.Q80Acts): wq/wk/wv
    # consume one normed x and w1/w3 another, so each site builds its
    # activation-quant + relayout operands ONCE instead of once per
    # matmul (one build feeds three dots at the attention site, two at
    # the FFN site). Single-chip only: under a mesh the matmuls go
    # through the GSPMD custom_partitioning wrapper, which takes raw
    # activations. shared_q80_acts itself no-ops when the Pallas kernel
    # is off, so every other path sees the plain activation.
    from ..quants.packed import PackedQ40

    share = mesh is None and isinstance(
        getattr(params.layers, "wq", None), PackedQ40
    )
    share_q80 = shared_q80_acts if share else (lambda y: y)

    x = params.embedding[tokens]  # [B, T, dim]
    lane_idx = jnp.arange(b)[:, None]  # [B, 1]

    # cache index validity: query at position p attends to cache slots s <= p
    s_idx = jnp.arange(h_cfg.seq_len)  # [S]
    attn_mask = s_idx[None, None, :] <= positions[:, :, None]  # [B, T, S]

    if paged:
        # page indirection, computed ONCE (the table is layer-invariant):
        # write targets (page, slot) per (lane, position) and the flat
        # gather index reassembling each lane's logical [S] view from its
        # pages. Sentinel table entries (== n_pages: unmapped blocks) and
        # positions >= seq_len (parked/idle lanes) become out-of-range
        # indices — the mode="drop" scatter discards those writes and the
        # clamped gather reads slots the s <= pos mask already excludes.
        n_pages, page = cache.k.shape[1], cache.k.shape[2]
        table = cache.table  # [B, blocks_per_lane]
        n_blocks = table.shape[1]
        w_blk = jnp.clip(positions // page, 0, n_blocks - 1)
        w_page = jnp.take_along_axis(table, w_blk, axis=1)  # [B, T]
        w_page = jnp.where(positions < h_cfg.seq_len, w_page, n_pages)
        w_slot = positions % page
        gather_idx = (
            table[:, :, None] * page
            + jnp.arange(page, dtype=jnp.int32)[None, None, :]
        ).reshape(b, n_blocks * page)[:, : h_cfg.seq_len]  # [B, S]

    def layer_step(x, layer_in):
        lp, k_cache, v_cache = layer_in  # contiguous: [B, S, n_kv, hd];
        # paged: [n_pages, page_size, n_kv, hd]
        dtype = x.dtype

        y = rms_norm(x, lp.rms_att, eps)
        yq = share_q80(maybe_qdq(y))  # one operand build for wq/wk/wv
        q = _maybe_bias(matmul(yq, lp.wq), lp.bq).reshape(b, t, n_heads, hd)
        k = _maybe_bias(matmul(yq, lp.wk), lp.bk).reshape(b, t, n_kv, hd)
        v = _maybe_bias(matmul(yq, lp.wv), lp.bv).reshape(b, t, n_kv, hd)

        q = apply_rope(q, params.rope_cos, params.rope_sin, positions)
        k = apply_rope(k, params.rope_cos, params.rope_sin, positions)

        # KV append at per-lane positions (reference OP_SHIFT, scatter on
        # TPU). mode="drop" pins JAX's default out-of-bounds scatter
        # semantics: a speculative-verify lane near seq_len writes its
        # overshooting draft slots nowhere, so per-lane spec gating needs no
        # global barrier (scheduler._run's per-lane d_max relies on this).
        # Paged caches scatter through the page table to (page, slot)
        # instead of (lane, position) — same drop rule, and unmapped
        # sentinel entries drop the write too.
        if paged:
            k_cache = k_cache.at[w_page, w_slot].set(
                _to_cache_dtype(k, k_cache.dtype), mode="drop"
            )
            v_cache = v_cache.at[w_page, w_slot].set(
                _to_cache_dtype(v, v_cache.dtype), mode="drop"
            )
        else:
            k_cache = k_cache.at[lane_idx, positions].set(
                _to_cache_dtype(k, k_cache.dtype), mode="drop"
            )
            v_cache = v_cache.at[lane_idx, positions].set(
                _to_cache_dtype(v, v_cache.dtype), mode="drop"
            )

        # GQA attention in f32 (reference multiheadAtt_F32, nn-cpu-ops.cpp:749-784)
        group = n_heads // n_kv
        qf = q.astype(jnp.float32).reshape(b, t, n_kv, group, hd)
        scale = 1.0 / float(hd) ** 0.5
        if paged:
            # gather each lane's logical [S] view through the page table:
            # the same values a contiguous lane plane would hold, in the
            # same order, so the f32 attention below is byte-identical to
            # the contiguous path (pinned by tests/test_prefix_cache.py)
            kf = k_cache.reshape(n_pages * page, n_kv, hd)[gather_idx]
            vf = v_cache.reshape(n_pages * page, n_kv, hd)[gather_idx]
            attn = _dense_attention(
                qf, kf.astype(jnp.float32), vf.astype(jnp.float32),
                attn_mask, scale,
            )
        elif use_sp:
            from ..parallel.ring_attention import sp_attention

            attn = sp_attention(qf, k_cache, v_cache, positions, mesh, scale)
        else:
            attn = _dense_attention(
                qf, k_cache.astype(jnp.float32), v_cache.astype(jnp.float32),
                attn_mask, scale,
            )
        attn = attn.reshape(b, t, n_heads * hd).astype(dtype)

        # sync-boundary cast (ZQ pipe) + merge_add; with a compressed wire
        # (q80/ring-q80) the quantization happens ON the wire instead of as
        # an output-side qdq cast
        x = x + synced_matmul(maybe_qdq(attn), lp.wo)

        y = rms_norm(x, lp.rms_ffn, eps)
        yq = maybe_qdq(y)
        if h_cfg.n_experts > 0:
            d = _moe_ffn(
                y, yq, lp, act_fn, h_cfg.n_active_experts, maybe_qdq,
                ep_sharded=mesh is not None and mesh.shape.get("ep", 1) > 1,
                mesh=mesh,
            )
            x = x + maybe_qdq(d)
        else:
            yqs = share_q80(yq)  # one operand build for w1/w3
            g = act_fn(matmul(yqs, lp.w1))
            u = matmul(yqs, lp.w3)
            x = x + synced_matmul(maybe_qdq(g * u), lp.w2)

        return x, (k_cache, v_cache)

    x, (new_k, new_v) = jax.lax.scan(layer_step, x, (params.layers, cache.k, cache.v))

    y = rms_norm(x, params.rms_final, eps)
    logits = matmul(maybe_qdq(y), params.wcls).astype(jnp.float32)  # [B, T, vocab]
    # wcls may be padded past vocab_size for the slab kernel's wide tiles
    # (quants/packed.pad_packed_d_out); identity slice otherwise
    out_cache = (
        PagedKVCache(k=new_k, v=new_v, table=cache.table)
        if paged
        else KVCache(k=new_k, v=new_v)
    )
    return logits[..., : h_cfg.vocab_size], out_cache


def llama_forward_train(
    config: LlamaConfig,
    params: LlamaParams,
    tokens: jnp.ndarray,  # [B, T] int32
    mesh=None,
) -> jnp.ndarray:
    """Cache-free causal forward over a full sequence — the training-mode twin
    of ``llama_forward`` (the reference is inference-only; training support is
    a capability extension, same layer math). Returns logits [B, T, vocab].

    With ``mesh`` and sp > 1 the sequence axis is sharded and attention runs
    as ring attention (KV blocks rotate over the sp axis via ppermute,
    parallel/ring_attention.ring_attention) — long-context training/prefill
    never materializes the full [T, T] score matrix per device."""
    b, t = tokens.shape
    eps = config.norm_epsilon
    use_sp = _use_sp(mesh, b, t)

    x = params.embedding[tokens]
    layer_step = train_layer_step_fn(
        config, params.rope_cos, params.rope_sin, mesh=mesh if use_sp else None,
        ep_sharded=mesh is not None and mesh.shape.get("ep", 1) > 1,
        moe_mesh=mesh,
    )
    x, _ = jax.lax.scan(layer_step, x, params.layers)
    y = rms_norm(x, params.rms_final, eps)
    return matmul(y, params.wcls).astype(jnp.float32)[..., : config.vocab_size]


def train_layer_step_fn(config: LlamaConfig, rope_cos, rope_sin, mesh=None,
                        ep_sharded=False, moe_mesh=None):
    """The causal full-sequence transformer layer as a lax.scan step
    ``(x [B,T,dim], lp) -> (x, None)`` — shared by llama_forward_train and
    the pipeline-parallel schedule (parallel/pipeline.py). With ``mesh``,
    attention runs as ring attention over sp (caller must guarantee whole
    shards; pipeline stages pass mesh=None — shard_map does not nest)."""
    n_heads, n_kv, hd = config.n_heads, config.n_kv_heads, config.head_size
    eps = config.norm_epsilon
    act_fn = silu if config.hidden_act == HiddenAct.SILU else gelu

    def layer_step(x, lp):
        b, t = x.shape[0], x.shape[1]
        dtype = x.dtype
        positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None, :], (b, t))
        y = rms_norm(x, lp.rms_att, eps)
        q = _maybe_bias(matmul(y, lp.wq), lp.bq).reshape(b, t, n_heads, hd)
        k = _maybe_bias(matmul(y, lp.wk), lp.bk).reshape(b, t, n_kv, hd)
        v = _maybe_bias(matmul(y, lp.wv), lp.bv).reshape(b, t, n_kv, hd)
        q = apply_rope(q, rope_cos, rope_sin, positions)
        k = apply_rope(k, rope_cos, rope_sin, positions)

        group = n_heads // n_kv
        qf = q.astype(jnp.float32).reshape(b, t, n_kv, group, hd)
        scale = 1.0 / float(hd) ** 0.5
        if mesh is not None:
            from ..parallel.ring_attention import ring_attention

            attn = ring_attention(qf, k.astype(jnp.float32), v.astype(jnp.float32), mesh, scale)
        else:
            causal = jnp.tril(jnp.ones((t, t), bool))
            attn = _dense_attention(
                qf, k.astype(jnp.float32), v.astype(jnp.float32),
                jnp.broadcast_to(causal[None], (b, t, t)), scale,
            )
        attn = attn.reshape(b, t, n_heads * hd)
        x = x + matmul(attn.astype(dtype), lp.wo)

        y = rms_norm(x, lp.rms_ffn, eps)
        if config.n_experts > 0:
            x = x + _moe_ffn(
                y, y, lp, act_fn, config.n_active_experts, lambda v: v,
                ep_sharded=ep_sharded, mesh=moe_mesh,
            )
        else:
            x = x + matmul(act_fn(matmul(y, lp.w1)) * matmul(y, lp.w3), lp.w2)
        return x, None

    return layer_step
