"""Pallas Q40 matmul kernel vs the XLA fallback (interpret mode on CPU).

The reference's kernel-equivalence analogue is matmul_Q80_Q40_F32 vs
matmul_F32 (src/nn/nn-cpu-ops-test.cpp:220-241); here the Pallas kernel and
q40_matmul_xla dequantize identically, so results must agree to float
rounding, not a quantization tolerance.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_llama_multiusers_tpu.ops.pallas_q40 import (
    _f16_bits_to_f32,
    q40_matmul_pallas,
)
from distributed_llama_multiusers_tpu.quants.packed import (
    PackedQ40,
    pack_q40_host,
    q40_matmul_xla,
)


def _pack(rng, d_out, d_in, scale=0.1):
    w = rng.standard_normal((d_out, d_in), dtype=np.float32) * scale
    packed, scales = pack_q40_host(w)
    return PackedQ40(packed=jnp.asarray(packed), scales=jnp.asarray(scales))


def test_f16_bit_conversion_exact():
    # every finite f16 bit pattern converts exactly (incl. denormals)
    bits = np.arange(65536, dtype=np.uint16)
    h = bits.view(np.float16)
    finite = np.isfinite(h)
    got = np.asarray(_f16_bits_to_f32(jnp.asarray(bits.astype(np.int16))))
    np.testing.assert_array_equal(got[finite], h[finite].astype(np.float32))


@pytest.mark.parametrize(
    "m,d_in,d_out",
    [
        (1, 64, 128),
        (5, 256, 384),
        (8, 2048, 512),
        (16, 128, 256),
        # d_in with no power-of-two chunk divisor (1376 = 43*32): the analogue
        # of Llama-2-7B's hidden_dim 11008 that crashed the halves layout
        (3, 1376, 128),
    ],
)
def test_pallas_matches_xla(m, d_in, d_out):
    rng = np.random.default_rng(d_in + d_out)
    pw = _pack(rng, d_out, d_in)
    x = jnp.asarray(rng.standard_normal((m, d_in), dtype=np.float32))
    ref = q40_matmul_xla(x, pw)
    got = q40_matmul_pallas(x, pw, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4, rtol=2e-4)


def test_pallas_leading_batch_dims():
    rng = np.random.default_rng(0)
    pw = _pack(rng, 256, 128)
    x = jnp.asarray(rng.standard_normal((2, 3, 128), dtype=np.float32))
    ref = q40_matmul_xla(x, pw)
    got = q40_matmul_pallas(x, pw, interpret=True)
    assert got.shape == (2, 3, 256)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4, rtol=2e-4)


def test_pallas_extreme_scales():
    # very small weights -> denormal f16 scales still convert exactly
    rng = np.random.default_rng(1)
    pw = _pack(rng, 128, 64, scale=1e-7)
    x = jnp.asarray(rng.standard_normal((4, 64), dtype=np.float32))
    ref = q40_matmul_xla(x, pw)
    got = q40_matmul_pallas(x, pw, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-10)
