"""sharding-axis: every named axis must be declared by the mesh builders.

``parallel/mesh.py`` is the single source of truth for mesh axis names
(``AXES = ("dp", "pp", "tp", "sp", "ep")``). A ``PartitionSpec`` /
``shard_map`` spec / ``lax`` collective that names an axis the mesh
builders never create fails only at trace time on a real mesh — which the
CPU test tier rarely reaches — or worse, silently no-ops when the
misspelled axis is treated as unsharded. This check catches it at lint
time, package-wide:

- ``P(...)`` / ``PartitionSpec(...)`` string and tuple-of-string args;
- axis-name args of ``lax`` collectives (``psum``, ``pmax``, ``pmin``,
  ``pmean``, ``ppermute``, ``all_gather``, ``all_to_all``,
  ``axis_index``) and ``axis_name=`` keywords;
- ``mesh.shape["..."]`` subscripts and ``*shape.get("...")`` lookups.

Non-constant axis expressions (variables, ``*spec`` splats) are skipped —
this is a lint for the literal 99% case, not an evaluator.
"""

from __future__ import annotations

import ast

from .core import Checker, Finding, Project, SourceFile, last_component

# mirror of parallel/mesh.py AXES — used only when no AXES declaration is
# in the analyzed file set (e.g. single-file runs)
DEFAULT_AXES = ("dp", "pp", "tp", "sp", "ep")

SPEC_CALLS = {"P", "PartitionSpec"}
COLLECTIVE_CALLS = {
    "psum", "pmax", "pmin", "pmean", "ppermute", "all_gather",
    "all_to_all", "axis_index", "psum_scatter", "axis_size",
    # ring collectives (ops/ring_collective.py) take the mesh axis name as
    # a plain argument, exactly like the lax primitives they wrap — a
    # misspelled axis would otherwise only die at trace time on a real mesh
    "ring_reduce_scatter", "ring_all_gather", "ring_all_gather_q80",
    "ring_all_reduce", "ring_sync_matmul",
}
AXIS_KWARGS = {"axis_name", "axis_names"}
# axis= is validated ONLY on known collective calls: it is the ubiquitous
# numpy/jnp kwarg everywhere else, where a string value is never a mesh axis
COLLECTIVE_AXIS_KWARG = "axis"


class ShardingAxisChecker(Checker):
    name = "sharding-axis"
    description = (
        "PartitionSpec/shard_map/lax-collective axis names must be "
        "declared by the mesh builders (parallel/mesh.py AXES)"
    )

    def collect(self, sf: SourceFile, project: Project) -> None:
        for node in ast.walk(sf.tree):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "AXES"
            ):
                continue
            try:
                axes = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                continue
            if isinstance(axes, (tuple, list)) and all(
                isinstance(a, str) for a in axes
            ):
                project.axes.update(axes)
                project.axes_src = sf.display

    def check(self, sf: SourceFile, project: Project):
        axes = project.axes or set(DEFAULT_AXES)
        src = project.axes_src or "the built-in default"
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(sf, node, axes, src)
            elif isinstance(node, ast.Subscript):
                # mesh.shape["tp"]
                if (
                    ast.unparse(node.value).endswith("shape")
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                ):
                    yield from self._validate(
                        sf, node.slice, node.slice.value, axes, src
                    )

    def _check_call(self, sf, node: ast.Call, axes, src):
        name = last_component(node.func)
        if name in SPEC_CALLS:
            for arg in node.args:
                yield from self._validate_expr(sf, arg, axes, src)
        elif name in COLLECTIVE_CALLS:
            for arg in node.args:
                yield from self._validate_expr(sf, arg, axes, src)
        elif (
            name == "get"
            and isinstance(node.func, ast.Attribute)
            and ast.unparse(node.func.value).endswith("shape")
            and node.args
        ):
            # mesh.shape.get("tp", 1) / mesh_shape.get("tp", 1)
            yield from self._validate_expr(sf, node.args[0], axes, src)
        for kw in node.keywords:
            if kw.arg in AXIS_KWARGS or (
                kw.arg == COLLECTIVE_AXIS_KWARG and name in COLLECTIVE_CALLS
            ):
                yield from self._validate_expr(sf, kw.value, axes, src)

    def _validate_expr(self, sf, expr: ast.AST, axes, src):
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            yield from self._validate(sf, expr, expr.value, axes, src)
        elif isinstance(expr, (ast.Tuple, ast.List)):
            for e in expr.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    yield from self._validate(sf, e, e.value, axes, src)

    def _validate(self, sf, node: ast.AST, axis: str, axes, src):
        if axis not in axes:
            yield Finding(
                self.name, sf.display, node.lineno,
                f"axis {axis!r} is not declared by the mesh builders "
                f"(AXES from {src}: {sorted(axes)})",
            )
