from .model_file import (
    ModelHeader,
    ArchType,
    HiddenAct,
    RopeType,
    load_model_header,
    write_model_header,
    iter_model_tensors,
    MODEL_MAGIC,
)
from .tokenizer_file import (
    TokenizerData,
    load_tokenizer_file,
    write_tokenizer_file,
    TOKENIZER_MAGIC,
)
