"""Sampler seed generation — OS entropy, never the wall clock.

``int(time.time())`` seeds (the seed repo's habit) hand identical sampler
streams to every request that lands in the same clock tick — at
million-user scale "two requests in the same microsecond" is the common
case, not the corner — and an NTP step can even replay past seeds. dlint's
``clock`` check bans wall-clock seeds; this is the sanctioned source.
"""

from __future__ import annotations

import time

# xorshift64* (tokenizer/sampler.py) has 0 as a fixed point: a zero seed
# would sample token 0 forever. Substitute when entropy lands on 0.
_ZERO_FALLBACK = 0x9E3779B9  # golden-ratio constant, arbitrary non-zero


def fresh_seed() -> int:
    """Fresh 32-bit sampler seed from OS entropy (``np.random.SeedSequence``
    pools ``os.urandom``); monotonic-clock fallback where numpy is absent.
    Never returns 0."""
    try:
        import numpy as np

        seed = int(np.random.SeedSequence().generate_state(1)[0])
    except ImportError:  # pragma: no cover - numpy is a hard dep elsewhere
        seed = time.monotonic_ns() & 0xFFFFFFFF
    return seed or _ZERO_FALLBACK
