"""dlint: project-invariant static analysis for the serving path.

PR 1 made the serving path heavily concurrent; the invariants that keep
it correct ("counters only under ``stats.lock``", "durations use
``time.monotonic()``", "one host transfer per decode step", "axis names
come from ``parallel/mesh.py``") were enforced only by comments and
reviewer memory. This package machine-checks them — the Python/JAX
analogue of the reference repo's sanitizer CI for C++ (SURVEY.md §5.2,
mirrored by ``make sanitize``).

Eighteen checks (docs/LINT.md has the full contract and waiver policy).
The four ``lock-*``/``pod-*`` checks are the v2 cross-file concurrency
layer: they share one lock model (lockgraph.py) of every class-qualified
lock in the package, and the statically computed lock-order graph doubles
as the runtime witness's seed (lockcheck.py, ``DLLAMA_LOCKCHECK=1``).
The ``protocol*``/``replay-determinism`` checks are the v3 wire-protocol
layer: a surface model of ``parallel/multihost.py`` (protocol_check.py)
pinned by ``analysis/protocol.lock``, plus a declared determinism scope
over the journal/recovery/migration/grammar replay closure. The
``jit-*``/``donation-*``/``warmup-*`` checks are the v4 compile-
stability layer: a device-program surface model of ``runtime/engine.py``
(jitmodel.py — every ``jax.jit`` site, step-family binding, dispatcher,
and what ``warmup_engine`` warms), paired with the runtime recompile
witness (jitcheck.py, ``DLLAMA_JITCHECK=1``). The ``resource-balance``/
``device-affinity`` checks are the v5 resource-lifecycle layer: a
cross-file acquire/release surface model (resourcemodel.py — kvpool
pages, stream-registry entries, journal marks, the scheduler's session
mirror, declared in-source via ``_dlint_acquires``/``_dlint_releases``
beside ``_dlint_guarded_by``), paired with the runtime leak witness
(leakcheck.py, ``DLLAMA_LEAKCHECK=1``) that counts — and in strict mode
raises at — resources still held after a drain/stop.

- ``lock-order``     — the cross-file "held while acquiring" graph over
  declared locks stays acyclic (one level of intra-package calls
  included); also pins witness-name/declaration agreement
- ``guarded-by``     — lock discipline for declared shared attributes
- ``lock-blocking``  — no blocking construct (I/O, waits, sends,
  broadcasts, observer calls, subprocesses) under a declared lock
- ``lock-atomicity`` — guarded read-modify-write may not straddle a
  lock release within one function
- ``pod-broadcast``  — multihost proxy methods: validate, broadcast,
  compute — nothing raises/returns between a packet and its paired
  engine call
- ``protocol``       — the pod wire-protocol surface model: every op has
  an encoder and a replay arm, slot indices stay < SLOTS, broadcasts
  are validated pre-broadcast, header widths agree encoder<->replay
- ``protocol-manifest`` — the extracted packet layout matches the pinned
  ``analysis/protocol.lock`` unless PROTOCOL_VERSION was bumped in the
  same diff (``--update-protocol-manifest`` regenerates the pin)
- ``replay-determinism`` — no unjournaled entropy, builtin ``hash()``,
  or set-iteration ordering inside the journal/recovery/migration/
  grammar replay scope
- ``jit-stability`` — device-pytree leaves stored into engine state
  come from the sanctioned sharding-preserving constructor
  (``_replace_leaf``), never a bare ``jnp.asarray``
- ``donation-discipline`` — every ``donate_argnums`` call site rebinds
  the donated operand from the call's results; no use-after-donate
- ``warmup-coverage`` — every dispatchable compiled step family is
  warmed by ``warmup_engine``, bucketed families per prefill bucket
- ``resource-balance`` — every acquire of a declared resource kind
  (kv pages, registry entries, journal marks, session-mirror records)
  is released on all exception paths; intentional transfers carry
  ``ok[resource-balance]`` waivers
- ``device-affinity`` — declared donated-device-pytree touchers run
  only on the batching loop or through ``scheduler.run_device_op()``
- ``host-sync``      — explicit, waived device->host transfers in decode
- ``pipeline-sync``  — NO host syncs at all in the async-pipeline dispatch
  half (engine.decode_pipelined / scheduler._pipeline_dispatch)
- ``clock``          — no wall clock for durations/deadlines/seeds
- ``condvar``        — predicate loops, no busy-polls, joined threads
- ``sharding-axis``  — PartitionSpec/collective axes declared by the mesh

Usage::

    python -m distributed_llama_multiusers_tpu.analysis   # or `make lint`
    python -m distributed_llama_multiusers_tpu.analysis path/to/file.py

Library::

    from distributed_llama_multiusers_tpu.analysis import analyze_paths
    findings = analyze_paths()          # whole package, shipped baseline

Pure stdlib (ast + tokenize): importable and runnable on CPython >= 3.10
with no jax/numpy present.
"""

from __future__ import annotations

from pathlib import Path

from .core import (
    Analyzer,
    Checker,
    Finding,
    Project,
    SourceFile,
    load_baseline,
    write_baseline,
)
from .registry import ALL_CHECKERS, default_checkers

PACKAGE_ROOT = Path(__file__).resolve().parent.parent
REPO_ROOT = PACKAGE_ROOT.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.txt"

__all__ = [
    "ALL_CHECKERS",
    "Analyzer",
    "Checker",
    "DEFAULT_BASELINE",
    "Finding",
    "PACKAGE_ROOT",
    "Project",
    "SourceFile",
    "analyze_paths",
    "default_checkers",
    "load_baseline",
    "write_baseline",
]


def analyze_paths(paths=None, baseline_path=DEFAULT_BASELINE) -> list[Finding]:
    """Run every checker over ``paths`` (default: the whole package) and
    return surviving findings (waivers and baseline applied).
    ``baseline_path=None`` disables the baseline."""
    analyzer = Analyzer(default_checkers())
    return analyzer.run(
        [PACKAGE_ROOT] if paths is None else paths,
        baseline=load_baseline(baseline_path),
        root=REPO_ROOT,
    )
