"""Circuit breaker for the serving path: shed instead of thrash.

When the engine fails repeatedly (an XLA RESOURCE_EXHAUSTED that will
recur on every dispatch, a wedged device link, a watchdog-detected
stall), admitting more work only queues more clients behind a broken
engine. The breaker turns that into the standard closed → open →
half-open machine:

- **closed** — healthy. Engine-scoped failures count; ``threshold``
  CONSECUTIVE ones (any successful step resets the streak) trip it open.
- **open** — ``/health`` reports unhealthy (load balancers route away)
  and ``submit()`` sheds new work with a typed
  :class:`~.qos.AdmissionRejected` (HTTP 503 + Retry-After). Work
  already admitted keeps running — the breaker gates admission, never
  execution. After ``cooldown_s`` the next ``allow()`` transitions to
  half-open and admits that caller as the probe.
- **half-open** — one probe request per cooldown window; a successful
  engine step closes the breaker (``recovered``), another engine-scoped
  failure re-opens it and restarts the cooldown.

The scheduler owns the one breaker instance and drives it from the
supervised loop (runtime/scheduler.py): ``record_engine_failure`` from
the containment path, ``record_success`` from every completed engine
step, ``trip`` from the watchdog. ``stats()`` feeds ``/stats`` and —
bridged like every other field — the ``dllama_breaker_state`` gauge and
``dllama_engine_failures_total{failure_class}`` counter on ``/metrics``
(telemetry/hub.bridge_stats, delta-fed so counter semantics survive
window resets).

Thread-safe; pure counter math under one lock, monotonic clocks only.
"""

from __future__ import annotations

import time

from ..lockcheck import make_lock

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"

# numeric encoding for the /metrics gauge (and the /stats twin field):
# gauges can't carry strings, and alert rules want `> 0` to mean unhealthy
STATE_CODES = {STATE_CLOSED: 0, STATE_HALF_OPEN: 1, STATE_OPEN: 2}


class CircuitBreaker:
    """``threshold`` consecutive engine-scoped failures open the circuit;
    ``cooldown_s`` later a single probe is allowed through (half-open);
    its success closes, its failure re-opens."""

    # dlint guarded-by declaration (analysis/lock_check.py): all breaker
    # state moves under _lock — read by HTTP threads (/health, /stats,
    # submit-time allow()), written by the scheduler loop and watchdog.
    _dlint_guarded_by = {
        ("_lock",): (
            "_state", "_consecutive", "_opened_at", "_last_probe_at",
            "_failures", "_trips", "_shed", "_probes", "_last_error",
            "_last_recovery_s",
        ),
    }

    def __init__(self, threshold: int = 3, cooldown_s: float = 5.0):
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        if cooldown_s <= 0:
            raise ValueError("breaker cooldown must be positive")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._lock = make_lock("CircuitBreaker._lock")
        self._state = STATE_CLOSED
        self._consecutive = 0  # engine failures since the last success
        self._opened_at = 0.0  # monotonic stamp of the last open
        self._last_probe_at = 0.0
        # failure accounting by class — the dllama_engine_failures_total
        # label vocabulary ("engine", "request", "watchdog")
        self._failures: dict[str, int] = {}
        self._trips = 0  # closed/half-open -> open transitions
        self._shed = 0  # allow() == False decisions (submissions refused)
        self._probes = 0  # half-open probes admitted
        self._last_error = ""
        self._last_recovery_s: float | None = None  # open -> closed span

    # -- admission gate ------------------------------------------------------

    def allow(self) -> bool:
        """May a new request be admitted right now? Open + cooldown
        elapsed transitions to half-open and admits THIS caller as the
        probe; half-open admits one probe per cooldown window."""
        now = time.monotonic()
        with self._lock:
            if self._state == STATE_CLOSED:
                return True
            if self._state == STATE_OPEN:
                if now - self._opened_at < self.cooldown_s:
                    self._shed += 1
                    return False
                self._state = STATE_HALF_OPEN
                self._last_probe_at = now
                self._probes += 1
                return True
            # half-open: one probe per cooldown window
            if now - self._last_probe_at >= self.cooldown_s:
                self._last_probe_at = now
                self._probes += 1
                return True
            self._shed += 1
            return False

    def retry_after_s(self) -> float:
        """Retry-After hint for shed submissions: the remaining cooldown,
        floored at 1s (a client retrying sooner meets the same open
        circuit)."""
        now = time.monotonic()
        with self._lock:
            if self._state == STATE_CLOSED:
                return 1.0
            remaining = self.cooldown_s - (now - self._opened_at)
        return max(1.0, remaining)

    # -- scheduler feedback --------------------------------------------------

    def record_engine_failure(self, error: str = "",
                              failure_class: str = "engine") -> str:
        """One engine-scoped failure (containment path). Returns the
        post-transition state for the caller's log line."""
        with self._lock:
            self._failures[failure_class] = (
                self._failures.get(failure_class, 0) + 1
            )
            self._last_error = error[:200]
            self._consecutive += 1
            if self._state == STATE_HALF_OPEN or (
                self._state == STATE_CLOSED
                and self._consecutive >= self.threshold
            ):
                self._state = STATE_OPEN
                self._opened_at = time.monotonic()
                self._trips += 1
            return self._state

    def record_request_failure(self) -> None:
        """Class accounting only: a request-scoped failure (bad prompt,
        tokenizer error) says nothing about engine health and never moves
        the state machine."""
        with self._lock:
            self._failures["request"] = self._failures.get("request", 0) + 1

    def record_success(self) -> None:
        """One successful engine step: the failure streak resets; a
        half-open probe's success closes the circuit. From OPEN, a
        success (work admitted before the trip, still being served)
        closes only once the cooldown has held — the circuit stays open
        at least ``cooldown_s`` after a trip, so a watchdog trip or a
        failure burst cannot flap closed off one lucky step."""
        now = time.monotonic()
        with self._lock:
            self._consecutive = 0
            if self._state == STATE_HALF_OPEN or (
                self._state == STATE_OPEN
                and now - self._opened_at >= self.cooldown_s
            ):
                self._last_recovery_s = now - self._opened_at
                self._state = STATE_CLOSED

    def trip(self, error: str = "watchdog",
             failure_class: str = "watchdog") -> None:
        """Force the circuit open regardless of the streak — the watchdog
        path (a stalled step is worse evidence than N failed ones)."""
        with self._lock:
            self._failures[failure_class] = (
                self._failures.get(failure_class, 0) + 1
            )
            self._last_error = error[:200]
            if self._state != STATE_OPEN:
                self._trips += 1
            self._state = STATE_OPEN
            self._opened_at = time.monotonic()

    # -- exposition ----------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def stats(self) -> dict:
        """Point-in-time snapshot for /stats (one lock hold). The
        ``breaker_state_code`` / ``engine_failures`` fields are the ones
        telemetry/hub.bridge_stats feeds the native metrics from."""
        with self._lock:
            return {
                "breaker_state": self._state,
                "breaker_state_code": STATE_CODES[self._state],
                "breaker_threshold": self.threshold,
                "breaker_consecutive_failures": self._consecutive,
                "breaker_trips": self._trips,
                "breaker_shed": self._shed,
                "breaker_probes": self._probes,
                "breaker_last_error": self._last_error,
                "breaker_last_recovery_s": (
                    None if self._last_recovery_s is None
                    else round(self._last_recovery_s, 3)
                ),
                "engine_failures": dict(self._failures),
                "engine_failures_total": sum(self._failures.values()),
            }
