"""Sharding specs: the reference's TP slicing math as GSPMD annotations.

Mapping from the reference slicers (src/nn/nn-core.cpp:198-266):

    sliceRowMatmul (split output dim)  -> shard last axis of [L, d_in, d_out]
        applies to wq, wk, wv, w1, w3, wcls
    sliceColMatmul (split input dim)   -> shard middle axis of [L, d_in, d_out]
        applies to wo, w2
    sliceKvCache (split kvDim)         -> shard n_kv_heads axis of the cache
    sliceMultiHeadAtt (split heads)    -> implied by the same tp axis
    ZQ all-gather + merge_add          -> XLA inserts reduce-scatter/all-reduce
                                          at the wo/w2 matmul outputs

The row->col pairing means activations stay sharded through attention and the
FFN with exactly one collective per half-layer — the same schedule the
reference realizes manually with its quantized TCP all-gather
(SYNC_NODE_SLICES, src/nn/nn-network.cpp:537-569), but on ICI.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import KVCache, LlamaLayerParams, LlamaParams, PagedKVCache
from ..quants.packed import PackedQ40


def param_shardings(mesh: Mesh, params: LlamaParams | None = None) -> LlamaParams:
    """A LlamaParams-shaped pytree of NamedShardings.

    When ``params`` is given, PackedQ40 weights get a matching PackedQ40 of
    shardings (both nibble and scale planes carry the same spec: row-sliced
    weights shard d_out = the last axis of every plane; col-sliced weights
    shard d_in = axis -2, where the packed/scale planes are d_in/2- and
    d_in/32-rows of the same logical input range)."""

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    def w(field, *spec):
        if params is not None and isinstance(field, PackedQ40):
            return PackedQ40(packed=ns(*spec), scales=ns(*spec))
        return ns(*spec)

    lp = (
        params.layers
        if params is not None
        else LlamaLayerParams(*([None] * len(LlamaLayerParams._fields)))
    )

    def ffn_rank(field):
        x = field.packed if isinstance(field, PackedQ40) else field
        return 3 if x is None else x.ndim

    moe = params is not None and ffn_rank(lp.w1) == 4

    def ffn(field, last_axis_tp: bool):
        # dense ffn: [L, d_in, d_out]; MoE: [L, E, d_in, d_out] — experts
        # shard over ep, the d dimension over tp as in the dense case
        # (sliceRowMatmul/sliceColMatmul, src/nn/nn-core.cpp:207-230)
        if moe:
            spec = ("pp", "ep", None, "tp") if last_axis_tp else ("pp", "ep", "tp", None)
        else:
            spec = ("pp", None, "tp") if last_axis_tp else ("pp", "tp", None)
        return w(field, *spec)

    # every layer-stacked leaf leads with the pp axis (layer stages); with
    # pp=1 that sharding is a no-op
    layers = LlamaLayerParams(
        wq=w(lp.wq, "pp", None, "tp"),
        wk=w(lp.wk, "pp", None, "tp"),
        wv=w(lp.wv, "pp", None, "tp"),
        wo=w(lp.wo, "pp", "tp", None),
        w1=ffn(lp.w1, True),
        w2=ffn(lp.w2, False),
        w3=ffn(lp.w3, True),
        rms_att=ns("pp", None),
        rms_ffn=ns("pp", None),
        moe_gate=ns("pp", None, None) if moe else None,
        # Qwen2 q/k/v biases: [L, d_out] vectors added to row-sliced matmul
        # outputs, so they shard along the same tp axis as the outputs
        **(
            {k: ns("pp", "tp") for k in ("bq", "bk", "bv")}
            if params is not None and lp.bq is not None
            else {}
        ),
    )
    return LlamaParams(
        # embedding replicated: the reference keeps it root-only
        # (src/llm.cpp:185-192); replication avoids a gather per step
        embedding=ns(None, None),
        layers=layers,
        rms_final=ns(None),
        # logits row-sliced across tp like final_matmul_logits (src/llm.cpp:420-432)
        wcls=w(params.wcls if params is not None else None, None, "tp"),
        rope_cos=ns(None, None),
        rope_sin=ns(None, None),
    )


def cache_shardings(mesh: Mesh) -> KVCache:
    """KV cache [L, B, S, n_kv, hd]: lanes over dp, sequence over sp, kv heads
    over tp (the reference shards only kvDim via TP, src/nn/nn-core.cpp:198-205;
    sp adds the sequence dimension it lacks, SURVEY.md §5.7)."""
    ns = lambda *spec: NamedSharding(mesh, P(*spec))
    return KVCache(
        k=ns(None, "dp", "sp", "tp", None),
        v=ns(None, "dp", "sp", "tp", None),
    )


def paged_cache_shardings(mesh: Mesh) -> PagedKVCache:
    """Paged KV pool [L, n_pages, page_size, n_kv, hd] + table [B, blocks]:
    kv heads over tp like the contiguous cache; the page axis is NOT
    sharded — the pool is one global resource every lane maps into, so
    splitting it over dp would re-partition pages by device and break the
    any-lane-any-page indirection (serving pod meshes are pure-TP; see
    llama_forward's paged sp note). The table is a few KB of int32 and
    rides replicated so every shard gathers identically."""
    ns = lambda *spec: NamedSharding(mesh, P(*spec))
    return PagedKVCache(
        k=ns(None, None, None, "tp", None),
        v=ns(None, None, None, "tp", None),
        table=ns(None, None),
    )


def data_shardings(mesh: Mesh):
    """(tokens/positions [B, T], logits [B, T, vocab]) shardings."""
    return (
        NamedSharding(mesh, P("dp", None)),
        NamedSharding(mesh, P("dp", None, "tp")),
    )


def shard_params(params: LlamaParams, mesh: Mesh) -> LlamaParams:
    """Place a host-side params pytree onto the mesh with TP/DP shardings —
    the moment that replaces the reference's root-splits-and-ships-weights
    protocol (NnRootWeightLoader, src/nn/nn-network.cpp:824-901). Handles
    dense and PackedQ40-quantized params alike."""
    shardings = param_shardings(mesh, params)
    return jax.tree.map(jax.device_put, params, shardings)
