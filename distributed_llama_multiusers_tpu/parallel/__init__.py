from .mesh import make_mesh, MeshPlan, validate_mesh_for_config
from .sharding import param_shardings, cache_shardings, data_shardings
from .collectives import q80_all_gather
