"""Graceful drain for the continuous-batching scheduler.

Shutdown today is ``Scheduler.stop()``: in-flight generations finish as
``"cancelled"`` and queued requests fail — correct for an emergency stop,
wrong for a rolling restart. Drain is the graceful path:

1. ``scheduler._draining`` flips — ``submit()`` starts shedding with the
   typed :class:`~..serving.qos.AdmissionRejected` (HTTP 503 + Retry-After),
   and the ``/health`` readiness endpoint flips to 503 so load balancers
   stop routing here;
2. the batching loop keeps serving everything already queued or active
   until every lane is free and the queue is empty (deadlines still apply,
   so a drain is bounded by the longest queue-timeout + budget when those
   are configured), then exits on its own;
3. the loop thread is joined. If ``timeout`` elapses first, the remaining
   work is force-cancelled via ``scheduler.stop()`` — either way every
   future resolves, so no client ever hangs on a draining server.
"""

from __future__ import annotations

import logging

log = logging.getLogger(__name__)


def drain_scheduler(scheduler, timeout: float | None = None) -> bool:
    """Run the drain protocol on ``scheduler``. Returns True on a clean
    drain (all work finished), False when ``timeout`` forced cancellation.
    Idempotent; safe on a scheduler that never started."""
    scheduler._draining.set()
    watchdog = getattr(scheduler, "watchdog", None)
    thread = scheduler._thread
    if thread is None or not thread.is_alive():
        # loop never ran (or already stopped): nothing is generating, but
        # queued futures must still resolve — as a retryable 503, since
        # these requests never got any service
        for req in scheduler.queue.drain():
            scheduler._shed_unadmitted(req)
        scheduler._thread = None
        if watchdog is not None:
            watchdog.stop()
        return True
    thread.join(timeout)
    if thread.is_alive():
        log.warning(
            "drain timed out after %ss with work still active; "
            "force-cancelling remaining lanes",
            timeout,
        )
        try:
            scheduler.stop()  # resolves in-flight as "cancelled", queued as failed
        except RuntimeError:
            # the loop thread survived even the forced join (hung device
            # dispatch). Nothing more can be done from here; report the
            # failed drain as False instead of masking it with a raise
            # from the cleanup path.
            log.error(
                "force-stop after drain timeout failed; loop thread still alive"
            )
        return False
    scheduler._thread = None
    # a submit() racing the drain flag can slip a request into the queue
    # after the loop took its exit snapshot; flush (as retryable 503s) so
    # every future resolves
    for req in scheduler.queue.drain():
        scheduler._shed_unadmitted(req)
    if watchdog is not None:  # the monitor thread drains with the loop
        watchdog.stop()
    return True
