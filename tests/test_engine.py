"""Engine + continuous-batching scheduler tests — the corrected multi-user
loop (SURVEY.md §2.3 defects (a)-(e) each have a test here)."""

import threading
import time

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats import load_model_header
from distributed_llama_multiusers_tpu.models import load_params_from_m
from distributed_llama_multiusers_tpu.models.oracle import OracleLlama, oracle_weights_from_m
from distributed_llama_multiusers_tpu.runtime import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    Request,
)
from distributed_llama_multiusers_tpu.tokenizer import Tokenizer


@pytest.fixture(scope="module")
def stack(tiny_model):
    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    tok = Tokenizer(tiny_model["tokenizer"])
    engine = InferenceEngine(config, params, n_lanes=4, prefill_buckets=(8, 16))
    oracle = OracleLlama(config, oracle_weights_from_m(tiny_model["model"], h), emulate_q80=False)
    return config, engine, tok, oracle


def test_prefill_then_decode_matches_oracle(stack):
    """Full prompt prefill + greedy decode == oracle (defect (a) fixed)."""
    config, engine, tok, oracle = stack
    prompt = tok.encode("hello world")
    ref = oracle.generate_greedy(prompt, 10)

    logits, greedy, pos = engine.prefill(0, prompt)
    out = []
    cur = greedy
    tokens = np.zeros(engine.n_lanes, np.int32)
    positions = np.zeros(engine.n_lanes, np.int32)
    for _ in range(10):
        out.append(cur)
        tokens[0] = cur
        positions[0] = pos
        logits2, g, _ = engine.decode(tokens, positions)
        cur = int(g[0])
        pos += 1
    assert out == ref


def test_scheduler_single_request(stack):
    config, engine, tok, oracle = stack
    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    try:
        req = sched.submit(Request(prompt="hello world", max_tokens=8, temperature=0.0))
        text = req.future.result(timeout=60)
        assert isinstance(text, str)
        assert req.generated_tokens
        assert len(req.generated_tokens) <= 8
        # matches oracle tokens
        ref = oracle.generate_greedy(tok.encode("hello world"), len(req.generated_tokens))
        assert req.generated_tokens == ref
    finally:
        sched.stop()


def test_scheduler_concurrent_requests_isolated(stack):
    """Concurrent requests produce the same outputs as solo runs
    (defects (b)+(c) fixed: per-lane positions + per-lane KV)."""
    config, engine, tok, oracle = stack
    prompts = ["hello world", "(42)", "worl", "hello hello"]
    solo = {}
    for p in prompts:
        ids = tok.encode(p)
        solo[p] = oracle.generate_greedy(ids, 6)

    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    try:
        reqs = [sched.submit(Request(prompt=p, max_tokens=6, temperature=0.0)) for p in prompts]
        for p, r in zip(prompts, reqs):
            r.future.result(timeout=120)
            assert r.generated_tokens == solo[p], f"prompt {p!r} diverged under batching"
    finally:
        sched.stop()


def test_scheduler_more_requests_than_lanes(stack):
    """Requests beyond lane capacity queue up and complete (continuous
    join/leave)."""
    config, engine, tok, _ = stack
    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    try:
        reqs = [
            sched.submit(Request(prompt="hello", max_tokens=4, temperature=0.0))
            for _ in range(10)  # > 4 lanes
        ]
        results = [r.future.result(timeout=120) for r in reqs]
        assert len(results) == 10
        assert len({tuple(r.generated_tokens) for r in reqs}) == 1  # all identical
    finally:
        sched.stop()


def test_scheduler_streaming_deltas(stack):
    config, engine, tok, _ = stack
    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    try:
        chunks = []
        req = Request(prompt="hello world", max_tokens=8, temperature=0.0, on_delta=chunks.append)
        sched.submit(req)
        text = req.future.result(timeout=60)
        assert "".join(chunks) == text
    finally:
        sched.stop()


def test_scheduler_clean_shutdown(stack):
    """stop() joins the loop thread (defect (d) fixed: the reference's loop
    never terminates and hangs the process on exit)."""
    config, engine, tok, _ = stack
    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    req = sched.submit(Request(prompt="hello", max_tokens=2, temperature=0.0))
    req.future.result(timeout=60)
    t0 = time.time()
    sched.stop()
    assert time.time() - t0 < 10
    assert sched._thread is None


def test_seeded_sampling_reproducible(stack):
    config, engine, tok, _ = stack
    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    try:
        a = sched.submit(Request(prompt="hello", max_tokens=8, temperature=0.9, seed=123))
        b = sched.submit(Request(prompt="hello", max_tokens=8, temperature=0.9, seed=123))
        a.future.result(timeout=60)
        b.future.result(timeout=60)
        assert a.generated_tokens == b.generated_tokens
    finally:
        sched.stop()


def test_prompt_longer_than_context_rejected_gracefully(stack):
    config, engine, tok, _ = stack
    # prompt longer than seq_len gets truncated to fit, not crash
    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    try:
        long_prompt = "hello " * 100  # way over seq_len=64
        req = sched.submit(Request(prompt=long_prompt, max_tokens=4, temperature=0.0))
        text = req.future.result(timeout=120)
        assert isinstance(text, str)
    finally:
        sched.stop()


def test_finish_reason_length_and_stop(stack):
    config, engine, tok, _ = stack
    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    try:
        req = sched.submit(Request(prompt="hello", max_tokens=3, temperature=0.0))
        req.future.result(timeout=60)
        assert req.finish_reason in ("length", "stop")
        assert req.finish_reason == "length" or len(req.generated_tokens) < 3
    finally:
        sched.stop()


def test_request_cancellation_frees_lane(stack):
    config, engine, tok, _ = stack
    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    try:
        req = sched.submit(Request(prompt="hello world", max_tokens=50, temperature=0.0))
        # let it start generating, then cancel
        while req.state.name != "GENERATING" and not req.future.done():
            time.sleep(0.01)
        req.cancel()
        req.future.result(timeout=60)
        assert req.finish_reason == "cancelled"
        assert len(req.generated_tokens) < 50
        # the lane must be reusable afterwards
        req2 = sched.submit(Request(prompt="hello", max_tokens=2, temperature=0.0))
        assert isinstance(req2.future.result(timeout=60), str)
    finally:
        sched.stop()


def test_stop_resolves_inflight_futures(stack):
    """Shutdown mid-generation must resolve futures, not hang clients."""
    config, engine, tok, _ = stack
    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    req = sched.submit(Request(prompt="hello world", max_tokens=1000, temperature=0.0))
    while req.state.name != "GENERATING" and not req.future.done():
        time.sleep(0.01)
    sched.stop()
    # future resolves (with partial text), no hang
    assert isinstance(req.future.result(timeout=10), str)
    assert req.finish_reason == "cancelled"


def test_empty_prompt_fails_cleanly(stack):
    config, engine, tok, _ = stack
    sched = ContinuousBatchingScheduler(engine, tok)
    sched.start()
    try:
        req = sched.submit(Request(prompt="", max_tokens=4, add_bos=False, temperature=0.0))
        with pytest.raises(Exception) as e:
            req.future.result(timeout=30)
        assert "empty prompt" in str(e.value) or "at least one token" in str(e.value)
    finally:
        sched.stop()


@pytest.mark.slow  # tier-2: heavy; a faster sibling keeps this class covered in tier-1 (see pyproject markers)
def test_warmup_engine_compiles_without_polluting_stats(tiny_model):
    """warmup_engine pre-compiles every serving program (prefill buckets,
    decode, spec verify) and restores the stats counters, so a warmed
    engine reports zero steps until real traffic arrives."""
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.formats import load_model_header
    from distributed_llama_multiusers_tpu.models import load_params_from_m
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.runtime.engine import warmup_engine

    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    engine = InferenceEngine(config, params, n_lanes=2, prefill_buckets=(4, 8))
    warmup_engine(engine)
    assert engine.stats.decode_steps == 0
    assert engine.stats.prefill_tokens == 0
    assert engine.stats.spec_steps == 0
    # the warmed programs still serve real traffic correctly
    _, greedy, pos = engine.prefill(0, [5, 9, 3])
    assert pos == 3
    import numpy as np

    _, g2, _ = engine.decode(np.zeros(2, np.int32), np.full(2, pos, np.int32))
    assert g2.shape == (2,)
    assert engine.stats.decode_steps == 1


def test_stats_reset_zeroes_spec_counters():
    """reset() must clear the speculation counters with the rest of the
    window (round-4 advisor finding: delta consumers saw stale totals)."""
    from distributed_llama_multiusers_tpu.runtime.engine import EngineStats

    s = EngineStats()
    s.decode_steps = 5
    s.spec_steps = 3
    s.spec_emitted = 9
    s.sync_bytes_per_decode = 1024  # program property: survives reset
    snap = s.reset()
    assert (snap.spec_steps, snap.spec_emitted) == (3, 9)
    assert (s.spec_steps, s.spec_emitted, s.decode_steps) == (0, 0, 0)
    assert s.sync_bytes_per_decode == 1024


def test_f8_kv_cache_quarter_footprint(tiny_model):
    """--kv-dtype f8: float8_e4m3 KV storage is a pure dtype change (the
    cache stays a plain [L,B,S,K,H] pair, dequant fuses into the attention
    reads) at a quarter of the f32 footprint — double the lanes or context
    per chip. Writes saturate at the f8 finite max instead of NaN-ing.
    Greedy decode must stay finite and close to the f32-KV stream."""
    import jax.numpy as jnp
    import numpy as np

    from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
    from distributed_llama_multiusers_tpu.models.loader import load_params_from_m
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.utils.testing import greedy_rollout

    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    prompt = [5, 9, 3, 17]

    def run(dtype):
        engine = InferenceEngine(
            config, params, n_lanes=2, prefill_buckets=(4,),
            cache_dtype=dtype,
        )
        toks, _ = greedy_rollout(engine, prompt, 16)
        logits, _, _ = engine.prefill(0, prompt)
        return engine, toks, np.asarray(logits)

    e8, toks8, logits8 = run(jnp.float8_e4m3fn)
    e32, toks32, logits32 = run(jnp.float32)
    assert e8.cache.k.dtype == jnp.float8_e4m3fn
    assert e8.cache.k.nbytes * 4 == e32.cache.k.nbytes
    assert np.all(np.isfinite(logits8))
    # f8 KV noise perturbs attention, not the weights: logits stay close
    np.testing.assert_allclose(logits8, logits32, atol=0.5, rtol=0.1)
    assert len(toks8) == len(toks32) == 16


def test_f8_kv_cache_on_mesh_compiles_and_decodes(tiny_model):
    """f8 KV + GSPMD mesh: the cache dtype change must compose with the
    sharded serving programs (tp-sharded KV heads, replicated outputs)."""
    import jax.numpy as jnp
    import numpy as np

    from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
    from distributed_llama_multiusers_tpu.models.loader import load_params_from_m
    from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.utils.testing import greedy_rollout

    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    mesh = make_mesh(MeshPlan(tp=2))
    engine = InferenceEngine(
        config, shard_params(params, mesh), n_lanes=2, prefill_buckets=(4,),
        mesh=mesh, replicate_outputs=True, cache_dtype=jnp.float8_e4m3fn,
    )
    toks, _ = greedy_rollout(engine, [5, 9, 3], 8)
    assert len(toks) == 8 and all(0 <= t < config.vocab_size for t in toks)
    engine.copy_lane(0, 1)  # prefix-cache copy composes with f8 + mesh
    logits, greedy, _ = engine.prefill(1, [7], start_pos=3)
    assert np.all(np.isfinite(np.asarray(logits)))
