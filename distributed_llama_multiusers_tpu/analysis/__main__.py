from .cli import main

raise SystemExit(main())
