"""Matmul dispatch: dense arrays or PackedQ40 weights, Pallas or XLA path.

The reference routes every matmul through a per-(op, quant-signature) kernel
registry (getCpuOpForward, src/nn/nn-cpu-ops.cpp:1315-1361); here the same
seam is a single function — ``matmul(x, w)`` — that picks the dequant-in-VMEM
Pallas kernel for quantized weights on TPU and a fused XLA fallback
elsewhere (CPU tests, interpret mode).
"""

from __future__ import annotations

import os
from functools import lru_cache

import jax
import jax.numpy as jnp

from ..quants.packed import PackedQ40, q40_matmul_xla

# The kernel carries its own GSPMD partitioning rule
# (ops/pallas_q40.q40_matmul_partitioned), so it stays on under meshes:
# row-sliced shards run it locally, col-sliced shards psum the partials.
_pallas_enabled = True

# Test hook: route PackedQ40 matmuls through the partitioned Pallas path in
# interpret mode even off-TPU, so CPU meshes exercise kernel + partitioning.
_pallas_interpret = False

# Compute dtype of the Pallas Q40 dot (dequantized weight planes AND the
# x operand). None -> kernel default: bf16 on TPU (single-pass MXU, the
# reference's Q80-activation precision class), exact f32 under interpret/
# CPU tests. Explicit jnp.float32 restores ~f32-accurate multi-pass MXU
# dots on TPU (the bench ablation knob).
_pallas_w_dtype = None

# Operand sharing (ops/pallas_q40.Q80Acts): llama_forward builds the
# activation-quant/relayout operands once per distinct input and feeds
# every matmul sharing it. Off switch for A/B and bisection only — the
# shared and per-call bundles are the same traced graph.
_shared_acts_enabled = os.environ.get("DLLAMA_SHARED_ACTS", "on") != "off"


def set_pallas_enabled(enabled: bool) -> None:
    global _pallas_enabled
    _pallas_enabled = enabled


def set_shared_acts(enabled: bool) -> None:
    global _shared_acts_enabled
    _shared_acts_enabled = enabled


def shared_acts_enabled() -> bool:
    return _shared_acts_enabled


def set_pallas_interpret(enabled: bool) -> None:
    global _pallas_interpret
    _pallas_interpret = enabled


def set_pallas_w_dtype(dtype) -> None:
    """dtype of dequantized weight tiles in VMEM (None -> exact f32)."""
    global _pallas_w_dtype
    _pallas_w_dtype = dtype


@lru_cache(maxsize=1)
def _pallas_q40_matmul():
    """The Pallas kernel entry, or None off-TPU / when disabled."""
    if os.environ.get("DLLAMA_NO_PALLAS") == "1":
        return None
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except RuntimeError:  # no backend at all (e.g. misconfigured platform)
        return None
    if not on_tpu:
        return None
    try:
        from .pallas_q40 import q40_matmul_pallas
    except ImportError as e:
        import warnings

        warnings.warn(f"Pallas Q40 kernel unavailable, using XLA fallback: {e}")
        return None
    return q40_matmul_pallas


def pallas_kernel_active() -> bool:
    """Whether PackedQ40 matmuls currently route to the Pallas kernel."""
    return _pallas_enabled and (_pallas_interpret or _pallas_q40_matmul() is not None)


def shared_q80_acts(x: jnp.ndarray):
    """Build the shared Q80/relayout operand bundle for ``x``, or return x
    unchanged when sharing cannot engage (kernel off, sharing disabled, or
    a d_in that does not cover whole quant blocks). Callers pass the
    result to ``matmul`` exactly like a raw activation."""
    if not (_shared_acts_enabled and pallas_kernel_active()):
        return x
    if x.shape[-1] % 32 != 0:
        return x
    try:
        from .pallas_q40 import make_q80_acts
    except ImportError:
        return x
    return make_q80_acts(x, shared=True)


def _raw_x(x):
    """Unwrap a Q80Acts bundle to its original activation for every
    non-kernel path (dense weights, XLA fallback)."""
    try:
        from .pallas_q40 import Q80Acts
    except ImportError:
        return x
    return x.x if isinstance(x, Q80Acts) else x


def matmul(x, w) -> jnp.ndarray:
    """y = x @ w for dense [.., d_in, d_out] arrays or PackedQ40 weights.
    ``x`` may be a Q80Acts bundle from ``shared_q80_acts``: the Pallas
    path consumes the prebuilt operands directly; every other path falls
    back to the bundle's original activation."""
    if isinstance(w, PackedQ40):
        if w.packed.ndim == 2 and pallas_kernel_active():
            from .pallas_q40 import (
                Q80Acts,
                pallas_supports,
                q40_matmul_pallas,
                q40_matmul_partitioned,
            )

            kw = {} if _pallas_w_dtype is None else {"w_dtype": _pallas_w_dtype}
            if isinstance(x, Q80Acts):
                # prebuilt operands skip the GSPMD wrapper: sharing is the
                # single-chip (mesh-free) fast path, and the bundle's
                # layouts are unsharded by construction
                if pallas_supports(w) and x.d_in == w.d_in:
                    return q40_matmul_pallas(
                        x, w, interpret=_pallas_interpret, **kw
                    )
                x = x.x
            return q40_matmul_partitioned(x, w, interpret=_pallas_interpret, **kw)
        return q40_matmul_xla(_raw_x(x), w)
    return _raw_x(x) @ w


def q40_matmul_local(x: jnp.ndarray, w: PackedQ40) -> jnp.ndarray:
    """y = x @ dequant(w) on ALREADY-LOCAL shard shapes — for use inside
    shard_map regions, where operands are per-device and the GSPMD
    custom_partitioning wrapper must not re-partition. Pallas when the local
    shapes fit, fused XLA dequant otherwise."""
    if w.packed.ndim == 2 and pallas_kernel_active():
        from .pallas_q40 import pallas_supports, q40_matmul_pallas

        # pallas_supports gates BOTH modes: interpret runs must not reach
        # the kernel with shapes the tiling planner rejects
        if pallas_supports(w):
            kw = {} if _pallas_w_dtype is None else {"w_dtype": _pallas_w_dtype}
            return q40_matmul_pallas(x, w, interpret=_pallas_interpret, **kw)
    return q40_matmul_xla(x, w)
