"""dlint CLI: ``python -m distributed_llama_multiusers_tpu.analysis``.

Exit status 0 = clean (after waivers + baseline), 1 = findings, 2 = usage
error. Pure stdlib — runs before any jax/numpy import is possible, so
``make lint`` is the cheap first gate in front of ``make verify``.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .core import Analyzer, iter_py_files, load_baseline, write_baseline
from .formats import render_github, render_sarif, render_text
from .lockgraph import scan_paths
from .registry import default_checkers

PACKAGE_ROOT = Path(__file__).resolve().parent.parent  # the package dir
REPO_ROOT = PACKAGE_ROOT.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.txt"


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="dlint",
        description=(
            "Project-invariant static analysis: cross-file lock-order "
            "graph, blocking-under-lock, guarded-attr atomicity, "
            "pod-broadcast pairing, lock discipline, host-sync transfers, "
            "clock hygiene, condvar/thread hygiene, sharding axis names. "
            "See docs/LINT.md."
        ),
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to analyze (default: the package itself)",
    )
    ap.add_argument(
        "--baseline", default=str(DEFAULT_BASELINE), metavar="FILE",
        help="baseline file of accepted pre-existing findings "
        "(default: %(default)s)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="report baselined findings too",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="accept every current baselinable finding into the baseline "
        "file (waiver-syntax/parse findings cannot be baselined: they are "
        "reported and keep the exit status at 1 until fixed)",
    )
    ap.add_argument(
        "--list-checks", action="store_true", help="list checks and exit"
    )
    ap.add_argument(
        "--format", choices=("text", "github", "sarif"), default="text",
        help="finding output format: plain file:line text (default), "
        "GitHub Actions ::error annotations, or SARIF 2.1.0 JSON "
        "(`make lint` picks github when GITHUB_ACTIONS=true)",
    )
    ap.add_argument(
        "--graph", action="store_true",
        help="dump the computed lock-order graph (DOT) and exit — nodes "
        "are class-qualified locks, edges are 'held while acquiring' "
        "sites, waived edges dashed; reviewers of new lock code eyeball "
        "the new edges here",
    )
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    checkers = default_checkers()
    if args.list_checks:
        for c in checkers:
            print(f"{c.name:14s} {c.description}")
        print(f"{'waiver':14s} waiver syntax: reasons mandatory, names known")
        return 0
    paths = [Path(p) for p in args.paths] or [PACKAGE_ROOT]
    for p in paths:
        if not p.exists():
            print(f"dlint: no such path: {p}", file=sys.stderr)
            return 2
    analyzer = Analyzer(checkers)
    if args.graph:
        model = scan_paths(paths, valid_checks=analyzer.valid_checks)
        model.ensure_semantics()
        print(model.dot())
        return 0
    baseline = (
        set() if (args.no_baseline or args.write_baseline)
        else load_baseline(args.baseline)
    )
    findings = analyzer.run(paths, baseline=baseline, root=REPO_ROOT)
    if args.write_baseline:
        # waiver/parse findings are never baseline-filtered by the analyzer,
        # so writing their keys would only accumulate dead entries while the
        # gate keeps failing — report them instead
        baselinable = [f for f in findings if f.check not in ("waiver", "parse")]
        unfixable = [f for f in findings if f.check in ("waiver", "parse")]
        write_baseline(args.baseline, baselinable)
        print(f"dlint: wrote {len(baselinable)} finding(s) to {args.baseline}")
        for f in unfixable:
            print(f.render())
        if unfixable:
            print(
                f"dlint: {len(unfixable)} waiver/parse finding(s) cannot be "
                "baselined — fix them"
            )
            return 1
        return 0
    if args.format == "github":
        lines = render_github(findings)
    elif args.format == "sarif":
        lines = render_sarif(findings, checkers)
    else:
        lines = render_text(findings)
    for line in lines:
        print(line)
    n_files = len(iter_py_files(paths))
    if findings:
        if args.format == "text":
            print(f"dlint: {len(findings)} finding(s) in {n_files} file(s)")
        return 1
    if args.format == "text":
        print(f"dlint: clean ({n_files} file(s))")
    return 0
