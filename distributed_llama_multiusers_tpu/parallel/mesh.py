"""Device-mesh construction and validity checks.

The reference distributes over 2^n TCP "nodes" in a flat ring and checks
nNodes <= nKvHeads before starting (src/app.cpp:237-240, README.md:44-46).
Here a node is a TPU chip in a `jax.sharding.Mesh` with named axes:

    dp — data/batch (request lanes)         [reference: none — single replica]
    tp — tensor parallel (heads / ffn dim)  [reference: the core strategy]
    sp — sequence parallel (KV cache S)     [reference: absent, §5.7]
    ep — expert parallel (MoE experts)      [reference: header fields only, §2.4]
    pp — pipeline parallel (layer stages)   [reference: explicitly absent, §2.4]

All collectives ride ICI via GSPMD; the bootstrap/config/weight-shipping
protocol of nn-network.cpp collapses into device_put with shardings.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax
from jax.sharding import Mesh

from ..models.config import LlamaConfig

AXES = ("dp", "pp", "tp", "sp", "ep")


@dataclass(frozen=True)
class MeshPlan:
    dp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.tp * self.sp * self.ep * self.pp


def make_mesh(plan: MeshPlan | None = None, devices=None) -> Mesh:
    """Build a Mesh with axes (dp, pp, tp, sp, ep). With no plan, all devices
    go to tp (the reference's pure-TP layout)."""
    if devices is None:
        devices = jax.devices()
    if plan is None:
        plan = MeshPlan(tp=len(devices))
    if plan.n_devices > len(devices):
        raise ValueError(f"mesh plan needs {plan.n_devices} devices, have {len(devices)}")
    devs = np.asarray(devices[: plan.n_devices]).reshape(
        plan.dp, plan.pp, plan.tp, plan.sp, plan.ep
    )
    return Mesh(devs, AXES)


def validate_mesh_for_config(config: LlamaConfig, plan: MeshPlan) -> None:
    """TP validity rules carried over from the reference (src/app.cpp:237,
    slicer asserts nn-core.cpp:198-266) plus SP divisibility."""
    tp, sp = plan.tp, plan.sp
    if tp > config.n_kv_heads:
        raise ValueError(f"tp={tp} exceeds n_kv_heads={config.n_kv_heads}")
    if config.n_kv_heads % tp != 0:
        raise ValueError(f"n_kv_heads={config.n_kv_heads} not divisible by tp={tp}")
    if config.n_heads % tp != 0:
        raise ValueError(f"n_heads={config.n_heads} not divisible by tp={tp}")
    if config.dim % tp != 0 or config.hidden_dim % tp != 0:
        raise ValueError("dim/hidden_dim not divisible by tp")
    if config.vocab_size % tp != 0:
        raise ValueError("vocab_size not divisible by tp")
    if config.seq_len % sp != 0:
        raise ValueError(f"seq_len={config.seq_len} not divisible by sp={sp}")
    if plan.pp > 1 and config.n_layers % plan.pp != 0:
        raise ValueError(f"n_layers={config.n_layers} not divisible by pp={plan.pp}")
    if plan.ep > 1:
        if config.n_experts <= 0:
            raise ValueError(f"ep={plan.ep} needs an MoE model (n_experts > 0)")
        if config.n_experts % plan.ep != 0:
            raise ValueError(
                f"n_experts={config.n_experts} not divisible by ep={plan.ep}"
            )
