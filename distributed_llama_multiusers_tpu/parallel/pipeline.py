"""Pipeline parallelism: GPipe-style microbatch schedule over the pp axis.

The reference explicitly has NO pipeline parallelism — its paper contrasts
the TP design with Petals/llama.cpp-MPI layer splitting (SURVEY.md §2.4) —
so this is a capability extension, built TPU-first:

- Layer-stacked params shard their leading [n_layers] axis over ``pp``
  (each device owns n_layers/pp consecutive layers).
- The batch splits into M microbatches; over M + pp - 1 ticks, stage d
  processes microbatch s - d while activations hop stage-to-stage via
  lax.ppermute — compute on different stages overlaps across microbatches.
- shard_map is manual over pp ONLY (``axis_names={"pp"}``): dp/tp/ep stay
  GSPMD-auto inside each stage, so pipeline composes with tensor and expert
  parallelism without hand-written collectives. (sp ring attention does not
  nest inside the pipeline — shard_map in shard_map — so stages use dense
  attention; pp+sp is validated as separate meshes, see __graft_entry__.)

Embedding and the final norm/logits run outside the pipeline under plain
GSPMD; only the layer stack is staged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..models.config import LlamaConfig
from ..models.llama import LlamaParams, train_layer_step_fn
from ..ops.linear import matmul
from ..ops.norm import rms_norm


def pipeline_forward_train(
    config: LlamaConfig,
    params: LlamaParams,
    tokens: jnp.ndarray,  # [B, T] int32
    mesh: Mesh,
    n_microbatches: int | None = None,
) -> jnp.ndarray:
    """Causal full-sequence forward with the layer stack pipelined over pp.
    Returns logits [B, T, vocab] f32; matches llama_forward_train exactly."""
    n_pp = mesh.shape["pp"]
    b, t = tokens.shape
    if n_pp <= 1:
        from ..models.llama import llama_forward_train

        return llama_forward_train(config, params, tokens, mesh=mesh)
    m = n_microbatches or n_pp
    if b % m != 0:
        raise ValueError(f"batch {b} not divisible into {m} microbatches")
    if config.n_layers % n_pp != 0:
        raise ValueError(f"n_layers={config.n_layers} not divisible by pp={n_pp}")
    mb = b // m

    x = params.embedding[tokens]  # [B, T, dim] — plain GSPMD
    xmb = x.reshape(m, mb, t, x.shape[-1])
    layer_step = train_layer_step_fn(config, params.rope_cos, params.rope_sin)

    def inner(layers_local, xall):
        d = jax.lax.axis_index("pp")
        is_first = d == 0
        is_last = d == n_pp - 1

        def stage(xin):
            return jax.lax.scan(layer_step, xin, layers_local)[0]

        state = jnp.zeros_like(xall[0])
        outs = jnp.zeros_like(xall)
        # M + pp - 1 ticks: stage d works on microbatch s - d at tick s
        for s in range(m + n_pp - 1):
            inject = xall[min(s, m - 1)]
            state_in = jnp.where(is_first, jnp.where(s < m, 1.0, 0.0) * inject, state)
            y = stage(state_in)
            out_idx = s - (n_pp - 1)
            if 0 <= out_idx < m:
                outs = outs.at[out_idx].set(jnp.where(is_last, y, outs[out_idx]))
            state = jax.lax.ppermute(
                y, "pp", [(i, i + 1) for i in range(n_pp - 1)]
            )
        # replicate the last stage's result over pp
        return jax.lax.psum(jnp.where(is_last, outs, 0.0), "pp")

    layer_specs = jax.tree.map(lambda _: P("pp"), params.layers)
    out = shard_map(
        inner,
        mesh=mesh,
        in_specs=(layer_specs, P()),
        out_specs=P(),
        axis_names={"pp"},
        check_vma=False,
    )(params.layers, xmb)

    x = out.reshape(b, t, -1)
    y = rms_norm(x, params.rms_final, config.norm_epsilon)
    return matmul(y, params.wcls).astype(jnp.float32)
