"""Numerics parity: the EXACT on-device top-p sampler vs the host
``Sampler`` (tokenizer/sampler.py) over a seeded (temperature, top_p) grid.

PINNED NUMERICS CLASS (the contract this file enforces):

- SUPPORT-EXACT: the device sampler's nucleus — full-vocab descending
  sort, cumulative sum, keep while (csum - p) < top_p including the
  crossing token — equals the host Sampler's exact nucleus for every
  (temp, topp) in the grid, including topp <= 0 / >= 1 (both samplers
  define those as full-vocab multinomial) and the old HOST_EXACT_TOPP /
  HOST_EXACT_TEMP routing boundaries, which no longer route anywhere:
  every draw from either sampler lands inside that set.
- DISTRIBUTION: probabilities are the same f32 softmax on both sides;
  empirical frequencies agree with the analytic distribution (loose
  total-variation bound — this is a smoke bound, not a statistical
  proof).
- RNG STREAMS DIFFER BY CONSTRUCTION: fold_in(seed, pos) + categorical
  on device vs xorshift64* on host — token-for-token equality between
  the two samplers is NOT part of the class and is never asserted.
  What IS asserted: the device draw is deterministic per (seed, pos),
  so seeded serving runs reproduce, and the device sampler equals
  itself across the sync/pipelined scheduler paths (pinned by the
  stream-identity tests in test_pipelined_decode.py /
  test_spec_pipelined.py).
"""

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats import load_model_header
from distributed_llama_multiusers_tpu.models import load_params_from_m
from distributed_llama_multiusers_tpu.runtime import InferenceEngine
from distributed_llama_multiusers_tpu.runtime.scheduler import (
    HOST_EXACT_TEMP,
    HOST_EXACT_TOPP,
)
from distributed_llama_multiusers_tpu.tokenizer.sampler import Sampler


@pytest.fixture(scope="module")
def engine(tiny_model):
    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h,
                                        dtype=jnp.float32)
    return InferenceEngine(config, params, n_lanes=1, prefill_buckets=(4,))


def _logits(vocab, seed=11):
    rng = np.random.default_rng(seed)
    # well-separated values: no nucleus-boundary ties for f32-vs-f64
    # cumsum order to disagree on (the documented edge of the class)
    return rng.permutation(np.linspace(-4.0, 4.0, vocab)).astype(np.float32)


def _host_nucleus(logits, temp, topp):
    """The host Sampler's exact kept set (src/tokenizer.cpp:416-457
    semantics): softmax, stable sort desc, keep through the first token
    whose cumulative crosses topp; topp <= 0 / >= 1 keep everything."""
    x = logits.astype(np.float32) / np.float32(temp)
    x = x - x.max()
    p = np.exp(x, dtype=np.float32)
    p /= p.sum(dtype=np.float32)
    if topp <= 0 or topp >= 1:
        return set(np.nonzero(p > 0)[0].tolist()), p
    order = np.argsort(-p, kind="stable")
    csum = np.cumsum(p[order], dtype=np.float64)
    over = np.nonzero(csum > topp)[0]
    last = int(over[0]) if len(over) else len(order) - 1
    return set(order[: last + 1].tolist()), p


GRID = [
    (0.2, 0.3),
    (0.7, 0.9),
    (0.8, 1.0),          # wide nucleus: full multinomial
    (0.8, 0.0),          # topp <= 0: both samplers define as full-vocab
    (0.8, -0.5),         # negative topp: same rule
    (0.8, HOST_EXACT_TOPP),   # the old host-exact routing boundary
    (HOST_EXACT_TEMP, 0.9),   # the old high-temp routing boundary
    (2.0, 0.5),
]


def test_device_draws_stay_in_exact_nucleus(engine):
    """Every device draw lands in the host Sampler's exact nucleus, for
    every grid point — the support-exactness half of the pinned class
    (the old top-k sampler violated this for wide nuclei, which is why
    host-exact routing existed)."""
    vocab = engine.config.vocab_size
    logits = _logits(vocab)
    for temp, topp in GRID:
        nucleus, _ = _host_nucleus(logits, temp, topp)
        draws = {
            engine.sample_token(logits, temp, topp, seed, pos)
            for seed in (1, 2, 3, 4, 5)
            for pos in range(10)
        }
        assert draws <= nucleus, (
            f"device draw outside the exact nucleus at temp={temp}, "
            f"topp={topp}: {sorted(draws - nucleus)}"
        )


def test_host_draws_stay_in_same_nucleus(engine):
    """The host Sampler's own draws land in the same analytic nucleus —
    i.e. the set both samplers are being held to IS the host's."""
    vocab = engine.config.vocab_size
    logits = _logits(vocab)
    for temp, topp in GRID:
        nucleus, _ = _host_nucleus(logits, temp, topp)
        s = Sampler(vocab, temp, topp, 42)
        draws = {s.sample(logits) for _ in range(50)}
        assert draws <= nucleus, (temp, topp, sorted(draws - nucleus))


def test_device_sampler_deterministic_per_seed_pos(engine):
    """Same (seed, pos) -> same token; different pos -> a fresh draw from
    the same stream (fold_in). Seeded serving runs reproduce."""
    logits = _logits(engine.config.vocab_size)
    a = [engine.sample_token(logits, 0.9, 0.95, 123, p) for p in range(20)]
    b = [engine.sample_token(logits, 0.9, 0.95, 123, p) for p in range(20)]
    assert a == b
    assert len(set(a)) > 1  # the position folds into the stream


def test_device_temp0_equals_host_greedy(engine):
    """temp == 0 is argmax on both sides — bit-equal, no RNG involved."""
    logits = _logits(engine.config.vocab_size)
    host = Sampler(engine.config.vocab_size, 0.0, 0.9, 7)
    assert engine.sample_token(logits, 0.0, 0.9, 7, 0) == host.sample(logits)


def test_device_frequencies_match_analytic_distribution(engine):
    """Distributional half of the pinned class: empirical device
    frequencies track the analytic f32-softmax nucleus distribution
    (loose total-variation smoke bound over a narrow nucleus, where a
    truncated sampler would be visibly wrong)."""
    vocab = engine.config.vocab_size
    logits = _logits(vocab)
    temp, topp = 0.7, 0.9
    nucleus, p = _host_nucleus(logits, temp, topp)
    keep = np.zeros(vocab)
    keep[list(nucleus)] = 1
    q = p * keep
    q /= q.sum()
    n = 1200
    counts = np.zeros(vocab)
    for seed in range(n):
        counts[engine.sample_token(logits, temp, topp, seed, seed % 7)] += 1
    emp = counts / n
    tv = 0.5 * np.abs(emp - q).sum()
    assert tv < 0.12, f"total variation {tv:.3f} vs analytic nucleus dist"


def test_wide_nucleus_tail_actually_reachable(engine):
    """The regression the exact sampler fixes: at topp=1.0 every token
    with meaningful mass is reachable — including tokens far past any
    fixed top-k cutoff. (With vocab > 64 = the old device_topk default,
    the truncated sampler could never emit rank-65+.)"""
    vocab = engine.config.vocab_size
    assert vocab > 64, "tiny model vocab must exceed the old top-k"
    # near-flat logits at high temp: substantial mass beyond rank 64
    logits = _logits(vocab)
    ranks = np.argsort(-logits)
    tail = set(ranks[64:].tolist())
    hit_tail = any(
        engine.sample_token(logits, 2.0, 1.0, seed, 0) in tail
        for seed in range(200)
    )
    assert hit_tail, "no draw ever reached past the old top-64 truncation"
