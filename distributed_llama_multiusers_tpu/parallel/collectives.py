"""Quantization-compressed collectives.

The reference cuts TP sync bandwidth ~4x by shipping Q80 (int8 + fp16 block
scale) instead of f32 over its TCP mesh (ZQ pipe, src/llm.cpp:150,
src/nn/nn-network.cpp:537-569). On ICI bandwidth is rarely the bottleneck,
but the same trick applies on DCN-spanning meshes — so the framework offers
an int8-compressed all-gather built from shard_map primitives.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..jax_compat import shard_map

from ..quants.jax_codec import Q80_BLOCK, q80_decode_blocks, q80_encode_blocks


def _gather_q80(local: jnp.ndarray, axis: str, n_shards: int) -> jnp.ndarray:
    """Shard-local half of the quantized gather: Q80-encode the owned slice,
    all_gather int8 values + f16 scales, decode, and concatenate the device
    slices along the last dim. Shared wire format of ``q80_all_gather`` and
    ``q80_sync_matmul``. Must run inside shard_map."""
    # converter-mode rounding (ties-to-even vectorizes as one jnp.round)
    q, s = q80_encode_blocks(local, mode="converter")
    qg = jax.lax.all_gather(q, axis, axis=0)  # [n, ..., blk, 32]
    sg = jax.lax.all_gather(s, axis, axis=0)
    full = q80_decode_blocks(qg, sg, (n_shards,) + local.shape)
    return jnp.concatenate([full[i] for i in range(n_shards)], axis=-1)


def q80_sync_supported(dim: int, tp: int) -> bool:
    """Whether a tp-sharded output of width ``dim`` can ship as Q80: each
    device slice must be whole 32-value blocks (for both the wire blocks and
    the packed/scale plane shard divisibility)."""
    return tp > 1 and dim % (Q80_BLOCK * tp) == 0


def q80_sync_engages(config, mesh_shape: dict) -> bool:
    """Single source of truth for whether the Q80 sync transport engages —
    used by both llama_forward (the compiled program) and the CLI startup
    log, so what is announced is what runs. Requires:

    - a PURE-TP mesh: the sync shard_map replicates its activations over
      every non-tp axis, so dp/sp/ep/pp > 1 would add per-layer gathers
      costing more than the f32 all-reduce saves (the reference's mesh is
      pure TP too, src/app.cpp:237-240);
    - whole Q80 blocks per tp shard of every synced output (wo -> dim;
      the dense-FFN w2 additionally needs hidden-sharded planes; MoE FFNs
      never route w2 through the wire sync)."""
    tp = mesh_shape.get("tp", 1)
    if tp <= 1:
        return False
    if any(mesh_shape.get(ax, 1) > 1 for ax in ("dp", "sp", "ep", "pp")):
        return False
    return q80_sync_supported(config.dim, tp) and (
        config.n_experts > 0 or q80_sync_supported(config.hidden_dim, tp)
    )


def q80_all_gather(x: jnp.ndarray, mesh: Mesh, axis: str = "tp") -> jnp.ndarray:
    """All-gather x's last dim across ``axis``, shipping int8+fp16 scales.

    x: sharded on its last axis over ``axis`` (each device holds its slice).
    Returns the full array, replicated over ``axis``; payload on the wire is
    ~25% of the f32 equivalent (34 bytes per 32 values, SURVEY.md §5.8).
    """
    n_axis_dims = x.ndim
    n_shards = mesh.shape[axis]
    if x.shape[-1] % (Q80_BLOCK * n_shards) != 0:
        raise ValueError(
            f"q80_all_gather needs last dim ({x.shape[-1]}) divisible by "
            f"{Q80_BLOCK} * mesh.shape[{axis!r}] ({n_shards}) so each device "
            f"slice is whole Q80 blocks"
        )

    def inner(local):
        return _gather_q80(local, axis, n_shards)

    in_spec = P(*([None] * (n_axis_dims - 1) + [axis]))
    out_spec = P(*([None] * n_axis_dims))
    return shard_map(
        inner, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec, check_vma=False
    )(x)


def q80_sync_matmul(x: jnp.ndarray, w, mesh: Mesh, axis: str = "tp") -> jnp.ndarray:
    """Row-parallel matmul whose TP sync ships Q80 instead of f32 — the
    serving wire-up of the reference's default transport (its wo/w2 outputs
    cross the node mesh as int8+scale, ZQ pipe src/llm.cpp:150,
    nn-network.cpp:537-569). GSPMD's plain all-reduce becomes:

        local partial matmul -> psum_scatter (f32, 1/tp of the payload)
        -> Q80-encode the owned slice -> all_gather int8+f16 -> decode

    Per-chip bytes drop from ~2N (ring all-reduce) to ~N + N/4. The gather
    half's quantization applies the same block-rounding the reference's
    transport does, so outputs match the f32 path within Q80 tolerance.

    x: [..., d_in] sharded over ``axis`` on its last dim; w: [d_in, d_out]
    (dense or PackedQ40) sharded over ``axis`` on d_in. Returns [..., d_out]
    replicated over ``axis``; needs d_out % (32 * mesh.shape[axis]) == 0.
    """
    from ..ops.linear import q40_matmul_local
    from ..quants.packed import PackedQ40

    n_shards = mesh.shape[axis]
    packed = isinstance(w, PackedQ40)
    d_out = w.d_out if packed else w.shape[-1]
    if d_out % (Q80_BLOCK * n_shards) != 0:
        raise ValueError(
            f"q80_sync_matmul needs d_out ({d_out}) divisible by "
            f"{Q80_BLOCK} * mesh.shape[{axis!r}] ({n_shards})"
        )
    nd = x.ndim

    def inner(xl, *wl):
        if packed:
            part = q40_matmul_local(xl, PackedQ40(*wl))
        else:
            part = xl @ wl[0]
        scat = jax.lax.psum_scatter(
            part, axis, scatter_dimension=nd - 1, tiled=True
        )  # [..., d_out / n] f32 — the reduce half stays full precision
        return _gather_q80(scat, axis, n_shards).astype(part.dtype)

    x_spec = P(*([None] * (nd - 1) + [axis]))
    w_specs = (
        (P(axis, None), P(axis, None)) if packed else (P(axis, None),)
    )
    w_args = (w.packed, w.scales) if packed else (w,)
    return shard_map(
        inner,
        mesh=mesh,
        in_specs=(x_spec,) + w_specs,
        out_specs=P(*([None] * nd)),
        check_vma=False,
    )(x, *w_args)
