"""Live session migration, router side: ticket fetch, inject, reattach.

The client half of journal-based migration (PR 10's deterministic replay
as a fleet primitive). The replica side lives in server/http.py:
``GET /admin/session/<id>`` exports a live session's admit wire record
(prompt tokens + RESOLVED seed + params + consumed-token watermark) and
``POST /admin/migrate`` feeds one into ``scheduler.build_recovered_request``
through normal breaker-gated admission. This module is what the router
does with those two endpoints:

1. **ticket** — at stream start the router fetches the session's export
   from the source replica and CACHES it. That is what makes replica
   DEATH migratable, not just graceful drains: when the source vanishes
   mid-stream there is nobody left to export from, but the ticket is
   already in hand.
2. **inject** — on a mid-stream break (socket died, typed shed chunk,
   drain force-cancel) the router posts the ticket to another replica,
   which regenerates the stream byte-identically from the same prompt
   tokens and the same resolved seed (the determinism class
   tests/test_sampler_parity.py pins).
3. **reattach** — ``GET /v1/stream/<id>`` with ``Last-Event-ID: 0``: the
   target's relay re-buffered the ENTIRE regenerated stream from base=0,
   and the router — which knows exactly how many characters its client
   has received — skips that many characters of the replayed text and
   forwards the rest. Character-level dedup makes the migrated stream
   byte-identical BY CONSTRUCTION, zero lost and zero duplicated, even
   when the source's force-cancel flushed held-back tail text whose
   delta indices no longer line up with the regenerated stream's.

Pure stdlib (http.client); no jax, no numpy.
"""

from __future__ import annotations

import http.client
import json

from ..telemetry.tracectx import TRACE_HEADER

DEFAULT_TIMEOUT_S = 10.0


class MigrationShed(RuntimeError):
    """The migration target shed the inject (breaker open / queue full /
    draining / pool exhausted): carries the typed reason + Retry-After
    hint so the router can honor it and try the next replica."""

    def __init__(self, reason: str, retry_after_s: float, status: int):
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.status = status
        super().__init__(
            f"migration target shed ({reason}, HTTP {status}); "
            f"retry in ~{retry_after_s:.0f}s"
        )


def _request_json(host: str, port: int, method: str, path: str,
                  body: dict | None = None,
                  timeout: float = DEFAULT_TIMEOUT_S,
                  trace: str | None = None):
    """One JSON exchange; returns ``(status, parsed_body, headers)``.
    Raises ``OSError``/``http.client.HTTPException`` on transport
    failure — the caller's signal to mark the replica dead. ``trace``
    (the wire-form context) rides as ``X-DLlama-Trace`` so the admin
    hop itself is attributable to the request's fleet trace."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        payload = None if body is None else json.dumps(body).encode()
        headers = {"Content-Type": "application/json"} if payload else {}
        if trace:
            headers[TRACE_HEADER] = str(trace)
        conn.request(method, path, body=payload, headers=headers)
        resp = conn.getresponse()
        raw = resp.read()
        try:
            parsed = json.loads(raw) if raw else {}
        except ValueError:
            parsed = {}
        return resp.status, parsed, dict(resp.getheaders())
    finally:
        conn.close()


def fetch_ticket(host: str, port: int, request_id: int,
                 timeout: float = DEFAULT_TIMEOUT_S,
                 trace: str | None = None) -> dict | None:
    """Fetch a live session's migration ticket from its source replica.
    ``None`` when the session is unknown/already finished (a completed
    stream needs no ticket)."""
    status, body, _ = _request_json(
        host, port, "GET", f"/admin/session/{int(request_id)}",
        timeout=timeout, trace=trace,
    )
    if status != 200 or "seed" not in body:
        return None
    return body


def inject_session(host: str, port: int, ticket: dict,
                   timeout: float = DEFAULT_TIMEOUT_S,
                   trace: str | None = None) -> dict:
    """Hand a ticket to a migration target (``POST /admin/migrate``).
    Returns the target's answer (``request_id`` — the ORIGINAL id, the
    reattach key — and ``stream_path``). Raises :class:`MigrationShed`
    on a typed 429/503 and ``ValueError`` on a non-retryable refusal
    (bad record / missing resume registry). The ticket's own ``trace``
    field (the admit wire record carries it) is what re-joins the
    REGENERATED stream to the original fleet trace; ``trace`` here only
    attributes the inject hop itself."""
    status, body, headers = _request_json(
        host, port, "POST", "/admin/migrate", body=ticket, timeout=timeout,
        trace=trace,
    )
    if status == 200:
        return body
    if status in (429, 503):
        try:
            retry = float(headers.get("Retry-After", 1.0))
        except (TypeError, ValueError):
            retry = 1.0
        raise MigrationShed(
            str(body.get("reason", "shed")), retry, status
        )
    raise ValueError(
        f"migration target refused (HTTP {status}): "
        f"{body.get('error', 'unknown error')}"
    )


def open_stream(host: str, port: int, request_id: int,
                last_event_id: int = 0,
                timeout: float = DEFAULT_TIMEOUT_S,
                connect_timeout: float = DEFAULT_TIMEOUT_S):
    """Reattach to a migrated (or live) stream: returns the open
    ``(connection, response)`` pair for ``GET /v1/stream/<id>`` — the
    caller pumps the SSE body and must close the connection. Two-phase
    timeout like the router's forwards: a short ``connect_timeout`` (a
    lingering dead listener must fail fast) then the generation-length
    ``timeout`` on the body. Raises ``ValueError`` on a non-200
    (unknown id / expired grace window)."""
    conn = http.client.HTTPConnection(host, port, timeout=connect_timeout)
    try:
        conn.connect()
        conn.sock.settimeout(timeout)
        conn.request(
            "GET", f"/v1/stream/{int(request_id)}",
            headers={"Last-Event-ID": str(int(last_event_id))},
        )
        resp = conn.getresponse()
    except BaseException:
        conn.close()
        raise
    if resp.status != 200:
        body = resp.read()
        conn.close()
        raise ValueError(
            f"stream reattach refused (HTTP {resp.status}): {body[:200]!r}"
        )
    return conn, resp
