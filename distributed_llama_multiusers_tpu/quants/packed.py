"""On-device packed Q40 weights: int4 nibbles + f16 block scales in HBM.

The reference keeps Q40 weights quantized at rest and dequantizes inside the
matmul kernel (src/nn/nn-cpu-ops.cpp:222-440 matmul_Q80_Q40_F32,
src/nn/vulkan/matmul-forward-q80-q40-f32.comp); the bf16 loader path instead
dequantizes on the host and ships 4x the bytes to HBM. Since TPU decode is
HBM-bandwidth-bound, keeping weights at 4 bit + 1/32 f16 scale (~4.5 bits/
element, exactly the .m Q40 footprint) is the main single-chip perf lever.

Device layout — block-local nibble halves, mirroring the .m Q40 block itself
(scale, 16 low-half bytes = inputs [0,16), high nibbles = inputs [16,32);
src/nn/nn-quants.hpp:64-67):

    packed: uint8 [..., d_in//2, d_out]
        row r = (b, j) with b = r // 16, j = r % 16:
        packed[r, o] = (v[32b + j, o] + 8) | ((v[32b + j + 16, o] + 8) << 4)
    scales: float16 [..., d_in//32, d_out]
        scales[b, o] covers input rows i in [32b, 32b+32)

i.e. the weight is stored transposed ([d_in, d_out], ready for y = x @ W)
and each 32-input quant block occupies 16 consecutive packed rows + 1 scale
row. Both planes are therefore CONTIGUOUS and PROPORTIONAL in the input
dimension: any slice of whole blocks — a TP shard of axis -2, or a Pallas
reduction chunk — covers the same input range in `packed`, `scales`, and
`x`, so identical PartitionSpecs shard both planes correctly (see
parallel/sharding.py) and kernels need no cross-chunk gather. Unpack is two
shifts + a block-local concat. Dequantization is (nibble - 8) * f16(scale),
bit-identical to src/nn/nn-quants.cpp:229-246.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax.numpy as jnp

from .codec import Q40_BLOCK_SIZE, q40_to_planar, quantize_q40


class PackedQ40(NamedTuple):
    """A Q40-quantized matmul weight resident on device.

    Logical shape [..., d_in, d_out] for y = x @ W; ``logical_shape`` helpers
    below recover it from the stored planes.
    """

    packed: jnp.ndarray  # uint8 [..., d_in//2, d_out]
    scales: jnp.ndarray  # float16 [..., d_in//32, d_out]

    @property
    def d_in(self) -> int:
        return self.packed.shape[-2] * 2

    @property
    def d_out(self) -> int:
        return self.packed.shape[-1]


def pack_q40_planar(values: np.ndarray, scales: np.ndarray):
    """Host-side repack: planar int8 values [..., d_out, d_in] (centered at 0,
    file orientation) + f16-exact scales [..., d_out, d_in//32] -> the device
    layout (packed uint8 [..., d_in//2, d_out], scales f16 [..., d_in//32, d_out])."""
    d_in = values.shape[-1]
    assert d_in % Q40_BLOCK_SIZE == 0, values.shape
    lead = values.shape[:-2]
    d_out = values.shape[-2]
    n_blk = d_in // Q40_BLOCK_SIZE
    half = Q40_BLOCK_SIZE // 2
    v = np.swapaxes(values, -1, -2)  # [..., d_in, d_out]
    vb = v.reshape(*lead, n_blk, Q40_BLOCK_SIZE, d_out)
    lo = (vb[..., :half, :].astype(np.int16) + 8).astype(np.uint8)
    hi = (vb[..., half:, :].astype(np.int16) + 8).astype(np.uint8)
    packed = ((lo & 0x0F) | ((hi & 0x0F) << 4)).reshape(*lead, d_in // 2, d_out)
    scales_t = np.swapaxes(scales, -1, -2).astype(np.float16)  # [..., d_in//32, d_out]
    return packed, scales_t


def pack_q40_from_blocks(raw_blocks: np.ndarray, shape: tuple[int, int]):
    """Packed .m Q40 block bytes (row-major over [d_out, d_in], blocks along
    d_in — src/llm.cpp:447-483 tensor layout) -> device layout, WITHOUT
    dequantizing. Returns (packed uint8 [d_in//2, d_out], scales f16
    [d_in//32, d_out])."""
    d_out, d_in = shape
    values, scales = q40_to_planar(raw_blocks)  # [(d_out*d_in/32), 32], f32 scales
    values = values.reshape(d_out, d_in)
    scales = scales.reshape(d_out, d_in // Q40_BLOCK_SIZE)
    return pack_q40_planar(values, scales)


def pack_q40_host(w: np.ndarray):
    """Quantize a float weight in file orientation [..., d_out, d_in] to the
    device layout (through the bit-exact Q40 encoder, codec.quantize_q40)."""
    lead = w.shape[:-2]
    d_out, d_in = w.shape[-2], w.shape[-1]
    blocks = quantize_q40(np.ascontiguousarray(w, np.float32).reshape(-1))
    values, scales = q40_to_planar(blocks)
    values = values.reshape(*lead, d_out, d_in)
    scales = scales.reshape(*lead, d_out, d_in // Q40_BLOCK_SIZE)
    return pack_q40_planar(values, scales)


# ---------------------------------------------------------------------------
# Slab-kernel geometry (shared with ops/pallas_q40): the Pallas kernel reads
# weights in full-width (or wide 512-multiple) contiguous slabs. These are
# pure-math helpers so the loader can pad without importing Pallas.
# ---------------------------------------------------------------------------

import os as _os

# widest output block of the slab kernel. Env-overridable for hardware
# geometry A/Bs (bench sweep "r02_narrow512": the round-2 kernel's
# 512-lane tiles measured hbm_util 0.438 where the full-width slab
# measured 0.259 — the sweep reproduces that layout via DLLAMA_W_MAX=512)
PALLAS_W_MAX = int(_os.environ.get("DLLAMA_W_MAX", 8192))
if PALLAS_W_MAX <= 0 or PALLAS_W_MAX % 128 != 0:
    # a non-128-multiple makes every plane silently take the XLA fallback
    # (no tile candidate divides the planes), which would mislabel a sweep
    # datapoint as kernel geometry — fail loudly instead
    raise ValueError(
        f"DLLAMA_W_MAX must be a positive multiple of 128, got {PALLAS_W_MAX}"
    )
PALLAS_SUB = 512  # in-kernel dequant sub-tile (lanes)


def pallas_sub_tiles(w: int) -> list[int] | None:
    """Static lane sub-tile sizes for a width-w kernel block: 512-lane
    tiles plus a 128-multiple remainder (slice offsets stay 128-aligned —
    e.g. Llama-2-7B's 5504-wide TP shard tiles as 10x512 + 384), a single
    tile for narrow test shapes, None when unsupported."""
    if w % 128 == 0:
        tiles = [PALLAS_SUB] * (w // PALLAS_SUB)
        if w % PALLAS_SUB:
            tiles.append(w % PALLAS_SUB)
        return tiles
    if w <= 4096:  # odd widths (e.g. 2752 = 11008/4 TP shard): one tile
        return [w]
    return None


def pallas_wide_tile(d_out: int) -> int | None:
    """Output-block width the slab kernel would use for this d_out, or None
    when unsupported (callers fall back to q40_matmul_xla, or pad — see
    pad_packed_d_out)."""
    if d_out <= PALLAS_W_MAX and pallas_sub_tiles(d_out) is not None:
        return d_out
    for cand in range(PALLAS_W_MAX, 127, -128):
        if d_out % cand == 0:
            return cand
    return None


PAD_MAX_OVERHEAD = 0.125  # never inflate a tensor's bytes by more than this


def padded_d_out(d_out: int) -> int:
    """The output width pad_packed_d_out would pad a tensor of width
    ``d_out`` to (shape-only: lets benchmarks draw padded planes directly
    on device without materializing the unpadded host tensor)."""
    tile = pallas_wide_tile(d_out)
    if d_out <= PALLAS_W_MAX or (tile is not None and tile >= 4096):
        return d_out
    pad = -d_out % PALLAS_W_MAX
    return d_out if pad > d_out * PAD_MAX_OVERHEAD else d_out + pad


def pad_packed_d_out(packed: np.ndarray, scales: np.ndarray):
    """Zero-pad a packed weight's OUTPUT dim to a multiple of 8192 when the
    slab kernel cannot tile it WELL (e.g. vocab 128256: best natural tile
    is a strided 768 — padding to 131072 buys full 8192-wide contiguous
    slabs for +2.2% bytes). Only valid for output-only tensors (wcls):
    consumers must slice the matmul result back to the true width
    (llama_forward slices logits to vocab_size). Zero scales make the pad
    columns exact zeros.

    Padding is capped at PAD_MAX_OVERHEAD of the tensor's bytes: an
    unlucky width like 8320 would round to 16384 (+97%), which costs more
    HBM than the wide tile saves — those widths keep their natural layout
    and take the narrow-tile or q40_matmul_xla path instead. Pads that do
    land are logged so the inflation is visible."""
    d_out = packed.shape[-1]
    target = padded_d_out(d_out)
    if target == d_out:
        return packed, scales
    pad = target - d_out
    import logging

    logging.getLogger(__name__).info(
        "padding packed d_out %d -> %d (+%.1f%% bytes) for wide slab tiles",
        d_out, d_out + pad, 100.0 * pad / d_out,
    )
    width = [(0, 0)] * (packed.ndim - 1) + [(0, pad)]
    return (
        np.pad(np.asarray(packed), width),
        np.pad(np.asarray(scales), width),
    )


def unpack_q40(w: PackedQ40, dtype=jnp.float32) -> jnp.ndarray:
    """Dequantize to a dense [..., d_in, d_out] array (XLA fallback path;
    the Pallas kernel in ops/pallas_q40.py does this tile-wise in VMEM)."""
    lead = w.packed.shape[:-2]
    d_in, d_out = w.d_in, w.d_out
    n_blk = d_in // Q40_BLOCK_SIZE
    half = Q40_BLOCK_SIZE // 2
    pb = w.packed.reshape(*lead, n_blk, half, d_out)
    lo = (pb & 0x0F).astype(jnp.int8) - 8
    hi = (pb >> 4).astype(jnp.int8) - 8
    vals = jnp.concatenate([lo, hi], axis=-2)  # [..., n_blk, 32, d_out]
    scales = w.scales.astype(jnp.float32)[..., :, None, :]
    out = vals.astype(jnp.float32) * scales
    return out.reshape(*lead, d_in, d_out).astype(dtype)


def q40_matmul_xla(x: jnp.ndarray, w: PackedQ40, compute_dtype=None) -> jnp.ndarray:
    """y = x @ dequant(w) without a Pallas kernel. XLA fuses the unpack/scale
    into the matmul's weight-read loop where it can; correctness path for CPU
    tests and the fallback when Pallas is unavailable."""
    dtype = compute_dtype or x.dtype
    wd = unpack_q40(w, dtype)
    return jnp.matmul(x, wd, preferred_element_type=jnp.float32).astype(x.dtype)
