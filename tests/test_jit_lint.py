"""dlint v4 (jit-stability / donation-discipline / warmup-coverage): the
device-program surface model and its verdict on the real tree.

Two layers, the PR-2 contract test_dlint.py established:

- **self-tests** — every new checker gets known-bad and known-good
  fixture snippets (waiver syntax included), so the analyzer is
  regression-tested as a program;
- **rot-guards over the real module** — the extracted surface of
  ``runtime/engine.py`` is pinned (>= 14 jit sites, the full family
  set, every family warmed, bucketed families warmed per bucket,
  donation discipline at every call site), so a refactor that silently
  drops a family out of the model — or out of warmup — fails tier-1
  here even before the package-wide lint runs.

Pure-stdlib imports: these tests run without jax.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

from distributed_llama_multiusers_tpu.analysis import (
    PACKAGE_ROOT,
    Analyzer,
    default_checkers,
)
from distributed_llama_multiusers_tpu.analysis.cli import main as dlint_main
from distributed_llama_multiusers_tpu.analysis.jitmodel import jit_model_of


def run_on(tmp_path: Path, files: dict[str, str]):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src), encoding="utf-8")
    analyzer = Analyzer(default_checkers())
    return analyzer.run([tmp_path], baseline=set(), root=tmp_path)


def checks_of(findings):
    return sorted(f.check for f in findings)


def only(findings, check):
    """The donation fixtures are intentionally minimal (families, no
    warmup_engine), so warmup-coverage fires alongside by design —
    scope the assertion to the check under test."""
    return [f for f in findings if f.check == check]


# -- jit-stability ------------------------------------------------------------

STABILITY_HEADER = """
    import jax
    import jax.numpy as jnp

    class Engine:
        def __init__(self, row):
            self.cache = None
            self._table_sharding = None
            self._host_tables = row

        def _replace_leaf(self, host_array, sharding):
            if sharding is None:
                return jnp.asarray(host_array)
            return jax.make_array_from_callback(
                host_array.shape, sharding, lambda idx: host_array[idx]
            )
"""


def test_jit_stability_flags_bare_asarray_leaf(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": STABILITY_HEADER + """
        def apply(self, row):
            self.cache = self.cache._replace(table=jnp.asarray(row))
    """})
    assert checks_of(findings) == ["jit-stability"]
    assert "_replace_leaf" in findings[0].message


def test_jit_stability_flags_carry_rebuild_and_unsharded_device_put(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": STABILITY_HEADER + """
        def reseed(self, tokens):
            self._pl_carry = jnp.array(tokens)

        def upload(self, row):
            self._g_dev = jax.device_put(row)
    """})
    assert checks_of(findings) == ["jit-stability", "jit-stability"]


def test_jit_stability_sanctioned_constructor_clean(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": STABILITY_HEADER + """
        def apply(self, row):
            self.cache = self.cache._replace(
                table=self._replace_leaf(row, self._table_sharding)
            )

        def upload(self, row):
            self._g_dev = jax.device_put(row, self._table_sharding)
    """})
    assert findings == []


def test_jit_stability_operands_and_init_are_exempt(tmp_path):
    # converting OPERANDS is universal (never stored state), and __init__
    # builds the avals every program is compiled against
    findings = run_on(tmp_path, {"runtime/engine.py": """
        import jax.numpy as jnp

        class Engine:
            def __init__(self, row):
                self.cache = jnp.asarray(row)

            def decode(self, tokens):
                return self._fn(jnp.asarray(tokens))
    """})
    assert findings == []


def test_jit_stability_out_of_scope_file_ignored(tmp_path):
    findings = run_on(tmp_path, {"serving/other.py": STABILITY_HEADER + """
        def apply(self, row):
            self.cache = jnp.asarray(row)
    """})
    assert findings == []


def test_jit_stability_covers_dequant_select_scope(tmp_path):
    # ops/dequant_select.py sits in the jit-stability scope: its rules
    # are read at trace time, so a table (re)load that constructs device
    # arrays into self state would become a captured leaf whose aval can
    # change — the same recompile class as an engine leaf swap
    findings = run_on(tmp_path, {"ops/dequant_select.py": """
        import jax.numpy as jnp

        class DequantTable:
            def __init__(self, path):
                self.rules = []

            def load(self, rows):
                self.rules = jnp.asarray(rows)
    """})
    assert checks_of(findings) == ["jit-stability"]


def test_jit_stability_dequant_select_pure_host_clean(tmp_path):
    # the real table's shape: plain dicts parsed from JSON, no device
    # arrays anywhere near self state
    findings = run_on(tmp_path, {"ops/dequant_select.py": """
        import json

        class DequantTable:
            def __init__(self, path):
                self.rules = []

            def load(self, path):
                with open(path) as f:
                    self.rules = json.load(f).get("rules", [])
    """})
    assert findings == []


# -- donation-discipline ------------------------------------------------------

DONATE_HEADER = """
    from functools import partial
    import jax

    @partial(jax.jit, donate_argnums=(1,))
    def _decode(params, cache, tokens):
        return tokens, cache

    class Engine:
        def __init__(self):
            self._decode_fn = _decode
"""


def test_donation_flags_use_after_donate(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": DONATE_HEADER + """
        def decode(self, tokens):
            toks, fresh = self._decode_fn(self.params, self.cache, tokens)
            junk = self.cache.k
            return toks
    """})
    dona = only(findings, "donation-discipline")
    assert len(dona) == 1
    assert "use-after-donate" in dona[0].message
    assert "'self.cache'" in dona[0].message


def test_donation_flags_escape_into_host_state(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": DONATE_HEADER + """
        def decode(self, tokens):
            self._stash = self.cache
            toks, self.cache = self._decode_fn(
                self.params, self.cache, tokens
            )
            return toks
    """})
    dona = only(findings, "donation-discipline")
    assert len(dona) == 1
    assert "escapes" in dona[0].message


def test_donation_rebound_result_clean(tmp_path):
    # the engine's actual shape: the donated operand is rebound from the
    # call's results, later reads see the new buffer
    findings = run_on(tmp_path, {"runtime/engine.py": DONATE_HEADER + """
        def decode(self, tokens):
            toks, self.cache = self._decode_fn(
                self.params, self.cache, tokens
            )
            return self.cache.k
    """})
    assert only(findings, "donation-discipline") == []


def test_donation_star_operands_resolved(tmp_path):
    # `fn(*operands)` with a local tuple literal (the real decode()):
    # the donated slot is found through the expansion
    findings = run_on(tmp_path, {"runtime/engine.py": DONATE_HEADER + """
        def decode(self, tokens):
            operands = (self.params, self.cache, tokens)
            toks, fresh = self._decode_fn(*operands)
            junk = self.cache.k
            return toks
    """})
    assert len(only(findings, "donation-discipline")) == 1


def test_donation_moved_never_read_again_clean(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": DONATE_HEADER + """
        def consume(self, cache, tokens):
            toks, fresh = self._decode_fn(self.params, cache, tokens)
            return toks, fresh
    """})
    assert only(findings, "donation-discipline") == []


# -- warmup-coverage ----------------------------------------------------------

COVERAGE_HEADER = """
    from functools import partial
    import jax
    import numpy as np

    @partial(jax.jit, donate_argnums=(1,))
    def _decode(params, cache, tokens):
        return tokens, cache

    @partial(jax.jit, donate_argnums=(0,))
    def _copy_lane(cache, src, dst):
        return cache

    class Engine:
        def __init__(self):
            self._decode_fn = _decode
            self._copy_lane_fn = _copy_lane

        def decode(self, tokens):
            toks, self.cache = self._decode_fn(
                self.params, self.cache, tokens
            )
            return toks

        def copy_lane(self, src, dst):
            self.cache = self._copy_lane_fn(self.cache, src, dst)
"""


def test_warmup_coverage_flags_unwarmed_family(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": COVERAGE_HEADER + """
    def warmup_engine(engine):
        engine.decode(np.zeros(2))
    """})
    assert checks_of(findings) == ["warmup-coverage"]
    assert "_copy_lane_fn" in findings[0].message
    assert "copy_lane" in findings[0].message


def test_warmup_coverage_full_warmup_clean(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": COVERAGE_HEADER + """
    def warmup_engine(engine):
        engine.decode(np.zeros(2))
        engine.copy_lane(0, 1)
    """})
    assert findings == []


def test_warmup_coverage_getattr_alias_counts_as_warmed(tmp_path):
    # the real warmup's apply_paged = getattr(engine, "apply_paged_admit")
    findings = run_on(tmp_path, {"runtime/engine.py": COVERAGE_HEADER + """
    def warmup_engine(engine):
        engine.decode(np.zeros(2))
        copy = getattr(engine, "copy_lane", None)
        if copy is not None:
            copy(0, 1)
    """})
    assert findings == []


def test_warmup_coverage_flags_dead_family(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": """
        from functools import partial
        import jax
        import numpy as np

        @partial(jax.jit, donate_argnums=(1,))
        def _decode(params, cache, tokens):
            return tokens, cache

        @partial(jax.jit, donate_argnums=(1,))
        def _orphan(params, cache, tokens):
            return tokens, cache

        class Engine:
            def __init__(self):
                self._decode_fn = _decode
                self._orphan_fn = _orphan

            def decode(self, tokens):
                toks, self.cache = self._decode_fn(
                    self.params, self.cache, tokens
                )
                return toks

        def warmup_engine(engine):
            engine.decode(np.zeros(2))
    """})
    assert checks_of(findings) == ["warmup-coverage"]
    assert "dead device-program surface" in findings[0].message


def test_warmup_coverage_flags_missing_warmup_fn(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": COVERAGE_HEADER})
    assert checks_of(findings) == ["warmup-coverage"]
    assert "no warmup_engine" in findings[0].message


BUCKETED = """
    from functools import partial
    import jax
    import numpy as np

    @partial(jax.jit, donate_argnums=(1,))
    def _prefill(params, cache, tokens):
        return tokens, cache

    class Engine:
        prefill_buckets = (16, 64)

        def __init__(self):
            self._prefill_fn = _prefill

        def bucket_for(self, n):
            return 16

        def prefill_chunk(self, chunk):
            bucket = self.bucket_for(len(chunk))
            padded = np.zeros(bucket)
            toks, self.cache = self._prefill_fn(
                self.params, self.cache, padded
            )
            return toks
"""


def test_warmup_coverage_flags_bucketed_family_warmed_once(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": BUCKETED + """
    def warmup_engine(engine):
        engine.prefill_chunk([0] * 16)
    """})
    assert checks_of(findings) == ["warmup-coverage"]
    assert "prefill_buckets` loop" in findings[0].message


def test_warmup_coverage_bucket_loop_clean(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": BUCKETED + """
    def warmup_engine(engine):
        for bucket in engine.prefill_buckets:
            engine.prefill_chunk([0] * bucket)
    """})
    assert findings == []


def test_warmup_coverage_waivable_with_reason(tmp_path):
    # waive at the family's binding line (where the finding anchors)
    waived = run_on(tmp_path, {"runtime/engine.py": COVERAGE_HEADER
        .replace(
            "self._copy_lane_fn = _copy_lane",
            "self._copy_lane_fn = _copy_lane  "
            "# dlint: ok[warmup-coverage] debug-only path, never serves",
        ) + """
    def warmup_engine(engine):
        engine.decode(np.zeros(2))
    """})
    assert waived == []


# -- rot-guards over the real runtime/engine.py -------------------------------

ENGINE = PACKAGE_ROOT / "runtime" / "engine.py"


def test_real_dequant_select_lints_clean():
    """The shipped selection table stays pure host state — the dlint
    baseline for ops/dequant_select.py is (and must remain) empty."""
    analyzer = Analyzer(default_checkers())
    findings = analyzer.run(
        [PACKAGE_ROOT / "ops" / "dequant_select.py"],
        baseline=set(), root=PACKAGE_ROOT.parent,
    )
    assert findings == [], [str(f) for f in findings]

# the full dispatchable family set the serving loop can reach; a new
# `self.*_fn = jax.jit(...)`-style binding must join this list AND the
# warmup loop, or the package-wide lint (test_dlint) fails first
EXPECTED_FAMILIES = {
    "_decode_fn", "_decode_nologits_fn", "_decode_pl_fn",
    "_decode_spec_pl_fn", "_decode_spec_prefill_fn", "_decode_spec_fn",
    "_prefill_fn", "_decode_prefill_fn", "_copy_lane_fn", "_copy_page_fn",
    "_sample_one", "_make_decode_multi",
}


def test_real_engine_jit_site_count_floor():
    """The extractor still SEES the surface: >= 14 jax.jit sites in
    runtime/engine.py (12 families + the two init-time cache jits). A
    drop means the extraction idiom rotted, not that code disappeared."""
    model = jit_model_of(ENGINE)
    assert len(model.sites) >= 14, [s.name for s in model.sites]
    assert EXPECTED_FAMILIES <= set(model.families), (
        EXPECTED_FAMILIES - set(model.families)
    )


def test_real_engine_every_family_is_dispatched_and_warmed():
    """THE pin for the PR 11 compile-mid-chain class: every compiled
    family has a dispatcher, and every dispatcher set is covered by
    warmup_engine (copy_lane and sample_token joined warmup in this PR
    — the two adoption findings)."""
    model = jit_model_of(ENGINE)
    assert model.has_warmup
    warmed = model.warmed_families()
    groups: dict[int, list[str]] = {}
    for attr, site in model.families.items():
        groups.setdefault(id(site), []).append(attr)
    for attrs in groups.values():
        dispatchers = [
            d.name for d in model.dispatchers.values()
            if any(a in d.families for a in attrs)
        ]
        assert dispatchers, f"family {attrs} dispatched by nobody"
        assert any(a in warmed for a in attrs), (
            f"family {attrs} (dispatched by {dispatchers}) not warmed"
        )


def test_real_engine_warmed_method_set_pinned():
    model = jit_model_of(ENGINE)
    expected = {
        "prefill_chunk", "decode", "decode_spec", "decode_multi",
        "decode_pipelined", "decode_prefill_fused",
        "decode_spec_pipelined", "decode_spec_prefill_fused",
        "apply_paged_admit", "copy_lane", "sample_token",
    }
    assert expected <= set(model.warmed), expected - set(model.warmed)
    # bucketed families compile per prefill bucket: their warmup calls
    # must sit inside the `for bucket in engine.prefill_buckets` loop
    for m in ("prefill_chunk", "decode_prefill_fused",
              "decode_spec_prefill_fused"):
        assert model.warmed[m].in_bucket_loop, m
        assert model.dispatchers[m].bucketed, m


def test_real_engine_donation_discipline_holds():
    """Every donate_argnums call site in the real engine rebinds the
    donated operand from the call's results (>= 10 sites modeled — the
    whole decode/prefill/copy family donates its cache)."""
    model = jit_model_of(ENGINE)
    uses = [u for d in model.dispatchers.values() for u in d.donate_calls]
    assert len(uses) >= 10, len(uses)
    for use in uses:
        assert use.rebound, (use.family, use.line, use.spelling)
        assert use.escape_line is None, use


def test_real_engine_device_topk_knob_is_gone():
    """The dead knob warmup-coverage would mis-model stays deleted."""
    src = ENGINE.read_text(encoding="utf-8")
    import ast as _ast

    for node in _ast.walk(_ast.parse(src)):
        if isinstance(node, (_ast.FunctionDef, _ast.AsyncFunctionDef)):
            assert "device_topk" not in {a.arg for a in node.args.args}, (
                f"device_topk resurfaced on {node.name}"
            )


def test_jit_table_cli(capsys):
    assert dlint_main(["--jit-table"]) == 0
    out = capsys.readouterr().out
    assert "_decode_fn" in out and "warmup_engine" in out
    # every family row's warmed column reads "yes"
    assert not [l for l in out.splitlines() if l.endswith("NO")], out
