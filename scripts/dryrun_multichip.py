#!/usr/bin/env python
"""One-command multichip parity gate: run ``dryrun_multichip(8)`` on the
8-virtual-device CPU mesh in a child process and bank the result as
``MULTICHIP_r06.json`` (same artifact shape as the r01-r05 rounds).

The dryrun asserts the SERVING path on a (dp, tp, sp, ep) mesh is
stream-identical to the mesh-free engine — scheduler decode, chunked
prefill, speculative verify, multi-step, prefix cache, and (r06) the
async stack under churn: pipelined decode + fused admissions with zero
pipeline flushes. Invoked by ``make dryrun``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(ROOT, "MULTICHIP_r06.json")
N_DEVICES = 8


def main() -> int:
    env = dict(os.environ, GRAFT_SMALL="1", JAX_PLATFORMS="cpu")
    code = (
        f"import sys; sys.path.insert(0, {ROOT!r}); "
        f"from __graft_entry__ import dryrun_multichip; "
        f"dryrun_multichip({N_DEVICES})"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, env=env, timeout=1800,
        )
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc = 124

        def _txt(x):
            return x.decode(errors="replace") if isinstance(x, bytes) else (x or "")

        out = _txt(e.stdout)
        # keep the child's stderr tail: a wedged mesh prints its last
        # assert/progress there, and that is all the unattended evidence
        # loop will ever have to debug from
        err = _txt(e.stderr)[-1200:] + "\ntimeout after 1800s"
    lines = [ln for ln in out.strip().splitlines() if ln.strip()]
    tail = (lines[-1] + "\n") if lines else ""
    ok = rc == 0 and tail.startswith("dryrun_multichip OK")
    artifact = {
        "n_devices": N_DEVICES,
        "rc": rc,
        "ok": ok,
        "skipped": False,
        "tail": tail if ok else (tail + err[-1500:]),
    }
    with open(ARTIFACT, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    sys.stdout.write(tail or err[-1500:] + "\n")
    print(f"[dryrun] artifact: {ARTIFACT} (ok={ok})")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
