"""Native C++ codec must be byte-exact with the numpy reference codecs."""

import numpy as np
import pytest

from distributed_llama_multiusers_tpu import native
from distributed_llama_multiusers_tpu.quants import codec


@pytest.fixture(scope="module", autouse=True)
def built():
    if not native.available():
        pytest.skip("native library unavailable (no g++?)")


def rand(n, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(n, dtype=np.float32) * scale).astype(np.float32)


def edge_values():
    """Blocks hitting f16 rounding edges, zeros, tiny/huge magnitudes."""
    x = np.zeros(32 * 6, np.float32)
    x[32:64] = rand(32, 1, 1e-6)      # subnormal f16 scales
    x[64:96] = rand(32, 2, 1e4)       # large
    x[96] = 127.0
    x[97] = 0.5
    x[98] = -0.5
    x[128:160] = rand(32, 3, 65504.0)  # f16 max territory
    x[160:192] = rand(32, 4)
    return x


@pytest.mark.parametrize("maker", [lambda: rand(32 * 1000, 7), edge_values])
def test_q40_quantize_byte_exact(maker):
    x = maker()
    a = native.quantize_q40(x)
    b = codec.quantize_q40(x)
    assert a.tobytes() == b.tobytes()


@pytest.mark.parametrize("mode", ["runtime", "converter"])
def test_q80_quantize_byte_exact(mode):
    x = np.concatenate([rand(32 * 1000, 8), edge_values()])
    a = native.quantize_q80(x, mode=mode)
    b = codec.quantize_q80(x, mode=mode)
    assert a.tobytes() == b.tobytes()


def test_q40_dequantize_bit_exact():
    x = rand(32 * 500, 9)
    blocks = codec.quantize_q40(x)
    a = native.dequantize_q40(blocks)
    b = codec.dequantize_q40(blocks)
    np.testing.assert_array_equal(a, b)


def test_q80_dequantize_bit_exact():
    x = rand(32 * 500, 10)
    blocks = codec.quantize_q80(x)
    a = native.dequantize_q80(blocks)
    b = codec.dequantize_q80(blocks)
    np.testing.assert_array_equal(a, b)


def test_q40_planar_matches():
    x = rand(32 * 200, 11)
    blocks = codec.quantize_q40(x)
    va, sa = native.q40_to_planar(blocks)
    vb, sb = codec.q40_to_planar(blocks)
    np.testing.assert_array_equal(va, vb)
    np.testing.assert_array_equal(sa, sb)


def test_f16_conversion_matches_numpy():
    import ctypes

    lib = native.load()
    # every possible f16 bit pattern decodes exactly like numpy
    h = np.arange(65536, dtype=np.uint16)
    out = np.empty(65536, np.float32)
    lib.dlq_f16_to_f32(
        h.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        65536, 1,
    )
    expect = h.view(np.float16).astype(np.float32)
    np.testing.assert_array_equal(np.nan_to_num(out, nan=0), np.nan_to_num(expect, nan=0))
    assert np.array_equal(np.isnan(out), np.isnan(expect))
    # f32 -> f16 round-trips bit-exactly vs numpy cast on random values
    f = np.concatenate([rand(10000, 12, s) for s in (1.0, 1e-5, 1e5)]).astype(np.float32)
    got = np.empty(f.size, np.uint16)
    lib.dlq_f32_to_f16(
        f.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        got.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        f.size, 1,
    )
    np.testing.assert_array_equal(got, f.astype(np.float16).view(np.uint16))


def test_loader_uses_native_and_matches(tiny_model):
    """Loading through the native dequant path equals pure-numpy loading."""
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.formats import load_model_header
    from distributed_llama_multiusers_tpu.models.loader import read_m_tensors

    h = load_model_header(tiny_model["model"])
    with_native = read_m_tensors(tiny_model["model"], h)
    # force numpy fallback
    saved = native._lib, native._load_failed
    native._lib, native._load_failed = None, True
    try:
        without = read_m_tensors(tiny_model["model"], h)
    finally:
        native._lib, native._load_failed = saved
    np.testing.assert_array_equal(with_native["wq"][0], without["wq"][0])
    np.testing.assert_array_equal(with_native["embedding"], without["embedding"])


def test_q40_tie_break_matches_numpy():
    """-min == max tie must pick the positive extreme (writer.py semantics)."""
    x = np.zeros(32, np.float32)
    x[0] = -3.0
    x[1] = 3.0
    a = native.quantize_q40(x)
    b = codec.quantize_q40(x)
    assert a.tobytes() == b.tobytes()


class TestNativeBpe:
    """The C++ scan+merge encoder must be TOKEN-IDENTICAL to the Python
    tokenizer on every path: plain text, specials, bos on/off, specials
    on/off, untokenizable fallback. The contract is exactness, not
    closeness — prompts admit through whichever side the length threshold
    picks, and streams must not depend on it."""

    @pytest.fixture(scope="class")
    def tok(self, tiny_model):
        from distributed_llama_multiusers_tpu.tokenizer import Tokenizer

        return Tokenizer(tiny_model["tokenizer"])

    def _ab(self, tok, text, **kw):
        import distributed_llama_multiusers_tpu.tokenizer.tokenizer as tm

        old = tm.NATIVE_MERGE_MIN_TOKENS
        try:
            tm.NATIVE_MERGE_MIN_TOKENS = 10**9  # force Python
            py = tok.encode(text, **kw)
            tm.NATIVE_MERGE_MIN_TOKENS = 1  # force native
            nat = tok.encode(text, **kw)
        finally:
            tm.NATIVE_MERGE_MIN_TOKENS = old
        assert nat == py, (nat[:20], py[:20])
        return py

    def test_long_random_text_identical(self, tok):
        import random

        random.seed(3)
        text = "".join(random.choice("abcdefgh .,") for _ in range(50_000))
        out = self._ab(tok, text)
        assert len(out) > 1000

    def test_specials_and_flags_identical(self, tok):
        sp = tok.vocab[tok.vocab_size - 1].decode()
        text = ("hello world " + sp) * 500
        self._ab(tok, text)
        self._ab(tok, text, add_bos=False)
        self._ab(tok, "abc " * 2000, add_special_tokens=False)

    def test_untokenizable_falls_back_to_python_error(self, tok):
        import distributed_llama_multiusers_tpu.tokenizer.tokenizer as tm

        # a byte outside the tiny vocab: native returns None, the Python
        # path raises the exact error either way
        bad = ("abc " * 200) + "\xff\xff"
        old = tm.NATIVE_MERGE_MIN_TOKENS
        try:
            tm.NATIVE_MERGE_MIN_TOKENS = 1
            with pytest.raises(ValueError, match="untokenizable"):
                tok.encode(bad)
        finally:
            tm.NATIVE_MERGE_MIN_TOKENS = old

    def test_merge_entry_point_identical(self, tok):
        """The standalone merge ABI (used when the seed tokens are already
        known) matches Tokenizer._merge."""
        from distributed_llama_multiusers_tpu.native import NativeBpe

        nb = NativeBpe(tok.vocab, tok.regular_vocab_size, tok.scores)
        import random

        random.seed(5)
        ids = [random.randrange(tok.regular_vocab_size) for _ in range(5000)]
        assert nb.merge(ids) == tok._merge(ids)
