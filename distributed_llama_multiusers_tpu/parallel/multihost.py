"""Multi-host serving: jax.distributed bootstrap + a root->worker control
plane over device collectives.

Reference mapping (src/app.cpp):
- cluster bootstrap (worker `serve()` + root connects and ships configs,
  src/app.cpp:405-464, src/nn/nn-network.cpp:264-348) ->
  ``jax.distributed.initialize``: every host runs the SAME program
  (multi-controller SPMD) and chips join one global mesh; there is no
  config/weight wire protocol because each host loads the model file and
  ``shard_params`` places its addressable shards.
- ``LlmControlPacket{position,batchSize}`` written to all workers before
  every forward (src/app.cpp:198-209, `writeAll`) -> ``ControlPlane``:
  a fixed-size int32 packet broadcast root->workers per engine call
  (jax.experimental.multihost_utils.broadcast_one_to_all), carrying the op
  (prefill/decode/stop) and its host-side arguments. batchSize=0 as the
  stop signal (src/app.cpp:204-209) maps to OP_STOP.
- worker mode's control-packet poll loop (src/app.cpp:218-231) ->
  ``worker_loop``: recv packet, replay the identical engine call so every
  process dispatches the same XLA program in lockstep.

Pod-deadlock rule — MACHINE-CHECKED by dlint's ``pod-broadcast`` check
(analysis/broadcast_check.py, scoped to this file): in every
``RootControlEngine`` proxy method, argument validation runs BEFORE the
packet broadcast, and no ``raise`` or early ``return`` is reachable
between a ``self._plane.send_*`` broadcast and its paired
``self._engine`` call. A packet with no matching root-side compute
leaves every worker blocked inside a collective the root never
dispatches — a hang with no timeout, invisible until the pod is dead.
Relatedly, dlint's ``lock-blocking`` check forbids broadcasting while
holding any declared lock anywhere in the package. See docs/LINT.md.

Wire-protocol surface — MACHINE-CHECKED by dlint's ``protocol`` and
``protocol-manifest`` checks (analysis/protocol_check.py, scoped to this
file): every ``OP_*`` constant pairs with exactly one ``send_*`` encoder
AND one ``worker_loop`` replay arm, packet slot indices stay below
``ControlPlane.SLOTS``, operand-carrying broadcasts are validated
pre-broadcast, and fixed header widths (the 7-word fused-prefill header)
agree between encoder and replay arm. The whole layout — version, op
table, HEADER/SLOTS, per-op payload counts and header widths — is PINNED
in ``analysis/protocol.lock``: changing the packet without bumping
``PROTOCOL_VERSION`` in the same diff fails ``make lint``; after a bump,
re-pin with ``dlint --update-protocol-manifest`` (and eyeball
``make protocol``, which prints the extracted op table + manifest diff).
"""

from __future__ import annotations

import os

import numpy as np

from ..telemetry.logs import log_event
from ..utils import faults

# Control-packet integrity word (failure containment satellite): every
# packet leads with a magic constant and the protocol version, validated
# on recv BEFORE the op dispatch. A torn packet (a worker joining
# mid-stream, a collective delivering garbage after a peer death) or a
# version-skewed peer (rolling restart mixing binaries) becomes a
# CLASSIFIED ReplayError naming what mismatched — not an "unknown control
# op N" crash deep in the replay switch that burns a supervised restart
# on a packet that was never valid.
PACKET_MAGIC = 0x444C4C41  # "DLLA"
# v2: zero-flush serving — SLOTS grew 7 -> 9 (the fused spec packet carries
# drafts + lengths + chunk + prefill header) and two new ops landed
# (OP_DECODE_SPEC_PIPELINED / OP_DECODE_SPEC_PREFILL_FUSED). The packet
# SIZE changed, so a v1 peer cannot even frame a v2 broadcast — the
# version word turns that into a classified ReplayError instead of a
# garbage replay.
# v3: paged KV — OP_KV_TABLE ships page-table rows + COW page copies. The
# packet size did NOT change, so a v2 peer COULD frame a v3 broadcast and
# would replay every op except the table updates — leaving its replicated
# page tables silently stale (wrong gathers, not a deadlock). The bump
# turns that silent divergence into a classified ReplayError on the first
# packet.
# v4: grammar-constrained decoding — SLOTS grew 9 -> 10 (every decode-
# family op carries the per-lane grammar-state vector; fused prefill
# headers grew to 7 words for the admitting lane's automaton start
# state) and OP_GRAMMAR landed (schema broadcast at admission, compiled
# locally by every process against its own tokenizer table). The packet
# size changed, so a v3 peer cannot frame a v4 broadcast — the version
# word classifies it.
# v5: disaggregated prefill — OP_KV_PAGES ships whole KV-page payloads
# (a prefill replica's committed pages adopted into this pod's pool,
# disagg/kvtransfer.py). The packet size did NOT change, so a v4 peer
# COULD frame a v5 broadcast and would replay every op except the page
# imports — adopted pages would read as garbage KV on that process's
# shard (wrong gathers, not a deadlock), the same silent-divergence
# class v3 closed for table rows. The bump classifies it on the first
# packet.
# v6: tiered KV residency — OP_KV_SWAP ships host-tier swap-in page
# payloads (parked pages evicted to host RAM reactivating by copy,
# runtime/kvpool.HostTier). The packet size did NOT change, so a v5
# peer COULD frame a v6 broadcast and would replay every op except the
# swap-ins — reactivated pages would read as stale/garbage KV on that
# process's shard (wrong gathers, not a deadlock), the same
# silent-divergence class v3/v5 closed. The bump classifies it on the
# first packet.
PROTOCOL_VERSION = 6

OP_STOP = 0
OP_PREFILL = 1
OP_DECODE = 2
OP_DECODE_SPEC = 3
OP_STATS_RESET = 4  # zero worker-side engine counters (post-warmup hygiene)
OP_COPY_LANE = 5  # prefix caching: copy one lane's KV into another
OP_DECODE_MULTI = 6  # h chained decode steps in one dispatch (h in header)
OP_DECODE_PIPELINED = 7  # async pipelined step: device-fed token carry,
# feed flag + ring depth in the header, so workers replay the same chain
OP_PIPELINE_FLUSH = 8  # root ended/aborted a pipelined chain: workers drain
# their own rings and drop their carries (no device program to replay, but
# a worker holding stale in-flight steps pins device buffers between chains)
OP_DECODE_PREFILL_FUSED = 9  # stall-free admission: ONE dispatch that both
# advances the pipelined decode lanes and consumes a bounded prompt chunk
# for one admitting lane — bucket + chunk header ride the packet so every
# process compiles/replays the identical per-bucket fused program
OP_DECODE_SPEC_PIPELINED = 10  # zero-flush speculation: a spec verify step
# INSIDE the pipelined ring — drafts (flattened [n * (SPEC_DRAFT+1)],
# candidate 0 = the host's guess at the device carry) + per-lane lengths
# ride slots 5/6 behind the magic/version header; feed flag + ring depth
# in the DECODE_PIPELINED header slots, so workers replay the same chain
# with the same bounded lag
OP_DECODE_SPEC_PREFILL_FUSED = 11  # the full composition: an admitting
# prompt chunk AND a spec verify step share one dispatch — the
# SPEC_PIPELINED slots plus the chunk (slot 7) and the prefill header
# (slot 8, the DECODE_PREFILL_FUSED layout)
OP_KV_TABLE = 12  # paged KV (runtime/kvpool.py): one lane's page-table row
# (slot 0, n entries) + flattened COW page copies (slot 1, start_pos
# pairs) — the pool bookkeeping (free list, refcounts, prefix tree) is
# root-only HOST state, so only its device half replays: workers apply
# the copies and the new table row via engine.apply_paged_admit, keeping
# the replicated table leaf byte-identical on every process. lane == -1
# means "unmap every lane" (containment reset, engine.paged_unmap_all).
OP_GRAMMAR = 13  # grammar-constrained decoding (grammar/): broadcast a
# response_format's canonical JSON at admission so every process compiles
# the SAME automaton against its own (identical) tokenizer table and
# installs it at the SAME slab base — deterministic, so the tables never
# ship over the wire. `lane` carries flags (bit 0: final fragment of the
# schema bytes, bit 1: detach — payload is the schema KEY, not JSON),
# `n` the fragment byte length, `start_pos` the fragment index; workers
# accumulate fragments until the final one, then attach/detach. The
# root compiles and validates BEFORE the first packet (the pod-deadlock
# rule: a schema that cannot compile dies with zero packets out).
OP_KV_PAGES = 14  # disaggregated prefill (disagg/kvtransfer.py): import a
# transferred KV page's raw payload bytes into the pool arrays on every
# process. Framed like OP_GRAMMAR: `lane` carries flags (bit 0: final
# fragment of this page's payload), `n` the fragment byte length,
# `start_pos` the DESTINATION page id; payload bytes ride slot 0 as
# packed int32 words. Workers accumulate fragments (the op stream is
# ordered and one page's fragments are contiguous) and on the final one
# dispatch engine.import_kv_page — the same warmed single-page write
# program the root runs, so the replicated pool arrays stay
# byte-identical. Pool bookkeeping (adopt(), refcounts, prefix tree)
# stays root-only HOST state, exactly like OP_KV_TABLE's split.
OP_KV_SWAP = 15  # tiered KV residency (runtime/kvpool.HostTier): reactivate
# host-swapped pages on every process. Framed like OP_KV_PAGES — `lane`
# carries flags (bit 0: final fragment of this page's payload, bit 1:
# final page of the BATCH), `n` the fragment byte length, `start_pos`
# the destination page id; payload bytes ride slot 0 as packed int32
# words. Workers accumulate fragments into (page, payload) pairs and on
# the batch-final flag dispatch ONE engine.swap_in_pages — the same
# warmed batched scatter program the root runs, so the replicated pool
# arrays stay byte-identical and the per-batch dispatch count matches.
# Swap-OUT never rides the wire: it is a root-local device READ (the
# host tier, like all pool bookkeeping, is root-only HOST state).


class ReplayError(RuntimeError):
    """Classified control-plane replay failure: the packet itself is bad
    (magic/version mismatch, unknown op) — detected BEFORE any engine
    dispatch, so no collective was entered and the pod cannot have
    desynced on it. ``worker_serve`` counts these separately from engine
    replay errors and does not burn its restart budget on them."""


def maybe_initialize_distributed(args=None) -> int:
    """Join a multi-host pod when coordinator flags/env are present; returns
    the process count (1 when not distributed). Must run before the backend
    initializes. Flags: --coordinator host:port --num-processes N
    --process-id I, or env DLLAMA_COORDINATOR / DLLAMA_NUM_PROCESSES /
    DLLAMA_PROCESS_ID."""
    coord = getattr(args, "coordinator", None) or os.environ.get("DLLAMA_COORDINATOR")
    if not coord:
        return 1
    n = int(
        getattr(args, "num_processes", None)
        or os.environ.get("DLLAMA_NUM_PROCESSES", "0")
    )
    pid_attr = getattr(args, "process_id", None)
    pid = int(
        pid_attr if pid_attr is not None else os.environ.get("DLLAMA_PROCESS_ID", "-1")
    )
    if n <= 0 or pid < 0:
        raise ValueError(
            "--coordinator requires --num-processes and --process-id "
            "(or the DLLAMA_* env equivalents)"
        )
    import jax

    jax.distributed.initialize(
        coordinator_address=coord, num_processes=n, process_id=pid
    )
    return n


class ControlPlane:
    """Fixed-size int32 packet, broadcast from process 0 each engine call.

    Layout: [magic, version, op, lane, n, start_pos,
    payload_a[L] .. payload_e[L]] with L = max(n_lanes, chunk). The
    leading magic + protocol-version words are validated on ``recv``
    (see PACKET_MAGIC above): a torn or version-skewed packet raises a
    classified :class:`ReplayError` before the op switch ever runs. PREFILL: payload_a[:n] = prompt-chunk tokens,
    payload_b/c[0] = temperature/top-p float32 bit patterns, payload_d[0] =
    sampler seed (first-token sampling is fused into the compiled prefill,
    so its scalar operands must be byte-identical on every process).
    DECODE: payload_a = tokens, payload_b = positions, payload_c/d =
    temperatures/top-p as float32 bit patterns, payload_e = sampler seeds —
    every process must dispatch the identical compiled decode (sampling is
    fused into it), so the sampling arguments ride the control packet the
    way position/batchSize ride LlmControlPacket (src/app.cpp:198-209).
    DECODE_SPEC: the DECODE slots plus payload_f = draft tokens (flattened
    [n_lanes * SPEC_DRAFT]) and payload_g = per-lane draft lengths, so
    speculative verify steps replay on pods too.
    DECODE_MULTI: the DECODE slots; the horizon h rides the start_pos
    header field (multi-step decode replays as one packet per h steps).
    DECODE_PIPELINED: the DECODE slots; the ``lane`` header field carries
    the feed flag (1 = host tokens in slot 0 reseed the chain after a
    flush, 0 = continue from the worker's own device carry) and
    ``start_pos`` carries the ring depth, so every process runs the same
    async chain with the same bounded lag.
    DECODE_PREFILL_FUSED: the DECODE_PIPELINED slots plus payload_f = the
    prompt-chunk tokens and payload_g = the prefill header
    [p_lane, p_start, p_n, p_temp bits, p_topp bits, p_seed bits] — the
    chunk length p_n picks the prefill bucket, so every process compiles
    and replays the identical fused prefill+decode program.
    DECODE_SPEC_PIPELINED: the DECODE_PIPELINED slots plus payload_f =
    the in-chain drafts (flattened [n_lanes * (SPEC_DRAFT+1)] — candidate
    0 per lane is the host's guess at the device carry token) and
    payload_g = per-lane draft lengths.
    DECODE_SPEC_PREFILL_FUSED: the DECODE_SPEC_PIPELINED slots plus
    payload_h = the prompt-chunk tokens and payload_i = the prefill
    header (the DECODE_PREFILL_FUSED layout) — an admitting chunk and a
    spec verify step replay as ONE packet.
    DECODE also rides its want_logits flag in the ``lane`` header field:
    the logits-materializing and no-logits steps are different compiled
    programs, and every process must dispatch the same one.
    """

    HEADER = 6  # [magic, version, op, lane, n, start_pos]
    SLOTS = 10

    def __init__(self, n_lanes: int, chunk: int = 1024):
        from ..runtime.spec import SPEC_DRAFT

        self.n_lanes = n_lanes
        # every slot must fit its largest payload: prompt chunks (chunk),
        # per-lane vectors (n_lanes), and the flattened in-chain drafts
        # (SPEC_DRAFT + 1 candidates per lane)
        self.chunk = max(chunk, n_lanes, n_lanes * (SPEC_DRAFT + 1))
        self._size = self.HEADER + self.SLOTS * self.chunk

    def _check_spec_payload(self, flat: np.ndarray) -> np.ndarray:
        """One copy of the drafts-fit-the-slot guard (constructor sizing
        guarantees it for engines the plane was built for; a mismatched
        plane must die before any packet goes out)."""
        if len(flat) > self.chunk:
            raise ValueError(
                f"spec drafts payload {len(flat)} exceeds packet slot "
                f"{self.chunk}; size ControlPlane for the engine's "
                "draft layout"
            )
        return flat

    def _bcast(self, pkt: np.ndarray) -> np.ndarray:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.broadcast_one_to_all(pkt))

    def slot(self, pkt: np.ndarray, i: int, n: int) -> np.ndarray:
        start = self.HEADER + i * self.chunk
        return pkt[start : start + n]

    def _send(self, op: int, lane: int, n: int, start_pos: int, *payloads) -> None:
        faults.fire("plane.broadcast")  # chaos harness; no-op unarmed
        pkt = np.zeros(self._size, np.int32)
        pkt[0:6] = (PACKET_MAGIC, PROTOCOL_VERSION, op, lane, n, start_pos)
        for i, payload in enumerate(payloads):
            if payload is not None:
                start = self.HEADER + i * self.chunk
                pkt[start : start + len(payload)] = payload
        self._bcast(pkt)

    def send_prefill(
        self, lane: int, tokens, start_pos: int,
        temp: float = 0.0, topp: float | None = None, seed: int = 0,
        g_state: int = 0,
    ) -> None:
        if topp is None:  # one default for every sampling surface
            from ..runtime.engine import DEFAULT_TOPP as topp
        tbits = np.asarray([temp], np.float32).view(np.int32)
        pbits = np.asarray([topp], np.float32).view(np.int32)
        sbits = np.asarray([seed & 0xFFFFFFFF], np.uint32).view(np.int32)
        gbits = np.asarray([g_state], np.int32)
        for off in range(0, len(tokens), self.chunk):
            part = tokens[off : off + self.chunk]
            self._send(
                OP_PREFILL, lane, len(part), start_pos + off,
                part, tbits, pbits, sbits, gbits,
            )

    def send_decode(
        self, tokens, positions, temps=None, topps=None, seeds=None,
        want_logits: bool = True, g_states=None,
    ) -> None:
        n = len(tokens)
        as_bits = lambda f: (
            None if f is None else np.asarray(f, np.float32).view(np.int32)
        )
        self._send(
            OP_DECODE, 1 if want_logits else 0, n, 0,
            tokens, positions, as_bits(temps), as_bits(topps),
            None if seeds is None else np.asarray(seeds, np.uint32).view(np.int32),
            None if g_states is None else np.asarray(g_states, np.int32),
        )

    def send_decode_pipelined(
        self, tokens, positions, temps, topps, seeds, depth: int,
        g_states=None,
    ) -> None:
        n = len(positions)
        # feed flag rides `lane` (tokens present = chain reseed), ring
        # depth rides `start_pos` — workers mirror the root's bounded lag;
        # grammar states ride slot 5 (-1 = the worker's own device carry,
        # the same select the root's dispatch applies)
        self._send(
            OP_DECODE_PIPELINED, 0 if tokens is None else 1, n, depth,
            tokens, positions,
            np.asarray(temps, np.float32).view(np.int32),
            np.asarray(topps, np.float32).view(np.int32),
            np.asarray(seeds, np.uint32).view(np.int32),
            None if g_states is None else np.asarray(g_states, np.int32),
        )

    @staticmethod
    def _prefill_header(p_lane, p_start, chunk, p_temp, p_topp, p_seed,
                        p_g) -> np.ndarray:
        """The 7-word fused-prefill header (v4: word 6 is the admitting
        lane's grammar start state) — ONE encoder for both fused ops."""
        phdr = np.zeros(7, np.int32)
        phdr[0:3] = (p_lane, p_start, len(chunk))
        phdr[3] = np.asarray([p_temp], np.float32).view(np.int32)[0]
        phdr[4] = np.asarray([p_topp], np.float32).view(np.int32)[0]
        phdr[5] = np.asarray([p_seed & 0xFFFFFFFF], np.uint32).view(np.int32)[0]
        phdr[6] = p_g
        return phdr

    def send_decode_prefill_fused(
        self, tokens, positions, temps, topps, seeds, depth: int,
        p_lane: int, chunk, p_start: int, p_temp: float, p_topp: float,
        p_seed: int, g_states=None, p_g: int = 0,
    ) -> None:
        n = len(positions)
        # DECODE_PIPELINED header layout (feed flag in `lane`, ring depth
        # in `start_pos`); the chunk rides slot 5, its header slot 6,
        # the grammar-state vector slot 7
        phdr = self._prefill_header(
            p_lane, p_start, chunk, p_temp, p_topp, p_seed, p_g
        )
        self._send(
            OP_DECODE_PREFILL_FUSED, 0 if tokens is None else 1, n, depth,
            tokens, positions,
            np.asarray(temps, np.float32).view(np.int32),
            np.asarray(topps, np.float32).view(np.int32),
            np.asarray(seeds, np.uint32).view(np.int32),
            np.asarray(chunk, np.int32),
            phdr,
            None if g_states is None else np.asarray(g_states, np.int32),
        )

    def send_decode_spec_pipelined(
        self, tokens, positions, temps, topps, seeds, depth: int,
        drafts, draft_len, g_states=None,
    ) -> None:
        n = len(positions)
        flat = self._check_spec_payload(np.asarray(drafts, np.int32).reshape(-1))
        # DECODE_PIPELINED header layout (feed flag in `lane`, ring depth
        # in `start_pos`); drafts + lengths ride slots 5/6, grammar
        # states slot 7
        self._send(
            OP_DECODE_SPEC_PIPELINED, 0 if tokens is None else 1, n, depth,
            tokens, positions,
            np.asarray(temps, np.float32).view(np.int32),
            np.asarray(topps, np.float32).view(np.int32),
            np.asarray(seeds, np.uint32).view(np.int32),
            flat,
            np.asarray(draft_len, np.int32),
            None if g_states is None else np.asarray(g_states, np.int32),
        )

    def send_decode_spec_prefill_fused(
        self, tokens, positions, temps, topps, seeds, depth: int,
        drafts, draft_len, p_lane: int, chunk, p_start: int,
        p_temp: float, p_topp: float, p_seed: int, g_states=None,
        p_g: int = 0,
    ) -> None:
        n = len(positions)
        flat = self._check_spec_payload(np.asarray(drafts, np.int32).reshape(-1))
        phdr = self._prefill_header(
            p_lane, p_start, chunk, p_temp, p_topp, p_seed, p_g
        )
        self._send(
            OP_DECODE_SPEC_PREFILL_FUSED, 0 if tokens is None else 1, n,
            depth,
            tokens, positions,
            np.asarray(temps, np.float32).view(np.int32),
            np.asarray(topps, np.float32).view(np.int32),
            np.asarray(seeds, np.uint32).view(np.int32),
            flat,
            np.asarray(draft_len, np.int32),
            np.asarray(chunk, np.int32),
            phdr,
            None if g_states is None else np.asarray(g_states, np.int32),
        )

    def send_decode_spec(
        self, tokens, drafts, draft_len, positions, temps, topps, seeds,
        g_states=None,
    ) -> None:
        n = len(tokens)
        flat = self._check_spec_payload(np.asarray(drafts, np.int32).reshape(-1))
        self._send(
            OP_DECODE_SPEC, 0, n, 0,
            tokens, positions,
            np.asarray(temps, np.float32).view(np.int32),
            np.asarray(topps, np.float32).view(np.int32),
            np.asarray(seeds, np.uint32).view(np.int32),
            flat,
            np.asarray(draft_len, np.int32),
            None if g_states is None else np.asarray(g_states, np.int32),
        )

    def send_decode_multi(
        self, tokens, positions, temps, topps, seeds, h: int,
        g_states=None,
    ) -> None:
        n = len(tokens)
        # the horizon rides the start_pos header field
        self._send(
            OP_DECODE_MULTI, 0, n, h,
            tokens, positions,
            np.asarray(temps, np.float32).view(np.int32),
            np.asarray(topps, np.float32).view(np.int32),
            np.asarray(seeds, np.uint32).view(np.int32),
            None if g_states is None else np.asarray(g_states, np.int32),
        )

    def send_grammar(self, blob: bytes, detach: bool = False) -> None:
        """Broadcast a grammar attach (canonical response_format JSON) or
        detach (the schema key string) — chunked when the blob outgrows
        one packet slot; workers accumulate fragments and act on the
        final one. Every process compiles locally, so the tables never
        ship over the wire (the broadcast is bytes-of-schema, not
        megabytes of masks)."""
        frag_bytes = self.chunk * 4  # int32 words carry 4 schema bytes each
        frags = [
            blob[off : off + frag_bytes]
            for off in range(0, max(1, len(blob)), frag_bytes)
        ]
        for idx, frag in enumerate(frags):
            flags = (1 if idx == len(frags) - 1 else 0) | (
                2 if detach else 0
            )
            pad = (-len(frag)) % 4
            words = np.frombuffer(frag + b"\0" * pad, np.uint8).view(
                np.int32
            )
            self._send(OP_GRAMMAR, flags, len(frag), idx, words)

    def send_pipeline_flush(self) -> None:
        self._send(OP_PIPELINE_FLUSH, 0, 0, 0)

    def send_stop(self) -> None:
        self._send(OP_STOP, 0, 0, 0)

    def send_stats_reset(self) -> None:
        self._send(OP_STATS_RESET, 0, 0, 0)

    def send_copy_lane(self, src: int, dst: int) -> None:
        # header fields carry the operands: lane=src, start_pos=dst
        self._send(OP_COPY_LANE, src, 0, dst)

    def send_kv_table(self, lane: int, row, copies) -> None:
        """Paged-KV table update: row length rides ``n``, the COW pair
        count rides ``start_pos``; lane == -1 unmaps every lane (reset).
        Raises (pre-broadcast, the pod-deadlock rule) when the row or the
        copies outgrow their packet slots."""
        row = np.asarray(row, np.int32)
        flat = np.asarray(
            [c for pair in copies for c in pair], np.int32
        )
        if len(row) > self.chunk or len(flat) > self.chunk:
            raise ValueError(
                f"kv table payload (row {len(row)}, copies {len(flat)}) "
                f"exceeds packet slot {self.chunk}; size "
                "ControlPlane(chunk=...) >= the engine's blocks-per-lane"
            )
        self._send(OP_KV_TABLE, lane, len(row), len(copies), row, flat)

    def send_kv_pages(self, pages) -> None:
        """Broadcast transferred KV page payloads (disagg adoption):
        each ``(page, payload_bytes)`` is chunked into packet-slot
        fragments like ``send_grammar``'s schema bytes — flags in
        ``lane`` (bit 0: final fragment of this page), fragment byte
        length in ``n``, the destination page id in ``start_pos``.
        Raises pre-broadcast (the pod-deadlock rule) on a negative page
        id — payload-size validation against the pool geometry is the
        caller's job (RootControlEngine.import_kv_page), since the
        plane does not know the engine's page shape."""
        frag_bytes = self.chunk * 4  # int32 words carry 4 payload bytes
        for page, payload in pages:
            if int(page) < 0:
                raise ValueError(
                    f"kv page id must be >= 0, got {page}"
                )
            blob = bytes(payload)
            frags = [
                blob[off : off + frag_bytes]
                for off in range(0, max(1, len(blob)), frag_bytes)
            ]
            for idx, frag in enumerate(frags):
                flags = 1 if idx == len(frags) - 1 else 0
                pad = (-len(frag)) % 4
                words = np.frombuffer(frag + b"\0" * pad, np.uint8).view(
                    np.int32
                )
                self._send(OP_KV_PAGES, flags, len(frag), int(page), words)

    def send_kv_swap(self, pages) -> None:
        """Broadcast a host-tier swap-in BATCH (tiered KV residency):
        each ``(page, payload_bytes)`` is chunked into packet-slot
        fragments like ``send_kv_pages`` — flags in ``lane`` (bit 0:
        final fragment of this page, bit 1: final page of the batch,
        set on that page's final fragment), fragment byte length in
        ``n``, the destination page id in ``start_pos``. The batch flag
        lets workers dispatch ONE batched scatter per root dispatch
        (engine.swap_in_pages), keeping program counts identical.
        Raises pre-broadcast (the pod-deadlock rule) on an empty batch
        or a negative page id — payload-size validation against the
        pool geometry is the caller's job
        (RootControlEngine.swap_in_pages)."""
        if not pages:
            raise ValueError("kv swap batch must not be empty")
        frag_bytes = self.chunk * 4  # int32 words carry 4 payload bytes
        for p, _ in pages:
            if int(p) < 0:
                raise ValueError(f"kv page id must be >= 0, got {p}")
        for j, (page, payload) in enumerate(pages):
            blob = bytes(payload)
            frags = [
                blob[off : off + frag_bytes]
                for off in range(0, max(1, len(blob)), frag_bytes)
            ]
            for idx, frag in enumerate(frags):
                flags = 0
                if idx == len(frags) - 1:
                    flags |= 1
                    if j == len(pages) - 1:
                        flags |= 2
                pad = (-len(frag)) % 4
                words = np.frombuffer(frag + b"\0" * pad, np.uint8).view(
                    np.int32
                )
                self._send(OP_KV_SWAP, flags, len(frag), int(page), words)

    def recv(self) -> np.ndarray:
        faults.fire("plane.recv")  # chaos harness; no-op unarmed
        pkt = self._bcast(np.zeros(self._size, np.int32))
        self.validate(pkt)
        return pkt

    @staticmethod
    def validate(pkt: np.ndarray) -> None:
        """Packet integrity gate, run on every recv BEFORE the op switch:
        a torn packet or a version-skewed root becomes a classified
        :class:`ReplayError` (pre-dispatch — no collective was entered on
        it), not an "unknown control op" crash burning a restart."""
        if len(pkt) < ControlPlane.HEADER:
            raise ReplayError(
                f"control packet truncated: {len(pkt)} words < header "
                f"{ControlPlane.HEADER}"
            )
        if int(pkt[0]) != PACKET_MAGIC:
            raise ReplayError(
                f"control packet magic mismatch: got 0x{int(pkt[0]) & 0xFFFFFFFF:08X}, "
                f"want 0x{PACKET_MAGIC:08X} (torn packet, or a peer that is "
                "not a dllama control plane)"
            )
        if int(pkt[1]) != PROTOCOL_VERSION:
            raise ReplayError(
                f"control packet protocol version {int(pkt[1])} != "
                f"{PROTOCOL_VERSION}: root and worker binaries are skewed "
                "(finish the rolling restart before serving)"
            )


class RootControlEngine:
    """Engine proxy for process 0: broadcasts the control packet, then makes
    the identical engine call the workers will replay — the analogue of
    RootLlmInference::forward's writeAll-then-forward (src/app.cpp:198-209).
    """

    def __init__(self, engine, plane: ControlPlane):
        self._engine = engine
        self._plane = plane

    def __getattr__(self, name):  # stats, config, lane_logits, ...
        return getattr(self._engine, name)

    def grammar_attach(self, rf: dict):
        """Grammar attach on a pod: compile + install ROOT-side FIRST
        (a schema that cannot compile or fit must die with zero packets
        out — the pod-deadlock rule), then broadcast the canonical JSON
        so every worker compiles the identical automaton against its own
        tokenizer table and lands it at the same slab base (the op
        stream is ordered, so the deterministic allocators agree)."""
        import json as _json

        from ..grammar.automaton import validate_response_format

        canon = validate_response_format(rf)
        handle = self._engine.grammar_attach(rf)
        # ORDER-PRESERVING serialization (no sort_keys): property
        # declaration order is semantic (keys emit in that order) — a
        # sorted broadcast would have workers compile a DIFFERENT
        # automaton at the same slab base, the silent-desync class the
        # protocol version exists to prevent
        self._plane.send_grammar(_json.dumps(canon).encode())
        return handle

    def grammar_detach(self, key: str) -> None:
        """Detach on a pod: root-side release FIRST — the slab detach is
        host-only bookkeeping (no collective to keep in lockstep), so a
        key the engine rejects dies with zero packets on the wire (the
        pod-deadlock rule; dlint's ``protocol`` check pins the order)."""
        self._engine.grammar_detach(key)
        self._plane.send_grammar(str(key).encode(), detach=True)

    def prefill_chunk(
        self, lane: int, chunk, start_pos: int,
        temp: float = 0.0, topp: float | None = None, seed: int = 0,
        g_state: int = 0,
    ):
        if topp is None:  # byte-identical default on packet AND root call
            from ..runtime.engine import DEFAULT_TOPP as topp
        # validate BEFORE broadcasting: every packet must pair with exactly
        # one root-side compute, or workers dispatch collective programs the
        # root never runs and the pod deadlocks. Empty chunks send 0 packets;
        # chunks over plane.chunk split into >1; chunks over the engine's
        # bucket make the root raise after the packet went out.
        limit = min(self._plane.chunk, self._engine.max_chunk())
        if not 1 <= len(chunk) <= limit:
            raise ValueError(
                f"prefill chunk of {len(chunk)} outside [1, {limit}] "
                f"(plane packet capacity {self._plane.chunk}, engine bucket "
                f"{self._engine.max_chunk()}); size ControlPlane(chunk=...) "
                f">= engine.max_chunk()"
            )
        self._plane.send_prefill(lane, list(chunk), start_pos, temp, topp,
                                 seed, g_state=g_state)
        return self._engine.prefill_chunk(
            lane, list(chunk), start_pos, temp=temp, topp=topp, seed=seed,
            g_state=g_state
        )

    def prefill(
        self, lane: int, tokens, start_pos: int = 0,
        temp: float = 0.0, topp: float | None = None, seed: int = 0,
    ):
        if topp is None:  # byte-identical default on packet AND root call
            from ..runtime.engine import DEFAULT_TOPP as topp
        # one packet, then the matching compute, per chunk: workers replay
        # each packet with a blocking engine call, so broadcasting the whole
        # prompt up front would deadlock the pod on prompts > plane.chunk
        # (root stuck in the next broadcast, worker stuck in collectives the
        # root never dispatched)
        tokens = list(tokens)
        if not tokens:
            # same error the inner engine raises — before zero packets go out
            raise ValueError("prefill needs at least one token (empty prompt)")
        chunk = self._plane.chunk
        out = None
        for off in range(0, len(tokens), chunk):
            part = tokens[off : off + chunk]
            self._plane.send_prefill(lane, part, start_pos + off, temp, topp, seed)
            out = self._engine.prefill(
                lane, part, start_pos=start_pos + off,
                temp=temp, topp=topp, seed=seed,
            )
        return out

    def _normalize_sampling(self, temps, topps, seeds):
        """Packet and root-side engine call must carry byte-identical
        sampling values (workers replay from the packet) — one place owns
        the defaults for every op type."""
        from ..runtime.engine import DEFAULT_TOPP

        n = self._engine.n_lanes
        return (
            np.zeros(n, np.float32) if temps is None else np.asarray(temps, np.float32),
            np.full(n, DEFAULT_TOPP, np.float32) if topps is None else np.asarray(topps, np.float32),
            np.zeros(n, np.uint32) if seeds is None else np.asarray(seeds, np.uint32),
        )

    def _check_lane_vectors(self, *vecs) -> None:
        """Pre-broadcast shape validation for the per-lane packet vectors
        of the plain decode-family ops (the pipelined/fused families run
        the engine's own ``check_*_dispatch`` set): a ragged or mis-sized
        vector must die with ZERO packets out, not in the root's engine
        call with every worker already inside the collective — the
        pod-deadlock rule, machine-checked by dlint's ``protocol``
        check."""
        n = self._engine.n_lanes
        for v in vecs:
            if v is not None and len(v) != n:
                raise ValueError(
                    f"per-lane packet vector of length {len(v)} != "
                    f"n_lanes {n}: decode-family packets carry exactly "
                    "one entry per lane"
                )

    def decode(self, tokens, positions, temps=None, topps=None, seeds=None,
               want_logits: bool = True, g_states=None):
        temps, topps, seeds = self._normalize_sampling(temps, topps, seeds)
        self._check_lane_vectors(tokens, positions, temps, topps, seeds,
                                 g_states)
        self._plane.send_decode(
            np.asarray(tokens, np.int32), np.asarray(positions, np.int32),
            temps, topps, seeds, want_logits=want_logits,
            g_states=g_states,
        )
        return self._engine.decode(
            tokens, positions, temps, topps, seeds, want_logits=want_logits,
            g_states=g_states,
        )

    def decode_pipelined(
        self, positions, temps=None, topps=None, seeds=None, tokens=None,
        g_states=None,
    ):
        """Pipelined dispatch on a pod: the packet goes out first, then the
        root enqueues its own half of the async chain. Consume/flush are
        host-only (they dispatch no device program, so there is nothing to
        replay) and forward through __getattr__; workers bound their own
        rings from the depth in the header."""
        # ring-full/missing-carry/bad-reseed-position must raise BEFORE the
        # packet goes out: a broadcast with no matching root-side compute
        # desyncs the pod
        self._engine.check_pipelined_dispatch(tokens is not None, positions,
                                              g_states)
        # materialize the default grammar vector NOW: packet and root-side
        # compute must carry byte-identical values (the sampling rule)
        g_states = self._engine._g_vec(g_states, tokens is not None)
        temps, topps, seeds = self._normalize_sampling(temps, topps, seeds)
        self._plane.send_decode_pipelined(
            None if tokens is None else np.asarray(tokens, np.int32),
            np.asarray(positions, np.int32), temps, topps, seeds,
            depth=getattr(self._engine, "pipeline_depth", 2),
            g_states=g_states,
        )
        return self._engine.decode_pipelined(
            positions, temps, topps, seeds, tokens=tokens,
            g_states=g_states,
        )

    def _check_fused_chunk(self, chunk, p_topp):
        """ONE copy of the fused-admission chunk validation + topp default
        (both fused entry points — plain and spec-carrying — must enforce
        the identical pre-broadcast rule or the pod-deadlock guarantee
        drifts between them). Returns the resolved p_topp."""
        if p_topp is None:  # byte-identical default on packet AND root call
            from ..runtime.engine import DEFAULT_TOPP as p_topp
        limit = min(self._plane.chunk, self._engine.max_chunk())
        if chunk is None or not 1 <= len(chunk) <= limit:
            raise ValueError(
                f"fused prefill chunk of {0 if chunk is None else len(chunk)} "
                f"outside [1, {limit}] (plane packet capacity "
                f"{self._plane.chunk}, engine bucket {self._engine.max_chunk()})"
            )
        return p_topp

    def decode_prefill_fused(
        self, positions, temps=None, topps=None, seeds=None,
        p_lane: int = 0, chunk=None, p_start: int = 0, p_temp: float = 0.0,
        p_topp: float | None = None, p_seed: int = 0, tokens=None,
        g_states=None, p_g: int = 0,
    ):
        """Stall-free admission on a pod: the fused prefill+decode packet
        goes out first (bucket implied by the chunk length, prefill header
        in its own slot), then the root enqueues its own half — every
        process dispatches the identical per-bucket fused program. The
        multihost prefill path for a mid-serving admission IS this op:
        no separate OP_PREFILL round is broadcast."""
        # validate BEFORE broadcasting (the prefill_chunk rule): every
        # packet must pair with exactly one root-side compute or the pod
        # deadlocks on mismatched collectives. The packet-capacity check
        # plus the FULL engine validation set (chunk bounds, seq_len
        # overflow, ring-full, missing carry) — any of those raising after
        # the broadcast would leave worker rings permanently diverged
        p_topp = self._check_fused_chunk(chunk, p_topp)
        self._engine.check_fused_dispatch(
            list(chunk), p_start, tokens is not None, positions, g_states
        )
        g_states = self._engine._g_vec(g_states, tokens is not None)
        temps, topps, seeds = self._normalize_sampling(temps, topps, seeds)
        self._plane.send_decode_prefill_fused(
            None if tokens is None else np.asarray(tokens, np.int32),
            np.asarray(positions, np.int32), temps, topps, seeds,
            depth=getattr(self._engine, "pipeline_depth", 2),
            p_lane=p_lane, chunk=list(chunk), p_start=p_start,
            p_temp=p_temp, p_topp=p_topp, p_seed=p_seed,
            g_states=g_states, p_g=p_g,
        )
        return self._engine.decode_prefill_fused(
            positions, temps, topps, seeds,
            p_lane=p_lane, chunk=list(chunk), p_start=p_start,
            p_temp=p_temp, p_topp=p_topp, p_seed=p_seed, tokens=tokens,
            g_states=g_states, p_g=p_g,
        )

    def decode_spec_pipelined(
        self, positions, drafts, draft_len, temps=None, topps=None,
        seeds=None, tokens=None, g_states=None,
    ):
        """Zero-flush speculation on a pod: the spec-verify packet goes
        out first (drafts + lengths in their own slots), then the root
        enqueues its own half of the async chain — every process
        dispatches the identical verify program with the same bounded
        lag. The FULL engine validation set (draft shape, ring-full,
        missing carry) runs BEFORE the broadcast: a packet whose
        root-side compute raises leaves worker rings permanently
        diverged (the pod-deadlock rule)."""
        drafts = np.asarray(drafts, np.int32)
        self._engine.check_spec_pipelined_dispatch(
            drafts, tokens is not None, positions, g_states
        )
        g_states = self._engine._g_vec(g_states, tokens is not None)
        temps, topps, seeds = self._normalize_sampling(temps, topps, seeds)
        self._plane.send_decode_spec_pipelined(
            None if tokens is None else np.asarray(tokens, np.int32),
            np.asarray(positions, np.int32), temps, topps, seeds,
            depth=getattr(self._engine, "pipeline_depth", 2),
            drafts=drafts, draft_len=np.asarray(draft_len, np.int32),
            g_states=g_states,
        )
        return self._engine.decode_spec_pipelined(
            positions, drafts, draft_len, temps, topps, seeds,
            tokens=tokens, g_states=g_states,
        )

    def decode_spec_prefill_fused(
        self, positions, drafts, draft_len, temps=None, topps=None,
        seeds=None, p_lane: int = 0, chunk=None, p_start: int = 0,
        p_temp: float = 0.0, p_topp: float | None = None, p_seed: int = 0,
        tokens=None, g_states=None, p_g: int = 0,
    ):
        """The full composition on a pod: an admitting chunk and a spec
        verify step replay as ONE packet. Validation is the union of the
        fused-prefill and spec-pipelined pre-broadcast sets — all of it
        BEFORE the packet goes out."""
        p_topp = self._check_fused_chunk(chunk, p_topp)
        drafts = np.asarray(drafts, np.int32)
        self._engine.check_spec_drafts(drafts)
        self._engine.check_fused_dispatch(
            list(chunk), p_start, tokens is not None, positions, g_states
        )
        g_states = self._engine._g_vec(g_states, tokens is not None)
        temps, topps, seeds = self._normalize_sampling(temps, topps, seeds)
        self._plane.send_decode_spec_prefill_fused(
            None if tokens is None else np.asarray(tokens, np.int32),
            np.asarray(positions, np.int32), temps, topps, seeds,
            depth=getattr(self._engine, "pipeline_depth", 2),
            drafts=drafts, draft_len=np.asarray(draft_len, np.int32),
            p_lane=p_lane, chunk=list(chunk), p_start=p_start,
            p_temp=p_temp, p_topp=p_topp, p_seed=p_seed,
            g_states=g_states, p_g=p_g,
        )
        return self._engine.decode_spec_prefill_fused(
            positions, drafts, draft_len, temps, topps, seeds,
            p_lane=p_lane, chunk=list(chunk), p_start=p_start,
            p_temp=p_temp, p_topp=p_topp, p_seed=p_seed, tokens=tokens,
            g_states=g_states, p_g=p_g,
        )

    def pipeline_flush(self) -> int:
        """Chain end/abort on a pod: tell the workers so they drain their
        own rings too — the root's drain happens through its local consume
        calls (no packets), so without this broadcast a worker would carry
        stale in-flight steps (pinned device buffers) across chains and
        into the post-warmup stats reset. Flush replays no device program;
        the packet broadcast itself is the only collective involved."""
        self._plane.send_pipeline_flush()
        return self._engine.pipeline_flush()

    def pipeline_abort(self) -> int:
        """Containment on a pod root (scheduler `_contain_engine_failure`):
        the workers must drop their rings and carries too, or they stay
        permanently diverged from the root's freshly aborted chain and
        every later pipelined packet fails their pre-dispatch validation
        — burning supervised restarts until the pod dies. The flush
        packet is the op workers already honor (their drain is their own
        harmless readback); the root side then aborts WITHOUT consuming
        (its readbacks would re-raise the failure being contained).
        Without this override, __getattr__ would forward to the inner
        engine and abort the root ring silently."""
        self._plane.send_pipeline_flush()
        return self._engine.pipeline_abort()

    def decode_spec(
        self, tokens, drafts, draft_len, positions,
        temps=None, topps=None, seeds=None, g_states=None,
    ):
        temps, topps, seeds = self._normalize_sampling(temps, topps, seeds)
        self._check_lane_vectors(tokens, positions, temps, topps, seeds,
                                 drafts, draft_len, g_states)
        self._plane.send_decode_spec(
            np.asarray(tokens, np.int32), np.asarray(drafts, np.int32),
            np.asarray(draft_len, np.int32), np.asarray(positions, np.int32),
            temps, topps, seeds, g_states=g_states,
        )
        return self._engine.decode_spec(
            tokens, drafts, draft_len, positions, temps, topps, seeds,
            g_states=g_states,
        )

    def decode_multi(
        self, tokens, positions, temps=None, topps=None, seeds=None,
        h: int = 8, g_states=None,
    ):
        temps, topps, seeds = self._normalize_sampling(temps, topps, seeds)
        self._check_lane_vectors(tokens, positions, temps, topps, seeds,
                                 g_states)
        if h < 1:
            raise ValueError(f"decode_multi horizon h={h} must be >= 1")
        self._plane.send_decode_multi(
            np.asarray(tokens, np.int32), np.asarray(positions, np.int32),
            temps, topps, seeds, h, g_states=g_states,
        )
        return self._engine.decode_multi(
            tokens, positions, temps, topps, seeds, h, g_states=g_states
        )

    def measured_sync_stats(self, steps: int = 4) -> dict:
        """Disabled on pod roots: the probe's direct decode calls would not
        be broadcast to workers, so the SPMD program would deadlock waiting
        for their matching dispatch. (Without this override __getattr__
        would happily forward to the inner engine.)"""
        del steps
        return {}

    def stop_workers(self) -> None:
        self._plane.send_stop()

    def reset_worker_stats(self) -> None:
        """Broadcast a stats reset so worker counters drop warmup traffic
        (the root restores its own via ``stats.preserved()``)."""
        self._plane.send_stats_reset()

    def copy_lane(self, src: int, dst: int,
                  prefix_len: int | None = None) -> None:
        """Prefix caching on a pod: every process must dispatch the same
        cache-copy program (the cache is sharded over the global mesh), so
        the operands ride a control packet before the root-side call —
        __getattr__ forwarding alone would desync the workers."""
        # the engine's own refusals (paged layout, lane bounds via the
        # cache index) must fire with zero packets out — the pod-deadlock
        # rule (dlint `protocol`). Paged refusal BEFORE the no-op
        # short-circuit, matching engine.copy_lane's guard order exactly
        # (src==dst on a paged engine raises on both surfaces)
        if getattr(self._engine, "kvpool", None) is not None:
            raise RuntimeError(
                "copy_lane is the contiguous layout's primitive; a paged "
                "engine shares prefix pages by refcount via paged_admit"
            )
        if src == dst or prefix_len == 0:
            return  # the engine-side short-circuit, BEFORE any packet
        n = self._engine.n_lanes
        if not (0 <= src < n and 0 <= dst < n):
            raise ValueError(
                f"copy_lane {src} -> {dst} outside lane range [0, {n})"
            )
        self._plane.send_copy_lane(src, dst)
        self._engine.copy_lane(src, dst)

    def apply_paged_admit(self, lane: int, row, copies) -> None:
        """Device half of a paged table update on a pod: broadcast the
        row + COW copies (OP_KV_TABLE) so every process dispatches the
        same page-copy program and lands the same table leaf —
        __getattr__ forwarding alone would desync the workers (the pool
        arrays are sharded over the global mesh). warmup_engine drives
        this directly to pre-compile the COW program."""
        self._plane.send_kv_table(lane, row, copies)
        self._engine.apply_paged_admit(lane, row, copies)

    def paged_admit(self, lane: int, tokens, reserve_tokens: int,
                    min_share_tokens: int = 1) -> int:
        """Paged admission on a pod: the pool bookkeeping (free list,
        refcounts, prefix tree) is HOST state and runs root-only, BEFORE
        the broadcast — so :class:`~..runtime.kvpool.PoolExhausted` (the
        admission shed) raises with no packet on the wire. Only the
        device half replays: the COW page copies and the new table row
        ride OP_KV_TABLE so every process's replicated table leaf (and
        the compiled gathers through it) stay byte-identical. Tiered
        residency keeps the engine's ordering: staged swap-outs drain
        root-locally (a device READ — nothing to replay), host-tier
        hits broadcast as ONE OP_KV_SWAP batch, then the table/COW
        packet follows."""
        start, blocks, copies, swapins = self._engine.kvpool.admit(
            lane, list(tokens), reserve_tokens, min_share_tokens
        )
        self._engine.drain_kv_swapouts()
        if swapins:
            self.swap_in_pages([p for p, _ in swapins],
                               [b for _, b in swapins])
        self.apply_paged_admit(
            lane, self._engine._paged_table_row(blocks), copies
        )
        return start

    def paged_finish(self, lane: int, park: bool = True) -> None:
        """Paged release on a pod: host bookkeeping (park/free) root-only
        and pre-broadcast, then the all-unmapped table row replays on
        every process — no packet at all when the lane never mapped
        anything (the exhaustion-shed reject path), matching the
        single-process skip so workers stay in step. LRU-overflow
        swap-outs drain root-locally (a device read, no packet)."""
        held = self._engine.kvpool.finish(lane, park=park)
        self._engine.drain_kv_swapouts()
        if held:
            self.apply_paged_admit(
                lane, self._engine._paged_table_row([]), []
            )

    def paged_reset(self) -> None:
        """Paged containment on a pod: drop the root's pool bookkeeping
        (host-only), then have every process unmap every lane — lane -1
        is the reset form of OP_KV_TABLE."""
        self._engine.kvpool.reset()
        self._plane.send_kv_table(-1, [], [])
        self._engine.paged_unmap_all()

    def import_kv_page(self, page: int, payload: bytes) -> None:
        """Disagg page import on a pod: validate ROOT-side first — a
        non-paged engine or a geometry-skewed payload must die with zero
        packets out (the pod-deadlock rule) — then broadcast the bytes
        (OP_KV_PAGES) so every process dispatches the same page-write
        program and the sharded pool arrays stay byte-identical.
        warmup_engine drives this to pre-compile the write program."""
        if getattr(self._engine, "kvpool", None) is None:
            raise RuntimeError("import_kv_page needs a paged engine")
        shape, dtype = self._engine._page_leaf_geometry()
        half = int(np.prod(shape)) * dtype.itemsize
        if len(payload) != 2 * half:
            raise ValueError(
                f"kv page payload is {len(payload)} bytes, expected "
                f"{2 * half} for page geometry {tuple(shape)} {dtype}"
            )
        self._plane.send_kv_pages([(page, payload)])
        self._engine.import_kv_page(page, payload)

    def swap_in_pages(self, pages, payloads) -> None:
        """Host-tier swap-in on a pod: validate ROOT-side first — a
        non-paged engine, a count mismatch or a geometry-skewed payload
        must die with zero packets out (the pod-deadlock rule) — then
        broadcast the whole batch (OP_KV_SWAP) so every process
        dispatches the same warmed batched scatter program and the
        sharded pool arrays stay byte-identical. warmup_engine reaches
        this through the engine facade to pre-compile the programs on
        every process."""
        if getattr(self._engine, "kvpool", None) is None:
            raise RuntimeError("swap_in_pages needs a paged engine")
        if len(pages) != len(payloads):
            raise ValueError(
                f"swap_in_pages: {len(pages)} pages vs "
                f"{len(payloads)} payloads"
            )
        if not pages:
            return
        shape, dtype = self._engine._page_leaf_geometry()
        half = int(np.prod(shape)) * dtype.itemsize
        for i, payload in enumerate(payloads):
            if len(payload) != 2 * half:
                raise ValueError(
                    f"swap payload {i} is {len(payload)} bytes, expected "
                    f"{2 * half} for page geometry {tuple(shape)} {dtype}"
                )
        self._plane.send_kv_swap(list(zip(pages, payloads)))
        self._engine.swap_in_pages(pages, payloads)


def worker_loop(engine, plane: ControlPlane, on_replay=None) -> None:
    """Replay root-broadcast engine calls until OP_STOP — the SPMD twin of
    runWorkerApp's poll-forward loop (src/app.cpp:405-464). Every process
    (root included, via RootControlEngine) executes the same compiled steps
    in the same order, so the global-mesh collectives line up.

    ``on_replay`` (if given) is called after each successfully replayed
    packet — ``worker_serve`` uses it to refresh its restart budget."""
    gram_buf = bytearray()  # OP_GRAMMAR fragment accumulator
    page_buf = bytearray()  # OP_KV_PAGES fragment accumulator
    swap_buf = bytearray()  # OP_KV_SWAP fragment accumulator (one page)
    swap_batch: list = []  # OP_KV_SWAP completed (page, payload) pairs
    while True:
        pkt = plane.recv()
        # header: [magic, version, op, lane, n, start_pos] — magic/version
        # already validated by plane.recv()
        op, lane, n, start_pos = (int(x) for x in pkt[2:6])
        if op == OP_STOP:
            return
        if op == OP_PREFILL:
            engine.prefill(
                lane,
                [int(t) for t in plane.slot(pkt, 0, n)],
                start_pos=start_pos,
                temp=float(plane.slot(pkt, 1, 1).view(np.float32)[0]),
                topp=float(plane.slot(pkt, 2, 1).view(np.float32)[0]),
                seed=int(plane.slot(pkt, 3, 1).view(np.uint32)[0]),
                g_state=int(plane.slot(pkt, 4, 1)[0]),
            )
        elif op == OP_DECODE:
            engine.decode(
                plane.slot(pkt, 0, n),
                plane.slot(pkt, 1, n),
                plane.slot(pkt, 2, n).view(np.float32),
                plane.slot(pkt, 3, n).view(np.float32),
                plane.slot(pkt, 4, n).view(np.uint32),
                want_logits=bool(lane),  # same compiled program as the root
                g_states=plane.slot(pkt, 5, n),
            )
        elif op == OP_DECODE_PIPELINED:
            # feed flag rides `lane`, ring depth rides `start_pos`. The
            # worker mirrors the root's bounded lag: consume (its own
            # harmless readback) only when its ring would exceed the bound,
            # and drop the whole chain when the root reseeds after a flush.
            if lane:
                engine.pipeline_flush(count=False)  # reseed: same lagged drain
            elif engine.pipeline_inflight() >= max(1, start_pos):
                engine.pipeline_consume()
            engine.decode_pipelined(
                plane.slot(pkt, 1, n),
                plane.slot(pkt, 2, n).view(np.float32),
                plane.slot(pkt, 3, n).view(np.float32),
                plane.slot(pkt, 4, n).view(np.uint32),
                tokens=plane.slot(pkt, 0, n) if lane else None,
                g_states=plane.slot(pkt, 5, n),
            )
        elif op == OP_DECODE_PREFILL_FUSED:
            # the pipelined replay rules (feed flag in `lane`, ring depth
            # in `start_pos`, bounded-lag consume) plus the prompt chunk +
            # prefill header riding slots 5/6 and the grammar states in
            # slot 7 — the worker dispatches the same per-bucket fused
            # program the root did
            if lane:
                engine.pipeline_flush(count=False)  # reseed: same lagged drain
            elif engine.pipeline_inflight() >= max(1, start_pos):
                engine.pipeline_consume()
            phdr = plane.slot(pkt, 6, 7)
            engine.decode_prefill_fused(
                plane.slot(pkt, 1, n),
                plane.slot(pkt, 2, n).view(np.float32),
                plane.slot(pkt, 3, n).view(np.float32),
                plane.slot(pkt, 4, n).view(np.uint32),
                p_lane=int(phdr[0]),
                chunk=[int(t) for t in plane.slot(pkt, 5, int(phdr[2]))],
                p_start=int(phdr[1]),
                p_temp=float(phdr[3:4].view(np.float32)[0]),
                p_topp=float(phdr[4:5].view(np.float32)[0]),
                p_seed=int(phdr[5:6].view(np.uint32)[0]),
                tokens=plane.slot(pkt, 0, n) if lane else None,
                g_states=plane.slot(pkt, 7, n),
                p_g=int(phdr[6]),
            )
        elif op == OP_DECODE_SPEC_PIPELINED:
            # the pipelined replay rules (feed flag in `lane`, ring depth
            # in `start_pos`, bounded-lag consume) with the in-chain
            # drafts + lengths riding slots 5/6, grammar states slot 7
            if lane:
                engine.pipeline_flush(count=False)  # reseed: same lagged drain
            elif engine.pipeline_inflight() >= max(1, start_pos):
                engine.pipeline_consume()
            k1 = engine.SPEC_DRAFT + 1
            engine.decode_spec_pipelined(
                plane.slot(pkt, 1, n),
                plane.slot(pkt, 5, n * k1).reshape(n, k1),
                plane.slot(pkt, 6, n),
                plane.slot(pkt, 2, n).view(np.float32),
                plane.slot(pkt, 3, n).view(np.float32),
                plane.slot(pkt, 4, n).view(np.uint32),
                tokens=plane.slot(pkt, 0, n) if lane else None,
                g_states=plane.slot(pkt, 7, n),
            )
        elif op == OP_DECODE_SPEC_PREFILL_FUSED:
            # the SPEC_PIPELINED rules plus the chunk + prefill header in
            # slots 7/8 and the grammar states in slot 9 — chunk and spec
            # verify replay as one program
            if lane:
                engine.pipeline_flush(count=False)  # reseed: same lagged drain
            elif engine.pipeline_inflight() >= max(1, start_pos):
                engine.pipeline_consume()
            k1 = engine.SPEC_DRAFT + 1
            phdr = plane.slot(pkt, 8, 7)
            engine.decode_spec_prefill_fused(
                plane.slot(pkt, 1, n),
                plane.slot(pkt, 5, n * k1).reshape(n, k1),
                plane.slot(pkt, 6, n),
                plane.slot(pkt, 2, n).view(np.float32),
                plane.slot(pkt, 3, n).view(np.float32),
                plane.slot(pkt, 4, n).view(np.uint32),
                p_lane=int(phdr[0]),
                chunk=[int(t) for t in plane.slot(pkt, 7, int(phdr[2]))],
                p_start=int(phdr[1]),
                p_temp=float(phdr[3:4].view(np.float32)[0]),
                p_topp=float(phdr[4:5].view(np.float32)[0]),
                p_seed=int(phdr[5:6].view(np.uint32)[0]),
                tokens=plane.slot(pkt, 0, n) if lane else None,
                g_states=plane.slot(pkt, 9, n),
                p_g=int(phdr[6]),
            )
        elif op == OP_DECODE_SPEC:
            k = engine.SPEC_DRAFT
            engine.decode_spec(
                plane.slot(pkt, 0, n),
                plane.slot(pkt, 5, n * k).reshape(n, k),
                plane.slot(pkt, 6, n),
                plane.slot(pkt, 1, n),
                plane.slot(pkt, 2, n).view(np.float32),
                plane.slot(pkt, 3, n).view(np.float32),
                plane.slot(pkt, 4, n).view(np.uint32),
                g_states=plane.slot(pkt, 7, n),
            )
        elif op == OP_DECODE_MULTI:
            engine.decode_multi(
                plane.slot(pkt, 0, n),
                plane.slot(pkt, 1, n),
                plane.slot(pkt, 2, n).view(np.float32),
                plane.slot(pkt, 3, n).view(np.float32),
                plane.slot(pkt, 4, n).view(np.uint32),
                start_pos,  # horizon h rides the start_pos header field
                g_states=plane.slot(pkt, 5, n),
            )
        elif op == OP_GRAMMAR:
            # grammar attach/detach: accumulate schema-byte fragments and
            # act on the final one. Compiling is deterministic, so this
            # worker's slab lands the automaton at the root's base. A
            # worker without grammar_init (config skew: root on, worker
            # off) raises the ValueError the attach path defines —
            # request-scoped on the root, a restartable replay error here.
            frag = plane.slot(pkt, 0, (n + 3) // 4).view(np.uint8)[:n]
            gram_buf += frag.tobytes()
            if lane & 1:  # final fragment
                blob = bytes(gram_buf)
                gram_buf = bytearray()
                if lane & 2:
                    engine.grammar_detach(blob.decode())
                else:
                    import json as _json

                    engine.grammar_attach(_json.loads(blob))
        elif op == OP_PIPELINE_FLUSH:
            # the root ended/aborted a pipelined chain: drop this worker's
            # lagged ring + carry so no stale step survives into the next
            # chain (or into a post-warmup stats reset). count=False: the
            # worker ring lags the root by design, so holding steps at a
            # CLEAN chain end is expected — counting it would read as
            # constant aborts in worker-side stats
            engine.pipeline_flush(count=False)
        elif op == OP_STATS_RESET:
            # warmup traffic must not pollute worker-side counters either
            # (the root restores its own via stats.preserved())
            engine.stats.reset()
        elif op == OP_COPY_LANE:
            # dlint: ok[device-affinity] the worker replay loop IS this process's batching thread — every device call replays here in root order
            engine.copy_lane(lane, start_pos)  # src, dst ride the header
        elif op == OP_KV_TABLE:
            # paged KV table update: row length rides n, COW pair count
            # rides start_pos, lane -1 = unmap everything (containment).
            # A non-paged engine receiving this is a config skew (root
            # and worker disagree on --paged-kv) — classified
            # pre-dispatch, no collective was entered on it
            if getattr(engine, "kvpool", None) is None:
                raise ReplayError(
                    "OP_KV_TABLE on a non-paged engine: root and worker "
                    "--paged-kv flags are skewed"
                )
            if lane < 0:
                # dlint: ok[device-affinity] worker replay loop = this process's batching thread
                engine.paged_unmap_all()
            else:
                if n != engine.kvpool.blocks_per_lane:
                    # geometry skew (root and worker disagree on
                    # --kv-page-size/--kv-pool-pages): classified
                    # pre-apply like the paged/non-paged skew above,
                    # instead of an unclassified broadcast-shape crash
                    # that burns a worker restart per admission
                    raise ReplayError(
                        f"OP_KV_TABLE row of {n} entries vs this "
                        f"worker's {engine.kvpool.blocks_per_lane} "
                        "blocks/lane: root and worker paged-KV "
                        "geometry flags are skewed"
                    )
                pairs = plane.slot(pkt, 1, 2 * start_pos)
                # dlint: ok[device-affinity] worker replay loop = this process's batching thread
                engine.apply_paged_admit(
                    lane,
                    plane.slot(pkt, 0, n).copy(),
                    list(zip(
                        (int(s) for s in pairs[0::2]),
                        (int(d) for d in pairs[1::2]),
                    )),
                )
        elif op == OP_KV_PAGES:
            # disagg page import: payload-byte fragments accumulate (the
            # op stream is ordered and one page's fragments are
            # contiguous); the destination page id rides start_pos. A
            # non-paged engine receiving this is a config skew — root
            # and worker disagree on --paged-kv — classified
            # pre-dispatch, no collective was entered on it
            if getattr(engine, "kvpool", None) is None:
                raise ReplayError(
                    "OP_KV_PAGES on a non-paged engine: root and worker "
                    "--paged-kv flags are skewed"
                )
            frag = plane.slot(pkt, 0, (n + 3) // 4).view(np.uint8)[:n]
            page_buf += frag.tobytes()
            if lane & 1:  # final fragment of this page's payload
                blob = bytes(page_buf)
                page_buf = bytearray()
                try:
                    # dlint: ok[device-affinity] worker replay loop = this process's batching thread
                    engine.import_kv_page(start_pos, blob)
                except ValueError as e:
                    # geometry skew (root and worker disagree on the
                    # page shape/dtype): classified like OP_KV_TABLE's
                    # row-width skew instead of burning a restart
                    raise ReplayError(
                        f"OP_KV_PAGES payload rejected: {e} — root and "
                        "worker paged-KV geometry flags are skewed"
                    ) from e
        elif op == OP_KV_SWAP:
            # host-tier swap-in replay: payload fragments accumulate per
            # page (flag bit 0 = final fragment of this page), completed
            # pages accumulate per batch (bit 1 = final page of the
            # batch) — then ONE batched scatter dispatches, matching the
            # root's program count dispatch-for-dispatch. A non-paged
            # engine receiving this is a config skew — classified
            # pre-dispatch, no collective was entered on it
            if getattr(engine, "kvpool", None) is None:
                raise ReplayError(
                    "OP_KV_SWAP on a non-paged engine: root and worker "
                    "--paged-kv flags are skewed"
                )
            frag = plane.slot(pkt, 0, (n + 3) // 4).view(np.uint8)[:n]
            swap_buf += frag.tobytes()
            if lane & 1:  # final fragment of this page's payload
                swap_batch.append((start_pos, bytes(swap_buf)))
                swap_buf = bytearray()
            if lane & 2:  # final page of the batch: dispatch as one
                batch = swap_batch
                swap_batch = []
                try:
                    # dlint: ok[device-affinity] worker replay loop = this process's batching thread
                    engine.swap_in_pages(
                        [p for p, _ in batch], [b for _, b in batch]
                    )
                except ValueError as e:
                    # geometry skew (root and worker disagree on the
                    # page shape/dtype): classified like OP_KV_PAGES'
                    # payload skew instead of burning a restart
                    raise ReplayError(
                        f"OP_KV_SWAP payload rejected: {e} — root and "
                        "worker paged-KV geometry flags are skewed"
                    ) from e
        else:
            # classified, pre-dispatch (no engine call was made for this
            # packet): worker_serve resubscribes without burning a restart
            raise ReplayError(f"unknown control op {op}")
        if on_replay is not None:
            on_replay()


def worker_serve(engine, plane: ControlPlane, max_restarts: int | None = 3,
                 healthy_window: int = 64, log=None) -> None:
    """Supervised worker: re-enter ``worker_loop`` after a replay error — the
    analogue of runWorkerApp's outer loop, which catches exceptions and
    re-``serve()``s instead of exiting (src/app.cpp:455-463). A worker that
    dies mid-collective cannot rejoin that collective, but a host-side replay
    failure (malformed packet, argument validation) should not take the pod
    process down: log, resubscribe to the control stream, and keep replaying.

    ``max_restarts`` is deliberately finite by default: an error raised AFTER
    the root dispatched its half of a collective leaves the pod desynced, and
    a worker that retries forever would turn that into a silent hang instead
    of a process death that jax.distributed's peer-failure detection surfaces.
    Bounded retries absorb pre-dispatch failures (the common, recoverable
    kind) while still crashing out of a persistent desync.

    The budget is a SLIDING WINDOW, not a lifetime total: after
    ``healthy_window`` consecutive successful replays the restart counter
    resets, so a long-lived worker absorbing an occasional transient error
    re-serves indefinitely like the reference's outer loop — while a
    persistent error (or a tight burst, the desync signature) still
    exhausts the budget within one window and raises.

    Classified :class:`ReplayError`\\ s (packet magic/version mismatch,
    unknown op — raised BEFORE any engine dispatch, so no collective was
    entered) do not burn the restart budget; they have their own, much
    larger storm bound. Every restart emits a structured JSON log event
    (telemetry/logs.py — ``worker_restart`` / ``worker_protocol_error``,
    greppable and pipeline-parsable like the root's request lines) and
    bumps ``engine.stats.worker_restarts`` / ``worker_replay_errors`` so
    worker health is a /stats read, not a stderr grep. ``log`` (optional
    callable) additionally receives a one-line human summary — the CLI
    passes its emoji logger."""
    restarts = 0
    healthy = 0
    protocol_errors = 0
    # a packet storm (every recv invalid) must still crash out eventually;
    # scale with the restart budget, never below a generous floor
    protocol_budget = max(64, (max_restarts or 0) * 16)
    stats = getattr(engine, "stats", None)

    def _count(field: str) -> None:
        if stats is not None:
            with stats.lock:
                setattr(stats, field, getattr(stats, field) + 1)

    def _replayed() -> None:
        nonlocal restarts, healthy, protocol_errors
        healthy += 1
        if healthy >= healthy_window:
            restarts = 0
            protocol_errors = 0
            healthy = 0

    while True:
        try:
            worker_loop(engine, plane, on_replay=_replayed)
            return
        except ReplayError as e:
            # pre-dispatch protocol failure: no engine call was made for
            # the bad packet, so no desync is possible — resubscribe
            # without burning the restart budget
            healthy = 0
            protocol_errors += 1
            _count("worker_replay_errors")
            log_event(
                "worker_protocol_error",
                error=str(e)[:200],
                protocol_errors=protocol_errors,
                protocol_budget=protocol_budget,
            )
            if log is not None:
                log(f"worker protocol error ({protocol_errors}): {e}")
            if protocol_errors > protocol_budget:
                raise
        except Exception as e:  # noqa: BLE001 — supervised restart boundary
            healthy = 0
            restarts += 1
            _count("worker_restarts")
            log_event(
                "worker_restart",
                error=f"{type(e).__name__}: {e}"[:200],
                restarts=restarts,
                max_restarts=max_restarts,
            )
            if log is not None:
                log(f"worker replay error (restart {restarts}): {e!r}")
            if max_restarts is not None and restarts > max_restarts:
                raise
