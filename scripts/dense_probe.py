"""Probe: dense bf16 matvec HBM utilization by shape on the real TPU.

Establishes the XLA roofline for decode matmuls (what the Pallas Q40 kernel
competes against) shape by shape, instead of the model-average number in
BENCH_r02 (which counted the never-streamed embedding table in read bytes).
"""

import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

HBM = 819.0

SHAPES = [
    # trimmed for tunnel-compile latency
    (1, 4096, 14336),
    (8, 4096, 14336),
    (1, 2048, 128256),
    (1, 2048, 8192),
]


def bench(m, d_in, d_out, reps=30):
    rng = np.random.default_rng(0)
    # two weights ping-ponged so we can chain x -> y -> x
    w1 = jnp.asarray(rng.standard_normal((d_in, d_out), np.float32), jnp.bfloat16)
    w2 = jnp.asarray(rng.standard_normal((d_out, d_in), np.float32), jnp.bfloat16)
    x = jnp.asarray(rng.standard_normal((m, d_in), np.float32), jnp.bfloat16)

    @jax.jit
    def chain(x):
        def body(_, x):
            y = jnp.dot(x, w1, preferred_element_type=jnp.float32)
            x2 = jnp.dot(y.astype(jnp.bfloat16), w2,
                         preferred_element_type=jnp.float32)
            return (x2 * 1e-4).astype(jnp.bfloat16)

        return jax.lax.fori_loop(0, reps, body, x)

    chain(x).block_until_ready()
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        chain(x).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    sec = best / reps / 2  # per single matmul
    gbs = d_in * d_out * 2 / sec / 1e9
    print(f"m={m:<4d} {d_in:>6d}x{d_out:<6d}  {sec * 1e6:8.1f} us  "
          f"{gbs:7.1f} GB/s ({gbs / HBM * 100:5.1f}% HBM)")


if __name__ == "__main__":
    print(f"device={jax.devices()[0].device_kind}")
    for m, d_in, d_out in SHAPES:
        bench(m, d_in, d_out)
