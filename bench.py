"""Benchmark: single-stream decode throughput of the flagship model on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: batch=1 greedy decode tokens/sec for a Llama-3.2-1B-shaped model with
Q40 weights at rest in HBM (int4+f16 scales, dequant-in-matmul Pallas kernel
— the same weight format the reference runs, src/nn/nn-quants.hpp:64-67) and
a 2048-token KV cache.

Timing is honest under async dispatch: the whole generation loop runs
device-side (lax.scan with the sampled token fed back), completion is forced
by fetching the produced tokens, and the reported rate is the MARGINAL rate
between a short and a long run — constant dispatch/transfer overheads cancel.

vs_baseline: ratio against the reference's best published single-device
number — Llama 2 7B on 1x RPi 4B at 1312.50 ms/token = 0.762 tok/s
(report.pdf Fig. 3, BASELINE.md). Caveat: model sizes differ (1B here vs 7B
there); the 7B/8-node figure (588 ms/token, 1.70 tok/s) is the distributed
headline this framework targets at scale.
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REFERENCE_SINGLE_DEVICE_TOK_S = 1000.0 / 1312.50  # report.pdf Fig. 3


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from __graft_entry__ import _flagship_config
    from distributed_llama_multiusers_tpu.models import (
        init_kv_cache,
        llama_forward,
        params_from_random,
    )
    from distributed_llama_multiusers_tpu.models.loader import quantize_params

    small = os.environ.get("GRAFT_SMALL") == "1"
    config = _flagship_config(small=small)
    # generate + quantize host-side; upload only the packed ~4.5-bit planes
    host = quantize_params(
        params_from_random(config, seed=0, dtype=jnp.bfloat16, to_device=False),
        to_device=False,
    )
    params = jax.tree.map(jax.device_put, host)

    def make_generate(n_steps):
        @partial(jax.jit, donate_argnums=(1,))
        def generate(params, cache, first_token, start_pos):
            def body(carry, _):
                tok, pos, cache = carry
                logits, cache = llama_forward(
                    config, params, tok[:, None], pos[:, None], cache
                )
                nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
                return (nxt, pos + 1, cache), nxt

            (_, _, cache), toks = jax.lax.scan(
                body,
                (first_token, start_pos, cache),
                None,
                length=n_steps,
            )
            return toks, cache

        return generate

    first = jnp.zeros((1,), jnp.int32)
    pos0 = jnp.zeros((1,), jnp.int32)

    def timed(n_steps, reps=3):
        gen = make_generate(n_steps)
        best = float("inf")
        for _ in range(reps + 1):  # first rep is compile+warmup
            cache = init_kv_cache(config, n_lanes=1, dtype=jnp.bfloat16)
            t0 = time.perf_counter()
            toks, cache = gen(params, cache, first, pos0)
            np.asarray(toks)  # forces completion (block_until_ready may not)
            dt = time.perf_counter() - t0
            best = min(best, dt)
        return best

    n_short, n_long = (4, 16) if small else (16, 128)
    t_short = timed(n_short)
    t_long = timed(n_long)
    if t_long - t_short > 0.1 * t_long:
        tok_s = (n_long - n_short) / (t_long - t_short)
    else:
        # marginal signal below dispatch-overhead noise (tiny models / fast
        # chips): report the conservative whole-run rate instead
        tok_s = n_long / t_long

    print(
        json.dumps(
            {
                "metric": "llama32_1b_q40_decode_tok_s",
                "value": round(tok_s, 2),
                "unit": "tok/s",
                "vs_baseline": round(tok_s / REFERENCE_SINGLE_DEVICE_TOK_S, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
