"""On-device (JAX) Q80 block codec — the single definition used by both the
activation-quantization emulation (models/llama.py) and the compressed
collectives (parallel/collectives.py).

Semantics match the host codec in ``codec.py``:
- mode="runtime": roundf ties-away-from-zero (src/nn/nn-quants.cpp:154-172)
- mode="converter": np.round ties-to-even (converter/writer.py:55-74)
The inverse scale is computed from the float32 delta; the fp16-rounded delta
is used only for dequantization (nn-quants.cpp:165-170).
"""

from __future__ import annotations

import jax.numpy as jnp

Q80_BLOCK = 32


def q80_encode_blocks(x: jnp.ndarray, mode: str = "runtime"):
    """x: [..., n] with n % 32 == 0. Returns (q int8 [..., n/32, 32],
    scales f16 [..., n/32, 1])."""
    shape = x.shape
    assert shape[-1] % Q80_BLOCK == 0, shape
    xf = x.astype(jnp.float32).reshape(*shape[:-1], shape[-1] // Q80_BLOCK, Q80_BLOCK)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    d32 = amax / 127.0
    inv = jnp.where(d32 != 0, 1.0 / jnp.where(d32 == 0, 1.0, d32), 0.0)
    scaled = xf * inv
    if mode == "runtime":
        q = jnp.sign(scaled) * jnp.floor(jnp.abs(scaled) + 0.5)
    elif mode == "converter":
        q = jnp.round(scaled)
    else:
        raise ValueError(mode)
    q = jnp.clip(q, -128, 127).astype(jnp.int8)
    return q, d32.astype(jnp.float16)


def q80_decode_blocks(q: jnp.ndarray, scales: jnp.ndarray, out_shape) -> jnp.ndarray:
    """Inverse of q80_encode_blocks; scales applied at their fp16 rounding."""
    return (q.astype(jnp.float32) * scales.astype(jnp.float32)).reshape(out_shape)


def qdq_q80(x: jnp.ndarray, mode: str = "runtime") -> jnp.ndarray:
    """Quantize-dequantize round trip along the last axis."""
    q, s = q80_encode_blocks(x, mode=mode)
    return q80_decode_blocks(q, s, x.shape).astype(x.dtype)
