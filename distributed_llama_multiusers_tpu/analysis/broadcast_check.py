"""pod-broadcast: every control packet pairs with exactly one engine call.

Scope: ``parallel/multihost.py`` (and fixture files with that suffix).
The pod control plane's deadlock rule (multihost.py's RootControlEngine):
workers replay every broadcast packet with a blocking engine call, so on
the root each ``self._plane.send_*`` broadcast must be followed —
unconditionally — by its paired ``self._engine.<method>`` call. Two ways
a proxy method can break the pod:

1. a ``raise`` (or an early ``return``) reachable BETWEEN the broadcast
   and the paired engine call: the packet went out, every worker enters
   the collective program, the root never dispatches its half — the pod
   hangs in ICI collectives with nothing to time out;
2. validation placed after the broadcast: the argument check that should
   have rejected the call locally now fires with the packet already on
   the wire, which is case 1 wearing a different hat.

So: validate first, broadcast second, compute third. This check walks
every method of every class in scope that broadcasts, takes each
broadcast site, finds its paired engine call (the next
``self._engine.*`` call in source order — a ``return`` whose expression
CONTAINS the engine call is the pair, not an escape), and flags any
``raise`` or ``return`` in between. A broadcast with no pair at all
(OP_STOP, stats reset, pipeline flush replay no device program) is legal,
but a ``raise`` after it is still flagged: the packet is already out.

Waive (``ok[pod-broadcast] reason``) only for ops documented to replay
nothing on the worker side where the post-send code cannot desync.
"""

from __future__ import annotations

import ast
import re

from .core import Checker, Finding, Project, SourceFile
from .lockgraph import walk_excluding_nested_defs

SCOPE = ("parallel/multihost.py",)
BCAST_RE = re.compile(r"^self\._plane\.(send_\w+|_send)$")
PAIR_RE = re.compile(r"^self\._engine\.\w+$")


def _pos(node: ast.AST) -> tuple[int, int]:
    return (node.lineno, node.col_offset)


class PodBroadcastChecker(Checker):
    name = "pod-broadcast"
    description = (
        "in RootControlEngine-style proxies, no raise/early-return between "
        "a control-packet broadcast and its paired engine call; validation "
        "precedes the broadcast"
    )

    def check(self, sf: SourceFile, project: Project):
        if not sf.endswith(*SCOPE):
            return
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._check_method(sf, node.name, stmt)

    def _check_method(self, sf: SourceFile, cls_name: str, fn):
        events = []  # (pos, kind, node) in source order; nested defs are
        # their own call stacks — a closure's return is not this method's
        for node in walk_excluding_nested_defs(fn):
            if isinstance(node, ast.Call):
                spelled = ast.unparse(node.func)
                if BCAST_RE.match(spelled):
                    events.append((_pos(node), "bcast", node))
                elif PAIR_RE.match(spelled):
                    events.append((_pos(node), "pair", node))
            elif isinstance(node, ast.Raise):
                events.append((_pos(node), "raise", node))
            elif isinstance(node, ast.Return):
                kind = "pair" if self._contains_pair(node) else "return"
                events.append((_pos(node), kind, node))
        if not any(kind == "bcast" for _, kind, _ in events):
            return
        events.sort(key=lambda e: e[0])
        open_bcast = None  # the broadcast awaiting its pair
        for i, (_, kind, node) in enumerate(events):
            if kind == "bcast":
                open_bcast = node
            elif kind == "pair":
                open_bcast = None
            elif open_bcast is not None:  # raise/return after a live send
                pair_follows = any(k == "pair" for _, k, _ in events[i + 1:])
                if kind == "return" and not pair_follows:
                    # a pair-less op (OP_STOP, stats reset, flush) replays
                    # no device program: returning after the send is its
                    # normal shape, only a raise still desyncs
                    continue
                b = ast.unparse(open_bcast.func)
                what = "raise" if kind == "raise" else "early return"
                yield Finding(
                    self.name, sf.display, node.lineno,
                    f"{what} reachable after broadcast '{b}(...)' (line "
                    f"{open_bcast.lineno}) in {cls_name}.{fn.name} before "
                    "its paired engine call — workers enter the collective "
                    "the root never dispatches and the pod deadlocks; "
                    "validate BEFORE broadcasting",
                )

    @staticmethod
    def _contains_pair(node: ast.Return) -> bool:
        if node.value is None:
            return False
        return any(
            isinstance(n, ast.Call) and PAIR_RE.match(ast.unparse(n.func))
            for n in ast.walk(node.value)
        )
