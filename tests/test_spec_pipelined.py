"""Zero-flush serving: speculative verification inside the pipelined step
family (``engine.decode_spec_pipelined`` / ``decode_spec_prefill_fused``)
and exact on-device top-p, so the async chain never aborts for a draft hit
or a wide-nucleus lane.

The invariants under test:

1. STREAM IDENTITY — a chain carrying spec verify steps emits exactly the
   plain-decode streams (speculative-verification identity composed with
   the carry-alignment gate), for greedy AND device-sampled lanes.
2. ZERO FLUSHES — mocked-engine churn with speculation ON and wide-nucleus
   sampled lanes in the mix completes with ``pipeline_flushes == 0``
   (the PR-9 acceptance criterion: only stop/drain may flush).
3. COMPOSITION — fused admissions and spec verify steps share dispatches
   (``fused_steps > 0`` and ``spec_emitted_per_lane_step > 1`` in one
   run), multiplying instead of trading off.
4. The POSITION CARRY — per-lane accept counts advance write positions on
   device (``pos + accepted + 1``); the device clamps drafts near
   seq_len from the carried positions (the host's view can be stale).
"""

import time

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats import load_model_header
from distributed_llama_multiusers_tpu.models import load_params_from_m
from distributed_llama_multiusers_tpu.runtime import (
    ContinuousBatchingScheduler,
    InferenceEngine,
    Request,
)
from distributed_llama_multiusers_tpu.runtime.engine import warmup_engine
from distributed_llama_multiusers_tpu.tokenizer import Tokenizer
from distributed_llama_multiusers_tpu.utils.testing import (
    MockAsyncEngine,
    StubStreamTokenizer,
    greedy_rollout,
)


@pytest.fixture(scope="module")
def loaded(tiny_model):
    h = load_model_header(tiny_model["model"])
    config, params = load_params_from_m(tiny_model["model"], h, dtype=jnp.float32)
    tok = Tokenizer(tiny_model["tokenizer"])
    return config, params, tok


def _fresh_engine(config, params, n_lanes=2, **kw):
    return InferenceEngine(
        config, params, n_lanes=n_lanes, prefill_buckets=(4,), **kw
    )


# ---------------------------------------------------------------------------
# engine level: the in-chain verify step
# ---------------------------------------------------------------------------


def test_engine_spec_pipelined_chain_identity(loaded):
    """A pipelined chain mixing spec verify steps (reseed-aligned AND
    chained one-step-behind drafts) with plain pipelined steps emits
    exactly the plain greedy stream, with full draft acceptance when the
    candidates are right — the zero-flush composition at engine level."""
    config, params, _ = loaded
    prompt = [5, 9, 3, 5, 9, 3, 5, 9]
    ref, _ = greedy_rollout(_fresh_engine(config, params), prompt, 16)

    engine = _fresh_engine(config, params)
    _, g0, pos = engine.prefill(0, prompt)
    assert int(g0) == ref[0]
    k = engine.SPEC_DRAFT
    n = engine.n_lanes
    out = [int(g0)]
    seq_len = config.seq_len

    # dispatch 0: RESEED spec step — the host knows the feed exactly and
    # ships it as candidate 0, followed by the true continuation
    drafts = np.zeros((n, k + 1), np.int32)
    dlen = np.zeros(n, np.int32)
    drafts[0] = [ref[0]] + ref[1 : 1 + k]
    dlen[0] = k + 1
    engine.decode_spec_pipelined(
        np.asarray([pos, seq_len], np.int32), drafts, dlen,
        tokens=np.asarray([g0, 0], np.int32),
    )
    # dispatch 1: chained plain step on the carried positions (-1)
    neg = np.asarray([-1, seq_len], np.int32)
    engine.decode_pipelined(neg)
    emitted, n_emit = engine.pipeline_consume()  # the spec step
    cnt = int(n_emit[0])
    assert cnt == k + 1  # full acceptance: every candidate was right
    out.extend(int(t) for t in emitted[0, : cnt - 1])
    out.append(int(emitted[0, cnt - 1]))
    g, _ = engine.pipeline_consume()  # the plain step
    out.append(int(g[0]))
    assert out == ref[: len(out)]

    # dispatch 2: plain in flight, then a CHAINED spec step — the host is
    # one token behind, so candidate 0 guesses the in-flight step's output
    engine.decode_pipelined(neg)
    i = len(out)
    drafts2 = np.zeros((n, k + 1), np.int32)
    dlen2 = np.zeros(n, np.int32)
    drafts2[0] = ref[i : i + k + 1]
    dlen2[0] = k + 1
    engine.decode_spec_pipelined(neg, drafts2, dlen2)
    g, _ = engine.pipeline_consume()
    out.append(int(g[0]))
    emitted, n_emit = engine.pipeline_consume()
    cnt = int(n_emit[0])
    assert cnt == k + 1  # the alignment gate passed and all drafts hit
    out.extend(int(t) for t in emitted[0, : cnt - 1])
    out.append(int(emitted[0, cnt - 1]))
    engine.pipeline_flush()
    assert out == ref[: len(out)]


def test_engine_spec_pipelined_wrong_carry_candidate_is_safe(loaded):
    """A candidate-0 mismatch (the host's stale guess at the carry) zeroes
    the effective draft — n_emit == 1 and the stream stays exactly the
    plain-decode stream. Misalignment costs acceptance, never
    correctness."""
    config, params, _ = loaded
    prompt = [5, 9, 3, 5, 9, 3, 5, 9]
    ref, _ = greedy_rollout(_fresh_engine(config, params), prompt, 8)

    engine = _fresh_engine(config, params)
    _, g0, pos = engine.prefill(0, prompt)
    k = engine.SPEC_DRAFT
    n = engine.n_lanes
    drafts = np.zeros((n, k + 1), np.int32)
    dlen = np.zeros(n, np.int32)
    # wrong candidate 0, RIGHT continuations: the gate must still reject
    drafts[0] = [(ref[0] + 1) % config.vocab_size] + ref[1 : 1 + k]
    dlen[0] = k + 1
    engine.decode_spec_pipelined(
        np.asarray([pos, config.seq_len], np.int32), drafts, dlen,
        tokens=np.asarray([g0, 0], np.int32),
    )
    emitted, n_emit = engine.pipeline_consume()
    engine.pipeline_flush()
    assert int(n_emit[0]) == 1
    assert int(emitted[0, 0]) == ref[1]


def test_engine_spec_pipelined_clamps_on_device_near_seq_len(loaded):
    """The draft clamp moved ON DEVICE (the host's stale position could
    under-clamp once accept counts ride the carry): a lane whose carried
    position sits within SPEC_DRAFT slots of seq_len accepts at most the
    slots it has left, and never scribbles past the end."""
    config, params, _ = loaded
    engine = _fresh_engine(config, params)
    seq_len = config.seq_len
    k = engine.SPEC_DRAFT
    n = engine.n_lanes
    prompt = [5, 9, 3]
    _, g0, pos = engine.prefill(0, prompt)
    # park the lane 2 slots short of seq_len: at most 1 draft can commit
    start = seq_len - 2
    drafts = np.full((n, k + 1), int(g0), np.int32)
    dlen = np.full(n, 0, np.int32)
    drafts[0, 0] = int(g0)  # candidate 0 == feed: gate passes
    dlen[0] = k + 1
    engine.decode_spec_pipelined(
        np.asarray([start, seq_len], np.int32), drafts, dlen,
        tokens=np.asarray([g0, 0], np.int32),
    )
    emitted, n_emit = engine.pipeline_consume()
    engine.pipeline_flush()
    # eff_len clamped to seq_len - pos - 1 = 1, so n_emit <= 2 regardless
    # of how many candidates matched
    assert 1 <= int(n_emit[0]) <= 2


def test_engine_spec_drafts_shape_validated(loaded):
    """The draft-shape contract raises BEFORE any dispatch (the root
    proxy's pre-broadcast validation relies on it)."""
    config, params, _ = loaded
    engine = _fresh_engine(config, params)
    z = np.zeros(engine.n_lanes, np.int32)
    bad = np.zeros((engine.n_lanes, engine.SPEC_DRAFT), np.int32)  # K, not K+1
    with pytest.raises(ValueError, match="drafts shape"):
        engine.decode_spec_pipelined(z, bad, z, tokens=z)
    with pytest.raises(ValueError, match="drafts shape"):
        engine.decode_spec_prefill_fused(z, bad, z, chunk=[1, 2], tokens=z)


@pytest.mark.slow  # tier-2: heavy; the fused-pack class stays tier-1 via test_pod_packet_replays_decode_spec_prefill_fused and the scheduler fused-admission pins (see pyproject markers)
def test_engine_spec_prefill_fused_pack(loaded):
    """The chunk+verify composition returns the spec pack with the
    boundary pair as an extra row, and the admitting lane's carry holds
    the boundary token at the chunk-boundary position — a freshly joined
    lane can ride the NEXT dispatch (spec or plain) straight from
    device."""
    config, params, _ = loaded
    ref_engine = _fresh_engine(config, params)
    prompt = [5, 9, 3, 7]
    ref, _ = greedy_rollout(ref_engine, prompt, 4)

    engine = _fresh_engine(config, params)
    warmup_engine(engine, spec=True, multi_step=0)
    k = engine.SPEC_DRAFT
    n = engine.n_lanes
    seq_len = config.seq_len
    drafts = np.zeros((n, k + 1), np.int32)
    dlen = np.zeros(n, np.int32)
    # lane 1 admits via the fused-spec step (lane 0 idle, no drafts):
    # the prefill half must behave exactly like prefill_chunk
    engine.decode_spec_prefill_fused(
        np.full(n, seq_len, np.int32), drafts, dlen,
        p_lane=1, chunk=prompt, p_start=0,
        tokens=np.zeros(n, np.int32),
    )
    emitted, n_emit = engine.pipeline_consume()
    assert emitted.shape == (n + 1, k + 1)
    assert int(emitted[-1, 0]) == ref[0]  # boundary greedy == cold prefill
    # the carry now feeds lane 1 at the boundary position: a plain chained
    # step must emit the next plain-decode token
    engine.decode_pipelined(np.asarray([seq_len, -1], np.int32))
    g, _ = engine.pipeline_consume()
    engine.pipeline_flush()
    assert int(g[1]) == ref[1]


# ---------------------------------------------------------------------------
# scheduler level (real engine): streams and flush accounting
# ---------------------------------------------------------------------------


def _run_sched(config, params, tok, reqs, n_lanes=4, **kw):
    engine = _fresh_engine(config, params, n_lanes=n_lanes)
    kw.setdefault("prefix_min_tokens", 0)
    kw.setdefault("multi_step", 0)
    sched = ContinuousBatchingScheduler(engine, tok, **kw)
    sched.start()
    try:
        for r in reqs:
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=300)
    finally:
        sched.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return [list(r.generated_tokens) for r in reqs], engine.stats.snapshot()


def test_scheduler_spec_rides_chain_zero_flush(loaded):
    """Draft-friendly greedy lanes + a seeded sampled lane + a WIDE-
    nucleus sampled lane (the old host-exact class): with speculation on,
    the chain serves everything — streams identical to the synchronous
    spec scheduler, spec verify steps dispatched IN-chain, and zero
    pipeline flushes (the PR-9 acceptance criterion)."""
    config, params, tok = loaded

    def reqs():
        return [
            Request(prompt="aa bb aa bb aa", max_tokens=14, temperature=0.0),
            Request(prompt="aa bb aa bb aa bb", max_tokens=10,
                    temperature=0.0),
            Request(prompt="sampled one", max_tokens=8, temperature=0.8,
                    seed=123),
            Request(prompt="wide nucleus", max_tokens=6, temperature=0.8,
                    topp=1.0, seed=7),
        ]

    base, base_stats = _run_sched(config, params, tok, reqs(),
                                  pipelined=False)
    out, stats = _run_sched(config, params, tok, reqs(), pipelined=True)
    assert out == base
    assert stats["spec_pipelined_steps"] > 0  # verify steps rode the ring
    assert stats["pipeline_flushes"] == 0  # nothing left to flush for
    assert stats["host_exact_lanes"] == 0
    # acceptance realized: more tokens than drafted-lane verify steps
    assert stats["spec_emitted"] > stats["spec_lane_steps"] > 0
    assert sum(stats["spec_accept_hist"].values()) == stats["spec_lane_steps"]


def test_scheduler_spec_chain_stop_string(loaded):
    """A stop string landing inside a spec step's multi-token commit: the
    lane finishes mid-sequence, surplus accepted tokens are discarded
    (junk-KV rule), and the stream equals the synchronous path's."""
    config, params, tok = loaded
    probe = Request(prompt="aa bb aa bb aa", max_tokens=20, temperature=0.0)
    _run_sched(config, params, tok, [probe], pipelined=False)
    dec = tok.make_stream_decoder()
    pieces = [dec.decode(t) for t in probe.generated_tokens]
    stop = next(
        (p for i, p in enumerate(pieces)
         if 4 <= i <= len(pieces) - 6 and p and p.strip()),
        None,
    )
    if stop is None:
        pytest.skip(f"no usable mid-stream piece in {pieces!r}")

    def stopped():
        return [Request(prompt="aa bb aa bb aa", max_tokens=20,
                        temperature=0.0, stop=[stop])]

    base, _ = _run_sched(config, params, tok, stopped(), pipelined=False)
    reqs = stopped()
    out, stats = _run_sched(config, params, tok, reqs, pipelined=True)
    assert out == base
    assert reqs[0].finish_reason == "stop"
    assert len(out[0]) < 20


# ---------------------------------------------------------------------------
# mocked-engine churn: THE zero-flush gate (tier-1 acceptance criterion)
# ---------------------------------------------------------------------------


def _drive(engine, rs, pipelined, staggered, **kw):
    sched = ContinuousBatchingScheduler(
        engine, StubStreamTokenizer(engine.config.vocab_size),
        prefix_min_tokens=0, multi_step=0, pipelined=pipelined, **kw,
    )
    sched.start()
    try:
        if not staggered:
            for r in rs:
                sched.submit(r)
        else:
            sched.submit(rs[0])
            deadline = time.monotonic() + 60
            while engine.stats.snapshot()["pipeline_dispatches"] < 3:
                assert time.monotonic() < deadline, "chain never formed"
                time.sleep(0.002)
            for r in rs[1:]:
                sched.submit(r)
                time.sleep(engine.step_s * 2)
        for r in rs:
            r.future.result(timeout=60)
    finally:
        sched.stop()
    assert all(r.error is None for r in rs), [r.error for r in rs]
    return [list(r.generated_tokens) for r in rs]


def test_mocked_churn_spec_and_wide_nucleus_zero_flush():
    """The PR-9 acceptance criterion, pinned deterministically: mocked-
    engine churn with speculation ON and wide-nucleus sampled lanes in
    the mix completes with ``pipeline_flushes == 0`` (only stop/drain),
    greedy streams byte-identical to the synchronous spec path, sampled
    streams identical to the on-device sampler's sync path under the
    same seeds — and speculation COMPOSES with fused admission in the
    same run (``fused_steps > 0`` with accepted drafts > 0)."""
    N = 8

    def reqs():
        return [
            Request(
                prompt="churn request text", max_tokens=24,
                temperature=0.0 if i % 2 == 0 else 0.8,
                topp=1.0 if i % 4 == 3 else 0.9,  # wide nucleus in the mix
                seed=50 + i,
            )
            for i in range(N)
        ]

    # vocab 16: the mock's f(lane, pos) streams have period 2, so the
    # n-gram drafter hits hard — near-full acceptance when aligned
    base_engine = MockAsyncEngine(n_lanes=4, vocab=16, max_chunk=4,
                                  speculative=True)
    base = _drive(base_engine, reqs(), pipelined=False, staggered=False)

    churn_engine = MockAsyncEngine(n_lanes=4, vocab=16, max_chunk=4,
                                   step_s=0.003, speculative=True)
    churn_reqs = reqs()
    out = _drive(churn_engine, churn_reqs, pipelined=True, staggered=True)

    assert out == base
    snap = churn_engine.stats.snapshot()
    assert snap["pipeline_flushes"] == 0  # THE zero-flush invariant
    assert snap["spec_pipelined_steps"] > 0  # drafts verified in-chain
    assert snap["fused_steps"] > 0  # admissions rode the chain too
    assert snap["host_exact_lanes"] == 0  # wide nucleus stayed on device
    # speculation genuinely multiplied: >1 token per drafted lane-step
    assert snap["spec_lane_steps"] > 0
    assert snap["spec_emitted"] > snap["spec_lane_steps"]
    # accept-hist accounts exactly the drafted lane-steps
    assert sum(snap["spec_accept_hist"].values()) == snap["spec_lane_steps"]


def test_mocked_spec_cancel_mid_draft_keeps_ratio_consistent():
    """A lane cancelled while a spec step is in flight must not count a
    drafted lane-step with zero consumed tokens — the acceptance ratio
    (spec_emitted / spec_lane_steps) stays in its [1, K+1] class (the
    PR-9 spec-accounting leak fix, scheduler side)."""
    engine = MockAsyncEngine(n_lanes=2, vocab=16, speculative=True,
                             step_s=0.004)
    victim = Request(prompt="cancel me", max_tokens=200, temperature=0.0)
    sched = ContinuousBatchingScheduler(
        engine, StubStreamTokenizer(engine.config.vocab_size),
        prefix_min_tokens=0, multi_step=0, pipelined=True,
    )
    sched.start()
    try:
        sched.submit(victim)
        deadline = time.monotonic() + 60
        while engine.stats.snapshot()["spec_pipelined_steps"] < 3:
            assert time.monotonic() < deadline, "speculation never engaged"
            time.sleep(0.002)
        victim.cancel()
        victim.future.result(timeout=60)
    finally:
        sched.stop()
    assert victim.finish_reason == "cancelled"
    snap = engine.stats.snapshot()
    if snap["spec_lane_steps"]:  # ratio class holds even after the cancel
        assert snap["spec_emitted"] >= snap["spec_lane_steps"]


# ---------------------------------------------------------------------------
# pod control plane: the new ops replay
# ---------------------------------------------------------------------------


def test_pod_packet_replays_decode_spec_pipelined():
    """OP_DECODE_SPEC_PIPELINED round-trips the feed flag, ring depth,
    drafts (K+1 candidates), and lengths through the control-plane packet
    into the worker's in-chain verify call, with the bounded-lag consume
    and flush-then-reseed rules of OP_DECODE_PIPELINED."""
    from distributed_llama_multiusers_tpu.parallel import multihost as mh

    calls = []

    class _Eng:
        n_lanes = 2
        SPEC_DRAFT = 3
        pipeline_depth = 2

        def __init__(self):
            self._ring = 0

        def pipeline_inflight(self):
            return self._ring

        def pipeline_consume(self):
            calls.append(("consume",))
            self._ring -= 1

        def pipeline_flush(self, count=True):
            assert count is False
            calls.append(("flush", self._ring))
            self._ring = 0

        def decode_spec_pipelined(self, positions, drafts, draft_len,
                                  temps=None, topps=None, seeds=None,
                                  tokens=None, g_states=None):
            self._ring += 1
            calls.append((
                "spec",
                None if tokens is None else np.asarray(tokens).tolist(),
                np.asarray(positions).tolist(),
                np.asarray(drafts).tolist(),
                np.asarray(draft_len).tolist(),
            ))

    sent = []

    class _Plane(mh.ControlPlane):
        def __init__(self):
            super().__init__(n_lanes=2, chunk=8)

        def _bcast(self, pkt):
            sent.append(pkt.copy())
            return pkt

    plane = _Plane()
    temps = np.asarray([0.0, 0.8], np.float32)
    topps = np.full(2, 0.9, np.float32)
    seeds = np.asarray([1, 2], np.uint32)
    drafts = np.asarray([[7, 8, 9, 10], [0, 0, 0, 0]], np.int32)
    dlen = np.asarray([4, 0], np.int32)
    plane.send_decode_spec_pipelined(
        np.asarray([7, 9], np.int32), np.asarray([3, 4], np.int32),
        temps, topps, seeds, depth=2, drafts=drafts, draft_len=dlen,
    )
    # device-fed chained verify on carried positions (-1 rides the packet)
    plane.send_decode_spec_pipelined(
        None, np.asarray([-1, 4], np.int32), temps, topps, seeds, depth=2,
        drafts=drafts, draft_len=dlen,
    )
    plane.send_pipeline_flush()
    plane.send_stop()

    replay = iter(sent)

    class _ReplayPlane:
        def recv(self):
            return next(replay)

        def slot(self, pkt, i, n):
            return plane.slot(pkt, i, n)

    mh.worker_loop(_Eng(), _ReplayPlane())
    kinds = [c[0] for c in calls]
    assert kinds == ["flush", "spec", "spec", "flush"], calls
    first = calls[1]
    assert first[1] == [7, 9] and first[2] == [3, 4]
    assert first[3] == [[7, 8, 9, 10], [0, 0, 0, 0]]
    assert first[4] == [4, 0]
    assert calls[2][1] is None and calls[2][2] == [-1, 4]


def test_pod_packet_replays_decode_spec_prefill_fused():
    """The fused-spec packet carries drafts AND the chunk + prefill
    header (slots 7/8) into one worker call."""
    from distributed_llama_multiusers_tpu.parallel import multihost as mh

    calls = []

    class _Eng:
        n_lanes = 2
        SPEC_DRAFT = 3
        pipeline_depth = 2

        def pipeline_inflight(self):
            return 0

        def pipeline_flush(self, count=True):
            calls.append(("flush",))

        def decode_spec_prefill_fused(self, positions, drafts, draft_len,
                                      temps=None, topps=None, seeds=None,
                                      p_lane=0, chunk=None, p_start=0,
                                      p_temp=0.0, p_topp=0.9, p_seed=0,
                                      tokens=None, g_states=None, p_g=0):
            calls.append((
                "specfused",
                np.asarray(drafts).tolist(),
                np.asarray(draft_len).tolist(),
                list(chunk), p_lane, p_start,
                round(float(p_temp), 4), p_seed,
            ))

    sent = []

    class _Plane(mh.ControlPlane):
        def __init__(self):
            super().__init__(n_lanes=2, chunk=8)

        def _bcast(self, pkt):
            sent.append(pkt.copy())
            return pkt

    plane = _Plane()
    temps = np.asarray([0.0, 0.0], np.float32)
    topps = np.full(2, 0.9, np.float32)
    seeds = np.asarray([1, 2], np.uint32)
    drafts = np.asarray([[5, 6, 7, 8], [0, 0, 0, 0]], np.int32)
    dlen = np.asarray([4, 0], np.int32)
    plane.send_decode_spec_prefill_fused(
        np.asarray([7, 9], np.int32), np.asarray([3, 4], np.int32),
        temps, topps, seeds, depth=2, drafts=drafts, draft_len=dlen,
        p_lane=1, chunk=[11, 12, 13], p_start=5,
        p_temp=0.8, p_topp=0.9, p_seed=99,
    )
    plane.send_stop()

    replay = iter(sent)

    class _ReplayPlane:
        def recv(self):
            return next(replay)

        def slot(self, pkt, i, n):
            return plane.slot(pkt, i, n)

    mh.worker_loop(_Eng(), _ReplayPlane())
    kinds = [c[0] for c in calls]
    assert kinds == ["flush", "specfused"], calls
    _, d, dl, chunk, p_lane, p_start, p_temp, p_seed = calls[1]
    assert d == [[5, 6, 7, 8], [0, 0, 0, 0]] and dl == [4, 0]
    assert chunk == [11, 12, 13] and p_lane == 1 and p_start == 5
    assert p_temp == 0.8 and p_seed == 99


def test_root_engine_validates_spec_dispatch_before_broadcast():
    """A bad draft shape or chunk must raise BEFORE any packet goes out
    (the pod-deadlock rule, extended to the new ops)."""
    from distributed_llama_multiusers_tpu.parallel import multihost as mh

    sent = []

    class _Plane(mh.ControlPlane):
        def __init__(self):
            super().__init__(n_lanes=2, chunk=8)

        def _bcast(self, pkt):
            sent.append(pkt.copy())
            return pkt

    class _Eng:
        n_lanes = 2
        SPEC_DRAFT = 3

        def max_chunk(self):
            return 4

        def check_spec_drafts(self, drafts):
            want = (2, 4)
            if getattr(drafts, "shape", None) != want:
                raise ValueError(f"spec drafts shape != {want}")

        def check_spec_pipelined_dispatch(self, drafts, reseed,
                                          positions=None, g_states=None):
            self.check_spec_drafts(drafts)

    root = mh.RootControlEngine(_Eng(), _Plane())
    z = np.zeros(2, np.int32)
    bad = np.zeros((2, 3), np.int32)
    with pytest.raises(ValueError, match="drafts shape"):
        root.decode_spec_pipelined(z, bad, z, tokens=z)
    good = np.zeros((2, 4), np.int32)
    with pytest.raises(ValueError, match="outside"):
        root.decode_spec_prefill_fused(z, good, z, chunk=[1] * 9, tokens=z)
    with pytest.raises(ValueError, match="drafts shape"):
        root.decode_spec_prefill_fused(z, bad, z, chunk=[1, 2], tokens=z)
    assert sent == []  # nothing was broadcast


# ---------------------------------------------------------------------------
# SpecStream accounting (the leak fix, CLI side)
# ---------------------------------------------------------------------------


def test_spec_accepted_counter_survives_retraction():
    """dllama_spec_accepted_total stays monotone AND does not re-count
    retracted tokens: a partial spec_emitted dip (discard_pending's
    retraction) keeps the high-water baseline, so the next rise counts
    only genuinely new consumption; a drop to 0 (stats reset) re-baselines
    like the other delta-fed counters."""
    from distributed_llama_multiusers_tpu.telemetry import Telemetry

    tel = Telemetry()

    def counter_value():
        for line in tel.registry.render().splitlines():
            if line.startswith("dllama_spec_accepted_total "):
                return float(line.split()[-1])
        return 0.0

    tel.bridge_stats({"spec_emitted": 10})
    assert counter_value() == 10
    tel.bridge_stats({"spec_emitted": 8})  # retraction: no change
    assert counter_value() == 10
    tel.bridge_stats({"spec_emitted": 12})  # only past the high water
    assert counter_value() == 12
    tel.bridge_stats({"spec_emitted": 0})  # window reset: re-baseline
    tel.bridge_stats({"spec_emitted": 3})
    assert counter_value() == 15


def test_specstream_discard_pending_retracts_partial_step(loaded):
    """A turn ending with unconsumed lookahead RETRACTS the partially
    consumed verify step from the acceptance counters: the bench ratio
    (emitted per drafted lane-step, class [1, K+1]) aggregates only
    fully realized steps — a discard can neither deflate it nor strand
    a dangling lane-step."""
    from distributed_llama_multiusers_tpu.runtime.spec import SpecStream

    config, params, tok = loaded
    prompt = tok.encode("aa bb aa bb aa bb aa bb")
    engine = _fresh_engine(config, params, n_lanes=1)
    _, g0, pos = engine.prefill(0, prompt)
    engine.stats.reset()
    spec = SpecStream(engine, config, enabled=True, prompt_tokens=prompt)
    cur = int(g0)
    # advance until a verify actually leaves lookahead pending
    for _ in range(32):
        nxt, _ = spec.advance(cur, pos)
        pos += 1
        cur = nxt
        if spec.pending:
            break
    assert spec.pending, "speculation never left a lookahead pending"
    before = engine.stats.snapshot()
    assert before["spec_lane_steps"] >= 1
    spec.discard_pending()
    after = engine.stats.snapshot()
    # the partially consumed step is gone from BOTH counters
    assert after["spec_lane_steps"] == before["spec_lane_steps"] - 1
    assert after["spec_emitted"] < before["spec_emitted"]
    assert spec.pending == [] and spec._pending_consumed == 0
    # ratio class: emitted >= lane_steps (>= 1 token per counted step)
    assert after["spec_emitted"] >= after["spec_lane_steps"]
