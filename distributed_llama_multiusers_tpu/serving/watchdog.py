"""Step watchdog: a hung device dispatch must become a signal, not a hang.

parallel/multihost.py documents the pod failure mode this exists for: a
broadcast with no matching compute (or a peer dying mid-collective)
leaves every process blocked inside a collective with NO timeout —
"a hang with no timeout, invisible until the pod is dead". Single-host,
the same shape appears as a device call that never returns: the
batching-loop thread blocks forever inside ``np.asarray`` on a poisoned
buffer, every future hangs, and ``/health`` keeps reporting healthy.

The watchdog is a monitor thread fed from the scheduler's blocking
engine-call sites: ``begin_step()`` right before the host blocks on the
device (sync decode, prefill chunk, lagged pipeline consume),
``step_done()`` when the call returns. If an armed step makes no
progress within ``deadline_s`` the watchdog trips ONCE for that step:

- **single-host** (``fatal=False``): invoke ``on_trip`` — the scheduler
  trips the circuit breaker (``/health`` flips, new work sheds with 503)
  and flags the pipelined chain to abort at the next opportunity. The
  blocked thread itself cannot be unblocked from here; the point is that
  the OUTSIDE of the process finds out (clients get 503s + the HTTP
  layer's bounded waits, operators get the log line + metrics) instead
  of a silent wedge.
- **pod** (``fatal=True``): after ``on_trip`` and the log line, CRASH
  the process (``os._exit``). Per multihost.py's own analysis, death
  beats silent desync: ``jax.distributed``'s peer-failure detection
  propagates a dead peer to every host, while a silently hung one wedges
  the whole pod forever.

Off by default: ``deadline_s <= 0`` never constructs one. The CLI
surface is ``--step-deadline`` / ``DLLAMA_STEP_DEADLINE`` (seconds).
Monotonic clocks only; no imports from runtime/ or server/ (this is a
serving-layer leaf like the rest of the package).
"""

from __future__ import annotations

import os
import threading
import time

from ..lockcheck import make_lock
from ..telemetry.logs import log_event

WATCHDOG_EXIT_CODE = 17  # distinctive: "killed by own watchdog, on purpose"


class StepWatchdog:
    """Trips when an armed step shows no progress for ``deadline_s``.

    ``on_trip(waited_s)`` runs on the watchdog thread, OUTSIDE the
    watchdog lock (it takes the breaker's and telemetry's locks; holding
    ours across that would put an edge in the lock-order graph for no
    reason). One trip per armed step: the trip disarms, and only the
    next ``begin_step()`` re-arms.
    """

    # dlint guarded-by declaration (analysis/lock_check.py): the arm
    # stamp and counters move under _lock / its condition (scheduler
    # thread arms, watchdog thread scans, /stats reads).
    _dlint_guarded_by = {
        ("_lock", "_cond"): ("_armed_at", "_running", "_wd_trips"),
    }

    def __init__(self, deadline_s: float, on_trip=None, fatal: bool = False):
        if deadline_s <= 0:
            raise ValueError("watchdog deadline must be positive (use no "
                             "watchdog at all to disable)")
        self.deadline_s = float(deadline_s)
        self.fatal = bool(fatal)
        self._trip_fn = on_trip
        self._lock = make_lock("StepWatchdog._lock")
        self._cond = threading.Condition(self._lock)
        self._armed_at: float | None = None
        self._running = False
        self._wd_trips = 0
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        with self._cond:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._run, name="step-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._armed_at = None
            self._cond.notify_all()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            self._thread = None

    # -- scheduler feed ------------------------------------------------------

    def begin_step(self) -> None:
        """The host is about to block on the device: arm the deadline."""
        with self._cond:
            self._armed_at = time.monotonic()
            self._cond.notify_all()

    def step_done(self) -> None:
        """The blocking call returned (success OR exception — a raised
        step is the containment layer's business, not a stall): disarm."""
        with self._cond:
            self._armed_at = None
            self._cond.notify_all()

    # -- exposition ----------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "watchdog_deadline_s": self.deadline_s,
                "watchdog_trips": self._wd_trips,
            }

    # -- monitor thread ------------------------------------------------------

    def _run(self) -> None:
        while True:
            waited = 0.0
            with self._cond:
                while self._running:
                    t0 = self._armed_at
                    if t0 is None:
                        self._cond.wait()
                        continue
                    now = time.monotonic()
                    if now - t0 > self.deadline_s:
                        # trip: disarm so one stall fires exactly once
                        waited = now - t0
                        self._armed_at = None
                        self._wd_trips += 1
                        break
                    self._cond.wait(self.deadline_s - (now - t0) + 0.001)
                if not self._running:
                    return
            # outside the lock: the callback takes breaker/telemetry locks
            self._fire(waited)

    def _fire(self, waited_s: float) -> None:
        log_event(
            "watchdog_trip",
            waited_s=round(waited_s, 3),
            deadline_s=self.deadline_s,
            fatal=self.fatal,
        )
        if self._trip_fn is not None:
            try:
                self._trip_fn(waited_s)
            except Exception:  # noqa: BLE001 — the trip must still crash a pod
                pass
        if self.fatal:
            # pod mode: deliberate process death — jax.distributed's
            # peer-failure detection turns it into a pod-wide signal,
            # which a silent hang never becomes (multihost.py's analysis)
            os._exit(WATCHDOG_EXIT_CODE)


def deadline_from_env(flag_value: float | None = None) -> float:
    """Resolve the step deadline: explicit flag wins, then
    ``DLLAMA_STEP_DEADLINE``, else 0 (off)."""
    if flag_value is not None:
        return max(0.0, float(flag_value))
    env = os.environ.get("DLLAMA_STEP_DEADLINE")
    if not env:
        return 0.0
    try:
        return max(0.0, float(env))
    except ValueError:
        return 0.0
