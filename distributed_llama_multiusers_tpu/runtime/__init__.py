from .engine import InferenceEngine
from .scheduler import Request, RequestQueue, ContinuousBatchingScheduler
