"""Crash-durable serving: journal, deterministic replay, resumable SSE.

The properties pinned here are the ISSUE 10 acceptance criteria:

- the journal is append-only, CRC-framed, and TORN-TAIL TOLERANT: a
  crash mid-write costs the un-fsynced tail, never a corrupt replay;
- admit records carry the RESOLVED sampler seed, so an unseeded request
  replays the identical stream;
- THE headline: kill the scheduler mid-stream under churn, restart with
  journal recovery, and every resumed stream is byte-identical to its
  uninterrupted run — zero lost, zero duplicated tokens — even when the
  restart places requests on different lanes;
- recovery composes with the circuit breaker's half-open probe instead
  of stampeding a freshly restarted engine;
- SSE chunks carry `id:` token indices and clients reattach with
  Last-Event-ID (GET /v1/stream/<id>) within the --reconnect-grace
  window, to live and recovered requests alike;
- recovery counters on /stats and /metrics reconcile field-for-field;
- every shed Retry-After carries deterministic ±20% jitter.

Everything runs on the MockAsyncEngine in ``content_keyed`` mode —
tokens are a pure function of (prompt content, position), the real
engine's replay-determinism class (per (seed, pos) sampling, never
per-lane), so byte-identity across a crash/restart is exact equality
with zero accelerator timing noise.
"""

import json
import os
import struct
import threading
import time
import urllib.error
import urllib.request
import zlib

import pytest

from distributed_llama_multiusers_tpu.runtime.scheduler import (
    ContinuousBatchingScheduler,
    Request,
    ensure_request_id_floor,
)
from distributed_llama_multiusers_tpu.serving import (
    CircuitBreaker,
    RequestJournal,
    StreamRegistry,
    StreamRelay,
    jittered_retry_after,
    read_journal,
    recover_scheduler,
)
from distributed_llama_multiusers_tpu.serving.journal import MAGIC, _FRAME
from distributed_llama_multiusers_tpu.utils import faults
from distributed_llama_multiusers_tpu.utils.testing import (
    MockAsyncEngine,
    StubStreamTokenizer,
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.disarm()
    yield
    faults.disarm()


class TokenTextTokenizer(StubStreamTokenizer):
    """Prompt-dependent encoding + per-token distinct decoding, so
    stream equality is a real assertion (the stub maps everything to
    the same tokens and every token to "x")."""

    def encode(self, text, add_bos=True, add_special_tokens=True):
        h = sum(ord(c) * (i + 1) for i, c in enumerate(text))
        return [(h + 5 * i) % self.vocab_size for i in range(8)]

    def decode(self, token):
        return f"[{token}]"


def _sched(journal=None, n_lanes=4, **kw):
    engine = MockAsyncEngine(n_lanes=n_lanes, max_chunk=8,
                             content_keyed=True)
    kw.setdefault("speculative", False)
    kw.setdefault("prefix_min_tokens", 0)
    kw.setdefault("multi_step", 0)
    sched = ContinuousBatchingScheduler(
        engine, TokenTextTokenizer(64), journal=journal, **kw
    )
    sched.start()
    return sched


def _reqs(n, max_tokens=40):
    return [
        Request(prompt=f"journal prompt {i} text", max_tokens=max_tokens,
                temperature=0.0)
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# journal format: framing, torn tail, replay fold
# ---------------------------------------------------------------------------


def _admit_kwargs(rid, **over):
    kw = dict(
        request_id=rid, prompt="p", tokens=[1, 2, 3], max_tokens=8,
        temperature=0.5, topp=0.9, seed=42, stop=["s"], add_bos=True,
        add_special_tokens=False, user="u", priority=1,
        queue_timeout_s=None, budget_s=2.0, stream=True, kind="chat",
    )
    kw.update(over)
    return kw


def test_journal_round_trip(tmp_path):
    p = str(tmp_path / "j.bin")
    j = RequestJournal(p, progress_every=2, fsync=False)
    j.record_admit(**_admit_kwargs(5))
    j.note_progress(5, 1)  # below the rate limit: not journaled
    j.note_progress(5, 4)
    j.record_admit(**_admit_kwargs(6, stream=False, kind=None, seed=7))
    j.record_finish(6, "stop")
    assert j.flush()
    stats = j.stats()
    assert stats["journal_records"] == 4  # the n=1 progress was absorbed
    assert stats["journal_errors"] == 0 and stats["journal_pending"] == 0
    j.close()

    img = read_journal(p)
    assert img.records == 4 and not img.torn
    inc = img.incomplete()
    assert [e.request_id for e in inc] == [5]
    e = inc[0]
    assert e.watermark == 4 and e.seed == 42 and e.stream
    assert e.kind == "chat" and e.stop == ["s"] and e.budget_s == 2.0
    assert not e.add_special_tokens and e.tokens == [1, 2, 3]
    done = img.entries[6]
    assert done.finished and done.finish_reason == "stop"


def test_journal_reopen_truncates_torn_tail(tmp_path):
    """Reopening a journal with a crash-torn tail truncates to the last
    durable frame BEFORE appending — frames written after the tear would
    be invisible to every reader (which stops at the first bad frame):
    finished gen-1 requests would resurrect forever and gen-2 in-flight
    requests would be unrecoverable."""
    p = str(tmp_path / "j.bin")
    j = RequestJournal(p, fsync=False)
    j.record_admit(**_admit_kwargs(1))
    assert j.flush()
    j.close()
    with open(p, "ab") as f:
        f.write(b"\x13\x37\x00")  # the torn half-frame a crash leaves

    j2 = RequestJournal(p, fsync=False)  # gen 2 on the same file
    j2.record_finish(1, "stop")
    j2.record_admit(**_admit_kwargs(2))
    assert j2.flush()
    j2.close()
    img = read_journal(p)
    assert not img.torn  # the tear was cut, gen-2 frames are readable
    assert img.entries[1].finished  # ...so request 1 stays finished
    assert [e.request_id for e in img.incomplete()] == [2]


def test_journal_reopen_refuses_foreign_file(tmp_path):
    p = str(tmp_path / "notes.txt")
    with open(p, "wb") as f:
        f.write(b"operator notes, definitely not a journal")
    with pytest.raises(ValueError, match="not a request journal"):
        RequestJournal(p, fsync=False)


def test_note_progress_after_finish_is_inert(tmp_path):
    """The HTTP pump can deliver the held-back tail delta AFTER the
    scheduler journaled the finish (the finish record is deliberately
    last). That late note_progress must journal nothing and must not
    resurrect the per-request progress mark (a leak per streamed
    request on a long-lived server)."""
    p = str(tmp_path / "j.bin")
    j = RequestJournal(p, progress_every=1, fsync=False)
    j.record_admit(**_admit_kwargs(1))
    j.note_progress(1, 3)
    j.record_finish(1, "stop")
    j.note_progress(1, 9)  # the pump's tail delivery, post-finish
    assert j.flush()
    stats = j.stats()
    assert 1 not in j._j_progress_mark  # not resurrected
    j.close()
    assert stats["journal_records"] == 3  # admit + progress(3) + finish
    assert read_journal(p).entries[1].watermark == 3


def test_journal_anonymous_user_round_trips_as_none(tmp_path):
    """user=None journals as null and recovers as None — an anonymous
    request must come back anonymous, not as a QoS fair-share bucket
    literally named "None" (distinct from every fresh anonymous
    request and colliding with a real user of that name)."""
    p = str(tmp_path / "j.bin")
    j = RequestJournal(p, fsync=False)
    j.record_admit(**_admit_kwargs(1, user=None))
    j.record_admit(**_admit_kwargs(2, user="None"))  # the literal string
    assert j.flush()
    j.close()
    img = read_journal(p)
    assert img.entries[1].user is None
    assert img.entries[2].user == "None"


def test_journal_torn_tail_and_crc(tmp_path):
    p = str(tmp_path / "j.bin")
    j = RequestJournal(p, fsync=False)
    j.record_admit(**_admit_kwargs(1))
    j.record_admit(**_admit_kwargs(2))
    assert j.flush()
    j.close()
    whole = open(p, "rb").read()

    # torn mid-frame: replay stops at the last durable record
    torn = str(tmp_path / "torn.bin")
    with open(torn, "wb") as f:
        f.write(whole[:-7])
    img = read_journal(torn)
    assert img.torn and img.records == 1
    assert [e.request_id for e in img.incomplete()] == [1]

    # flipped byte inside the last payload: CRC catches it
    bad = bytearray(whole)
    bad[-3] ^= 0xFF
    crc = str(tmp_path / "crc.bin")
    with open(crc, "wb") as f:
        f.write(bytes(bad))
    img = read_journal(crc)
    assert img.torn and img.records == 1

    # not a journal at all
    with open(str(tmp_path / "junk.bin"), "wb") as f:
        f.write(b"not a journal")
    assert read_journal(str(tmp_path / "junk.bin")).torn
    # missing file: empty image, not an error
    img = read_journal(str(tmp_path / "nope.bin"))
    assert not img.torn and img.records == 0


def test_journal_unknown_record_kind_skipped(tmp_path):
    """Forward compat: an unknown `k` is skipped, later records still
    apply."""
    p = str(tmp_path / "j.bin")
    j = RequestJournal(p, fsync=False)
    j.record_admit(**_admit_kwargs(1))
    assert j.flush()
    j.close()
    payload = json.dumps({"k": "future-thing", "id": 1}).encode()
    frame = _FRAME.pack(zlib.crc32(payload), len(payload)) + payload
    payload2 = json.dumps({"k": "progress", "id": 1, "n": 9}).encode()
    frame2 = _FRAME.pack(zlib.crc32(payload2), len(payload2)) + payload2
    with open(p, "ab") as f:
        f.write(frame + frame2)
    img = read_journal(p)
    assert img.skipped == 1 and img.entries[1].watermark == 9


def test_journal_readmit_carries_watermark(tmp_path):
    """A recovered request re-journals under its original id; delivery
    watermarks are ABSOLUTE so they carry across crash generations."""
    p = str(tmp_path / "j.bin")
    j = RequestJournal(p, progress_every=1, fsync=False)
    j.record_admit(**_admit_kwargs(3))
    j.note_progress(3, 6)
    j.record_admit(**_admit_kwargs(3))  # second-generation re-admission
    assert j.flush()
    j.close()
    img = read_journal(p)
    e = img.entries[3]
    assert not e.finished and e.watermark == 6


def test_journal_write_fault_contained(tmp_path):
    """An injected journal.write fault (ENOSPC stand-in) costs records,
    never serving: errors are counted, later batches still write."""
    p = str(tmp_path / "j.bin")
    j = RequestJournal(p, fsync=False)
    faults.arm("journal.write:@1:n=1")
    j.record_admit(**_admit_kwargs(1))
    assert j.flush()
    j.record_admit(**_admit_kwargs(2))
    assert j.flush()
    stats = j.stats()
    j.close()
    assert stats["journal_errors"] == 1
    img = read_journal(p)
    assert [e.request_id for e in img.incomplete()] == [2]


def test_journal_header_validated(tmp_path):
    """An absurd frame length reads as a torn tail, not a giant alloc."""
    p = str(tmp_path / "j.bin")
    with open(p, "wb") as f:
        f.write(MAGIC + struct.pack("<II", 0, 1 << 30))
    img = read_journal(p)
    assert img.torn and img.records == 0


# ---------------------------------------------------------------------------
# relay + registry
# ---------------------------------------------------------------------------


def test_relay_fast_forward_eviction_and_supersede():
    r = StreamRelay(1, base=2, capacity=3)
    for i in range(1, 7):
        r.push(i, f"t{i}")
    pushed, buffered = r.counts()
    # 1,2 fast-forwarded; nothing delivered yet, so nothing evicted —
    # past capacity the undelivered tail backpressures into memory
    assert pushed == 4 and buffered == 4
    gen = r.attach()
    assert r.next_after(2, timeout=0.2, gen=gen) == ("delta", 3, "t3")
    assert r.next_after(3, timeout=0.2, gen=gen) == ("delta", 4, "t4")
    # the delivered prefix (3,4) is now the evictable replay window:
    # the next over-capacity push compacts it
    r.push(7, "t7")
    assert r.next_after(2, timeout=0.2, gen=gen)[0] == "gap"  # behind horizon
    assert r.next_after(4, timeout=0.2, gen=gen) == ("delta", 5, "t5")
    assert r.next_after(7, timeout=0.05, gen=gen) is None  # nothing yet
    r.finish()
    assert r.next_after(7, timeout=0.2, gen=gen) == ("done",)
    gen2 = r.attach()
    assert r.next_after(0, timeout=0.2, gen=gen)[0] == "superseded"
    assert r.next_after(7, timeout=0.2, gen=gen2) == ("done",)


def test_relay_slow_connected_client_never_gaps():
    """The capacity bound is on the DELIVERED replay window: a connected
    client that drains slower than generation (buffer far past capacity)
    still receives every delta in order — undelivered deltas are never
    evicted out from under it."""
    r = StreamRelay(1, capacity=4)
    for i in range(1, 51):
        r.push(i, f"t{i}")
    r.finish()
    gen = r.attach()
    got, last = [], 0
    while True:
        item = r.next_after(last, timeout=0.2, gen=gen)
        if item == ("done",):
            break
        assert item[0] == "delta", item
        got.append(item[1])
        last = item[1]
    assert got == list(range(1, 51))


def test_relay_capacity0_frees_delivered():
    """The default no-reconnect path (capacity 0) keeps no replay
    window: delivered deltas are freed at the next push, so memory
    holds only the undelivered backlog — the plain delta queue's
    behavior, not a second full copy of the generated text."""
    r = StreamRelay(1, capacity=0)
    for i in range(1, 11):
        r.push(i, f"t{i}")
    gen = r.attach()
    last = 0
    for _ in range(10):
        item = r.next_after(last, timeout=0.2, gen=gen)
        assert item[0] == "delta"
        last = item[1]
    assert last == 10
    r.push(11, "t11")  # freeing happens at push time
    pushed, buffered = r.counts()
    assert pushed == 11 and buffered == 1  # delivered 1..10 freed
    assert r.next_after(last, timeout=0.2, gen=gen) == ("delta", 11, "t11")


def test_registry_grace_expiry_cancels():
    reg = StreamRegistry(grace_s=0.2)
    req = Request(prompt="x", max_tokens=4)
    reg.register(req, kind="chat")
    reg.detach(req.id)
    deadline = time.monotonic() + 10
    while not req._cancelled.is_set() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert req._cancelled.is_set()
    assert reg.attach(req.id) is None  # entry dropped
    assert reg.stats()["resume_expired_cancels"] == 1
    reg.close()


def test_registry_reattach_clears_grace_clock():
    reg = StreamRegistry(grace_s=0.3)
    req = Request(prompt="x", max_tokens=4)
    reg.register(req, kind="chat")
    reg.detach(req.id)
    time.sleep(0.1)
    assert reg.attach(req.id) is not None  # back inside the window
    time.sleep(0.5)  # attached entries are never reaped while live
    assert not req._cancelled.is_set()
    reg.close()


# ---------------------------------------------------------------------------
# Retry-After jitter (satellite)
# ---------------------------------------------------------------------------


def test_jitter_deterministic_and_banded():
    vals = [jittered_retry_after(10.0, k) for k in range(64)]
    assert all(8.0 <= v <= 12.0 for v in vals)  # ±20% band
    assert len(set(vals)) > 16  # genuinely spread
    assert jittered_retry_after(10.0, 7) == jittered_retry_after(10.0, 7)
    assert jittered_retry_after(0.2, 7) == 1.0  # floored


# ---------------------------------------------------------------------------
# scheduler wiring: admits with resolved seeds, finishes final
# ---------------------------------------------------------------------------


def test_scheduler_journals_resolved_seed_and_finish(tmp_path):
    p = str(tmp_path / "j.bin")
    journal = RequestJournal(p, fsync=False)
    sched = _sched(journal=journal, n_lanes=2)
    try:
        unseeded = Request(prompt="no seed given", max_tokens=4,
                           temperature=0.9)  # draws OS entropy at claim
        cancelled = Request(prompt="queued forever", max_tokens=4)
        sched.submit(unseeded)
        unseeded.future.result(timeout=30)
        cancelled.cancel()  # resolved while queued: never admitted
    finally:
        sched.stop()
        journal.close()
    img = read_journal(p)
    e = img.entries[unseeded.id]
    assert e.seed != 0  # the RESOLVED draw, not the None the client sent
    assert e.finished and e.finish_reason == "length"
    assert e.tokens  # prompt tokens journaled
    # never-admitted requests are not journaled at all
    assert cancelled.id not in img.entries
    assert img.incomplete() == []


# ---------------------------------------------------------------------------
# THE headline: crash mid-churn, recover, byte-identical resumed streams
# ---------------------------------------------------------------------------


def _run_reference(reqs):
    """The uninterrupted streams, as (token_index, delta) lists."""
    sched = _sched(n_lanes=4)
    caps = []
    try:
        for rq in reqs:
            cap = []
            rq.on_delta = (
                lambda d, c=cap, r=rq: c.append((len(r.generated_tokens), d))
            )
            caps.append(cap)
            sched.submit(rq)
        for rq in reqs:
            rq.future.result(timeout=60)
    finally:
        sched.stop()
    return caps


def _crash_run(journal, reqs, min_deltas=5):
    """Submit under churn, capture the 'client view' pre-kill, then die:
    detach the journal (nothing after this reaches disk — the process is
    gone) and stop. Returns (pre-kill views, delivered watermarks)."""
    sched = _sched(journal=journal, n_lanes=4)
    pre = [[] for _ in reqs]
    delivered = [0] * len(reqs)

    def cb(i, rq):
        def on_delta(d):
            pre[i].append((len(rq.generated_tokens), d))
            delivered[i] = len(rq.generated_tokens)
            journal.note_progress(rq.id, delivered[i])
        return on_delta

    for i, rq in enumerate(reqs):
        rq.on_delta = cb(i, rq)
        sched.submit(rq)
        time.sleep(0.004)  # staggered churn arrivals
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and any(
        len(v) < min_deltas for v in pre
    ):
        time.sleep(0.002)
    sched.journal = None  # the kill: no finish records ever land
    journal.flush()
    journal.close()
    sched.stop()
    return pre, delivered


def _reattach_all(registry, incomplete, delivered_by_id):
    """Reattach a 'client' per recovered stream at its true
    Last-Event-ID; drain to done."""
    out = {}
    for e in incomplete:
        got = registry.attach(e.request_id)
        assert got is not None, f"stream {e.request_id} not reattachable"
        _req, relay, _kind, gen = got
        last = delivered_by_id[e.request_id]
        assert last >= e.watermark  # journal trails delivery
        got_deltas = []
        while True:
            item = relay.next_after(last, timeout=60, gen=gen)
            assert item is not None, "recovered stream stalled"
            if item[0] == "delta":
                _, last, text = item
                got_deltas.append((last, text))
            else:
                assert item == ("done",), item
                break
        out[e.request_id] = got_deltas
    return out


def test_crash_recovery_streams_byte_identical(tmp_path):
    """Kill the scheduler mid-stream under churn, restart with journal
    recovery, reattach each client at its Last-Event-ID: every resumed
    stream equals its uninterrupted run exactly — zero lost, zero
    duplicated tokens — even though the restarted scheduler has HALF the
    lanes (different lane placement)."""
    refs = _reqs(3)
    ref_streams = _run_reference(refs)

    p = str(tmp_path / "j.bin")
    journal = RequestJournal(p, progress_every=1, fsync=False)
    crash = _reqs(3)
    pre, delivered = _crash_run(journal, crash)
    img = read_journal(p)
    incomplete = img.incomplete()
    assert len(incomplete) == 3  # all were mid-flight: no finish records

    registry = StreamRegistry(grace_s=30.0)
    sched2 = _sched(n_lanes=2)  # DIFFERENT lane geometry than the crash run
    try:
        coordinator = recover_scheduler(sched2, p, registry=registry)
        assert coordinator.join(60)
        delivered_by_id = {
            rq.id: delivered[i] for i, rq in enumerate(crash)
        }
        resumed = _reattach_all(registry, incomplete, delivered_by_id)
    finally:
        sched2.stop()
        registry.close()

    lost = dup = 0
    for i, rq in enumerate(crash):
        view = pre[i] + resumed[rq.id]
        seen = {}
        for idx, text in view:
            if idx in seen:
                dup += 1
            seen[idx] = text
        ref = dict(ref_streams[i])
        lost += sum(1 for idx in ref if idx not in seen)
        assert "".join(t for _, t in sorted(seen.items())) == "".join(
            t for _, t in sorted(ref.items())
        ), f"stream {i} diverged across the crash"
    assert lost == 0 and dup == 0
    stats = coordinator.stats()
    assert stats["recovered_requests"] == 3
    assert stats["recovery_failed"] == 0
    assert stats["recovery_replayed_tokens"] == sum(
        e.watermark for e in incomplete
    )
    # fresh ids never collide with recovered ones
    assert Request(prompt="fresh").id > max(e.request_id for e in incomplete)


def test_reattach_below_journal_watermark_no_gap(tmp_path):
    """A crash strands socket-written-but-never-received deltas: the
    journaled watermark trails transport WRITES, so it can run AHEAD of
    the client's true position. Recovery must not fast-forward through
    it — a client reattaching at its honest (lower) Last-Event-ID gets
    every missing delta back, byte-identical, not a resume_gap."""
    p = str(tmp_path / "j.bin")
    journal = RequestJournal(p, progress_every=1, fsync=False)
    reqs = _reqs(1)
    pre, _delivered = _crash_run(journal, reqs, min_deltas=8)
    # the dead server journaled further than the client ever received:
    # the client's honest position is only the 3rd delta
    client_last = pre[0][2][0]
    client_prefix = pre[0][:3]
    incomplete = read_journal(p).incomplete()
    assert incomplete[0].watermark > client_last  # the hazard is real

    registry = StreamRegistry(grace_s=30.0)
    sched2 = _sched(n_lanes=2)
    try:
        coordinator = recover_scheduler(sched2, p, registry=registry)
        assert coordinator.join(60)
        got = registry.attach(reqs[0].id)
        assert got is not None, "recovered stream not reattachable"
        _req2, relay, _kind, gen = got
        last, resumed = client_last, []
        while True:
            item = relay.next_after(last, timeout=60, gen=gen)
            assert item is not None, "recovered stream stalled"
            assert item[0] != "gap", (
                "honest Last-Event-ID below the watermark must not gap"
            )
            if item == ("done",):
                break
            _, last, text = item
            resumed.append((last, text))
    finally:
        registry.close()
        sched2.stop()
    [ref] = _run_reference(_reqs(1))
    got_stream = client_prefix + resumed
    assert got_stream == ref, (
        f"diverged:\n  ref={ref}\n  got={got_stream}"
    )


def test_completed_requests_not_resurrected(tmp_path):
    """A request that FINISHED before the crash has a finish record and
    is not re-admitted; only the mid-flight one replays."""
    p = str(tmp_path / "j.bin")
    journal = RequestJournal(p, progress_every=1, fsync=False)
    sched = _sched(journal=journal, n_lanes=2)
    done = Request(prompt="short one", max_tokens=3)
    live = Request(prompt="long one", max_tokens=60)
    caught = []
    live.on_delta = caught.append
    try:
        sched.submit(done)
        done.future.result(timeout=30)
        sched.submit(live)
        deadline = time.monotonic() + 30
        while len(caught) < 3 and time.monotonic() < deadline:
            time.sleep(0.002)
    finally:
        sched.journal = None
        journal.flush()
        journal.close()
        sched.stop()
    incomplete = read_journal(p).incomplete()
    assert [e.request_id for e in incomplete] == [live.id]

    sched2 = _sched(n_lanes=2)
    try:
        coordinator = recover_scheduler(sched2, p)
        assert coordinator.join(60)
        assert coordinator.stats()["recovered_requests"] == 1
        assert [r.id for r in coordinator.requests] == [live.id]
        assert all(r.recovered for r in coordinator.requests)
        for r in coordinator.requests:
            r.future.result(timeout=30)
    finally:
        sched2.stop()


def test_recovery_composes_with_breaker(tmp_path):
    """A restart into an open breaker does not stampede: the replay is
    shed like any client, retries on the breaker's hint, and lands once
    the half-open probe window opens."""
    p = str(tmp_path / "j.bin")
    journal = RequestJournal(p, progress_every=1, fsync=False)
    crash = _reqs(2, max_tokens=30)
    _crash_run(journal, crash, min_deltas=3)

    breaker = CircuitBreaker(threshold=1, cooldown_s=0.4)
    breaker.trip("still recovering from the crash")
    sched2 = _sched(n_lanes=2, breaker=breaker)
    try:
        coordinator = recover_scheduler(sched2, p, pace_s=0.01)
        assert coordinator.join(60)
        stats = coordinator.stats()
        assert stats["recovered_requests"] == 2
        assert stats["recovery_retries"] >= 1  # it WAS shed, then paced in
        for r in coordinator.requests:
            r.future.result(timeout=30)
        assert breaker.state == "closed"  # the replay was the probe
    finally:
        sched2.stop()


def test_recovery_replay_fault_contained(tmp_path):
    """An injected recovery.replay fault skips one entry (counted) and
    the rest still recover."""
    p = str(tmp_path / "j.bin")
    journal = RequestJournal(p, progress_every=1, fsync=False)
    crash = _reqs(3, max_tokens=30)
    _crash_run(journal, crash, min_deltas=3)
    faults.arm("recovery.replay:@1:n=1")
    sched2 = _sched(n_lanes=2)
    try:
        coordinator = recover_scheduler(sched2, p)
        assert coordinator.join(60)
        stats = coordinator.stats()
        assert stats["recovery_failed"] == 1
        assert stats["recovered_requests"] == 2
        for r in coordinator.requests:
            r.future.result(timeout=30)
    finally:
        sched2.stop()


# ---------------------------------------------------------------------------
# HTTP: SSE ids, live reattach, recovery counters reconcile
# ---------------------------------------------------------------------------


def _serve(sched, registry=None):
    from distributed_llama_multiusers_tpu.server import ApiServer
    from distributed_llama_multiusers_tpu.tokenizer import TemplateType

    api = ApiServer(sched, TokenTextTokenizer(64), model_name="jrnl",
                    template_type=TemplateType.LLAMA2, resume=registry)
    httpd = api.serve(host="127.0.0.1", port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return api, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _read_sse(resp):
    """[(event_id | None, payload_str)] until [DONE]."""
    out, cur_id = [], None
    for line in resp:
        line = line.decode().strip()
        if line.startswith("id: "):
            cur_id = int(line[4:])
        elif line.startswith("data: "):
            out.append((cur_id, line[6:]))
            cur_id = None
            if line == "data: [DONE]":
                break
    return out


def test_sse_chunks_carry_token_index_ids():
    sched = _sched(n_lanes=2)
    _api, httpd, base = _serve(sched)
    try:
        req = urllib.request.Request(
            base + "/v1/chat/completions",
            data=json.dumps({
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 6, "stream": True,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            events = _read_sse(r)
        assert events[-1][1] == "[DONE]"
        ids = [i for i, _ in events[:-1] if i is not None]
        # monotone token indices 1..n, terminal stamped with the total
        assert ids[: len(ids) - 1] == list(range(1, len(ids)))
        assert ids[-1] == len(ids) - 1
    finally:
        httpd.shutdown()
        sched.stop()


def test_live_disconnect_reattach_within_grace():
    """Drop the connection mid-stream; the request keeps generating
    (grace window), and a GET /v1/stream/<id> with Last-Event-ID picks
    up exactly where the client left off — no gap, no repeat."""
    registry = StreamRegistry(grace_s=10.0)
    sched = _sched(n_lanes=2)
    _api, httpd, base = _serve(sched, registry)
    try:
        req = urllib.request.Request(
            base + "/v1/chat/completions",
            data=json.dumps({
                "messages": [{"role": "user", "content": "hello there"}],
                "max_tokens": 30, "stream": True,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        r = urllib.request.urlopen(req, timeout=30)
        got, cur_id, rid = [], None, None
        for line in r:
            line = line.decode().strip()
            if line.startswith("id: "):
                cur_id = int(line[4:])
            elif line.startswith("data: "):
                payload = json.loads(line[6:])
                rid = int(payload["id"].split("-")[1])
                got.append((cur_id, line[6:]))
                if len(got) >= 4:
                    break
        r.close()  # the disconnect: server sees a broken pipe on write
        last_seen = got[-1][0]
        assert last_seen is not None and rid is not None

        req2 = urllib.request.Request(
            base + f"/v1/stream/{rid}",
            headers={"Last-Event-ID": str(last_seen)},
        )
        deadline = time.monotonic() + 20
        events = None
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(req2, timeout=30) as r2:
                    events = _read_sse(r2)
                break
            except urllib.error.HTTPError:
                time.sleep(0.05)  # detach may not have landed yet
        assert events is not None and events[-1][1] == "[DONE]"
        ids = [i for i, _ in events[:-1] if i is not None]
        assert ids[0] == last_seen + 1  # resumes exactly after Last-Event-ID
        # delta ids are gapless through the end of the stream
        assert ids[:-1] == list(range(last_seen + 1, ids[-2] + 1))
        term = json.loads(events[-2][1])
        assert term["choices"][0]["finish_reason"] in ("length", "stop")
    finally:
        httpd.shutdown()
        registry.close()
        sched.stop()


def test_shed_streaming_post_does_not_leak_registry_entry():
    """A streaming POST registers its relay at build time; a shed at
    submit (draining/breaker/queue-full) must drop that entry — nothing
    will ever resolve the future or detach it, so the sweep alone would
    leak one entry per shed."""
    registry = StreamRegistry(grace_s=5.0)
    sched = _sched(n_lanes=2)
    _api, httpd, base = _serve(sched, registry)
    try:
        sched._draining.set()  # every submit sheds with 503
        req = urllib.request.Request(
            base + "/v1/chat/completions",
            data=json.dumps({
                "messages": [{"role": "user", "content": "x"}],
                "max_tokens": 4, "stream": True,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 503
        assert registry.depth() == 0  # the shed entry was discarded
        sched._draining.clear()
    finally:
        httpd.shutdown()
        registry.close()
        sched.stop()


def test_stream_route_404s():
    registry = StreamRegistry(grace_s=1.0)
    sched = _sched(n_lanes=2)
    _api, httpd, base = _serve(sched, registry)
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/v1/stream/424242", timeout=10)
        assert e.value.code == 404
    finally:
        httpd.shutdown()
        registry.close()
        sched.stop()


def test_recovery_counters_reconcile_stats_vs_metrics(tmp_path):
    """Acceptance criterion: after a recovery, /stats and /metrics agree
    field-for-field on the journal + recovery counters."""
    p = str(tmp_path / "j.bin")
    journal = RequestJournal(p, progress_every=1, fsync=False)
    crash = _reqs(2, max_tokens=30)
    _crash_run(journal, crash, min_deltas=3)

    journal2 = RequestJournal(p, fsync=False)  # the restarted process's
    sched2 = _sched(journal=journal2, n_lanes=2)
    registry = StreamRegistry(grace_s=10.0)
    _api, httpd, base = _serve(sched2, registry)
    try:
        coordinator = recover_scheduler(sched2, p, registry=registry)
        assert coordinator.join(60)
        for r in coordinator.requests:
            r.future.result(timeout=30)
        sched2.journal.flush()

        with urllib.request.urlopen(base + "/stats", timeout=10) as r:
            stats = json.loads(r.read())
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            metrics = r.read().decode()

        assert stats["recovered_requests"] == 2
        assert stats["recovery_incomplete"] == 2
        assert stats["recovery_done"] is True
        assert stats["journal_records"] >= 2  # the re-admission records

        gauges = {}
        for line in metrics.splitlines():
            if line.startswith("#") or not line.strip():
                continue
            name, _, value = line.rpartition(" ")
            gauges[name] = float(value)
        for field in ("recovered_requests", "recovery_incomplete",
                      "recovery_failed", "recovery_retries",
                      "recovery_replayed_tokens", "journal_records",
                      "journal_errors"):
            assert gauges[f"dllama_stats_{field}"] == float(stats[field]), field
        # the native delta-fed counters track the same totals
        assert gauges["dllama_recovered_requests_total"] == float(
            stats["recovered_requests"]
        )
        assert gauges["dllama_journal_records_total"] == float(
            stats["journal_records"]
        )
    finally:
        httpd.shutdown()
        registry.close()
        sched2.stop()
        journal2.close()


# ---------------------------------------------------------------------------
# id-floor hygiene
# ---------------------------------------------------------------------------


def test_ensure_request_id_floor():
    a = Request(prompt="a")
    ensure_request_id_floor(a.id + 1000)
    b = Request(prompt="b")
    assert b.id > a.id + 1000
