"""The runtime resource-leak witness (analysis/leakcheck.py,
``DLLAMA_LEAKCHECK=1``): resource lifecycles proven drained at runtime.

Layers, mirroring tests/test_jitcheck.py / test_lockcheck.py:

- **wiring** — counting-mode accumulation, strict-mode raising, the
  ``force(fresh=True)`` reset, the /stats surface shape;
- **the serving pin** — a REAL scheduler churn over the mock engine
  under the forced witness: submit, generate, stop — and the drain
  snapshot reads all-zero (``leak_counts()`` is the authoritative
  source, not a shadow counter);
- **the firing regression** — a deliberately leaked StreamRegistry
  entry (registered, never serviced, never discarded: the PR 10 shed
  class) makes ``close()`` RAISE under the witness and the counter
  record it;
- **the tier-1 fixture pattern** — a subprocess rerun of the serving +
  prefix-cache suites with ``DLLAMA_LEAKCHECK=1`` in the environment
  (the env path, not ``force()``), the test_lockcheck.py recipe.
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

import pytest

from distributed_llama_multiusers_tpu.analysis import leakcheck
from distributed_llama_multiusers_tpu.analysis.leakcheck import ResourceLeak
from distributed_llama_multiusers_tpu.runtime.scheduler import (
    ContinuousBatchingScheduler,
    Request,
)
from distributed_llama_multiusers_tpu.serving import StreamRegistry
from distributed_llama_multiusers_tpu.utils.testing import (
    MockAsyncEngine,
    StubStreamTokenizer,
)

REPO_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture
def witness_on():
    """Force strict mode (fresh counters) and restore the env-driven
    default afterwards."""
    leakcheck.force(True, fresh=True)
    try:
        yield
    finally:
        leakcheck.force(None, fresh=True)


@pytest.fixture
def witness_off():
    """Counting-only mode, fresh counters."""
    leakcheck.force(False, fresh=True)
    try:
        yield
    finally:
        leakcheck.force(None, fresh=True)


# -- wiring -------------------------------------------------------------------


def test_resource_leak_is_assertion_error():
    assert issubclass(ResourceLeak, AssertionError)


def test_counting_mode_counts_without_raising(witness_off):
    leaked = leakcheck.check_drained("t", {"kv_pages": 3, "marks": 0})
    assert leaked == 3
    assert leakcheck.leaks_total() == 3
    assert leakcheck.live_counts() == {"kv_pages": 3, "marks": 0}
    assert leakcheck.last_leak() == {
        "where": "t", "leaked": {"kv_pages": 3}
    }
    # a later clean drain updates the gauge but not the lifetime counter
    assert leakcheck.check_drained("t", {"kv_pages": 0}) == 0
    assert leakcheck.leaks_total() == 3
    assert leakcheck.live_counts()["kv_pages"] == 0


def test_strict_mode_raises_and_counts(witness_on):
    with pytest.raises(ResourceLeak, match="kv_pages"):
        leakcheck.check_drained("stop", {"kv_pages": 2})
    assert leakcheck.leaks_total() == 2


def test_clean_drain_never_raises(witness_on):
    assert leakcheck.check_drained("stop", {"kv_pages": 0}) == 0
    assert leakcheck.leaks_total() == 0


def test_force_fresh_resets_counters(witness_off):
    leakcheck.check_drained("t", {"x": 5})
    leakcheck.force(False, fresh=True)
    assert leakcheck.leaks_total() == 0
    assert leakcheck.live_counts() == {}
    assert leakcheck.last_leak() is None


def test_stats_surface_shape(witness_off):
    leakcheck.check_drained("t", {"x": 1})
    s = leakcheck.stats()
    assert s["resource_leaks_total"] == 1
    assert s["resource_drain_checks"] == 1
    assert s["resources_live"] == {"x": 1}


# -- the serving pin: a real churn drains clean ------------------------------


def test_scheduler_stop_drains_clean(witness_on):
    engine = MockAsyncEngine(n_lanes=2)
    sched = ContinuousBatchingScheduler(
        engine, StubStreamTokenizer(engine.config.vocab_size),
        speculative=False, prefix_min_tokens=0,
    )
    reqs = [
        Request(prompt=f"drain pin {i}", max_tokens=8, temperature=0.0)
        for i in range(4)
    ]
    sched.start()
    try:
        for r in reqs:
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=60)
    finally:
        sched.stop()  # raises ResourceLeak if anything is still held
    assert all(r.error is None for r in reqs)
    assert all(v == 0 for v in sched.leak_counts().values())
    assert leakcheck.leaks_total() == 0


def test_scheduler_stop_mid_flight_drains_clean(witness_on):
    """The crash-sim shape every recovery test uses: stop with lanes
    mid-decode — _resolve_exit must settle every mirror record."""
    engine = MockAsyncEngine(n_lanes=2, step_s=0.01)
    sched = ContinuousBatchingScheduler(
        engine, StubStreamTokenizer(engine.config.vocab_size),
        speculative=False, prefix_min_tokens=0,
    )
    reqs = [
        Request(prompt=f"mid-flight {i}", max_tokens=500, temperature=0.0)
        for i in range(2)
    ]
    sched.start()
    for r in reqs:
        sched.submit(r)
    while not any(r.generated_tokens for r in reqs):
        pass
    sched.stop()  # force-cancels the lanes; must still drain clean
    assert all(v == 0 for v in sched.leak_counts().values())


# -- the firing regression: a leaked registry entry is caught ----------------


def test_leaked_registry_entry_fires(witness_on):
    """Register a request that never enters service and never gets
    discarded — the orphan class nothing can reap. close() must raise."""
    registry = StreamRegistry(grace_s=60.0)
    leaked = Request(prompt="never serviced", max_tokens=4)
    registry.register(leaked)
    with pytest.raises(ResourceLeak, match="stream_entries"):
        registry.close()
    assert leakcheck.leaks_total() == 1
    assert leakcheck.last_leak()["where"] == "stream registry close"


def test_leaked_registry_entry_counted_without_witness(witness_off):
    registry = StreamRegistry(grace_s=60.0)
    registry.register(Request(prompt="never serviced", max_tokens=4))
    registry.close()  # counting mode: no raise
    assert leakcheck.leaks_total() == 1


def test_discarded_entry_is_clean(witness_on):
    """The fix for the orphan class: discard() releases the entry."""
    registry = StreamRegistry(grace_s=60.0)
    req = Request(prompt="shed at submit", max_tokens=4)
    registry.register(req)
    registry.discard(req.id)
    registry.close()
    assert leakcheck.leaks_total() == 0


def test_resolved_entry_is_clean(witness_on):
    """A finished stream's entry is retention, not a leak — the reaper
    owns its grace expiry."""
    registry = StreamRegistry(grace_s=60.0)
    req = Request(prompt="served", max_tokens=4)
    registry.register(req)
    req.future.set_result("done")
    registry.close()
    assert leakcheck.leaks_total() == 0


# -- the env path, end to end ------------------------------------------------


@pytest.mark.slow
def test_serving_suites_leak_free_under_env_flag():
    """Rerun the scheduler-serving and prefix-cache suites in a
    subprocess with DLLAMA_LEAKCHECK=1: every stop()/close() they
    perform becomes a raising drain point. Green = the whole serving
    lifecycle holds nothing at any drain."""
    env = dict(os.environ)
    env["DLLAMA_LEAKCHECK"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "pytest",
         "tests/test_scheduler_serving.py", "tests/test_prefix_cache.py",
         "-q", "-p", "no:cacheprovider"],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
        timeout=600,
    )
    assert proc.returncode == 0, (
        f"serving suites leaked under DLLAMA_LEAKCHECK=1:\n"
        f"{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}"
    )
