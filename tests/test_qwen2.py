"""Qwen2-family support: q/k/v projection biases (KEY_QKV_BIAS) + ChatML.

The reference runtime executes only the bias-free Llama graph
(src/llm.cpp:21-24); Qwen2 support is a framework extension: the same graph
plus per-layer q/k/v biases carried in the .m file (bias tensors follow
their matmul tensors, formats/model_file.py model_tensor_specs) and the
ChatML turn template (tokenizer/chat.py).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats import load_model_header
from distributed_llama_multiusers_tpu.formats.model_file import model_tensor_specs
from distributed_llama_multiusers_tpu.formats.synthetic import (
    tiny_header,
    write_synthetic_model,
)
from distributed_llama_multiusers_tpu.models import (
    init_kv_cache,
    llama_forward,
    load_params_from_m,
)
from distributed_llama_multiusers_tpu.models.loader import (
    load_params_from_m_quantized,
)
from distributed_llama_multiusers_tpu.models.oracle import (
    OracleLlama,
    oracle_weights_from_m,
)
from distributed_llama_multiusers_tpu.tokenizer.chat import (
    ChatItem,
    ChatTemplateGenerator,
    TemplateType,
)

from test_model_parity import jax_greedy


@pytest.fixture(scope="module")
def qwen_model(tmp_path_factory):
    d = tmp_path_factory.mktemp("qwen2")
    header = tiny_header(
        dim=64, hidden_dim=160, n_layers=2, n_heads=4, n_kv_heads=2,
        vocab_size=96, seq_len=48, qkv_bias=1,
    )
    path = str(d / "qwen.m")
    write_synthetic_model(path, header, seed=11)
    return path


def test_header_carries_qkv_bias(qwen_model):
    h = load_model_header(qwen_model)
    assert h.qkv_bias == 1
    names = [s.name for s in model_tensor_specs(h)]
    assert names.index("block_bias_q") == names.index("block_matmul_q") + 1
    assert names.index("block_bias_k") == names.index("block_matmul_k") + 1
    assert names.index("block_bias_v") == names.index("block_matmul_v") + 1
    # the walk must consume the file exactly (src/llm.cpp:477-479 semantics)
    last = model_tensor_specs(h)[-1]
    assert last.offset + last.n_bytes == h.file_size


def test_biasfree_header_unchanged(tiny_model):
    """Bias-free files never see the new key: header parse yields 0 and the
    walk has no bias tensors (old files stay byte-identical)."""
    h = load_model_header(tiny_model["model"])
    assert h.qkv_bias == 0
    assert not [s for s in model_tensor_specs(h) if s.name.startswith("block_bias")]


def test_greedy_parity_vs_oracle(qwen_model):
    """BASELINE.md's token-identity bar, with biases in the graph."""
    h = load_model_header(qwen_model)
    config, params = load_params_from_m(qwen_model, h, dtype=jnp.float32)
    assert config.qkv_bias == 1
    assert params.layers.bq is not None and params.layers.bq.shape == (2, 64)
    assert params.layers.bk.shape == (2, 32)
    oracle = OracleLlama(config, oracle_weights_from_m(qwen_model, h), emulate_q80=True)
    prompt = [1, 17, 42, 9]
    assert jax_greedy(config, params, prompt, 16) == oracle.generate_greedy(prompt, 16)


def test_bias_changes_the_output(qwen_model):
    """Guard against the graph silently dropping the bias leaves."""
    h = load_model_header(qwen_model)
    config, params = load_params_from_m(qwen_model, h, dtype=jnp.float32)
    zeroed = params._replace(
        layers=params.layers._replace(
            bq=jnp.zeros_like(params.layers.bq),
            bk=jnp.zeros_like(params.layers.bk),
            bv=jnp.zeros_like(params.layers.bv),
        )
    )
    tok = jnp.array([[5]], jnp.int32)
    pos = jnp.array([[0]], jnp.int32)
    with_b, _ = llama_forward(config, params, tok, pos, init_kv_cache(config, 1))
    without_b, _ = llama_forward(config, zeroed, tok, pos, init_kv_cache(config, 1))
    assert np.abs(np.asarray(with_b) - np.asarray(without_b)).max() > 1e-4


def test_quantized_loader_parity(qwen_model):
    """PackedQ40-resident load keeps the bias leaves; stream matches the
    dense f32 load (same dequant numerics: Q40 is exact through f32)."""
    h = load_model_header(qwen_model)
    config_d, params_d = load_params_from_m(qwen_model, h, dtype=jnp.float32)
    config_q, params_q = load_params_from_m_quantized(qwen_model, h, dtype=jnp.float32)
    assert params_q.layers.bq is not None
    prompt = [3, 8, 21]
    assert jax_greedy(config_d, params_d, prompt, 12) == jax_greedy(
        config_q, params_q, prompt, 12
    )


def test_sharded_forward_with_bias(qwen_model):
    """TP-sharded placement: bias vectors shard along the same tp axis as
    their matmul outputs; the sharded stream matches the unsharded one."""
    from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params

    h = load_model_header(qwen_model)
    config, params = load_params_from_m(qwen_model, h, dtype=jnp.float32)
    mesh = make_mesh(MeshPlan(tp=2))
    sharded = shard_params(params, mesh)
    assert sharded.layers.bq.sharding.spec[-1] == "tp"
    prompt = [1, 17, 42]
    ref = jax_greedy(config, params, prompt, 8)
    got = jax_greedy(config, sharded, prompt, 8)
    assert got == ref


def test_training_updates_biases(qwen_model):
    """The training twin carries the bias leaves: one optimizer step moves
    bq/bk/bv (gradients flow through the biased projections)."""
    import optax

    from distributed_llama_multiusers_tpu.training import Trainer

    h = load_model_header(qwen_model)
    config, params = load_params_from_m(qwen_model, h, dtype=jnp.float32)
    t = Trainer(config, params, optax.adamw(1e-2))
    before = {
        k: np.asarray(getattr(t.params.layers, k)).copy()
        for k in ("bq", "bk", "bv")
    }
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, config.vocab_size, size=(2, 16)).astype(np.int32)
    loss = t.step(tokens)
    assert np.isfinite(loss)
    for k, b in before.items():
        after = np.asarray(getattr(t.params.layers, k))
        assert np.abs(after - b).max() > 0, k


def test_chatml_template():
    gen = ChatTemplateGenerator(
        TemplateType.UNKNOWN,
        "{% for m in messages %}<|im_start|>{{ m.role }}...{% endfor %}",
        "<|im_end|>",
    )
    assert gen.type == TemplateType.CHATML
    chat = gen.generate(
        [ChatItem("system", "be brief"), ChatItem("user", "hi")],
        append_generation_prompt=True,
    )
    assert chat.content == (
        "<|im_start|>system\nbe brief<|im_end|>\n"
        "<|im_start|>user\nhi<|im_end|>\n"
        "<|im_start|>assistant\n"
    )
    # Qwen semantics: a conversation without a system turn gets the
    # family's default system prompt prepended
    chat = gen.generate([ChatItem("user", "hi")], append_generation_prompt=False)
    assert chat.content == (
        "<|im_start|>system\nYou are a helpful assistant.<|im_end|>\n"
        "<|im_start|>user\nhi<|im_end|>\n"
    )
