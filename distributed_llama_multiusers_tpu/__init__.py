"""distributed-llama-multiusers_tpu — TPU-native distributed multi-user LLM inference.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
`LatadosUnited/distributed-llama-MultiUsers` (C++/TCP tensor-parallel Llama
inference with a multi-user continuous-batching server):

- Q40/Q80 block quantization and the `.m` / `.t` binary formats
  (reference: src/nn/nn-quants.cpp, src/llm.cpp, src/tokenizer.cpp)
- a pure-functional Llama model compiled by XLA, with quantized weights
  (reference: src/llm.cpp buildLlmNet)
- tensor/data/sequence parallelism over a `jax.sharding.Mesh` with XLA
  collectives over ICI in place of the reference's full-mesh TCP
  (reference: src/nn/nn-network.cpp)
- a lane-based continuous-batching engine + OpenAI-ish HTTP server
  (reference: src/Request.hpp, src/app.cpp inference_loop, src/dllama-api.cpp)
"""

__version__ = "0.1.0"
