"""Prompt-lookup draft index for speculative decoding.

Drafts come from the token stream itself: the previous occurrence of the
current suffix n-gram (3-gram, falling back to 2-gram) proposes the tokens
that followed it — no draft model. The index is maintained incrementally
(each committed token updates two dict entries), so a draft probe is O(1)
per step instead of a backward history scan.

Used by the continuous-batching scheduler (per lane) and the CLI inference
loop (single stream). The engine's verify program
(`InferenceEngine.decode_spec`) guarantees the speculative-verification
identity: greedy output streams are exactly the plain-decode streams.
"""

from __future__ import annotations

# drafts per speculative step (K = SPEC_DRAFT + 1 verified tokens); shared
# by the engine's verify program and the control plane's packet sizing
SPEC_DRAFT = 3


class NgramDraftIndex:
    """Committed token history + n-gram -> last-start-position index."""

    GRAM_SIZES = (2, 3)

    def __init__(self, tokens=()):
        self.hist: list[int] = []
        self._last: dict = {}
        for t in tokens:
            self.append(t)

    def append(self, tok: int) -> None:
        self.hist.append(tok)
        for g in self.GRAM_SIZES:
            if len(self.hist) >= g:
                self._last[(g, tuple(self.hist[-g:]))] = len(self.hist) - g

    def draft(self, next_token: int, k: int) -> list[int]:
        """Up to k draft tokens continuing (hist + [next_token]). The probe
        gram ends at next_token, which is not yet committed, so a hit is
        always a strictly earlier occurrence."""
        hist = self.hist
        for g in sorted(self.GRAM_SIZES, reverse=True):
            if len(hist) < g - 1:
                continue
            tail = (*hist[len(hist) - g + 1:], next_token)
            j = self._last.get((g, tail))
            if j is not None:
                cont = hist[j + g : j + g + k]
                if cont:
                    return cont
        return []
