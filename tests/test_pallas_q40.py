"""Pallas Q40 matmul kernel vs the XLA fallback (interpret mode on CPU).

The reference's kernel-equivalence analogue is matmul_Q80_Q40_F32 vs
matmul_F32 (src/nn/nn-cpu-ops-test.cpp:220-241); here the Pallas kernel and
q40_matmul_xla dequantize identically, so results must agree to float
rounding, not a quantization tolerance.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_llama_multiusers_tpu.ops.pallas_q40 import (
    _f16_bits_to_f32,
    q40_matmul_pallas,
)
from distributed_llama_multiusers_tpu.quants.packed import (
    PackedQ40,
    pack_q40_host,
    q40_matmul_xla,
)


def _pack(rng, d_out, d_in, scale=0.1):
    w = rng.standard_normal((d_out, d_in), dtype=np.float32) * scale
    packed, scales = pack_q40_host(w)
    return PackedQ40(packed=jnp.asarray(packed), scales=jnp.asarray(scales))


def test_f16_bit_conversion_exact():
    # every finite f16 bit pattern converts exactly (incl. denormals)
    bits = np.arange(65536, dtype=np.uint16)
    h = bits.view(np.float16)
    finite = np.isfinite(h)
    got = np.asarray(_f16_bits_to_f32(jnp.asarray(bits.astype(np.int16))))
    np.testing.assert_array_equal(got[finite], h[finite].astype(np.float32))


@pytest.mark.parametrize(
    "m,d_in,d_out",
    [
        (1, 64, 128),
        (5, 256, 384),
        (8, 2048, 512),
        (16, 128, 256),
        # d_in with no power-of-two chunk divisor (1376 = 43*32): the analogue
        # of Llama-2-7B's hidden_dim 11008 that crashed the halves layout
        (3, 1376, 128),
        # Llama-2-7B hidden_dim itself: d_out > 8192 with no 512-multiple
        # divisor — the wide-tile planner must fall back to 128-multiples
        # (5504 = 43*128), not reject the shape
        (2, 256, 11008),
        # and its tp=2 shard: d_out <= 8192, 512-multiple + 384 remainder
        (2, 256, 5504),
        # multi-chunk reduction (n_k > 1): half=2048 x W=2048 exceeds the
        # single-slab budget, exercising the k-axis accumulator
        (4, 4096, 2048),
        # wide-tile grid (j > 1): d_out 16384 tiles as 2 x 8192
        (2, 512, 16384),
        # multiple m tiles: m_pad 512 = 2 x 256 with full-extent checks on
        # the bsum lane dim
        (300, 64, 256),
    ],
)
def test_pallas_matches_xla(m, d_in, d_out):
    rng = np.random.default_rng(d_in + d_out)
    pw = _pack(rng, d_out, d_in)
    x = jnp.asarray(rng.standard_normal((m, d_in), dtype=np.float32))
    ref = q40_matmul_xla(x, pw)
    got = q40_matmul_pallas(x, pw, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4, rtol=2e-4)


def test_pallas_leading_batch_dims():
    rng = np.random.default_rng(0)
    pw = _pack(rng, 256, 128)
    x = jnp.asarray(rng.standard_normal((2, 3, 128), dtype=np.float32))
    ref = q40_matmul_xla(x, pw)
    got = q40_matmul_pallas(x, pw, interpret=True)
    assert got.shape == (2, 3, 256)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4, rtol=2e-4)


def test_pallas_extreme_scales():
    # very small weights -> denormal f16 scales still convert exactly
    rng = np.random.default_rng(1)
    pw = _pack(rng, 128, 64, scale=1e-7)
    x = jnp.asarray(rng.standard_normal((4, 64), dtype=np.float32))
    ref = q40_matmul_xla(x, pw)
    got = q40_matmul_pallas(x, pw, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-10)


# ---------------------------------------------------------------------------
# GSPMD partitioning (q40_matmul_partitioned): the kernel under meshes.
# Round 1 disabled Pallas on any mesh; these pin the custom_partitioning rule
# that keeps dequant-in-matmul on every shard (the reference runs its
# quantized matmul on every node, src/nn/nn-cpu-ops.cpp:222-440).
# ---------------------------------------------------------------------------

from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from distributed_llama_multiusers_tpu.ops.pallas_q40 import q40_matmul_partitioned  # noqa: E402
from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh  # noqa: E402


def _sharded(arr, mesh, *spec):
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


@pytest.mark.parametrize("w_spec,expect_out_tp", [
    ((None, "tp"), True),   # row-sliced: d_out sharded, output stays sharded
    (("tp", None), False),  # col-sliced: d_in sharded, psum -> replicated
])
def test_partitioned_matmul_parity(w_spec, expect_out_tp):
    rng = np.random.default_rng(7)
    pw = _pack(rng, 256, 128)
    x = jnp.asarray(rng.standard_normal((8, 128), dtype=np.float32))
    ref = q40_matmul_xla(x, pw)

    mesh = make_mesh(MeshPlan(tp=2, dp=2))
    w_sh = PackedQ40(
        packed=_sharded(pw.packed, mesh, *w_spec),
        scales=_sharded(pw.scales, mesh, *w_spec),
    )
    x_sh = _sharded(x, mesh, "dp", None)
    f = jax.jit(
        lambda a, p, s: q40_matmul_partitioned(a, PackedQ40(p, s), interpret=True)
    )
    got = f(x_sh, w_sh.packed, w_sh.scales)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4, rtol=2e-4)
    out_axes = set()
    for entry in got.sharding.spec:
        out_axes |= {entry} if isinstance(entry, str) else set(entry or ())
    assert ("tp" in out_axes) == expect_out_tp, got.sharding


def test_sharded_forward_takes_pallas_path(monkeypatch, tmp_path):
    """tp=2 quantized model forward routes through the Pallas kernel
    (interpret mode) and matches the dense single-device forward."""
    import distributed_llama_multiusers_tpu.ops.pallas_q40 as pq
    from distributed_llama_multiusers_tpu.formats.synthetic import (
        tiny_header,
        write_synthetic_model,
    )
    from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
    from distributed_llama_multiusers_tpu.models import init_kv_cache, llama_forward
    from distributed_llama_multiusers_tpu.models.loader import (
        load_params_from_m,
        load_params_from_m_quantized,
    )
    from distributed_llama_multiusers_tpu.ops import linear
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params

    calls = {"n": 0}
    real_kernel = pq.q40_matmul_pallas

    def counting_kernel(x, w, interpret=False, **kw):
        calls["n"] += 1
        return real_kernel(x, w, interpret=interpret, **kw)

    monkeypatch.setattr(pq, "q40_matmul_pallas", counting_kernel)
    linear.set_pallas_interpret(True)
    try:
        path = str(tmp_path / "tiny.m")
        write_synthetic_model(path, tiny_header(), seed=11)
        h = load_model_header(path)
        config, dense_params = load_params_from_m(path, h, dtype=jnp.float32)
        _, qparams = load_params_from_m_quantized(path, h, dtype=jnp.float32)
        tokens = jnp.asarray([[3, 9, 27]], jnp.int32)
        positions = jnp.asarray([[0, 1, 2]], jnp.int32)
        ref, _ = llama_forward(
            config, dense_params, tokens, positions, init_kv_cache(config, 1)
        )

        mesh = make_mesh(MeshPlan(tp=2))
        q_sh = shard_params(qparams, mesh)
        got, _ = llama_forward(
            config, q_sh, tokens, positions, init_kv_cache(config, 1)
        )
    finally:
        linear.set_pallas_interpret(False)

    assert calls["n"] > 0, "sharded forward never reached the Pallas kernel"
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_pallas_bf16_weight_tiles_close():
    """w_dtype=bf16 (the VMEM-bandwidth ablation knob) stays within bf16
    rounding of the exact f32 kernel — reachable via
    linear.set_pallas_w_dtype and the bench ablation."""
    rng = np.random.default_rng(3)
    pw = _pack(rng, 256, 128)
    x = jnp.asarray(rng.standard_normal((4, 128), dtype=np.float32))
    exact = q40_matmul_pallas(x, pw, interpret=True)
    loose = q40_matmul_pallas(x, pw, interpret=True, w_dtype=jnp.bfloat16)
    # bf16 has 8 mantissa bits: ~0.4% relative error per product
    np.testing.assert_allclose(
        np.asarray(loose), np.asarray(exact), rtol=2e-2, atol=2e-2
    )
    assert not np.array_equal(np.asarray(loose), np.asarray(exact))


def test_dequant_mode_variants_close():
    """Every DEQUANT_MODE (the bf16-path arithmetic A/B: v4 f32-chain,
    bf16chain, repeat) stays within bf16 rounding of the exact f32 kernel,
    and the mode switch actually retraces (set_dequant_mode is a static
    arg of the jitted matmul)."""
    from distributed_llama_multiusers_tpu.ops.pallas_q40 import (
        DEQUANT_MODES,
        set_dequant_mode,
    )

    rng = np.random.default_rng(7)
    pw = _pack(rng, 256, 128)
    x = jnp.asarray(rng.standard_normal((4, 128), dtype=np.float32))
    exact = np.asarray(q40_matmul_pallas(x, pw, interpret=True))
    # per-mode error class: bf16-rounding-only chains sit at ~5e-3;
    # i8blockdot ALSO quantizes the activations (reference Q80 class,
    # ~1e-2 mean / 1.6e-2 max over seeds) so it gets the lab's bound
    bound = {"i8blockdot": 5e-2}
    try:
        for mode in DEQUANT_MODES:
            set_dequant_mode(mode)
            got = np.asarray(
                q40_matmul_pallas(x, pw, interpret=True, w_dtype=jnp.bfloat16)
            )
            # bf16 rounding error scales with the CONTRACTION magnitude,
            # not the output element (cancellation leaves small outputs
            # with proportionally larger error) — bound it vs max|y|
            rel = np.abs(got - exact).max() / (np.abs(exact).max() + 1e-9)
            assert rel < bound.get(mode, 2e-2), f"mode {mode}: max-rel {rel:.3e}"
            # exact-f32 dots ignore the mode knob entirely
            f32 = np.asarray(q40_matmul_pallas(x, pw, interpret=True))
            np.testing.assert_array_equal(f32, exact, err_msg=f"mode {mode}")
        # blockdot's post-scale cost scales with m: large-m calls
        # (prefill/training) must RESOLVE to bf16chain (observed via the
        # impl's mode argument — output closeness alone can't distinguish
        # a working fallback from blockdot incorrectly running at m=64)
        from distributed_llama_multiusers_tpu.ops import pallas_q40 as pq

        seen_modes = []
        real_impl = pq._q40_matmul_pallas_impl

        def spy(x_, w_, interpret_, w_dtype_, mode_):
            seen_modes.append(mode_)
            return real_impl(x_, w_, interpret_, w_dtype_, mode_)

        set_dequant_mode("blockdot")
        pq._q40_matmul_pallas_impl = spy
        try:
            x_big = jnp.asarray(
                rng.standard_normal((64, 128), dtype=np.float32)
            )
            exact_big = np.asarray(q40_matmul_pallas(x_big, pw, interpret=True))
            seen_modes.clear()
            got_big = np.asarray(
                q40_matmul_pallas(
                    x_big, pw, interpret=True, w_dtype=jnp.bfloat16
                )
            )
        finally:
            pq._q40_matmul_pallas_impl = real_impl
        assert seen_modes == ["bf16chain"], seen_modes
        rel = np.abs(got_big - exact_big).max() / (np.abs(exact_big).max() + 1e-9)
        assert rel < 2e-2, f"blockdot large-m fallback: max-rel {rel:.3e}"
    finally:
        set_dequant_mode(None)


def test_bf16_w_dtype_greedy_stream_model_scale(tiny_model):
    """End-to-end greedy stream with the SHIPPING TPU numeric default
    (w_dtype=bf16 dots, round-4 advisor finding: that path had no CI
    parity coverage — every other parity gate runs exact f32). On the
    synthetic tiny model the bf16 stream is token-identical to the exact
    f32 kernel stream for 32 tokens; per-step logits stay within bf16
    rounding. ``set_pallas_w_dtype(jnp.float32)`` restores exact-f32
    semantics (README/PERF document the default)."""
    from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
    from distributed_llama_multiusers_tpu.models.loader import (
        load_params_from_m_quantized,
    )
    from distributed_llama_multiusers_tpu.ops import linear
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.utils.testing import greedy_rollout

    h = load_model_header(tiny_model["model"])
    config, qparams = load_params_from_m_quantized(
        tiny_model["model"], h, dtype=jnp.float32
    )
    prompt = [5, 9, 3, 17, 2]

    def rollout(w_dtype):
        linear.set_pallas_interpret(True)
        linear.set_pallas_w_dtype(w_dtype)
        try:
            engine = InferenceEngine(
                config, qparams, n_lanes=1, prefill_buckets=(8,)
            )
            toks, _ = greedy_rollout(engine, prompt, 32)
            logits, _, _ = engine.prefill(0, prompt)
            return toks, np.asarray(logits)
        finally:
            linear.set_pallas_w_dtype(None)
            linear.set_pallas_interpret(False)

    toks_bf16, logits_bf16 = rollout(jnp.bfloat16)
    toks_f32, logits_f32 = rollout(jnp.float32)
    np.testing.assert_allclose(logits_bf16, logits_f32, rtol=2e-2, atol=2e-2)
    assert toks_bf16 == toks_f32, (
        f"bf16-dot greedy stream diverged from exact f32: "
        f"{toks_bf16} vs {toks_f32}"
    )


# ---------------------------------------------------------------------------
# Shared Q80 activation operands (Q80Acts): one build per distinct input,
# every matmul sharing it consumes the prebuilt layouts.
# ---------------------------------------------------------------------------

from distributed_llama_multiusers_tpu.ops.pallas_q40 import (  # noqa: E402
    BLOCKDOT_MAX_M,
    DEQUANT_MODES,
    TRACE_STATS,
    make_q80_acts,
    reset_trace_stats,
    set_dequant_mode,
)


@pytest.mark.parametrize("mode", ["v4", "blockdot", "i8blockdot"])
def test_q80_acts_shared_vs_raw_parity(mode):
    """A prebuilt Q80Acts bundle and a raw activation run the SAME traced
    math per mode — only XLA fusion boundaries differ between the eager
    build and the in-jit build, so i8blockdot (the one mode with a
    reduction in operand prep) sits at ~1e-7 reduction-order wiggle.
    Covers the two acts-consuming modes plus the v4 chain standing in for
    the bf16-chain family (all chains unwrap the bundle via _raw_x on the
    same line, so one representative pins the passthrough)."""
    rng = np.random.default_rng(5)
    pw = _pack(rng, 256, 128)
    x = jnp.asarray(rng.standard_normal((4, 128), dtype=np.float32))
    set_dequant_mode(mode)
    try:
        raw = np.asarray(
            q40_matmul_pallas(x, pw, interpret=True, w_dtype=jnp.bfloat16)
        )
        acts = make_q80_acts(x)
        assert make_q80_acts(acts) is acts  # idempotent
        shared = np.asarray(
            q40_matmul_pallas(acts, pw, interpret=True, w_dtype=jnp.bfloat16)
        )
    finally:
        set_dequant_mode(None)
    np.testing.assert_allclose(shared, raw, rtol=1e-5, atol=1e-5)


def test_q80_acts_build_and_consume_counters():
    """Trace-time counters witness the sharing: one shared build feeds N
    consumes with zero per-site rebuilds."""
    rng = np.random.default_rng(6)
    weights = [_pack(rng, d_out, 128) for d_out in (128, 256, 384)]
    x = jnp.asarray(rng.standard_normal((4, 128), dtype=np.float32))
    reset_trace_stats()
    acts = make_q80_acts(x, shared=True)
    for pw in weights:
        q40_matmul_pallas(acts, pw, interpret=True)
    assert TRACE_STATS["acts_builds"] == 1, TRACE_STATS
    assert TRACE_STATS["shared_builds"] == 1, TRACE_STATS
    assert TRACE_STATS["shared_consumes"] == 3, TRACE_STATS


def test_shared_acts_build_counts_model_scale(tiny_model):
    """THE operand-sharing win at model scale: one llama_forward trace
    builds exactly TWO shared bundles (the normed x for wq/wk/wv; the
    FFN input for w1/w3) consumed at five matmul sites — the layer body
    traces once under lax.scan. The remaining builds are the unshared
    single-consumer sites (wo, w2 in the layer, wcls at the head)."""
    from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
    from distributed_llama_multiusers_tpu.models import init_kv_cache, llama_forward
    from distributed_llama_multiusers_tpu.models.loader import (
        load_params_from_m_quantized,
    )
    from distributed_llama_multiusers_tpu.ops import linear

    h = load_model_header(tiny_model["model"])
    config, qparams = load_params_from_m_quantized(
        tiny_model["model"], h, dtype=jnp.float32
    )
    tokens = jnp.asarray([[3, 9, 27]], jnp.int32)
    positions = jnp.asarray([[0, 1, 2]], jnp.int32)
    linear.set_pallas_interpret(True)
    try:
        reset_trace_stats()
        llama_forward(
            config, qparams, tokens, positions, init_kv_cache(config, 1)
        )
        assert TRACE_STATS["shared_builds"] == 2, TRACE_STATS
        assert TRACE_STATS["shared_consumes"] == 5, TRACE_STATS
        # the only other builds come from the three unshared sites, each
        # at most once per kernel-family trace (0 on a warm jit cache) —
        # never one-per-consumer like the pre-sharing layout
        assert TRACE_STATS["acts_builds"] - 2 <= 3, TRACE_STATS
    finally:
        linear.set_pallas_interpret(False)


def test_blockdot_max_m_cap_routes_and_caches():
    """BLOCKDOT_MAX_M boundary (documented in PERF.md): m at/under the cap
    runs the selected blockdot-family mode, one past it falls back to
    bf16chain — observed via the impl's resolved mode argument — and
    repeated same-shape calls never re-trace the kernel core."""
    from distributed_llama_multiusers_tpu.ops import pallas_q40 as pq

    rng = np.random.default_rng(11)
    pw = _pack(rng, 128, 64)
    seen = []
    real_impl = pq._q40_matmul_pallas_impl

    def spy(x_, w_, interpret_, w_dtype_, mode_):
        seen.append(mode_)
        return real_impl(x_, w_, interpret_, w_dtype_, mode_)

    pq._q40_matmul_pallas_impl = spy
    try:
        for mode in ("blockdot", "i8blockdot"):
            set_dequant_mode(mode)
            for m, expect in [
                (BLOCKDOT_MAX_M - 1, mode),
                (BLOCKDOT_MAX_M, mode),
                (BLOCKDOT_MAX_M + 1, "bf16chain"),
            ]:
                seen.clear()
                x = jnp.asarray(
                    rng.standard_normal((m, 64), dtype=np.float32)
                )
                q40_matmul_pallas(x, pw, interpret=True, w_dtype=jnp.bfloat16)
                assert seen == [expect], (mode, m, seen)
        # auto resolves through the same boundary: the table's decode
        # class IS the blockdot cap, so the m-class flip and the kernel
        # fallback agree at m = BLOCKDOT_MAX_M + 1
        set_dequant_mode("auto")
        for m, expect in [
            (BLOCKDOT_MAX_M, "i8blockdot"),
            (BLOCKDOT_MAX_M + 1, "bf16chain"),
        ]:
            seen.clear()
            x = jnp.asarray(rng.standard_normal((m, 64), dtype=np.float32))
            q40_matmul_pallas(x, pw, interpret=True, w_dtype=jnp.bfloat16)
            assert seen == [expect], ("auto", m, seen)
        # no recompile churn: the second same-shape call is a jit cache
        # hit — the kernel core's python body does not run again
        set_dequant_mode("i8blockdot")
        x = jnp.asarray(
            rng.standard_normal((BLOCKDOT_MAX_M, 64), dtype=np.float32)
        )
        q40_matmul_pallas(x, pw, interpret=True, w_dtype=jnp.bfloat16)
        traces = TRACE_STATS["impl_traces"]
        q40_matmul_pallas(x, pw, interpret=True, w_dtype=jnp.bfloat16)
        assert TRACE_STATS["impl_traces"] == traces, TRACE_STATS
    finally:
        pq._q40_matmul_pallas_impl = real_impl
        set_dequant_mode(None)


# ---------------------------------------------------------------------------
# Q80xQ40 numerics pinning (make kernelcheck runs this grid standalone):
# interpret-mode i8blockdot vs the exact f32 chain across shapes.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "d_in,d_out,m",
    [
        # the (d_in, d_out) axis at the m extremes of the decode class,
        # plus the multi-chunk plane at the blockdot cap — the interpret
        # kernel is slow enough that tier-1 keeps the informative corners
        # and `make kernelcheck` + the slow stream pin carry the rest
        (128, 256, 1), (128, 256, 8), (128, 256, 32),
        (512, 256, 1),
        (512, 1024, 32),
    ],
)
def test_i8blockdot_parity_grid(d_in, d_out, m):
    rng = np.random.default_rng(d_in * 7 + d_out + m)
    pw = _pack(rng, d_out, d_in)
    x = jnp.asarray(rng.standard_normal((m, d_in), dtype=np.float32))
    exact = np.asarray(q40_matmul_pallas(x, pw, interpret=True))
    set_dequant_mode("i8blockdot")
    try:
        got = np.asarray(
            q40_matmul_pallas(x, pw, interpret=True, w_dtype=jnp.bfloat16)
        )
    finally:
        set_dequant_mode(None)
    rel = np.abs(got - exact).max() / (np.abs(exact).max() + 1e-9)
    assert rel <= 2e-2, f"({d_in}x{d_out}, m={m}): max-rel {rel:.3e}"


@pytest.mark.slow
def test_i8blockdot_greedy_stream_token_identity(tmp_path):
    """Decode-stream half of the numerics pin: >= 256 greedy tokens under
    the shipping bf16 dot are token-identical between the i8blockdot
    chain and the v4 chain on a seeded synthetic model, with bounded
    prefill-logit drift."""
    from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
    from distributed_llama_multiusers_tpu.formats.synthetic import (
        tiny_header,
        write_synthetic_model,
    )
    from distributed_llama_multiusers_tpu.models.loader import (
        load_params_from_m_quantized,
    )
    from distributed_llama_multiusers_tpu.ops import linear
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.utils.testing import greedy_rollout

    path = str(tmp_path / "stream.m")
    write_synthetic_model(path, tiny_header(seq_len=320), seed=23)
    h = load_model_header(path)
    config, qparams = load_params_from_m_quantized(path, h, dtype=jnp.float32)
    prompt = [5, 9, 3, 17, 2]

    def rollout(mode):
        linear.set_pallas_interpret(True)
        linear.set_pallas_w_dtype(jnp.bfloat16)
        set_dequant_mode(mode)
        try:
            engine = InferenceEngine(
                config, qparams, n_lanes=1, prefill_buckets=(8,)
            )
            toks, _ = greedy_rollout(engine, prompt, 256)
            logits, _, _ = engine.prefill(0, prompt)
            return toks, np.asarray(logits)
        finally:
            set_dequant_mode(None)
            linear.set_pallas_w_dtype(None)
            linear.set_pallas_interpret(False)

    toks_i8, logits_i8 = rollout("i8blockdot")
    toks_v4, logits_v4 = rollout("v4")
    assert len(toks_i8) >= 256
    np.testing.assert_allclose(logits_i8, logits_v4, rtol=2e-2, atol=2e-2)
    assert toks_i8 == toks_v4, (
        f"i8blockdot greedy stream diverged from the v4 chain at "
        f"position {next(i for i, (a, b) in enumerate(zip(toks_i8, toks_v4)) if a != b)}"
    )


# ---------------------------------------------------------------------------
# Mode-knob validation (set_dequant_mode / DLLAMA_DEQUANT fail loudly).
# ---------------------------------------------------------------------------


def test_set_dequant_mode_rejects_unknown():
    with pytest.raises(ValueError, match="unknown dequant mode"):
        set_dequant_mode("q31wizard")
    # the knob is unchanged after the rejection
    from distributed_llama_multiusers_tpu.ops.pallas_q40 import DEQUANT_MODE

    assert DEQUANT_MODE in DEQUANT_MODES + ("auto",)


def test_env_dequant_rejects_unknown_on_import():
    import os
    import subprocess
    import sys

    env = dict(os.environ, DLLAMA_DEQUANT="q31wizard", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c",
         "import distributed_llama_multiusers_tpu.ops.pallas_q40"],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert proc.returncode != 0
    assert "not a known dequant mode" in proc.stderr
