"""protocol / protocol-manifest: the pod wire protocol as a checked model.

Scope: ``parallel/multihost.py`` (and fixture files with that suffix)
that declare ``PROTOCOL_VERSION`` — the gate that keeps protocol-shaped
test fixtures for OTHER checks out of this one's business.

``parallel/multihost.py`` is at PROTOCOL_VERSION 4 after three
hand-audited bumps, each justified by "a skewed peer could silently
replay garbage". The invariants those audits re-derived every time are
mechanical, so this module extracts a **protocol surface model** from
the AST — op constants, ``send_*`` encoders (with the op each passes to
``self._send``), ``RootControlEngine`` broadcast sites, ``worker_loop``
replay arms, packet-slot indices, and fixed header widths (payloads
built by ``np.zeros(<literal>)`` builders like ``_prefill_header``) —
and checks it two ways:

- ``protocol`` — structural pairing: every op has an encoder AND a
  replay arm; no encoder writes a packet slot index >= ``SLOTS``; every
  operand-carrying broadcast in a proxy method is PRECEDED by a
  pre-broadcast validation (a ``check_*``/``validate*`` call, a
  conditional ``raise``, or root-side ``self._engine`` work — the
  pod-deadlock rule generalized beyond the ``pod-broadcast`` check's
  raise-placement: a bad argument must die with zero packets on the
  wire); fixed header widths agree between the encoder and the replay
  arm that re-slices them.
- ``protocol-manifest`` — the extracted layout is pinned in
  ``analysis/protocol.lock`` (version, op table, HEADER/SLOTS, per-op
  payload counts and header widths). A layout that differs from the
  manifest WITHOUT a ``PROTOCOL_VERSION`` bump in the same diff is a
  finding — "changed the packet without bumping the version" cannot
  merge. A bump makes the check pass; regenerate the pin with
  ``dlint --update-protocol-manifest`` (a tier-1 rot-guard keeps the
  shipped manifest byte-current, so it cannot go stale either).
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

from .core import Checker, Finding, Project, SourceFile
from .lockgraph import walk_excluding_nested_defs

SCOPE = ("parallel/multihost.py",)
OP_RE = re.compile(r"^OP_[A-Z0-9_]+$")
BCAST_RE = re.compile(r"^self\._plane\.(send_\w+|_send)$")
# a call spelled through any of these before the broadcast counts as
# pre-broadcast validation (the raise may live inside the callee)
VALIDATE_RE = re.compile(r"^_?(check|validate)|valid", re.IGNORECASE)
MANIFEST_NAME = "protocol.lock"


def _int_const(node: ast.AST) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub)
            and isinstance(node.operand, ast.Constant)
            and isinstance(node.operand.value, int)):
        return -node.operand.value
    return None


def _last(func: ast.AST) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


@dataclass
class EncoderInfo:
    name: str
    line: int
    op: str | None = None  # OP_* constant passed to self._send
    payloads: int = 0  # payload slots written (args past the 4 header args)
    self_validating: bool = False  # raises / calls a _check* before _send
    widths: dict[int, tuple[int, int]] = field(default_factory=dict)
    # payload slot -> (fixed width from an np.zeros(<literal>) builder, line)


@dataclass
class ArmInfo:
    op: str
    line: int
    # (slot index, literal width or None, line) for plane.slot(pkt, i, w)
    slot_reads: list[tuple[int, int | None, int]] = field(default_factory=list)


@dataclass
class RootSendInfo:
    cls: str
    method: str
    send_name: str
    line: int
    n_args: int
    validated: bool  # some validation event precedes it in source order


@dataclass
class ProtocolModel:
    display: str
    version: int
    version_line: int
    header: int | None = None
    slots: int | None = None
    slots_line: int = 0
    ops: dict[str, int] = field(default_factory=dict)
    op_lines: dict[str, int] = field(default_factory=dict)
    encoders: dict[str, EncoderInfo] = field(default_factory=dict)
    arms: dict[str, ArmInfo] = field(default_factory=dict)
    # a second `op == OP_X` arm is dead (shadowed) protocol surface
    duplicate_arms: list[tuple[str, int]] = field(default_factory=list)
    has_worker_loop: bool = False
    worker_loop_line: int = 0
    root_sends: list[RootSendInfo] = field(default_factory=list)
    # pkt[lo:hi] header slices: (lo, hi, tuple_len or None, line)
    header_slices: list[tuple[int, int, int | None, int]] = field(
        default_factory=list
    )


def extract_protocol(tree: ast.Module, display: str) -> ProtocolModel | None:
    """Build the surface model; None when the file declares no
    ``PROTOCOL_VERSION`` (not a protocol file — fixtures for other
    checks stay out of scope)."""
    version = version_line = None
    ops: dict[str, int] = {}
    op_lines: dict[str, int] = {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        value = _int_const(node.value)
        if value is None:
            continue
        if name == "PROTOCOL_VERSION":
            version, version_line = value, node.lineno
        elif OP_RE.match(name):
            ops[name] = value
            op_lines[name] = node.lineno
    if version is None:
        return None
    model = ProtocolModel(display, version, version_line,
                          ops=ops, op_lines=op_lines)

    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            _scan_class(node, model)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "worker_loop":
            model.has_worker_loop = True
            model.worker_loop_line = node.lineno
            _scan_worker_loop(node, model)
    return model


def _zeros_width(call: ast.AST) -> int | None:
    """``np.zeros(<int literal>, ...)`` -> the literal; None otherwise."""
    if isinstance(call, ast.Call) and _last(call.func) == "zeros" and call.args:
        return _int_const(call.args[0])
    return None


def _scan_class(cls: ast.ClassDef, model: ProtocolModel) -> None:
    # class-level HEADER / SLOTS literals
    for stmt in cls.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            v = _int_const(stmt.value)
            if v is None:
                continue
            if stmt.targets[0].id == "HEADER" and model.header is None:
                model.header = v
            elif stmt.targets[0].id == "SLOTS" and model.slots is None:
                model.slots, model.slots_line = v, stmt.lineno

    # header builders: methods assigning X = np.zeros(<literal>) and
    # returning X (the _prefill_header shape) -> fixed payload width
    builders: dict[str, int] = {}
    methods = [s for s in cls.body
               if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in methods:
        zeroed: dict[str, int] = {}
        returned: set[str] = set()
        for node in walk_excluding_nested_defs(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                w = _zeros_width(node.value)
                if w is not None:
                    zeroed[node.targets[0].id] = w
            elif isinstance(node, ast.Return) and isinstance(node.value, ast.Name):
                returned.add(node.value.id)
        for name, w in zeroed.items():
            if name in returned:
                builders[fn.name] = w

    for fn in methods:
        _scan_encoder(fn, builders, model)
        _scan_proxy_method(cls.name, fn, model)


def _scan_encoder(fn, builders: dict[str, int], model: ProtocolModel) -> None:
    """A ``send_*`` method calling ``self._send(OP_X, lane, n, start_pos,
    *payloads)`` is op X's encoder."""
    if not fn.name.startswith("send_"):
        return
    # names assigned from a header-builder call inside this encoder
    built: dict[str, int] = {}
    for node in walk_excluding_nested_defs(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            comp = _last(node.value.func)
            if comp in builders:
                built[node.targets[0].id] = builders[comp]
    events = []  # ((line, col), kind, node) sorted into source order —
    # ast.walk is breadth-first, and "validation BEFORE the _send" is a
    # lexical-order fact
    for node in walk_excluding_nested_defs(fn):
        pos = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))
        if isinstance(node, ast.Raise):
            events.append((pos, "validate", None))
        elif isinstance(node, ast.Call):
            comp = _last(node.func)
            if comp == "_send":
                events.append((pos, "send", node))
            elif comp and VALIDATE_RE.match(comp):
                events.append((pos, "validate", None))
    events.sort(key=lambda e: e[0])
    info = None
    saw_validation = False
    for _, kind, node in events:
        if kind == "validate":
            saw_validation = True
            continue
        if info is None:
            info = EncoderInfo(fn.name, node.lineno,
                               self_validating=saw_validation)
            model.encoders[fn.name] = info
        if node.args and isinstance(node.args[0], ast.Name) \
                and OP_RE.match(node.args[0].id):
            info.op = node.args[0].id
        info.payloads = max(info.payloads, len(node.args) - 4)
        for slot, arg in enumerate(node.args[4:]):
            width = None
            if isinstance(arg, ast.Name) and arg.id in built:
                width = built[arg.id]
            elif isinstance(arg, ast.Call) and _last(arg.func) in builders:
                width = builders[_last(arg.func)]
            if width is not None:
                info.widths[slot] = (width, node.lineno)


def _scan_proxy_method(cls_name: str, fn, model: ProtocolModel) -> None:
    """RootControlEngine-style methods: ``self._plane.send_*`` sites plus
    whether any validation event precedes them. Also collects
    ``pkt[lo:hi] = (...)`` header-tuple assignments (the _send framing)."""
    events = []  # ((line, col), kind, payload)
    for node in walk_excluding_nested_defs(fn):
        pos = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))
        if isinstance(node, ast.Raise):
            events.append((pos, "validate", None))
        elif isinstance(node, ast.Call):
            spelled = ast.unparse(node.func)
            if BCAST_RE.match(spelled):
                events.append((pos, "send", node))
            elif spelled.startswith("self._engine."):
                events.append((pos, "validate", None))
            else:
                comp = _last(node.func)
                if comp and VALIDATE_RE.match(comp):
                    events.append((pos, "validate", None))
        elif isinstance(node, ast.Assign):
            sl = _header_slice(node)
            if sl is not None:
                model.header_slices.append(sl)
    events.sort(key=lambda e: e[0])
    validated = False
    for _, kind, node in events:
        if kind == "validate":
            validated = True
        elif kind == "send":
            model.root_sends.append(RootSendInfo(
                cls_name, fn.name,
                ast.unparse(node.func).rsplit(".", 1)[-1],
                node.lineno, len(node.args), validated,
            ))
            validated = True  # later sends in a loop share the gate


def _header_slice(node: ast.Assign) -> tuple[int, int, int | None, int] | None:
    """``pkt[0:6] = (<6-tuple>)`` -> (0, 6, 6, line)."""
    if len(node.targets) != 1:
        return None
    t = node.targets[0]
    if not (isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name)
            and t.value.id == "pkt" and isinstance(t.slice, ast.Slice)):
        return None
    lo = 0 if t.slice.lower is None else _int_const(t.slice.lower)
    hi = _int_const(t.slice.upper) if t.slice.upper is not None else None
    if lo is None or hi is None:
        return None
    n = len(node.value.elts) if isinstance(node.value, (ast.Tuple, ast.List)) \
        else None
    return (lo, hi, n, node.lineno)


def _arm_op(test: ast.AST) -> str | None:
    """``op == OP_X`` -> ``"OP_X"``; None for any other test."""
    if (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.ops[0], ast.Eq)
            and isinstance(test.left, ast.Name) and test.left.id == "op"
            and isinstance(test.comparators[0], ast.Name)
            and OP_RE.match(test.comparators[0].id)):
        return test.comparators[0].id
    return None


def _scan_worker_loop(fn, model: ProtocolModel) -> None:
    for node in ast.walk(fn):
        if isinstance(node, ast.If):
            op = _arm_op(node.test)
            if op is None:
                continue
            arm = ArmInfo(op, node.lineno)
            if op in model.arms:
                model.duplicate_arms.append((op, node.lineno))
                continue
            model.arms[op] = arm
            for inner in node.body:
                for sub in ast.walk(inner):
                    if isinstance(sub, ast.Call) and _last(sub.func) == "slot" \
                            and len(sub.args) >= 3:
                        slot = _int_const(sub.args[1])
                        if slot is None:
                            continue
                        arm.slot_reads.append(
                            (slot, _int_const(sub.args[2]), sub.lineno)
                        )
        elif isinstance(node, ast.Subscript):
            # the header unpack read: pkt[2:6]
            if (isinstance(node.value, ast.Name) and node.value.id == "pkt"
                    and isinstance(node.slice, ast.Slice)
                    and node.slice.lower is not None
                    and node.slice.upper is not None):
                lo = _int_const(node.slice.lower)
                hi = _int_const(node.slice.upper)
                if lo is not None and hi is not None:
                    model.header_slices.append((lo, hi, None, node.lineno))


# -- the manifest ------------------------------------------------------------


def manifest_from_model(model: ProtocolModel) -> dict:
    """The pinned layout: everything whose silent change is the
    "skewed peer replays garbage" hazard the version word classifies."""
    widths: dict[str, dict[str, int]] = {}
    for enc in model.encoders.values():
        if enc.op and enc.widths:
            widths[enc.op] = {
                str(slot): w for slot, (w, _) in sorted(enc.widths.items())
            }
    return {
        "protocol_version": model.version,
        "header": model.header,
        "slots": model.slots,
        "ops": dict(sorted(model.ops.items())),
        "encoders": {
            name: enc.op for name, enc in sorted(model.encoders.items())
        },
        "payload_slots": {
            name: enc.payloads for name, enc in sorted(model.encoders.items())
        },
        "header_widths": widths,
    }


def render_manifest(manifest: dict) -> str:
    return json.dumps(manifest, indent=2, sort_keys=True) + "\n"


def manifest_path_for(multihost: Path) -> Path:
    """``<pkg>/parallel/multihost.py`` -> ``<pkg>/analysis/protocol.lock``
    — the same relative shape for the real tree and for fixtures."""
    return multihost.resolve().parent.parent / "analysis" / MANIFEST_NAME


def write_protocol_manifest(multihost: Path,
                            lock_path: Path | None = None) -> Path:
    src = Path(multihost).read_text(encoding="utf-8")
    model = extract_protocol(ast.parse(src), str(multihost))
    if model is None:
        raise ValueError(
            f"{multihost}: no PROTOCOL_VERSION found — not a protocol file"
        )
    out = lock_path if lock_path is not None else manifest_path_for(Path(multihost))
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(render_manifest(manifest_from_model(model)),
                   encoding="utf-8")
    return out


def manifest_diff(pinned: dict, current: dict) -> list[str]:
    """Human-readable field diffs, pinned -> current (version excluded —
    the caller decides what a version delta means)."""
    diffs: list[str] = []
    for key in ("header", "slots"):
        if pinned.get(key) != current.get(key):
            diffs.append(f"{key}: {pinned.get(key)} -> {current.get(key)}")
    for key in ("ops", "encoders", "payload_slots", "header_widths"):
        old, new = pinned.get(key) or {}, current.get(key) or {}
        for k in sorted(set(old) | set(new)):
            if k not in new:
                diffs.append(f"{key}[{k}] removed (was {old[k]})")
            elif k not in old:
                diffs.append(f"{key}[{k}] added ({new[k]})")
            elif old[k] != new[k]:
                diffs.append(f"{key}[{k}]: {old[k]} -> {new[k]}")
    return diffs


# -- the checkers ------------------------------------------------------------


class ProtocolChecker(Checker):
    name = "protocol"
    description = (
        "pod wire protocol surface: every op has an encoder and a replay "
        "arm, no slot index >= SLOTS, operand-carrying broadcasts are "
        "validated pre-broadcast, header widths agree encoder<->replay"
    )

    def check(self, sf: SourceFile, project: Project):
        if not sf.endswith(*SCOPE):
            return
        model = extract_protocol(sf.tree, sf.display)
        if model is None:
            return

        # -- op table integrity
        by_value: dict[int, str] = {}
        for name, value in model.ops.items():
            if value in by_value:
                yield Finding(
                    self.name, sf.display, model.op_lines[name],
                    f"op value collision: {name} = {value} duplicates "
                    f"{by_value[value]} — a replayed packet would take the "
                    "wrong arm",
                )
            else:
                by_value[value] = name

        # "exactly one" cuts both ways: a second encoder for an op (two
        # senders whose framings can drift) and a second replay arm (the
        # later one is unreachable dead surface) are findings too
        encoders_by_op: dict[str, list[EncoderInfo]] = {}
        for enc in model.encoders.values():
            if enc.op:
                encoders_by_op.setdefault(enc.op, []).append(enc)
        for op, encs in sorted(encoders_by_op.items()):
            if len(encs) > 1:
                encs.sort(key=lambda e: e.line)
                for dup in encs[1:]:
                    yield Finding(
                        self.name, sf.display, dup.line,
                        f"op {op} has more than one encoder "
                        f"({', '.join(e.name for e in encs)}) — two "
                        "framings of one op drift; exactly one send_* "
                        "owns each packet layout",
                    )
        for op, line in model.duplicate_arms:
            yield Finding(
                self.name, sf.display, line,
                f"duplicate replay arm for {op} — the first arm wins the "
                "elif chain, so this one is unreachable dead protocol "
                "surface",
            )

        encoded_ops = set(encoders_by_op)
        for name in sorted(model.ops):
            if name not in encoded_ops:
                yield Finding(
                    self.name, sf.display, model.op_lines[name],
                    f"op {name} ({model.ops[name]}) has no send_* encoder "
                    "passing it to self._send — an op nothing can emit is "
                    "dead protocol surface (or its encoder bypasses the "
                    "modelled framing)",
                )
        if model.has_worker_loop:
            for name in sorted(model.ops):
                if name not in model.arms:
                    yield Finding(
                        self.name, sf.display, model.op_lines[name],
                        f"op {name} ({model.ops[name]}) has no replay arm "
                        "in worker_loop — a root broadcasting it leaves "
                        "every worker raising 'unknown control op' (or "
                        "silently skewed)",
                    )
        elif model.ops:
            yield Finding(
                self.name, sf.display, model.version_line,
                "protocol file declares ops but no worker_loop replay "
                "switch — nothing replays the broadcasts",
            )

        # -- encoder sanity
        for enc in model.encoders.values():
            if enc.op is None:
                yield Finding(
                    self.name, sf.display, enc.line,
                    f"encoder {enc.name} does not pass a literal OP_* "
                    "constant as self._send's first argument — the op "
                    "table cannot be modelled (or the op is computed, "
                    "which a skewed peer cannot validate)",
                )
            elif enc.op not in model.ops:
                yield Finding(
                    self.name, sf.display, enc.line,
                    f"encoder {enc.name} sends undeclared op {enc.op} — "
                    "every op must be a module-level OP_* constant",
                )
            if model.slots is not None and enc.payloads > model.slots:
                yield Finding(
                    self.name, sf.display, enc.line,
                    f"encoder {enc.name} writes payload slot "
                    f"{enc.payloads - 1} but SLOTS is {model.slots} — the "
                    "packet is sized for SLOTS payloads; later slots land "
                    "out of bounds (or silently truncate)",
                )

        # -- replay-arm slot bounds + header-width agreement
        if model.slots is not None:
            for arm in model.arms.values():
                for slot, _width, line in arm.slot_reads:
                    if slot >= model.slots:
                        yield Finding(
                            self.name, sf.display, line,
                            f"replay arm for {arm.op} reads packet slot "
                            f"{slot} but SLOTS is {model.slots}",
                        )
        for enc in model.encoders.values():
            arm = model.arms.get(enc.op or "")
            if arm is None:
                continue
            for slot, (width, _line) in enc.widths.items():
                for a_slot, a_width, a_line in arm.slot_reads:
                    if a_slot == slot and a_width is not None \
                            and a_width != width:
                        yield Finding(
                            self.name, sf.display, a_line,
                            f"header width disagreement for {enc.op} slot "
                            f"{slot}: encoder {enc.name} writes {width} "
                            f"words, the replay arm reads {a_width} — the "
                            "worker would decode a shifted header",
                        )
        if model.header is not None:
            for lo, hi, tuple_len, line in model.header_slices:
                if hi != model.header or (lo == 0 and tuple_len is not None
                                          and tuple_len != model.header):
                    yield Finding(
                        self.name, sf.display, line,
                        f"packet header slice pkt[{lo}:{hi}]"
                        + (f" (tuple of {tuple_len})" if tuple_len else "")
                        + f" disagrees with HEADER = {model.header}",
                    )

        # -- pre-broadcast validation (pod-deadlock rule, generalized)
        for send in model.root_sends:
            if send.n_args == 0:
                continue  # operand-less ops (stop/flush/reset): nothing
                # argument-dependent can raise post-send
            enc = model.encoders.get(send.send_name)
            if enc is not None and enc.self_validating:
                continue  # the encoder raises before its own _send
            if not send.validated:
                yield Finding(
                    self.name, sf.display, send.line,
                    f"broadcast '{send.send_name}' in "
                    f"{send.cls}.{send.method} has no pre-broadcast "
                    "validation (no check_*/validate*/raise/self._engine "
                    "call precedes it, and the encoder does not validate) "
                    "— a bad argument would raise with the packet already "
                    "on the wire and the pod deadlocks; validate BEFORE "
                    "broadcasting",
                )


class ProtocolManifestChecker(Checker):
    name = "protocol-manifest"
    description = (
        "extracted packet layout matches analysis/protocol.lock unless "
        "PROTOCOL_VERSION was bumped; regenerate with "
        "--update-protocol-manifest"
    )

    def check(self, sf: SourceFile, project: Project):
        if not sf.endswith(*SCOPE):
            return
        model = extract_protocol(sf.tree, sf.display)
        if model is None:
            return
        lock = manifest_path_for(sf.path)
        if not lock.exists():
            yield Finding(
                self.name, sf.display, model.version_line,
                f"no protocol manifest at {lock.name} — pin the current "
                "layout with `dlint --update-protocol-manifest`",
            )
            return
        try:
            pinned = json.loads(lock.read_text(encoding="utf-8"))
        except (OSError, ValueError) as e:
            yield Finding(
                self.name, sf.display, model.version_line,
                f"unreadable protocol manifest {lock.name} "
                f"({type(e).__name__}: {e}) — regenerate with "
                "`dlint --update-protocol-manifest`",
            )
            return
        current = manifest_from_model(model)
        if pinned.get("protocol_version") != current["protocol_version"]:
            # the sanctioned path: the layout change came with a version
            # bump in the same diff. The tier-1 manifest rot-guard
            # (tests/test_protocol_lint.py) forces the regenerated pin
            # into the same merge, so the manifest cannot go stale.
            return
        diffs = manifest_diff(pinned, current)
        if diffs:
            shown = "; ".join(diffs[:4]) + (
                f"; … {len(diffs) - 4} more" if len(diffs) > 4 else ""
            )
            yield Finding(
                self.name, sf.display, model.version_line,
                f"packet layout changed without a PROTOCOL_VERSION bump "
                f"(manifest pins v{pinned.get('protocol_version')}): "
                f"{shown} — a skewed peer would frame this packet and "
                "silently replay garbage; bump PROTOCOL_VERSION in the "
                "same diff, then `dlint --update-protocol-manifest`",
            )
