"""Token-parity gate against the REAL C++ reference binary.

Every other parity test compares the JAX path to this repo's own numpy
oracle; this one closes the loop against the actual reference
(src/dllama.cpp:36-113): build `dllama` from the reference sources, write a
tiny synthetic Q40 .m/.t pair with THIS repo's writers, run both engines
greedy on the same prompt, and assert the predicted tokens are identical —
the BASELINE.md "output token-identical to the 1-node CPU reference" bar.

Heavy (builds C++, and the reference's busy-spinning request-queue thread
makes it ~30 s/token on a single-core box — fork defect, app.cpp:314-402),
so it runs only when DLLAMA_REF_PARITY=1. A recorded transcript lives in
examples/reference_parity_transcript.md.

    DLLAMA_REF_PARITY=1 DLLAMA_REF_SRC=/root/reference \
        python -m pytest tests/test_reference_parity.py -v
"""

from __future__ import annotations

import os
import re
import shutil
import subprocess
import sys
import threading

import pytest

REF_SRC = os.environ.get("DLLAMA_REF_SRC", "/root/reference")
N_PREDICT = 6  # predicted tokens to compare (~30 s each on 1 core, worst case)
REF_DEADLINE_S = 600.0  # wall clock for the reference to produce them

pytestmark = pytest.mark.skipif(
    os.environ.get("DLLAMA_REF_PARITY") != "1"
    or not os.path.isdir(REF_SRC)
    or shutil.which("g++") is None,
    reason="reference parity gate runs only with DLLAMA_REF_PARITY=1, "
    "the reference sources, and g++",
)

_PRED_RE = re.compile(r"^🔶 Pred.*kB Recv\s*\d+ kB \| (.*)$")


def _build_reference(tmp: str) -> str:
    """Build the reference dllama CPU-only (its Makefile, -Werror relaxed:
    the vendored llamafile sgemm trips newer-gcc warnings)."""
    build = os.path.join(tmp, "refbuild")
    shutil.copytree(REF_SRC, build)
    mk = os.path.join(build, "Makefile")
    with open(mk) as f:
        text = f.read()
    text = text.replace(
        "CXXFLAGS = -std=c++11 -Werror -Wformat -Werror=format-security",
        "CXXFLAGS = -std=c++11 -Wformat",
    )
    with open(mk, "w") as f:
        f.write(text)
    # the reference tree ships prebuilt (foreign-ABI) .o artifacts that make
    # considers up-to-date; they must go before the real build
    for f_ in os.listdir(build):
        if f_.endswith(".o") or f_ == "dllama":
            os.unlink(os.path.join(build, f_))
    subprocess.run(
        ["make", "dllama"], cwd=build, check=True, capture_output=True, timeout=600
    )
    return os.path.join(build, "dllama")


def _run_reference_greedy(binary: str, model: str, tok: str, prompt: str) -> list[str]:
    """Stream the reference CLI and collect predicted pieces. The process is
    killed once enough tokens arrive: its inference_loop thread never exits
    (fork defect (d), app.cpp:303-317), so a clean exit never comes."""
    proc = subprocess.Popen(
        [
            binary, "inference", "--model", model, "--tokenizer", tok,
            "--prompt", prompt, "--steps", "32", "--temperature", "0.0",
            "--buffer-float-type", "q80", "--nthreads", "1",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    # the process never exits on its own, so the read loop needs its own
    # deadline: a watchdog timer kills it and unblocks the blocking read
    watchdog = threading.Timer(REF_DEADLINE_S, proc.kill)
    watchdog.start()
    pieces: list[str] = []
    try:
        for line in proc.stdout:
            m = _PRED_RE.match(line.rstrip("\n"))
            if m:
                pieces.append(m.group(1))
                if len(pieces) >= N_PREDICT:
                    break
    finally:
        watchdog.cancel()
        proc.kill()
        proc.wait()
    return pieces


def _run_repo_greedy(model: str, tok: str, prompt: str) -> list[str]:
    """The repo engine, greedy, with the reference's Q80 activation casts
    emulated (--buffer-float-type q80 semantics)."""
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.formats.model_file import load_model_header
    from distributed_llama_multiusers_tpu.models import init_kv_cache, llama_forward
    from distributed_llama_multiusers_tpu.models.loader import load_params_from_m
    from distributed_llama_multiusers_tpu.tokenizer import Tokenizer

    h = load_model_header(model)
    config, params = load_params_from_m(model, h, dtype=jnp.float32)
    t = Tokenizer(tok)
    ids = t.encode(prompt, add_bos=True)

    cache = init_kv_cache(config, 1)
    logits = None
    for pos, token in enumerate(ids):
        logits, cache = llama_forward(
            config, params,
            jnp.asarray([[token]], jnp.int32), jnp.asarray([[pos]], jnp.int32),
            cache, emulate_q80_activations=True,
        )
    pieces = []
    pos = len(ids)
    cur = int(logits[0, 0].argmax())
    for _ in range(N_PREDICT):
        pieces.append(t.vocab[cur].decode("utf-8", errors="replace"))
        logits, cache = llama_forward(
            config, params,
            jnp.asarray([[cur]], jnp.int32), jnp.asarray([[pos]], jnp.int32),
            cache, emulate_q80_activations=True,
        )
        pos += 1
        cur = int(logits[0, 0].argmax())
    return pieces


def test_greedy_tokens_match_reference_binary(tmp_path):
    from distributed_llama_multiusers_tpu.formats.synthetic import (
        tiny_header,
        write_synthetic_model,
        write_synthetic_tokenizer,
    )

    tmp = str(tmp_path)
    model = os.path.join(tmp, "m.m")
    tok = os.path.join(tmp, "t.t")
    header = tiny_header()
    write_synthetic_model(model, header, seed=5)
    write_synthetic_tokenizer(tok, vocab_size=header.vocab_size)

    binary = _build_reference(tmp)
    prompt = "hello world"
    ref_pieces = _run_reference_greedy(binary, model, tok, prompt)
    assert len(ref_pieces) == N_PREDICT, f"reference produced {ref_pieces}"
    print(f"reference: {ref_pieces}", file=sys.stderr)

    repo_pieces = _run_repo_greedy(model, tok, prompt)
    print(f"repo:      {repo_pieces}", file=sys.stderr)
    assert repo_pieces == ref_pieces
