"""host-sync: device->host transfers in the decode path must be explicit.

The serving invariant (ROADMAP north star, VERDICT Weak #3): one small
host transfer per decode step. A stray ``np.asarray(logits)`` / ``.item()``
in the engine step functions or the scheduler loop silently serializes the
pipeline on a full [n_lanes, vocab] f32 row every token — the classic
silent throughput killer on an accelerator behind a high-latency link.

Scope: the decode-path files (``runtime/engine.py``,
``runtime/scheduler.py``, ``runtime/spec.py``) plus the whole
``telemetry/`` package — the scheduler hands telemetry hooks values from
inside the serving loop, so a stray ``np.asarray``/``.item()`` there
would serialize the decode path from one layer out; telemetry is pure
stdlib by contract and should never need a waiver. Three sub-rules:

1. **transfer calls** — every ``np.asarray`` / ``np.array`` /
   ``jax.device_get`` call, and every ``.item()`` / ``.tolist()`` /
   ``.block_until_ready()`` / ``.all_logits()`` / ``.lane_logits()``
   method call, needs a waiver. The intentional single-transfer sites
   (the packed token readback per step, the host-exact logits row) carry
   waivers stating exactly what is transferred and why.
2. **casts** (``runtime/engine.py`` only) — ``int()`` / ``float()`` /
   ``bool()`` over a name that is not host-annotated forces a device
   sync. Host-side numpy results use the ``*_np`` naming convention and
   are exempt; everything else needs a waiver.
3. **implicit bool** — ``if x:`` / ``while x:`` on a value returned by a
   compiled step function (names assigned from ``*_fn`` / ``*_exec``
   calls) blocks on the device to evaluate truthiness.
"""

from __future__ import annotations

import ast
import re

from .core import (
    Checker,
    Finding,
    Project,
    SourceFile,
    last_component,
    root_name,
    walk_with_ancestors,
)

SCOPE = (
    "runtime/engine.py", "runtime/scheduler.py", "runtime/spec.py",
    # the paged KV pool's bookkeeping runs inside the admission path
    # (runtime/scheduler._start_request -> engine.paged_admit); host
    # dicts/lists by contract, never a device value
    "runtime/kvpool.py",
    # the telemetry package rides the serving loop (scheduler hooks);
    # registered file-by-file because scope matching is suffix-based
    "telemetry/__init__.py", "telemetry/hub.py", "telemetry/spans.py",
    "telemetry/metrics.py", "telemetry/trace.py", "telemetry/logs.py",
    # the fleet trace context rides every hop the router makes AND the
    # replica admission path (journal admit records) — pure stdlib by
    # the same contract as the rest of telemetry/
    "telemetry/tracectx.py",
    # failure containment rides the serving loop too: the breaker is fed
    # from every engine step, the watchdog brackets every blocking call,
    # and the fault hooks sit inside the dispatch paths — none of them
    # may ever touch a device value
    "serving/breaker.py", "serving/watchdog.py", "utils/faults.py",
    # crash durability rides it the same way: admit/finish records are
    # enqueued from the serving loop, relay pushes run inside _consume,
    # and recovery re-admits through submit() — all host-side by
    # contract, never holding a device value
    "serving/journal.py", "serving/recovery.py", "serving/resume.py",
    # the fleet front-end is pure stdlib BY DESIGN (the router holds no
    # model, no tokenizer, no device): a transfer spelling appearing in
    # any of these would mean device state leaked a layer up
    "fleet/__init__.py", "fleet/balancer.py", "fleet/router.py",
    "fleet/migrate.py",
    # grammar-constrained decoding rides the admission + dispatch paths
    # (scheduler _start_request -> engine.grammar_attach; per-dispatch
    # mask-state vectors): the compiler and slab are pure-host numpy BY
    # CONTRACT — a device transfer spelling here would serialize every
    # constrained dispatch on the automaton tables
    "grammar/__init__.py", "grammar/automaton.py", "grammar/slab.py",
    # disaggregated prefill is pure stdlib BY DESIGN like fleet/: page
    # payloads cross replicas as OPAQUE bytes behind the engine's
    # export/import hooks — a transfer spelling here would mean device
    # state leaked into the hand-off orchestration layer
    "disagg/__init__.py", "disagg/kvtransfer.py", "disagg/prefill.py",
)
CAST_SCOPE = ("runtime/engine.py",)

SYNC_METHODS = {"item", "tolist", "block_until_ready", "all_logits",
                "lane_logits", "device_get"}
SYNC_FUNCS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
              "jax.device_get"}
CASTS = {"int", "float", "bool"}
# compiled-step callables by convention: jit handles stored as *_fn/*_exec
DEVICE_FN_RE = re.compile(r"(_fn|_exec)$")
DEVICE_FN_EXPR_RE = re.compile(r"\b\w*(_fn|_exec)\b")
# host-side numpy results by convention (toks_np, logits_np, out_np, ...)
HOST_NAME_RE = re.compile(r"(_np|_host)$")


class HostSyncChecker(Checker):
    name = "host-sync"
    description = (
        "device->host syncs (np.asarray/.item()/casts/implicit bool) in "
        "the decode path must carry a waiver naming the transfer"
    )

    def check(self, sf: SourceFile, project: Project):
        if not sf.endswith(*SCOPE):
            return
        cast_scoped = sf.endswith(*CAST_SCOPE)
        for node, ancestors in walk_with_ancestors(sf.tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(sf, node, cast_scoped)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_implicit_bool(sf, node)

    # -- rule 1 + 2: transfer calls and casts -------------------------------

    def _check_call(self, sf: SourceFile, node: ast.Call, cast_scoped: bool):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in SYNC_METHODS:
            yield Finding(
                self.name, sf.display, node.lineno,
                f"device->host sync '{ast.unparse(func)}(...)' in the decode "
                "path needs '# dlint: ok[host-sync] <what is transferred and "
                "why>'",
            )
            return
        if ast.unparse(func) in SYNC_FUNCS:
            yield Finding(
                self.name, sf.display, node.lineno,
                f"device->host sync '{ast.unparse(func)}(...)' in the decode "
                "path needs '# dlint: ok[host-sync] <what is transferred and "
                "why>'",
            )
            return
        if (
            cast_scoped
            and isinstance(func, ast.Name)
            and func.id in CASTS
            and len(node.args) == 1
            and not node.keywords
            and isinstance(node.args[0], (ast.Name, ast.Attribute, ast.Subscript))
        ):
            root = root_name(node.args[0])
            if root is not None and not HOST_NAME_RE.search(root):
                yield Finding(
                    self.name, sf.display, node.lineno,
                    f"cast '{func.id}({ast.unparse(node.args[0])})' syncs a "
                    "device value to host; read from a *_np host array or "
                    "waive the intentional transfer",
                )

    # -- rule 3: implicit bool on compiled-step outputs ---------------------

    def _check_implicit_bool(self, sf: SourceFile, func_node):
        device_fns: set[str] = set()
        tainted: set[str] = set()
        for stmt in ast.walk(func_node):
            if not isinstance(stmt, ast.Assign):
                continue
            rhs = stmt.value
            if isinstance(rhs, ast.Call):
                callee = rhs.func
                last = last_component(callee)
                is_device = (
                    last is not None and DEVICE_FN_RE.search(last) is not None
                ) or (isinstance(callee, ast.Name) and callee.id in device_fns)
                if is_device:
                    for tgt in stmt.targets:
                        tainted.update(self._target_names(tgt))
            elif DEVICE_FN_EXPR_RE.search(ast.unparse(rhs)):
                # e.g. fn = self._decode_exec if ... else self._decode_fn
                for tgt in stmt.targets:
                    device_fns.update(self._target_names(tgt))
        if not tainted:
            return
        for node in ast.walk(func_node):
            if not isinstance(node, (ast.If, ast.While, ast.Assert)):
                continue
            test = node.test
            for name in self._bool_names(test):
                if name in tainted:
                    yield Finding(
                        self.name, sf.display, node.lineno,
                        f"implicit bool of device value '{name}' blocks on "
                        "the device; compare against a host copy or waive",
                    )

    @staticmethod
    def _target_names(tgt: ast.AST) -> list[str]:
        if isinstance(tgt, ast.Name):
            return [tgt.id]
        if isinstance(tgt, (ast.Tuple, ast.List)):
            return [e.id for e in tgt.elts if isinstance(e, ast.Name)]
        return []

    @staticmethod
    def _bool_names(test: ast.AST) -> list[str]:
        if isinstance(test, ast.Name):
            return [test.id]
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return HostSyncChecker._bool_names(test.operand)
        if isinstance(test, ast.BoolOp):
            out: list[str] = []
            for v in test.values:
                out.extend(HostSyncChecker._bool_names(v))
            return out
        return []
