"""Runtime lock-order witness (``DLLAMA_LOCKCHECK=1``).

The static lock-order graph (analysis/lockgraph.py) proves what the
SOURCE nests; this module proves what the PROCESS nests. Every declared
lock in the package is built through :func:`make_lock`, which returns a
plain ``threading.Lock`` in production (zero overhead — the witness
costs nothing unless asked for) and a :class:`WitnessLock` wrapper when
the check is enabled. The wrapper records per-thread acquisition chains
and, before every blocking acquire, asserts the acquisition respects the
established order:

- the witness is seeded with the **statically computed** lock-order
  edges (lockgraph.package_lock_graph), so the first runtime acquisition
  that inverts an order the source already commits to raises
  :class:`LockOrderViolation` immediately — no second thread, no racy
  schedule required;
- every observed "A held while acquiring B" adds a runtime edge, so an
  inversion between two DYNAMIC orders (neither visible statically, e.g.
  through callbacks) raises on the first inverted acquire;
- re-acquiring a held non-reentrant lock raises instead of deadlocking.

Witness names are the static graph's class-qualified ids
(``make_lock("QosQueue._lock")``); dlint's lock-order collect pass
cross-checks each literal against its declaration site, so the two
vocabularies cannot drift. ``threading.Condition`` built over a wrapped
lock works unchanged (the condition acquires/releases through the
wrapper, so waits keep the per-thread chain honest), and waived static
edges (``ok[lock-order]``) are excluded from the seed — the witness must
not fire on nesting a waiver just sanctioned.

Enable via the environment (``DLLAMA_LOCKCHECK=1`` before process
start — tier-1 runs the QoS + telemetry suites this way) or via
:func:`force` from a test fixture; only locks constructed AFTER enabling
are wrapped.

Pure stdlib; importable (and a no-op) everywhere the package is.
"""

from __future__ import annotations

import os
import threading

ENV_FLAG = "DLLAMA_LOCKCHECK"

_forced: bool | None = None
_witness: "LockWitness | None" = None
_witness_guard = threading.Lock()


class LockOrderViolation(AssertionError):
    """An acquisition that contradicts the established lock order (or
    re-enters a held non-reentrant lock). AssertionError on purpose:
    the witness is a test-time oracle, and a violation is a failed
    invariant, not an operational error to catch and retry."""


class LockWitness:
    """Order oracle shared by every wrapped lock in the process."""

    def __init__(self):
        self._graph_lock = threading.Lock()  # guards _after/_sites only
        self._after: dict[str, set[str]] = {}  # a -> {b}: a ordered before b
        self._sites: dict[tuple[str, str], str] = {}
        self._tls = threading.local()

    # -- order graph ---------------------------------------------------------

    def add_order(self, a: str, b: str, site: str = "runtime") -> None:
        """Declare/record 'a before b' without checking (seeding and
        already-validated runtime edges)."""
        with self._graph_lock:
            self._after.setdefault(a, set()).add(b)
            self._sites.setdefault((a, b), site)

    def _ordered_before(self, a: str, b: str) -> list[str] | None:
        """Path a ⇝ b in the order graph (meaning a is ordered before b),
        as the node list, else None. Called with _graph_lock held."""
        stack = [(a, [a])]
        seen = {a}
        while stack:
            node, path = stack.pop()
            for nxt in self._after.get(node, ()):
                if nxt == b:
                    return path + [b]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def order_snapshot(self) -> dict[str, set[str]]:
        with self._graph_lock:
            return {a: set(bs) for a, bs in self._after.items()}

    # -- per-thread chain ----------------------------------------------------

    def _stack(self) -> list[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def held(self) -> tuple[str, ...]:
        return tuple(self._stack())

    def on_acquire(self, name: str) -> None:
        """Validate and record a blocking acquire of ``name``; raises
        BEFORE the caller blocks, so an ordering bug is a stack trace at
        the guilty acquire instead of a hung process."""
        stack = self._stack()
        if name in stack:
            raise LockOrderViolation(
                f"re-acquisition of non-reentrant lock '{name}' "
                f"(chain: {' -> '.join(stack)}) would deadlock this thread"
            )
        for holder in stack:
            with self._graph_lock:
                path = self._ordered_before(name, holder)
                site = self._sites.get((name, holder)) if path else None
            if path is not None:
                raise LockOrderViolation(
                    f"lock-order inversion: acquiring '{name}' while "
                    f"holding '{holder}', but the established order is "
                    f"{' -> '.join(path)} (first established: {site}); "
                    f"this thread's chain: {' -> '.join(stack)} -> {name}"
                )
        for holder in stack:
            self.add_order(holder, name)
        stack.append(name)

    def push(self, name: str) -> None:
        """Record a non-blocking acquire that succeeded (no order check:
        a try-acquire cannot deadlock, and Condition._is_owned probes
        held locks non-blockingly by design)."""
        self._stack().append(name)

    def pop(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return


class WitnessLock:
    """``threading.Lock`` stand-in that reports every acquire/release to
    the witness. Supports the full Lock protocol (and the subset
    ``threading.Condition`` uses), so it drops into
    ``Condition(make_lock(...))`` unchanged."""

    __slots__ = ("name", "_witness", "_inner")

    def __init__(self, name: str, witness: LockWitness):
        self.name = name
        self._witness = witness
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not blocking:
            got = self._inner.acquire(False)
            if got:
                self._witness.push(self.name)
            return got
        self._witness.on_acquire(self.name)  # raises on inversion; pushes
        got = self._inner.acquire(True, timeout)
        if not got:  # timed out: we never held it
            self._witness.pop(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        self._witness.pop(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<WitnessLock {self.name} {self._inner!r}>"


# -- module surface ----------------------------------------------------------


def enabled() -> bool:
    if _forced is not None:
        return _forced
    return os.environ.get(ENV_FLAG, "") not in ("", "0")


def witness() -> LockWitness:
    """The process-wide witness, created (and seeded with the static
    order) on first use."""
    global _witness
    with _witness_guard:
        if _witness is None:
            _witness = LockWitness()
            _seed_static(_witness)
        return _witness


def _seed_static(w: LockWitness) -> None:
    try:
        from .analysis.lockgraph import package_lock_graph

        for a, b, site in package_lock_graph():
            if a != b:
                w.add_order(a, b, site=f"static {site}")
    except Exception:  # analysis unavailable: dynamic-only witness
        pass


def make_lock(name: str):
    """The one lock constructor for declared shared locks: a plain
    ``threading.Lock`` unless the witness is enabled. ``name`` must be
    the class-qualified id of the declaration site — dlint's lock-order
    collect pass verifies it."""
    if not enabled():
        return threading.Lock()
    return WitnessLock(name, witness())


def force(value: bool | None, fresh: bool = True) -> None:
    """Test hook: override the env flag (None restores it). ``fresh``
    drops the current witness so the next wrapped lock starts from a
    clean order graph."""
    global _forced, _witness
    _forced = value
    if fresh:
        with _witness_guard:
            _witness = None
