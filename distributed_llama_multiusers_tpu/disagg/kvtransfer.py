"""Bulk KV-page export/import between replicas (disaggregated prefill).

The transfer unit is the paged pool's committed block chain: only FULL
blocks enter the prefix tree (``runtime/kvpool.py``'s granularity rule),
and a committed block's content is immutable — so a prefill replica can
export a session's prefix pages while its lane keeps decoding, and the
bytes cannot tear. The bundle is plain JSON (the fleet's admin plane is
HTTP + stdlib everywhere):

```
{"v": 1, "page_size": 16, "n_tokens": 4096,
 "blocks": [{"t": [tokens...], "p": "<base64 payload>", "h": "<sha256>"},
            ...]}
```

``h`` is :func:`page_hash` over a canonical framing of (page_size, block
tokens, payload bytes) — computed by the EXPORTER and re-verified by the
importer before any pool mutation, so a torn or corrupted transfer dies
with a typed :class:`KVTransferError` instead of adopting garbage KV
that every future same-prefix admission would silently share.

Adoption is refcount-correct by construction: :meth:`KVPagePool.adopt`
reuses chain blocks the local tree already holds (refcount bump, no
payload write) and allocates only the missing suffix; the whole chain is
pinned by a park entry — the exact accounting a local
``finish(park=True)`` produces — so the adopted prefix survives until a
real admission shares it or LRU pressure evicts it. Only FRESH pages get
their payload imported (``engine.import_kv_page``, the warmed
single-page write program; on pod roots the bytes ride ``OP_KV_PAGES``
so every process lands identical pool arrays).

Pure stdlib: the engine hooks are duck-typed (MockAsyncEngine implements
them content-canonically, so the integrity machinery is exercised end to
end in CPU smokes).
"""

from __future__ import annotations

import base64
import hashlib

BUNDLE_VERSION = 1
# canonical framing domain separator: versioned so a framing change can
# never silently collide with old hashes
_HASH_DOMAIN = b"dllama-kvpage-v1\0"


class KVTransferError(ValueError):
    """Typed transfer failure (malformed bundle, geometry mismatch,
    integrity-hash mismatch): the importing replica's pool is untouched
    and the router falls back to the monolithic path — never a partial
    adoption."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(f"kv transfer failed ({reason})"
                         + (f": {detail}" if detail else ""))


def _le32(value: int) -> bytes:
    return int(value).to_bytes(4, "little", signed=True)


def page_hash(page_size: int, tokens, payload: bytes) -> str:
    """Integrity hash of one transferred page: sha256 over a canonical
    framing of (page_size, block tokens, payload bytes). The tokens are
    part of the framing on purpose — a payload attached to the WRONG
    block (an off-by-one page mix-up in transit) fails verification even
    when the bytes themselves are intact."""
    h = hashlib.sha256(_HASH_DOMAIN)
    h.update(_le32(page_size))
    h.update(_le32(len(tokens)))
    for t in tokens:
        h.update(_le32(t))
    h.update(len(payload).to_bytes(8, "little"))
    h.update(payload)
    return h.hexdigest()


def export_bundle(pool, engine, tokens) -> dict:
    """Export the committed prefix chain over ``tokens`` as a transfer
    bundle. ``pool`` is the session's :class:`~..runtime.kvpool.
    KVPagePool`, ``engine`` anything with ``export_kv_page(page) ->
    bytes``. The chain may be empty (prompt shorter than one block, or
    nothing committed yet) — the bundle still carries the geometry so
    the importer can distinguish "nothing to adopt" from a bad reply."""
    blocks = []
    for blk, page in pool.chain_pages(list(tokens)):
        payload = bytes(engine.export_kv_page(page))
        blocks.append({
            "t": [int(t) for t in blk],
            "p": base64.b64encode(payload).decode("ascii"),
            "h": page_hash(pool.page_size, blk, payload),
        })
    return {
        "v": BUNDLE_VERSION,
        "page_size": int(pool.page_size),
        "n_tokens": len(list(tokens)),
        "blocks": blocks,
    }


def decode_bundle(pool, bundle: dict) -> list[tuple[list[int], bytes]]:
    """Validate a bundle against the DESTINATION pool's geometry and
    verify every page hash; returns ``(block_tokens, payload)`` pairs in
    chain order. Raises :class:`KVTransferError` BEFORE any pool
    mutation — verification is the importer's first step, so a corrupt
    bundle can never partially adopt."""
    if not isinstance(bundle, dict) or bundle.get("v") != BUNDLE_VERSION:
        raise KVTransferError(
            "bundle_version",
            f"got {bundle.get('v') if isinstance(bundle, dict) else bundle!r}"
            f", want {BUNDLE_VERSION}",
        )
    if int(bundle.get("page_size", -1)) != int(pool.page_size):
        raise KVTransferError(
            "page_size_mismatch",
            f"bundle {bundle.get('page_size')} vs pool {pool.page_size} — "
            "replicas disagree on --kv-page-size",
        )
    out: list[tuple[list[int], bytes]] = []
    for i, blk in enumerate(bundle.get("blocks") or ()):
        try:
            tokens = [int(t) for t in blk["t"]]
            payload = base64.b64decode(blk["p"], validate=True)
            want = str(blk["h"])
        except (KeyError, TypeError, ValueError) as e:
            raise KVTransferError(
                "malformed_block", f"block {i}: {type(e).__name__}: {e}"
            ) from e
        if len(tokens) != pool.page_size:
            raise KVTransferError(
                "partial_block",
                f"block {i} holds {len(tokens)} tokens, want "
                f"{pool.page_size} — only full committed blocks transfer",
            )
        got = page_hash(pool.page_size, tokens, payload)
        if got != want:
            raise KVTransferError(
                "integrity",
                f"block {i} hash mismatch (got {got[:16]}…, "
                f"want {want[:16]}…) — transfer corrupted, not adopting",
            )
        out.append((tokens, payload))
    return out


def adopt_bundle(pool, engine, bundle: dict) -> dict:
    """Verify + adopt a transfer bundle into ``pool``, importing fresh
    pages' payloads through ``engine.import_kv_page``. Returns the
    adoption receipt ``{"pages": n, "fresh": n, "reused": n}``.

    Order of operations is the safety argument: (1) every hash verifies
    (:func:`decode_bundle`) before anything mutates; (2) ``pool.adopt``
    registers the chain — it either completes or raises with the pool
    untouched (:class:`~..runtime.kvpool.PoolExhausted` propagates as
    the caller's typed shed); (3) only then do payload writes dispatch,
    and only for FRESH pages — reused pages already hold identical
    content by the tree's content-hash keying, so skipping them is not
    an optimization but the correctness rule (their bytes may be live
    read targets of co-resident lanes)."""
    pairs = decode_bundle(pool, bundle)
    if not pairs:
        return {"pages": 0, "fresh": 0, "reused": 0}
    pages, fresh = pool.adopt([tokens for tokens, _ in pairs])
    # adopt() may have evicted parked pages and staged them for the host
    # swap tier: drain (device gather -> host store) BEFORE the payload
    # imports below could reuse those pages. Duck-typed like the import
    # hook — engines without a swap tier simply skip.
    drain = getattr(engine, "drain_kv_swapouts", None)
    if callable(drain):
        drain()
    for idx, page in fresh:
        engine.import_kv_page(page, pairs[idx][1])
    return {
        "pages": len(pages),
        "fresh": len(fresh),
        "reused": len(pages) - len(fresh),
    }
