"""lock-order: the cross-file lock-order graph must stay acyclic.

The collect pass builds the package's lock model (analysis/lockgraph.py):
every class-qualified lock declaration (``QosQueue._lock``,
``EngineStats.lock``, ``SpanTracer._trace_lock``, ...), every Condition
alias, and — in finalize, once all files have been seen — every "A held
while acquiring B" edge, including one level of intra-package calls (a
``with self._lock:`` body calling a method that takes another known
lock). Any cycle in that graph is a potential deadlock the test suite
will only reproduce under exactly the wrong interleaving, so it is a
lint finding instead:

- a two-or-more-lock cycle means two threads can each hold one lock and
  wait for the other;
- a self-edge means re-acquiring a non-reentrant lock — a deadlock with
  no second thread required.

Intentional nesting is waived at the inner acquisition site
(``# dlint: ok[lock-order] reason``); waived edges are dropped from the
cycle check but still drawn (dashed) by ``dlint --graph``, and excluded
from the runtime witness's static seed so lockcheck honors the waiver.

The same statically computed order seeds the runtime witness
(``DLLAMA_LOCKCHECK=1``, lockcheck.py): the graph reviewed here is the
order the witness enforces on the real scheduler/QoS/telemetry paths.
"""

from __future__ import annotations

from .core import Checker, Finding, Project, SourceFile
from .lockgraph import LockModel


class LockOrderChecker(Checker):
    name = "lock-order"
    description = (
        "the cross-file 'held while acquiring' graph over declared locks "
        "must be acyclic (one level of intra-package calls included)"
    )

    def collect(self, sf: SourceFile, project: Project) -> None:
        if project.lock_model is None:
            project.lock_model = LockModel()
        project.lock_model.add_file(sf)

    def finalize(self, project: Project):
        model: LockModel = project.lock_model
        if model is None:
            return
        yield from model.findings  # declaration findings (witness-name drift)
        for cycle in model.cycles():
            first = cycle[0]
            if len(cycle) == 1 and first.a == first.b:
                via = f" via {first.via}()" if first.via else ""
                yield Finding(
                    self.name, first.path, first.line,
                    f"re-acquisition of non-reentrant lock '{first.a}'"
                    f"{via} — deadlocks with no second thread involved",
                )
                continue
            hops = " -> ".join(
                f"{e.b} ({e.site}{f' via {e.via}()' if e.via else ''})"
                for e in cycle
            )
            yield Finding(
                self.name, first.path, first.line,
                f"lock-order cycle: {cycle[0].a} -> {hops} — two threads "
                "taking these locks in opposite orders deadlock; pick one "
                "order (or waive the intentional edge with "
                "'# dlint: ok[lock-order] reason')",
            )
