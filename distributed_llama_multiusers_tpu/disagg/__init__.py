"""Disaggregated prefill (DistServe/Splitwise pattern): prefill and
decode as independently scalable fleet resources.

A single 100k-token prompt used to ride the decode chain of whichever
replica owned it, taxing every co-resident lane's TBT. This package
lets the router designate prefill-role replicas that build KV pages and
ship them to decode replicas:

- :mod:`.kvtransfer` — bulk KV-page export/import on top of
  ``runtime/kvpool.py``: integrity-hashed page bundles, refcount-correct
  adoption into the destination pool's prefix tree. Serialized either
  over HTTP between replicas (``server/http.py`` admin endpoints) or as
  the ``OP_KV_PAGES`` pod wire op (``parallel/multihost.py``).
- :mod:`.prefill` — the hand-off orchestration: prompt-length
  classification, the prefill worker contract (prefill on the prefill
  replica, first token proves the pages are committed), and the
  page-transfer + ticket-migration sequence that moves the session to a
  decode replica char-exact (PR 12's ``fleet/migrate.py`` machinery).

Pure stdlib, like ``serving/`` and ``fleet/``: importable wherever
dlint runs, no numpy/jax — the device half stays in ``runtime/engine``
behind the ``export_kv_page``/``import_kv_page`` hooks.

See docs/DISAGG.md for the wire format, the hand-off ticket lifecycle
and the failure-mode table.
"""

from .kvtransfer import (
    BUNDLE_VERSION,
    KVTransferError,
    adopt_bundle,
    decode_bundle,
    export_bundle,
    page_hash,
)
from .prefill import (
    DEFAULT_LONG_PROMPT_CHARS,
    HandoffAborted,
    classify_prompt,
    fetch_pages,
    hand_off,
    prompt_chars,
    push_pages,
)

__all__ = [
    "BUNDLE_VERSION",
    "KVTransferError",
    "adopt_bundle",
    "decode_bundle",
    "export_bundle",
    "page_hash",
    "DEFAULT_LONG_PROMPT_CHARS",
    "HandoffAborted",
    "classify_prompt",
    "fetch_pages",
    "hand_off",
    "prompt_chars",
    "push_pages",
]
