"""Multi-chip sharding tests on the virtual 8-device CPU mesh — the TPU
analogue of the reference's local-cluster tests (examples/n-workers.sh):
sharded execution must be token-identical to single-device."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llama_multiusers_tpu.formats.synthetic import tiny_header
from distributed_llama_multiusers_tpu.models import (
    LlamaConfig,
    init_kv_cache,
    llama_forward,
    params_from_random,
)
from distributed_llama_multiusers_tpu.parallel import (
    MeshPlan,
    cache_shardings,
    data_shardings,
    make_mesh,
    param_shardings,
    q80_all_gather,
    validate_mesh_for_config,
)
from distributed_llama_multiusers_tpu.parallel.sharding import shard_params


@pytest.fixture(scope="module")
def cfg_params():
    header = tiny_header(dim=64, hidden_dim=128, n_layers=2, n_heads=8, n_kv_heads=4, vocab_size=128, seq_len=32)
    config = LlamaConfig.from_header(header)
    params = params_from_random(config, seed=5, dtype=jnp.float32)
    return config, params


def _greedy_tokens(config, params, cache, fwd, prompt, n_steps, n_lanes):
    """Greedy decode on lane 0; other lanes idle at pos 0."""
    toks = np.zeros((n_lanes, len(prompt)), np.int32)
    toks[0] = prompt
    poss = np.zeros((n_lanes, len(prompt)), np.int32)
    poss[0] = np.arange(len(prompt))
    logits, cache = fwd(params, jnp.asarray(toks), jnp.asarray(poss), cache)
    out = []
    cur = int(jnp.argmax(logits[0, -1]))
    pos = len(prompt)
    for _ in range(n_steps):
        out.append(cur)
        t = np.zeros((n_lanes, 1), np.int32)
        t[0, 0] = cur
        p = np.zeros((n_lanes, 1), np.int32)
        p[0, 0] = pos
        logits, cache = fwd(params, jnp.asarray(t), jnp.asarray(p), cache)
        cur = int(jnp.argmax(logits[0, -1]))
        pos += 1
    return out


@pytest.mark.parametrize("plan", [MeshPlan(tp=4), MeshPlan(dp=2, tp=2, sp=2), MeshPlan(tp=2, sp=4)])
def test_sharded_forward_token_identical(cfg_params, plan):
    config, params = cfg_params
    validate_mesh_for_config(config, plan)
    prompt = [1, 9, 77, 30]
    n_lanes = max(2, plan.dp)

    # single-device reference run
    fwd1 = jax.jit(lambda p, t, pos, c: llama_forward(config, p, t, pos, c))
    ref = _greedy_tokens(config, params, init_kv_cache(config, n_lanes), fwd1, prompt, 12, n_lanes)

    # sharded run
    mesh = make_mesh(plan)
    sp_params = shard_params(params, mesh)
    cache = jax.tree.map(
        lambda x, s: jax.device_put(x, s), init_kv_cache(config, n_lanes), cache_shardings(mesh)
    )
    tok_sh, _ = data_shardings(mesh)
    fwd_sh = jax.jit(
        lambda p, t, pos, c: llama_forward(config, p, t, pos, c),
        in_shardings=(param_shardings(mesh), tok_sh, tok_sh, cache_shardings(mesh)),
    )
    got = _greedy_tokens(config, sp_params, cache, fwd_sh, prompt, 12, n_lanes)
    assert got == ref


def test_validate_mesh_rejects_bad_tp(cfg_params):
    config, _ = cfg_params
    with pytest.raises(ValueError):
        validate_mesh_for_config(config, MeshPlan(tp=8))  # > n_kv_heads=4
    with pytest.raises(ValueError):
        validate_mesh_for_config(config, MeshPlan(tp=3))  # not a divisor


def test_q80_all_gather_matches_plain():
    mesh = make_mesh(MeshPlan(tp=8))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 256), dtype=np.float32)
    from jax.sharding import NamedSharding, PartitionSpec as P

    xs = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(None, "tp")))
    full = q80_all_gather(xs, mesh)
    assert full.shape == x.shape
    # quantization error bounded by one Q80 step per 32-block
    err = np.abs(np.asarray(full) - x)
    assert err.max() < np.abs(x).max() / 127.0 + 1e-6
    # and the result is exactly the blockwise QDQ of the input
    from distributed_llama_multiusers_tpu.quants.codec import quantize_dequantize_q80

    expect = np.stack([quantize_dequantize_q80(row, mode="converter") for row in x])
    np.testing.assert_allclose(np.asarray(full), expect, rtol=0, atol=1e-7)
