"""Collective-traffic accounting from compiled XLA programs.

The reference counts every byte its TCP sockets move and prints Sent/Recv kB
per token (src/nn/nn-network.cpp:493-508, src/dllama.cpp:54-64). Under
GSPMD the collectives live inside the compiled executable, so the equivalent
observability comes from the post-partitioning HLO: every all-reduce /
all-gather / reduce-scatter / collective-permute op is visible there with
its per-chip output shape. This module parses them into a byte estimate —
an honest static analogue of the reference's measured socket counters
(payload bytes per chip per step; wire/ICI overheads not included).
"""

from __future__ import annotations

import re

_DTYPE_BYTES = {
    "pred": 1,
    "s4": 1, "s8": 1, "s16": 2, "s32": 4, "s64": 8,
    "u4": 1, "u8": 1, "u16": 2, "u32": 4, "u64": 8,
    "f8e4m3fn": 1, "f8e5m2": 1,
    "bf16": 2, "f16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16,
}

_COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# e.g. `%all-reduce.3 = f32[8,2048]{1,0} all-reduce(` or a tuple shape
_OP_RE = re.compile(
    r"=\s*(?:\(([^)]*)\)|(\w+\[[\d,]*\])(?:\{[^}]*\})?)\s*"
    r"(" + "|".join(_COLLECTIVES) + r")(-start|-done)?\("
)
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def collective_stats_from_hlo(hlo_text: str) -> dict:
    """Parse post-SPMD HLO text into per-collective byte totals.

    Bytes counted are each collective's OUTPUT payload on one chip (for
    all-gather that is the received data; for reduce-scatter the reduced
    shard; for all-reduce the full reduced tensor)."""
    per_kind: dict[str, int] = {}
    counts: dict[str, int] = {}
    total = 0
    n_ops = 0
    for m in _OP_RE.finditer(hlo_text):
        tuple_body, single, kind, suffix = m.groups()
        # async -start/-done pairs would double count; count the -start only
        if suffix == "-done":
            continue
        shapes = _SHAPE_RE.findall(tuple_body if tuple_body else single)
        sizes = [_shape_bytes(dt, dims) for dt, dims in shapes]
        if suffix == "-start" and tuple_body:
            # async-start outputs carry (operand, result, contexts...): the
            # payload is the largest buffer, not the tuple sum
            nbytes = max(sizes, default=0)
        else:
            nbytes = sum(sizes)
        per_kind[kind] = per_kind.get(kind, 0) + nbytes
        counts[kind] = counts.get(kind, 0) + 1
        total += nbytes
        n_ops += 1
    return {
        "total_bytes": total,
        "n_collectives": n_ops,
        "bytes_by_kind": per_kind,
        "count_by_kind": counts,
    }


def collective_stats_of_compiled(compiled) -> dict:
    """Analyze an already-compiled executable's collective traffic."""
    try:
        text = compiled.as_text()
    except Exception:  # some backends restrict HLO dumps
        return {"total_bytes": 0, "n_collectives": 0, "error": "hlo unavailable"}
    return collective_stats_from_hlo(text)


def collective_stats_of(jitted_fn, *args, **kwargs) -> dict:
    """Compile and analyze a jitted function's collective traffic for the
    given example arguments. Callers that want to keep the executable (e.g.
    to dispatch it) should lower+compile themselves and use
    ``collective_stats_of_compiled``."""
    return collective_stats_of_compiled(jitted_fn.lower(*args, **kwargs).compile())


# ---------------------------------------------------------------------------
# MEASURED step/sync breakdown (vs the static byte estimates above).
#
# The reference prints measured per-token Sync *time* from wall clocks
# around its socket syncs (src/nn/nn-executor.cpp:148-157, dllama.cpp:54-64).
# Under XLA the collectives run inside the compiled program, so the measured
# equivalent comes from a profiler trace: collect the xplane, sum the op
# events, and split out those whose names are collective ops. On TPU these
# live on the /device:* planes; on XLA:CPU (virtual-mesh tests) the thunks
# emit the same op names as host TraceMes.
# ---------------------------------------------------------------------------


def _parse_xplanes(pb_paths) -> dict | None:
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: PLC0415
    except ImportError:
        return None

    busy_ps = 0
    coll_ps = 0
    coll_by_kind: dict[str, int] = {}
    saw_device_plane = False
    for path in pb_paths:
        space = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            space.ParseFromString(f.read())
        for plane in space.planes:
            is_device = plane.name.startswith("/device:")
            saw_device_plane |= is_device
            metas = plane.event_metadata
            # device planes: use ONE op-level line — "XLA Ops", else the
            # line with the largest summed duration (lines overlap in wall
            # time, so summing several would multiply-count busy time).
            # Host planes: scan every thread for thunk TraceMes.
            lines = plane.lines
            if is_device:
                op_lines = [ln for ln in lines if ln.name == "XLA Ops"]
                if not op_lines and lines:
                    op_lines = [max(
                        lines,
                        key=lambda ln: sum(e.duration_ps for e in ln.events),
                    )]
                lines = op_lines
            for line in lines:
                for ev in line.events:
                    name = metas[ev.metadata_id].name
                    if is_device:
                        busy_ps += ev.duration_ps
                    for kind in _COLLECTIVES:
                        if name.startswith(kind):
                            coll_ps += ev.duration_ps
                            coll_by_kind[kind] = (
                                coll_by_kind.get(kind, 0) + ev.duration_ps
                            )
                            if not is_device:
                                busy_ps += ev.duration_ps
                            break
                    else:
                        if not is_device and name == "PjRtCpuExecutable::Execute":
                            busy_ps += ev.duration_ps
    return {
        "busy_ps": busy_ps,
        "collective_ps": coll_ps,
        "collective_by_kind_ps": coll_by_kind,
        "from_device_plane": saw_device_plane,
    }


def measured_step_breakdown(run_step, steps: int = 4, warmup: int = 1) -> dict:
    """Profile ``steps`` calls of ``run_step()`` (which must block until the
    device finishes) and return the MEASURED per-step time split:

    {"step_ms": wall per step,
     "device_busy_ms": summed op time per step (across local devices),
     "sync_ms": collective op time per step, "sync_frac": of device_busy_ms,
     "source": "device-plane" | "host-traceme" | "wall-only"}

    The collective split is the measured analogue of the reference's per-
    token Sync ms. On multi-device (virtual CPU) meshes op times sum over
    all local devices, so sync_frac (same multiplicity in numerator and
    denominator) is the comparable number, not sync_ms itself.

    source="host-traceme" (XLA:CPU) is an APPROXIMATION: busy time counts
    executable-dispatch spans plus collective thunks (other compute thunks
    don't emit TraceMes), and CPU collective time is mostly rendezvous wait
    between the virtual devices sharing one host — treat the split as
    indicative, and the device-plane numbers (real TPU) as the measurement."""
    import glob
    import shutil
    import tempfile
    import time

    import jax

    for _ in range(max(0, warmup)):
        run_step()
    tmpdir = tempfile.mkdtemp(prefix="dllama-prof-")
    try:
        wall = 0.0
        with jax.profiler.trace(tmpdir):
            # time each call individually so profiler session start/stop and
            # the xplane dump don't inflate the per-step number
            for _ in range(steps):
                t0 = time.perf_counter()
                run_step()
                wall += time.perf_counter() - t0
        parsed = _parse_xplanes(
            glob.glob(tmpdir + "/**/*.xplane.pb", recursive=True)
        )
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)

    out = {"step_ms": wall / steps * 1e3}
    if parsed is None or not (parsed["busy_ps"] or parsed["collective_ps"]):
        out.update(device_busy_ms=None, sync_ms=None, sync_frac=None,
                   source="wall-only")
        return out
    busy_ms = parsed["busy_ps"] / 1e9 / steps
    sync_ms = parsed["collective_ps"] / 1e9 / steps
    out.update(
        device_busy_ms=round(busy_ms, 3),
        sync_ms=round(sync_ms, 3),
        sync_frac=round(sync_ms / busy_ms, 4) if busy_ms else None,
        sync_ms_by_kind={
            k: round(v / 1e9 / steps, 3)
            for k, v in parsed["collective_by_kind_ps"].items()
        },
        source="device-plane" if parsed["from_device_plane"] else "host-traceme",
    )
    return out
