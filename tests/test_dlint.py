"""dlint (distributed_llama_multiusers_tpu/analysis): the analyzer itself
AND its verdict on the real tree.

Two layers, per the PR-2 contract:

- **self-tests** — every checker gets known-bad and known-good fixture
  snippets (including waiver syntax), so the analyzer is regression-tested
  as a program, not just trusted on its current verdict;
- **the tier-1 gate** — the full package must analyze clean (zero
  non-baselined findings). A new unlocked counter bump, un-waived
  host-sync in the decode path, wall-clock read, busy-poll, or undeclared
  sharding axis anywhere in the package fails this test.

Pure-stdlib imports: these tests run without jax.
"""

from __future__ import annotations

import textwrap
from pathlib import Path

import pytest

from distributed_llama_multiusers_tpu.analysis import (
    DEFAULT_BASELINE,
    PACKAGE_ROOT,
    Analyzer,
    analyze_paths,
    default_checkers,
    load_baseline,
)
from distributed_llama_multiusers_tpu.analysis.cli import main as dlint_main


def run_on(tmp_path: Path, files: dict[str, str], baseline: set | None = None):
    """Write fixture files under tmp_path and analyze them (no baseline
    unless given). Returns the finding list."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src), encoding="utf-8")
    analyzer = Analyzer(default_checkers())
    return analyzer.run([tmp_path], baseline=baseline or set(), root=tmp_path)


def checks_of(findings):
    return sorted(f.check for f in findings)


# -- the tier-1 gate ---------------------------------------------------------


def test_package_analyzes_clean():
    """THE gate: zero non-baselined findings over the real package. If this
    fails, either fix the finding, waive it in place with a reason, or (last
    resort) baseline it — see docs/LINT.md."""
    findings = analyze_paths()
    assert findings == [], "dlint findings on the tree:\n" + "\n".join(
        f.render() for f in findings
    )


def test_cli_runs_clean_with_shipped_baseline(capsys):
    assert dlint_main([]) == 0
    assert "clean" in capsys.readouterr().out


def test_shipped_baseline_is_empty():
    """Adoption fixed or waived everything; keep it that way."""
    assert load_baseline(DEFAULT_BASELINE) == set()


def test_real_decl_sites_are_collected():
    """The EngineStats/QosQueue declarations actually reach the checker
    (guards against the declaration syntax silently rotting)."""
    from distributed_llama_multiusers_tpu.analysis.core import Project
    from distributed_llama_multiusers_tpu.analysis.lock_check import GuardedByChecker
    import ast

    project = Project()
    checker = GuardedByChecker()
    for rel in ("runtime/engine.py", "serving/qos.py"):
        p = PACKAGE_ROOT / rel
        from distributed_llama_multiusers_tpu.analysis.core import SourceFile

        sf = SourceFile(
            path=p, display=rel, text=p.read_text(), tree=ast.parse(p.read_text())
        )
        checker.collect(sf, project)
    assert "decode_steps" in project.guarded
    assert "prefix_hits" in project.guarded
    assert "_deficit" in project.guarded
    assert project.guarded["_depth"][0] == frozenset({"_lock", "_not_empty"})


# -- guarded-by --------------------------------------------------------------

GUARDED_CLS = """
    import threading

    class Stats:
        _dlint_guarded_by = {("lock",): ("hits", "misses")}

        def __init__(self):
            self.lock = threading.Lock()
            self.hits = 0
            self.misses = 0
"""


def test_guarded_by_flags_unlocked_access(tmp_path):
    findings = run_on(tmp_path, {"m.py": GUARDED_CLS + """
        def bump(s):
            s.hits += 1
    """})
    assert checks_of(findings) == ["guarded-by"]
    assert "'s.hits'" in findings[0].message


def test_guarded_by_engine_stats_shape(tmp_path):
    """Acceptance-criterion demo: a guarded EngineStats-style counter
    accessed outside stats.lock is a finding, even through a chain base
    (self.engine.stats) and even when SOME lock is held — it must be the
    declared lock on the SAME base."""
    src = GUARDED_CLS + """
        class Scheduler:
            def __init__(self, engine):
                self.engine = engine

            def good(self):
                with self.engine.stats.lock:
                    self.engine.stats.hits += 1

            def bad_unlocked(self):
                self.engine.stats.hits += 1

            def bad_wrong_base(self, other):
                with other.stats.lock:
                    self.engine.stats.hits += 1
    """
    findings = run_on(tmp_path, {"m.py": src})
    assert checks_of(findings) == ["guarded-by", "guarded-by"]
    lines = {f.line for f in findings}
    assert len(lines) == 2


def test_guarded_by_accepts_lock_locked_and_init(tmp_path):
    findings = run_on(tmp_path, {"m.py": GUARDED_CLS + """
        class User:
            def ok_with(self, s):
                with s.lock:
                    s.hits += 1

            def _bump_locked(self, s):
                s.misses += 1  # caller holds s.lock by contract
    """})
    assert findings == []


def test_guarded_by_alternate_locks_and_waiver(tmp_path):
    findings = run_on(tmp_path, {"m.py": """
        import threading

        class Q:
            _dlint_guarded_by = {("_lock", "_cv"): ("_depth",)}

            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._depth = 0

            def push(self):
                with self._cv:
                    self._depth += 1

            def empty(self):
                # dlint: ok[guarded-by] advisory racy read by contract
                return self._depth == 0
    """})
    assert findings == []


def test_guarded_by_closure_in_with_block_is_not_protected(tmp_path):
    """A closure defined inside `with lock:` runs after the lock is
    released — the enclosing with must not count across the def/lambda
    boundary."""
    findings = run_on(tmp_path, {"m.py": GUARDED_CLS + """
        def make_cb(s):
            with s.lock:
                cb = lambda: s.hits + 1
                def cb2():
                    return s.misses
            return cb, cb2
    """})
    assert checks_of(findings) == ["guarded-by", "guarded-by"]


def test_guarded_by_malformed_declaration(tmp_path):
    findings = run_on(tmp_path, {"m.py": """
        class Bad:
            _dlint_guarded_by = {("lock",): 42}
    """})
    assert checks_of(findings) == ["guarded-by"]
    assert "malformed" in findings[0].message


# -- host-sync ---------------------------------------------------------------


def test_host_sync_flags_unwaived_asarray_in_decode_path(tmp_path):
    """Acceptance-criterion demo: a new un-waived host sync in the decode
    path is a finding."""
    src = """
        import numpy as np

        def decode(logits):
            return np.asarray(logits)
    """
    findings = run_on(tmp_path, {"runtime/engine.py": src})
    assert checks_of(findings) == ["host-sync"]
    # the same code OUTSIDE the decode-path scope is not flagged
    assert run_on(tmp_path / "other", {"models/llama.py": src}) == []


def test_host_sync_waiver_suppresses(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": """
        import numpy as np

        def decode(logits):
            # dlint: ok[host-sync] the one packed readback per step
            return np.asarray(logits)
    """})
    assert findings == []


def test_host_sync_flags_item_and_cast(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": """
        def f(x, toks_np):
            a = x.item()
            b = int(x)
            c = int(toks_np[0])  # *_np host-array convention: exempt
            return a, b, c
    """})
    assert checks_of(findings) == ["host-sync", "host-sync"]


def test_host_sync_cast_rule_is_engine_only(tmp_path):
    findings = run_on(tmp_path, {"runtime/scheduler.py": """
        def f(greedy):
            return int(greedy[0])  # host numpy from the engine: fine here
    """})
    assert findings == []


def test_host_sync_implicit_bool_on_compiled_step_output(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": """
        class E:
            def step(self, x):
                logits, toks = self._decode_fn(x)
                if logits:
                    return toks
                return None
    """})
    assert checks_of(findings) == ["host-sync"]
    assert "implicit bool" in findings[0].message


def test_host_sync_covers_telemetry_package(tmp_path):
    """PR-5 satellite: the telemetry package is registered under host-sync
    — a device->host transfer construct added to a telemetry hot path
    (the scheduler calls these hooks from inside the serving loop) is a
    finding there exactly like in runtime/."""
    bad = """
        import numpy as np

        def on_token(tokens):
            return np.asarray(tokens)
    """
    findings = run_on(tmp_path, {"telemetry/spans.py": bad})
    assert checks_of(findings) == ["host-sync"]
    # metrics.py is scoped too; .item() is the other transfer spelling
    findings = run_on(tmp_path / "b", {"telemetry/metrics.py": """
        def observe(h, v):
            h.observe(v.item())
    """})
    assert checks_of(findings) == ["host-sync"]
    # the clean shape: host floats in, host floats out — no findings
    clean = run_on(tmp_path / "c", {"telemetry/hub.py": """
        import time

        def on_step(tracer, t0):
            tracer.slice("step.sync", "pipeline", t0, time.perf_counter())
    """})
    assert clean == []


def test_clock_covers_telemetry_files(tmp_path):
    """clock is package-wide, telemetry included: a wall-clock duration in
    a telemetry file is a finding; the one sanctioned absolute-timestamp
    site (the JSON log envelope) carries a waiver in the real tree."""
    findings = run_on(tmp_path, {"telemetry/logs.py": """
        import time

        def stamp():
            return time.time()
    """})
    assert checks_of(findings) == ["clock"]


def test_real_telemetry_guard_decls_are_collected():
    """The SpanTracer/metrics declarations reach the guarded-by checker
    (same rot-guard as the EngineStats/QosQueue assertion above)."""
    import ast

    from distributed_llama_multiusers_tpu.analysis.core import Project, SourceFile
    from distributed_llama_multiusers_tpu.analysis.lock_check import GuardedByChecker

    project = Project()
    checker = GuardedByChecker()
    for rel in ("telemetry/spans.py", "telemetry/metrics.py"):
        p = PACKAGE_ROOT / rel
        sf = SourceFile(
            path=p, display=rel, text=p.read_text(), tree=ast.parse(p.read_text())
        )
        checker.collect(sf, project)
    assert "_trace_ring" in project.guarded
    assert "_hist_counts" in project.guarded
    assert "_reg_metrics" in project.guarded
    assert project.guarded["_trace_dropped"][0] == frozenset({"_trace_lock"})


def test_guarded_by_flags_unlocked_telemetry_ring_access(tmp_path):
    """A new unlocked touch of the tracer ring state is a finding — the
    telemetry satellite's known-bad fixture."""
    findings = run_on(tmp_path, {"telemetry/spans.py": """
        import threading

        class SpanTracer:
            _dlint_guarded_by = {("_trace_lock",): ("_trace_ring",)}

            def __init__(self):
                self._trace_lock = threading.Lock()
                self._trace_ring = []

            def bad_append(self, ev):
                self._trace_ring.append(ev)

            def good_append(self, ev):
                with self._trace_lock:
                    self._trace_ring.append(ev)
    """})
    assert checks_of(findings) == ["guarded-by"]
    assert "_trace_ring" in findings[0].message


# -- pipeline-sync -----------------------------------------------------------


def test_pipeline_sync_flags_sync_in_dispatch_half(tmp_path):
    """Acceptance-criterion demo: a host-sync construct inside the
    pipelined dispatch half is a finding (on top of the file-wide host-sync
    rule) — the dispatch half must enqueue device work from host metadata
    only, or the async chain silently re-serializes."""
    findings = run_on(tmp_path, {"runtime/scheduler.py": """
        import numpy as np

        class Sched:
            def _pipeline_dispatch(self, live, pl_pos, feed):
                arr = np.asarray(feed)
                self.engine.decode_pipelined(arr)
    """})
    assert "pipeline-sync" in checks_of(findings)
    # the same sync OUTSIDE the dispatch half is host-sync's business only
    other = run_on(tmp_path / "other", {"runtime/scheduler.py": """
        import numpy as np

        class Sched:
            def _pipeline_consume(self, live):
                # dlint: ok[host-sync] the lagged per-step readback
                return np.asarray(self.engine.pipeline_consume())
    """})
    assert "pipeline-sync" not in checks_of(other)


def test_pipeline_sync_clean_dispatch_half(tmp_path):
    """Building host metadata arrays and dispatching is exactly what the
    dispatch half is for — no findings."""
    findings = run_on(tmp_path, {"runtime/scheduler.py": """
        import numpy as np

        class Sched:
            def _pipeline_dispatch(self, live, pl_pos, feed):
                positions = np.full(4, 128, np.int32)
                for i, lane in live.items():
                    positions[i] = pl_pos[i]
                self.engine.decode_pipelined(positions, tokens=feed)
    """})
    assert findings == []


def test_pipeline_sync_implicit_bool_and_cast(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": """
        class E:
            def decode_pipelined(self, positions, tokens=None):
                nxt, packed, self.cache = self._decode_pl_fn(positions)
                if nxt:
                    return int(packed)
                return None
    """})
    pipeline = [f for f in findings if f.check == "pipeline-sync"]
    msgs = " ".join(f.message for f in pipeline)
    assert "implicit bool" in msgs and "cast" in msgs


def test_pipeline_sync_covers_fused_dispatch(tmp_path):
    """The fused prefill+decode admission step is a dispatch half too: a
    host-sync construct inside ``engine.decode_prefill_fused`` (or the
    fused branch of ``_pipeline_dispatch``) re-serializes the chain at the
    exact moment it is supposed to hide admission work — a finding."""
    findings = run_on(tmp_path, {"runtime/engine.py": """
        import numpy as np

        class E:
            def decode_prefill_fused(self, positions, chunk=None, tokens=None):
                nxt, packed, self.cache = self._decode_prefill_fn(positions)
                return np.asarray(packed)
    """})
    assert "pipeline-sync" in checks_of(findings)
    # the clean shape: host chunk data goes IN, nothing comes back
    clean = run_on(tmp_path / "clean", {"runtime/engine.py": """
        import numpy as np

        class E:
            def decode_prefill_fused(self, positions, chunk=None, tokens=None):
                padded = np.zeros(16, np.int32)
                padded[: len(chunk)] = chunk
                nxt, packed, self.cache = self._decode_prefill_fn(
                    positions, padded
                )
                self._pl_carry = nxt
                self._pl_inflight.append(packed)
    """})
    assert "pipeline-sync" not in checks_of(clean)


def test_pipeline_sync_waiver_suppresses(tmp_path):
    """A waiver naming BOTH overlapping checks silences the line (host-sync
    also scopes these files)."""
    findings = run_on(tmp_path, {"runtime/engine.py": """
        import numpy as np

        class E:
            def decode_pipelined(self, positions, tokens=None):
                # dlint: ok[host-sync, pipeline-sync] probe build: deliberate sync
                return np.asarray(positions)
    """})
    assert findings == []


# -- clock -------------------------------------------------------------------


def test_clock_flags_time_time_everywhere(tmp_path):
    findings = run_on(tmp_path, {"anywhere/mod.py": """
        import time

        def seed():
            return int(time.time())
    """})
    assert checks_of(findings) == ["clock"]


def test_clock_accepts_monotonic_and_waived_timestamps(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        import time

        def dur():
            return time.monotonic() + time.perf_counter()

        def created():
            return int(time.time())  # dlint: ok[clock] absolute API timestamp
    """})
    assert findings == []


def test_clock_is_import_aware(tmp_path):
    """`from time import time` and `import time as t` must not bypass the
    wall-clock ban (the dotted-attribute spelling is not the only one)."""
    findings = run_on(tmp_path, {"a.py": """
        from time import time

        def deadline():
            return time() + 5.0
    """})
    assert checks_of(findings) == ["clock"]
    assert "from time import time" in findings[0].message
    findings = run_on(tmp_path / "b", {"b.py": """
        import time as t

        def seed():
            return int(t.time())
    """})
    assert checks_of(findings) == ["clock"]


def test_clock_flags_naive_datetime_now(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        from datetime import datetime

        def now():
            return datetime.now()
    """})
    assert checks_of(findings) == ["clock"]


# -- condvar -----------------------------------------------------------------


def test_condvar_wait_needs_predicate_loop(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()
                self._n = 0

            def bad(self):
                with self._cv:
                    self._cv.wait()

            def good_loop(self):
                with self._cv:
                    while self._n == 0:
                        self._cv.wait()

            def good_wait_for(self):
                with self._cv:
                    self._cv.wait_for(lambda: self._n > 0)
    """})
    assert checks_of(findings) == ["condvar"]
    assert "predicate loop" in findings[0].message


def test_condvar_flags_event_busy_poll(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        import threading

        class Loop:
            def __init__(self):
                self._stop = threading.Event()

            def bad(self):
                while not self._stop.is_set():
                    self._stop.wait(0.001)

            def good(self):
                self._stop.wait(0.25)
    """})
    assert checks_of(findings) == ["condvar"]
    assert "busy-poll" in findings[0].message


def test_condvar_daemon_thread_needs_join(tmp_path):
    bad = """
        import threading

        def serve():
            t = threading.Thread(target=print, daemon=True)
            t.start()
    """
    findings = run_on(tmp_path, {"mod.py": bad})
    assert checks_of(findings) == ["condvar"]
    assert "join" in findings[0].message
    good = """
        import threading

        class S:
            def start(self):
                self._t = threading.Thread(target=print, daemon=True)
                self._t.start()

            def stop(self):
                self._t.join(timeout=30)
    """
    assert run_on(tmp_path / "g", {"mod.py": good}) == []


# -- sharding-axis -----------------------------------------------------------


def test_sharding_axis_must_be_declared(tmp_path):
    """Acceptance-criterion demo: a PartitionSpec naming an axis the mesh
    builders never create is a finding."""
    findings = run_on(tmp_path, {
        "parallel/mesh.py": 'AXES = ("dp", "tp")\n',
        "parallel/sharding.py": """
            from jax.sharding import PartitionSpec as P

            GOOD = P("dp", None, "tp")
            BAD = P("dp", "model")
        """,
    })
    assert checks_of(findings) == ["sharding-axis"]
    assert "'model'" in findings[0].message


def test_sharding_axis_covers_collectives_and_shape_lookups(tmp_path):
    findings = run_on(tmp_path, {
        "parallel/mesh.py": 'AXES = ("dp", "tp", "sp")\n',
        "parallel/ops.py": """
            import jax

            def f(x, mesh):
                a = jax.lax.psum(x, "sp")
                b = jax.lax.ppermute(x, "ring", [(0, 1)])
                n = mesh.shape["tp"]
                m = mesh.shape.get("oops", 1)
                return a, b, n, m
        """,
    })
    assert checks_of(findings) == ["sharding-axis", "sharding-axis"]
    msgs = " ".join(f.message for f in findings)
    assert "'ring'" in msgs and "'oops'" in msgs


def test_sharding_axis_default_axes_without_decl(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        from jax.sharding import PartitionSpec as P

        OK = P("tp")
        BAD = P("nope")
    """})
    assert checks_of(findings) == ["sharding-axis"]


# -- waiver hygiene ----------------------------------------------------------


def test_bare_waiver_is_a_finding(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        import time

        def f():
            return time.time()  # dlint: ok[clock]
    """})
    # the bare waiver is rejected AND therefore does not suppress the clock
    # finding either
    assert checks_of(findings) == ["clock", "waiver"]
    assert "without a reason" in [f for f in findings if f.check == "waiver"][0].message


def test_unknown_check_name_in_waiver(tmp_path):
    findings = run_on(tmp_path, {"mod.py": """
        X = 1  # dlint: ok[not-a-check] some reason
    """})
    assert checks_of(findings) == ["waiver"]
    assert "unknown check" in findings[0].message


def test_waiver_only_covers_named_check(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": """
        import numpy as np
        import time

        def f(logits):
            # dlint: ok[clock] wrong check name for this line
            return np.asarray(logits)

        def g():
            return time.time()  # dlint: ok[host-sync] also wrong
    """})
    assert checks_of(findings) == ["clock", "host-sync"]


def test_star_waiver_and_standalone_placement(tmp_path):
    findings = run_on(tmp_path, {"runtime/engine.py": """
        import numpy as np

        def f(logits):
            # dlint: ok[*] benchmark probe: sync everything on purpose
            return np.asarray(logits)
    """})
    assert findings == []


def test_waiver_in_string_literal_does_not_suppress(tmp_path):
    findings = run_on(tmp_path, {"mod.py": '''
        import time

        def f():
            doc = "# dlint: ok[clock] not a comment"
            return time.time(), doc
    '''})
    assert checks_of(findings) == ["clock"]


# -- baseline ----------------------------------------------------------------


def test_baseline_suppresses_only_listed_findings(tmp_path):
    files = {"mod.py": """
        import time

        def f():
            return time.time()

        def g():
            return datetime.datetime.now()

        import datetime
    """}
    all_findings = run_on(tmp_path, files)
    assert len(all_findings) == 2
    baseline = {all_findings[0].key}
    remaining = run_on(tmp_path, files, baseline=baseline)
    assert len(remaining) == 1
    assert remaining[0].key == all_findings[1].key


def test_write_baseline_roundtrip(tmp_path, capsys):
    (tmp_path / "mod.py").write_text("import time\nT = time.time()\n")
    bl = tmp_path / "bl.txt"
    assert dlint_main([str(tmp_path), "--baseline", str(bl), "--write-baseline"]) == 0
    assert bl.exists()
    capsys.readouterr()
    # with the written baseline the same tree is clean
    assert dlint_main([str(tmp_path), "--baseline", str(bl)]) == 0
    # without it, the finding is back
    assert dlint_main([str(tmp_path), "--no-baseline", "--baseline", str(bl)]) == 1


def test_write_baseline_excludes_unbaselinable_findings(tmp_path, capsys):
    """waiver/parse findings are never filtered by the baseline, so writing
    their keys would strand dead entries while the gate keeps failing; the
    CLI must report them and exit 1 instead."""
    (tmp_path / "mod.py").write_text(
        "import time\nT = time.time()  # dlint: ok[clock]\n"
    )
    bl = tmp_path / "bl.txt"
    rc = dlint_main([str(tmp_path), "--baseline", str(bl), "--write-baseline"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "cannot be baselined" in out
    keys = [
        line for line in bl.read_text().splitlines()
        if line.strip() and not line.startswith("#")
    ]
    # the clock finding (un-suppressed by the bare waiver) was baselined;
    # the waiver finding was not
    assert len(keys) == 1 and keys[0].startswith("clock\t")


def test_cli_missing_path_is_usage_error(tmp_path):
    assert dlint_main([str(tmp_path / "nope")]) == 2


def test_syntax_error_is_a_parse_finding(tmp_path):
    findings = run_on(tmp_path, {"mod.py": "def broken(:\n"})
    assert checks_of(findings) == ["parse"]
