"""Serving QoS subsystem: bounded admission, deadlines, graceful drain.

The substrate between the HTTP layer (server/http.py) and the
continuous-batching loop (runtime/scheduler.py): qos.py owns who gets in
and in what order, deadlines.py owns how long anything may wait or run,
drain.py owns how the whole thing shuts down without dropping clients,
breaker.py owns when a failing engine stops admitting at all,
watchdog.py owns turning a hung step into a signal instead of a silent
wedge, and the crash-durability trio — journal.py (append-only request
journal), recovery.py (deterministic replay re-admission), resume.py
(bounded delta relays for mid-stream SSE reattach) — owns making a
process death a latency blip instead of data loss. Imports nothing from
runtime/ or server/ — it is a leaf both depend on.
"""

from .breaker import CircuitBreaker
from .deadlines import (
    DeadlinePolicy,
    budget_expired,
    budget_for,
    queue_expired,
    queue_timeout_for,
)
from .drain import drain_scheduler
from .journal import (
    JournalEntry,
    JournalImage,
    RequestJournal,
    admit_record,
    entry_from_admit_record,
    read_journal,
)
from .qos import (
    AdmissionRejected,
    Priority,
    QosQueue,
    jittered_retry_after,
    page_cost,
)
from .recovery import (
    RecoveryCoordinator,
    attach_recovered_stream,
    recover_scheduler,
)
from .resume import StreamRegistry, StreamRelay
from .watchdog import StepWatchdog
