"""Sweep the slab-kernel tuning knobs on real hardware.

Runs `BENCH_CHILD=1 BENCH_PHASE=primary python bench.py` in a child process
per configuration (the knobs are read at module import, so each combo needs
a fresh interpreter) and reports decode tok/s + hbm_util per combo.

Run: python scripts/kernel_sweep.py [timeout_per_combo_s] [--update-table]

With --update-table, a winning dequant_* candidate is written back into
ops/dequant_table.json as a wildcard decode-class row, so the next
DLLAMA_DEQUANT=auto serving start resolves to the measured winner.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_llama_multiusers_tpu.ops.pallas_q40 import (  # noqa: E402
    DEQUANT_MODES,
    SWEEP_COMBOS,  # the one shared DMA-geometry table
)

# same candidate families as bench.py's in-bench sweep: dequant arithmetic
# variants (the round-5 VPU-bound hypothesis), the round-2 narrow-tile
# geometry, then the DMA-size combos
CANDIDATES: dict[str, dict] = {
    **{
        f"dequant_{m}": {"DLLAMA_DEQUANT": m}
        for m in DEQUANT_MODES if m != "v4"
    },
    "r02_narrow512": {
        "DLLAMA_W_MAX": "512",
        "DLLAMA_SINGLE_SLAB": "262144",
        "DLLAMA_TARGET_BLOCK": "262144",
    },
    **{
        n: {"DLLAMA_SINGLE_SLAB": str(s), "DLLAMA_TARGET_BLOCK": str(b)}
        for n, (s, b) in SWEEP_COMBOS.items()
    },
}


def main():
    flags = [a for a in sys.argv[1:] if a.startswith("--")]
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    update_table = "--update-table" in flags
    budget = float(args[0]) if args else 420.0
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    results = {}
    for name, knobs in CANDIDATES.items():
        env = dict(
            os.environ,
            BENCH_CHILD="1",
            BENCH_PHASE="primary",
            **knobs,
        )
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(repo, "bench.py")],
                capture_output=True, text=True, timeout=budget, env=env,
                cwd=repo,
            )
            line = next(
                (ln for ln in reversed(proc.stdout.strip().splitlines())
                 if ln.startswith("{")),
                None,
            )
            rec = json.loads(line) if line else {"error": proc.stderr[-200:]}
        except subprocess.TimeoutExpired:
            rec = {"error": f"timeout {budget:.0f}s"}
        results[name] = rec
        print(f"{name:20s} tok/s={rec.get('value')} "
              f"hbm={rec.get('hbm_util')} err={rec.get('error', '')[:80]}",
              flush=True)
    best = max(
        (r for r in results.items() if r[1].get("value")),
        key=lambda kv: kv[1]["value"],
        default=None,
    )
    if best:
        print(f"BEST: {best[0]} -> {best[1]['value']} tok/s "
              f"(hbm_util {best[1].get('hbm_util')})")
        if update_table and best[0].startswith("dequant_"):
            # feed the measured winner back into the persisted selection
            # table (the primary phase measures decode throughput, so the
            # row lands in the decode m-class)
            from distributed_llama_multiusers_tpu.ops.dequant_select import (
                record_win,
            )

            mode = best[0][len("dequant_"):]
            path = record_win(
                "*", "*", "decode", mode,
                source=f"scripts/kernel_sweep.py ({best[1]['value']} tok/s)",
            )
            print(f"TABLE: decode -> {mode} recorded in {path}")


if __name__ == "__main__":
    main()
