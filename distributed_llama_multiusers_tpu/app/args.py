"""CLI argument surface — flag-compatible with the reference's hand-rolled
parser (src/app.cpp:33-146), reinterpreted for TPU where needed:

--workers      reference: space-separated worker ip:port list; here: a device
               count or mesh spec ("8" or "dp2,tp2,sp2") selecting how many
               chips / which axes to shard over.
--nthreads     reference: executor thread count; here: host-side threads
               (tokenization etc.) — accepted, mostly advisory.
--gpu-index / --gpu-segments / --net-turbo: accepted for CLI compatibility,
               no-ops on TPU (single-program SPMD has no segment split or
               socket turbo mode).
"""

from __future__ import annotations

import argparse


def _float_type(s: str) -> int:
    # lazy: quants.codec pulls numpy, and this module is also the
    # dllama-router CLI's surface — the router is stdlib-only by design
    # and must start on hosts without numpy/jax installed
    from ..quants.codec import FloatType

    m = {"f32": FloatType.F32, "f16": FloatType.F16, "q40": FloatType.Q40, "q80": FloatType.Q80}
    if s not in m:
        raise argparse.ArgumentTypeError(f"unknown float type {s!r}")
    return m[s]


def build_parser(prog: str, api: bool = False) -> argparse.ArgumentParser:
    # imported here, not at module top: build_router_parser below shares
    # this module, and the router CLI must import without numpy
    from ..quants.codec import FloatType

    p = argparse.ArgumentParser(prog=prog)
    if not api:
        p.add_argument("mode", choices=["inference", "chat", "worker", "train"],
                       help="run mode (src/dllama.cpp:216-239; train is a "
                            "beyond-parity extension — the reference is "
                            "inference-only)")
    p.add_argument("--model", help="path to .m model file")
    p.add_argument("--tokenizer", help="path to .t tokenizer file")
    p.add_argument("--prompt", default=None)
    p.add_argument("--steps", type=int, default=64, help="tokens to generate (inference mode)")
    p.add_argument("--max-seq-len", type=int, default=0, help="clamp context length (src/llm.cpp:89-91)")
    p.add_argument("--buffer-float-type", type=_float_type, default=FloatType.F32,
                   help="activation quant emulation: q80 reproduces the reference's lossy "
                        "activation casts (bit-fidelity mode); f32 (default) runs clean — "
                        "the reference defaults to q80 because its TCP links need the "
                        "bandwidth, which ICI does not")
    p.add_argument("--weights", default="auto", choices=["auto", "packed", "dense"],
                   help="Q40 models: 'packed' keeps int4+scales resident in HBM with "
                        "dequant-in-matmul (the reference's Q40-at-rest execution, "
                        "src/nn/nn-cpu-ops.cpp:222-440); 'dense' dequantizes at load. "
                        "auto = packed on TPU, dense elsewhere")
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--topp", type=float, default=0.9)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--nthreads", type=int, default=1)
    p.add_argument("--max-lanes", type=int, default=8, help="concurrent request lanes (continuous batching)")
    p.add_argument("--kv-dtype", default="auto",
                   choices=["auto", "bf16", "f32", "f8"],
                   help="KV cache dtype: auto = bf16 on TPU (half the HBM), "
                        "f32 on CPU; f8 = float8_e4m3 storage (quarter the "
                        "f32 HBM — double the lanes or context per chip; "
                        "dequant fuses into the attention reads)")
    p.add_argument("--chat-template", default=None,
                   choices=[None, "llama2", "llama3", "deepSeek3", "chatml"])
    p.add_argument("--workers", nargs="*", default=None,
                   help="TPU: device count or mesh spec (dp2,tp4); reference compat")
    # multi-host pod bootstrap (reference: worker serve() + root connect,
    # src/app.cpp:405-464 -> jax.distributed). Run the SAME command on every
    # host with its own --process-id; workers use mode `worker`.
    p.add_argument("--coordinator", default=None,
                   help="host:port of process 0 for jax.distributed multi-host")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--port", type=int, default=9990)
    p.add_argument("--host", default="0.0.0.0")
    # accepted for reference CLI compatibility; no-ops on TPU:
    p.add_argument("--gpu-index", type=int, default=-1, help=argparse.SUPPRESS)
    p.add_argument("--gpu-segments", default=None, help=argparse.SUPPRESS)
    p.add_argument("--net-turbo", type=int, default=1, help=argparse.SUPPRESS)
    p.add_argument("--benchmark", action="store_true", help="print per-token timing stats")
    p.add_argument("--no-spec", action="store_true",
                   help="disable prompt-lookup speculative decoding "
                        "(serving and greedy CLI inference)")
    p.add_argument("--prefix-min-tokens", type=int, default=None,
                   help="serving: reuse resident lane KV when a new "
                        "request shares at least this many leading prompt "
                        "tokens (prefix caching); 0 disables; default: "
                        "scheduler default (16)")
    # paged KV pool (runtime/kvpool.py; docs/SERVING.md "Paged KV")
    p.add_argument("--paged-kv", default="off", choices=["on", "off"],
                   help="serving: store KV as a pooled set of fixed-size "
                        "pages behind a per-lane page table instead of "
                        "contiguous per-lane planes. Prefix sharing "
                        "becomes a refcount bump on the SAME physical "
                        "pages (zero HBM copies; copy_lane dies), "
                        "divergence is a single-page copy-on-write, and "
                        "finished sessions park their sharable pages so "
                        "resident sessions exceed lanes; pool exhaustion "
                        "sheds with a retryable 429. Token streams are "
                        "byte-identical to the contiguous layout. 'off' "
                        "(default) keeps contiguous planes bit-for-bit "
                        "(escape hatch)")
    p.add_argument("--kv-page-size", type=int, default=None,
                   help="--paged-kv on: tokens per KV page (power of two; "
                        "shrunk automatically to fit short contexts). "
                        "Smaller pages = finer sharing granularity and "
                        "less tail waste, larger = smaller page tables "
                        "and fewer, bigger COW copies; default: pool "
                        "default (64)")
    p.add_argument("--kv-pool-pages", type=int, default=None,
                   help="--paged-kv on: total pages in the device pool "
                        "(default: the contiguous layout's exact HBM "
                        "footprint, max-lanes x blocks-per-full-lane — "
                        "oversubscription then comes from sessions "
                        "reserving only prompt + max_tokens, not from a "
                        "bigger pool)")
    p.add_argument("--kv-max-parked", type=int, default=None,
                   help="--paged-kv on: max finished sessions whose "
                        "sharable prefix pages stay resident (refcounted, "
                        "LRU-evicted under pool pressure; evicted "
                        "sessions rebuild deterministically from the "
                        "request journal on next activity); 0 disables "
                        "parking; default: pool default (64)")
    p.add_argument("--kv-host-bytes", type=int, default=None,
                   help="--paged-kv on: host-RAM byte budget for the KV "
                        "swap tier (runtime/kvpool.py HostTier). Parked "
                        "pages evicted under pool pressure swap their "
                        "bytes to host RAM (sha256-framed, LRU within "
                        "the budget) instead of dropping; a later "
                        "admission that misses HBM but hits the host "
                        "tier swaps pages back in — cheaper than a "
                        "journal rebuild, dearer than resident reuse. "
                        "0 (default) disables the tier and restores "
                        "drop-to-rebuild behavior bit-for-bit")
    # structured output (grammar/; docs/SERVING.md "Structured output")
    p.add_argument("--grammar", default="on", choices=["on", "off"],
                   help="serving: grammar-constrained decoding — requests "
                        "with response_format {'type':'json_object'} or "
                        "{'type':'json_schema',...} compile into a "
                        "token-level automaton enforced INSIDE the "
                        "compiled step families (masked exact top-p + "
                        "on-device state carry), so constrained and "
                        "unconstrained lanes coexist with zero pipeline "
                        "flushes. 'off' (escape hatch) makes such "
                        "requests fail with a typed 400")
    p.add_argument("--grammar-slab-states", type=int, default=None,
                   help="structured output: device slab capacity in "
                        "automaton states shared by all live schemas "
                        "(fixed at startup so schema churn can never "
                        "recompile XLA programs; admissions beyond it "
                        "shed retryably). Default: grammar default "
                        "(1024)")
    # serving QoS (serving/ package): bounded admission + deadlines
    p.add_argument("--max-queue", type=int, default=256,
                   help="serving: max requests waiting for a lane before "
                        "submissions are shed with HTTP 429 + Retry-After "
                        "(bounded admission; 0 = unbounded)")
    p.add_argument("--queue-timeout", type=float, default=0.0,
                   help="serving: seconds a request may wait queued before "
                        "finishing with finish_reason=timeout instead of "
                        "holding the client open (0 disables)")
    p.add_argument("--request-budget", type=float, default=0.0,
                   help="serving: wall-clock seconds a request may spend "
                        "generating after admission; exceeding it finishes "
                        "with finish_reason=timeout and frees the lane "
                        "(0 disables)")
    p.add_argument("--multi-step", type=int, default=None,
                   help="serving: chain up to this many decode steps per "
                        "device dispatch in steady-state decode (identical "
                        "token streams, 1/h the per-token dispatch "
                        "overhead); 0 disables; default: scheduler "
                        "default (8)")
    p.add_argument("--pipeline-depth", type=int, default=None,
                   help="serving: async decode pipeline — bound on "
                        "dispatched-but-unconsumed decode steps. Step k+1 "
                        "dispatches from the on-device token carry while "
                        "step k's host readback (detokenize, stream, "
                        "stop/EOS checks) runs one step behind, overlapped "
                        "with device execution; token streams stay "
                        "byte-identical to synchronous stepping. 0 or 1 "
                        "disables; default: engine default (2)")
    p.add_argument("--fused-prefill", default="on", choices=["on", "off"],
                   help="serving: stall-free admissions — a queued request "
                        "claims a lane inside the live async decode chain "
                        "and its prompt chunks ride fused prefill+decode "
                        "dispatches (one compiled program advances every "
                        "decoding lane one token AND consumes one bounded "
                        "prompt chunk), so admissions never flush the "
                        "pipeline and pipeline_flushes stays ~0 under "
                        "churn. 'off' restores the pre-fused behavior: an "
                        "admission exits the chain to the synchronous "
                        "admit+prefill path (escape hatch)")
    p.add_argument("--ring-sync", default=None, choices=["on", "off"],
                   help="pure-TP mesh serving: overlap the wo/w2 TP "
                        "activation sync with the dequant matmul as a ring "
                        "reduce-scatter + all-gather (chunked hops XLA "
                        "hides under compute; Q80 wire when "
                        "--buffer-float-type q80 engages) instead of "
                        "XLA's sequential post-matmul all-reduce. Default "
                        "on (DLLAMA_RING_SYNC env equivalent); 'off' "
                        "restores the plain psum sync bit-for-bit "
                        "(escape hatch)")
    # mirrors ops/pallas_q40.SELECTABLE_MODES; argparse must stay importable
    # without jax, so the list is spelled out and the pairing is pinned by
    # tests/test_dequant_select.py
    p.add_argument("--dequant", default=None,
                   choices=["auto", "v4", "bf16chain", "repeat", "u8chain",
                            "blockdot", "i8blockdot"],
                   help="Q40 dequant arithmetic variant for the Pallas "
                        "kernel's bf16 dot path (DLLAMA_DEQUANT env "
                        "equivalent; default v4). 'auto' resolves the mode "
                        "per (d_in, d_out, m-class) matmul site from the "
                        "persisted selection table "
                        "(ops/dequant_table.json, refreshed by the bench "
                        "sweeps) BEFORE warmup, so every program still "
                        "compiles exactly once; interpret/CPU always runs "
                        "the exact-f32 v4 chain")
    p.add_argument("--step-deadline", type=float, default=None,
                   help="serving: failure-containment watchdog — if a "
                        "dispatched engine step makes no progress for "
                        "this many seconds, trip the circuit breaker and "
                        "abort the async chain (single host) or crash "
                        "the process deliberately (pods, where "
                        "jax.distributed peer-failure detection turns "
                        "death into a pod-wide signal while a silent "
                        "hang wedges everything). Default: "
                        "DLLAMA_STEP_DEADLINE env, else off (0)")
    # crash-durable serving (serving/journal.py, serving/recovery.py,
    # serving/resume.py; docs/SERVING.md "Crash recovery")
    p.add_argument("--journal-path", default=None,
                   help="serving: append-only CRC-framed request journal "
                        "(crash durability) — admitted requests with "
                        "their resolved sampler seeds plus periodic "
                        "delivery watermarks, written by a background "
                        "thread off the hot path. Off by default; pair "
                        "with --recover-journal to resume after a crash")
    p.add_argument("--recover-journal", action="store_true",
                   help="serving: on startup, replay the --journal-path "
                        "journal — every admitted-but-unfinished request "
                        "is re-admitted and regenerated from its prompt "
                        "with the same seed (byte-identical streams), "
                        "fast-forwarded through its delivered-token "
                        "watermark; re-admission is paced through the "
                        "circuit breaker so recovery cannot stampede a "
                        "freshly restarted engine")
    # fleet serving (fleet/; docs/SERVING.md "Fleet serving")
    p.add_argument("--replica-id", default=None,
                   help="serving: this replica's name in a fleet — "
                        "stamped as the X-DLlama-Replica header on every "
                        "response and onto SSE terminal chunks so the "
                        "dllama-router's traces and the migration path "
                        "can attribute sheds and streams to their source "
                        "replica. Default: host:port (the machine "
                        "hostname when binding all interfaces — a fleet "
                        "of 0.0.0.0:8080s would all share one id)")
    p.add_argument("--role", default="mixed",
                   choices=["mixed", "prefill", "decode"],
                   help="serving: this replica's fleet role, advertised "
                        "on GET /load. 'prefill': the dllama-router "
                        "steers long-classified prompts here and hands "
                        "their sessions (KV pages + migration ticket) to "
                        "a decode replica at first token "
                        "(disagg/; docs/DISAGG.md). 'decode': preferred "
                        "hand-off target. 'mixed' (default): the "
                        "monolithic single-tier behavior")
    p.add_argument("--reconnect-grace", type=float, default=0.0,
                   help="serving: seconds a disconnected SSE client may "
                        "reattach (GET /v1/stream/<id> with "
                        "Last-Event-ID) before the request is cancelled; "
                        "while the window is open the request keeps "
                        "generating into a bounded delta buffer. 0 "
                        "(default) preserves cancel-on-disconnect")
    # observability (telemetry/, docs/OBSERVABILITY.md)
    p.add_argument("--trace-path", default=None,
                   help="serving: write the request-lifecycle span ring as "
                        "Chrome trace-event JSON (Perfetto / "
                        "chrome://tracing loadable) to this path when the "
                        "server drains; the live ring is always fetchable "
                        "at GET /trace and metrics at GET /metrics")
    # train mode (beyond parity — no reference analogue)
    p.add_argument("--data", default=None,
                   help="train: UTF-8 text file tokenized into training batches")
    p.add_argument("--train-steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--warmup-steps", type=int, default=0,
                   help="train: linear LR warmup steps, then cosine decay "
                        "to 10%% of --lr over --train-steps (0 = flat --lr)")
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--train-seq-len", type=int, default=0,
                   help="tokens per training sequence (0 = model seq_len)")
    p.add_argument("--ckpt-dir", default=None,
                   help="train: save/resume orbax checkpoints here "
                        "(resumes from the latest step_<N> if present)")
    p.add_argument("--save-every", type=int, default=50,
                   help="train: checkpoint every N steps (and at the end)")
    return p


def build_router_parser(prog: str = "dllama-router") -> argparse.ArgumentParser:
    """CLI surface for the fleet front-end (fleet/router.py) — model-free
    by design: the router holds no weights and no tokenizer, only the
    replica table and the client sockets."""
    p = argparse.ArgumentParser(prog=prog)
    p.add_argument("--replicas", nargs="+", required=True,
                   help="engine replica addresses (host:port ...), each a "
                        "dllama-api process; replica ids default to the "
                        "addresses (match each replica's --replica-id)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9980)
    p.add_argument("--affinity-block-chars", type=int, default=None,
                   help="prefix-affinity block size in prompt characters "
                        "(~4 chars/token x the KV pool's 64-token page); "
                        "the affinity key chains content hashes over the "
                        "prompt's leading blocks, the router twin of the "
                        "KV prefix tree's node-key chain. Default: "
                        "fleet default (256)")
    p.add_argument("--affinity-blocks", type=int, default=None,
                   help="how many leading blocks the affinity key covers "
                        "(a long shared system prompt maps to ONE key "
                        "regardless of what follows); 0 disables prefix "
                        "affinity — every request balances by load. "
                        "Default: fleet default (4)")
    p.add_argument("--scrape-interval", type=float, default=0.5,
                   help="seconds between /load scrapes of each replica "
                        "(queue depth, free lanes, pool pressure, "
                        "breaker, draining — the routing signals)")
    p.add_argument("--migration", default="on", choices=["on", "off"],
                   help="live session migration: cache each stream's "
                        "exported journal admit record (its migration "
                        "ticket) and, when the serving replica dies or "
                        "drains mid-stream, regenerate the session "
                        "byte-identically on another replica and splice "
                        "the resumed stream onto the same client socket "
                        "— zero lost, zero duplicated tokens. Replicas "
                        "need --reconnect-grace > 0 for the reattach "
                        "half. 'off': mid-stream failures surface to "
                        "the client as typed errors instead")
    p.add_argument("--disagg-threshold", type=int, default=None,
                   help="disaggregated prefill: prompts at/above this "
                        "many characters classify 'long' and route to a "
                        "replica advertising role=prefill on /load; at "
                        "first token the session (KV-page bundle + "
                        "migration ticket) hands off to a decode "
                        "replica, char-exact on the same client socket. "
                        "0 disables the policy. Default: disagg default "
                        "(8000). Needs --migration on and at least one "
                        "--role prefill replica to take effect; without "
                        "them every request rides the monolithic path")
    return p


def parse_mesh_spec(workers: list[str] | None):
    """--workers '8' -> tp=8 (reference pure-TP); 'dp2,tp2,sp2,ep2' -> explicit."""
    from ..parallel import MeshPlan

    if not workers:
        return None
    spec = workers[0]
    if spec.isdigit():
        return MeshPlan(tp=int(spec))
    plan = {"dp": 1, "tp": 1, "sp": 1, "ep": 1, "pp": 1}
    for part in spec.split(","):
        for axis in plan:
            if part.startswith(axis):
                plan[axis] = int(part[len(axis):])
                break
        else:
            raise ValueError(f"bad mesh spec part {part!r}")
    return MeshPlan(**plan)
