"""Counters, gauges, fixed-bucket log-scale histograms + Prometheus text.

The serving path's latency distributions (TTFT, inter-token gap, queue
wait, step duration) are heavy-tailed over four-plus decades — from
sub-millisecond mock steps to multi-second cold prefills — so the
histograms use FIXED geometric bucket edges (``log_buckets``): every
process, every restart, every bench child bins identically, which is what
lets bench percentiles and a scraped ``/metrics`` series be compared
without re-bucketing. Rendering follows the Prometheus text exposition
format (``*_bucket{le=...}`` cumulative counts + ``_sum``/``_count``;
counters end in ``_total``), so any Prometheus-compatible scraper ingests
``GET /metrics`` directly.

Pure stdlib, no numpy/jax: importable wherever dlint runs, and nothing in
here can ever touch a device value (the package is registered under the
``host-sync`` check all the same — see analysis/host_sync_check.py).

Thread-safety: every metric guards its state with its own ``_m_lock``
(``_dlint_guarded_by``-declared, machine-checked); the registry guards
its name map with ``_reg_lock``. Writers are the scheduler loop and HTTP
threads; scrapes take one lock per metric, never all at once.
"""

from __future__ import annotations

import math
from bisect import bisect_left
from typing import Iterable

from ..lockcheck import make_lock


def log_buckets(lo: float, hi: float, per_decade: int = 4) -> tuple[float, ...]:
    """Geometric bucket edges from ``lo`` to at least ``hi`` with
    ``per_decade`` buckets per factor of 10 — the fixed log-scale grid
    every latency histogram bins on."""
    if lo <= 0 or hi <= lo:
        raise ValueError("need 0 < lo < hi")
    if per_decade < 1:
        raise ValueError("per_decade must be >= 1")
    n = math.ceil(math.log10(hi / lo) * per_decade)
    # round to 6 significant digits so edges are stable, printable values
    return tuple(
        float(f"{lo * 10 ** (i / per_decade):.6g}") for i in range(n + 1)
    )


# THE latency grid (seconds): 100 µs .. 100 s, 4 buckets per decade.
# Shared by TTFT / inter-token / queue-wait / step-duration so their
# exposition lines line up column-for-column.
LATENCY_BUCKETS_S = log_buckets(1e-4, 100.0, per_decade=4)


def _fmt(v: float) -> str:
    """Prometheus sample value / le formatting: trim trailing float noise."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.10g}"


def _label_str(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class Counter:
    """Monotonic counter, optionally labelled (one value per label set)."""

    _dlint_guarded_by = {("_m_lock",): ("_ctr_values",)}

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._m_lock = make_lock("Counter._m_lock")
        self._ctr_values: dict[tuple[tuple[str, str], ...], float] = {}

    def inc(self, value: float = 1.0, **labels: str) -> None:
        if value < 0:
            raise ValueError("counters only go up")
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._m_lock:
            self._ctr_values[key] = self._ctr_values.get(key, 0.0) + value

    def value(self, **labels: str) -> float:
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._m_lock:
            return self._ctr_values.get(key, 0.0)

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._m_lock:
            items = sorted(self._ctr_values.items())
        if not items:
            items = [((), 0.0)]
        for labels, v in items:
            out.append(f"{self.name}{_label_str(labels)} {_fmt(v)}")
        return out


class Gauge:
    """Last-write-wins value, optionally labelled."""

    _dlint_guarded_by = {("_m_lock",): ("_gauge_values",)}

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._m_lock = make_lock("Gauge._m_lock")
        self._gauge_values: dict[tuple[tuple[str, str], ...], float] = {}

    def set(self, value: float, **labels: str) -> None:
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._m_lock:
            self._gauge_values[key] = float(value)

    def value(self, **labels: str) -> float | None:
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._m_lock:
            return self._gauge_values.get(key)

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._m_lock:
            items = sorted(self._gauge_values.items())
        if not items:
            items = [((), 0.0)]
        for labels, v in items:
            out.append(f"{self.name}{_label_str(labels)} {_fmt(v)}")
        return out


class Histogram:
    """Fixed-bucket histogram over pre-computed (log-scale) edges.

    ``observe(v)`` bins by ``v <= edge`` (Prometheus ``le`` semantics;
    values past the last edge land in the implicit +Inf bucket).
    ``quantile(q)`` interpolates linearly inside the winning bucket —
    a bucketed estimate, which is the point: the server's ``/metrics``
    and the bench's reported percentiles come from the SAME counts, so
    they cannot drift."""

    _dlint_guarded_by = {("_m_lock",): ("_hist_counts", "_hist_sum", "_hist_n")}

    def __init__(self, name: str, help_: str = "",
                 buckets: Iterable[float] = LATENCY_BUCKETS_S):
        self.name = name
        self.help = help_
        self.edges = tuple(float(b) for b in buckets)
        if not self.edges or any(
            b >= a for a, b in zip(self.edges[1:], self.edges)
        ):
            raise ValueError("bucket edges must be strictly increasing")
        self._m_lock = make_lock("Histogram._m_lock")
        self._hist_counts = [0] * (len(self.edges) + 1)  # last = +Inf
        self._hist_sum = 0.0
        self._hist_n = 0

    def observe(self, value: float) -> None:
        idx = bisect_left(self.edges, value)  # first edge >= value
        with self._m_lock:
            self._hist_counts[idx] += 1
            self._hist_sum += value
            self._hist_n += 1

    @property
    def count(self) -> int:
        with self._m_lock:
            return self._hist_n

    @property
    def sum(self) -> float:
        with self._m_lock:
            return self._hist_sum

    def snapshot(self) -> tuple[list[int], float, int]:
        with self._m_lock:
            return list(self._hist_counts), self._hist_sum, self._hist_n

    def quantile(self, q: float) -> float | None:
        """Bucket-interpolated q-quantile (0 < q <= 1); None when empty.
        The +Inf bucket reports the last finite edge (a floor, stated as
        such in docs/OBSERVABILITY.md)."""
        if not 0.0 < q <= 1.0:
            raise ValueError("q must be in (0, 1]")
        counts, _, n = self.snapshot()
        if n == 0:
            return None
        target = q * n
        cum = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            prev = cum
            cum += c
            if cum >= target:
                if i >= len(self.edges):  # +Inf bucket: no upper edge
                    return self.edges[-1]
                lo = self.edges[i - 1] if i > 0 else 0.0
                hi = self.edges[i]
                return lo + (hi - lo) * (target - prev) / c
        return self.edges[-1]

    def render(self) -> list[str]:
        counts, total_sum, n = self.snapshot()
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        cum = 0
        for edge, c in zip(self.edges, counts):
            cum += c
            out.append(f'{self.name}_bucket{{le="{_fmt(edge)}"}} {cum}')
        out.append(f'{self.name}_bucket{{le="+Inf"}} {n}')
        out.append(f"{self.name}_sum {_fmt(total_sum)}")
        out.append(f"{self.name}_count {n}")
        return out


class LabelledHistogram:
    """A histogram FAMILY over one shared edge grid: ``observe(v,
    **labels)`` bins into the per-label-set series, and ``render()``
    emits ONE metric whose ``_bucket``/``_sum``/``_count`` lines carry
    the labels alongside ``le`` — the shape a per-phase attribution
    series (``dllama_request_phase_seconds{phase="prefill_ms"}``)
    needs. Same fixed log-scale edges discipline as :class:`Histogram`:
    every label set bins identically, so series are comparable without
    re-bucketing."""

    _dlint_guarded_by = {("_m_lock",): ("_hist_series",)}

    def __init__(self, name: str, help_: str = "",
                 buckets: Iterable[float] = LATENCY_BUCKETS_S):
        self.name = name
        self.help = help_
        self.edges = tuple(float(b) for b in buckets)
        if not self.edges or any(
            b >= a for a, b in zip(self.edges[1:], self.edges)
        ):
            raise ValueError("bucket edges must be strictly increasing")
        self._m_lock = make_lock("LabelledHistogram._m_lock")
        # label-set key -> [bucket counts (last = +Inf), sum, n]
        self._hist_series: dict[tuple[tuple[str, str], ...], list] = {}

    @staticmethod
    def _key(labels: dict) -> tuple[tuple[str, str], ...]:
        return tuple(sorted((k, str(v)) for k, v in labels.items()))

    def observe(self, value: float, **labels: str) -> None:
        idx = bisect_left(self.edges, value)  # first edge >= value
        key = self._key(labels)
        with self._m_lock:
            s = self._hist_series.get(key)
            if s is None:
                s = self._hist_series[key] = [
                    [0] * (len(self.edges) + 1), 0.0, 0,
                ]
            s[0][idx] += 1
            s[1] += value
            s[2] += 1

    def snapshot(self, **labels: str) -> tuple[list[int], float, int] | None:
        """One label set's ``(bucket counts, sum, n)``; None if unseen."""
        with self._m_lock:
            s = self._hist_series.get(self._key(labels))
            return None if s is None else (list(s[0]), s[1], s[2])

    def quantile(self, q: float, **labels: str) -> float | None:
        """Bucket-interpolated q-quantile of one label set's series
        (same estimate contract as :meth:`Histogram.quantile`)."""
        if not 0.0 < q <= 1.0:
            raise ValueError("q must be in (0, 1]")
        snap = self.snapshot(**labels)
        if snap is None or snap[2] == 0:
            return None
        counts, _, n = snap
        target = q * n
        cum = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            prev = cum
            cum += c
            if cum >= target:
                if i >= len(self.edges):  # +Inf bucket: no upper edge
                    return self.edges[-1]
                lo = self.edges[i - 1] if i > 0 else 0.0
                hi = self.edges[i]
                return lo + (hi - lo) * (target - prev) / c
        return self.edges[-1]

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._m_lock:
            items = sorted(
                (k, (list(s[0]), s[1], s[2]))
                for k, s in self._hist_series.items()
            )
        for labels, (counts, total_sum, n) in items:
            cum = 0
            for edge, c in zip(self.edges, counts):
                cum += c
                le = (("le", _fmt(edge)),)
                out.append(
                    f"{self.name}_bucket{_label_str(labels + le)} {cum}"
                )
            out.append(
                f'{self.name}_bucket{_label_str(labels + (("le", "+Inf"),))}'
                f" {n}"
            )
            out.append(f"{self.name}_sum{_label_str(labels)} {_fmt(total_sum)}")
            out.append(f"{self.name}_count{_label_str(labels)} {n}")
        return out


class MetricsRegistry:
    """Name -> metric map with idempotent constructors and one-call text
    exposition. Re-registering a name returns the existing instance (the
    bench and the server share instruments by construction)."""

    _dlint_guarded_by = {("_reg_lock",): ("_reg_metrics",)}

    def __init__(self):
        self._reg_lock = make_lock("MetricsRegistry._reg_lock")
        self._reg_metrics: dict[str, object] = {}

    def _get_or_make(self, name: str, factory, kind):
        with self._reg_lock:
            m = self._reg_metrics.get(name)
            if m is None:
                m = self._reg_metrics[name] = factory()
            elif not isinstance(m, kind):
                raise ValueError(
                    f"metric {name!r} already registered as {type(m).__name__}"
                )
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_make(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_make(name, lambda: Gauge(name, help_), Gauge)

    def histogram(self, name: str, help_: str = "",
                  buckets: Iterable[float] = LATENCY_BUCKETS_S) -> Histogram:
        return self._get_or_make(
            name, lambda: Histogram(name, help_, buckets), Histogram
        )

    def labelled_histogram(
        self, name: str, help_: str = "",
        buckets: Iterable[float] = LATENCY_BUCKETS_S,
    ) -> LabelledHistogram:
        return self._get_or_make(
            name, lambda: LabelledHistogram(name, help_, buckets),
            LabelledHistogram,
        )

    def get(self, name: str):
        with self._reg_lock:
            return self._reg_metrics.get(name)

    def render(self) -> str:
        """Full Prometheus text exposition (trailing newline included,
        per the format spec)."""
        with self._reg_lock:
            metrics = [self._reg_metrics[k] for k in sorted(self._reg_metrics)]
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"
