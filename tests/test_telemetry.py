"""Telemetry subsystem (distributed_llama_multiusers_tpu/telemetry): the
instruments themselves AND their wiring through the serving path.

Three layers, per the PR-5 contract:

- **unit** — histogram bucket edges / le semantics / quantiles, ring
  eviction under overflow, Chrome trace JSON validity (pid/tid/ts/ph),
  Prometheus text that actually parses;
- **scheduler** — lifecycle spans and per-request summaries over the
  mocked async engine (utils.testing.MockAsyncEngine — the same stub the
  pipelined-decode tests pin), including the cancel/timeout/flush span
  endings and the queue-wait histogram reconciling with ``queue_popped``;
- **HTTP** — ``GET /metrics`` parses and reconciles field-for-field with
  ``GET /stats``, ``GET /trace`` is loadable, per-request summaries are
  identical between the stream and non-stream paths, and error payloads
  carry the request id.
"""

from __future__ import annotations

import io
import json
import re
import threading
import time
import urllib.error
import urllib.request

import pytest

from distributed_llama_multiusers_tpu.telemetry import (
    JsonLogger,
    MetricsRegistry,
    SpanTracer,
    Telemetry,
    chrome_trace,
    log_buckets,
)
from distributed_llama_multiusers_tpu.telemetry.metrics import (
    LATENCY_BUCKETS_S,
    Histogram,
)

# -- Prometheus text parser (the format contract, enforced line by line) -----

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'        # metric name
    r'(\{[^{}]*\})?'                        # optional labels
    r' (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf)|NaN)$'
)


def parse_prometheus(text: str) -> dict[tuple[str, str], float]:
    """Parse Prometheus text exposition; asserts every non-comment line
    matches the sample grammar. Returns {(name, labels): value}."""
    samples: dict[tuple[str, str], float] = {}
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) ", line), line
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        samples[(m.group(1), m.group(2) or "")] = float(m.group(3))
    return samples


# -- unit: histograms ---------------------------------------------------------


def test_log_buckets_are_geometric_and_cover_range():
    edges = log_buckets(1e-3, 1.0, per_decade=3)
    assert edges[0] == pytest.approx(1e-3)
    assert edges[-1] >= 1.0
    assert all(b > a for a, b in zip(edges, edges[1:]))
    ratios = [b / a for a, b in zip(edges, edges[1:])]
    for r in ratios:  # fixed log scale: constant ratio 10^(1/3)
        assert r == pytest.approx(10 ** (1 / 3), rel=1e-3)
    # the shared latency grid spans 100 µs .. >= 100 s
    assert LATENCY_BUCKETS_S[0] == pytest.approx(1e-4)
    assert LATENCY_BUCKETS_S[-1] >= 100.0


def test_histogram_le_semantics_and_counts():
    h = Histogram("t_seconds", buckets=(0.1, 1.0, 10.0))
    h.observe(0.1)   # exactly an edge: belongs to that bucket (le)
    h.observe(0.05)
    h.observe(5.0)
    h.observe(100.0)  # past the last edge: +Inf bucket
    counts, total, n = h.snapshot()
    assert counts == [2, 0, 1, 1]
    assert n == 4
    assert total == pytest.approx(105.15)


def test_histogram_quantile_interpolates():
    h = Histogram("t_seconds", buckets=(1.0, 2.0, 4.0))
    for _ in range(100):
        h.observe(1.5)  # all in the (1, 2] bucket
    q50 = h.quantile(0.5)
    assert 1.0 < q50 <= 2.0
    assert h.quantile(1.0) <= 2.0
    assert Histogram("e_seconds", buckets=(1.0,)).quantile(0.5) is None
    with pytest.raises(ValueError):
        h.quantile(0.0)


def test_histogram_rejects_bad_edges():
    with pytest.raises(ValueError):
        Histogram("bad", buckets=(1.0, 1.0))
    with pytest.raises(ValueError):
        Histogram("bad", buckets=())


def test_registry_render_parses_and_histogram_invariants():
    reg = MetricsRegistry()
    c = reg.counter("x_total", "a counter")
    c.inc()
    c.inc(2, reason="stop")
    reg.gauge("g", "a gauge").set(3.5, depth="2")
    h = reg.histogram("lat_seconds", "a histogram", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(50.0)
    samples = parse_prometheus(reg.render())
    assert samples[("x_total", "")] == 1
    assert samples[("x_total", '{reason="stop"}')] == 2
    assert samples[("g", '{depth="2"}')] == 3.5
    # cumulative buckets are non-decreasing and +Inf == count
    cum = [samples[("lat_seconds_bucket", '{le="0.1"}')],
           samples[("lat_seconds_bucket", '{le="1"}')],
           samples[("lat_seconds_bucket", '{le="+Inf"}')]]
    assert cum == sorted(cum) and cum[-1] == samples[("lat_seconds_count", "")]
    assert samples[("lat_seconds_sum", "")] == pytest.approx(50.55)
    # idempotent re-registration returns the same instrument
    assert reg.histogram("lat_seconds") is h
    with pytest.raises(ValueError):
        reg.counter("lat_seconds")  # name claimed by another kind


# -- unit: ring + chrome trace ------------------------------------------------


def test_ring_eviction_under_overflow():
    tr = SpanTracer(capacity=8)
    for i in range(20):
        tr.instant(f"ev{i}", "queue")
    events = tr.snapshot()
    assert len(events) == 8
    assert [e.name for e in events] == [f"ev{i}" for i in range(12, 20)]
    counts = tr.counts()
    assert counts["trace_events_recorded"] == 20
    assert counts["trace_events_dropped"] == 12
    assert counts["trace_events_buffered"] == 8


def test_chrome_trace_json_validity():
    tr = SpanTracer(capacity=64)
    t0 = tr.now()
    tr.slice("generate", "lane0", t0, t0 + 0.01, req_id=7)
    tr.slice("step.pipelined", "pipeline", t0, t0 + 0.002)
    tr.instant("finish.stop", "lane0", req_id=7)
    doc = chrome_trace(tr.snapshot(), origin=tr.origin)
    doc = json.loads(json.dumps(doc))  # round-trips
    events = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    tids_named = set()
    for e in events:
        assert {"name", "ph", "pid", "tid", "ts"} <= set(e), e
        if e["ph"] == "X":
            assert e["dur"] >= 0 and e["ts"] >= 0
        elif e["ph"] == "i":
            assert e["s"] == "t"
        elif e["ph"] == "M" and e["name"] == "thread_name":
            tids_named.add(e["tid"])
    # every tid used by a real event has a thread_name metadata row
    used = {e["tid"] for e in events if e["ph"] in ("X", "i")}
    assert used <= tids_named
    gen = [e for e in events if e["name"] == "generate"][0]
    assert gen["dur"] == pytest.approx(10_000, rel=0.01)  # µs
    assert gen["args"]["request_id"] == 7
    # lanes sort ahead of the pipeline track
    name_of = {e["tid"]: e["args"]["name"] for e in events
               if e["ph"] == "M" and e["name"] == "thread_name"}
    lane_tid = [t for t, n in name_of.items() if n == "lane0"][0]
    pipe_tid = [t for t, n in name_of.items() if n == "pipeline"][0]
    assert lane_tid < pipe_tid


# -- scheduler wiring (mocked async engine) -----------------------------------


def _mock_stack(log_sink=None, **sched_kw):
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
    )
    from distributed_llama_multiusers_tpu.utils.testing import (
        MockAsyncEngine,
        StubStreamTokenizer,
    )

    tel = Telemetry(logger=JsonLogger(log_sink) if log_sink is not None else None)
    engine = MockAsyncEngine()
    kw = dict(speculative=False, prefix_min_tokens=0, multi_step=0)
    kw.update(sched_kw)
    sched = ContinuousBatchingScheduler(
        engine, StubStreamTokenizer(engine.config.vocab_size),
        telemetry=tel, **kw,
    )
    return engine, sched, tel


def _run_requests(sched, reqs, timeout=60):
    sched.start()
    try:
        for r in reqs:
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=timeout)
    finally:
        sched.stop()


def _wait(pred, timeout=10):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, "condition never became true"
        time.sleep(0.002)


def test_request_summary_and_log_line():
    from distributed_llama_multiusers_tpu.runtime.scheduler import Request

    sink = io.StringIO()
    engine, sched, tel = _mock_stack(log_sink=sink)
    reqs = [Request(prompt="hello world", max_tokens=8) for _ in range(3)]
    _run_requests(sched, reqs)
    for r in reqs:
        s = r.summary
        assert s is not None and s["request_id"] == r.id
        assert s["finish_reason"] == "length"
        assert s["n_generated_tokens"] == 8
        assert s["ttft_s"] is not None and s["ttft_s"] >= 0
        assert s["tbt_p50_s"] is not None and s["queued_s"] is not None
    # exactly one structured JSON log line per request, same dict
    lines = [json.loads(l) for l in sink.getvalue().splitlines()]
    req_lines = [l for l in lines if l["event"] == "request"]
    assert sorted(l["request_id"] for l in req_lines) == sorted(r.id for r in reqs)
    by_id = {l["request_id"]: l for l in req_lines}
    for r in reqs:
        for k, v in r.summary.items():
            assert by_id[r.id][k] == v
    # startup log line names the serving config
    boot = [l for l in lines if l["event"] == "scheduler_start"]
    assert boot and {"n_lanes", "pipeline_depth", "fused_prefill"} <= set(boot[0])
    # metrics observed once per request / once per token
    assert tel.ttft.count == 3
    assert tel.tokens_generated.value() == 24
    assert tel.requests_finished.value(finish_reason="length") == 3


def test_failed_request_log_line_carries_error():
    """A request that fails before generating gets a summary/log line with
    finish_reason=error AND the error string — the log record must name
    the reason the 500 carries, or the request_id correlation is
    pointless."""
    from distributed_llama_multiusers_tpu.runtime.scheduler import Request

    sink = io.StringIO()
    engine, sched, tel = _mock_stack(log_sink=sink)

    class BoomTokenizer(type(sched.tokenizer)):
        def encode(self, text, add_bos=True, add_special_tokens=True):
            raise RuntimeError("tokenizer exploded")

    sched.tokenizer = BoomTokenizer(engine.config.vocab_size)
    req = Request(prompt="anything", max_tokens=4)
    sched.start()
    try:
        sched.submit(req)
        with pytest.raises(RuntimeError, match="tokenizer exploded"):
            req.future.result(timeout=30)
    finally:
        sched.stop()
    assert req.summary["finish_reason"] == "error"
    assert req.summary["error"] == "tokenizer exploded"
    line = [
        json.loads(l) for l in sink.getvalue().splitlines()
        if '"event": "request"' in l
    ][0]
    assert line["request_id"] == req.id
    assert line["error"] == "tokenizer exploded"
    assert tel.requests_finished.value(finish_reason="error") == 1


def test_lifecycle_spans_complete_for_normal_finish():
    from distributed_llama_multiusers_tpu.runtime.scheduler import Request

    engine, sched, tel = _mock_stack()
    req = Request(prompt="hello world", max_tokens=6)
    _run_requests(sched, [req])
    mine = [e for e in tel.tracer.snapshot() if e.req_id == req.id]
    names = [e.name for e in mine]
    for expected in ("submitted", "queued", "generate", "finish.length"):
        assert expected in names, names
    gen = [e for e in mine if e.name == "generate"][0]
    assert gen.track.startswith("lane") and gen.ph == "X"
    assert gen.args["finish_reason"] == "length"
    queued = [e for e in mine if e.name == "queued"][0]
    assert queued.track == "queue" and queued.ph == "X"


def test_span_endings_cancel_and_timeout():
    from distributed_llama_multiusers_tpu.runtime.scheduler import Request

    engine, sched, tel = _mock_stack()
    cancelled = Request(prompt="hello world", max_tokens=100_000)
    timed_out = Request(prompt="hello world", max_tokens=100_000, budget_s=0.05)
    sched.start()
    try:
        sched.submit(cancelled)
        sched.submit(timed_out)
        _wait(lambda: len(cancelled.generated_tokens) > 2)
        cancelled.cancel()
        cancelled.future.result(timeout=30)
        timed_out.future.result(timeout=30)
    finally:
        sched.stop()
    assert cancelled.finish_reason == "cancelled"
    assert timed_out.finish_reason == "timeout"
    assert cancelled.summary["finish_reason"] == "cancelled"
    assert timed_out.summary["finish_reason"] == "timeout"
    names = {(e.req_id, e.name) for e in tel.tracer.snapshot()}
    assert (cancelled.id, "finish.cancelled") in names
    assert (timed_out.id, "finish.timeout") in names
    # both still have complete generate slices (admit -> ending)
    assert (cancelled.id, "generate") in names
    assert (timed_out.id, "generate") in names
    assert tel.requests_finished.value(finish_reason="cancelled") == 1
    assert tel.requests_finished.value(finish_reason="timeout") == 1


def test_span_ending_for_queued_timeout_without_lane():
    """A request that expires while QUEUED (all lanes busy) ends with a
    queued slice + finish instant on the queue track and a summary whose
    ttft is None — it never generated."""
    from distributed_llama_multiusers_tpu.runtime.scheduler import Request
    from distributed_llama_multiusers_tpu.serving import DeadlinePolicy

    engine, sched, tel = _mock_stack(
        deadlines=DeadlinePolicy(queue_timeout_s=0.05)
    )
    blockers = [
        Request(prompt="hello world", max_tokens=100_000)
        for _ in range(engine.n_lanes)
    ]
    starved = Request(prompt="hello world", max_tokens=4)
    sched.start()
    try:
        for r in blockers:
            sched.submit(r)
        _wait(lambda: all(len(r.generated_tokens) > 0 for r in blockers))
        sched.submit(starved)
        starved.future.result(timeout=30)
        assert starved.finish_reason == "timeout"
    finally:
        for r in blockers:
            r.cancel()
        sched.stop()
    s = starved.summary
    assert s["finish_reason"] == "timeout"
    assert s["ttft_s"] is None and s["queued_s"] is None
    assert s["n_generated_tokens"] == 0
    mine = [e for e in tel.tracer.snapshot() if e.req_id == starved.id]
    assert {"queued", "finish.timeout"} <= {e.name for e in mine}
    assert all(e.track == "queue" for e in mine)


def test_pipeline_flush_instant_recorded():
    """With the fused-prefill escape hatch OFF, an admission into a live
    chain forces a flush — the trace must carry the pipeline.flush
    instant (span completeness for the flush ending)."""
    from distributed_llama_multiusers_tpu.runtime.scheduler import Request

    engine, sched, tel = _mock_stack(fused_prefill=False)
    a = Request(prompt="hello world", max_tokens=200)
    b = Request(prompt="hello world", max_tokens=4)
    sched.start()
    try:
        sched.submit(a)
        _wait(lambda: len(a.generated_tokens) > 3)  # chain is live
        sched.submit(b)  # fused off: this admission flushes the chain
        b.future.result(timeout=30)
        a.cancel()
        a.future.result(timeout=30)
    finally:
        sched.stop()
    flushes = [e for e in tel.tracer.snapshot() if e.name == "pipeline.flush"]
    assert flushes and flushes[0].ph == "i"
    assert engine.stats.snapshot()["pipeline_flushes"] >= 1


def test_queue_wait_histogram_reconciles_with_queue_popped():
    from distributed_llama_multiusers_tpu.runtime.scheduler import Request

    engine, sched, tel = _mock_stack()
    reqs = [Request(prompt="hello world", max_tokens=4) for _ in range(6)]
    _run_requests(sched, reqs)
    qstats = sched.queue.stats()
    assert tel.queue_wait.count == qstats["queue_popped"] == 6
    # and the histogram's total wait tracks the queue's own accounting
    assert tel.queue_wait.sum == pytest.approx(
        qstats["queue_wait_s_total"], abs=0.05
    )


def test_fused_admission_marked_in_summary():
    """A request admitted into a LIVE chain rides fused dispatches and its
    summary says so; the first request (admitted into an idle scheduler,
    sync prefill) does not."""
    from distributed_llama_multiusers_tpu.runtime.scheduler import Request

    engine, sched, tel = _mock_stack()
    a = Request(prompt="hello world", max_tokens=60)
    sched.start()
    try:
        sched.submit(a)
        _wait(lambda: len(a.generated_tokens) > 3)  # chain is live
        b = Request(prompt="hello world", max_tokens=4)
        sched.submit(b)
        b.future.result(timeout=30)
        a.future.result(timeout=30)
    finally:
        sched.stop()
    assert a.summary["fused_admitted"] is False
    assert b.summary["fused_admitted"] is True
    fused_slices = [
        e for e in tel.tracer.snapshot() if e.name == "step.fused"
    ]
    assert fused_slices, "no fused-step slices in the trace"


# -- HTTP surface -------------------------------------------------------------


@pytest.fixture()
def mock_server():
    from distributed_llama_multiusers_tpu.server import ApiServer
    from distributed_llama_multiusers_tpu.tokenizer import TemplateType

    engine, sched, tel = _mock_stack()
    sched.start()
    api = ApiServer(
        sched, sched.tokenizer, model_name="mock-tel",
        template_type=TemplateType.CHATML,
    )
    httpd = api.serve(host="127.0.0.1", port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield base, sched, tel
    httpd.shutdown()
    sched.stop()


def _post(base, path, body, timeout=60):
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get_raw(base, path, timeout=30):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.headers, r.read()


def _sse(base, path, body, timeout=60):
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    chunks = []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        for line in r:
            line = line.decode().strip()
            if line.startswith("data: "):
                chunks.append(line[6:])
    assert chunks[-1] == "[DONE]"
    return [json.loads(c) for c in chunks[:-1]]


def test_metrics_endpoint_parses_and_reconciles_with_stats(mock_server):
    base, sched, tel = mock_server
    _post(base, "/v1/completions",
          {"prompt": "hello world", "max_tokens": 5, "temperature": 0})
    # idle now: /stats and /metrics sample the same counters
    _, stats_raw = _get_raw(base, "/stats")
    stats = json.loads(stats_raw)
    headers, metrics_raw = _get_raw(base, "/metrics")
    assert headers["Content-Type"].startswith("text/plain; version=0.0.4")
    samples = parse_prometheus(metrics_raw.decode())
    # the bridge: every scalar /stats field is a dllama_stats_* gauge with
    # the SAME value (counters reconcile across the two endpoints)
    for key in ("decode_steps", "pipeline_dispatches", "fused_steps",
                "queue_popped", "prefill_tokens", "lanes_total"):
        assert samples[(f"dllama_stats_{key}", "")] == stats[key], key
    # dict-valued /stats histograms become labelled gauges
    for depth, n in stats["pipeline_depth_hist"].items():
        assert samples[("dllama_stats_pipeline_depth_hist",
                        f'{{key="{depth}"}}')] == n
    # native latency instruments are present and populated
    assert samples[("dllama_ttft_seconds_count", "")] >= 1
    assert samples[("dllama_requests_finished_total",
                    '{finish_reason="length"}')] >= 1
    # /stats surfaces the ring accounting
    assert stats["trace_events_recorded"] > 0


def test_trace_endpoint_is_loadable_chrome_json(mock_server):
    base, sched, tel = mock_server
    _post(base, "/v1/completions",
          {"prompt": "hello world", "max_tokens": 4, "temperature": 0})
    _, raw = _get_raw(base, "/trace")
    doc = json.loads(raw)
    events = doc["traceEvents"]
    assert events
    for e in events:
        assert {"name", "ph", "pid", "tid", "ts"} <= set(e)
    assert any(e["name"] == "generate" and e["ph"] == "X" for e in events)
    assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in events)


def test_summary_identical_between_stream_and_nonstream(mock_server):
    base, sched, tel = mock_server
    body = {"prompt": "hello world", "max_tokens": 6, "temperature": 0}
    _, full = _post(base, "/v1/completions", body)
    payloads = _sse(base, "/v1/completions", {**body, "stream": True})
    final = payloads[-1]
    assert final["choices"][0]["finish_reason"] == "length"
    # the summary rides ONLY the terminal chunk
    assert all("summary" not in p for p in payloads[:-1])
    s_stream, s_full = final["summary"], full["summary"]
    assert set(s_stream) == set(s_full)
    for key in ("finish_reason", "n_prompt_tokens", "n_generated_tokens",
                "prefix_tokens_saved", "fused_admitted"):
        assert s_stream[key] == s_full[key], key
    assert s_stream["request_id"] != s_full["request_id"]  # distinct requests
    assert s_stream["ttft_s"] is not None and s_full["ttft_s"] is not None


def test_error_payloads_carry_request_id(mock_server):
    base, sched, tel = mock_server
    from distributed_llama_multiusers_tpu.utils.testing import StubStreamTokenizer

    class BoomTokenizer(StubStreamTokenizer):
        def encode(self, text, add_bos=True, add_special_tokens=True):
            if "boom" in text:
                raise RuntimeError("tokenizer exploded")
            return super().encode(text, add_bos, add_special_tokens)

    sched.tokenizer = BoomTokenizer(sched.engine.config.vocab_size)
    try:
        # non-streaming: a 500 whose body names the request
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(base, "/v1/completions", {"prompt": "boom", "max_tokens": 3})
        assert e.value.code == 500
        payload = json.loads(e.value.read())
        assert payload["request_id"] > 0 and "error" in payload
        # streaming: headers already out, so the error is an SSE event —
        # still correlatable with server logs via the id
        payloads = _sse(base, "/v1/completions",
                        {"prompt": "boom", "max_tokens": 3, "stream": True})
        err = payloads[-1]
        assert err["error"] == "tokenizer exploded"
        assert err["request_id"] > 0
    finally:
        sched.tokenizer = StubStreamTokenizer(sched.engine.config.vocab_size)


def test_sync_bytes_bridge_is_delta_fed_across_resets():
    """The PR-7 sync bridge (telemetry/hub.bridge_stats): the native
    ``dllama_sync_bytes_total`` counter tracks the /stats
    ``sync_bytes_total`` field by DELTAS, so it keeps Prometheus counter
    semantics across engine.stats.reset() windows — the bridged gauge
    resets with /stats, the counter never goes backwards."""
    tel = Telemetry(logger=JsonLogger(stream=io.StringIO()))

    def counter_value():
        m = re.search(
            r"^dllama_sync_bytes_total (\S+)$",
            tel.registry.render(), re.M,
        )
        return float(m.group(1)) if m else 0.0

    tel.bridge_stats({"sync_bytes_total": 1000})
    assert counter_value() == 1000
    tel.bridge_stats({"sync_bytes_total": 1000})  # unchanged window
    assert counter_value() == 1000
    tel.bridge_stats({"sync_bytes_total": 1500})
    assert counter_value() == 1500
    # stats window reset: the gauge drops to 0, the counter must NOT
    tel.bridge_stats({"sync_bytes_total": 0})
    assert counter_value() == 1500
    # accrual resumes from the new baseline
    tel.bridge_stats({"sync_bytes_total": 300})
    assert counter_value() == 1800
    # and the verbatim gauge tracks the raw field (endpoint reconciliation)
    m = re.search(
        r"^dllama_stats_sync_bytes_total (\S+)$", tel.registry.render(), re.M
    )
    assert float(m.group(1)) == 300


def test_kv_swap_bridge_is_delta_fed_by_direction():
    """The tiered-residency bridge: ``dllama_kv_swap_total`` tracks the
    /stats ``swap_ins``/``swap_outs`` fields by DELTAS under a direction
    label, keeping Prometheus counter semantics across stats-window
    resets — while the verbatim ``dllama_stats_swap_*`` gauges keep the
    endpoint-reconciliation property (same number on /stats and
    /metrics when sampled idle)."""
    tel = Telemetry(logger=JsonLogger(stream=io.StringIO()))

    def counter(direction):
        m = re.search(
            r'^dllama_kv_swap_total\{direction="%s"\} (\S+)$' % direction,
            tel.registry.render(), re.M,
        )
        return float(m.group(1)) if m else 0.0

    tel.bridge_stats({"swap_ins": 5, "swap_outs": 2})
    assert counter("in") == 5 and counter("out") == 2
    tel.bridge_stats({"swap_ins": 5, "swap_outs": 4})  # only outs moved
    assert counter("in") == 5 and counter("out") == 4
    # stats window reset: the gauges drop to 0, the counters must NOT
    tel.bridge_stats({"swap_ins": 0, "swap_outs": 0})
    assert counter("in") == 5 and counter("out") == 4
    # accrual resumes from the new baseline
    tel.bridge_stats({"swap_ins": 3, "swap_outs": 1})
    assert counter("in") == 8 and counter("out") == 5
    # verbatim gauges track the raw fields, host-tier occupancy included
    render = tel.registry.render()
    assert re.search(r"^dllama_stats_swap_ins 3(\.0)?$", render, re.M)
    assert re.search(r"^dllama_stats_swap_outs 1(\.0)?$", render, re.M)
    tel.bridge_stats({"pool_host_pages": 7, "pool_host_bytes": 448,
                      "swap_in_ms": 1.25})
    render = tel.registry.render()
    assert re.search(r"^dllama_stats_pool_host_pages 7(\.0)?$", render, re.M)
    assert re.search(r"^dllama_stats_pool_host_bytes 448(\.0)?$", render, re.M)
    assert re.search(r"^dllama_stats_swap_in_ms 1\.25$", render, re.M)


def test_observe_sync_probe_feeds_histogram():
    """``observe_sync_probe`` turns a measured_step_breakdown dict into one
    dllama_sync_seconds observation per probed step; wall-only breakdowns
    (no collective data, e.g. off-mesh) observe nothing."""
    tel = Telemetry(logger=JsonLogger(stream=io.StringIO()))
    tel.observe_sync_probe({"step_ms": 5.0, "sync_ms": None}, steps=4)
    assert tel.sync_seconds.count == 0
    tel.observe_sync_probe({"step_ms": 5.0}, steps=4)  # key absent entirely
    assert tel.sync_seconds.count == 0
    tel.observe_sync_probe({"step_ms": 5.0, "sync_ms": 2.0}, steps=4)
    assert tel.sync_seconds.count == 4
    # the observed value is seconds (2 ms each)
    q = tel.sync_seconds.quantile(0.5)
    assert q is not None and 5e-4 < q < 5e-3
