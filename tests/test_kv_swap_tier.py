"""Tiered KV residency (runtime/kvpool.py HostTier + the engine's swap
programs): parked pages evicted under pool pressure swap their bytes to
a bounded host-RAM tier instead of dropping, and a later admission that
misses HBM but hits the tier reactivates by host->device copy instead of
re-prefill. The eviction ladder is resident-parked -> swap-to-host ->
drop-to-rebuild, and every rung must stay byte-identical: a swapped-in
prefix serves the same KV bytes a resident or rebuilt one would.

The integrity frame is disagg/kvtransfer.py's per-page sha256 (same
canonical framing, so the two serializers cannot drift); a failed
re-hash is REQUEST-scoped — typed :class:`HostTierCorrupt`, raised
before any pool mutation, entry dropped, tree never poisoned.

Pool/tier bookkeeping is pure host/stdlib, so most tests run without a
backend via MockAsyncEngine's paged mode (the REAL KVPagePool + a
content-canonical device half, shared with tests/test_disagg.py); the
real-engine three-tier byte-identity pin lives in
tests/test_prefix_cache.py's module for fixture reuse.
"""

import numpy as np
import pytest

from distributed_llama_multiusers_tpu.disagg.kvtransfer import page_hash
from distributed_llama_multiusers_tpu.runtime.kvpool import (
    HostTier,
    HostTierCorrupt,
    KVPagePool,
    PoolExhausted,
)
from distributed_llama_multiusers_tpu.utils.testing import MockAsyncEngine


def _paged_engine(pool_pages=32, max_parked=8, page_size=4, seq_len=64,
                  n_lanes=2, host_bytes=1 << 20):
    """A paged mock with the host swap tier armed: the REAL KVPagePool
    bookkeeping, device half mocked content-canonically (swap-outs and
    swap-ins are genuine byte round trips)."""
    return MockAsyncEngine(
        n_lanes=n_lanes, content_keyed=True, paged=True,
        kv_page_size=page_size, kv_pool_pages=pool_pages,
        kv_max_parked=max_parked, seq_len=seq_len,
        kv_host_bytes=host_bytes,
    )


def _park_chain(engine, lane, tokens):
    """Admit + commit + park one session's chain on ``engine``."""
    engine.paged_admit(lane, tokens, reserve_tokens=len(tokens))
    engine.paged_commit(lane, tokens)
    engine.paged_finish(lane, park=True)


# ---------------------------------------------------------------------------
# HostTier unit: bounded LRU byte budget + integrity frame
# ---------------------------------------------------------------------------


def test_host_tier_lru_byte_bound_eviction():
    """The byte budget is LRU-enforced at put, a get refreshes recency
    (the entry STAYS — one host copy serves N admissions), and an entry
    larger than the whole budget is refused, not stored truncated."""
    blk = (1, 2, 3, 4)
    pay = b"x" * 100
    tier = HostTier(budget_bytes=250, page_size=4)
    assert tier.enabled and not tier.full()

    assert tier.put(("a",), blk, pay)
    assert tier.put(("b",), blk, pay)
    # touch "a": now "b" is the LRU victim
    assert tier.get(("a",), blk) == pay
    assert tier.put(("c",), blk, pay)  # 300 bytes > 250: evicts "b"
    s = tier.stats()
    assert s["pool_host_pages"] == 2 and s["pool_host_bytes"] == 200
    assert s["pool_host_evicted"] == 1
    assert tier.get(("b",), blk) is None  # evicted
    assert tier.get(("a",), blk) == pay  # recency refresh kept it

    # oversize payload: refused whole (full_drops), nothing evicted for it
    assert not tier.put(("big",), blk, b"y" * 300)
    assert tier.stats()["pool_host_full_drops"] == 1
    assert tier.stats()["pool_host_pages"] == 2

    # budget 0 disables the tier outright (the --kv-host-bytes 0 hatch)
    off = HostTier(budget_bytes=0, page_size=4)
    assert not off.enabled
    assert not off.put(("a",), blk, pay)
    assert off.stats()["pool_host_pages"] == 0


def test_host_tier_rehash_failure_drops_entry_and_raises_typed():
    """A payload that no longer matches its stored hash dies with the
    typed :class:`HostTierCorrupt` (a ValueError — the scheduler's
    request-scoped class) and the entry is dropped, so the retry takes
    the rebuild path instead of re-hitting the corruption."""
    blk = (1, 2, 3, 4)
    tier = HostTier(budget_bytes=1 << 10, page_size=4)
    assert tier.put(("a",), blk, b"x" * 64)
    # corrupt the stored payload behind the hash's back
    with tier._lock:
        tier._swapped[("a",)] = (b"y" * 64, tier._swapped[("a",)][1])
    with pytest.raises(HostTierCorrupt) as ei:
        tier.get(("a",), blk)
    assert isinstance(ei.value, ValueError)  # request-scoped by class
    s = tier.stats()
    assert s["pool_host_corrupt"] == 1
    assert s["pool_host_pages"] == 0 and s["pool_host_bytes"] == 0
    assert tier.get(("a",), blk) is None  # dropped: clean miss now


def test_host_tier_hash_framing_matches_disagg():
    """The tier's integrity hash IS kvtransfer's page_hash framing —
    pinned so the two serializers can never drift apart."""
    blk = (7, 8, 9, 10)
    tier = HostTier(budget_bytes=1 << 10, page_size=4)
    tier.put(("k",), blk, b"payload-bytes")
    with tier._lock:
        _, stored_hash = tier._swapped[("k",)]
    assert stored_hash == page_hash(4, blk, b"payload-bytes")


# ---------------------------------------------------------------------------
# Pool + engine: the eviction ladder and swapped admission
# ---------------------------------------------------------------------------


def test_evicted_parked_pages_swap_to_host_and_readmit():
    """The tiered round trip: a parked chain evicted into the host tier
    reactivates on the next same-prefix admission — start covers the
    swapped blocks, the payloads land back byte-identically, and the
    re-registered pages serve from the prefix tree again."""
    eng = _paged_engine()
    tokens = list(range(2, 22))  # 20 tokens = 5 full blocks of 4
    _park_chain(eng, 0, tokens)
    # remember the content-canonical payloads the chain exported
    chain = eng.kvpool.chain_pages(tokens)
    assert len(chain) == 5
    before = [bytes(eng.export_kv_page(p)) for _, p in chain]

    assert eng.swap_out_parked() == 1
    s = eng.pool_stats()
    assert s["pool_host_pages"] == 5 and s["swap_outs"] == 5
    assert s["pool_swap_pending"] == 0  # the drain took everything
    assert eng.kvpool.parked_sessions() == 0
    assert not eng.kvpool.chain_pages(tokens)  # gone from the tree

    # same-prefix admission: 4 full blocks swap back in (the 5th holds
    # the prompt's final token — max_reuse = len-1 keeps one to prefill)
    start = eng.paged_admit(1, tokens, reserve_tokens=24)
    s = eng.pool_stats()
    assert start == 16
    assert s["swap_ins"] == 4 and s["pool_swap_in_admits"] == 1
    assert s["pool_host_pages_swapped_in"] == 4
    assert s["pool_host_hits"] == 4
    # byte identity through the tier: the reactivated pages export the
    # exact bytes the parked originals held
    after = [bytes(eng.export_kv_page(p))
             for _, p in eng.kvpool.chain_pages(tokens[:16])]
    assert after == before[:4]


def test_shared_swapped_prefix_two_sessions_one_host_copy():
    """One host copy serves N sessions: the first admission after the
    swap-out pays the swap-in, re-registers the chain, and the second
    admission shares it RESIDENT by refcount — zero extra swap-ins,
    zero extra host-tier hits."""
    eng = _paged_engine(n_lanes=2)
    prefix = list(range(2, 18))  # 16 tokens = 4 full blocks
    _park_chain(eng, 0, prefix + [30, 31])
    assert eng.swap_out_parked() == 1

    s0 = eng.pool_stats()
    start_a = eng.paged_admit(0, prefix + [40, 41], reserve_tokens=20)
    s1 = eng.pool_stats()
    assert start_a == 16
    assert s1["swap_ins"] - s0["swap_ins"] == 4  # A paid the swap-in
    eng.paged_commit(0, prefix + [40, 41])

    start_b = eng.paged_admit(1, prefix + [50, 51], reserve_tokens=20)
    s2 = eng.pool_stats()
    assert start_b == 16
    assert s2["swap_ins"] == s1["swap_ins"]  # B paid nothing
    assert s2["pool_host_hits"] == s1["pool_host_hits"]
    assert s2["pool_prefix_admits"] == s1["pool_prefix_admits"] + 1
    # and the tier still holds its copy (a hit never removes the entry)
    assert s2["pool_host_pages"] >= 4


def test_corrupt_swap_entry_fails_request_never_poisons_tree():
    """THE containment pin: a corrupt host-tier payload discovered
    during the admission walk raises the typed error BEFORE any pool
    mutation — no refcounts taken, no pages popped, no tree nodes
    registered — and the corrupt entry is dropped so the retry admits
    clean down the rebuild path."""
    eng = _paged_engine()
    tokens = list(range(2, 22))
    _park_chain(eng, 0, tokens)
    assert eng.swap_out_parked() == 1
    pool = eng.kvpool
    tier = pool.host_tier

    # corrupt EVERY entry's payload behind its hash (deposit order is
    # an eviction detail — whichever entry the walk probes first must
    # trip the re-hash)
    with tier._lock:
        for key in list(tier._swapped):
            data, h = tier._swapped[key]
            tier._swapped[key] = (b"\xff" * len(data), h)
    free_before = len(pool._free)
    nodes_before = dict(pool._nodes)
    with pytest.raises(HostTierCorrupt):
        eng.paged_admit(1, tokens, reserve_tokens=24)
    # pool untouched: same free pages, same tree, lane 1 unmapped
    assert len(pool._free) == free_before
    assert pool._nodes == nodes_before
    assert not pool._lane_blocks[1]
    assert eng.pool_stats()["pool_host_corrupt"] == 1

    # retry: the corrupt entry is gone, the walk misses, the request
    # rebuilds from scratch (start == 0) and completes
    start = eng.paged_admit(1, tokens, reserve_tokens=24)
    assert start == 0
    assert eng.pool_stats()["swap_ins"] == 0
    eng.paged_commit(1, tokens)
    eng.paged_finish(1, park=False)


def test_drop_parked_stays_drop_no_tier_deposit():
    """drop_parked() is the REBUILD lever (the bench's third rung): it
    must not stage swap-outs even with the tier enabled, or the
    'rebuild' measurement would quietly serve from host RAM."""
    eng = _paged_engine()
    _park_chain(eng, 0, list(range(2, 22)))
    assert eng.kvpool.drop_parked() == 1
    s = eng.pool_stats()
    assert s["pool_host_pages"] == 0 and s["swap_outs"] == 0
    assert s["pool_swap_pending"] == 0


def test_host_bytes_zero_restores_drop_to_rebuild_bitwise():
    """--kv-host-bytes 0 (the default): the tier never stores, admit
    never returns swapins, eviction deposits nothing — the PR 11
    drop-to-rebuild pool behavior, field-for-field."""
    on = _paged_engine(host_bytes=0)
    tokens = list(range(2, 22))
    _park_chain(on, 0, tokens)
    assert on.swap_out_parked() == 1  # evicts, but nothing to deposit
    s = on.pool_stats()
    assert s["pool_host_pages"] == 0 and s["swap_outs"] == 0
    assert s["pool_swap_pending"] == 0
    assert s["pool_host_budget_bytes"] == 0

    # the re-admission takes the rebuild path, exactly like a pool that
    # predates the tier: no sharing, no swap-ins, fresh pages. (The
    # stream-level bit-for-bit half of this hatch rides the existing
    # paged-vs-contiguous byte-identity pins — every one of them
    # constructs its engines with the default kv_host_bytes=0, so the
    # disabled-tier path IS the path they pin.)
    start = on.paged_admit(1, tokens, reserve_tokens=24)
    assert start == 0
    assert on.pool_stats()["swap_ins"] == 0


def test_pool_exhausted_reason_distinguishes_host_tier_full():
    """The typed shed carries host_tier_full so the scheduler can tell
    the operator which lever to pull (--kv-host-bytes vs
    --kv-pool-pages): False when the tier has headroom or is disabled,
    True when the shed fired with the tier at budget."""
    # tiny tier: one 4-token page payload (mock payloads are 64 bytes)
    # fills the 64-byte budget exactly
    eng = _paged_engine(pool_pages=6, max_parked=4, host_bytes=64)
    _park_chain(eng, 0, list(range(2, 12)))  # 2 committed pages parked
    eng.swap_out_parked()
    assert eng.pool_stats()["pool_host_bytes"] == 64  # LRU kept one
    assert eng.kvpool.host_tier.full()
    # pin lane 0 with an ACTIVE reservation (3 pages held, nothing
    # parked, so nothing is evictable) ...
    eng.paged_admit(0, list(range(50, 60)), reserve_tokens=12)
    # ... and a 4-page reservation against the 3 remaining free pages
    # sheds: structurally servable (4 <= 6 total) but unservable now,
    # with the tier reported FULL
    with pytest.raises(PoolExhausted) as ei:
        eng.paged_admit(1, list(range(100, 115)), reserve_tokens=16)
    assert ei.value.host_tier_full is True

    # same shed with the tier disabled: plain pool_exhausted
    off = _paged_engine(pool_pages=6, max_parked=4, host_bytes=0)
    off.paged_admit(0, list(range(50, 60)), reserve_tokens=12)
    with pytest.raises(PoolExhausted) as ei:
        off.paged_admit(1, list(range(100, 115)), reserve_tokens=16)
    assert ei.value.host_tier_full is False


def test_pool_reset_discards_pending_and_clears_tier():
    """Containment: reset() drops staged-but-undrained swap-outs (their
    bytes are untrusted after a failure) and clears the host tier — no
    stale payload can reactivate into a rebuilt pool."""
    eng = _paged_engine()
    _park_chain(eng, 0, list(range(2, 22)))
    # stage WITHOUT draining (reach under the engine: simulates a
    # failure between eviction and the drain)
    assert eng.kvpool.swap_out_parked() == 1
    assert eng.pool_stats()["pool_swap_pending"] > 0
    eng.paged_reset()
    s = eng.pool_stats()
    assert s["pool_swap_pending"] == 0
    assert s["pool_host_pages"] == 0


def test_swap_in_count_mismatch_is_typed():
    """The engine-side validation the pod replay path converts into a
    ReplayError: page/payload count mismatch is a ValueError before
    anything is recorded."""
    eng = _paged_engine()
    with pytest.raises(ValueError):
        eng.swap_in_pages([0, 1], [b"x"])


# ---------------------------------------------------------------------------
# OP_KV_SWAP: pod broadcast framing + worker replay
# ---------------------------------------------------------------------------


def _capture_plane(n_lanes=2, chunk=8):
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        ControlPlane,
    )

    class _Plane(ControlPlane):
        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            self.sent = []

        def _bcast(self, pkt):
            self.sent.append(np.array(pkt))
            return pkt

    return _Plane(n_lanes=n_lanes, chunk=chunk)


class _FeedPlane:
    """Worker-side plane serving previously captured packets."""

    def __init__(self, plane, pkts):
        self._plane = plane
        self._pkts = list(pkts)

    def recv(self):
        from distributed_llama_multiusers_tpu.parallel.multihost import (
            ControlPlane,
        )

        pkt = self._pkts.pop(0)
        ControlPlane.validate(pkt)
        return pkt

    def slot(self, pkt, i, n):
        return self._plane.slot(pkt, i, n)


def test_send_kv_swap_frames_fragments_and_batch_flag():
    """send_kv_swap framing: per-page payload fragments with bit 0 on
    each page's final fragment and bit 1 only on the batch's last
    page's final fragment — and the pod-deadlock rule (empty batch /
    negative page id raise with ZERO packets broadcast)."""
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        OP_KV_SWAP,
    )

    plane = _capture_plane(chunk=8)  # 32 payload bytes per fragment
    with pytest.raises(ValueError):
        plane.send_kv_swap([])
    with pytest.raises(ValueError):
        plane.send_kv_swap([(3, b"x"), (-1, b"y")])
    assert plane.sent == []  # nothing escaped pre-validation

    plane.send_kv_swap([(5, b"a" * 40), (9, b"b" * 8)])
    # page 5: 40 bytes -> fragments of 32 + 8; page 9: one 8-byte frag
    hdrs = [tuple(p[2:6]) for p in plane.sent]
    assert hdrs == [
        (OP_KV_SWAP, 0, 32, 5),  # mid fragment
        (OP_KV_SWAP, 1, 8, 5),  # final fragment of page 5
        (OP_KV_SWAP, 3, 8, 9),  # final fragment of final page: bits 0|1
    ]


def test_worker_replays_kv_swap_as_one_batched_dispatch():
    """The worker reassembles fragments per page, accumulates completed
    pages, and dispatches ONE engine.swap_in_pages for the whole batch
    (bit 1) — program counts identical to the root's."""
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        worker_loop,
    )

    plane = _capture_plane(chunk=8)
    payload_a, payload_b = b"a" * 40, b"b" * 8
    plane.send_kv_swap([(5, payload_a), (9, payload_b)])
    plane.send_stop()

    class _WEng:
        kvpool = object()  # paged marker

        def __init__(self):
            self.calls = []

        def swap_in_pages(self, pages, payloads):
            self.calls.append((list(pages), [bytes(b) for b in payloads]))

    weng = _WEng()
    worker_loop(weng, _FeedPlane(plane, plane.sent))
    assert weng.calls == [([5, 9], [payload_a, payload_b])]


def test_worker_kv_swap_geometry_skew_is_replay_error():
    """A worker whose engine rejects the payload geometry (root and
    worker paged-KV flags skewed) classifies as ReplayError — the
    supervised worker resubscribes instead of dying — and a non-paged
    worker classifies the same way pre-dispatch."""
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        ReplayError,
        worker_loop,
    )

    plane = _capture_plane(chunk=8)
    plane.send_kv_swap([(5, b"a" * 8)])

    class _SkewEng:
        kvpool = object()

        def swap_in_pages(self, pages, payloads):
            raise ValueError("payload 0 is 8 bytes, expected 4096")

    with pytest.raises(ReplayError) as ei:
        worker_loop(_SkewEng(), _FeedPlane(plane, plane.sent))
    assert "geometry" in str(ei.value)

    class _NonPaged:
        kvpool = None

    with pytest.raises(ReplayError) as ei:
        worker_loop(_NonPaged(), _FeedPlane(plane, plane.sent))
    assert "non-paged" in str(ei.value)


def test_pod_root_swap_in_validates_before_broadcast():
    """RootControlEngine.swap_in_pages: count/geometry skew dies ROOT-
    side with zero packets out (the pod-deadlock rule); a valid batch
    broadcasts exactly one OP_KV_SWAP batch then applies root-side."""
    from distributed_llama_multiusers_tpu.parallel.multihost import (
        OP_KV_SWAP,
        RootControlEngine,
    )

    # chunk >= the inner engine's blocks-per-lane (16) so OP_KV_TABLE
    # rows fit their packet slot; swap payloads fragment fine either way
    plane = _capture_plane(chunk=16)
    inner = _paged_engine(page_size=4)
    root = RootControlEngine(inner, plane)

    with pytest.raises(ValueError):
        root.swap_in_pages([0, 1], [b"x"])  # count mismatch
    assert plane.sent == []

    # a valid single-page batch rides the wire and lands on the inner
    # engine (the mock's device half records the payload)
    _park_chain(inner, 0, list(range(2, 22)))
    assert inner.swap_out_parked() == 1
    start = root.paged_admit(1, list(range(2, 22)), reserve_tokens=24)
    assert start == 16
    swap_pkts = [p for p in plane.sent if p[2] == OP_KV_SWAP]
    assert swap_pkts  # the host-tier hits rode OP_KV_SWAP
    assert any(p[3] & 2 for p in swap_pkts)  # batch-final flag present
