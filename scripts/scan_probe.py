"""Probe: matmul bandwidth inside lax.scan over stacked layer weights —
the model's real execution context (llama_forward scans layers). Standalone
matvecs measure ~135 GB/s while the full model implies ~600 GB/s; this
isolates whether cross-layer pipelining is the difference, and how the
Pallas Q40 kernel behaves in that context.
"""

import sys
import time
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from distributed_llama_multiusers_tpu.quants.packed import (  # noqa: E402
    PackedQ40,
    pack_q40_host,
)
from distributed_llama_multiusers_tpu.ops.pallas_q40 import q40_matmul_pallas  # noqa: E402
from scripts.kernel_lab import q40_matmul_v1  # noqa: E402

HBM = 819.0


def timeit(fn, *args, reps=3):
    # np.asarray, not block_until_ready: the axon backend's
    # block_until_ready returns before execution completes (see bench.py)
    np.asarray(fn(*args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    m = int(sys.argv[1]) if len(sys.argv) > 1 else 1
    d_in = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    d_out = int(sys.argv[3]) if len(sys.argv) > 3 else 14336
    L = int(sys.argv[4]) if len(sys.argv) > 4 else 16
    loops = 8

    rng = np.random.default_rng(0)
    print(f"m={m} {d_in}x{d_out} L={L} device={jax.devices()[0].device_kind}",
          flush=True)

    # stacked planes, like LlamaLayerParams
    host_w = rng.standard_normal((L, d_out, d_in), dtype=np.float32) * 0.05
    packed_l, scales_l = [], []
    for l in range(L):
        p, s = pack_q40_host(host_w[l])
        packed_l.append(p)
        scales_l.append(s)
    packed = jnp.asarray(np.stack(packed_l))   # [L, d_in//2, d_out]
    scales = jnp.asarray(np.stack(scales_l))   # [L, d_in//32, d_out]
    dense = jnp.asarray(np.swapaxes(host_w, 1, 2), jnp.bfloat16)  # [L, d_in, d_out]
    x = jnp.asarray(rng.standard_normal((m, d_in), np.float32))

    pbytes = packed.size + scales.size * 2
    dbytes = dense.size * 2

    @jax.jit
    def scan_dense(x, dense):
        def outer(_, x):
            def step(x, w):
                y = jnp.dot(x.astype(jnp.bfloat16), w,
                            preferred_element_type=jnp.float32)
                return (y[..., :d_in] * 1e-2).astype(x.dtype), None

            x, _ = jax.lax.scan(step, x, dense)
            return x

        return jax.lax.fori_loop(0, loops, outer, x)

    @partial(jax.jit, static_argnames=("which",))
    def scan_q40(x, packed, scales, which="v0"):
        def outer(_, x):
            def step(x, ws):
                p, s = ws
                if which == "v0":
                    y = q40_matmul_pallas(x, PackedQ40(p, s))
                else:
                    y = q40_matmul_v1(x, p, s, w_dtype=jnp.bfloat16,
                                      x_dtype=jnp.bfloat16)
                return (y[..., :d_in] * 1e-2).astype(x.dtype), None

            x, _ = jax.lax.scan(step, x, (packed, scales))
            return x

        return jax.lax.fori_loop(0, loops, outer, x)

    sec = timeit(scan_dense, x, dense) / loops / L
    gbs = dbytes / L / sec / 1e9
    print(f"{'dense_scan':16s} {sec * 1e6:8.1f} us/mm  {gbs:7.1f} GB/s "
          f"({gbs / HBM * 100:5.1f}% HBM)", flush=True)

    for which in ("v0", "v1"):
        try:
            sec = timeit(lambda a, b, c: scan_q40(a, b, c, which=which),
                         x, packed, scales) / loops / L
            gbs = pbytes / L / sec / 1e9
            print(f"{'q40_scan_' + which:16s} {sec * 1e6:8.1f} us/mm  {gbs:7.1f} GB/s "
                  f"({gbs / HBM * 100:5.1f}% HBM)", flush=True)
        except Exception as e:
            print(f"q40_scan_{which} FAILED: {type(e).__name__}: {str(e)[:150]}",
                  flush=True)


if __name__ == "__main__":
    main()
