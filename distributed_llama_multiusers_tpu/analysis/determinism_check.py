"""replay-determinism: nothing nondeterministic inside the replay closure.

The crash-recovery / fleet-migration / grammar stack (PRs 10-13) is
correct because generation is a pure function of the journaled admit
record: (prompt tokens, resolved sampler seed, params, schema). Three
review rounds on PR 10 alone were spent finding the leaks that break
that closure — an unjournaled entropy draw, a ``hash()`` that changes
per process (PYTHONHASHSEED randomization — the hazard
``fleet/balancer.stable_hash``'s crc32 exists to dodge), a ``set``
whose iteration order feeds serialized output. This check mechanizes
the rule over a declared scope:

- ``serving/journal.py`` / ``serving/recovery.py`` — the admit record
  and its replay;
- ``fleet/migrate.py`` — the same record as a live-migration ticket;
- ``grammar/automaton.py`` — schema canonicalization (every process
  must compile the identical automaton from the broadcast bytes);
- ``runtime/scheduler.py`` — the admit-record build and everything
  around it;
- ``app/dllama.py`` — the CLI's seed handling (the training batch
  stream replays on resume).

Findings, unless waived with ``ok[replay-determinism] <reason naming
the journaled draw>``:

- **entropy**: ``random.*`` / ``np.random.*`` / ``os.urandom`` /
  ``uuid.uuid*`` / ``secrets.*``. The ONE sanctioned source is
  ``utils.seeds.fresh_seed()`` — its draw is resolved at admission and
  journaled in the admit record, so replay re-reads the recorded value
  instead of re-drawing. Explicitly seeded RNG construction
  (``np.random.default_rng(seed)`` with a resolved seed argument) is
  deterministic and allowed; the argument-less form is the hazard.
- **builtin ``hash()``**: varies per process for str/bytes under hash
  randomization — two replicas disagree on anything derived from it.
- **set iteration**: ``for x in {...}`` / ``set(...)`` — iteration
  order is hash-order; ``sorted(...)`` the set before it can feed a
  record, packet, or replayed stream.
"""

from __future__ import annotations

import ast

from .core import Checker, Finding, Project, SourceFile, walk_with_ancestors
from .lockgraph import walk_excluding_nested_defs

SCOPE = (
    # the journal IS the replay closure; recovery replays it
    "serving/journal.py", "serving/recovery.py",
    # migration ships the same admit record between replicas
    "fleet/migrate.py",
    # disagg hand-off rides the same replay closure: the decode replica
    # regenerates the session from the ticket, and the page bundle's
    # integrity hashes must be a pure function of (geometry, tokens,
    # payload) — any entropy here would break cross-replica adoption
    "disagg/kvtransfer.py", "disagg/prefill.py",
    # schema canonicalization: every process compiles the same automaton
    "grammar/automaton.py",
    # the admit-record build (resolved seed, QoS class, deadlines)
    "runtime/scheduler.py",
    # CLI seed handling: the no-seed case must route through fresh_seed
    "app/dllama.py",
)

# dotted prefixes that ARE entropy (resolved through import aliases)
ENTROPY_PREFIXES = ("random.", "numpy.random.", "secrets.")
ENTROPY_EXACT = {"os.urandom", "os.getrandom", "uuid.uuid1", "uuid.uuid3",
                 "uuid.uuid4", "uuid.uuid5", "uuid.getnode"}
# RNG constructors that are deterministic WHEN explicitly seeded
SEEDED_CTORS = {"default_rng", "Generator", "SeedSequence", "RandomState",
                "Random", "PCG64", "Philox"}
# `from <module> import <name>` bindings banned at the import line —
# a bare-Name call site is invisible to the Attribute resolver, so the
# import IS the finding ("*" = every name except the seeded
# constructors above)
BANNED_FROM = {"random": "*", "secrets": "*", "numpy.random": "*",
               "os": {"urandom", "getrandom"},
               "uuid": {"uuid1", "uuid3", "uuid4", "uuid5", "getnode"}}

_FIX = (
    "— replay must re-derive byte-identical state; draw through "
    "utils.seeds.fresh_seed() at admission and journal the result (the "
    "admit-record pattern), or waive naming the journaled draw"
)


class ReplayDeterminismChecker(Checker):
    name = "replay-determinism"
    description = (
        "no unjournaled entropy, builtin hash(), or set-iteration order "
        "inside the journal/recovery/migration/grammar replay scope"
    )

    def check(self, sf: SourceFile, project: Project):
        if not sf.endswith(*SCOPE):
            return
        aliases = self._aliases(sf.tree)
        yield from self._check_imports(sf)
        shadowed_hash = self._shadows_builtin_hash(sf.tree, aliases)
        yield from self._check_set_iteration(sf)

        for node, ancestors in walk_with_ancestors(sf.tree):
            if isinstance(node, ast.Attribute):
                dotted = self._resolve(node, aliases)
                if dotted is None or not self._is_entropy(dotted):
                    continue
                if self._seeded_ctor_call(node, ancestors):
                    continue
                yield Finding(
                    self.name, sf.display, node.lineno,
                    f"'{ast.unparse(node)}' is an unjournaled entropy "
                    f"source in the replay-determinism scope {_FIX}",
                )
            elif isinstance(node, ast.Call) and not shadowed_hash \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == "hash":
                yield Finding(
                    self.name, sf.display, node.lineno,
                    "builtin hash() varies per process under "
                    "PYTHONHASHSEED randomization — two replicas disagree "
                    "on anything derived from it; use a stable digest "
                    "(fleet/balancer.stable_hash's crc32 recipe, zlib, "
                    "hashlib) or waive naming why the value never leaves "
                    "this process",
                )

    # -- entropy -------------------------------------------------------------

    @staticmethod
    def _aliases(tree: ast.Module) -> dict[str, str]:
        aliases: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        aliases[a.asname] = a.name
                    else:
                        # `import os.path` binds the ROOT name `os` to
                        # the ROOT module — mapping it to "os.path"
                        # would resolve os.urandom as os.path.urandom
                        # and let the entropy draw escape
                        root = a.name.split(".")[0]
                        aliases[root] = root
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def _check_imports(self, sf: SourceFile):
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.ImportFrom) and node.module):
                continue
            banned = BANNED_FROM.get(node.module)
            if banned is None:
                continue
            for a in node.names:
                if a.name in SEEDED_CTORS:
                    continue
                if banned == "*" or a.name in banned:
                    yield Finding(
                        self.name, sf.display, node.lineno,
                        f"'from {node.module} import {a.name}' binds an "
                        f"entropy source in the replay-determinism scope "
                        f"{_FIX}",
                    )

    def _resolve(self, node: ast.Attribute, aliases: dict[str, str]) -> str | None:
        parts = [node.attr]
        cur = node.value
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name) or cur.id not in aliases:
            return None  # only imported roots: `self.random.x` is not the
            # random module
        return ".".join([aliases[cur.id], *reversed(parts)])

    @staticmethod
    def _is_entropy(dotted: str) -> bool:
        return dotted in ENTROPY_EXACT or any(
            dotted.startswith(p) for p in ENTROPY_PREFIXES
        )

    @staticmethod
    def _seeded_ctor_call(node: ast.Attribute, ancestors) -> bool:
        """``np.random.default_rng(resolved_seed)`` is a deterministic
        construction, not a draw — allowed when explicitly seeded."""
        if node.attr not in SEEDED_CTORS or not ancestors:
            return False
        parent = ancestors[-1]
        return (isinstance(parent, ast.Call) and parent.func is node
                and bool(parent.args or parent.keywords))

    @staticmethod
    def _shadows_builtin_hash(tree: ast.Module, aliases: dict[str, str]) -> bool:
        if "hash" in aliases:
            return True
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == "hash":
                return True
        return False

    # -- set iteration -------------------------------------------------------

    @staticmethod
    def _is_set_expr(node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        return (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id in ("set", "frozenset"))

    def _set_names_in(self, nodes) -> set[str]:
        names: set[str] = set()
        for node in nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and self._is_set_expr(node.value):
                names.add(node.targets[0].id)
            elif isinstance(node, ast.AnnAssign) and node.value is not None \
                    and isinstance(node.target, ast.Name) \
                    and self._is_set_expr(node.value):
                names.add(node.target.id)
        return names

    def _check_set_iteration(self, sf: SourceFile):
        """Name-bound set iteration resolves PER SCOPE: module-level
        bindings are visible everywhere, a function's own bindings only
        inside it — `pending = {1, 2}` in one function must not convict
        an unrelated `pending` list in another."""
        module_names = self._set_names_in(walk_excluding_nested_defs(sf.tree))
        yield from self._check_scope(
            sf, list(walk_excluding_nested_defs(sf.tree)), module_names
        )
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                body = list(walk_excluding_nested_defs(node))
                yield from self._check_scope(
                    sf, body, module_names | self._set_names_in(body)
                )

    def _check_scope(self, sf: SourceFile, nodes, set_names: set[str]):
        for node in nodes:
            if isinstance(node, ast.For):
                yield from self._check_iter(sf, node.iter, set_names)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp, ast.DictComp)):
                for gen in node.generators:
                    yield from self._check_iter(sf, gen.iter, set_names)

    def _check_iter(self, sf: SourceFile, it: ast.AST, set_names: set[str]):
        if self._is_set_expr(it) or (
            isinstance(it, ast.Name) and it.id in set_names
        ):
            yield Finding(
                self.name, sf.display, it.lineno,
                f"iterating a set ('{ast.unparse(it)}') — iteration order "
                "is hash order (PYTHONHASHSEED-randomized for str/bytes), "
                "so anything it feeds into a journal record, packet, or "
                "replayed stream differs across processes; sorted(...) it, "
                "or waive naming why the order cannot leak",
            )
