"""Shared model/engine bootstrapping for the CLI entry points — the analogue
of runInferenceApp's setup sequence (src/app.cpp:233-312): load header ->
validate -> tokenizer -> build model -> place on devices -> engine."""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp

from ..formats import load_model_header
from ..models import load_params_from_m
from ..models.loader import load_params_from_m_quantized
from ..parallel import make_mesh, validate_mesh_for_config
from ..parallel.sharding import shard_params
from ..runtime import ContinuousBatchingScheduler, InferenceEngine
from ..tokenizer import Tokenizer
from .args import parse_mesh_spec


def log(emoji: str, msg: str) -> None:
    print(f"{emoji} {msg}", flush=True)


def load_stack(args, n_lanes: int | None = None):
    """Returns (config, params, tokenizer, engine)."""
    if not args.model or not args.tokenizer:
        print("error: --model and --tokenizer are required", file=sys.stderr)
        raise SystemExit(2)
    header = load_model_header(args.model, max_seq_len=args.max_seq_len)
    config_dtype = jnp.bfloat16
    if jax.default_backend() == "cpu":
        config_dtype = jnp.float32  # parity-friendly on host runs

    log("💡", f"Dim: {header.dim}  HiddenDim: {header.hidden_dim}  Layers: {header.n_layers}")
    log("💡", f"Heads: {header.n_heads}/{header.n_kv_heads}  Vocab: {header.vocab_size}  SeqLen: {header.seq_len}")

    tokenizer = Tokenizer(args.tokenizer)
    log("📄", f"Vocab: {tokenizer.vocab_size}  Bos: {tokenizer.bos_id}  Eos: {tokenizer.eos_token_ids}")

    weights_mode = getattr(args, "weights", "auto")
    if weights_mode == "auto":
        weights_mode = "packed" if jax.default_backend() == "tpu" else "dense"
    if weights_mode == "packed":
        config, params = load_params_from_m_quantized(args.model, header, dtype=config_dtype)
        from ..quants.packed import PackedQ40

        if any(isinstance(x, PackedQ40) for x in [params.wcls, params.layers.wq]):
            log("🔷", "Q40 weights resident in HBM (dequant-in-matmul)")
        else:
            log("🔶", "model has no Q40 tensors; loaded dense")
    else:
        config, params = load_params_from_m(args.model, header, dtype=config_dtype)

    mesh = None
    plan = parse_mesh_spec(args.workers)
    if plan is not None and plan.n_devices > 1:
        validate_mesh_for_config(config, plan)
        mesh = make_mesh(plan)
        params = shard_params(params, mesh)
        # the Pallas Q40 kernel stays enabled: q40_matmul_partitioned carries
        # a GSPMD partitioning rule, so every shard runs dequant-in-matmul —
        # the reference's every-node-runs-the-quantized-matmul property
        # (src/nn/nn-cpu-ops.cpp:222-440)
        log(
            "⭕",
            f"Mesh: dp={plan.dp} pp={plan.pp} tp={plan.tp} sp={plan.sp} "
            f"ep={plan.ep} over {plan.n_devices} devices",
        )
    log("💿", "Weights loaded")

    from ..quants.codec import FloatType

    emulate_q80 = args.buffer_float_type == FloatType.Q80
    if emulate_q80:
        log("🔶", "Q80 activation-cast emulation enabled (--buffer-float-type q80)")
    engine = InferenceEngine(
        config,
        params,
        n_lanes=n_lanes or args.max_lanes,
        cache_dtype=jnp.float32,
        emulate_q80_activations=emulate_q80,
        mesh=mesh,
    )
    return config, params, tokenizer, engine


def make_scheduler(engine, tokenizer) -> ContinuousBatchingScheduler:
    sched = ContinuousBatchingScheduler(engine, tokenizer)
    sched.start()
    return sched
