#!/usr/bin/env python
"""Convert a Llama-2 sentencepiece `tokenizer.model` to the `.t` format.

Usage: python convert-tokenizer-llama2.py <folderPathWithTokenizerModel>

Reimplementation of the reference (converter/convert-tokenizer-llama2.py):
sentencepiece pieces + scores; ▁ metaspace becomes a space byte; byte tokens
<0xNN> become raw bytes; llama2 [INST] chat template embedded.
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_llama_multiusers_tpu.formats.tokenizer_file import TokenizerData, write_tokenizer_file

LLAMA2_CHAT_TEMPLATE = (
    "{% if messages[0]['role'] == 'system' %}[INST] <<SYS>>\n{{ messages[0]['content'] }}"
    "\n<</SYS>>\n\n{% endif %}{% for message in messages %}"
    "{% if message['role'] == 'user' %}[INST] {{ message['content'] }} [/INST]"
    "{% elif message['role'] == 'assistant' %}{{ message['content'] }}{% endif %}{% endfor %}"
)

_BYTE_RE = re.compile(r"^<0x([0-9A-Fa-f]{2})>$")


def convert(folder: str, out_path: str) -> None:
    try:
        import sentencepiece as spm
    except ImportError as e:
        raise SystemExit(
            "sentencepiece is required for llama2 tokenizer conversion "
            "(pip install sentencepiece)"
        ) from e

    model_path = os.path.join(folder, "tokenizer.model") if os.path.isdir(folder) else folder
    sp = spm.SentencePieceProcessor(model_file=model_path)
    vocab: list[bytes] = []
    scores: list[float] = []
    for i in range(sp.vocab_size()):
        piece = sp.id_to_piece(i)
        m = _BYTE_RE.match(piece)
        if m:
            b = bytes([int(m.group(1), 16)])
        else:
            b = piece.replace("▁", " ").encode("utf-8")
        vocab.append(b if b else b" ")
        scores.append(float(sp.get_score(i)))

    data = TokenizerData(
        vocab=vocab,
        scores=scores,
        bos_id=sp.bos_id(),
        eos_token_ids=[sp.eos_id()],
        chat_template=LLAMA2_CHAT_TEMPLATE,
    )
    with open(out_path, "wb") as f:
        write_tokenizer_file(f, data)
    print(f"✅ {out_path}: vocab {len(vocab)}, bos {sp.bos_id()}, eos {sp.eos_id()}")


def main() -> None:
    if len(sys.argv) < 2:
        print("Usage: python convert-tokenizer-llama2.py <folderPathWithTokenizerModel>")
        raise SystemExit(1)
    convert(sys.argv[1], "dllama_tokenizer_llama2.t")


if __name__ == "__main__":
    main()
