"""Benchmark: decode + serving throughput of the flagship model on TPU.

Prints JSON lines: {"metric", "value", "unit", "vs_baseline", ...extras}.
The LAST line is the cumulative artifact; it is re-printed after every
completed phase, so a driver timeout at any point still records everything
measured so far (round-3 failure mode: rc=124 with nothing parsed).

Primary metric: batch=1 greedy decode tokens/sec for a Llama-3.2-1B-shaped
model with Q40 weights at rest in HBM (int4+f16 scales, dequant-in-matmul
Pallas kernel — the same weight format the reference runs,
src/nn/nn-quants.hpp:64-67) and a 2048-token KV cache.

Extra phases, each in its OWN child process with its OWN timeout so no
single phase can eat the budget:
  serving    — aggregate tok/s + p50/p95 step latency through the
               ContinuousBatchingScheduler at 8 concurrent requests (the
               reference's headline numbers are end-to-end app-loop
               per-token times, src/dllama.cpp:36-113)
  serving_churn — Poisson arrivals against the real scheduler: TTFT
               p50/p95 (submit -> first stream delta), aggregate tok/s,
               and the pipeline flush count under churn — the stall-free
               admission path (fused prefill+decode dispatch) keeps
               flushes ~0 while requests join mid-chain
  pod_serving — the same churn workload on a pure-TP mesh(tp=N): Q40
               planes TP-sharded (each chip reads 1/N of the weights per
               token), mesh-native pipelined+fused dispatch, ring-
               overlapped activation sync; reports tok/s/chip against
               the 200 north star plus the measured sync-ms split
  serving_faults — the chaos gate: churn with a deterministic engine
               fault injected mid-run (DLLAMA_FAULTS, utils/faults.py);
               reports error rate, hang-free, and breaker recovery time
               — the failure-containment layer's evidence
  serving_recovery — the crash-durability gate: churn with the request
               journal on, a simulated process death mid-stream, and a
               --recover-journal restart; reports resume-latency-ms,
               lost-token count (must be 0) and duplicate-token count
               (must be 0) for clients reattaching via Last-Event-ID
  serving_fleet — the fleet gate: Poisson SSE traffic through the
               dllama-router at 3 mock-backed replicas while one is
               SIGTERM-drained and one is killed mid-run; reports
               TTFT/TBT percentiles through the router, shed rate
               (must be 0 — sheds are retried or migrated), affinity
               hit rate, migration count + latency, and the loss
               ledger (byte-identical, 0 lost / 0 duplicated)
  serving_structured — the structured-output gate: Poisson churn with a
               JSON-schema workload mixed into plain lanes; reports
               valid-JSON rate (must be 1.0), schema-compile ms
               (cold/cached), masked-steps per dispatch, pipeline
               flushes (must be 0), and a constrained stream killed
               mid-flight replaying byte-identically through journal
               recovery
  serving_disagg — the disaggregated-prefill gate: a prefill-role, a
               decode-role and a mixed replica behind the router with
               prompt-length routing on; reports long-prompt TTFT, the
               KV-page hand-off count/latency (integrity-verified,
               refcount-correct adoption on the REAL pool), co-resident
               short-session TBT p95 vs a no-long-prompt baseline (must
               stay within 10%), byte-identity across the hand-off, and
               the monolithic fallback after the prefill replica dies
  ablations  — packed Q40 via XLA dequant, dense bf16 (what the kernel buys)
  8b         — the BASELINE north star: Llama-3.1-8B Q40 decode tok/s vs
               200 tok/s/chip (BASELINE.md), now on by default
  parity     — greedy token-identity of the shipping bf16-dot kernel vs
               exact f32 over 256 tokens (BASELINE.md gate-dtype clause)
  longctx    — decode tok/s at FULL context (whole-KV attention reads),
               bf16 KV vs --kv-dtype f8 (macbeth.sh's regime, measured)

Perf-path hygiene: weights are generated DIRECTLY as random packed planes
(no 2.5-16 GB dense intermediate on the host), so the first measurement
lands within a couple of minutes even over a slow device tunnel.

vs_baseline: ratio against the reference's best published single-device
number — Llama 2 7B on 1x RPi 4B at 1312.50 ms/token = 0.762 tok/s
(report.pdf Fig. 3, BASELINE.md). Reported ONLY for TPU runs (null on the
CPU fallback: a 1B-on-CPU vs 7B-on-RPi ratio is not a comparison), and
overwritten with the matched-model Llama-3.1-8B ratio when the 8b phase
lands; vs_baseline_model names the pairing.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REFERENCE_SINGLE_DEVICE_TOK_S = 1000.0 / 1312.50  # report.pdf Fig. 3
METRIC = "llama32_1b_q40_decode_tok_s"

# bf16 peak TFLOP/s and HBM GB/s per chip by device kind (public specs)
_CHIP_SPECS = {
    "TPU v4": (275e12, 1228e9),
    "TPU v5 lite": (197e12, 819e9),
    "TPU v5e": (197e12, 819e9),
    "TPU v5": (459e12, 2765e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v6 lite": (918e12, 1640e9),
    "TPU v6e": (918e12, 1640e9),
}


def _chip_spec(device_kind: str):
    for k, v in _CHIP_SPECS.items():
        if device_kind.lower().startswith(k.lower()):
            return v
    return None, None


# ---------------------------------------------------------------------------
# Child: one benchmark phase per process (BENCH_PHASE env).
# ---------------------------------------------------------------------------


def _weight_specs(config):
    """(d_in, d_out, lead) per matmul weight (wcls/embedding handled by
    callers) — the single shape table every bench param generator draws
    from, so the TPU on-device path, the CPU host path, and the dense
    ablation cannot drift apart."""
    L, d, h = config.n_layers, config.dim, config.hidden_dim
    kv = config.n_kv_heads * config.head_size
    e = (config.n_experts,) if config.n_experts > 0 else ()
    return {
        "wq": (d, d, (L,)),
        "wk": (d, kv, (L,)),
        "wv": (d, kv, (L,)),
        "wo": (d, d, (L,)),
        "w1": (d, h, (L, *e)),
        "w2": (h, d, (L, *e)),
        "w3": (d, h, (L, *e)),
    }


def _random_packed_params(config, seed: int = 0, dtype=None):
    """Random PackedQ40 params WITHOUT the dense host intermediate: the
    packed nibble/scale planes are drawn directly (values are irrelevant to
    a bandwidth benchmark; shapes and bytes are exactly the Q40 footprint).
    Returns a host pytree ready for one device_put."""
    import numpy as np
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.models.llama import (
        LlamaLayerParams,
        LlamaParams,
    )
    from distributed_llama_multiusers_tpu.models.loader import _rope_cache
    from distributed_llama_multiusers_tpu.quants.packed import PackedQ40

    if dtype is None:
        dtype = jnp.bfloat16
    rng = np.random.default_rng(seed)
    L, d = config.n_layers, config.dim

    from distributed_llama_multiusers_tpu.quants.packed import pad_packed_d_out

    def packed(d_in, d_out, lead=(), pad=False):
        pk = rng.integers(0, 256, (*lead, d_in // 2, d_out), dtype=np.uint8)
        sc = (rng.random((*lead, d_in // 32, d_out), dtype=np.float32)
              * 0.01 + 0.001).astype(np.float16)
        if pad:  # wcls only: vocab padding for the slab kernel's wide
            # tiles, mirroring the loader; llama_forward slices logits back
            pk, sc = pad_packed_d_out(pk, sc)
        return PackedQ40(packed=pk, scales=sc)

    w = {k: packed(*s[:2], s[2]) for k, s in _weight_specs(config).items()}
    layers = LlamaLayerParams(
        **w,
        rms_att=np.ones((L, d), np.float32),
        rms_ffn=np.ones((L, d), np.float32),
        moe_gate=(rng.standard_normal((L, d, config.n_experts), dtype=np.float32)
                  if config.n_experts > 0 else None),
    )
    cos, sin = _rope_cache(config)
    return LlamaParams(
        embedding=(rng.standard_normal((config.vocab_size, d), dtype=np.float32)
                   * 0.02).astype(dtype),
        layers=layers,
        rms_final=np.ones((d,), np.float32),
        wcls=packed(d, config.vocab_size, pad=True),
        rope_cos=cos,
        rope_sin=sin,
    )


def _assemble_params(config, t, cos, sin):
    """Shared LlamaParams assembly for the on-device generators: ``t`` maps
    weight names to device arrays; rms planes are ones; only the tiny RoPE
    tables cross the host->device link."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.models.llama import (
        LlamaLayerParams,
        LlamaParams,
    )

    L, d = config.n_layers, config.dim
    layers = LlamaLayerParams(
        wq=t["wq"], wk=t["wk"], wv=t["wv"], wo=t["wo"],
        w1=t["w1"], w2=t["w2"], w3=t["w3"],
        rms_att=jnp.ones((L, d), jnp.float32),
        rms_ffn=jnp.ones((L, d), jnp.float32),
        moe_gate=t.get("moe_gate"),
    )
    return LlamaParams(
        embedding=t["embedding"],
        layers=layers,
        rms_final=jnp.ones((d,), jnp.float32),
        wcls=t["wcls"],
        rope_cos=jax.device_put(cos),
        rope_sin=jax.device_put(sin),
    )


def _device_packed_params(config, seed: int = 0, dtype=None):
    """Random PackedQ40 params generated ON DEVICE in one jitted program.

    Over the axon device tunnel, `device_put` of the 0.7 GB (1B) / 4.3 GB
    (8B) host planes is the dominant setup cost — and heavy bulk transfer
    is the prime suspect for the tunnel wedging mid-round (rounds 4-5 both
    lost it right after a multi-hundred-MB put). Values are irrelevant to a
    bandwidth benchmark; on-chip random bits have identical shapes/bytes
    and cost zero host->device traffic (only the tiny RoPE tables cross)."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.models.loader import _rope_cache
    from distributed_llama_multiusers_tpu.quants.packed import (
        PackedQ40,
        padded_d_out,
    )

    if dtype is None:
        dtype = jnp.bfloat16
    L, d = config.n_layers, config.dim
    specs = dict(_weight_specs(config))
    specs["wcls"] = (d, padded_d_out(config.vocab_size), ())

    def gen(key):
        out = {}
        for name, (d_in, d_out, lead) in specs.items():
            key, kp, ks = jax.random.split(key, 3)
            pk = jax.random.bits(kp, (*lead, d_in // 2, d_out), jnp.uint8)
            sc = (
                jax.random.uniform(ks, (*lead, d_in // 32, d_out), jnp.float32)
                * 0.01 + 0.001
            )
            if name == "wcls" and d_out > config.vocab_size:
                # keep the loader's invariant: zero scales make the vocab
                # pad columns dequantize to exact zeros
                sc = jnp.where(
                    jnp.arange(d_out) < config.vocab_size, sc, 0.0
                )
            out[name] = PackedQ40(packed=pk, scales=sc.astype(jnp.float16))
        key, ke, kg = jax.random.split(key, 3)
        out["embedding"] = (
            jax.random.normal(ke, (config.vocab_size, d), jnp.float32) * 0.02
        ).astype(dtype)
        if config.n_experts > 0:
            out["moe_gate"] = jax.random.normal(
                kg, (L, d, config.n_experts), jnp.float32
            )
        return out

    t = jax.jit(gen)(jax.random.PRNGKey(seed))
    jax.block_until_ready(t)
    return _assemble_params(config, t, *_rope_cache(config))


def _device_dense_params(config, seed: int = 0, dtype=None):
    """Dense random params generated on device (see _device_packed_params
    for why): the 1B bf16 tree is ~2.5 GB — never ship that over the
    tunnel for an ablation."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.models.loader import _rope_cache

    if dtype is None:
        dtype = jnp.bfloat16
    L, d = config.n_layers, config.dim
    specs = {
        k: (*lead, d_in, d_out)
        for k, (d_in, d_out, lead) in _weight_specs(config).items()
    }
    specs["embedding"] = (config.vocab_size, d)
    specs["wcls"] = (d, config.vocab_size)

    def gen(key):
        out = {}
        for name, shape in specs.items():
            key, k1 = jax.random.split(key)
            out[name] = (jax.random.normal(k1, shape, jnp.float32) * 0.02).astype(dtype)
        if config.n_experts > 0:
            key, kg = jax.random.split(key)
            out["moe_gate"] = jax.random.normal(
                kg, (L, d, config.n_experts), jnp.float32
            )
        return out

    t = jax.jit(gen)(jax.random.PRNGKey(seed))
    jax.block_until_ready(t)
    return _assemble_params(config, t, *_rope_cache(config))


def _resident_packed_params(config, seed: int = 0):
    """Device-resident PackedQ40 params by the cheapest route for the
    backend: on-chip generation on TPU (zero bulk host->device traffic —
    the tunnel is slow and fragile under load), host numpy + device_put on
    CPU (threefry on XLA:CPU is slower than one memcpy)."""
    import jax

    if jax.devices()[0].platform == "tpu":
        return _device_packed_params(config, seed)
    return jax.tree.map(jax.device_put, _random_packed_params(config, seed))


def _resident_dense_params(config, seed: int = 0, dtype=None):
    """Dense twin of _resident_packed_params (same backend dispatch)."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.models import params_from_random

    if dtype is None:
        dtype = jnp.bfloat16
    if jax.devices()[0].platform == "tpu":
        return _device_dense_params(config, seed, dtype)
    host = params_from_random(config, seed=seed, dtype=dtype, to_device=False)
    return jax.tree.map(jax.device_put, host)


def _tree_device_bytes(tree) -> int:
    import jax

    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def _param_matmul_flops_per_token(config) -> int:
    """2 * weight-params FLOPs/token (embedding lookup excluded, wcls
    included; MoE counts k active experts)."""
    d, h, kv = config.dim, config.hidden_dim, config.n_kv_heads * config.head_size
    ffn_mults = config.n_active_experts if config.n_experts > 0 else 1
    per_layer = d * d * 2 + d * kv * 2 + ffn_mults * 3 * d * h
    return 2 * (config.n_layers * per_layer + d * config.vocab_size)


def _bench_decode(config, params, n_short, n_long, reps=3, tag="",
                  start_pos=0, cache_dtype=None):
    """Marginal decode tok/s for one param set. ``start_pos``/``cache_dtype``
    parameterize the long-context phase (full-KV attention reads, f8 KV)
    without a second copy of the timing protocol."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_llama_multiusers_tpu.models import init_kv_cache, llama_forward

    kv_dtype = cache_dtype or jnp.bfloat16

    def make_generate(n_steps):
        @partial(jax.jit, donate_argnums=(1,))
        def generate(params, cache, first_token, start_pos):
            def body(carry, _):
                tok, pos, cache = carry
                logits, cache = llama_forward(
                    config, params, tok[:, None], pos[:, None], cache
                )
                nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
                return (nxt, pos + 1, cache), nxt

            (_, _, cache), toks = jax.lax.scan(
                body, (first_token, start_pos, cache), None, length=n_steps
            )
            return toks, cache

        return generate

    first = jnp.zeros((1,), jnp.int32)
    pos0 = jnp.full((1,), start_pos, jnp.int32)

    def timed(n_steps):
        gen = make_generate(n_steps)

        def run():
            cache = init_kv_cache(config, n_lanes=1, dtype=kv_dtype)
            t0 = time.perf_counter()
            toks, _ = gen(params, cache, first, pos0)
            np.asarray(toks)  # forces completion (block_until_ready may not)
            return time.perf_counter() - t0

        return _best_of_reps(run, reps)

    t_short = timed(n_short)
    t_long = timed(n_long)
    print(f"[bench] {tag}: short({n_short})={t_short:.3f}s long({n_long})={t_long:.3f}s",
          file=sys.stderr, flush=True)
    if t_long - t_short > 0.1 * t_long:
        return (n_long - n_short) / (t_long - t_short)
    # marginal signal below dispatch-overhead noise: conservative whole-run rate
    return n_long / t_long


class _BenchTokenizer:
    """Duck-typed tokenizer stub for the serving phase: the measurement is
    the engine + scheduler loop, not BPE. EOS id = vocab_size (never
    produced), so every request runs to max_tokens."""

    class _Vocab:  # TokenizerChatStops renders eos pieces from .vocab
        def __getitem__(self, i) -> bytes:
            return b"</s>"

    def __init__(self, vocab_size: int):
        self.vocab_size = vocab_size
        self.eos_token_ids = [vocab_size]
        self.chat_template = None
        self.bos_id = 1
        self.vocab = self._Vocab()

    def encode(self, text, add_bos=True, add_special_tokens=True):
        # long enough that the serving phase's identical prompts clear the
        # scheduler's prefix_min_tokens=16, so admissions 2..8 exercise
        # prefix caching in the measured number
        n = max(1, min(len(text), 48))
        return [(7 + i) % self.vocab_size for i in range(n)]

    def make_stream_decoder(self):
        return self

    def decode(self, token):  # stream-decoder protocol
        return "x"


def _best_of_reps(run, reps):
    """min-of-(reps+1) of run()'s self-reported seconds (first rep doubles
    as compile + warmup). run times its own measured segment so setup (e.g.
    allocating the donated KV cache) stays OUTSIDE the window, and must
    block on the device — np.asarray a result, since block_until_ready can
    lie through the device tunnel."""
    return min(run() for _ in range(reps + 1))


def _bench_prefill(config, params, t_prompt, reps=3, t_short=None):
    """(seconds for one t_prompt-token prefill, marginal tok/s).

    The single-call seconds (-> ttft_ms) is honest end-to-end latency and
    includes one host<->device round trip — through the axon tunnel that
    RTT (~40 ms) dominates, so the throughput number uses the MARGINAL
    rate between a long and a short prefill (same fixed costs, different
    token counts), the same trick the decode metric uses. Reference
    analogue: the Eval phase readout, src/dllama.cpp:36-55."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_llama_multiusers_tpu.models import init_kv_cache, llama_forward

    if t_short is None:
        t_short = max(16, t_prompt // 8)

    def timed(n_tok):
        @partial(jax.jit, donate_argnums=(1,))
        def prefill(params, cache, tokens, positions):
            logits, cache = llama_forward(config, params, tokens, positions, cache)
            return jnp.argmax(logits[:, -1, :], axis=-1), cache

        tokens = jnp.zeros((1, n_tok), jnp.int32)
        positions = jnp.arange(n_tok, dtype=jnp.int32)[None, :]

        def run():
            cache = init_kv_cache(config, n_lanes=1, dtype=jnp.bfloat16)
            t0 = time.perf_counter()
            nxt, _ = prefill(params, cache, tokens, positions)
            np.asarray(nxt)
            return time.perf_counter() - t0

        return _best_of_reps(run, reps)

    t_long_s = timed(t_prompt)
    marginal = None
    if t_short < t_prompt:
        t_short_s = timed(t_short)
        if t_long_s - t_short_s > 0.05 * t_long_s:
            marginal = (t_prompt - t_short) / (t_long_s - t_short_s)
    if marginal is None:
        # fixed costs dominate: whole-run rate (different semantics — the
        # caller records which method produced the number)
        return t_long_s, t_prompt / t_long_s, "whole_run"
    return t_long_s, marginal, "marginal"


def _phase_primary(config, platform, device_kind, small):
    import jax

    n_short, n_long = (4, 16) if small else (16, 128)
    t0 = time.perf_counter()
    params_q = _resident_packed_params(config)
    print(f"[bench] packed params resident in {time.perf_counter()-t0:.1f}s "
          f"({_tree_device_bytes(params_q)/1e9:.2f} GB)", file=sys.stderr, flush=True)

    tok_s = _bench_decode(config, params_q, n_short, n_long, tag="packed+pallas")
    # prefill is additive: a failure here must not discard the banked decode
    # number (the round-3 lesson: never lose the primary metric)
    t_prompt = 16 if small else 128
    prefill_extra = {}
    try:
        prefill_s, prefill_rate, rate_method = _bench_prefill(
            config, params_q, t_prompt
        )
        print(f"[bench] prefill({t_prompt})={prefill_s * 1e3:.1f} ms "
              f"({rate_method} {prefill_rate:.0f} tok/s)",
              file=sys.stderr, flush=True)
        prefill_extra = {
            "prefill_tok_s": round(prefill_rate, 1),
            "prefill_rate_method": rate_method,
            "ttft_ms": round(prefill_s * 1e3, 1),
        }
    except Exception as e:  # noqa: BLE001
        prefill_extra = {"prefill_error": f"{type(e).__name__}: {e}"[:200]}
    weight_bytes = _tree_device_bytes(params_q)
    peak_flops, peak_bw = _chip_spec(str(device_kind))
    flops_tok = _param_matmul_flops_per_token(config)
    return {
        "metric": METRIC,
        "value": round(tok_s, 2),
        "unit": "tok/s",
        # ratio only for TPU runs (a CPU-fallback 1B number vs the
        # reference's 7B-on-RPi invites misreading — round-4 weak #8); the
        # 8b phase overwrites this with the matched-model ratio when it
        # lands (see main)
        "vs_baseline": (
            round(tok_s / REFERENCE_SINGLE_DEVICE_TOK_S, 2)
            if platform == "tpu" else None
        ),
        "vs_baseline_model": (
            "llama32_1b (this) vs llama2_7b on 1x RPi 4B (reference)"
            if platform == "tpu" else None
        ),
        "platform": platform,
        "device_kind": str(device_kind),
        "weight_read_gb_s": round(weight_bytes * tok_s / 1e9, 1),
        "mfu": round(flops_tok * tok_s / peak_flops, 4) if peak_flops else None,
        "hbm_util": round(weight_bytes * tok_s / peak_bw, 3) if peak_bw else None,
        **prefill_extra,
        "baseline_note": "reference Llama-2-7B on 1x RPi 4B, 0.762 tok/s (report.pdf Fig.3)",
    }


def _serve_batch(config, params, n_lanes, max_tokens):
    """One warmup + one measured batch of n_lanes concurrent requests
    (half greedy, half sampled) through the real serving loop. Returns
    (tok/s, sorted step latencies, engine stats)."""
    import numpy as np

    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )

    engine = InferenceEngine(
        config, params, n_lanes=n_lanes, prefill_buckets=(16,)
    )

    step_times: list[float] = []

    def _timed(fn):
        def wrapper(*a, **k):
            t0 = time.perf_counter()
            out = fn(*a, **k)
            step_times.append(time.perf_counter() - t0)
            return out
        return wrapper

    # pipeline_consume is the pipelined path's per-step blocking point (the
    # dispatch half is async), so timing it is the step-latency analogue of
    # timing the synchronous decode call
    for name in ("decode", "decode_spec", "decode_multi", "pipeline_consume"):
        setattr(engine, name, _timed(getattr(engine, name)))

    tokenizer = _BenchTokenizer(config.vocab_size)
    sched = ContinuousBatchingScheduler(engine, tokenizer)

    def run_batch():
        reqs = [
            Request(
                prompt="benchmark " * 2,
                max_tokens=max_tokens,
                temperature=0.0 if i % 2 == 0 else 0.8,
                seed=100 + i,
            )
            for i in range(n_lanes)
        ]
        t0 = time.perf_counter()
        sched.start()
        try:
            for r in reqs:
                sched.submit(r)
            for r in reqs:
                r.future.result(timeout=600)
        finally:
            sched.stop()
        wall = time.perf_counter() - t0
        toks = sum(len(r.generated_tokens) for r in reqs)
        assert all(r.error is None for r in reqs), [r.error for r in reqs]
        return toks, wall

    run_batch()  # compile + warmup (prefill bucket + decode programs)
    step_times.clear()
    engine.stats.reset()  # spec counters must cover the measured batch only
    toks, wall = run_batch()
    _drained_report("serve_batch", sched)
    return toks / wall, np.sort(np.asarray(step_times)), engine.stats


def _drained_report(phase, sched, pre_pages=0):
    """``leaked_resources == 0`` beside ``compiles_after_warmup == 0``
    (ISSUE 17): after stop() the leak witness's drain snapshot
    (scheduler.leak_counts() — session mirrors, pending device ops, open
    journal marks, lane-held KV pages) must be all-zero, with
    ``pool_pages_in_use`` back at its pre-phase count. Asserted, not just
    reported: a phase that leaked measured a dirtier steady state than
    the number it banked claims."""
    counts = sched.leak_counts()
    leaked = {
        k: v for k, v in counts.items()
        if v != (pre_pages if k == "kv_lane_pages" else 0)
    }
    assert not leaked, (
        f"{phase}: resources still held after stop: {leaked} "
        "(rerun under DLLAMA_LEAKCHECK=1 to raise at the exact drain "
        "point; docs/LINT.md resource-balance names the static twin)"
    )
    return {f"{phase}_leaked_resources": 0}


def _phase_serving(config, small):
    """Aggregate multi-user throughput through the real serving loop:
    ContinuousBatchingScheduler + InferenceEngine, 8 concurrent requests
    (half greedy, half sampled), chunked prefill interleaving with decode.
    A second 32-lane batch measures throughput scaling: decode is
    weight-read-bound, so the shared weight pass amortizes over lanes
    (the multi-user fork's raison d'etre; HBM holds far more than 8
    lanes of KV)."""
    max_tokens = 12 if small else 48
    params = _resident_packed_params(config)
    tok_s, lat, stats = _serve_batch(config, params, 8, max_tokens)

    # 32-lane scaling batch: TPU only (the rationale — amortizing the HBM
    # weight pass over lanes — doesn't exist on the CPU smoke path, and a
    # 32-lane compile would eat the unattended window's budget)
    wide: dict = {}
    if not small:
        try:
            import gc

            gc.collect()  # the _timed wrappers cycle-trap the 8-lane
            # engine (engine.decode -> wrapper -> bound method -> engine);
            # its ~GB-scale cache must be freed before the 32-lane
            # engine allocates, not whenever the cycle GC gets around to it
            wide_tok_s, _, _ = _serve_batch(config, params, 32, max_tokens)
            wide = {"serving_tok_s_32lanes": round(wide_tok_s, 2)}
        except Exception as e:  # noqa: BLE001 - the 8-lane number survives
            wide = {"serving_32lanes_error": f"{type(e).__name__}: {e}"[:200]}

    return {
        "serving_tok_s_8lanes": round(tok_s, 2),
        **wide,
        "serving_step_ms_p50": round(float(lat[len(lat) // 2]) * 1e3, 2),
        "serving_step_ms_p95": round(float(lat[int(len(lat) * 0.95)]) * 1e3, 2),
        "serving_requests": 8,
        "serving_leaked_resources": 0,  # asserted in _serve_batch
        # speculation acceptance over the measured batch, per (DRAFTED
        # lane, verify-step): 1.0 = no draft accepted, K+1 = full
        # acceptance. Sampled/draft-less lanes are excluded from both
        # counters, so the ratio is undiluted acceptance.
        "serving_spec_steps": stats.spec_steps,
        "spec_tokens_per_lane_step": (
            round(stats.spec_emitted / stats.spec_lane_steps, 2)
            if stats.spec_lane_steps else None
        ),
        # the 8 requests share a prompt, so admissions 2..8 reuse lane KV
        # via prefix caching — the measured serving number includes it
        "prefix_hits": stats.prefix_hits,
        "prefix_tokens_saved": stats.prefix_tokens_saved,
        # multi-step horizons taken during the measured batch (each = up to
        # 8 decode steps in one dispatch; step_ms percentiles count a whole
        # horizon as one step, so read them alongside this)
        "multi_dispatches": stats.multi_dispatches,
        # async decode pipeline over the measured batch: fraction of engine
        # decode wall-time the lagged consume hid behind device execution
        # (0 = fully serialized, the pre-pipeline regime), dispatches taken
        # device-fed, and chains aborted before their lanes finished
        "serving_overlap_frac": (
            round(stats.overlap_s / (stats.overlap_s + stats.decode_s), 3)
            if (stats.overlap_s + stats.decode_s) > 0 else None
        ),
        "pipeline_dispatches": stats.pipeline_dispatches,
        "pipeline_flushes": stats.pipeline_flushes,
        # deterministic overlap evidence independent of backend timing
        # noise: a mocked-engine scheduler run (see _pipeline_microbench)
        **_pipeline_microbench_safe(),
    }


def _run_churn(sched, n_requests, max_tokens, interval_mean=0.05, seed=7):
    """Poisson-arrival churn against a STARTED-then-stopped scheduler:
    deterministic seeded arrivals, MIXED traffic — half greedy (their
    generated streams go repetitive on the tiny config, so the n-gram
    drafter genuinely hits), a quarter regular-nucleus sampled, and a
    quarter WIDE-nucleus sampled (top_p = 1.0 — the class that used to
    flush to the host-exact path and now samples on device with the
    exact full-vocab sampler). Returns (total generated tokens, wall
    seconds). Shared by the single-chip ``serving_churn`` phase and the
    mesh ``pod_serving`` phase so the two workloads cannot drift apart."""
    import numpy as np

    from distributed_llama_multiusers_tpu.runtime.scheduler import Request

    rng = np.random.default_rng(seed)
    intervals = rng.exponential(interval_mean, n_requests)
    reqs = [
        Request(
            prompt="churn benchmark prompt " * 2,
            max_tokens=max_tokens,
            temperature=0.0 if i % 2 == 0 else 0.8,
            topp=1.0 if i % 4 == 3 else 0.9,
            seed=200 + i,
        )
        for i in range(n_requests)
    ]
    sched.start()
    t0 = time.perf_counter()
    try:
        for r, dt in zip(reqs, intervals):
            time.sleep(dt)
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=600)
    finally:
        sched.stop()
    wall = time.perf_counter() - t0
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return sum(len(r.generated_tokens) for r in reqs), wall


def _phase_serving_churn(config, small):
    """Poisson-arrival churn against the REAL scheduler: requests join a
    live serving loop mid-generation (the regime the fused prefill+decode
    dispatch exists for) instead of arriving all up front like the
    `serving` phase's batch. ZERO-FLUSH configuration: speculation ON
    (drafts verify inside the pipelined chain) and wide-nucleus sampled
    lanes in the mix (on-device exact top-p — the old host-exact flush
    class), so `serving_churn_pipeline_flushes` must read 0: no
    systematic flush class is left except stop/drain. Reports aggregate
    `serving_churn_tok_s`, `spec_emitted_per_dispatch` (tokens per
    drafted-lane verify step, >1 = speculation composing with the
    chain), and TTFT/TBT percentiles read from the SAME telemetry
    histogram registry the server's /metrics serves — bench numbers and
    scraped metrics cannot drift, because they are the same counts. Also
    writes the span ring as a Perfetto-loadable Chrome trace artifact
    (BENCH_TRACE_PATH overrides the tmp-dir default) and reports its
    fused/spec slice counts — the visible form of "admissions and
    speculation rode the live chain". CPU-smoke safe: small lane/request
    counts, deterministic seeded arrivals."""
    import numpy as np

    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.runtime.engine import warmup_engine
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )
    from distributed_llama_multiusers_tpu.telemetry import Telemetry

    n_lanes = 4 if small else 8
    n_requests = 10 if small else 48
    max_tokens = 10 if small else 48
    params = _resident_packed_params(config)
    engine = InferenceEngine(
        config, params, n_lanes=n_lanes, prefill_buckets=(16,)
    )
    tokenizer = _BenchTokenizer(config.vocab_size)
    telemetry = Telemetry()
    # speculation ON: drafts verify INSIDE the pipelined chain (the
    # zero-flush tentpole) — the phase measures admission AND speculation
    # composing, not one at a time
    sched = ContinuousBatchingScheduler(
        engine, tokenizer, telemetry=telemetry
    )
    # compile everything (incl. the per-bucket fused family AND the spec
    # verify families) OUTSIDE the measured window: TTFT under churn must
    # not read as XLA compile time
    warmup_engine(engine, spec=True, multi_step=sched.multi_step)

    toks, wall = _run_churn(sched, n_requests, max_tokens)
    drained = _drained_report("serving_churn", sched)
    stats = engine.stats.snapshot()
    # compile-stability evidence (ISSUE 15): warmup armed the recompile
    # witness (analysis/jitcheck.py), so this is the MEASURED count of
    # XLA compiles the churn paid mid-serving. Assert, not just report:
    # a phase that recompiled measured warmup latency as serving tok/s,
    # and the artifact must not bank that silently.
    assert stats["jit_compiles_after_warmup"] == 0, (
        f"serving_churn recompiled {stats['jit_compiles_after_warmup']} "
        "program(s) after warmup — an unwarmed (family, bucket) is back "
        "(run the suite under DLLAMA_JITCHECK=1 for the guilty stack)"
    )

    # percentiles from the serving histogram registry (TTFT = submit ->
    # first consumed token, observed by the scheduler's telemetry hook)
    def pct_ms(hist, q):
        v = hist.quantile(q)
        return None if v is None else round(v * 1e3, 2)

    # the Perfetto artifact: lanes as tracks, fused/pipelined steps as
    # slices, admissions/finishes as instants
    import tempfile

    trace_path = os.environ.get("BENCH_TRACE_PATH") or os.path.join(
        tempfile.gettempdir(), "dllama_serving_churn_trace.json"
    )
    try:
        doc = telemetry.dump_trace(trace_path)
        slices = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        trace_extra = {
            "serving_churn_trace_path": trace_path,
            "serving_churn_trace_events": len(doc["traceEvents"]),
            "serving_churn_trace_fused_slices": sum(
                1 for e in slices if e["name"] == "step.fused"
            ),
            # the full composition made visible: verify steps that ALSO
            # carried an admission chunk (one dispatch, both jobs)
            "serving_churn_trace_spec_fused_slices": sum(
                1 for e in slices if e["name"] == "step.spec_fused"
            ),
            "serving_churn_trace_spec_slices": sum(
                1 for e in slices if e["name"] == "step.spec_pipelined"
            ),
            "serving_churn_trace_pipelined_slices": sum(
                1 for e in slices if e["name"] == "step.pipelined"
            ),
        }
    except OSError as e:  # artifact is evidence, not the headline
        trace_extra = {"serving_churn_trace_error": f"{type(e).__name__}: {e}"[:200]}

    return {
        "serving_churn_tok_s": round(toks / wall, 2),
        "serving_churn_requests": n_requests,
        "serving_churn_lanes": n_lanes,
        "serving_churn_ttft_ms_p50": pct_ms(telemetry.ttft, 0.5),
        "serving_churn_ttft_ms_p95": pct_ms(telemetry.ttft, 0.95),
        "serving_churn_tbt_ms_p50": pct_ms(telemetry.tbt, 0.5),
        "serving_churn_tbt_ms_p95": pct_ms(telemetry.tbt, 0.95),
        "serving_churn_queue_wait_ms_p95": pct_ms(telemetry.queue_wait, 0.95),
        # the headline churn evidence: admissions rode fused dispatches
        # and drafts rode spec-verify dispatches inside the live chain —
        # pipeline_flushes MUST read 0 (no systematic flush class remains)
        "serving_churn_pipeline_flushes": stats["pipeline_flushes"],
        "serving_churn_fused_steps": stats["fused_steps"],
        "serving_churn_pipeline_dispatches": stats["pipeline_dispatches"],
        # zero-flush speculation: verify steps dispatched in-chain, and
        # tokens consumed per DRAFTED-lane verify step (1.0 = drafts never
        # accepted, K+1 = full acceptance; > 1 means speculation's extra
        # tokens multiplied with the overlap instead of aborting it)
        "serving_churn_spec_pipelined_steps": stats["spec_pipelined_steps"],
        "serving_churn_spec_emitted_per_dispatch": (
            round(stats["spec_emitted"] / stats["spec_lane_steps"], 3)
            if stats["spec_lane_steps"] else None
        ),
        "serving_churn_spec_accept_hist": {
            str(k): v for k, v in sorted(stats["spec_accept_hist"].items())
        },
        # must read 0: the exact on-device sampler serves wide-nucleus
        # lanes; host_sampling=True is the only remaining host-exact path
        "serving_churn_host_exact_lanes": stats["host_exact_lanes"],
        "serving_churn_admission_stall_s": round(
            stats["admission_stall_s"], 4
        ),
        # compile stability alongside tok/s (evidence_loop.sh banks this
        # with every run): 0 = every program the churn dispatched was
        # compiled at warmup — the asserted invariant above
        "serving_churn_compiles_after_warmup": stats[
            "jit_compiles_after_warmup"
        ],
        "serving_churn_prefix_hits": stats["prefix_hits"],
        **drained,
        **trace_extra,
    }


def _phase_serving_prefix(config, small):
    """Paged KV + cross-request prefix sharing under a shared-system-
    prompt Poisson workload with SESSIONS > LANES (the oversubscription
    regime ROADMAP item 3 names): N sessions arrive Poisson against a
    paged engine (``--paged-kv on`` equivalent), every prompt opens with
    the same system prefix, and finished sessions PARK — their tree-
    registered pages stay resident (refcounted) so follow-up admissions
    share them copy-free. Reports the prefix hit rate, pages per
    resident session, shared admissions and the zero-copy subset that
    needed no single-page COW either (a paged engine refuses
    ``copy_lane`` outright, so lane-copy HBM traffic is zero by
    construction — ``serving_prefix_lane_copies`` counts actual
    ``copy_lane`` entries to show it measured, not asserted), and the
    park vs drop-rebuild TTFT pair: a parked follow-up served by
    refcount bump against the same prompt re-prefilled from scratch
    after ``drop_parked()`` (the LRU-eviction path an oversubscribed
    admission takes; determinism of the rebuild is pinned in
    tests/test_prefix_cache.py). ``pipeline_flushes`` must stay 0:
    paged indirection lives inside the step families, not beside them.
    CPU-smoke safe: small lane/session counts, deterministic arrivals."""
    import numpy as np

    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.runtime.engine import warmup_engine
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )
    from distributed_llama_multiusers_tpu.telemetry import Telemetry
    from distributed_llama_multiusers_tpu.utils.testing import (
        # prompt-DEPENDENT char-level encoding (shared text prefixes stay
        # shared token prefixes), one home with tests/test_prefix_cache.py
        CharStreamTokenizer,
    )

    n_lanes = 2 if small else 4
    n_sessions = 3 * n_lanes  # oversubscription: sessions >> lanes
    max_tokens = 8 if small else 32
    # long enough that the shared prefix spans several full pages — the
    # swap rung's TTFT delta scales with pages swapped back in, and a
    # one-page swap would drown in CPU-smoke scheduler jitter
    system = ("system: you are a terse and careful assistant. "
              "answer each user question briefly. ")
    params = _resident_packed_params(config)
    engine = InferenceEngine(
        config, params, n_lanes=n_lanes, prefill_buckets=(16,),
        paged_kv=True, kv_page_size=16,
        # host-RAM swap tier (runtime/kvpool.py HostTier): budget big
        # enough that every parked chain swaps rather than drops — the
        # phase measures all THREE residency tiers (park / swap / rebuild).
        # BENCH_KV_HOST_BYTES=0 is the evidence loop's A/B lever (swap
        # off -> the tier walk vanishes and swap_ttft degenerates to
        # rebuild, the pre-tier behavior)
        kv_host_bytes=int(os.environ.get("BENCH_KV_HOST_BYTES", 64 << 20)),
    )
    # MEASURE whole-lane HBM copy attempts instead of asserting zero:
    # every copy_lane entry (the contiguous path's prefix-reuse
    # primitive, the copy class this phase exists to show dying) is
    # counted BEFORE the call — a paged engine refuses copy_lane, so a
    # future change routing admissions back through a lane copy either
    # surfaces in this count (if the refusal were lifted) or fails the
    # phase loudly on the refusal; it can never read as a silent 0
    lane_copy_calls = 0
    _orig_copy_lane = engine.copy_lane

    def _counting_copy_lane(src, dst, prefix_len=None):
        nonlocal lane_copy_calls
        lane_copy_calls += 1
        return _orig_copy_lane(src, dst, prefix_len=prefix_len)

    engine.copy_lane = _counting_copy_lane
    # pre-phase lane-page occupancy: the drain check below asserts the
    # pool returns exactly here (parked pages are intentionally resident
    # and excluded from pool_pages_in_use by construction)
    pre_pages = engine.pool_stats().get("pool_pages_in_use", 0)
    tokenizer = CharStreamTokenizer(config.vocab_size, max_chars=96)
    telemetry = Telemetry()
    sched = ContinuousBatchingScheduler(engine, tokenizer,
                                        telemetry=telemetry)
    warmup_engine(engine, spec=True, multi_step=sched.multi_step)

    rng = np.random.default_rng(11)
    intervals = rng.exponential(0.05, n_sessions)
    reqs = [
        Request(prompt=system + f"user {i}: question {i}",
                max_tokens=max_tokens,
                temperature=0.0 if i % 2 == 0 else 0.8, seed=300 + i)
        for i in range(n_sessions)
    ]
    sched.start()
    t0 = time.perf_counter()
    try:
        for r, dt in zip(reqs, intervals):
            time.sleep(dt)
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=600)
        wall = time.perf_counter() - t0
        assert all(r.error is None for r in reqs), [r.error for r in reqs]
        toks = sum(len(r.generated_tokens) for r in reqs)
        pool_wave = engine.pool_stats()

        def ttft_one():
            r = Request(prompt=system + "user 0: question 0",
                        max_tokens=2, temperature=0.0)
            t = time.perf_counter()
            sched.submit(r)
            r.future.result(timeout=600)
            assert r.error is None, r.error
            return (time.perf_counter() - t) * 1e3

        def drop_all():
            # what LRU eviction does under an oversubscribed admission
            # when the host tier is full or disabled — drop the parked
            # chains AND clear the host tier (a served follow-up
            # re-parks, and its chain may still live in host RAM, so
            # without the clear a "rebuild" would quietly serve from
            # the tier)
            n = engine.kvpool.drop_parked()
            engine.kvpool.host_tier.clear()
            return n

        # the three residency rungs' TTFTs, measured INTERLEAVED as
        # min-of-N floors: the per-request cost differences (refcount
        # bump vs host->device swap-in vs full re-prefill) sit near the
        # scheduler's polling jitter on the CPU smoke, the MIN is the
        # jitter-free estimator of a deterministic cost, and the
        # round-robin ordering makes all three floors share the same
        # load drift instead of each eating a different slice of it.
        # Each rep re-establishes the state its request must hit: a
        # served follow-up re-parks, so the park rep is free, the swap
        # rep re-evicts to the tier, the rebuild rep drops everything
        park_ttft_ms = swap_ttft_ms = rebuild_ttft_ms = float("inf")
        swapped = dropped = 0
        for rep in range(15):
            # warm: served from PARKED pages by refcount bump (plus at
            # most one single-page COW)
            park_ttft_ms = min(park_ttft_ms, ttft_one())
            # swap: evict every parked chain into the host-RAM tier
            # (device gather -> sha256-framed host store, via the loop
            # thread — the gather must not race a dispatch that donates
            # the cache), then the same follow-up misses HBM, hits the
            # host tier, and swaps its prefix pages back in
            n = sched.run_device_op(lambda: engine.swap_out_parked())
            swapped = max(swapped, n)
            swap_ttft_ms = min(swap_ttft_ms, ttft_one())
            # rebuild: nothing resident anywhere — full re-prefill from
            # the prompt (the journal-rebuild cost class)
            dropped = max(dropped, drop_all())
            rebuild_ttft_ms = min(rebuild_ttft_ms, ttft_one())
        pool_swap = engine.pool_stats()
    finally:
        sched.stop()
    drained = _drained_report("serving_prefix", sched, pre_pages)
    stats = engine.stats.snapshot()
    pool = engine.pool_stats()
    # the swap gather/scatter programs were warmed (warmup_engine's
    # swap_in([0], swap_out([0])) round-trip), so even with the host
    # tier active the phase must run compile-free after warmup
    assert stats["jit_compiles_after_warmup"] == 0, (
        f"serving_prefix recompiled {stats['jit_compiles_after_warmup']} "
        "time(s) after warmup — the swap programs must be warmup-covered"
    )

    return {
        "serving_prefix_tok_s": round(toks / wall, 2),
        "serving_prefix_lanes": n_lanes,
        "serving_prefix_sessions": n_sessions,
        # resident sessions at the end of the wave: every finished
        # session parked (>= 2x lanes = the oversubscription headline)
        "serving_prefix_resident_sessions": pool_wave[
            "pool_parked_sessions"
        ],
        "serving_prefix_hit_rate": round(
            pool_wave["pool_prefix_admits"]
            / max(1, pool_wave["pool_admits"]), 3
        ),
        "serving_prefix_tokens_shared": pool_wave[
            "pool_prefix_tokens_shared"
        ],
        # shared-prefix admissions: full blocks by refcount bump on the
        # SAME physical pages, plus AT MOST one single-page COW at a
        # divergent block (the pool counts one cow_copy per such
        # admission, so shared - cow = the subset that needed no page
        # traffic at all). Both from the SAME end-of-wave snapshot, so
        # the subset can never read larger than its superset. Whole-lane
        # (copy_lane-class) copies are the class this layout kills —
        # measured via the call counter
        "serving_prefix_shared_admissions": pool_wave[
            "pool_prefix_admits"
        ],
        "serving_prefix_zero_copy_admissions": (
            pool_wave["pool_prefix_admits"] - pool_wave["pool_cow_copies"]
        ),
        "serving_prefix_lane_copies": lane_copy_calls,
        "serving_prefix_cow_copies": pool_wave["pool_cow_copies"],
        # HBM cost of a resident (parked) session, in pages: parked
        # pages are DISTINCT physical pages (shared pages count once),
        # so LOWER = sessions overlap more — pure-private sessions
        # would each pay their full ceil((prompt+gen)/page)
        "serving_prefix_pages_per_session": round(
            pool_wave["pool_parked_pages"]
            / max(1, pool_wave["pool_parked_sessions"]), 2
        ),
        "serving_prefix_pool_pages_total": pool["pool_pages_total"],
        "serving_prefix_park_ttft_ms": round(park_ttft_ms, 2),
        # the middle residency rung: same follow-up served by host-tier
        # swap-in — dearer than a refcount bump (park), cheaper than a
        # full re-prefill (rebuild); the three TTFTs together are the
        # tiered-residency headline
        "serving_prefix_swap_ttft_ms": round(swap_ttft_ms, 2),
        "serving_prefix_swapped_sessions": swapped,
        "serving_prefix_swap_outs": pool_swap["swap_outs"],
        "serving_prefix_swap_ins": pool_swap["swap_ins"],
        "serving_prefix_swap_out_bytes": pool_swap["swap_out_bytes"],
        "serving_prefix_swap_in_bytes": pool_swap["swap_in_bytes"],
        "serving_prefix_swap_in_ms": pool_swap["swap_in_ms"],
        "serving_prefix_host_hit_rate": round(
            pool_swap["pool_host_hits"]
            / max(1, pool_swap["pool_host_hits"]
                  + pool_swap["pool_host_misses"]), 3
        ),
        "serving_prefix_dropped_sessions": dropped,
        "serving_prefix_rebuild_ttft_ms": round(rebuild_ttft_ms, 2),
        "serving_prefix_parked_evicted": pool["pool_parked_evicted"],
        "serving_prefix_exhausted_sheds": pool["pool_exhausted_sheds"],
        "serving_prefix_ttft_ms_p50": (
            None if telemetry.ttft.quantile(0.5) is None
            else round(telemetry.ttft.quantile(0.5) * 1e3, 2)
        ),
        "serving_prefix_ttft_ms_p95": (
            None if telemetry.ttft.quantile(0.95) is None
            else round(telemetry.ttft.quantile(0.95) * 1e3, 2)
        ),
        "serving_prefix_pipeline_flushes": stats["pipeline_flushes"],
        "serving_prefix_compiles_after_warmup": stats[
            "jit_compiles_after_warmup"
        ],
        "serving_prefix_prefix_hits": stats["prefix_hits"],
        "serving_prefix_prefix_tokens_saved": stats["prefix_tokens_saved"],
        **drained,
    }


def _phase_pod_serving(config, small):
    """Pod-native serving: the churn workload (the `serving_churn` phase's
    exact arrival process) on a pure-TP mesh(tp=N) with the Q40 planes
    TP-sharded — each chip reads 1/N of the weights per token, the explicit
    route past the single-chip HBM roofline (BASELINE.md: ~182 tok/s
    theoretical, 200 tok/s/chip north star needs the pod). The engine is
    mesh-native end to end: sharded KV (cache_shardings), replicated token
    carry, pipelined + fused-admission dispatches, and the TP activation
    sync ring-overlapped with the dequant matmul (DLLAMA_RING_SYNC;
    ops/ring_collective.py). Honors DLLAMA_DEQUANT so the in-bench kernel
    sweep can bank the kernel A/B and the pod number in one unattended
    pass. Reports `pod_serving_tok_s_per_chip` against the 200 north star
    plus the measured per-step sync split (engine.measured_sync_stats).

    Off-TPU (CPU smoke) the mesh is the 8-virtual-device test mesh; with a
    single real chip tp degenerates to 1 (the mesh-native path still runs
    — dispatch under GSPMD — but the sync is trivial and the per-chip
    number equals the aggregate)."""
    import jax

    from distributed_llama_multiusers_tpu.ops.ring_collective import (
        ring_sync_enabled,
    )
    from distributed_llama_multiusers_tpu.parallel import (
        MeshPlan,
        make_mesh,
        validate_mesh_for_config,
    )
    from distributed_llama_multiusers_tpu.parallel.sharding import shard_params
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.runtime.engine import warmup_engine
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
    )
    from distributed_llama_multiusers_tpu.telemetry import Telemetry

    n_dev = len(jax.devices())
    # largest valid pure-TP width, by the validator itself (the single
    # source of truth for mesh constraints — a new rule there must degrade
    # this phase to a smaller tp, not crash it)
    tp, plan = 1, MeshPlan(tp=1)
    for cand in range(min(n_dev, config.n_kv_heads), 0, -1):
        try:
            validate_mesh_for_config(config, MeshPlan(tp=cand))
        except ValueError:
            continue
        tp, plan = cand, MeshPlan(tp=cand)
        break
    mesh = make_mesh(plan)
    print(f"[bench] pod_serving: mesh(tp={tp}) over {n_dev} device(s), "
          f"ring_sync={'on' if ring_sync_enabled() else 'off'}",
          file=sys.stderr, flush=True)

    if jax.devices()[0].platform == "tpu":
        params = shard_params(_device_packed_params(config), mesh)
    else:
        params = shard_params(_random_packed_params(config), mesh)

    n_lanes = 4 if small else 8
    n_requests = 10 if small else 48
    max_tokens = 10 if small else 48
    engine = InferenceEngine(
        config, params, n_lanes=n_lanes, prefill_buckets=(16,), mesh=mesh
    )
    tokenizer = _BenchTokenizer(config.vocab_size)
    telemetry = Telemetry()
    sched = ContinuousBatchingScheduler(
        engine, tokenizer, speculative=False, telemetry=telemetry
    )
    # compiles every sharded program family per bucket (and AOT-compiles
    # the decode step for the collective byte estimate) OUTSIDE the window
    warmup_engine(engine, spec=False, multi_step=sched.multi_step)
    coll = engine.collective_stats()

    toks, wall = _run_churn(sched, n_requests, max_tokens)
    drained = _drained_report("pod_serving", sched)
    # snapshot BEFORE the sync probe below: the probe is diagnostics and
    # must not blur the serving window's compile-stability evidence
    stats = engine.stats.snapshot()
    # the pod twin of serving_churn's compile-stability gate: a recompile
    # on a mesh stalls EVERY chip of the pod mid-serving, and a phase
    # that recompiled banked warmup latency as tok/s/chip (the number
    # ROADMAP item 2 spends real v5e-8 time on)
    assert stats["jit_compiles_after_warmup"] == 0, (
        f"pod_serving recompiled {stats['jit_compiles_after_warmup']} "
        "program(s) after warmup — an unwarmed mesh family is back"
    )

    # measured per-step sync split (profiler probe; rewrites cache slot 0,
    # safe after the workload) — fed into the telemetry histogram so the
    # bench numbers and a pod's scraped dllama_sync_seconds reconcile
    probe_steps = 4
    sync = engine.measured_sync_stats(steps=probe_steps)
    telemetry.observe_sync_probe(sync, steps=probe_steps)

    def pct_ms(hist, q):
        v = hist.quantile(q)
        return None if v is None else round(v * 1e3, 2)

    tok_s = toks / wall
    return {
        "pod_serving_tok_s": round(tok_s, 2),
        "pod_serving_tok_s_per_chip": round(tok_s / tp, 2),
        "pod_serving_northstar_frac": round(tok_s / tp / 200.0, 4),
        "pod_serving_mesh_tp": tp,
        "pod_serving_devices": n_dev,
        "pod_serving_ring_sync": ring_sync_enabled(),
        "pod_serving_requests": n_requests,
        "pod_serving_lanes": n_lanes,
        "pod_serving_ttft_ms_p50": pct_ms(telemetry.ttft, 0.5),
        "pod_serving_ttft_ms_p95": pct_ms(telemetry.ttft, 0.95),
        "pod_serving_tbt_ms_p50": pct_ms(telemetry.tbt, 0.5),
        # the mesh-native async chain held under churn: admissions rode
        # fused dispatches, zero aborts
        "pod_serving_pipeline_flushes": stats["pipeline_flushes"],
        "pod_serving_fused_steps": stats["fused_steps"],
        "pod_serving_pipeline_dispatches": stats["pipeline_dispatches"],
        # compile stability over the measured window (asserted 0 above)
        "pod_serving_compiles_after_warmup": stats[
            "jit_compiles_after_warmup"
        ],
        # static per-step collective payload (post-SPMD HLO) + measured split
        "pod_serving_sync_bytes_per_decode": coll.get("total_bytes", 0),
        "pod_serving_sync_collectives_per_decode": coll.get("n_collectives", 0),
        "pod_serving_sync_bytes_total": stats["sync_bytes_total"],
        "pod_serving_step_ms": sync.get("step_ms"),
        "pod_serving_sync_ms": sync.get("sync_ms"),
        "pod_serving_sync_frac": sync.get("sync_frac"),
        "pod_serving_sync_source": sync.get("source"),
        **drained,
    }


def _phase_serving_faults(config, small):
    """Chaos gate as a bench phase (failure containment, ISSUE 8): the
    churn arrival process with a DETERMINISTIC engine fault injected
    mid-run (utils/faults.py; `DLLAMA_FAULTS` overrides the default
    one-shot dispatch fault). Reports what the containment layer is FOR:

    - error rate — how many requests the one engine fault actually cost
      (only the lanes occupied at the failure instant, finish_reason
      "error", request_id-carrying failures);
    - hang-free — every submitted future RESOLVED (the pre-containment
      failure mode was a dead loop thread with every client blocked);
    - recovery — the circuit breaker re-closed after the fault
      (`serving_faults_recovery_ms` = how long the circuit held open),
      and the loop kept serving: requests after the fault completed
      normally with the ring drained."""
    from concurrent.futures import TimeoutError as FuturesTimeout

    import numpy as np

    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.runtime.engine import warmup_engine
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )
    from distributed_llama_multiusers_tpu.serving import (
        AdmissionRejected,
        CircuitBreaker,
    )
    from distributed_llama_multiusers_tpu.telemetry import Telemetry
    from distributed_llama_multiusers_tpu.utils import faults

    n_lanes = 2 if small else 4
    n_requests = 10 if small else 24
    max_tokens = 8 if small else 24
    params = _resident_packed_params(config)
    engine = InferenceEngine(
        config, params, n_lanes=n_lanes, prefill_buckets=(16,)
    )
    telemetry = Telemetry()
    breaker = CircuitBreaker(threshold=1, cooldown_s=0.5)
    # threshold 1: the single default fault also walks the breaker through
    # open -> (cooldown) -> recovery, so the phase banks a recovery time
    sched = ContinuousBatchingScheduler(
        engine, _BenchTokenizer(config.vocab_size), speculative=False,
        telemetry=telemetry, breaker=breaker,
    )
    # compile OUTSIDE the armed window: warmup dispatches must not
    # advance the fault plan's arrival counters
    warmup_engine(engine, spec=False, multi_step=sched.multi_step)
    spec = os.environ.get("DLLAMA_FAULTS", "engine.dispatch:@20:n=1")
    plan = faults.arm(spec)

    rng = np.random.default_rng(11)
    intervals = rng.exponential(0.05, n_requests)
    reqs = [
        Request(
            prompt="chaos benchmark prompt " * 2,
            max_tokens=max_tokens,
            temperature=0.0 if i % 2 == 0 else 0.8,
            seed=300 + i,
        )
        for i in range(n_requests)
    ]
    submitted, shed = [], 0
    hang_free = True
    sched.start()
    t0 = time.perf_counter()
    try:
        for r, dt in zip(reqs, intervals):
            time.sleep(dt)
            try:
                sched.submit(r)
                submitted.append(r)
            except AdmissionRejected:
                shed += 1  # open circuit mid-churn: shed is correct behavior
        for r in submitted:
            try:
                r.future.result(timeout=300)
            except FuturesTimeout:
                hang_free = False  # THE failure containment exists to prevent
                r.cancel()
            except Exception:  # noqa: BLE001 — failed requests are the point
                pass
        # recovery: if the circuit is still open (fault landed late), give
        # it a cooldown and drive one probe request through
        probes = 0
        deadline = time.monotonic() + 10
        while breaker.state != "closed" and time.monotonic() < deadline:
            time.sleep(0.55)
            probe = Request(prompt="probe", max_tokens=2, temperature=0.0)
            try:
                sched.submit(probe)
                probes += 1
                probe.future.result(timeout=60)
            except Exception:  # noqa: BLE001 — the state read below decides
                pass
        wall = time.perf_counter() - t0
    finally:
        faults.disarm()
        sched.stop()
    # the chaos twin of ring-drained: even with a fault mid-dispatch,
    # containment released every mirror/page/op the failed lanes held
    drained = _drained_report("serving_faults", sched)

    outcomes: dict[str, int] = {}
    for r in submitted:
        outcomes[str(r.finish_reason)] = outcomes.get(str(r.finish_reason), 0) + 1
    n_err = outcomes.get("error", 0)
    br = breaker.stats()
    qos = sched.qos_stats()
    rec_ms = (
        None if br["breaker_last_recovery_s"] is None
        else round(br["breaker_last_recovery_s"] * 1e3, 1)
    )
    return {
        "serving_faults_spec": spec,
        "serving_faults_fired": len(plan.fired_log()),
        "serving_faults_requests": n_requests,
        "serving_faults_submitted": len(submitted),
        "serving_faults_shed": shed,
        "serving_faults_errors": n_err,
        "serving_faults_error_rate": round(n_err / max(1, len(submitted)), 4),
        "serving_faults_finish_reasons": outcomes,
        # the three headline properties of the chaos gate:
        "serving_faults_hang_free": hang_free,
        "serving_faults_recovered": breaker.state == "closed",
        "serving_faults_recovery_ms": rec_ms,
        "serving_faults_probes": probes,
        "serving_faults_engine_failure_rounds": qos["engine_failure_rounds"],
        "serving_faults_breaker_trips": br["breaker_trips"],
        "serving_faults_ring_drained": engine.pipeline_inflight() == 0,
        "serving_faults_wall_s": round(wall, 2),
        **drained,
    }


def _phase_serving_recovery(config, small):
    """Crash-durability gate as a bench phase (ISSUE 10): the churn
    arrival process with the JOURNAL on, a simulated process death
    mid-stream, and a ``--recover-journal``-style restart. Reports what
    the recovery layer is FOR:

    - resume-latency-ms — recovery start -> first RESUMED delta reaching
      a reattached client (the "latency blip" claim, measured);
    - lost tokens (MUST be 0) — reference-stream tokens a client that
      reconnected with its Last-Event-ID never saw;
    - duplicate tokens (MUST be 0) — tokens delivered twice across the
      kill.

    The kill is a journal detach + abrupt stop, NOT an injected engine
    fault: PR 8's containment layer CATCHES injected faults and journals
    a finish (finish_reason="error") — by design, a contained failure is
    final. Only a real process death leaves admit records without
    finishes, so that is what the phase models (the same crash image a
    watchdog ``os._exit(17)`` or an OOM kill leaves behind)."""
    import numpy as np

    from distributed_llama_multiusers_tpu.runtime import InferenceEngine
    from distributed_llama_multiusers_tpu.runtime.engine import warmup_engine
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )
    from distributed_llama_multiusers_tpu.serving import (
        RequestJournal,
        StreamRegistry,
        read_journal,
        recover_scheduler,
    )
    from distributed_llama_multiusers_tpu.telemetry import Telemetry

    n_lanes = 2 if small else 4
    n_requests = n_lanes  # all lanes mid-flight at the kill
    max_tokens = 24 if small else 64

    class _RecoveryTokenizer(_BenchTokenizer):
        """Per-token distinct text + prompt-dependent encoding, so
        byte-identity across the kill is a REAL assertion (the base
        bench tokenizer decodes every token to "x")."""

        def encode(self, text, add_bos=True, add_special_tokens=True):
            h = sum(ord(c) * (i + 1) for i, c in enumerate(text))
            return [(h + 5 * i) % self.vocab_size for i in range(24)]

        def decode(self, token):
            return f"[{token}]"

    def make_sched(journal):
        params = _resident_packed_params(config)
        engine = InferenceEngine(
            config, params, n_lanes=n_lanes, prefill_buckets=(16,)
        )
        sched = ContinuousBatchingScheduler(
            engine, _RecoveryTokenizer(config.vocab_size),
            speculative=False, prefix_min_tokens=0, telemetry=Telemetry(),
            journal=journal,
        )
        warmup_engine(engine, spec=False, multi_step=sched.multi_step)
        return sched

    def make_reqs():
        return [
            Request(
                prompt=f"recovery benchmark prompt {i}",
                max_tokens=max_tokens,
                temperature=0.0 if i % 2 == 0 else 0.8,
                seed=400 + i,
            )
            for i in range(n_requests)
        ]

    # -- reference: the uninterrupted streams --------------------------------
    sched = make_sched(None)
    refs = make_reqs()
    ref_streams: dict[int, list] = {i: [] for i in range(n_requests)}

    def ref_cb(i, rq):
        return lambda d: ref_streams[i].append(
            (len(rq.generated_tokens), d)
        )

    sched.start()
    for i, rq in enumerate(refs):
        rq.on_delta = ref_cb(i, rq)
        sched.submit(rq)
    for rq in refs:
        rq.future.result(timeout=300)
    sched.stop()
    _drained_report("serving_recovery_ref", sched)

    # -- crash run: journal on, die mid-stream -------------------------------
    journal_path = os.path.join(
        tempfile.mkdtemp(prefix="dllama_recovery_"), "journal.bin"
    )
    journal = RequestJournal(journal_path, progress_every=2, fsync=False)
    sched = make_sched(journal)
    crash = make_reqs()
    pre: dict[int, list] = {i: [] for i in range(n_requests)}
    delivered = {i: 0 for i in range(n_requests)}

    def crash_cb(i, rq):
        def cb(d):
            pre[i].append((len(rq.generated_tokens), d))
            delivered[i] = len(rq.generated_tokens)
            journal.note_progress(rq.id, delivered[i])
        return cb

    rng = np.random.default_rng(17)
    intervals = rng.exponential(0.02, n_requests)
    sched.start()
    for (i, rq), dt in zip(enumerate(crash), intervals):
        time.sleep(dt)
        rq.on_delta = crash_cb(i, rq)
        sched.submit(rq)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and any(
        len(v) < 4 for v in pre.values()
    ):
        time.sleep(0.005)
    # the kill: nothing after this instant reaches the journal — the
    # stop() below stands in for the process dying with lanes mid-decode
    sched.journal = None
    journal.flush()
    journal.close()
    sched.stop()
    # the crash image's open marks live in the DETACHED journal (the
    # whole point); the scheduler's own resources must still settle —
    # stop() is a clean shutdown standing in for the process dying
    _drained_report("serving_recovery_crash", sched)
    pre_tokens = sum(len(v) for v in pre.values())
    incomplete = read_journal(journal_path).incomplete()

    # -- restart + recovery --------------------------------------------------
    registry = StreamRegistry(grace_s=60.0)
    sched = make_sched(None)
    sched.start()
    t_recover = time.perf_counter()
    coordinator = recover_scheduler(sched, journal_path, registry=registry)
    first_delta_at: dict[int, float] = {}
    resumed: dict[int, list] = {}

    def reattach(i, rid, last):
        got = registry.attach(rid)
        if got is None:
            return
        _rq, relay, _kind, gen = got
        out = []
        while True:
            item = relay.next_after(last, timeout=120, gen=gen)
            if item is None:
                break
            if item[0] == "delta":
                if i not in first_delta_at:
                    first_delta_at[i] = time.perf_counter()
                _, last, text = item
                out.append((last, text))
            elif item[0] == "done":
                break
            else:
                break  # gap/superseded: recorded via lost-token count
        resumed[i] = out

    coordinator.join(240)
    by_id = {rq.id: i for i, rq in enumerate(crash)}
    threads = [
        threading.Thread(
            target=reattach, args=(by_id[e.request_id], e.request_id,
                                   delivered[by_id[e.request_id]]),
        )
        for e in incomplete
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    sched.stop()
    registry.close()
    drained = _drained_report("serving_recovery", sched)

    # -- reconcile: the client view vs the uninterrupted streams -------------
    lost = dup = 0
    identical = True
    resume_ms = []
    for i in range(n_requests):
        view = pre[i] + resumed.get(i, [])
        seen: dict[int, str] = {}
        for idx, text in view:
            if idx in seen:
                dup += 1
            seen[idx] = text
        ref = dict(ref_streams[i])
        lost += sum(1 for idx in ref if idx not in seen)
        if "".join(t for _, t in sorted(seen.items())) != "".join(
            t for _, t in sorted(ref.items())
        ):
            identical = False
        if i in first_delta_at:
            resume_ms.append((first_delta_at[i] - t_recover) * 1e3)
    rec = coordinator.stats()
    jstats = read_journal(journal_path)
    return {
        "serving_recovery_requests": n_requests,
        "serving_recovery_killed_inflight": len(incomplete),
        "serving_recovery_pre_kill_tokens": pre_tokens,
        "serving_recovery_recovered_requests": rec["recovered_requests"],
        "serving_recovery_replayed_tokens": rec["recovery_replayed_tokens"],
        # the three headline properties of the recovery gate:
        "serving_recovery_resume_latency_ms": (
            round(min(resume_ms), 1) if resume_ms else None
        ),
        "serving_recovery_lost_tokens": lost,
        "serving_recovery_duplicate_tokens": dup,
        "serving_recovery_byte_identical": identical,
        "serving_recovery_journal_records": jstats.records,
        "serving_recovery_journal_torn_tail": jstats.torn,
        **drained,
    }


def _phase_serving_structured(config, small):
    """The structured-output gate (ISSUE 13): Poisson churn with a
    JSON-schema workload MIXED into plain lanes against the real
    scheduler — constrained (json_object + json_schema, greedy and
    sampled) and unconstrained requests share the fused pipelined chain.
    Reports:

    - ``structured_valid_json_rate`` — fraction of constrained
      completions that parse as (schema-valid) JSON: MUST be 1.0, the
      on-device mask is the whole point;
    - ``structured_schema_compile_ms`` — cold automaton compile cost
      (token closure over the vocab) and the cached re-admission cost;
    - ``structured_masked_steps_per_dispatch`` — how often the mask
      actually bit, over all pipeline dispatches;
    - ``structured_pipeline_flushes`` — MUST be 0: constrained lanes
      ride the zero-flush chain like everyone else;
    - ``structured_replay_identical`` — a constrained stream killed
      mid-flight replays byte-identically through journal recovery
      (the crash-durability contract extended to grammars).

    Mock-backed on purpose (content_keyed determinism class): the phase
    measures the GRAMMAR layer — compile cost, mask cadence, validity,
    replay — not kernel speed, and runs identically on any host."""
    import tempfile

    import numpy as np

    from distributed_llama_multiusers_tpu.grammar.automaton import (
        _cache as _gram_cache,
    )
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )
    from distributed_llama_multiusers_tpu.serving import (
        RequestJournal,
        read_journal,
    )
    from distributed_llama_multiusers_tpu.utils.testing import (
        ByteJsonTokenizer,
        MockAsyncEngine,
    )

    schema = {
        "type": "object",
        "properties": {
            "name": {"type": "string"},
            "score": {"type": "integer"},
            "tags": {"type": "array", "items": {"type": "string"}},
            "verdict": {"enum": ["pass", "fail", None]},
        },
        "required": ["name", "verdict"],
    }
    schema_rf = {"type": "json_schema", "json_schema": {"schema": schema}}
    n_requests = 12 if small else 48
    n_lanes = 4 if small else 8

    def build():
        tok = ByteJsonTokenizer()
        eng = MockAsyncEngine(
            n_lanes=n_lanes, vocab=258, speculative=True,
            content_keyed=True,
        )
        eng.grammar_init(tok.token_table(), tok.eos_token_ids)
        return tok, eng

    # cold vs cached schema compile (the per-admission cost ladder)
    tok0, eng0 = build()
    _gram_cache.clear()
    t0 = time.perf_counter()
    h0 = eng0.grammar_attach(schema_rf)
    compile_cold_ms = (time.perf_counter() - t0) * 1e3
    eng0.grammar_detach(h0.key)
    t0 = time.perf_counter()
    eng0.grammar_attach(schema_rf)  # cache hit + parked-slab re-attach
    compile_cached_ms = (time.perf_counter() - t0) * 1e3

    tok, engine = build()
    sched = ContinuousBatchingScheduler(engine, tok, prefix_min_tokens=0)
    rng = np.random.default_rng(7)
    reqs = [
        Request(
            prompt=f"structured churn {i}",
            max_tokens=800,
            temperature=0.0 if i % 2 == 0 else 0.7,
            seed=300 + i,
            response_format=[
                schema_rf, None, {"type": "json_object"}, None
            ][i % 4],
        )
        for i in range(n_requests)
    ]
    sched.start()
    t0 = time.perf_counter()
    try:
        for r, dt in zip(reqs, rng.exponential(0.01, n_requests)):
            time.sleep(dt)
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=600)
    finally:
        sched.stop()
    drained = _drained_report("serving_structured", sched)
    wall = time.perf_counter() - t0
    assert all(r.error is None for r in reqs), [r.error for r in reqs]

    constrained = [r for r in reqs if r.response_format is not None]
    valid = 0
    for r in constrained:
        try:
            obj = json.loads(r.generated_text)
        except ValueError:
            continue
        if r.response_format is schema_rf:
            if (
                isinstance(obj, dict)
                and {"name", "verdict"} <= set(obj)
                and set(obj) <= {"name", "score", "tags", "verdict"}
                and obj["verdict"] in ("pass", "fail", None)
            ):
                valid += 1
        elif isinstance(obj, dict):
            valid += 1
    stats = engine.stats.snapshot()

    # kill-and-replay: journal a constrained stream, cancel it mid-
    # flight (the crash stand-in), regenerate from the journaled
    # (prompt, seed, schema) on a FRESH scheduler — byte-identical
    tokr, engr = build()
    ref_sched = ContinuousBatchingScheduler(engr, tokr, prefix_min_tokens=0)
    ref_sched.start()
    try:
        ref = ref_sched.submit(Request(
            prompt="replay probe", max_tokens=800, seed=99,
            response_format=schema_rf,
        ))
        ref_text = ref.future.result(timeout=120)
    finally:
        ref_sched.stop()
    jpath = os.path.join(
        tempfile.gettempdir(), "dllama_structured_bench_journal.bin"
    )
    if os.path.exists(jpath):
        os.unlink(jpath)
    journal = RequestJournal(jpath, progress_every=1, fsync=False)
    tokc, engc = build()
    crash_sched = ContinuousBatchingScheduler(
        engc, tokc, prefix_min_tokens=0, journal=journal
    )
    crash_sched.start()
    try:
        crash = crash_sched.submit(Request(
            prompt="replay probe", max_tokens=800, seed=99,
            response_format=schema_rf,
        ))
        while not crash.generated_tokens:
            time.sleep(0.001)
        journal.flush()
        img = read_journal(jpath)
    finally:
        crash_sched.stop()
        journal.close()
    tok2, eng2 = build()
    sched2 = ContinuousBatchingScheduler(eng2, tok2, prefix_min_tokens=0)
    sched2.start()
    try:
        re_req = sched2.build_recovered_request(img.entries[crash.id])
        sched2.submit(re_req)
        replayed = re_req.future.result(timeout=120)
    finally:
        sched2.stop()
    # all three replay schedulers drain clean too — the crash stand-in's
    # force-cancel journals its finish, so even ITS marks close
    for tag, s in (("ref", ref_sched), ("crash", crash_sched),
                   ("replay", sched2)):
        _drained_report(f"serving_structured_{tag}", s)

    return {
        "phase": "serving_structured",
        "structured_requests": n_requests,
        "structured_constrained": len(constrained),
        "structured_valid_json_rate": round(valid / len(constrained), 4),
        "structured_tok_s": round(
            sum(len(r.generated_tokens) for r in reqs) / wall, 2
        ),
        "structured_schema_compile_ms": round(compile_cold_ms, 2),
        "structured_schema_compile_cached_ms": round(compile_cached_ms, 3),
        "structured_masked_steps_per_dispatch": round(
            stats["grammar_masked_steps"]
            / max(1, stats["pipeline_dispatches"]), 3
        ),
        "structured_grammar_lanes": stats["grammar_lanes"],
        "structured_pipeline_flushes": stats["pipeline_flushes"],
        "structured_fused_steps": stats["fused_steps"],
        "structured_spec_pipelined_steps": stats["spec_pipelined_steps"],
        "structured_replay_identical": bool(
            replayed == ref_text and json.loads(replayed)
        ),
        **drained,
    }


def _phase_serving_fleet(config, small):
    """The fleet gate (ISSUE 12): Poisson SSE traffic through the
    ``dllama-router`` at THREE MockAsyncEngine-backed replicas while one
    replica is SIGTERM-drained and another is KILLED mid-run — the
    measured "millions of users" curve ROADMAP item 4 asks for. Reports:

    - TTFT / TBT percentiles THROUGH the router (the routing + proxy
      overhead is in the number);
    - shed rate (client-visible give-ups; the zero-requests-shed claim:
      must be 0 — replica sheds are retried or migrated, never passed
      through);
    - affinity hit rate (streams landing on their consistent-hash ring
      owner — the prefix-warmth multiplier);
    - migration count + latency (stream break -> first resumed byte),
      and the loss ledger: every completed stream byte-identical to its
      oracle run, 0 lost / 0 duplicated.

    Mock-backed on purpose (the same content_keyed determinism class the
    recovery bench and chaos tests pin): the phase measures the FLEET
    layer — routing, shed handling, migration — not kernel speed, and
    runs identically on any host."""
    import numpy as np

    from distributed_llama_multiusers_tpu.fleet import FleetRouter
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
    )
    from distributed_llama_multiusers_tpu.serving import StreamRegistry
    from distributed_llama_multiusers_tpu.server import ApiServer
    from distributed_llama_multiusers_tpu.tokenizer import TemplateType
    from distributed_llama_multiusers_tpu.utils.testing import (
        CharStreamTokenizer,
        MockAsyncEngine,
    )
    import json as _json
    import urllib.request

    class _FleetTokenizer(CharStreamTokenizer):
        def decode(self, token):
            return f"[{token}]"

    n_lanes = 2 if small else 4
    n_requests = 12 if small else 32
    max_tokens = 24 if small else 40
    step_s = 0.004

    def make_replica(rid):
        engine = MockAsyncEngine(n_lanes=n_lanes, max_chunk=8,
                                 content_keyed=True, step_s=step_s)
        sched = ContinuousBatchingScheduler(
            engine, _FleetTokenizer(64, max_chars=24),
            speculative=False, prefix_min_tokens=0, multi_step=0,
        )
        sched.start()
        registry = StreamRegistry(grace_s=60.0)
        api = ApiServer(sched, _FleetTokenizer(64, max_chars=24),
                        model_name="fleet",
                        template_type=TemplateType.LLAMA2,
                        resume=registry, replica_id=rid)
        httpd = api.serve(host="127.0.0.1", port=0)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return {"rid": rid, "sched": sched, "registry": registry,
                "httpd": httpd,
                "base": f"127.0.0.1:{httpd.server_address[1]}"}

    replicas = [make_replica(f"r{i}") for i in range(3)]
    # the 1000-char threshold makes every 4th prompt classify "long"
    # (below): with NO prefill-role replica in this fleet the long class
    # routes monolithic — exercising the disagg policy's fallback under
    # churn — and the TTFT/TBT columns split by length class
    router = FleetRouter(
        {r["rid"]: r["base"] for r in replicas}, scrape_interval_s=0.1,
        long_prompt_chars=1000,
    ).start()
    rhttpd = router.serve(host="127.0.0.1", port=0)
    threading.Thread(target=rhttpd.serve_forever, daemon=True).start()
    router.scrape_once()
    rbase = f"http://127.0.0.1:{rhttpd.server_address[1]}"

    # three shared-system-prompt families: affinity has something to
    # steer, and the hit-rate number means prefix-warmth concentration.
    # Every 4th prompt carries a long tail AFTER the family prefix (the
    # affinity key covers leading blocks only, so the key is unchanged)
    # to populate the long length class.
    def prompt_for(i):
        fam = i % 3
        text = ("family %d system prompt " % fam) * 20 + f"user {i}"
        if i % 4 == 0:
            text += " long-context filler" * 40
        return text

    bodies = [
        {"prompt": prompt_for(i), "max_tokens": max_tokens, "stream": True}
        for i in range(n_requests)
    ]

    # oracle pass: each prompt's uninterrupted text, straight off one
    # replica (content_keyed: the stream is a pure function of prompt
    # content, identical on every replica — the determinism class)
    oracle = {}
    for i, body in enumerate(bodies):
        req = urllib.request.Request(
            f"http://{replicas[0]['base']}/v1/completions",
            data=_json.dumps({**body, "stream": False}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            oracle[i] = _json.loads(resp.read())["generated_text"]

    # the churn: Poisson arrivals, one client thread per stream
    results = {}
    lock = threading.Lock()

    def client(i, body, t_submit):
        req = urllib.request.Request(
            rbase + "/v1/completions", data=_json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        texts, stamps, err, phases = [], [], None, None
        try:
            with urllib.request.urlopen(req, timeout=240) as resp:
                for line in resp:
                    line = line.decode().strip()
                    if not line.startswith("data: ") or line == "data: [DONE]":
                        continue
                    p = _json.loads(line[6:])
                    if "error" in p:
                        err = p.get("reason", "error")
                        continue
                    ch = p.get("choices", [{}])[0]
                    if ch.get("finish_reason") is None:
                        texts.append(ch.get("text", ""))
                        stamps.append(time.perf_counter())
                    else:
                        # terminal chunk: the per-request phases record
                        # (queue/prefill/decode/ITL/migration gap) the
                        # router stamped its gap attribution into
                        s = p.get("summary")
                        if isinstance(s, dict) and isinstance(
                            s.get("phases"), dict
                        ):
                            phases = s["phases"]
        except Exception as e:  # noqa: BLE001 — the ledger records it
            err = f"{type(e).__name__}"
        with lock:
            results[i] = ("".join(texts), stamps, t_submit, err, phases)

    rng = np.random.default_rng(23)
    intervals = rng.exponential(0.04, n_requests)
    threads = []
    t0 = time.perf_counter()
    drained = killed = False
    for i, (body, dt) in enumerate(zip(bodies, intervals)):
        time.sleep(dt)
        th = threading.Thread(
            target=client, args=(i, body, time.perf_counter()),
        )
        th.start()
        threads.append(th)
        if not drained and i >= n_requests // 3:
            # SIGTERM shape on r1: health flips + sheds immediately, a
            # SHORT drain window, then force-cancel of the remainder —
            # exactly what a rolling restart that runs out of patience
            # does. Streams still on r1 must migrate, not die.
            drained = True
            threading.Thread(
                target=lambda: replicas[1]["sched"].drain(timeout=0.3),
                daemon=True,
            ).start()
        if not killed and i >= (2 * n_requests) // 3:
            # replica death on r2: listener closed (new connects get
            # ECONNREFUSED, like a dead process) + abrupt stop with
            # streams mid-flight
            killed = True
            replicas[2]["httpd"].shutdown()
            replicas[2]["httpd"].server_close()
            threading.Thread(
                target=replicas[2]["sched"].stop, daemon=True,
            ).start()
    for th in threads:
        th.join(timeout=300)
    wall = time.perf_counter() - t0

    # the loss ledger: byte-identity against the oracle per stream
    lost = dup = failed = completed = 0
    byte_identical = True
    # latency split by the router's prompt-length class: long prompts
    # are the disagg policy's subject, and their TTFT must be
    # attributable separately from the short traffic's TBT
    ttfts = {"short": [], "long": []}
    tbts = {"short": [], "long": []}
    for i in range(n_requests):
        text, stamps, t_submit, err, _phases = results.get(
            i, ("", [], t0, "no_result", None)
        )
        if err is not None:
            failed += 1
            continue
        completed += 1
        if text != oracle[i]:
            byte_identical = False
            # char-level ledger: missing chars = lost, extras = dup
            if len(text) < len(oracle[i]):
                lost += len(oracle[i]) - len(text)
            else:
                dup += len(text) - len(oracle[i])
        if stamps:
            cls = (
                "long" if len(bodies[i]["prompt"]) >= 1000 else "short"
            )
            ttfts[cls].append((stamps[0] - t_submit) * 1e3)
            tbts[cls].extend(
                (b - a) * 1e3 for a, b in zip(stamps, stamps[1:])
            )

    def pct(vals, q):
        if not vals:
            return None
        s = sorted(vals)
        return round(s[min(len(s) - 1, int(q * len(s)))], 1)

    # per-request phase attribution (telemetry/tracectx.py PHASE_KEYS):
    # the replica-reported records off the terminal chunks, with the
    # router's migration-gap stamp — where each stream's wall time went
    phase_recs = [
        r[4] for r in results.values() if r[3] is None and r[4]
    ]

    def phase_vals(key):
        return [
            float(p[key]) for p in phase_recs
            if isinstance(p.get(key), (int, float))
        ]

    stats = router.handle_stats()
    mig_hist = router.registry.get("dllama_router_migration_seconds")
    mig_p50 = mig_hist.quantile(0.5) if mig_hist.count else None
    # the router-side aggregation of the SAME records: its ttft series
    # must reconcile with the client-collected phases (the histogram is
    # bucket-interpolated — a coarse estimate, reported as such)
    phase_hist = router.registry.get("dllama_request_phase_seconds")
    router_ttft_p95_s = (
        phase_hist.quantile(0.95, phase="ttft_ms")
        if phase_hist is not None else None
    )
    router.close()
    rhttpd.shutdown()
    fleet_drained = True
    for r in replicas:
        try:
            r["httpd"].shutdown()
            r["registry"].close()
            r["sched"].stop()
            _drained_report(f"serving_fleet_{r['rid']}", r["sched"])
        except RuntimeError:
            fleet_drained = False  # a hung stop can't certify its drain
    affinity_routes = max(1, stats["fleet_affinity_routes"])
    return {
        "serving_fleet_replicas": 3,
        "serving_fleet_requests": n_requests,
        "serving_fleet_completed": completed,
        "serving_fleet_failed": failed,
        "serving_fleet_wall_s": round(wall, 2),
        "serving_fleet_ttft_p50_ms": pct(
            ttfts["short"] + ttfts["long"], 0.50
        ),
        "serving_fleet_ttft_p95_ms": pct(
            ttfts["short"] + ttfts["long"], 0.95
        ),
        "serving_fleet_ttft_p99_ms": pct(
            ttfts["short"] + ttfts["long"], 0.99
        ),
        "serving_fleet_tbt_p50_ms": pct(
            tbts["short"] + tbts["long"], 0.50
        ),
        "serving_fleet_tbt_p95_ms": pct(
            tbts["short"] + tbts["long"], 0.95
        ),
        "serving_fleet_tbt_p99_ms": pct(
            tbts["short"] + tbts["long"], 0.99
        ),
        # the length-class split: what disagg routing acts on (long
        # prompts here ride the monolithic fallback — no prefill-role
        # replica in this fleet; serving_disagg measures the split
        # WITH one)
        "serving_fleet_ttft_p95_ms_short": pct(ttfts["short"], 0.95),
        "serving_fleet_ttft_p95_ms_long": pct(ttfts["long"], 0.95),
        "serving_fleet_tbt_p95_ms_short": pct(tbts["short"], 0.95),
        "serving_fleet_tbt_p95_ms_long": pct(tbts["long"], 0.95),
        # the zero-requests-shed claim: replica sheds are retried or
        # migrated by the router; only a total fleet outage reaches the
        # client (must be 0 here — one replica stays healthy)
        "serving_fleet_shed_rate": round(
            stats["router_giveups"] / n_requests, 3
        ),
        "serving_fleet_replica_shed_retries": stats["router_shed_retries"],
        "serving_fleet_affinity_hit_rate": round(
            stats["fleet_affinity_hits"] / affinity_routes, 3
        ),
        "serving_fleet_migrations": stats["router_migrations_ok"],
        "serving_fleet_migrations_failed": stats["router_migrations_failed"],
        "serving_fleet_migration_p50_ms": (
            round(mig_p50 * 1e3, 1) if mig_p50 is not None else None
        ),
        # the loss ledger across a drain AND a kill (chars, not tokens:
        # finer — a partial-token text diff still counts)
        "serving_fleet_lost_chars": lost,
        "serving_fleet_duplicate_chars": dup,
        "serving_fleet_byte_identical": byte_identical,
        # per-replica leak_counts() asserted zero above — the drained
        # replica AND the killed one both released every mirror/page
        "serving_fleet_leaked_resources": 0 if fleet_drained else None,
        # per-phase latency attribution (replica-reported phases records
        # off the terminal chunks + the router's migration-gap stamp):
        # where completed streams' wall time went, phase by phase
        "serving_fleet_phase_records": len(phase_recs),
        "serving_fleet_phase_queue_wait_p95_ms": pct(
            phase_vals("queue_wait_ms"), 0.95
        ),
        "serving_fleet_phase_prefill_p95_ms": pct(
            phase_vals("prefill_ms"), 0.95
        ),
        "serving_fleet_phase_decode_p95_ms": pct(
            phase_vals("decode_ms"), 0.95
        ),
        "serving_fleet_phase_itl_p50_ms": pct(
            phase_vals("itl_p50_ms"), 0.50
        ),
        "serving_fleet_phase_itl_p99_ms": pct(
            phase_vals("itl_p99_ms"), 0.95
        ),
        "serving_fleet_phase_migration_gap_max_ms": round(
            max(phase_vals("migration_gap_ms"), default=0.0), 1
        ),
        # the router-side dllama_request_phase_seconds aggregation of
        # the same records (bucket-interpolated estimate)
        "serving_fleet_router_phase_ttft_p95_ms": (
            round(router_ttft_p95_s * 1e3, 1)
            if router_ttft_p95_s is not None else None
        ),
    }


def _phase_serving_disagg(config, small):
    """The disaggregated-prefill gate (ISSUE 16): a three-replica fleet
    with an explicit **prefill** replica, a **decode** replica and a
    **mixed** replica behind the ``dllama-router`` with prompt-length
    routing on. The phase measures the policy's whole claim:

    - a long-classified prompt routes to the prefill-role replica, its
      KV pages transfer (integrity hashes verified by the importer) and
      adopt refcount-correctly into the decode replica's pool, and the
      client stream hands off char-exact vs the single-replica oracle;
    - decode TBT p95 on co-resident SHORT sessions stays within 10% of
      a no-long-prompt baseline (the DistServe/Splitwise motivation:
      prefill interference off the decode tier);
    - zero device-program compiles after warmup in-phase;
    - killing the prefill replica degrades long traffic to the
      monolithic path (typed, routed, byte-identical) — not a hung
      stream.

    Mock-backed like serving_fleet (the same content-keyed determinism
    class), but the KV POOL IS REAL: adoption, refcounts, parking and
    the integrity hashes run the shipping ``runtime/kvpool.py`` +
    ``disagg/kvtransfer.py`` code on every host."""
    import numpy as np

    from distributed_llama_multiusers_tpu.fleet import FleetRouter
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
    )
    from distributed_llama_multiusers_tpu.serving import StreamRegistry
    from distributed_llama_multiusers_tpu.server import ApiServer
    from distributed_llama_multiusers_tpu.tokenizer import TemplateType
    from distributed_llama_multiusers_tpu.utils.testing import (
        CharStreamTokenizer,
        MockAsyncEngine,
    )
    import json as _json
    import urllib.request

    class _DisaggTokenizer(CharStreamTokenizer):
        def decode(self, token):
            return f"[{token}]"

    n_lanes = 2 if small else 4
    n_short = 8 if small else 20
    max_tokens = 16 if small else 32
    step_s = 0.004
    page = 16
    # 160 prompt tokens = 10 full pool blocks: enough chain for the
    # transfer to mean something, small enough for a CPU smoke
    max_chars = 160
    long_chars = 1000  # the router threshold for THIS phase

    def make_tok():
        return _DisaggTokenizer(64, max_chars=max_chars)

    def make_replica(rid, role):
        engine = MockAsyncEngine(
            n_lanes=n_lanes, max_chunk=8, content_keyed=True,
            step_s=step_s, paged=True, kv_page_size=page,
            kv_pool_pages=256, kv_max_parked=64,
        )
        sched = ContinuousBatchingScheduler(
            engine, make_tok(), speculative=False,
            prefix_min_tokens=page, multi_step=0,
        )
        sched.start()
        registry = StreamRegistry(grace_s=60.0)
        api = ApiServer(sched, make_tok(), model_name="disagg",
                        template_type=TemplateType.LLAMA2,
                        resume=registry, replica_id=rid, role=role)
        httpd = api.serve(host="127.0.0.1", port=0)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return {"rid": rid, "role": role, "engine": engine,
                "sched": sched, "registry": registry, "httpd": httpd,
                "base": f"127.0.0.1:{httpd.server_address[1]}"}

    replicas = [
        make_replica("p0", "prefill"),
        make_replica("d0", "decode"),
        make_replica("m0", "mixed"),
    ]
    router = FleetRouter(
        {r["rid"]: r["base"] for r in replicas}, scrape_interval_s=0.1,
        long_prompt_chars=long_chars,
    ).start()
    rhttpd = router.serve(host="127.0.0.1", port=0)
    threading.Thread(target=rhttpd.serve_forever, daemon=True).start()
    router.scrape_once()
    rbase = f"http://127.0.0.1:{rhttpd.server_address[1]}"

    # prompts: shorts stay under one affinity block (keyless, least-
    # loaded — today's path); longs clear the router threshold by chars
    # (the tokenizer caps TOKENS, the classifier reads the raw text)
    long_a = "analyse this corpus properly: " + "lorem ipsum filler " * 60
    long_b = "second long corpus to survive: " + "dolor sit amet pad " * 60
    assert min(len(long_a), len(long_b)) >= long_chars
    shorts_a = [f"baseline question {i} topic {i % 5}" for i in range(n_short)]
    shorts_b = [f"coresident question {i} topic {i % 5}" for i in range(n_short)]

    # oracle pass — every prompt's uninterrupted text off ONE replica
    # (content-keyed: identical on all three), BEFORE any churn and
    # before the prefill replica is killed for the fallback leg
    def oracle_for(prompt, mt):
        req = urllib.request.Request(
            f"http://{replicas[0]['base']}/v1/completions",
            data=_json.dumps({"prompt": prompt, "max_tokens": mt,
                              "stream": False}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return _json.loads(resp.read())["generated_text"]

    oracle = {p: oracle_for(p, max_tokens)
              for p in [long_a, long_b, *shorts_a, *shorts_b]}

    results = {}
    lock = threading.Lock()

    def client(tag, prompt, t_submit):
        req = urllib.request.Request(
            rbase + "/v1/completions",
            data=_json.dumps({"prompt": prompt, "max_tokens": max_tokens,
                              "stream": True}).encode(),
            headers={"Content-Type": "application/json"},
        )
        texts, stamps, err, served_by, phases = [], [], None, None, None
        try:
            with urllib.request.urlopen(req, timeout=240) as resp:
                served_by = resp.headers.get("X-DLlama-Replica")
                for line in resp:
                    line = line.decode().strip()
                    if not line.startswith("data: ") or line == "data: [DONE]":
                        continue
                    p = _json.loads(line[6:])
                    if "error" in p:
                        err = p.get("reason", "error")
                        continue
                    ch = p.get("choices", [{}])[0]
                    if ch.get("finish_reason") is None:
                        texts.append(ch.get("text", ""))
                        stamps.append(time.perf_counter())
                    else:
                        # terminal chunk: the per-request phases record
                        # (the hand-off's decode side reports it for the
                        # long stream)
                        s = p.get("summary")
                        if isinstance(s, dict) and isinstance(
                            s.get("phases"), dict
                        ):
                            phases = s["phases"]
        except Exception as e:  # noqa: BLE001 — the ledger records it
            err = f"{type(e).__name__}"
        with lock:
            results[tag] = ("".join(texts), stamps, t_submit, err,
                            served_by, phases)

    rng = np.random.default_rng(31)

    def run_wave(tagged_prompts):
        threads = []
        for (tag, prompt), dt in zip(
            tagged_prompts, rng.exponential(0.03, len(tagged_prompts))
        ):
            time.sleep(dt)
            th = threading.Thread(
                target=client, args=(tag, prompt, time.perf_counter()),
            )
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=300)

    def tbts_of(tags):
        out = []
        for tag in tags:
            _, stamps, _, err, _, _ = results[tag]
            if err is None:
                out.extend(
                    (b - a) * 1e3 for a, b in zip(stamps, stamps[1:])
                )
        return out

    def pct(vals, q):
        if not vals:
            return None
        s = sorted(vals)
        return round(s[min(len(s) - 1, int(q * len(s)))], 1)

    # wave A — the no-long-prompt baseline for short-session decode TBT
    run_wave([(f"a{i}", p) for i, p in enumerate(shorts_a)])
    tbt_base_p95 = pct(tbts_of([f"a{i}" for i in range(n_short)]), 0.95)

    # wave B — the measured regime: one long prompt CO-RESIDENT with the
    # short traffic; the router steers it to p0, hands it to d0 at first
    # token, and the shorts' TBT must not notice
    run_wave([("long", long_a)]
             + [(f"b{i}", p) for i, p in enumerate(shorts_b)])
    tbt_co_p95 = pct(tbts_of([f"b{i}" for i in range(n_short)]), 0.95)

    long_text, long_stamps, long_t0, long_err, long_served, long_phases = (
        results["long"]
    )
    assert long_err is None, f"long stream failed: {long_err}"
    # acceptance: the long prompt ROUTED to the prefill-role replica
    assert long_served == "p0", (
        f"long prompt served by {long_served!r}, want prefill replica p0"
    )
    # acceptance: char-exact across the hand-off vs the oracle
    assert long_text == oracle[long_a], (
        f"hand-off stream diverged: {len(long_text)} chars vs "
        f"{len(oracle[long_a])} oracle chars"
    )
    short_ok = sum(
        1 for i in range(n_short)
        if results[f"b{i}"][3] is None
        and results[f"b{i}"][0] == oracle[shorts_b[i]]
    )
    assert short_ok == n_short, (
        f"only {short_ok}/{n_short} co-resident shorts byte-identical"
    )
    stats = router.handle_stats()
    # acceptance: pages genuinely transferred + adopted (receipt counts
    # come from the DESTINATION pool's real bookkeeping)
    assert stats["router_disagg_handoffs_ok"] >= 1, stats
    assert stats["router_disagg_pages_fresh"] >= 1, stats
    d0 = replicas[1]
    d0_pool = d0["engine"].kvpool.stats()
    assert d0_pool["pool_adopts"] >= 1, d0_pool
    assert d0["engine"].pages_imported >= 1
    # acceptance: decode TBT p95 within 10% of baseline (+2ms noise
    # floor: mock steps are 4ms, thread-scheduling jitter on a shared
    # CI host must not fail the gate the policy passed)
    assert tbt_co_p95 <= tbt_base_p95 * 1.10 + 2.0, (
        f"co-resident short TBT p95 {tbt_co_p95}ms vs "
        f"baseline {tbt_base_p95}ms"
    )
    # acceptance: compile stability in-phase, every replica
    for r in replicas:
        snap = r["engine"].stats.snapshot()
        assert snap["jit_compiles_after_warmup"] == 0, (r["rid"], snap)

    # fallback leg — kill the PREFILL replica, then send another long
    # prompt: with no prefill-role replica eligible the router routes it
    # monolithic (typed, still byte-identical), never a hung stream
    replicas[0]["httpd"].shutdown()
    replicas[0]["httpd"].server_close()
    threading.Thread(target=replicas[0]["sched"].stop, daemon=True).start()
    router.scrape_once()
    run_wave([("long_fb", long_b)])
    fb_text, _, _, fb_err, fb_served, _fb_phases = results["long_fb"]
    assert fb_err is None, f"post-kill long stream failed: {fb_err}"
    assert fb_served in ("d0", "m0"), fb_served
    assert fb_text == oracle[long_b], "monolithic fallback diverged"

    hand_hist = router.registry.get("dllama_router_disagg_handoff_seconds")
    hand_p50 = hand_hist.quantile(0.5) if hand_hist.count else None
    phase_hist = router.registry.get("dllama_request_phase_seconds")
    router_ttft_p95_s = (
        phase_hist.quantile(0.95, phase="ttft_ms")
        if phase_hist is not None else None
    )
    router.close()
    rhttpd.shutdown()
    for r in replicas[1:]:
        try:
            r["httpd"].shutdown()
            r["registry"].close()
            r["sched"].stop()
            # the decode replica ADOPTED transferred pages mid-phase: its
            # pool must still drain to zero lane-held pages (adopted
            # pages park or free with their session like native ones)
            _drained_report(f"serving_disagg_{r['rid']}", r["sched"])
        except RuntimeError:
            pass
    long_ttft_ms = (
        round((long_stamps[0] - long_t0) * 1e3, 1) if long_stamps else None
    )
    # fleet-wide latency attribution: client-observed TTFT/ITL over every
    # successful stream, plus the per-request phases records the replicas
    # attached to their terminal chunks (satellite of the tracing PR)
    ttfts = [
        (r[1][0] - r[2]) * 1e3
        for r in results.values() if r[3] is None and r[1]
    ]
    itls = tbts_of([t for t in results if results[t][3] is None])
    phase_recs = [
        r[5] for r in results.values() if r[3] is None and r[5]
    ]

    def phase_vals(key):
        return [
            float(p[key]) for p in phase_recs
            if isinstance(p.get(key), (int, float))
        ]

    return {
        "serving_disagg_replicas": 3,
        "serving_disagg_short_requests": 2 * n_short,
        "serving_disagg_long_requests": 2,
        "serving_disagg_long_routed_to": long_served,
        "serving_disagg_long_ttft_ms": long_ttft_ms,
        "serving_disagg_handoffs_ok": stats["router_disagg_handoffs_ok"],
        "serving_disagg_fallbacks": stats["router_disagg_fallbacks"],
        "serving_disagg_pages_moved": stats["router_disagg_pages_moved"],
        "serving_disagg_pages_fresh": stats["router_disagg_pages_fresh"],
        "serving_disagg_handoff_p50_ms": (
            round(hand_p50 * 1e3, 1) if hand_p50 is not None else None
        ),
        "serving_disagg_decode_adopts": d0_pool["pool_adopts"],
        "serving_disagg_decode_pages_imported": d0["engine"].pages_imported,
        "serving_disagg_tbt_p95_ms_baseline": tbt_base_p95,
        "serving_disagg_tbt_p95_ms_coresident": tbt_co_p95,
        "serving_disagg_tbt_ratio": (
            round(tbt_co_p95 / tbt_base_p95, 3)
            if tbt_base_p95 else None
        ),
        "serving_disagg_ttft_p50_ms": pct(ttfts, 0.50),
        "serving_disagg_ttft_p95_ms": pct(ttfts, 0.95),
        "serving_disagg_ttft_p99_ms": pct(ttfts, 0.99),
        "serving_disagg_itl_p50_ms": pct(itls, 0.50),
        "serving_disagg_itl_p95_ms": pct(itls, 0.95),
        "serving_disagg_itl_p99_ms": pct(itls, 0.99),
        "serving_disagg_phase_records": len(phase_recs),
        "serving_disagg_phase_prefill_p95_ms": pct(
            phase_vals("prefill_ms"), 0.95
        ),
        "serving_disagg_phase_decode_p95_ms": pct(
            phase_vals("decode_ms"), 0.95
        ),
        "serving_disagg_phase_queue_wait_p95_ms": pct(
            phase_vals("queue_wait_ms"), 0.95
        ),
        "serving_disagg_phase_swap_in_max_ms": round(
            max(phase_vals("swap_in_ms"), default=0.0), 1
        ),
        "serving_disagg_phase_migration_gap_max_ms": round(
            max(phase_vals("migration_gap_ms"), default=0.0), 1
        ),
        "serving_disagg_router_phase_ttft_p95_ms": (
            round(router_ttft_p95_s * 1e3, 1)
            if router_ttft_p95_s is not None else None
        ),
        "serving_disagg_byte_identical": True,  # asserted above
        "serving_disagg_monolithic_fallback_ok": True,  # asserted above
        "serving_disagg_compiles_after_warmup": 0,  # asserted above
        "serving_disagg_leaked_resources": 0,  # asserted per replica above
    }


def _pipeline_microbench(n_requests=4, max_tokens=48):
    """Drive the REAL scheduler loop over the mocked async engine
    (utils.testing.MockAsyncEngine — the same stub the pinned tests in
    tests/test_pipelined_decode.py use, so bench evidence and tests cannot
    drift) and read back the overlap evidence: in steady-state decode the
    consume of step k must happen after step k+1's dispatch (one-step
    lag), with zero chain aborts. Deterministic on any host — the CPU
    fallback's real-engine timings are too noisy to prove overlap."""
    from distributed_llama_multiusers_tpu.runtime.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )
    from distributed_llama_multiusers_tpu.utils.testing import (
        MockAsyncEngine,
        StubStreamTokenizer,
    )

    engine = MockAsyncEngine()
    sched = ContinuousBatchingScheduler(
        engine, StubStreamTokenizer(engine.config.vocab_size),
        speculative=False, prefix_min_tokens=0, multi_step=0,
    )
    reqs = [
        Request(prompt="microbench", max_tokens=max_tokens, temperature=0.0)
        for _ in range(n_requests)
    ]
    sched.start()
    try:
        for r in reqs:
            sched.submit(r)
        for r in reqs:
            r.future.result(timeout=60)
    finally:
        sched.stop()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    consumed, overlapped = engine.count_overlapped_consumes()
    stats = engine.stats.snapshot()
    return {
        "pipeline_microbench_steps": consumed,
        "pipeline_microbench_overlapped_consumes": overlapped,
        "pipeline_microbench_flushes": stats["pipeline_flushes"],
        "pipeline_microbench_overlap_s": round(stats["overlap_s"], 4),
    }


def _pipeline_microbench_safe() -> dict:
    try:
        return _pipeline_microbench()
    except Exception as e:  # noqa: BLE001 — evidence, not the headline
        return {"pipeline_microbench_error": f"{type(e).__name__}: {e}"[:200]}


def _phase_ablations(config, small):
    import jax
    import jax.numpy as jnp

    from distributed_llama_multiusers_tpu.ops import linear

    n_short, n_long = (4, 16) if small else (16, 128)
    out = {}
    params_q = _resident_packed_params(config)
    linear.set_pallas_enabled(False)
    try:
        out["ablation_xla_dequant_tok_s"] = round(
            _bench_decode(config, params_q, n_short, n_long, tag="packed+xla-dequant"), 2
        )
    finally:
        linear.set_pallas_enabled(True)
    # f32 dequantized-weight planes (multi-pass f32 MXU semantics — what the
    # pre-round-4 "exact" default cost; bf16 planes are now the TPU default
    # since f32 dot operands round to bf16 MXU passes anyway)
    linear.set_pallas_w_dtype(jnp.float32)
    try:
        out["ablation_pallas_f32w_tok_s"] = round(
            _bench_decode(config, params_q, n_short, n_long, tag="packed+pallas-f32w"), 2
        )
    finally:
        linear.set_pallas_w_dtype(None)
    del params_q
    params_d = _resident_dense_params(config, seed=0, dtype=jnp.bfloat16)
    out["ablation_dense_bf16_tok_s"] = round(
        _bench_decode(config, params_d, n_short, n_long, tag="dense-bf16"), 2
    )
    return out


def _phase_8b(platform):
    from distributed_llama_multiusers_tpu.models.config import LlamaConfig

    if platform != "tpu":
        return {"llama31_8b_q40_decode_tok_s": None,
                "llama31_8b_note": f"skipped off-TPU ({platform})"}
    cfg8 = LlamaConfig(
        dim=4096, hidden_dim=14336, n_layers=32, n_heads=32, n_kv_heads=8,
        vocab_size=128256, seq_len=2048, rope_theta=500000.0,
        rope_scaling_factor=8.0, rope_scaling_low_freq_factor=1.0,
        rope_scaling_high_freq_factor=4.0, rope_scaling_orig_max_seq_len=8192,
    )
    import jax

    t0 = time.perf_counter()
    params8 = _resident_packed_params(cfg8)
    print(f"[bench] 8B packed params resident in {time.perf_counter()-t0:.1f}s "
          f"({_tree_device_bytes(params8)/1e9:.2f} GB)", file=sys.stderr, flush=True)
    tok8 = _bench_decode(cfg8, params8, 8, 64, reps=2, tag="8b packed+pallas")
    return {
        "llama31_8b_q40_decode_tok_s": round(tok8, 2),
        "llama31_8b_northstar_frac": round(tok8 / 200.0, 3),
    }


def _phase_longctx(config, small):
    """Decode throughput at FULL context: every step's attention reads the
    whole KV cache (the long-context serving regime; reference analogue:
    macbeth.sh's cache-filling generation). Measured with the bf16 KV
    default AND --kv-dtype f8 — at long context the KV read is marginal
    traffic alongside the weights, so f8 is a bandwidth lever there, not
    just a capacity one. Cache CONTENTS are irrelevant to bandwidth, so
    the cache starts zeroed at a high position (no prefill cost)."""
    import jax
    import jax.numpy as jnp

    n_short, n_long = (8, 16) if small else (16, 64)
    start = config.seq_len - n_long - 1
    params = _resident_packed_params(config)
    out = {"longctx_context": start, "longctx_steps": n_long}

    for name, dtype in (("bf16", jnp.bfloat16), ("f8", jnp.float8_e4m3fn)):
        tok_s = _bench_decode(
            config, params, n_short, n_long, reps=2,
            tag=f"longctx-{name}kv", start_pos=start, cache_dtype=dtype,
        )
        out[f"longctx_decode_tok_s_{name}kv"] = round(tok_s, 2)
    return out


def _phase_parity(config, platform):
    """BASELINE.md's token-identity gate, measured with the SHIPPING TPU
    dtype: greedy-decode 256 tokens with the default bf16-dot kernel and
    with the exact-f32 XLA dequant path (set_pallas_enabled(False); both
    streams on f32 activations), same synthetic Q40 weights, and report
    whether the streams are token-identical — plus the first divergence
    step if not. Random weights have near-zero logit margins, so a
    divergence here is the worst case, not the real-model rate; the
    interpret-mode CI test (tests/test_pallas_q40.py) pins model-scale
    identity."""
    if platform != "tpu":
        return {"token_parity_bf16": None,
                "parity_note": f"skipped off-TPU ({platform})"}
    import jax
    import jax.numpy as jnp

    import numpy as np

    from distributed_llama_multiusers_tpu.ops import linear
    from distributed_llama_multiusers_tpu.runtime import InferenceEngine

    # f32 embedding -> f32 activations in BOTH streams: the comparison then
    # isolates exactly the shipping kernel's bf16 dot (which casts x down
    # internally) against full-f32 math, instead of confounding it with
    # bf16 activations everywhere else
    params = _device_packed_params(config, seed=0, dtype=jnp.float32)
    prompt = list(range(1, 17))
    n = 256
    streams = {}
    # exact-f32 oracle = the XLA dequant path (unpack + f32 matmul), NOT
    # set_pallas_w_dtype(f32): the multi-pass f32 Pallas compile blew the
    # phase budget on hardware (round 5: >300 s, and the timeout kill wedged
    # the tunnel). The XLA path is the same math at ordinary compile cost
    # and is independently pinned against the numpy oracle in CI.
    def greedy_multi(engine, n_tokens):
        """Greedy rollout in multi-step horizons: n/8 dispatches instead
        of n (the per-step host RTT through the tunnel blew this phase's
        budget in round 5 — and the timeout kill wedged the tunnel)."""
        _, g0, pos = engine.prefill(0, prompt)
        out = [int(g0)]
        toks = np.asarray([g0], np.int32)
        poss = np.asarray([pos], np.int32)
        while len(out) < n_tokens:
            # always h=8: a shorter final horizon would compile a SECOND
            # full-model scan program (decode_multi caches per h) in the
            # budget-tightest phase; overshot tokens are just trimmed
            chosen = engine.decode_multi(toks, poss, h=8)
            out.extend(int(chosen[j, 0]) for j in range(chosen.shape[0]))
            toks = chosen[-1].astype(np.int32)
            poss = poss + chosen.shape[0]
        return out[:n_tokens]

    for name, enabled in (("bf16", True), ("f32", False)):
        linear.set_pallas_enabled(enabled)
        try:
            engine = InferenceEngine(
                config, params, n_lanes=1, prefill_buckets=(16,)
            )
            streams[name] = greedy_multi(engine, n)
        finally:
            linear.set_pallas_enabled(True)
        del engine
    mism = [i for i, (a, b) in enumerate(zip(streams["bf16"], streams["f32"]))
            if a != b]
    return {
        "token_parity_bf16": not mism,
        "parity_tokens": n,
        "parity_first_divergence": mism[0] if mism else None,
        "parity_divergent_steps": len(mism),
    }


def child_main() -> None:
    # the parent's timeout sends SIGTERM; without a handler the default
    # disposition kills the process as abruptly as SIGKILL (no finally
    # blocks, no PJRT teardown) and the graceful-shutdown grace period in
    # _run_child buys nothing. SystemExit unwinds the stack so the axon
    # tunnel connection closes cleanly instead of dying mid-RPC.
    import signal

    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))

    # CPU runs must strip the TPU PJRT plugin BEFORE backend discovery: this
    # box's sitecustomize registers one whose init dials a network tunnel,
    # and it blocks discovery even under JAX_PLATFORMS=cpu (see
    # utils/testing.force_cpu_mesh — the same reason round 1's bench hung).
    # The pod_serving smoke needs the 8-virtual-device mesh (the tests'
    # standard TP fixture); every other phase runs single-device.
    if os.environ.get("BENCH_FORCE_CPU") == "1" or os.environ.get("JAX_PLATFORMS") == "cpu":
        from distributed_llama_multiusers_tpu.utils.testing import force_cpu_mesh

        force_cpu_mesh(
            n_devices=8
            if os.environ.get("BENCH_PHASE") == "pod_serving"
            else 1
        )

    import jax

    from __graft_entry__ import _flagship_config
    from distributed_llama_multiusers_tpu.app.runtime_setup import (
        enable_compilation_cache,
    )

    # phase children build many identical programs (primary retries, the
    # parity phase's two engines, serving warmup, longctx variants): the
    # persistent cache makes every repeat compile near-instant, which
    # matters most when compiles travel a slow device tunnel
    enable_compilation_cache()

    phase = os.environ.get("BENCH_PHASE", "primary")
    dev = jax.devices()[0]
    platform = dev.platform
    device_kind = getattr(dev, "device_kind", platform)
    print(f"[bench] backend up: {platform} ({device_kind}) phase={phase}",
          file=sys.stderr, flush=True)

    small = os.environ.get("GRAFT_SMALL") == "1" or platform != "tpu"
    config = _flagship_config(small=small)

    if phase == "primary":
        result = _phase_primary(config, platform, device_kind, small)
    elif phase == "serving":
        result = _phase_serving(config, small)
    elif phase == "serving_churn":
        result = _phase_serving_churn(config, small)
    elif phase == "serving_prefix":
        result = _phase_serving_prefix(config, small)
    elif phase == "pod_serving":
        result = _phase_pod_serving(config, small)
    elif phase == "serving_faults":
        result = _phase_serving_faults(config, small)
    elif phase == "serving_recovery":
        result = _phase_serving_recovery(config, small)
    elif phase == "serving_fleet":
        result = _phase_serving_fleet(config, small)
    elif phase == "serving_structured":
        result = _phase_serving_structured(config, small)
    elif phase == "serving_disagg":
        result = _phase_serving_disagg(config, small)
    elif phase == "ablations":
        result = _phase_ablations(config, small)
    elif phase == "8b":
        result = _phase_8b(platform)
    elif phase == "parity":
        result = _phase_parity(config, platform)
    elif phase == "longctx":
        result = _phase_longctx(config, small)
    else:
        raise ValueError(f"unknown BENCH_PHASE {phase!r}")
    # Every phase result carries the resolved dequant mode (and, under
    # auto, the selection-table provenance + per-site resolutions) next to
    # its tok/s numbers, so BENCH_LIVE.json rows are self-describing.
    from distributed_llama_multiusers_tpu.ops.dequant_select import bench_stamp

    result.update(bench_stamp(phase))
    print(json.dumps(result), flush=True)


# ---------------------------------------------------------------------------
# Parent: watchdog. Phase children with own timeouts; cumulative artifact
# re-printed after every phase; CPU fallback; diagnostic JSON on failure.
# ---------------------------------------------------------------------------


def _text(x) -> str:
    if isinstance(x, bytes):
        return x.decode(errors="replace")
    return x or ""


def _last_json_line(out: str):
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _run_child(env_extra: dict, timeout_s: float):
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    env.update(env_extra)
    # Popen + SIGTERM-then-SIGKILL, NOT subprocess.run(timeout=...): run()
    # SIGKILLs on timeout, and a child killed mid-TPU-RPC is the prime
    # suspect for the recurring axon-tunnel wedge (round 5: the tunnel died
    # at the parity child's timeout kill and every later phase NO_BACKENDed).
    # A TERMed child unwinds the Python/PJRT stack and closes the tunnel
    # connection cleanly; 20 s grace before the hard kill.
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )
    timed_out = False
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        timed_out = True
        proc.terminate()
        try:
            stdout, stderr = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            stdout, stderr = proc.communicate()
    except BaseException:
        # subprocess.run killed the child on ANY exception; keep that
        # guarantee (e.g. KeyboardInterrupt mid-communicate) — an orphaned
        # child would keep holding the TPU tunnel
        proc.terminate()
        try:
            proc.communicate(timeout=20)
        except Exception:
            proc.kill()
        raise
    if timed_out:
        parsed = _last_json_line(_text(stdout))
        if parsed is not None:
            return parsed, None
        err = f"timeout after {timeout_s:.0f}s; stderr tail: {_text(stderr)[-300:]}"
        if "[bench] backend up" not in _text(stderr):
            # the device tunnel never initialized: retrying burns the whole
            # deadline on another hang — callers should fall back instead
            err = "NO_BACKEND " + err
        return None, err
    parsed = _last_json_line(_text(stdout))
    if parsed is not None:
        if proc.returncode != 0:
            parsed.setdefault("phase_rc", proc.returncode)
        return parsed, None
    return None, f"rc={proc.returncode}; stderr tail: {_text(stderr)[-400:]}"


def main() -> None:
    # the driver's outer limit killed round 3 at 1500 s with nothing parsed;
    # keep the WHOLE watchdog comfortably under it
    deadline = time.monotonic() + float(os.environ.get("BENCH_DEADLINE", "1260"))
    errors: list[str] = []
    merged: dict | None = None

    def bank(update: dict) -> None:
        nonlocal merged
        if merged is None:
            merged = dict(update)
        else:
            merged.update(update)
        print(json.dumps(merged), flush=True)  # driver parses the LAST line

    # -- primary metric first, retried: nothing else runs until it banks ----
    for attempt in range(2):
        # 420 s is generous for the primary phase alone (~90 s observed on
        # hardware incl. param gen, but tunnel init alone has taken ~90 s
        # on a sick-but-alive tunnel); capping it keeps a hung tunnel from
        # eating the whole deadline before the CPU fallback
        budget = min(420.0, deadline - time.monotonic())
        if budget < 120:
            break
        result, err = _run_child({"BENCH_PHASE": "primary"}, budget)
        if result is not None:
            result["attempts"] = attempt + 1
            bank(result)
            break
        errors.append(f"primary[{attempt}]: {err}")
        print(f"[bench-watchdog] {errors[-1]}", file=sys.stderr, flush=True)
        if err and err.startswith("NO_BACKEND"):
            break  # dead tunnel: spend the remaining budget on CPU fallback
        if attempt < 1:
            time.sleep(15)

    if merged is None:
        # CPU fallback: degraded evidence beats no evidence
        budget = max(120.0, deadline - time.monotonic())
        result, err = _run_child(
            {"BENCH_PHASE": "primary", "BENCH_FORCE_CPU": "1", "GRAFT_SMALL": "1"},
            budget,
        )
        if result is not None:
            result["platform"] = "cpu-fallback"
            result["tpu_errors"] = errors
            bank(result)
        else:
            errors.append(f"cpu: {err}")
            print(json.dumps({
                "metric": METRIC, "value": None, "unit": "tok/s",
                "vs_baseline": None, "error": "; ".join(errors)[-1200:],
            }))
            return

    # -- extras, each sandboxed in its own child + timeout ------------------
    force_cpu = merged.get("platform") == "cpu-fallback"
    extra_env = (
        {"BENCH_FORCE_CPU": "1", "GRAFT_SMALL": "1"} if force_cpu else {}
    )
    # priority order under a shared deadline = the round-4 verdict's:
    # serving numbers, the 8B north star, then the ablation diagnostics.
    # parity runs LAST (after the sweep): it is the phase most likely to
    # blow its budget (two fresh engine compiles + 512 host-stepped
    # decodes), and a timeout kill mid-TPU-RPC has wedged the tunnel for
    # every phase after it (round 5) — order so a wedge costs nothing.
    for phase, cap in (
        ("serving", 420.0), ("serving_churn", 300.0),
        ("serving_prefix", 240.0), ("pod_serving", 300.0),
        ("serving_faults", 240.0), ("serving_recovery", 240.0),
        ("serving_fleet", 240.0), ("serving_structured", 240.0),
        ("serving_disagg", 240.0),
        ("8b", 500.0), ("ablations", 420.0), ("longctx", 300.0),
    ):
        budget = min(cap, deadline - time.monotonic() - 10)
        if budget < 90:
            errors.append(f"{phase}: skipped (out of budget)")
            continue
        result, err = _run_child({"BENCH_PHASE": phase, **extra_env}, budget)
        if result is not None:
            bank(result)
        else:
            errors.append(f"{phase}: {err}")
            print(f"[bench-watchdog] {errors[-1]}", file=sys.stderr, flush=True)

    # -- kernel-knob sweep, TPU only: A/B the slab kernel's DMA geometry ----
    # (round-4 verdict #1: the sweep harness existed but never produced a
    # datapoint; running it inside the bench banks the A/B even when the
    # tunnel only comes back for the driver's round-end run). Each combo is
    # a fresh primary child (the knobs are read at module import); if one
    # beats the default headline by >2%, the headline adopts it and records
    # the knobs.
    if merged.get("platform") == "tpu":
        from distributed_llama_multiusers_tpu.ops.pallas_q40 import (
            DEFAULT_COMBO,
            DEQUANT_MODES,
            SWEEP_COMBOS,
        )

        tunnel_dead = False
        sweep: dict = {}
        # dequant-arithmetic variants FIRST (the round-5 hypothesis: the
        # kernel is VPU-bound on the dequant chain, so arithmetic beats DMA
        # geometry as the lever), then the DMA geometry combos
        candidates = [
            (f"dequant_{m}", {"DLLAMA_DEQUANT": m})
            for m in DEQUANT_MODES if m != "v4"
        ] + [
            # the round-2 kernel's narrow-tile layout (512-lane blocks,
            # ~256 KB chunks) measured hbm_util 0.438 where the full-width
            # slab measured 0.259 — reproduce it as a geometry candidate
            ("r02_narrow512", {
                "DLLAMA_W_MAX": "512",
                "DLLAMA_SINGLE_SLAB": "262144",
                "DLLAMA_TARGET_BLOCK": "262144",
            }),
        ] + [
            # geometry largest-first: the whole-plane single-DMA combo is
            # the most distinct datapoint, the near-default ones the least
            (n, {"DLLAMA_SINGLE_SLAB": str(s), "DLLAMA_TARGET_BLOCK": str(b)})
            for n, (s, b) in reversed(list(SWEEP_COMBOS.items()))
            if n != DEFAULT_COMBO
        ]
        combos = candidates[:7]
        for n, _ in candidates[7:]:  # no silent caps
            errors.append(f"sweep[{n}]: skipped (combo cap)")
        best_env: dict = {}
        for name, env in combos:
            budget = min(300.0, deadline - time.monotonic() - 10)
            if budget < 90:
                errors.append("sweep: skipped (out of budget)")
                break
            result, err = _run_child({"BENCH_PHASE": "primary", **env}, budget)
            if result is not None and result.get("value"):
                sweep[name] = {
                    k: result.get(k)
                    for k in ("value", "hbm_util", "weight_read_gb_s")
                }
                if result["value"] > (merged.get("value") or 0) * 1.02:
                    merged.update({
                        k: result[k]
                        for k in ("value", "hbm_util", "weight_read_gb_s", "mfu")
                        if k in result
                    })
                    merged["kernel_knobs"] = name
                    best_env = env
                    if name.startswith("dequant_"):
                        # a measured dequant win feeds the persisted
                        # selection table so DLLAMA_DEQUANT=auto serves it
                        # from the next warmup on (primary measures decode,
                        # so the row lands in the decode m-class)
                        try:
                            from distributed_llama_multiusers_tpu.ops import (
                                dequant_select,
                            )

                            dequant_select.record_win(
                                "*", "*", "decode", name[len("dequant_"):],
                                source="bench.py in-bench sweep (primary A/B"
                                f", {merged.get('device_kind') or 'tpu'})",
                            )
                        except Exception as exc:  # table update is advisory
                            errors.append(f"sweep[{name}]: record_win: {exc}")
                    # keep the headline ratio consistent with the adopted
                    # value (the 8b matched-model overwrite below may still
                    # supersede it)
                    merged["vs_baseline"] = round(
                        result["value"] / REFERENCE_SINGLE_DEVICE_TOK_S, 2
                    )
            else:
                errors.append(f"sweep[{name}]: {err}")
                if err and err.startswith("NO_BACKEND"):
                    tunnel_dead = True
                    break  # tunnel died mid-sweep: stop burning budget
        if sweep:
            bank({"kernel_sweep": sweep})

        # pod serving under the ADOPTED kernel knobs (if the sweep found a
        # winner): one unattended pass banks the kernel A/B AND the pod
        # number for the same configuration — the next tunnel window needs
        # no second run to connect them
        if best_env and not tunnel_dead:
            budget = min(300.0, deadline - time.monotonic() - 10)
            if budget >= 90:
                result, err = _run_child(
                    {"BENCH_PHASE": "pod_serving", **best_env}, budget
                )
                if result is not None:
                    bank({"pod_serving_swept": {
                        **result, "knobs": merged.get("kernel_knobs"),
                    }})
                else:
                    errors.append(f"pod_serving_swept: {err}")
            else:
                errors.append("pod_serving_swept: skipped (out of budget)")

        # parity last — see the phase-order comment above. It runs under
        # the ADOPTED sweep knobs (if any), so the token-identity gate
        # describes the same configuration as the headline number
        budget = min(300.0, deadline - time.monotonic() - 10)
        if tunnel_dead:
            errors.append("parity: skipped (tunnel died mid-sweep)")
        elif budget >= 90:
            result, err = _run_child(
                {"BENCH_PHASE": "parity", **best_env}, budget
            )
            if result is not None:
                if best_env:
                    result["parity_knobs"] = merged.get("kernel_knobs")
                bank(result)
            else:
                errors.append(f"parity: {err}")
        else:
            errors.append("parity: skipped (out of budget)")
    else:
        errors.append("parity: skipped (off-TPU)")

    # matched-model headline ratio: once the 8B north star lands on TPU,
    # compare it (not the 1B primary) against the reference's published 7B
    # number — the closest model-for-model comparison available
    eight_b = merged.get("llama31_8b_q40_decode_tok_s")
    if eight_b and merged.get("platform") == "tpu":
        merged["vs_baseline"] = round(eight_b / REFERENCE_SINGLE_DEVICE_TOK_S, 2)
        merged["vs_baseline_model"] = (
            "llama31_8b_q40 (this, 1 TPU chip) vs llama2_7b_q40 "
            "(reference, 1x RPi 4B, report.pdf Fig.3)"
        )

    if errors:
        merged["phase_errors"] = "; ".join(errors)[-600:]
    print(json.dumps(merged), flush=True)


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        child_main()
    else:
        main()
