"""guarded-by: lock discipline for declared shared attributes.

A class declares which of its attributes a lock guards with a plain
(non-annotated, so dataclasses ignore it) class attribute:

    class EngineStats:
        _dlint_guarded_by = {
            ("lock",): ("decode_steps", "host_bytes_in", ...),
        }

Keys are tuples of acceptable lock attribute names (a Condition built over
the lock counts — holding either is holding the lock); values are the
guarded attribute names. Enforcement is lexical and name-based (no type
inference): any ``BASE.attr`` access where ``attr`` is declared guarded
must sit inside ``with BASE.<lock>:`` for one of the acceptable locks on
the *same* base expression — so ``self.engine.stats.prefix_hits`` needs
``with self.engine.stats.lock:``, and a lock held on a different object
does not count. Exemptions, matching classic @GuardedBy semantics:

- ``__init__`` bodies (the object is not shared yet);
- methods named ``*_locked`` (the caller holds the lock by contract);
- waivers, for contractually-racy advisory reads.

Name-based matching means guarded attribute names should be distinctive;
the declared sets here (EngineStats counters, QosQueue internals) are
unique within the package, which is the analyzer's default scope.
"""

from __future__ import annotations

import ast

from .core import (
    GUARD_DECL_NAME,
    Checker,
    Finding,
    Project,
    SourceFile,
    nearest,
    walk_with_ancestors,
)


class GuardedByChecker(Checker):
    name = "guarded-by"
    description = (
        "attributes declared in _dlint_guarded_by may only be touched "
        "inside `with <base>.<lock>:` (or __init__ / *_locked methods)"
    )

    # -- collect: find declarations anywhere in the analyzed set ------------

    def collect(self, sf: SourceFile, project: Project) -> None:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if not (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == GUARD_DECL_NAME
                ):
                    continue
                try:
                    decl = ast.literal_eval(stmt.value)
                    if not isinstance(decl, dict):
                        raise ValueError("declaration must be a dict literal")
                    items = []
                    for locks, attrs in decl.items():
                        locks_t = (locks,) if isinstance(locks, str) else tuple(locks)
                        attrs_t = (attrs,) if isinstance(attrs, str) else tuple(attrs)
                        if not locks_t or not all(isinstance(x, str) for x in locks_t):
                            raise ValueError("lock names must be strings")
                        if not all(isinstance(x, str) for x in attrs_t):
                            raise ValueError("attribute names must be strings")
                        items.append((frozenset(locks_t), attrs_t))
                except (ValueError, TypeError, SyntaxError) as e:
                    project.collect_findings.append(Finding(
                        self.name, sf.display, stmt.lineno,
                        f"malformed {GUARD_DECL_NAME} on class {node.name}: {e} "
                        "(expected {('lock', ...): ('attr', ...)} literals)",
                    ))
                    continue
                site = f"{node.name} ({sf.display})"
                for locks, attrs in items:
                    for attr in attrs:
                        prev = project.guarded.get(attr)
                        if prev is not None and prev[0] != locks:
                            project.collect_findings.append(Finding(
                                self.name, sf.display, stmt.lineno,
                                f"guarded attribute {attr!r} redeclared with "
                                f"different locks (first declared by {prev[1]})",
                            ))
                            continue
                        project.guarded[attr] = (locks, site)

    # -- check --------------------------------------------------------------

    def check(self, sf: SourceFile, project: Project):
        if not project.guarded:
            return
        for node, ancestors in walk_with_ancestors(sf.tree):
            if not isinstance(node, ast.Attribute):
                continue
            entry = project.guarded.get(node.attr)
            if entry is None:
                continue
            locks, decl_site = entry
            func = nearest(ancestors, ast.FunctionDef, ast.AsyncFunctionDef)
            if func is not None and (
                func.name == "__init__" or func.name.endswith("_locked")
            ):
                continue
            base = ast.unparse(node.value)
            accepted = {f"{base}.{lk}" for lk in locks}
            if self._held(ancestors, accepted):
                continue
            yield Finding(
                self.name, sf.display, node.lineno,
                f"'{base}.{node.attr}' accessed outside "
                f"'with {base}.{{{'|'.join(sorted(locks))}}}:' "
                f"(declared guarded by {decl_site})",
            )

    @staticmethod
    def _held(ancestors, accepted: set[str]) -> bool:
        """Scan ancestors innermost-out, stopping at the first function or
        lambda boundary: a closure DEFINED inside `with lock:` runs after
        the lock is released, so an enclosing with-block beyond the def
        does not protect accesses in the closure body."""
        for a in reversed(ancestors):
            if isinstance(a, (ast.With, ast.AsyncWith)):
                for item in a.items:
                    if ast.unparse(item.context_expr) in accepted:
                        return True
            elif isinstance(
                a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return False
        return False
