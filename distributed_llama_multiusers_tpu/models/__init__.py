from .config import LlamaConfig
from .llama import LlamaParams, llama_forward, llama_forward_train, init_kv_cache
from .loader import load_params_from_m, params_from_random
