"""condvar: condition/event/thread hygiene in the concurrent serving path.

Three classic latent-bug shapes, all of which have bitten continuous-
batching servers:

1. **Condition.wait outside a predicate loop** — condition variables wake
   spuriously and race with other waiters; a bare ``cv.wait()`` that is
   not re-checking its predicate in a ``while`` (or using ``wait_for``)
   proceeds on stale state.
2. **Event.wait with a tiny timeout** — ``ev.wait(0.001)`` in a loop is a
   busy-poll dressed as a wait: it burns a core and adds latency jitter.
   Park on a real condition (the queue's) or use a meaningful timeout.
3. **daemon threads with no join** — ``Thread(daemon=True)`` started by a
   class/function whose scope never ``join``s anything means the stop
   path abandons a live thread that still mutates shared state (the seed
   repo's loop-thread leak, SURVEY.md §2.3 defect (d)).

Attributes/locals are classified by their construction site
(``threading.Condition(...)`` / ``threading.Event(...)`` assignments,
including dataclass ``field(default_factory=threading.Event)``), matched
by name within the file — no type inference, so keep constructor
assignments and use sites in the same module (they naturally are).
"""

from __future__ import annotations

import ast
import re

from .core import Checker, Finding, Project, SourceFile, walk_with_ancestors

COND_RE = re.compile(r"\bthreading\.Condition\b|\bCondition\(")
EVENT_RE = re.compile(r"\bthreading\.Event\b|\bEvent\(")
BUSY_POLL_S = 0.05  # Event.wait timeouts under this are busy-polls


def _target_names(tgt: ast.AST) -> list[str]:
    """Bindable name of an assignment target: `x` -> x, `self._stop` ->
    _stop (the attribute name is what use sites spell)."""
    if isinstance(tgt, ast.Name):
        return [tgt.id]
    if isinstance(tgt, ast.Attribute):
        return [tgt.attr]
    if isinstance(tgt, (ast.Tuple, ast.List)):
        out = []
        for e in tgt.elts:
            out.extend(_target_names(e))
        return out
    return []


class CondvarChecker(Checker):
    name = "condvar"
    description = (
        "Condition.wait needs a predicate loop; Event.wait(<0.05s) is a "
        "busy-poll; daemon threads need a join on the stop path"
    )

    def check(self, sf: SourceFile, project: Project):
        conds, events = self._classify(sf.tree)
        for node, ancestors in walk_with_ancestors(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr == "wait":
                holder = (
                    func.value.attr
                    if isinstance(func.value, ast.Attribute)
                    else func.value.id if isinstance(func.value, ast.Name) else None
                )
                if holder in conds:
                    if not any(isinstance(a, ast.While) for a in ancestors):
                        yield Finding(
                            self.name, sf.display, node.lineno,
                            f"Condition.wait on '{ast.unparse(func.value)}' "
                            "without an enclosing predicate loop — use "
                            "'while <pred>: cv.wait(...)' or cv.wait_for()",
                        )
                elif holder in events:
                    timeout = self._const_timeout(node)
                    if timeout is not None and timeout < BUSY_POLL_S:
                        yield Finding(
                            self.name, sf.display, node.lineno,
                            f"busy-poll: Event.wait({timeout:g}) on "
                            f"'{ast.unparse(func.value)}' — park on a "
                            "condition variable or use a real timeout",
                        )
            elif self._is_daemon_thread(node):
                scope = self._join_scope(ancestors, sf.tree)
                if not self._has_join(scope):
                    where = getattr(scope, "name", "module scope")
                    yield Finding(
                        self.name, sf.display, node.lineno,
                        "daemon Thread started with no .join() anywhere in "
                        f"'{where}' — the stop path abandons a live thread "
                        "still mutating shared state",
                    )

    # -- helpers ------------------------------------------------------------

    @staticmethod
    def _classify(tree: ast.AST) -> tuple[set[str], set[str]]:
        conds: set[str] = set()
        events: set[str] = set()
        for node in ast.walk(tree):
            targets: list[ast.AST] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None:
                continue
            rhs = ast.unparse(value)
            bucket = None
            if COND_RE.search(rhs):
                bucket = conds
            elif EVENT_RE.search(rhs):
                bucket = events
            if bucket is None:
                continue
            for tgt in targets:
                bucket.update(_target_names(tgt))
        return conds, events

    @staticmethod
    def _const_timeout(node: ast.Call) -> float | None:
        arg = None
        if node.args:
            arg = node.args[0]
        else:
            for kw in node.keywords:
                if kw.arg == "timeout":
                    arg = kw.value
        if isinstance(arg, ast.Constant) and isinstance(arg.value, (int, float)):
            return float(arg.value)
        return None

    @staticmethod
    def _is_daemon_thread(node: ast.Call) -> bool:
        func = node.func
        callee = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        if callee != "Thread":
            return False
        return any(
            kw.arg == "daemon"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            for kw in node.keywords
        )

    @staticmethod
    def _join_scope(ancestors, tree: ast.AST) -> ast.AST:
        """Where a matching join must live: the enclosing class if any
        (create in start(), join in stop()), else the enclosing function,
        else the module."""
        for a in reversed(ancestors):
            if isinstance(a, ast.ClassDef):
                return a
        for a in reversed(ancestors):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return tree

    @staticmethod
    def _has_join(scope: ast.AST) -> bool:
        for node in ast.walk(scope):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and not (
                    # exclude str.join / os.path.join — receivers are a
                    # string constant or a *path attribute chain
                    isinstance(node.func.value, ast.Constant)
                    or ast.unparse(node.func.value).endswith("path")
                )
            ):
                return True
        return False
