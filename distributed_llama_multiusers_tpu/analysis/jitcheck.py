"""Runtime recompile witness (``DLLAMA_JITCHECK=1``).

The static surface model (``jitmodel.py`` + the ``warmup-coverage`` /
``jit-stability`` checks) proves what the SOURCE compiles at warmup;
this module proves what the PROCESS compiles after it — the
``lockcheck.make_lock`` pattern applied to compile stability. A
``jax.monitoring`` duration listener counts backend XLA compiles
(``/jax/core/compile/backend_compile_duration`` fires exactly once per
real compile and never on an executable-cache hit):

- ``warming()`` — ``warmup_engine`` wraps its body in this context, so
  warmup's own compiles (of ANY engine in the process — tests build
  several) never count against an armed witness;
- ``arm(stats)`` — called by ``warmup_engine`` as its last act: from
  here on, every backend compile bumps the engine's
  ``EngineStats.jit_compiles_after_warmup`` counter (under the stats
  lock — surfaced on ``/stats``, bridged to ``/metrics``, banked by the
  bench phases as ``*_compiles_after_warmup``), and with the witness
  ENABLED (``DLLAMA_JITCHECK=1`` or :func:`force`) additionally raises
  :class:`RecompileAfterWarmup` out of the guilty dispatch — a stack
  trace at the exact call that changed an aval or hit an unwarmed
  family, instead of a latency graph three weeks later.

The counter is always on once armed (one listener call per compile —
compiles are the rare event being asserted absent — and zero per-step
overhead); only the RAISE is opt-in, mirroring the lock witness's
zero-production-overhead contract. Pure stdlib at import; jax is
imported lazily the first time a witness is armed, so ``make lint``'s
jax-free import surface is untouched.
"""

from __future__ import annotations

import contextlib
import os
import threading
import weakref

from ..lockcheck import make_lock

ENV_FLAG = "DLLAMA_JITCHECK"
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_forced: bool | None = None
# guards the registry below (never held around a sink's stats lock or
# any jax call — the listener snapshots under it and bumps outside, so
# the package lock-order graph stays edge-free)
_lock = make_lock("jitcheck._lock")
_installed = False
_pause_depth = 0
_armed = False
_sinks: list = []  # weakrefs to EngineStats-like sinks
_total_compiles = 0  # process lifetime, diagnostics


class RecompileAfterWarmup(AssertionError):
    """XLA compiled a new program after ``warmup_engine`` returned.
    AssertionError on purpose (the lockcheck convention): the witness is
    a test-time oracle and a post-warmup compile is a failed invariant —
    an unwarmed (family, bucket) or an aval-changing operand — not an
    operational error to catch and retry."""


def enabled() -> bool:
    """Strict mode: raise on post-warmup compiles (the counter runs
    regardless once armed)."""
    if _forced is not None:
        return _forced
    return os.environ.get(ENV_FLAG, "") not in ("", "0")


def force(value: bool | None, fresh: bool = True) -> None:
    """Test hook: override the env flag (None restores it). ``fresh``
    disarms and drops registered sinks so the next ``arm`` starts
    clean; the process-global jax listener stays installed (it is
    inert while disarmed)."""
    global _forced, _armed
    _forced = value
    if fresh:
        with _lock:
            _armed = False
            _sinks.clear()


def _on_duration(event: str, duration: float, **kw) -> None:
    """The jax.monitoring listener — one call per backend compile."""
    global _total_compiles
    if event != COMPILE_EVENT:
        return
    with _lock:
        _total_compiles += 1
        if _pause_depth > 0 or not _armed:
            return
        sinks = [ref() for ref in _sinks]
    strict = enabled()
    for stats in sinks:
        if stats is None:
            continue
        # EngineStats discipline: the counter is declared in
        # _dlint_guarded_by, so the bump holds the stats lock
        with stats.lock:
            stats.jit_compiles_after_warmup += 1
    if strict:
        raise RecompileAfterWarmup(
            "XLA compiled a new program after warmup_engine returned — "
            "an unwarmed (family, bucket) or an aval-changing operand "
            "rebuild; the dispatch that triggered it is in this stack. "
            "Fix the warmup/leaf recipe (see docs/LINT.md, "
            "warmup-coverage / jit-stability) rather than disabling "
            f"{ENV_FLAG}."
        )


def _install() -> None:
    """Register the process-global listener once. Caller holds no lock;
    jax import happens here, lazily — the arming site already runs under
    jax by construction (it just finished a warmup)."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    import jax.monitoring

    jax.monitoring.register_event_duration_secs_listener(_on_duration)


@contextlib.contextmanager
def warming():
    """Suppress counting/raising for the duration (re-entrant):
    ``warmup_engine`` compiles on purpose, and one engine's warmup must
    not fire another engine's armed witness in the same process."""
    global _pause_depth
    with _lock:
        _pause_depth += 1
    try:
        yield
    finally:
        with _lock:
            _pause_depth -= 1


def arm(stats) -> None:
    """Start witnessing for ``stats`` (an ``EngineStats``: needs
    ``.lock`` and ``.jit_compiles_after_warmup``). Idempotent per
    object; sinks are weak so dead engines cost nothing."""
    _install()
    with _lock:
        global _armed
        _armed = True
        _sinks[:] = [r for r in _sinks if r() is not None]
        if not any(r() is stats for r in _sinks):
            _sinks.append(weakref.ref(stats))


def armed() -> bool:
    with _lock:
        return _armed


def total_compiles() -> int:
    """Process-lifetime backend compile count (0 until a witness was
    armed at least once — the listener installs lazily)."""
    with _lock:
        return _total_compiles
