"""On-device packed Q40 weights: int4 nibbles + f16 block scales in HBM.

The reference keeps Q40 weights quantized at rest and dequantizes inside the
matmul kernel (src/nn/nn-cpu-ops.cpp:222-440 matmul_Q80_Q40_F32,
src/nn/vulkan/matmul-forward-q80-q40-f32.comp); the bf16 loader path instead
dequantizes on the host and ships 4x the bytes to HBM. Since TPU decode is
HBM-bandwidth-bound, keeping weights at 4 bit + 1/32 f16 scale (~4.5 bits/
element, exactly the .m Q40 footprint) is the main single-chip perf lever.

Device layout, chosen so that unpacking needs no nibble interleave:

    packed: uint8 [..., d_in//2, d_out]
        packed[i, o] = (v[i, o] + 8) | ((v[i + d_in//2, o] + 8) << 4)
    scales: float16 [..., d_in//32, d_out]
        scales[b, o] covers input rows i in [32b, 32b+32)

i.e. the weight is stored transposed ([d_in, d_out], ready for y = x @ W)
with the low-nibble plane holding the first half of d_in and the high-nibble
plane the second half — unpack is two shifts + a concat, both layout-friendly
on TPU (the split planes are contiguous sublane ranges). Matmul reduction
order is i-invariant, so any consistent permutation of d_in would be legal;
the identity-halves choice keeps x untouched and scales in original block
order. Dequantization is (nibble - 8) * f16(scale), bit-identical to
src/nn/nn-quants.cpp:229-246.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax.numpy as jnp

from .codec import Q40_BLOCK_SIZE, q40_to_planar, quantize_q40


class PackedQ40(NamedTuple):
    """A Q40-quantized matmul weight resident on device.

    Logical shape [..., d_in, d_out] for y = x @ W; ``logical_shape`` helpers
    below recover it from the stored planes.
    """

    packed: jnp.ndarray  # uint8 [..., d_in//2, d_out]
    scales: jnp.ndarray  # float16 [..., d_in//32, d_out]

    @property
    def d_in(self) -> int:
        return self.packed.shape[-2] * 2

    @property
    def d_out(self) -> int:
        return self.packed.shape[-1]


def pack_q40_planar(values: np.ndarray, scales: np.ndarray):
    """Host-side repack: planar int8 values [..., d_out, d_in] (centered at 0,
    file orientation) + f16-exact scales [..., d_out, d_in//32] -> the device
    layout (packed uint8 [..., d_in//2, d_out], scales f16 [..., d_in//32, d_out])."""
    d_in = values.shape[-1]
    assert d_in % Q40_BLOCK_SIZE == 0 and d_in % 2 == 0, values.shape
    v = np.swapaxes(values, -1, -2)  # [..., d_in, d_out]
    half = d_in // 2
    lo = (v[..., :half, :].astype(np.int16) + 8).astype(np.uint8)
    hi = (v[..., half:, :].astype(np.int16) + 8).astype(np.uint8)
    packed = (lo & 0x0F) | ((hi & 0x0F) << 4)
    scales_t = np.swapaxes(scales, -1, -2).astype(np.float16)  # [..., d_in//32, d_out]
    return packed, scales_t


def pack_q40_from_blocks(raw_blocks: np.ndarray, shape: tuple[int, int]):
    """Packed .m Q40 block bytes (row-major over [d_out, d_in], blocks along
    d_in — src/llm.cpp:447-483 tensor layout) -> device layout, WITHOUT
    dequantizing. Returns (packed uint8 [d_in//2, d_out], scales f16
    [d_in//32, d_out])."""
    d_out, d_in = shape
    values, scales = q40_to_planar(raw_blocks)  # [(d_out*d_in/32), 32], f32 scales
    values = values.reshape(d_out, d_in)
    scales = scales.reshape(d_out, d_in // Q40_BLOCK_SIZE)
    return pack_q40_planar(values, scales)


def pack_q40_host(w: np.ndarray):
    """Quantize a float weight in file orientation [..., d_out, d_in] to the
    device layout (through the bit-exact Q40 encoder, codec.quantize_q40)."""
    lead = w.shape[:-2]
    d_out, d_in = w.shape[-2], w.shape[-1]
    blocks = quantize_q40(np.ascontiguousarray(w, np.float32).reshape(-1))
    values, scales = q40_to_planar(blocks)
    values = values.reshape(*lead, d_out, d_in)
    scales = scales.reshape(*lead, d_out, d_in // Q40_BLOCK_SIZE)
    return pack_q40_planar(values, scales)


def unpack_q40(w: PackedQ40, dtype=jnp.float32) -> jnp.ndarray:
    """Dequantize to a dense [..., d_in, d_out] array (XLA fallback path;
    the Pallas kernel in ops/pallas_q40.py does this tile-wise in VMEM)."""
    lo = (w.packed & 0x0F).astype(jnp.int8) - 8
    hi = (w.packed >> 4).astype(jnp.int8) - 8
    vals = jnp.concatenate([lo, hi], axis=-2)  # [..., d_in, d_out]
    scales = jnp.repeat(
        w.scales.astype(jnp.float32), Q40_BLOCK_SIZE, axis=-2
    )  # [..., d_in, d_out]
    return (vals.astype(jnp.float32) * scales).astype(dtype)


def q40_matmul_xla(x: jnp.ndarray, w: PackedQ40, compute_dtype=None) -> jnp.ndarray:
    """y = x @ dequant(w) without a Pallas kernel. XLA fuses the unpack/scale
    into the matmul's weight-read loop where it can; correctness path for CPU
    tests and the fallback when Pallas is unavailable."""
    dtype = compute_dtype or x.dtype
    wd = unpack_q40(w, dtype)
    return jnp.matmul(x, wd, preferred_element_type=jnp.float32).astype(x.dtype)
