from .tokenizer import Tokenizer
from .chat import ChatTemplateGenerator, ChatItem, GeneratedChat, TokenizerChatStops, TemplateType
from .eos import EosDetector, EosResult
from .sampler import Sampler
