"""OpenAI-ish JSON request/response shapes (reference: src/api-types.hpp).

The fork's web UI reads the non-standard ``generated_text`` field
(web-ui/app.js:27-40); standard clients read ``choices``. Responses carry
both."""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..serving.qos import Priority


@dataclass
class ChatMessage:
    role: str
    content: str


def parse_chat_messages(body: dict) -> list[ChatMessage]:
    """api-types.hpp:166-177."""
    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        raise ValueError("missing messages")
    out = []
    for m in messages:
        if not isinstance(m, dict) or "role" not in m or "content" not in m:
            raise ValueError("message entries need role and content")
        content = m["content"]
        if isinstance(content, list):  # OpenAI content-part arrays
            content = "".join(
                p.get("text", "") for p in content if isinstance(p, dict) and p.get("type") == "text"
            )
        out.append(ChatMessage(role=str(m["role"]), content=str(content)))
    return out


@dataclass
class InferenceParams:
    """Per-request generation params (dllama-api.cpp parseRequest analogue —
    but actually honored here, unlike the fork).

    Sampling semantics: sampled requests run on-device (fused into the
    compiled decode step) over the top-64 logits — exact whenever the
    nucleus fits, which is the overwhelmingly common case. Requests with
    top_p >= 0.99 or temperature >= 1.5 automatically fall back to the
    bit-exact full-vocab host sampler (reference xorshift semantics,
    runtime/scheduler.py HOST_EXACT_*), trading one [vocab] f32 transfer
    per token for distribution exactness."""

    max_tokens: int = 128
    temperature: float = 0.0
    top_p: float = 0.9
    seed: int | None = None
    stop: list[str] = field(default_factory=list)
    stream: bool = False
    # structured output (grammar/; docs/SERVING.md "Structured output"):
    # {"type": "json_object"} or {"type": "json_schema", ...}; validated
    # structurally at parse time so malformed schemas 400 before any
    # admission work
    response_format: dict | None = None
    # QoS identity (serving/qos.py): "user" is the OpenAI API's own
    # end-user field and keys the per-user fair share; "priority" is
    # "high" | "normal" | "low" (or the int class value)
    user: str = ""
    priority: int = Priority.NORMAL

    @staticmethod
    def from_body(body: dict) -> "InferenceParams":
        p = InferenceParams()
        if "max_tokens" in body:
            p.max_tokens = max(1, int(body["max_tokens"]))
        if "temperature" in body and body["temperature"] is not None:
            p.temperature = float(body["temperature"])
        if "top_p" in body and body["top_p"] is not None:
            p.top_p = float(body["top_p"])
        if "seed" in body and body["seed"] is not None:
            p.seed = int(body["seed"])
        stop = body.get("stop")
        if isinstance(stop, str):
            p.stop = [stop]
        elif isinstance(stop, list):
            p.stop = [str(s) for s in stop]
        p.stream = bool(body.get("stream", False))
        if body.get("response_format") is not None:
            # GrammarError is a ValueError -> the route's typed 400; the
            # canonical form ships onward so journal/migration records
            # are stable regardless of client-side field ordering
            from ..grammar.automaton import validate_response_format

            validate_response_format(body["response_format"])
            p.response_format = dict(body["response_format"])
        if body.get("user") is not None:
            p.user = str(body.get("user", ""))
        if body.get("priority") is not None:
            p.priority = Priority.parse(body["priority"])  # ValueError -> 400
        return p


def chat_completion_response(
    model: str, req_id: int, text: str, prompt_tokens: int, completion_tokens: int,
    finish_reason: str = "stop", summary: dict | None = None,
) -> dict:
    out = {
        "id": f"chatcmpl-{req_id}",
        "object": "chat.completion",
        "created": int(time.time()),  # dlint: ok[clock] 'created' is an absolute unix timestamp by OpenAI API contract
        "model": model,
        "generated_text": text,  # fork-compat field (dllama-api.cpp:283)
        "choices": [
            {
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": finish_reason,
            }
        ],
        "usage": {
            "prompt_tokens": prompt_tokens,
            "completion_tokens": completion_tokens,
            "total_tokens": prompt_tokens + completion_tokens,
        },
    }
    if summary is not None:
        # per-request telemetry summary (telemetry/spans.py RequestTrace;
        # docs/OBSERVABILITY.md): ttft_s, tbt p50/p95, queued_s, ... —
        # the same dict the server's per-request JSON log line carries
        out["summary"] = summary
    return out


def chat_chunk_response(
    model: str, req_id: int, delta: str | None, done: bool,
    finish_reason: str = "stop", summary: dict | None = None,
) -> dict:
    choice: dict = {"index": 0, "delta": {}, "finish_reason": finish_reason if done else None}
    if delta:
        choice["delta"] = {"content": delta}
    out = {
        "id": f"chatcmpl-{req_id}",
        "object": "chat.completion.chunk",
        "created": int(time.time()),  # dlint: ok[clock] 'created' is an absolute unix timestamp by OpenAI API contract
        "model": model,
        "choices": [choice],
    }
    if done and summary is not None:
        out["summary"] = summary  # terminal chunk only, same dict as non-stream
    return out


def parse_completion_prompt(body: dict) -> str:
    """Raw prompt for /v1/completions: a string, or a 1-element list of
    strings (the OpenAI API's batched-prompt form; >1 is unsupported —
    submit them as separate requests, the batching loop runs them
    concurrently anyway)."""
    prompt = body.get("prompt")
    if isinstance(prompt, list):
        if len(prompt) > 1:
            raise ValueError(
                "prompt lists with more than one entry are unsupported; "
                "submit separate requests (they batch concurrently)"
            )
        prompt = prompt[0] if prompt else None
    if not isinstance(prompt, str) or not prompt:
        raise ValueError(
            "missing or empty 'prompt' (must be a non-empty string or a "
            "1-element list of strings; token-id prompts are unsupported)"
        )
    return prompt


def completion_response(
    model: str, req_id: int, text: str, prompt_tokens: int, completion_tokens: int,
    finish_reason: str = "stop", summary: dict | None = None,
) -> dict:
    out = {
        "id": f"cmpl-{req_id}",
        "object": "text_completion",
        "created": int(time.time()),  # dlint: ok[clock] 'created' is an absolute unix timestamp by OpenAI API contract
        "model": model,
        "generated_text": text,  # fork-compat field, same as the chat route
        "choices": [
            {"index": 0, "text": text, "finish_reason": finish_reason}
        ],
        "usage": {
            "prompt_tokens": prompt_tokens,
            "completion_tokens": completion_tokens,
            "total_tokens": prompt_tokens + completion_tokens,
        },
    }
    if summary is not None:
        out["summary"] = summary  # per-request telemetry (OBSERVABILITY.md)
    return out


def completion_chunk_response(
    model: str, req_id: int, delta: str | None, done: bool,
    finish_reason: str = "stop", summary: dict | None = None,
) -> dict:
    out = {
        "id": f"cmpl-{req_id}",
        "object": "text_completion",
        "created": int(time.time()),  # dlint: ok[clock] 'created' is an absolute unix timestamp by OpenAI API contract
        "model": model,
        "choices": [
            {
                "index": 0,
                "text": delta or "",
                "finish_reason": finish_reason if done else None,
            }
        ],
    }
    if done and summary is not None:
        out["summary"] = summary  # terminal chunk only, same dict as non-stream
    return out


def models_response(model: str) -> dict:
    return {
        "object": "list",
        "data": [
            # dlint: ok[clock] 'created' is an absolute unix timestamp by OpenAI API contract
            {"id": model, "object": "model", "created": int(time.time()), "owned_by": "user"}
        ],
    }
