"""`dllama-api` entry point: the multi-user HTTP server
(reference: src/dllama-api.cpp:388-411), backed by the continuous-batching
scheduler instead of the fork's serialized accept loop."""

from __future__ import annotations

import os
import signal

from ..server import ApiServer
from ..tokenizer import template_type_from_name
from .args import build_parser
from .runtime_setup import honor_cpu_platform_env, load_stack, log, make_scheduler


def main(argv=None) -> None:
    honor_cpu_platform_env()
    args = build_parser("dllama-api", api=True).parse_args(argv)
    config, params, tokenizer, engine = load_stack(args)
    scheduler = make_scheduler(engine, tokenizer, args)
    template_type = template_type_from_name(args.chat_template)
    model_name = os.path.basename(args.model or "dllama")
    server = ApiServer(scheduler, tokenizer, model_name=model_name, template_type=template_type)
    httpd = server.serve(host=args.host, port=args.port)
    log("⭐", f"Server listening on {args.host}:{args.port} ({engine.n_lanes} lanes)")

    def _shutdown(*_):
        log("⭐", "Shutting down")
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _shutdown)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        scheduler.stop()


if __name__ == "__main__":
    main()
