"""Span tracer: a bounded ring of lifecycle/step events, host-side only.

The scheduler stamps what it already knows from its own host metadata —
request lifecycle transitions (submitted → queued → admitted → prefill
chunks → pipelined dispatch/consume pairs → finish/cancel/timeout) and
per-dispatch step slices — into a fixed-capacity ring. Nothing in here
ever reads a device value (no numpy, no jax; the package is registered
under dlint's ``host-sync`` check), and nothing in here is called from
the pipelined DISPATCH half: step slices are recorded at CONSUME time,
one step behind, where the host is already blocking on the lagged
readback — so tracing adds zero syncs and zero locks to the hot
dispatch path (``decode_pipelined`` / ``decode_prefill_fused`` /
``_pipeline_dispatch``), which dlint's ``pipeline-sync`` check pins.

Timestamps are ``time.perf_counter()`` relative to the tracer's origin —
monotonic by construction (the ``clock`` check covers this package), and
exactly the timebase Chrome trace events want (µs offsets, not wall
time). The ring evicts oldest-first under overflow and counts what it
dropped, so a trace pulled from a long-lived server is the most recent
window, honestly labelled.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, replace

from ..lockcheck import make_lock
from .tracectx import trace_id_of


# span/instant names, for reference (docs/OBSERVABILITY.md lists them all):
#   queued          X  submit -> admit (or -> unadmitted resolution)
#   generate        X  admit -> finish, on the lane's track
#   prefill.sync    X  one synchronous prompt chunk on a lane
#   prefill.fused   X  one fused-dispatch prompt chunk on a lane
#   step.sync/spec/multi  X  one synchronous engine dispatch
#   step.pipelined  X  pipelined step, dispatch -> lagged consume
#   step.fused      X  fused prefill+decode step, dispatch -> lagged consume
#   submitted / admitted / finish.<reason> / pipeline.flush   i  instants


@dataclass(frozen=True)
class SpanEvent:
    """One trace event. ``ts``/``dur`` are seconds on the tracer's
    monotonic timebase; ``ph`` is the Chrome phase ("X" slice, "i"
    instant); ``track`` names the Perfetto row it lands on."""

    name: str
    ph: str
    ts: float
    dur: float
    track: str
    req_id: int | None = None
    args: dict | None = None
    # monotone per-tracer event cursor (assigned at append): pollers pass
    # the last seq they saw as /trace's `since=` param and stop
    # re-downloading the whole ring every scrape
    seq: int = 0


class SpanTracer:
    """Bounded, thread-safe event ring (oldest evicted first)."""

    # dlint guarded-by declaration (analysis/lock_check.py): ring state
    # only under `_trace_lock`. Machine-checked by `make lint`.
    _dlint_guarded_by = {
        ("_trace_lock",): (
            "_trace_ring", "_trace_dropped", "_trace_total", "_trace_seq",
            "_trace_dropped_by_track",
        ),
    }

    def __init__(self, capacity: int = 16384):
        self.capacity = max(1, int(capacity))
        # perf_counter origin: every event's ts is relative to this, so a
        # trace's µs timestamps start near 0 regardless of process uptime
        self.origin = time.perf_counter()
        # witness-wrappable (DLLAMA_LOCKCHECK=1): the literal names the
        # class-qualified declaration, cross-checked by dlint lock-order
        self._trace_lock = make_lock("SpanTracer._trace_lock")
        # eviction is explicit (not deque maxlen) so drops attribute to
        # the track they truncated — a silently shortened lane track is
        # the failure mode per-track counts exist to make visible
        self._trace_ring: deque[SpanEvent] = deque()
        self._trace_dropped = 0
        self._trace_dropped_by_track: dict[str, int] = {}
        self._trace_total = 0
        self._trace_seq = 0

    def now(self) -> float:
        return time.perf_counter()

    def _append(self, ev: SpanEvent) -> None:
        with self._trace_lock:
            self._trace_seq += 1
            ev = replace(ev, seq=self._trace_seq)
            if len(self._trace_ring) >= self.capacity:
                old = self._trace_ring.popleft()
                self._trace_dropped += 1
                self._trace_dropped_by_track[old.track] = (
                    self._trace_dropped_by_track.get(old.track, 0) + 1
                )
            self._trace_ring.append(ev)
            self._trace_total += 1

    def slice(self, name: str, track: str, t0: float, t1: float | None = None,
              req_id: int | None = None, args: dict | None = None) -> None:
        """Record a complete span [t0, t1] (t1 defaults to now)."""
        if t1 is None:
            t1 = time.perf_counter()
        self._append(SpanEvent(
            name, "X", t0, max(0.0, t1 - t0), track, req_id, args
        ))

    def instant(self, name: str, track: str, ts: float | None = None,
                req_id: int | None = None, args: dict | None = None) -> None:
        if ts is None:
            ts = time.perf_counter()
        self._append(SpanEvent(name, "i", ts, 0.0, track, req_id, args))

    def snapshot(self, since: int = 0,
                 trace_id: str | None = None) -> list[SpanEvent]:
        """Point-in-time copy of the ring, oldest first.

        ``since`` keeps only events with ``seq`` strictly greater (the
        /trace poller cursor); ``trace_id`` keeps only events whose args
        carry that trace id (the cross-replica merge filter)."""
        with self._trace_lock:
            events = list(self._trace_ring)
        if since:
            events = [e for e in events if e.seq > since]
        if trace_id is not None:
            events = [
                e for e in events
                if e.args is not None and e.args.get("trace_id") == trace_id
            ]
        return events

    def counts(self) -> dict:
        """{recorded, dropped, buffered, cursor, per-track drops} —
        surfaced on /stats so an evicting ring is visible, not silent,
        and a truncated track is attributable (dict-valued: the stats
        bridge republishes it as ``{key="..."}``-labelled gauges)."""
        with self._trace_lock:
            return {
                "trace_events_recorded": self._trace_total,
                "trace_events_dropped": self._trace_dropped,
                "trace_events_buffered": len(self._trace_ring),
                "trace_events_cursor": self._trace_seq,
                "trace_events_dropped_by_track": dict(
                    self._trace_dropped_by_track
                ),
            }


class RequestTrace:
    """Per-request latency record, attached to a ``Request`` at submit.

    NOT thread-safe by design: only the scheduler loop writes it (token
    stamps), and readers (summary in the HTTP response, the per-request
    log line) run after the request's future resolves, which the Future
    machinery orders after the scheduler's last write."""

    __slots__ = (
        "submitted_at", "admitted_at", "first_token_at", "last_token_at",
        "gaps", "n_tokens", "fused_admitted", "prefix_saved",
        "span_t0", "lane", "swap_in_s", "sync_s",
    )

    def __init__(self, submitted_at: float | None = None):
        # monotonic request clock (time.monotonic, the deadline timebase)
        self.submitted_at = (
            time.monotonic() if submitted_at is None else submitted_at
        )
        self.admitted_at: float | None = None
        self.first_token_at: float | None = None
        self.last_token_at: float | None = None
        self.gaps: list[float] = []  # inter-token gaps, seconds
        self.n_tokens = 0
        self.fused_admitted = False
        self.prefix_saved = 0
        # span clock (perf_counter) for the lifecycle slices
        self.span_t0 = time.perf_counter()
        self.lane: int | None = None
        # phase attribution extras: host-tier swap-in cost paid at this
        # request's admission, and measured per-request collective time
        # (mesh runs only — stays 0 off-mesh)
        self.swap_in_s = 0.0
        self.sync_s = 0.0

    def on_token(self, now: float) -> None:
        """Stamp one consumed token (``now`` = time.monotonic())."""
        if self.first_token_at is None:
            self.first_token_at = now
        else:
            self.gaps.append(max(0.0, now - self.last_token_at))
        self.last_token_at = now
        self.n_tokens += 1

    @property
    def ttft_s(self) -> float | None:
        if self.first_token_at is None:
            return None
        return max(0.0, self.first_token_at - self.submitted_at)

    @property
    def queued_s(self) -> float | None:
        if self.admitted_at is None:
            return None
        return max(0.0, self.admitted_at - self.submitted_at)

    def tbt_quantile(self, q: float) -> float | None:
        """Exact per-request inter-token-gap quantile (nearest-rank) —
        raw gaps, not the bucketed registry histogram (a single request
        has few enough gaps to keep them all)."""
        if not self.gaps:
            return None
        ordered = sorted(self.gaps)
        idx = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
        return ordered[idx]

    def phases(self) -> dict:
        """Per-request phase attribution (milliseconds): where this
        request's wall time went, phase by phase. Attached to completion
        responses and the journal finish record, and aggregated
        router-side into ``dllama_request_phase_seconds``.

        ``migration_gap_ms`` is 0 at this producer by construction — a
        replica cannot see its own death; the router stamps the measured
        gap into the record it forwards when a stream was spliced."""
        ms = lambda v: 0.0 if v is None else round(max(0.0, v) * 1e3, 3)
        prefill_s = None
        if self.admitted_at is not None and self.first_token_at is not None:
            prefill_s = self.first_token_at - self.admitted_at
        decode_s = None
        if self.first_token_at is not None and self.last_token_at is not None:
            decode_s = self.last_token_at - self.first_token_at
        total_s = None
        if self.last_token_at is not None:
            total_s = self.last_token_at - self.submitted_at
        return {
            "queue_wait_ms": ms(self.queued_s),
            "prefill_ms": ms(prefill_s),
            "decode_ms": ms(decode_s),
            "itl_p50_ms": ms(self.tbt_quantile(0.50)),
            "itl_p99_ms": ms(self.tbt_quantile(0.99)),
            "migration_gap_ms": 0.0,
            "swap_in_ms": ms(self.swap_in_s),
            "sync_ms": ms(self.sync_s),
            "ttft_ms": ms(self.ttft_s),
            "total_ms": ms(total_s),
        }

    def summary(self, req, finish_reason: str | None) -> dict:
        """The per-request summary attached to completion responses and
        emitted as the request's JSON log line — identical between the
        stream and non-stream paths by construction (one producer)."""
        rnd = lambda v: None if v is None else round(v, 6)
        out = {
            "request_id": req.id,
            "finish_reason": finish_reason,
            "queued_s": rnd(self.queued_s),
            "ttft_s": rnd(self.ttft_s),
            "tbt_p50_s": rnd(self.tbt_quantile(0.50)),
            "tbt_p95_s": rnd(self.tbt_quantile(0.95)),
            "n_prompt_tokens": req.n_prompt_tokens,
            "n_generated_tokens": len(req.generated_tokens),
            "prefix_tokens_saved": self.prefix_saved,
            "fused_admitted": self.fused_admitted,
            "phases": self.phases(),
        }
        # requests carry the wire-form context ("<trace>-<span>", the
        # X-DLlama-Trace value); the summary surfaces just the trace id,
        # the key clients and the router correlate on
        trace_id = trace_id_of(getattr(req, "trace", None))
        if trace_id:
            out["trace_id"] = trace_id
        return out
