# Build targets (reference: Makefile — here the compute path is XLA-compiled
# at runtime; native builds cover the C++ host components).

NATIVE_DIR := distributed_llama_multiusers_tpu/native
NATIVE_SO := $(NATIVE_DIR)/libdllama_native.so

.PHONY: all native test clean

all: native

native: $(NATIVE_SO)

$(NATIVE_SO): $(NATIVE_DIR)/quant_codec.cpp
	python -c "from distributed_llama_multiusers_tpu.native import ensure_built; import sys; sys.exit(0 if ensure_built(quiet=False) else 1)"

test: native
	python -m pytest tests/ -x -q

clean:
	rm -f $(NATIVE_SO)
