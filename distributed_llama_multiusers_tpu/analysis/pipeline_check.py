"""pipeline-sync: the pipelined dispatch half must never touch the host.

The async decode pipeline's whole point is that the dispatch half
(``engine.decode_pipelined``, the fused admission dispatch
``engine.decode_prefill_fused``, ``scheduler._pipeline_dispatch``)
enqueues the next device step from host METADATA only — the tokens
feeding it stay on device. One stray ``np.asarray`` / ``.item()`` /
implicit bool of a device value in there blocks the host on the in-flight
step and silently re-serializes the chain: the code still produces
identical streams, so nothing but a latency graph would ever catch it.
This check makes the regression a lint failure instead.

Scope: functions named in ``PIPELINE_FUNCS`` inside ``runtime/engine.py``
and ``runtime/scheduler.py`` — the dispatch halves the scheduler
restructure created, including the fused prefill+decode path (stall-free
admissions: the prompt chunk is host data going IN; nothing may come
back). Stricter than host-sync (which also covers these files): inside
the dispatch half even a *counted, waived-elsewhere-style* transfer is
wrong by construction, so every sync construct needs its own explicit
``# dlint: ok[pipeline-sync] reason`` — and there should essentially never
be one.

Rules (same constructs host-sync knows, scoped to the dispatch half):

1. **transfer calls** — ``np.asarray`` / ``np.array`` / ``jax.device_get``
   calls and ``.item()`` / ``.tolist()`` / ``.block_until_ready()`` /
   ``.all_logits()`` / ``.lane_logits()`` method calls;
2. **casts** — ``int()`` / ``float()`` / ``bool()`` over a name that is
   not host-annotated (``*_np`` / ``*_host``);
3. **implicit bool** — ``if x:`` / ``while x:`` / ``assert x`` on a value
   assigned from a compiled-step call (``*_fn`` / ``*_exec`` names).
"""

from __future__ import annotations

import ast
import re

from .core import (
    Checker,
    Finding,
    Project,
    SourceFile,
    last_component,
    root_name,
)

SCOPE = ("runtime/engine.py", "runtime/scheduler.py")
# the dispatch halves by name: the engine's public dispatch entry points
# (plain pipelined step, the fused prefill+decode admission step, and the
# zero-flush spec-verify family — the draft-shipping steps must not sync
# any more than the plain ones) and the scheduler's dispatch-half method,
# whose draft-probing branch is a pure host-side n-gram lookup (legal);
# any device sync in it is a finding
PIPELINE_FUNCS = (
    "decode_pipelined", "decode_prefill_fused", "decode_spec_pipelined",
    "decode_spec_prefill_fused", "_pipeline_dispatch",
)

SYNC_METHODS = {"item", "tolist", "block_until_ready", "all_logits",
                "lane_logits", "device_get"}
SYNC_FUNCS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
              "jax.device_get"}
CASTS = {"int", "float", "bool"}
DEVICE_FN_RE = re.compile(r"(_fn|_exec)$")
HOST_NAME_RE = re.compile(r"(_np|_host)$")


class PipelineSyncChecker(Checker):
    name = "pipeline-sync"
    description = (
        "host-sync constructs inside the pipelined dispatch half "
        "(engine.decode_pipelined / engine.decode_prefill_fused / "
        "scheduler._pipeline_dispatch) re-serialize the async chain"
    )

    def check(self, sf: SourceFile, project: Project):
        if not sf.endswith(*SCOPE):
            return
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in PIPELINE_FUNCS
            ):
                yield from self._check_fn(sf, node)

    def _check_fn(self, sf: SourceFile, fn):
        # names assigned from compiled-step calls: implicit bool on them
        # blocks on the device (host-sync rule 3, scoped here)
        tainted: set[str] = set()
        for stmt in ast.walk(fn):
            if not (isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call)):
                continue
            last = last_component(stmt.value.func)
            if last is not None and DEVICE_FN_RE.search(last):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        tainted.add(tgt.id)
                    elif isinstance(tgt, (ast.Tuple, ast.List)):
                        tainted.update(
                            e.id for e in tgt.elts if isinstance(e, ast.Name)
                        )
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                yield from self._check_call(sf, fn, node)
            elif isinstance(node, (ast.If, ast.While, ast.Assert)):
                for name in self._bool_names(node.test):
                    if name in tainted:
                        yield Finding(
                            self.name, sf.display, node.lineno,
                            f"implicit bool of device value '{name}' inside "
                            f"dispatch half '{fn.name}' blocks on the "
                            "in-flight step and re-serializes the pipeline",
                        )

    def _check_call(self, sf: SourceFile, fn, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in SYNC_METHODS:
            yield Finding(
                self.name, sf.display, node.lineno,
                f"device->host sync '{ast.unparse(func)}(...)' inside "
                f"dispatch half '{fn.name}' re-serializes the pipeline; "
                "move it to the consume half or waive with "
                "'# dlint: ok[pipeline-sync] <why>'",
            )
            return
        if ast.unparse(func) in SYNC_FUNCS:
            yield Finding(
                self.name, sf.display, node.lineno,
                f"device->host sync '{ast.unparse(func)}(...)' inside "
                f"dispatch half '{fn.name}' re-serializes the pipeline; "
                "move it to the consume half or waive with "
                "'# dlint: ok[pipeline-sync] <why>'",
            )
            return
        if (
            isinstance(func, ast.Name)
            and func.id in CASTS
            and len(node.args) == 1
            and not node.keywords
            and isinstance(node.args[0], (ast.Name, ast.Attribute, ast.Subscript))
        ):
            root = root_name(node.args[0])
            if root is not None and not HOST_NAME_RE.search(root):
                yield Finding(
                    self.name, sf.display, node.lineno,
                    f"cast '{func.id}({ast.unparse(node.args[0])})' inside "
                    f"dispatch half '{fn.name}' may sync a device value; "
                    "read host metadata instead or waive",
                )

    @staticmethod
    def _bool_names(test: ast.AST) -> list[str]:
        if isinstance(test, ast.Name):
            return [test.id]
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return PipelineSyncChecker._bool_names(test.operand)
        if isinstance(test, ast.BoolOp):
            out: list[str] = []
            for v in test.values:
                out.extend(PipelineSyncChecker._bool_names(v))
            return out
        return []
