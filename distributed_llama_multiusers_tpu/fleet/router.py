"""``dllama-router``: the fleet front-end above N engine replicas.

One process, pure stdlib, no model state: the router owns client
connections and steers requests across replicas using only the surfaces
the serving stack already exposes —

- **placement** (fleet/balancer.py): prefix-affine consistent hashing
  steers same-leading-prompt sessions to the replica whose paged KV pool
  already holds the warm prefix pages; keyless requests go least-loaded
  by the queue-depth/free-lane fields scraped from each replica's
  ``GET /load``.
- **typed shed handling**: a replica's 429/503 (queue full, breaker
  open, draining, pool exhausted) is honored — its jittered Retry-After
  becomes a routing backoff — and the request is retried on the next
  eligible replica. Only when EVERY replica is shedding or unreachable
  does the client see a failure: one aggregate 503 whose Retry-After is
  the smallest outstanding hint in the fleet.
- **disaggregated prefill** (disagg/): requests classified **long** by
  prompt length (``--disagg-threshold`` chars) route to a replica
  advertising ``role: prefill`` on its ``/load``; once the first delta
  proves the prompt's KV pages are committed there, the router moves
  the session to a decode replica — KV-page bundle first
  (``/admin/kvpages`` → ``/admin/kvimport``, integrity-hashed), then
  the migration ticket, then reattach — so long prompts stop taxing
  co-resident decode TBT. Any hand-off failure (including the prefill
  replica dying mid-transfer) degrades to the monolithic path: the
  router keeps pumping whatever stream it has, typed fallback counters
  record why.
- **live migration** (fleet/migrate.py): the router caches each
  stream's migration ticket (the session's exported journal admit
  record) at stream start; when the serving replica dies mid-stream, is
  drain-flushed, or sheds the stream, the router injects the ticket
  into another replica (``POST /admin/migrate`` — deterministic replay
  through normal breaker-gated admission), reattaches via
  ``GET /v1/stream/<id>``, skips exactly the characters its client
  already received, and keeps pumping on the SAME client socket. The
  client sees one uninterrupted, byte-identical stream: drains, rolling
  restarts and replica death shed zero requests.

The router re-stamps SSE ``id:`` lines with its own delta counter (it —
not any single replica — owns the client's stream position across
migrations); the ``id`` field inside each chunk keeps the original
request id end-to-end.

Observability mirrors a replica's: ``GET /stats`` (routing table +
counters), ``GET /metrics`` (Prometheus text via telemetry/metrics.py:
per-replica route counts, shed retries, the migration latency
histogram), ``GET /health`` (200 while at least one replica is
eligible).

Fleet-wide distributed tracing (telemetry/tracectx.py,
docs/OBSERVABILITY.md): the router MINTS a trace context per request
(or accepts a valid client ``X-DLlama-Trace``) and propagates it on
every hop — forwards, retries, redispatches, migration ticket
fetch/inject, disagg hand-off — so one request's spans share one trace
id across every process that touched it. The router keeps its OWN span
ring (route/queue-wait slices, migration gaps, hand-off windows) and
merges it with the replicas' rings on ``GET /trace/<trace_id>``:
per-replica clock offsets estimated from the ``/load`` scrape
(offset = local scrape midpoint − the replica's ``trace_clock_us``
stamp, uncertainty = RTT/2) align every ring onto the router's
timebase — applied, and stamped visibly onto every migrated event.
Replica-reported per-request ``phases`` records aggregate into the
``dllama_request_phase_seconds{phase=...}`` histogram on ``/metrics``.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..disagg.prefill import (
    DEFAULT_LONG_PROMPT_CHARS,
    HandoffAborted,
    classify_prompt,
    hand_off,
)
from ..lockcheck import make_lock
from ..telemetry.metrics import MetricsRegistry, log_buckets
from ..telemetry.spans import SpanTracer
from ..telemetry.trace import merge_chrome_traces, tracer_chrome_trace
from ..telemetry.tracectx import TRACE_HEADER, PhaseAccumulator, TraceContext
from .balancer import (
    DEFAULT_AFFINITY_BLOCKS,
    DEFAULT_BLOCK_CHARS,
    FleetBalancer,
    ReplicaState,
    prefix_key,
)
from .migrate import (
    MigrationShed,
    _request_json,
    fetch_ticket,
    inject_session,
    open_stream,
)

DEFAULT_SCRAPE_INTERVAL_S = 0.5
DEFAULT_CONNECT_TIMEOUT_S = 5.0
# streaming reads wait on generation; mirror the replica's own bound
DEFAULT_READ_TIMEOUT_S = 600.0
# migration latency is sub-second locally, seconds cross-rack
MIGRATION_BUCKETS_S = log_buckets(1e-3, 100.0, per_decade=4)

_TRANSPORT_ERRORS = (OSError, http.client.HTTPException)


class _ClientGone(Exception):
    """The router's OWN client dropped the connection — unwind quietly
    (closing the upstream socket lets the replica's cancel-on-disconnect
    / reconnect-grace semantics apply there)."""


class _StreamSession:
    """Router-side state for one proxied SSE stream: what the client has
    received (the char-exact dedup floor migrations resume against), the
    cached migration ticket, and any replica-side failure payload held
    while a migration is attempted."""

    __slots__ = ("key", "request_id", "ticket", "deltas_out",
                 "chars_out", "terminal_seen", "pending_error",
                 "migrations", "handoff_due", "trace", "gap_ms")

    def __init__(self, key):
        self.key = key  # affinity key (None = keyless)
        self.request_id = None
        self.ticket = None
        self.deltas_out = 0  # the router's own SSE id counter
        self.chars_out = 0  # delta chars delivered to the client
        self.terminal_seen = False
        self.pending_error = None
        self.migrations = 0
        # disagg: True while a prefill→decode hand-off is owed — armed
        # when the stream lands on a prefill-role replica, cleared at
        # the (single) attempt so a fallback never retries forever
        self.handoff_due = False
        # fleet trace context (wire form): rides every hop this stream
        # takes as X-DLlama-Trace; the ticket's own trace field re-joins
        # migrated regenerations to the same trace id
        self.trace = None
        # client-visible dead air accumulated across migrations/hand-offs
        # (break detected -> first resumed byte): the router — the only
        # process that saw the whole gap — stamps it into the terminal
        # phases record it forwards
        self.gap_ms = 0.0


class FleetRouter:
    """The routing core + HTTP front-end. ``serve()`` mirrors
    :class:`~..server.http.ApiServer.serve` (returns the bound
    ``ThreadingHTTPServer``; the caller runs ``serve_forever``)."""

    # dlint guarded-by declaration (analysis/lock_check.py): the
    # per-replica clock-offset table is written by concurrent scrape
    # probe threads and read by /trace/<id> merges — only under
    # `_clock_lock`. Machine-checked by `make lint`.
    _dlint_guarded_by = {("_clock_lock",): ("_clock_offsets",)}

    def __init__(self, replicas, balancer: FleetBalancer | None = None,
                 affinity_block_chars: int = DEFAULT_BLOCK_CHARS,
                 affinity_blocks: int = DEFAULT_AFFINITY_BLOCKS,
                 scrape_interval_s: float = DEFAULT_SCRAPE_INTERVAL_S,
                 migration: bool = True,
                 connect_timeout_s: float = DEFAULT_CONNECT_TIMEOUT_S,
                 read_timeout_s: float = DEFAULT_READ_TIMEOUT_S,
                 disagg: bool = True,
                 long_prompt_chars: int = DEFAULT_LONG_PROMPT_CHARS):
        self.balancer = balancer or FleetBalancer(replicas)
        self.affinity_block_chars = int(affinity_block_chars)
        self.affinity_blocks = int(affinity_blocks)
        self.scrape_interval_s = float(scrape_interval_s)
        self.migration = bool(migration)
        self.connect_timeout_s = float(connect_timeout_s)
        self.read_timeout_s = float(read_timeout_s)
        # disaggregated prefill: classify by prompt length and steer
        # long prompts to prefill-role replicas; <=0 threshold (or
        # disagg=False) turns the whole policy off — everything
        # classifies "short" and no hand-offs are armed
        self.disagg = bool(disagg)
        self.long_prompt_chars = int(long_prompt_chars)
        # plain counters for /stats (single GIL-atomic int bumps, the
        # scheduler-counter pattern); the registry carries the same
        # signals as native Prometheus series for /metrics
        self.routed_total = 0
        self.shed_retries = 0
        self.giveups = 0
        self.migrations_ok = 0
        self.migrations_failed = 0
        self.redispatches = 0
        self.disagg_handoffs_ok = 0
        self.disagg_fallbacks = 0
        self.disagg_pages_moved = 0  # pages adopted by decode replicas
        self.disagg_pages_fresh = 0  # ...whose payload actually shipped
        self.registry = MetricsRegistry()
        self._m_routed = self.registry.counter(
            "dllama_router_requests_total",
            "requests routed, by replica and placement mode",
        )
        self._m_sheds = self.registry.counter(
            "dllama_router_replica_sheds_total",
            "typed replica sheds observed (reason label)",
        )
        self._m_retries = self.registry.counter(
            "dllama_router_shed_retries_total",
            "requests retried on another replica after a shed",
        )
        self._m_giveups = self.registry.counter(
            "dllama_router_giveups_total",
            "requests failed because every replica shed or was down",
        )
        self._m_migrations = self.registry.counter(
            "dllama_router_migrations_total",
            "live stream migrations, by outcome",
        )
        self._m_migration_s = self.registry.histogram(
            "dllama_router_migration_seconds",
            "stream break detected -> first resumed byte forwarded",
            buckets=MIGRATION_BUCKETS_S,
        )
        self._m_disagg = self.registry.counter(
            "dllama_router_disagg_handoffs_total",
            "prefill->decode hand-offs, by outcome "
            "(fallbacks carry the typed abort reason)",
        )
        self._m_disagg_pages = self.registry.counter(
            "dllama_router_disagg_pages_total",
            "KV pages adopted across replicas, by kind (fresh/reused)",
        )
        self._m_handoff_s = self.registry.histogram(
            "dllama_router_disagg_handoff_seconds",
            "first prefill delta -> decode stream reattached",
            buckets=MIGRATION_BUCKETS_S,
        )
        # fleet tracing: the router's own span ring (route/queue-wait
        # slices, migration gaps, hand-off windows — the rows the merged
        # /trace/<id> timeline leads with), the per-request phase
        # aggregation fed from replica-reported `phases` records, and
        # the per-replica clock-offset table the merge aligns with
        self.tracer = SpanTracer()
        self.phase_acc = PhaseAccumulator()
        self._m_phase_s = self.registry.labelled_histogram(
            "dllama_request_phase_seconds",
            "per-request phase attribution (seconds; phase label is the "
            "phases-record key, ms fields observed /1000) aggregated "
            "router-side from replica-reported phase records",
        )
        self._clock_lock = make_lock("FleetRouter._clock_lock")
        # rid -> (offset_us, uncertainty_us): what to ADD to that
        # replica's /trace timestamps to land them on the router's
        # timebase, and the RTT/2 error bound of the estimate
        self._clock_offsets: dict[str, tuple[float, float]] = {}
        self._stop_evt = threading.Event()
        self._scrape_thread: threading.Thread | None = None
        self._httpd: ThreadingHTTPServer | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetRouter":
        """Start the /load scrape loop (idempotent)."""
        if self._scrape_thread is None or not self._scrape_thread.is_alive():
            self._stop_evt.clear()
            self._scrape_thread = threading.Thread(
                target=self._scrape_loop, name="fleet-scrape", daemon=True
            )
            self._scrape_thread.start()
        return self

    def close(self, timeout: float | None = 5.0) -> None:
        self._stop_evt.set()
        if self._scrape_thread is not None and self._scrape_thread.is_alive():
            self._scrape_thread.join(timeout)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None

    def scrape_once(self) -> None:
        """One scrape pass over every replica (the loop's body; also the
        test/bench lever for deterministic state). Replicas are scraped
        CONCURRENTLY: a blackholed host (no RST — each attempt eats the
        full 2s timeout) must not stall the healthy replicas' load and
        draining freshness behind it, so a pass costs max(one probe),
        never sum."""
        threads = [
            threading.Thread(
                target=self._probe_load, args=(s,), daemon=True
            )
            for s in self.balancer.replicas()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(3.0)  # bounded by the probe's own 2s timeout

    def _probe_load(self, state: ReplicaState) -> None:
        """One /load probe: balancer freshness plus the clock-offset
        estimate the fleet trace merge needs. The probe is bracketed
        with local ``perf_counter`` stamps; the replica's ``/load``
        carries ``trace_clock_us`` (its CURRENT position on its /trace
        timebase), so offset = local scrape midpoint (on the router's
        trace timebase) − that stamp, with RTT/2 as the error bound —
        perf_counter origins are per-process, there is no shared clock
        to read."""
        host, port = state.host_port()
        t0 = time.perf_counter()
        try:
            status, body, _ = _request_json(
                host, port, "GET", "/load", timeout=2.0
            )
        except _TRANSPORT_ERRORS:
            self.balancer.note_scrape_failed(state.rid)
            return
        t1 = time.perf_counter()
        if status == 200 and "queue_depth" in body:
            self.balancer.update_load(state.rid, body)
            clock = body.get("trace_clock_us")
            if isinstance(clock, (int, float)):
                mid_us = ((t0 + t1) / 2 - self.tracer.origin) * 1e6
                with self._clock_lock:
                    self._clock_offsets[state.rid] = (
                        mid_us - float(clock), (t1 - t0) / 2 * 1e6,
                    )
        else:
            self.balancer.note_scrape_failed(state.rid)

    def clock_offset(self, rid: str) -> tuple[float, float] | None:
        """The latest (offset_us, uncertainty_us) estimate for ``rid``,
        or None before its first successful scrape."""
        with self._clock_lock:
            return self._clock_offsets.get(rid)

    def merged_trace(self, trace_id: str) -> dict:
        """``GET /trace/<trace_id>``: ONE Perfetto timeline for a fleet
        trace. Fans ``/trace?trace_id=`` out to every replica, aligns
        each ring onto the router's timebase with the scraped clock
        offsets (replicas with no estimate yet get one probed inline —
        this is a debug surface, an extra RTT is fine), and merges with
        the router's own spans at offset 0. A dead replica contributes
        nothing — its ring died with it; the merge is every ring still
        reachable, honestly labelled via per-event ``span_source``."""
        parts = [(
            "router",
            tracer_chrome_trace(self.tracer, trace_id=trace_id),
            0.0, 0.0,
        )]
        for state in self.balancer.replicas():
            if self.clock_offset(state.rid) is None:
                self._probe_load(state)
            host, port = state.host_port()
            try:
                status, doc, _ = _request_json(
                    host, port, "GET", f"/trace?trace_id={trace_id}",
                    timeout=self.connect_timeout_s,
                )
            except _TRANSPORT_ERRORS:
                continue
            if status != 200 or not isinstance(doc, dict):
                continue
            off = self.clock_offset(state.rid) or (0.0, 0.0)
            parts.append((state.rid, doc, off[0], off[1]))
        return merge_chrome_traces(parts)

    def observe_phases(self, phases) -> None:
        """Fold one replica-reported ``phases`` record into the fleet
        aggregation: the /stats counts/sums (PhaseAccumulator validates
        and filters) and the ``dllama_request_phase_seconds{phase=...}``
        histogram (ms fields observed as seconds)."""
        rec = self.phase_acc.observe(phases)
        if not rec:
            return
        for k, v in rec.items():
            self._m_phase_s.observe(v / 1e3, phase=k)

    def _harvest_phases(self, data: bytes) -> None:
        """Pull the ``summary.phases`` record off a buffered completion
        body the router just proxied. Best-effort by design — tracing
        and attribution never fail a response."""
        try:
            body = json.loads(data)
            phases = body["summary"]["phases"]
        except (ValueError, TypeError, KeyError):
            return
        if isinstance(phases, dict):
            self.observe_phases(phases)

    def _scrape_loop(self) -> None:
        while not self._stop_evt.wait(self.scrape_interval_s):
            self.scrape_once()

    # -- placement -----------------------------------------------------------

    def affinity_key(self, body: dict) -> int | None:
        """The request's affinity key: the content-hash chain over the
        prompt text's leading blocks. Chat requests key on the
        concatenated message contents (the leading system prompt
        dominates, which is exactly the sharable part)."""
        if "prompt" in body:
            prompt = body.get("prompt")
            if isinstance(prompt, list):
                prompt = prompt[0] if prompt else ""
            text = prompt if isinstance(prompt, str) else ""
        else:
            parts = []
            for m in body.get("messages") or []:
                if isinstance(m, dict):
                    c = m.get("content")
                    if isinstance(c, str):
                        parts.append(c)
            text = "\n".join(parts)
        return prefix_key(
            text, self.affinity_block_chars, self.affinity_blocks
        )

    # -- surfaces ------------------------------------------------------------

    def handle_stats(self) -> dict:
        out = {
            "router_routed_total": self.routed_total,
            "router_shed_retries": self.shed_retries,
            "router_giveups": self.giveups,
            "router_migrations_ok": self.migrations_ok,
            "router_migrations_failed": self.migrations_failed,
            "router_redispatches": self.redispatches,
            "router_disagg_handoffs_ok": self.disagg_handoffs_ok,
            "router_disagg_fallbacks": self.disagg_fallbacks,
            "router_disagg_pages_moved": self.disagg_pages_moved,
            "router_disagg_pages_fresh": self.disagg_pages_fresh,
            "router_long_prompt_chars": (
                self.long_prompt_chars if self.disagg else 0
            ),
        }
        out.update(self.balancer.stats())
        # fleet tracing surfaces: the router's own ring occupancy (an
        # evicting ring is visible, not silent), the per-replica clock
        # offsets behind /trace/<id>'s alignment, and the aggregated
        # phase-attribution counts/sums
        out.update(self.tracer.counts())
        with self._clock_lock:
            out["clock_offset_us"] = {
                rid: round(v[0], 1) for rid, v in self._clock_offsets.items()
            }
            out["clock_uncertainty_us"] = {
                rid: round(v[1], 1) for rid, v in self._clock_offsets.items()
            }
        out.update(self.phase_acc.snapshot())
        return out

    def handle_metrics(self) -> str:
        return self.registry.render()

    def any_eligible(self) -> bool:
        return self.balancer.any_eligible()

    # -- proxying ------------------------------------------------------------

    def _shed_info(self, body: dict, headers: dict) -> tuple[str, float]:
        reason = str(body.get("reason", "shed"))
        try:
            retry = float(headers.get("Retry-After", 1.0))
        except (TypeError, ValueError):
            retry = 1.0
        return reason, retry

    def _forward_once(self, state: ReplicaState, path: str,
                      body_bytes: bytes, streaming: bool,
                      trace: str | None = None):
        """POST to one replica. Returns ``("ok", conn, resp)`` for a
        streaming 200 (caller owns the connection), ``("done", status,
        data, content_type)`` for a buffered answer, or ``("shed",
        reason, retry_s)`` / ``("dead", None, None)``. ``trace`` (wire
        form) rides as ``X-DLlama-Trace`` — the replica stamps it onto
        the request's spans and journal admit record."""
        host, port = state.host_port()
        # two-phase timeout: a SHORT connect bound (a dead replica whose
        # listener socket lingers — SIGKILL mid-accept-backlog — must
        # fail the route in seconds, not hold the client for the whole
        # generation window), then the generation-length read bound once
        # the connection is up
        conn = http.client.HTTPConnection(
            host, port, timeout=self.connect_timeout_s
        )
        headers = {"Content-Type": "application/json"}
        if trace:
            headers[TRACE_HEADER] = trace
        try:
            conn.connect()
            conn.sock.settimeout(self.read_timeout_s)
            conn.request("POST", path, body=body_bytes, headers=headers)
            resp = conn.getresponse()
        except _TRANSPORT_ERRORS:
            conn.close()
            return ("dead", None, None, None)
        if resp.status in (429, 503):
            try:
                raw = resp.read()
                parsed = json.loads(raw) if raw else {}
            except (ValueError, *_TRANSPORT_ERRORS):
                parsed = {}
            headers = dict(resp.getheaders())
            conn.close()
            reason, retry = self._shed_info(parsed, headers)
            return ("shed", reason, retry, None)
        if streaming and resp.status == 200:
            return ("ok", conn, resp, None)
        try:
            data = resp.read()
        except _TRANSPORT_ERRORS:
            conn.close()
            return ("dead", None, None, None)
        ctype = resp.getheader("Content-Type", "application/json")
        served_by = resp.getheader("X-DLlama-Replica")
        conn.close()
        return ("done", resp.status, data, (ctype, served_by))

    def route(self, path: str, body: dict, sse,
              trace_header: str | None = None):
        """Route one POST. ``sse`` is the client-side SSE surface (a
        ``_SseClient``) for streaming requests, ``None`` otherwise.
        Returns ``(status, data, content_type)`` for buffered answers,
        or ``None`` when the stream was fully handled (headers/chunks
        already written).

        ``trace_header`` is the client's raw ``X-DLlama-Trace`` (or
        None): a valid value is adopted, anything else is replaced by a
        freshly MINTED context — every routed request has a fleet trace
        id from here on, and every hop below carries it."""
        streaming = sse is not None
        ctx = TraceContext.accept(trace_header)
        t_recv = time.perf_counter()
        key = self.affinity_key(body)
        # prompt-length class: "long" routes to a prefill-role replica
        # (disagg); short traffic keeps today's affinity/least-loaded
        len_class = (
            classify_prompt(body, self.long_prompt_chars)
            if self.disagg else "short"
        )
        body_bytes = json.dumps(body).encode()
        tried: set[str] = set()
        sheds: dict[str, dict] = {}
        attempts = 0
        while True:
            state = None
            if len_class == "long":
                # least-loaded among prefill-role replicas (keyless on
                # purpose: a long prompt's pages will MOVE, so pinning
                # it to the affinity ring owner buys nothing); when no
                # prefill replica is eligible the normal pick below is
                # the monolithic fallback
                state = self.balancer.pick(exclude=tried, role="prefill")
            if state is None:
                state = self.balancer.pick(key, exclude=tried)
            if state is None:
                break
            tried.add(state.rid)
            attempts += 1
            # fresh child span id per hop, SAME trace id: each forward
            # is its own hop in the trace, all correlated by trace_id
            verdict, a, b, c = self._forward_once(
                state, path, body_bytes, streaming,
                trace=ctx.child().to_header(),
            )
            if verdict == "dead":
                self.balancer.note_dead(state.rid)
                sheds[state.rid] = {"reason": "unreachable"}
                continue
            if verdict == "shed":
                reason, retry = a, b
                self.balancer.note_shed(
                    state.rid, retry, draining=(reason == "draining")
                )
                self._m_sheds.inc(reason=reason)
                self.shed_retries += 1
                self._m_retries.inc()
                sheds[state.rid] = {
                    "reason": reason, "retry_after_s": retry,
                }
                continue
            # routed (served or a non-shed error the client should see)
            self.routed_total += 1
            # the per-request routing decision, attributable in one
            # scrape: which replica, which placement mode, the prompt's
            # length class and the serving replica's advertised role
            self._m_routed.inc(
                replica=state.rid,
                mode="affinity" if key is not None else "load",
                len_class=len_class,
                role=state.role,
            )
            # the router's own span: client request received -> a
            # replica accepted it (the fleet timeline's queue-wait row;
            # shed/dead retries are inside this window by construction)
            self.tracer.slice(
                "route", "router", t_recv, args={
                    "trace_id": ctx.trace_id, "replica": state.rid,
                    "attempts": attempts, "len_class": len_class,
                },
            )
            if verdict == "ok":
                self._pump_stream(
                    sse, a, b, state, key, path, body_bytes,
                    handoff=(
                        self.disagg and self.migration
                        and len_class == "long"
                        and state.role == "prefill"
                    ),
                    ctx=ctx,
                )
                return None
            status, data, (ctype, served_by) = a, b, c
            if status == 200:
                # per-request phase attribution: buffered completion
                # bodies carry summary.phases — fold it into the fleet
                # histogram the same way streamed terminals are
                self._harvest_phases(data)
            # the replica's attribution header passes through, so fleet
            # clients see WHO served them even behind the router; the
            # trace context goes back too — the client's key into
            # GET /trace/<trace_id>
            extra = {TRACE_HEADER: ctx.to_header()}
            if served_by:
                extra["X-DLlama-Replica"] = served_by
            return (status, data, ctype, extra)
        # every replica shed or unreachable: ONE aggregate failure with
        # the smallest outstanding hint — the router's own typed shed
        self.giveups += 1
        self._m_giveups.inc()
        retry = self.balancer.min_retry_after_s()
        # streams included: SSE headers only commit on an upstream 200,
        # so a total give-up still gets a proper 503 status line
        payload = json.dumps({
            "error": "no replica available (all shedding or unreachable)",
            "reason": "fleet_exhausted",
            "replicas_tried": attempts,
            "sheds": sheds,
        }).encode()
        return (503, payload, "application/json",
                {"Retry-After": str(max(1, round(retry)))})

    # -- streaming pump + migration ------------------------------------------

    def _pump_stream(self, sse, conn, resp, state, key, path,
                     body_bytes, handoff: bool = False,
                     ctx: TraceContext | None = None) -> None:
        """Own a streaming request end-to-end: commit the client SSE
        headers, pump the upstream body through, and on a mid-stream
        failure migrate to another replica and keep pumping — same
        client socket, zero lost/duplicated output. With ``handoff``
        (a long prompt landed on a prefill-role replica) the pump
        pauses after the FIRST forwarded delta — the proof that
        prefill committed its pages — and tries the disagg hand-off;
        a failed hand-off simply resumes the same upstream stream (the
        monolithic fallback, the source never stopped decoding)."""
        st = _StreamSession(key)
        st.handoff_due = handoff
        if ctx is not None:
            st.trace = ctx.to_header()
        tried = {state.rid}
        sse.headers(state.rid, trace=st.trace)
        skip_chars = 0
        while True:
            try:
                outcome = self._pump_upstream(
                    sse, st, conn, resp, state, skip_chars
                )
            except _ClientGone:
                # our client left: closing upstream lets the replica's
                # own disconnect semantics (cancel / grace) apply
                conn.close()
                return
            if outcome == "handoff":
                t_gap = time.perf_counter()
                nxt = self._hand_off(st, state)
                if nxt is None:
                    # typed fallback (counted in _hand_off): the source
                    # stream is still live and still ours — keep
                    # pumping it. skip_chars resets: the SAME response
                    # body continues, nothing replays.
                    skip_chars = 0
                    continue
                # the decode replica replays from 0; close the source
                # only now, after the reattach succeeded (closing it
                # earlier would burn the fallback path)
                conn.close()
                from_rid = state.rid
                conn, resp, state = nxt
                tried.add(state.rid)
                skip_chars = st.chars_out  # char-exact dedup floor
                st.pending_error = None
                st.terminal_seen = False
                # the hand-off window is NOT client-visible dead air the
                # way a migration gap is (the source kept streaming until
                # the reattach), but the transfer is a trace row: the
                # fleet timeline shows prefill ending and decode starting
                # across it
                self.tracer.slice(
                    "disagg.handoff", "disagg", t_gap, args={
                        "trace_id": _ctx_trace_id(ctx),
                        "from": from_rid, "to": state.rid,
                        "request_id": st.request_id,
                    },
                )
                continue
            conn.close()
            tried.add(state.rid)
            if outcome == "done":
                sse.done()
                return
            # outcome == "migrate": the source died / shed / cancelled
            t0 = time.perf_counter()
            nxt = self._migrate(st, state)
            migrated = nxt is not None
            if nxt is None and st.chars_out == 0:
                # nothing was delivered yet (the queued-at-kill window:
                # a request the dead replica never admitted exports no
                # ticket) — a fresh re-dispatch elsewhere is lossless
                # by definition. Counted as a redispatch, NOT a
                # migration: no ticket, no deterministic replay, and
                # the migration latency histogram must not absorb it.
                nxt = self._redispatch(path, body_bytes, key, tried,
                                       trace=st.trace)
                if nxt is not None:
                    st.request_id = None
                    st.ticket = None
                    self.redispatches += 1
                    self._m_migrations.inc(outcome="redispatch")
            if nxt is None:
                self.migrations_failed += 1
                self._m_migrations.inc(outcome="failed")
                try:
                    err = st.pending_error or {
                        "error": "replica lost mid-stream and no "
                                 "migration target accepted the session",
                        "reason": "migration_failed",
                    }
                    err.setdefault("request_id", st.request_id)
                    sse.chunk(err)
                    sse.done()
                except _ClientGone:
                    pass
                return
            from_rid = state.rid
            conn, resp, state = nxt
            tried.add(state.rid)
            skip_chars = st.chars_out  # char-exact dedup floor
            st.pending_error = None
            st.terminal_seen = False
            gap_s = time.perf_counter() - t0
            # the migration gap: break detected -> resumed stream in
            # hand. Client-visible dead air only the ROUTER saw whole —
            # a span on the fleet timeline AND an accumulated phases
            # field stamped into the terminal record (redispatches
            # count too: the client's stall is the same either way)
            st.gap_ms += gap_s * 1e3
            self.tracer.slice(
                "migration.gap", "migrate", t0, args={
                    "trace_id": _ctx_trace_id(ctx),
                    "from": from_rid, "to": state.rid,
                    "request_id": st.request_id,
                    "kind": "migration" if migrated else "redispatch",
                },
            )
            if migrated:
                st.migrations += 1
                self.migrations_ok += 1
                self._m_migrations.inc(outcome="ok")
                self._m_migration_s.observe(gap_s)

    def _redispatch(self, path, body_bytes, key, tried,
                    trace: str | None = None):
        """Re-send the ORIGINAL request to a replica not yet tried (only
        ever called with zero delivered output — a fresh request id and
        a fresh seed are invisible to the client). Returns ``(conn,
        resp, state)`` or ``None``. The original trace context rides
        along: the re-dispatched request is the SAME client request,
        so it keeps the same trace id."""
        while True:
            state = self.balancer.pick(key, exclude=tried)
            if state is None:
                return None
            tried.add(state.rid)
            verdict, a, b, _c = self._forward_once(
                state, path, body_bytes, True, trace=trace
            )
            if verdict == "ok":
                return a, b, state
            if verdict == "shed":
                self.balancer.note_shed(
                    state.rid, b, draining=(a == "draining")
                )
                self._m_sheds.inc(reason=a)
            elif verdict == "dead":
                self.balancer.note_dead(state.rid)
            else:
                # a buffered non-200: the SSE headers are already out,
                # so it cannot be relayed as a status line — give up
                return None

    def _pump_upstream(self, sse, st, conn, resp, state,
                       skip_chars: int) -> str:
        """Forward one upstream SSE body. Returns ``"done"`` (terminal +
        [DONE] forwarded) or ``"migrate"`` (source broke / shed / was
        force-cancelled mid-flight). Raises :class:`_ClientGone` when
        the router's own client disappears."""
        if st.request_id is None:
            rid_hdr = resp.getheader("X-DLlama-Request")
            if rid_hdr is not None:
                try:
                    st.request_id = int(rid_hdr)
                except ValueError:
                    pass
        self._ensure_ticket(st, state)
        skip = skip_chars
        try:
            for raw in resp:
                line = raw.decode("utf-8", "replace").rstrip("\r\n")
                if not line.startswith("data: "):
                    continue  # upstream ids are re-stamped by the router
                data = line[len("data: "):]
                if data == "[DONE]":
                    # a clean upstream end without a terminal chunk is a
                    # break (e.g. the handler died): migrate
                    return "done" if st.terminal_seen else "migrate"
                try:
                    payload = json.loads(data)
                except ValueError:
                    continue
                if st.request_id is None:
                    st.request_id = _rid_from_payload(payload)
                    self._ensure_ticket(st, state)
                if "error" in payload:
                    # typed mid-stream failure (drain flush, pool shed,
                    # engine error): try to move the session instead of
                    # passing the failure through
                    st.pending_error = payload
                    return "migrate"
                choices = payload.get("choices") or [{}]
                choice = choices[0] if isinstance(choices[0], dict) else {}
                fin = choice.get("finish_reason")
                if fin is None:
                    text = _delta_text(choice)
                    if skip:
                        if len(text) <= skip:
                            skip -= len(text)
                            continue
                        text = text[skip:]
                        skip = 0
                        _set_delta_text(choice, text)
                    if not text:
                        continue
                    if st.ticket is None and st.deltas_out == 0:
                        # the stream-start fetch can race admission (a
                        # queued request exports nothing); the first
                        # delta PROVES admission, so one retry here
                        # makes the ticket reliable before any output
                        # is at stake
                        self._ensure_ticket(st, state)
                    st.deltas_out += 1
                    st.chars_out += len(text)
                    sse.chunk(payload, event_id=st.deltas_out)
                    if st.handoff_due:
                        # disagg: the first delta PROVES the prompt's
                        # blocks are committed to the prefill replica's
                        # pool — pause here and try the hand-off (the
                        # caller resumes this same stream on fallback)
                        return "handoff"
                    continue
                if fin in ("cancelled", "error"):
                    # the source gave the request up mid-flight (drain
                    # force-cancel, contained failure): migratable
                    st.pending_error = payload
                    return "migrate"
                # natural ending (stop/length/timeout): pass through —
                # after stamping the router-owned attribution into the
                # phases record and folding it into the fleet histogram
                self._finish_phases(st, payload)
                st.terminal_seen = True
                sse.chunk(payload, event_id=st.deltas_out)
        except _TRANSPORT_ERRORS:
            return "migrate"  # the source replica died mid-stream
        return "done" if st.terminal_seen else "migrate"

    def _finish_phases(self, st: _StreamSession, payload: dict) -> None:
        """Stamp router-owned attribution into a terminal chunk's
        ``summary.phases`` record — ``migration_gap_ms`` is dead air
        only the ROUTER saw whole (the replica that finished the stream
        never knew the break happened) — then fold the record into the
        fleet aggregation. Best-effort: attribution never breaks a
        stream."""
        summ = payload.get("summary")
        if not isinstance(summ, dict):
            return
        phases = summ.get("phases")
        if not isinstance(phases, dict):
            return
        if st.gap_ms:
            phases["migration_gap_ms"] = round(st.gap_ms, 3)
        self.observe_phases(phases)

    def _ensure_ticket(self, st: _StreamSession, state: ReplicaState) -> None:
        """Cache the session's migration ticket (fleet/migrate.py) the
        moment the request id is known — while the SOURCE is still
        alive, so its later death is still migratable. A miss (not yet
        admitted, export raced the finish) retries on the next call."""
        if not self.migration or st.ticket is not None or st.request_id is None:
            return
        host, port = state.host_port()
        try:
            st.ticket = fetch_ticket(
                host, port, st.request_id, timeout=self.connect_timeout_s,
                trace=st.trace,
            )
        except _TRANSPORT_ERRORS:
            st.ticket = None

    def _hand_off(self, st: _StreamSession, src: ReplicaState):
        """Disagg prefill→decode hand-off (disagg/prefill.py): page
        bundle, then ticket, then reattach. Returns ``(conn, resp,
        state)`` on the decode replica or ``None`` — and ``None`` is
        ALWAYS safe: the session is still streaming on ``src``, the
        caller just keeps pumping it (typed fallback, never a hung
        stream). One attempt per stream: ``handoff_due`` clears here."""
        st.handoff_due = False

        def fallback(reason: str):
            self.disagg_fallbacks += 1
            self._m_disagg.inc(outcome="fallback", reason=reason)
            return None

        if st.request_id is None:
            return fallback("no_request_id")
        tried = {src.rid}
        # decode-role replicas first; a mixed fleet (no explicit decode
        # role) falls back to any eligible non-source replica
        state = self.balancer.pick(exclude=tried, role="decode")
        if state is None:
            state = self.balancer.pick(st.key, exclude=tried)
        if state is None:
            return fallback("no_decode_replica")
        src_host, src_port = src.host_port()
        dst_host, dst_port = state.host_port()
        t0 = time.perf_counter()
        try:
            conn, resp, new_rid, receipt = hand_off(
                src_host, src_port, st.request_id, dst_host, dst_port,
                timeout=self.connect_timeout_s,
                read_timeout=self.read_timeout_s,
                trace=st.trace,
            )
        except HandoffAborted as e:
            # covers the prefill replica dying mid-transfer (ticket or
            # page fetch fails → no_ticket / transport reasons): the
            # caller's next pump pass hits the broken source and takes
            # the NORMAL migration path off the cached ticket
            return fallback(e.reason)
        st.request_id = new_rid
        self.disagg_handoffs_ok += 1
        self.disagg_pages_moved += int(receipt.get("pages", 0) or 0)
        self.disagg_pages_fresh += int(receipt.get("fresh", 0) or 0)
        self._m_disagg.inc(outcome="ok")
        self._m_disagg_pages.inc(
            float(receipt.get("fresh", 0) or 0), kind="fresh")
        self._m_disagg_pages.inc(
            float(receipt.get("reused", 0) or 0), kind="reused")
        self._m_handoff_s.observe(time.perf_counter() - t0)
        return conn, resp, state

    def _migrate(self, st: _StreamSession, failed: ReplicaState):
        """Move a broken stream: inject the cached ticket into the next
        eligible replica and reattach from 0 (the caller's char-skip
        dedups the replay). Returns ``(conn, resp, state)`` or ``None``
        when no target accepted."""
        if not self.migration:
            return None
        if st.ticket is None and st.request_id is not None:
            # last chance: the source may still be alive (drain window)
            self._ensure_ticket(st, failed)
        if st.ticket is None or st.request_id is None:
            return None
        tried = {failed.rid}
        while True:
            state = self.balancer.pick(st.key, exclude=tried)
            if state is None:
                return None
            tried.add(state.rid)
            host, port = state.host_port()
            try:
                injected = inject_session(
                    host, port, st.ticket, timeout=self.connect_timeout_s,
                    trace=st.trace,
                )
            except MigrationShed as e:
                self.balancer.note_shed(state.rid, e.retry_after_s)
                self._m_sheds.inc(reason=e.reason)
                continue
            except ValueError:
                continue  # refused (config): try the next replica
            except _TRANSPORT_ERRORS:
                self.balancer.note_dead(state.rid)
                continue
            # the response's request_id is authoritative: the target
            # REMAPS an id that collides with one of its own live
            # requests (replicas all number from 1), and the reattach —
            # plus any later re-export for a second migration — must
            # use the id the session actually lives under there
            try:
                new_rid = int(injected.get("request_id", st.request_id))
            except (TypeError, ValueError):
                new_rid = st.request_id
            try:
                conn, resp = open_stream(
                    host, port, new_rid, last_event_id=0,
                    timeout=self.read_timeout_s,
                    connect_timeout=self.connect_timeout_s,
                )
            except (ValueError, *_TRANSPORT_ERRORS):
                self.balancer.note_dead(state.rid)
                continue
            st.request_id = new_rid
            return conn, resp, state

    # -- HTTP front-end ------------------------------------------------------

    def serve(self, host: str = "0.0.0.0", port: int = 9980) -> ThreadingHTTPServer:
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _json_raw(self, code: int, data: bytes,
                          content_type: str = "application/json",
                          headers: dict | None = None):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def _json(self, code: int, payload: dict,
                      headers: dict | None = None):
                self._json_raw(code, json.dumps(payload).encode(),
                               headers=headers)

            def do_GET(self):
                if self.path in ("/", "/health"):
                    if router.any_eligible():
                        self._json(200, {
                            "status": "ok",
                            **router.balancer.stats(),
                        })
                    else:
                        self._json(503, {
                            "status": "unhealthy",
                            "error": "no eligible replica",
                        }, headers={"Retry-After": str(max(
                            1, round(router.balancer.min_retry_after_s())
                        ))})
                elif self.path == "/stats":
                    self._json(200, router.handle_stats())
                elif self.path == "/metrics":
                    self._json_raw(
                        200, router.handle_metrics().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif self.path == "/trace":
                    # the router's OWN span ring (route slices,
                    # migration gaps, hand-off windows)
                    self._json(200, tracer_chrome_trace(router.tracer))
                elif self.path.startswith("/trace/"):
                    # cross-replica merge: ONE Perfetto timeline for a
                    # fleet trace id — router rows at offset 0, every
                    # reachable replica's matching events aligned by the
                    # scraped clock-offset estimates (stamped per event)
                    tid = self.path.rsplit("/", 1)[1].lower()
                    if len(tid) != 32 or any(
                        c not in "0123456789abcdef" for c in tid
                    ):
                        self._json(400, {
                            "error": "bad trace id (want 32 lowercase "
                                     "hex chars)",
                        })
                        return
                    self._json(200, router.merged_trace(tid))
                elif self.path == "/v1/models":
                    self._proxy_get("/v1/models")
                else:
                    self._json(404, {"error": "not found"})

            def _proxy_get(self, path):
                state = router.balancer.pick()
                if state is None:
                    self._json(503, {"error": "no eligible replica"})
                    return
                host_, port_ = state.host_port()
                try:
                    status, body, _ = _request_json(
                        host_, port_, "GET", path,
                        timeout=router.connect_timeout_s,
                    )
                except _TRANSPORT_ERRORS:
                    router.balancer.note_dead(state.rid)
                    self._json(502, {"error": "replica unreachable"})
                    return
                self._json(status, body)

            def do_POST(self):
                if self.path not in ("/v1/chat/completions",
                                     "/v1/completions"):
                    self._json(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                sse = _SseClient(self) if body.get("stream") else None
                try:
                    out = router.route(
                        self.path, body, sse,
                        trace_header=self.headers.get(TRACE_HEADER),
                    )
                except _ClientGone:
                    return
                if out is None:
                    return  # stream fully handled
                status, data, ctype, *extra = out
                self._json_raw(status, data, ctype,
                               headers=extra[0] if extra else None)

        httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd = httpd
        return httpd


class _SseClient:
    """The router's client-facing SSE surface: headers, chunks with the
    router's own ``id:`` stamps, the terminal [DONE]. Client-socket
    failures become :class:`_ClientGone` so the pump can distinguish
    them from upstream (replica-side) breaks."""

    def __init__(self, handler):
        self._h = handler

    def headers(self, replica_id: str | None = None,
                trace: str | None = None) -> None:
        try:
            h = self._h
            h.send_response(200)
            h.send_header("Content-Type", "text/event-stream")
            h.send_header("Cache-Control", "no-cache")
            h.send_header("Connection", "close")
            if replica_id:
                # first-serving replica: attribution for fleet traces
                # (migrations are counted on the router's own /metrics)
                h.send_header("X-DLlama-Replica", replica_id)
            if trace:
                # the stream's fleet trace context (minted if the client
                # sent none): the key into GET /trace/<trace_id>
                h.send_header(TRACE_HEADER, trace)
            h.end_headers()
        except (BrokenPipeError, ConnectionError, OSError) as e:
            raise _ClientGone from e

    def chunk(self, payload: dict, event_id=None) -> None:
        try:
            buf = b""
            if event_id is not None:
                buf += f"id: {event_id}\n".encode()
            buf += b"data: " + json.dumps(payload).encode() + b"\n\n"
            self._h.wfile.write(buf)
            self._h.wfile.flush()
        except (BrokenPipeError, ConnectionError, OSError) as e:
            raise _ClientGone from e

    def done(self) -> None:
        try:
            self._h.wfile.write(b"data: [DONE]\n\n")
            self._h.wfile.flush()
        except (BrokenPipeError, ConnectionError, OSError) as e:
            raise _ClientGone from e


def _ctx_trace_id(ctx: TraceContext | None) -> str | None:
    """The span-args trace id off an optional context (spans whose
    request had no context simply omit the arg)."""
    return ctx.trace_id if ctx is not None else None


def _rid_from_payload(payload: dict) -> int | None:
    """The request id from a chunk's ``id`` field (``chatcmpl-<n>`` /
    ``cmpl-<n>`` — api_types.py's shapes)."""
    rid = payload.get("id")
    if isinstance(rid, str) and "-" in rid:
        try:
            return int(rid.rsplit("-", 1)[1])
        except ValueError:
            return None
    if isinstance(payload.get("request_id"), int):
        return payload["request_id"]
    return None


def _delta_text(choice: dict) -> str:
    """Delta text from either chunk shape: chat (``delta.content``) or
    completion (``text``)."""
    if "delta" in choice:
        d = choice.get("delta")
        return d.get("content", "") if isinstance(d, dict) else ""
    return choice.get("text", "") or ""


def _set_delta_text(choice: dict, text: str) -> None:
    if "delta" in choice and isinstance(choice.get("delta"), dict):
        choice["delta"]["content"] = text
    else:
        choice["text"] = text
