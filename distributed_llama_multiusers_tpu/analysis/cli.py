"""dlint CLI: ``python -m distributed_llama_multiusers_tpu.analysis``.

Exit status 0 = clean (after waivers + baseline), 1 = findings, 2 = usage
error. Pure stdlib — runs before any jax/numpy import is possible, so
``make lint`` is the cheap first gate in front of ``make verify``.
"""

from __future__ import annotations

import argparse
import ast
import json
import subprocess
import sys
from pathlib import Path

from .core import Analyzer, iter_py_files, load_baseline, write_baseline
from .formats import render_github, render_sarif, render_text
from .lockgraph import scan_paths
from .protocol_check import (
    extract_protocol,
    manifest_diff,
    manifest_from_model,
    manifest_path_for,
    write_protocol_manifest,
)
from .registry import default_checkers

PACKAGE_ROOT = Path(__file__).resolve().parent.parent  # the package dir
REPO_ROOT = PACKAGE_ROOT.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.txt"


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="dlint",
        description=(
            "Project-invariant static analysis: cross-file lock-order "
            "graph, blocking-under-lock, guarded-attr atomicity, "
            "pod-broadcast pairing, lock discipline, host-sync transfers, "
            "clock hygiene, condvar/thread hygiene, sharding axis names. "
            "See docs/LINT.md."
        ),
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to analyze (default: the package itself)",
    )
    ap.add_argument(
        "--baseline", default=str(DEFAULT_BASELINE), metavar="FILE",
        help="baseline file of accepted pre-existing findings "
        "(default: %(default)s)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="report baselined findings too",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="accept every current baselinable finding into the baseline "
        "file (waiver-syntax/parse findings cannot be baselined: they are "
        "reported and keep the exit status at 1 until fixed)",
    )
    ap.add_argument(
        "--list-checks", action="store_true", help="list checks and exit"
    )
    ap.add_argument(
        "--format", choices=("text", "github", "sarif"), default="text",
        help="finding output format: plain file:line text (default), "
        "GitHub Actions ::error annotations, or SARIF 2.1.0 JSON "
        "(`make lint` picks github when GITHUB_ACTIONS=true)",
    )
    ap.add_argument(
        "--graph", nargs="?", const="locks", default=None,
        choices=("locks", "resources"), metavar="MODE",
        help="dump a computed surface graph (DOT) and exit — 'locks' "
        "(the default when bare) draws the lock-order graph: nodes are "
        "class-qualified locks, edges 'held while acquiring' sites, "
        "waived edges dashed; 'resources' draws the lifecycle flow: "
        "acquire methods -> resource kinds -> release methods, with "
        "ok[resource-balance] transfers as dashed edges",
    )
    ap.add_argument(
        "--changed", nargs="?", const="HEAD", default=None, metavar="REF",
        help="lint only files changed vs a git ref (default HEAD when the "
        "flag is bare); cross-file checks still load the whole model, "
        "and the run falls back to a full lint when git is unavailable",
    )
    ap.add_argument(
        "--update-protocol-manifest", action="store_true",
        help="re-pin analysis/protocol.lock from the current "
        "parallel/multihost.py layout (run after a PROTOCOL_VERSION "
        "bump) and exit",
    )
    ap.add_argument(
        "--protocol-table", action="store_true",
        help="print the extracted pod wire-protocol op table plus the "
        "diff vs the pinned manifest, and exit — the reviewer aid for "
        "packet-layout changes (`make protocol`)",
    )
    ap.add_argument(
        "--resource-table", action="store_true",
        help="print the extracted resource-lifecycle surface — every "
        "declared kind with its acquire/release vocabulary and "
        "transitive releaser closure, the device-affine methods, and "
        "the batching-loop roots — and exit; the reviewer aid for new "
        "acquire/release pairs (`make leakcheck`)",
    )
    ap.add_argument(
        "--jit-table", action="store_true",
        help="print the extracted device-program surface of "
        "runtime/engine.py — every compiled step family with its "
        "donation spec, dispatchers, and warmup coverage — and exit; "
        "the reviewer aid for new step families (`make jitcheck`)",
    )
    return ap


def git_changed_files(
    ref: str, anchor: Path
) -> tuple[Path, set[Path]] | None:
    """``(repo_root, changed)``: absolute resolved paths changed vs
    ``ref`` (diff + untracked) in the git repo containing ``anchor``.
    Returns None when git is unavailable or ``anchor`` is not inside a
    work tree (the caller falls back to a full run — degraded scope
    must only ever GROW coverage); raises ValueError when the repo
    resolves but ``ref`` does not (a typo'd ref is a usage error, not
    a fallback). The repo root lets the caller treat analyzed files
    OUTSIDE this repo as always-checked rather than silently skipped."""
    anchor = anchor if anchor.is_dir() else anchor.parent

    def _git(*args: str) -> subprocess.CompletedProcess | None:
        try:
            return subprocess.run(
                ["git", "-C", str(anchor), *args],
                capture_output=True, text=True, timeout=30,
            )
        except (OSError, subprocess.SubprocessError):
            return None

    top = _git("rev-parse", "--show-toplevel")
    if top is None or top.returncode != 0:
        return None
    repo_root = Path(top.stdout.strip())
    # both listings must be repo-root-relative: diff already is;
    # ls-files is cwd-relative without --full-name
    diff = _git("diff", "--name-only", "-z", ref, "--")
    if diff is None:
        return None
    if diff.returncode != 0:
        raise ValueError(
            f"--changed {ref}: {diff.stderr.strip() or 'git diff failed'}"
        )
    names = [n for n in diff.stdout.split("\0") if n]
    untracked = _git("ls-files", "--others", "--exclude-standard",
                     "--full-name", "-z")
    if untracked is None or untracked.returncode != 0:
        # an untracked file with a real finding must not vanish from
        # scope because ls-files hiccuped — degraded git state falls
        # back to the FULL run, never a silently smaller one
        return None
    names.extend(n for n in untracked.stdout.split("\0") if n)
    return repo_root.resolve(), {(repo_root / n).resolve() for n in names}


def _find_multihost(paths: list[Path]) -> Path | None:
    for p in iter_py_files(paths):
        if p.as_posix().endswith("parallel/multihost.py"):
            return p
    return None


def _protocol_table(paths: list[Path]) -> int:
    target = _find_multihost(paths)
    if target is None:
        print("dlint: no parallel/multihost.py under the given paths",
              file=sys.stderr)
        return 2
    model = extract_protocol(
        ast.parse(target.read_text(encoding="utf-8")), str(target)
    )
    if model is None:
        print(f"dlint: {target} declares no PROTOCOL_VERSION",
              file=sys.stderr)
        return 2
    enc_by_op = {e.op: e for e in model.encoders.values() if e.op}
    print(f"protocol v{model.version}  HEADER={model.header}  "
          f"SLOTS={model.slots}  ({target})")
    print(f"{'op':34s} {'value':>5s}  {'encoder':30s} {'replay arm':>10s}  "
          "header widths")
    for name, value in sorted(model.ops.items(), key=lambda kv: kv[1]):
        enc = enc_by_op.get(name)
        arm = model.arms.get(name)
        widths = "" if enc is None or not enc.widths else " ".join(
            f"slot{s}={w}" for s, (w, _) in sorted(enc.widths.items())
        )
        print(f"{name:34s} {value:5d}  "
              f"{(enc.name if enc else '— MISSING —'):30s} "
              f"{('line ' + str(arm.line)) if arm else 'MISSING':>10s}  "
              f"{widths}")
    lock = manifest_path_for(target)
    if not lock.exists():
        print(f"\nmanifest: MISSING ({lock}) — run "
              "--update-protocol-manifest")
        return 0
    try:
        pinned = json.loads(lock.read_text(encoding="utf-8"))
    except (OSError, ValueError) as e:
        print(f"\nmanifest: UNREADABLE ({e})")
        return 0
    current = manifest_from_model(model)
    diffs = manifest_diff(pinned, current)
    if pinned.get("protocol_version") != current["protocol_version"]:
        print(f"\nmanifest: pinned v{pinned.get('protocol_version')}, "
              f"extracted v{current['protocol_version']} (bump in flight "
              "— regenerate with --update-protocol-manifest)")
        for d in diffs:
            print(f"  {d}")
    elif diffs:
        print(f"\nmanifest: LAYOUT DRIFT at the same version "
              f"(v{current['protocol_version']}) — `make lint` will fail:")
        for d in diffs:
            print(f"  {d}")
    else:
        print(f"\nmanifest: in sync ({lock.name}, "
              f"v{current['protocol_version']})")
    return 0


def _find_engine(paths: list[Path]) -> Path | None:
    for p in iter_py_files(paths):
        if p.as_posix().endswith("runtime/engine.py"):
            return p
    return None


def _jit_table(paths: list[Path]) -> int:
    from .jitmodel import jit_model_of

    target = _find_engine(paths)
    if target is None:
        print("dlint: no runtime/engine.py under the given paths",
              file=sys.stderr)
        return 2
    model = jit_model_of(target)
    warmed_fams = model.warmed_families()
    n_fam = len({id(s) for s in model.families.values()})
    print(f"jit surface: {len(model.sites)} jax.jit site(s), "
          f"{n_fam} step families  ({target})")
    print(f"{'family':26s} {'line':>5s} {'donate':8s} "
          f"{'dispatched by':34s} warmed")
    seen: set[int] = set()
    for attr, site in sorted(model.families.items(),
                             key=lambda kv: model.family_lines[kv[1].name]
                             if kv[1].name in model.family_lines
                             else kv[1].line):
        if id(site) in seen:
            continue
        seen.add(id(site))
        dispatchers = sorted(
            d.name + ("[b]" if d.bucketed else "")
            for d in model.dispatchers.values()
            if any(a in d.families for a, s in model.families.items()
                   if s is site)
        )
        warm = any(
            a in warmed_fams for a, s in model.families.items() if s is site
        )
        donate = ",".join(map(str, site.donate)) or "-"
        print(f"{attr:26s} {site.line:5d} {donate:8s} "
              f"{', '.join(dispatchers) or '— NONE —':34s} "
              f"{'yes' if warm else 'NO'}")
    if model.has_warmup:
        calls = ", ".join(
            m + ("[bucketed]" if c.in_bucket_loop else "")
            for m, c in sorted(model.warmed.items())
        )
        print(f"\nwarmup_engine (line {model.warmup_line}) warms: {calls}")
    else:
        print("\nwarmup_engine: MISSING")
    print("([b] = compiles per prefill bucket; the runtime twin is "
          f"DLLAMA_JITCHECK=1 — docs/LINT.md)")
    return 0


def _resource_table(paths: list[Path]) -> int:
    from .resourcemodel import build_model

    model = build_model(paths)
    if not model.kinds and not model.device_methods:
        print("dlint: no _dlint_acquires/_dlint_device_affine "
              "declarations under the given paths", file=sys.stderr)
        return 2
    n_scoped = sum(
        1 for fn in model.functions
        for decl in model.kinds.values()
        if fn.name not in decl.vocabulary
        and {c.name for c in fn.calls} & set(decl.acquires)
    )
    print(f"resource surface: {len(model.kinds)} kind(s), "
          f"{len(model.device_methods)} device-affine method(s), "
          f"{n_scoped} acquiring function(s) in scope")
    for kind in sorted(model.kinds):
        decl = model.kinds[kind]
        releasers = model.transitive_releasers(kind)
        wrappers = sorted(releasers - set(decl.releases))
        print(f"\nkind {kind!r}")
        for m, site in sorted(decl.acquires.items()):
            print(f"  acquire  {m:24s} {site}")
        for m, site in sorted(decl.releases.items()):
            print(f"  release  {m:24s} {site}")
        if wrappers:
            print(f"  via      {', '.join(wrappers)}")
    if model.device_methods:
        print("\ndevice-affine (loop thread or run_device_op only):")
        for m, site in sorted(model.device_methods.items()):
            print(f"  {m:26s} {site}")
    for (path, cls), roots in sorted(model.loop_roots.items()):
        closure = sorted(model.loop_closure(path, cls))
        print(f"\nloop roots {cls} ({path}): {', '.join(roots)}")
        print(f"  closure: {len(closure)} method(s)")
    print("\n(runtime twin: DLLAMA_LEAKCHECK=1 raises at the drain "
          "point — docs/LINT.md)")
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    checkers = default_checkers()
    if args.list_checks:
        for c in checkers:
            print(f"{c.name:14s} {c.description}")
        print(f"{'waiver':14s} waiver syntax: reasons mandatory, names known")
        return 0
    paths = [Path(p) for p in args.paths] or [PACKAGE_ROOT]
    for p in paths:
        if not p.exists():
            print(f"dlint: no such path: {p}", file=sys.stderr)
            return 2
    if args.update_protocol_manifest:
        target = _find_multihost(paths)
        if target is None:
            print("dlint: no parallel/multihost.py under the given paths",
                  file=sys.stderr)
            return 2
        lock = write_protocol_manifest(target)
        print(f"dlint: wrote protocol manifest {lock}")
        return 0
    if args.protocol_table:
        return _protocol_table(paths)
    if args.jit_table:
        return _jit_table(paths)
    if args.resource_table:
        return _resource_table(paths)
    analyzer = Analyzer(checkers)
    if args.graph == "resources":
        from .resourcemodel import build_model, resource_dot

        print(resource_dot(build_model(paths)))
        return 0
    if args.graph:
        model = scan_paths(paths, valid_checks=analyzer.valid_checks)
        model.ensure_semantics()
        print(model.dot())
        return 0
    check_only = None
    if args.changed is not None:
        if args.write_baseline:
            # check_only would truncate the baseline to the changed
            # files' findings, silently un-baselining everything else
            print("dlint: --changed cannot be combined with "
                  "--write-baseline (the baseline must cover the whole "
                  "tree)", file=sys.stderr)
            return 2
        try:
            got = git_changed_files(args.changed, paths[0])
        except ValueError as e:
            print(f"dlint: {e}", file=sys.stderr)
            return 2
        if got is None:
            print("dlint: --changed: git unavailable here; falling back "
                  "to a full run", file=sys.stderr)
        else:
            repo_root, check_only = got
            # analyzed paths OUTSIDE the anchored repo have no diff to
            # consult — always-checked, never silently skipped (the
            # degraded-scope-only-grows rule)
            check_only |= {
                q for q in (p.resolve() for p in iter_py_files(paths))
                if not q.is_relative_to(repo_root)
            }
    baseline = (
        set() if (args.no_baseline or args.write_baseline)
        else load_baseline(args.baseline)
    )
    findings = analyzer.run(paths, baseline=baseline, root=REPO_ROOT,
                            check_only=check_only)
    if args.write_baseline:
        # waiver/parse findings are never baseline-filtered by the analyzer,
        # so writing their keys would only accumulate dead entries while the
        # gate keeps failing — report them instead
        baselinable = [f for f in findings if f.check not in ("waiver", "parse")]
        unfixable = [f for f in findings if f.check in ("waiver", "parse")]
        write_baseline(args.baseline, baselinable)
        print(f"dlint: wrote {len(baselinable)} finding(s) to {args.baseline}")
        for f in unfixable:
            print(f.render())
        if unfixable:
            print(
                f"dlint: {len(unfixable)} waiver/parse finding(s) cannot be "
                "baselined — fix them"
            )
            return 1
        return 0
    if args.format == "github":
        lines = render_github(findings)
    elif args.format == "sarif":
        lines = render_sarif(findings, checkers)
    else:
        lines = render_text(findings)
    for line in lines:
        print(line)
    all_files = iter_py_files(paths)
    if check_only is None:
        scope = f"{len(all_files)} file(s)"
    else:
        n = sum(1 for p in all_files if p.resolve() in check_only)
        scope = f"{n} changed of {len(all_files)} file(s)"
    if findings:
        if args.format == "text":
            print(f"dlint: {len(findings)} finding(s) in {scope}")
        return 1
    if args.format == "text":
        print(f"dlint: clean ({scope})")
    return 0
