"""Test config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip sharding is validated without TPU hardware the same way the
reference validates multi-node without a cluster (its NnFakeNodeSynchronizer
+ local process clusters, src/nn/nn-executor.cpp:6-8, examples/n-workers.sh):
here, XLA's host platform is split into 8 virtual devices and the real
collectives run through the same GSPMD paths they would take over ICI.
"""

import os

from distributed_llama_multiusers_tpu.utils.testing import force_cpu_mesh

force_cpu_mesh(n_devices=8)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tiny_model(tmp_path_factory):
    """A tiny Q40 .m + .t pair on disk, shared across the session."""
    from distributed_llama_multiusers_tpu.formats.synthetic import (
        tiny_header,
        write_synthetic_model,
        write_synthetic_tokenizer,
    )

    d = tmp_path_factory.mktemp("tiny_model")
    header = tiny_header()
    model_path = str(d / "model.m")
    tok_path = str(d / "tokenizer.t")
    write_synthetic_model(model_path, header, seed=0)
    write_synthetic_tokenizer(tok_path, vocab_size=header.vocab_size)
    return {"model": model_path, "tokenizer": tok_path, "header": header}
