"""Benchmark: batched decode throughput of the flagship model on real TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: single-stream (batch=1) decode tokens/sec for a Llama-3.2-1B-shaped
bf16 model with a 2048-token KV cache, measured over 64 steps after warmup.

vs_baseline: ratio against the reference's best published single-device
number — Llama 2 7B on 1x RPi 4B at 1312.50 ms/token = 0.762 tok/s
(report.pdf Fig. 3, BASELINE.md). Caveat: model sizes differ (1B here vs 7B
there); the 7B/8-node figure (588 ms/token, 1.70 tok/s) is the distributed
headline this framework targets at scale. Later rounds calibrate against the
reference built from source on identical synthetic models.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REFERENCE_SINGLE_DEVICE_TOK_S = 1000.0 / 1312.50  # report.pdf Fig. 3


def main() -> None:
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _flagship_config
    from distributed_llama_multiusers_tpu.models import (
        init_kv_cache,
        llama_forward,
        params_from_random,
    )

    small = os.environ.get("GRAFT_SMALL") == "1"
    config = _flagship_config(small=small)
    params = params_from_random(config, seed=0, dtype=jnp.bfloat16)
    cache = init_kv_cache(config, n_lanes=1, dtype=jnp.bfloat16)

    from functools import partial

    # donate the cache so XLA updates it in place instead of copying ~64 MB
    # of KV per step
    @partial(jax.jit, donate_argnums=(3,))
    def decode_step(params, tokens, positions, cache):
        return llama_forward(config, params, tokens, positions, cache)

    tok = jnp.zeros((1, 1), jnp.int32)

    # warmup / compile
    logits, cache = decode_step(params, tok, jnp.array([[0]], jnp.int32), cache)
    logits.block_until_ready()

    n_steps = 16 if small else 64
    start_pos = 1
    t0 = time.perf_counter()
    for i in range(n_steps):
        pos = jnp.array([[start_pos + i]], jnp.int32)
        logits, cache = decode_step(params, tok, pos, cache)
    logits.block_until_ready()
    dt = time.perf_counter() - t0

    tok_s = n_steps / dt
    print(
        json.dumps(
            {
                "metric": "llama32_1b_bf16_decode_tok_s",
                "value": round(tok_s, 2),
                "unit": "tok/s",
                "vs_baseline": round(tok_s / REFERENCE_SINGLE_DEVICE_TOK_S, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
