"""Pipeline parallelism (parallel/pipeline.py): GPipe schedule over pp.

The reference has no pipeline parallelism (SURVEY.md §2.4 — its paper
explicitly contrasts TP with layer splitting), so the bar here is
self-parity: the staged schedule must match the plain scanned forward
exactly, forward and backward, alone and composed with dp/tp.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_llama_multiusers_tpu.models import params_from_random
from distributed_llama_multiusers_tpu.models.config import LlamaConfig
from distributed_llama_multiusers_tpu.models.llama import llama_forward_train
from distributed_llama_multiusers_tpu.parallel import MeshPlan, make_mesh
from distributed_llama_multiusers_tpu.parallel.pipeline import pipeline_forward_train
from distributed_llama_multiusers_tpu.parallel.sharding import shard_params

CONFIG = LlamaConfig(
    dim=64, hidden_dim=128, n_layers=4, n_heads=4, n_kv_heads=2,
    vocab_size=96, seq_len=32,
)


def _tokens(b=4, t=8):
    return jnp.asarray(np.random.default_rng(0).integers(0, 96, (b, t)), jnp.int32)


def test_pipeline_pp2_logits_parity():
    mesh = make_mesh(MeshPlan(pp=2))
    params = shard_params(params_from_random(CONFIG, seed=0, dtype=jnp.float32), mesh)
    tokens = _tokens()
    got = pipeline_forward_train(CONFIG, params, tokens, mesh=mesh)
    ref = llama_forward_train(CONFIG, params, tokens, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_pipeline_composes_with_tp_dp():
    """pp2 x tp2 x dp2 — per-stage compute stays tensor-parallel under GSPMD."""
    mesh = make_mesh(MeshPlan(pp=2, tp=2, dp=2))
    params = shard_params(params_from_random(CONFIG, seed=0, dtype=jnp.float32), mesh)
    tokens = _tokens()
    got = pipeline_forward_train(CONFIG, params, tokens, mesh=mesh)
    ref = llama_forward_train(CONFIG, params, tokens, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_pipeline_grad_matches_dense():
    """The staged schedule transposes correctly: grads == plain-scan grads."""
    mesh = make_mesh(MeshPlan(pp=2, tp=2, dp=2))
    params = shard_params(params_from_random(CONFIG, seed=0, dtype=jnp.float32), mesh)
    tokens = _tokens()

    def loss(fwd):
        def f(p):
            logits = fwd(CONFIG, p, tokens, mesh=mesh)
            return jnp.mean(jax.nn.logsumexp(logits, axis=-1))
        return jax.jit(jax.value_and_grad(f))

    val_pp, grads_pp = loss(pipeline_forward_train)(params)
    val_ref, grads_ref = loss(llama_forward_train)(params)
    assert abs(float(val_pp) - float(val_ref)) < 1e-6
    for a, b in zip(jax.tree.leaves(grads_pp), jax.tree.leaves(grads_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4)


def test_pipeline_extra_microbatches():
    """M > pp microbatches fill the bubble; schedule stays exact."""
    mesh = make_mesh(MeshPlan(pp=2))
    params = shard_params(params_from_random(CONFIG, seed=0, dtype=jnp.float32), mesh)
    tokens = _tokens(b=8)
    got = pipeline_forward_train(CONFIG, params, tokens, mesh=mesh, n_microbatches=4)
    ref = llama_forward_train(CONFIG, params, tokens, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_pipeline_pp1_falls_back():
    mesh = make_mesh(MeshPlan(tp=2))
    params = shard_params(params_from_random(CONFIG, seed=0, dtype=jnp.float32), mesh)
    tokens = _tokens()
    got = pipeline_forward_train(CONFIG, params, tokens, mesh=mesh)
    ref = llama_forward_train(CONFIG, params, tokens, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=0, rtol=0)


def test_pipeline_validation_errors():
    mesh = make_mesh(MeshPlan(pp=2))
    params = shard_params(params_from_random(CONFIG, seed=0, dtype=jnp.float32), mesh)
    with pytest.raises(ValueError, match="not divisible into"):
        pipeline_forward_train(CONFIG, params, _tokens(b=3), mesh=mesh, n_microbatches=2)
    bad = LlamaConfig(
        dim=64, hidden_dim=128, n_layers=3, n_heads=4, n_kv_heads=2,
        vocab_size=96, seq_len=32,
    )
    bad_params = params_from_random(bad, seed=0, dtype=jnp.float32)
    with pytest.raises(ValueError, match="not divisible by pp"):
        pipeline_forward_train(bad, bad_params, _tokens(), mesh=mesh)
